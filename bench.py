"""Benchmark: Llama train-step MFU on the available accelerator.

Prints ONE JSON line: {"metric": ..., "value": ..., "unit": ..., "vs_baseline": ...}.
Baseline: the north-star target of 40% MFU via the stock Trainer API (BASELINE.json),
scored here as achieved-MFU / 0.40 on the single-chip flagship-family model.

All diagnostics go to stderr; stdout carries only the JSON line.
"""
import json
import os
import sys
import time


def log(msg: str) -> None:
    print(msg, file=sys.stderr, flush=True)


# TPU bf16 peak FLOP/s per chip by device-kind substring; fallback conservative.
PEAK_FLOPS = {
    "v5 lite": 197e12,
    "v5e": 197e12,
    "v5p": 459e12,
    "v4": 275e12,
    "v6 lite": 918e12,
    "v6e": 918e12,
}


def peak_flops_for(device) -> float:
    kind = getattr(device, "device_kind", "").lower()
    for key, val in PEAK_FLOPS.items():
        if key in kind:
            return val
    return 197e12


def run_one(model_name: str, batch: int, seq: int, steps: int,
            remat_policy: str) -> tuple:
    import jax

    from ray_tpu.models import get_config
    from ray_tpu.train import init_state, make_optimizer, make_train_step

    dev = jax.devices()[0]
    cfg = get_config(model_name)
    if remat_policy != cfg.remat_policy:
        import dataclasses

        # save matmul outputs, recompute only elementwise: a few pp MFU over
        # full remat whenever the saved activations still fit HBM
        cfg = dataclasses.replace(cfg, remat_policy=remat_policy)
    log(f"model={model_name} n_params={cfg.n_params/1e9:.3f}B batch={batch} seq={seq} "
        f"remat={remat_policy}")

    tx = make_optimizer(total_steps=1000)
    state = init_state(jax.random.PRNGKey(0), cfg, tx)
    step = make_train_step(cfg, tx)
    tokens = jax.random.randint(jax.random.PRNGKey(1), (batch, seq + 1), 0, cfg.vocab_size)
    batch_dict = {"tokens": tokens}

    t0 = time.perf_counter()
    state, metrics = step(state, batch_dict)
    # fetch (not block_until_ready): over a remote-device tunnel only a data
    # fetch reliably synchronizes the stream
    first_loss = float(metrics["loss"])
    log(f"compile+first step: {time.perf_counter() - t0:.1f}s loss={first_loss:.3f}")

    t0 = time.perf_counter()
    for _ in range(steps):
        state, metrics = step(state, batch_dict)
    final_loss = float(metrics["loss"])  # fetch = true sync point
    dt = (time.perf_counter() - t0) / steps

    tokens_per_sec = batch * seq / dt
    flops_per_token = 6 * cfg.n_params  # standard fwd+bwd transformer estimate
    mfu = tokens_per_sec * flops_per_token / peak_flops_for(dev)
    log(f"step={dt*1e3:.1f}ms tokens/s={tokens_per_sec:,.0f} "
        f"mfu={mfu:.3f} loss={final_loss:.3f}")
    return mfu, tokens_per_sec


def _trainer_loop(config) -> None:
    """The stock-Trainer-path measurement body: identical model/step/config as
    run_one, but driven inside a JaxTrainer.fit() worker session (BASELINE.md:25
    words the north star as MFU 'via a stock Trainer API' — this measures exactly
    that, not the bare step function)."""
    import dataclasses
    import time

    import jax

    import ray_tpu.train as train
    from ray_tpu.models import get_config
    from ray_tpu.train import init_state, make_optimizer, make_train_step

    cfg = get_config(config["model"])
    if config["remat"] != cfg.remat_policy:
        cfg = dataclasses.replace(cfg, remat_policy=config["remat"])
    batch, seq, steps = config["batch"], config["seq"], config["steps"]
    tx = make_optimizer(total_steps=1000)
    state = init_state(jax.random.PRNGKey(0), cfg, tx)
    step = make_train_step(cfg, tx)
    tokens = jax.random.randint(
        jax.random.PRNGKey(1), (batch, seq + 1), 0, cfg.vocab_size)
    batch_dict = {"tokens": tokens}
    state, metrics = step(state, batch_dict)
    float(metrics["loss"])  # fetch = sync (block_until_ready is unreliable on the tunnel)
    t0 = time.perf_counter()
    for _ in range(steps):
        state, metrics = step(state, batch_dict)
    final_loss = float(metrics["loss"])
    dt = (time.perf_counter() - t0) / steps
    tokens_per_sec = batch * seq / dt
    mfu = tokens_per_sec * 6 * cfg.n_params / peak_flops_for(jax.devices()[0])
    train.report({"mfu": mfu, "tokens_per_sec": tokens_per_sec, "loss": final_loss})


def run_trainer_path(model_name: str, batch: int, seq: int, steps: int,
                     remat_policy: str, grad_sync=None) -> tuple:
    """Same measurement as run_one but through JaxTrainer.fit() (1 worker owning the
    chip). Returns (mfu, tokens_per_sec) reported from inside the session.

    grad_sync: GradSyncConfig handed to the workers via JaxConfig — how the
    winning `--grad-sync` row reaches the default trainer-path MFU run (the
    worker's make_train_step picks it up from env)."""
    import tempfile

    import ray_tpu
    from ray_tpu.air import RunConfig, ScalingConfig
    from ray_tpu.train import JaxConfig, JaxTrainer

    log(f"trainer-path: model={model_name} batch={batch} seq={seq} steps={steps} "
        f"grad_sync={grad_sync}")
    import jax

    on_cpu = jax.default_backend() == "cpu"
    worker_env = {"JAX_PLATFORMS": "cpu"} if on_cpu else None
    ray_tpu.init(num_cpus=2, worker_env=worker_env)
    try:
        # use_tpu: the worker must be spawned with the "tpu" accel tag — plain
        # CPU workers force JAX_PLATFORMS=cpu and would run the model on host
        scaling = (ScalingConfig(num_workers=1, cpus_per_worker=1.0) if on_cpu
                   else ScalingConfig(num_workers=1, use_tpu=True,
                                      chips_per_worker=1))
        trainer = JaxTrainer(
            _trainer_loop,
            train_loop_config={"model": model_name, "batch": batch, "seq": seq,
                               "steps": steps, "remat": remat_policy},
            backend_config=JaxConfig(collective_group=False, grad_sync=grad_sync),
            scaling_config=scaling,
            run_config=RunConfig(name="bench_trainer_path",
                                 storage_path=tempfile.mkdtemp(prefix="bench_tp_")),
        )
        result = trainer.fit()
        if result.error is not None:
            raise RuntimeError(f"trainer-path bench failed: {result.error}")
        m = result.metrics
        log(f"trainer-path: mfu={m['mfu']:.3f} tokens/s={m['tokens_per_sec']:,.0f} "
            f"loss={m['loss']:.3f}")
        return m["mfu"], m["tokens_per_sec"]
    finally:
        ray_tpu.shutdown()


# ---------------------------------------------------------------- grad-sync bench
# `bench.py --grad-sync`: paired device-plane gradient-sync rows (monolithic vs
# bucketed vs bucketed+int8 vs +sharded-update) -> TRAIN_SYNC_BENCH.json, the
# evidence behind train/grad_sync.py. The device-mesh section runs in a child
# with its own 8-device CPU platform (the multichip dryrun mesh); the
# loss-parity section runs on the native backend (llama-500m when a TPU is
# attached); the sharded-HBM section is analytic at llama3-8b fsdp-pod
# geometry. The winning mesh-section config is wired into the default
# trainer-path MFU row via JaxConfig(grad_sync=...).

GRAD_SYNC_MODES = {
    "monolithic": {},
    "bucketed": {"mode": "bucketed"},
    "bucketed_int8": {"mode": "bucketed", "compression": "int8"},
    "bucketed_int8_sharded": {"mode": "bucketed", "compression": "int8",
                              "sharded_update": True},
    "sharded_update": {"sharded_update": True},
}


def _grad_sync_child() -> None:
    """Child body for the device-mesh section: dp=8 virtual-CPU mesh, every
    mode stepped in interleaved rounds (drift-fair), one JSON line out."""
    import time as _time

    import jax
    import jax.numpy as jnp

    from ray_tpu.models import get_config
    from ray_tpu.parallel import MeshSpec, build_mesh, use_mesh
    from ray_tpu.parallel.sharding import named_sharding
    from ray_tpu.train import (GradSyncConfig, grad_sync, init_state,
                               make_optimizer, make_train_step)

    model = os.environ.get("BENCH_SYNC_MODEL", "test-tiny")
    batch = int(os.environ.get("BENCH_SYNC_BATCH", "16"))
    seq = int(os.environ.get("BENCH_SYNC_SEQ", "64"))
    steps = int(os.environ.get("BENCH_SYNC_STEPS", "8"))
    rounds = int(os.environ.get("BENCH_SYNC_ROUNDS", "3"))
    ndev = len(jax.devices())
    cfg = get_config(model)
    mesh = build_mesh(MeshSpec(dp=-1).resolve(ndev), jax.devices())
    tx = make_optimizer(total_steps=10_000)
    tokens_host = jax.random.randint(
        jax.random.PRNGKey(1), (batch, seq + 1), 0, cfg.vocab_size)

    runs = {}
    with use_mesh(mesh):
        tokens = jax.device_put(tokens_host, named_sharding(mesh, "batch", None))
        batch_dict = {"tokens": tokens}
        for name, kw in GRAD_SYNC_MODES.items():
            sync = GradSyncConfig(**kw)
            state = init_state(jax.random.PRNGKey(0), cfg, tx, mesh=mesh, sync=sync)
            step = make_train_step(cfg, tx, donate=False, sync=sync)
            overlap = None
            if not sync.is_default:
                overlap = grad_sync.overlap_report(
                    step.lower(state, batch_dict).compile())
            state, metrics = step(state, batch_dict)  # compile + step 1
            losses = [float(metrics["loss"])]
            runs[name] = {"sync": sync, "step": step, "state": state,
                          "losses": losses, "overlap": overlap, "best_dt": None}
        for _ in range(rounds):
            for name, run in runs.items():
                step, state = run["step"], run["state"]
                t0 = _time.perf_counter()
                for _ in range(steps):
                    state, metrics = step(state, batch_dict)
                loss = float(metrics["loss"])  # fetch = sync point
                dt = (_time.perf_counter() - t0) / steps
                run["state"] = state
                run["losses"].append(loss)
                if run["best_dt"] is None or dt < run["best_dt"]:
                    run["best_dt"] = dt

    out = {}
    for name, run in runs.items():
        payload = grad_sync.sync_payload_bytes(run["state"].params, run["sync"])
        out[name] = {
            "tokens_per_sec": round(batch * seq / run["best_dt"], 1),
            "step_ms": round(run["best_dt"] * 1e3, 2),
            "losses": [round(v, 6) for v in run["losses"]],
            "payload_f32_bytes": payload["f32_bytes"],
            "payload_bytes": payload["compressed_bytes"],
            "overlap": run["overlap"],
        }
    print("GRAD_SYNC_RESULT " + json.dumps(
        {"model": model, "batch": batch, "seq": seq, "steps": steps,
         "world": ndev, "modes": out}))


def _grad_sync_hbm_child() -> None:
    """Analytic sharded-optimizer HBM rows at llama3-8b pod geometry (needs a
    64-device platform; nothing compiles or materializes)."""
    import jax

    from ray_tpu.models import get_config
    from ray_tpu.parallel import MeshSpec, build_mesh
    from ray_tpu.train import grad_sync, make_optimizer

    sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
    import __graft_entry__ as ge

    gib = 1024**3
    cfg = get_config("llama3-8b", dtype="bfloat16", remat_policy="full")
    tx = make_optimizer(total_steps=10)
    mesh = build_mesh(MeshSpec(dp=8, fsdp=8).resolve(64), jax.devices()[:64])
    state = ge._abstract_train_state(cfg, mesh, tx)
    base = grad_sync.opt_state_bytes_per_shard(
        grad_sync.abstract_sharded_opt_state(tx, state.params, mesh, axes=()))
    sharded = grad_sync.opt_state_bytes_per_shard(
        grad_sync.abstract_sharded_opt_state(
            tx, state.params, mesh, axes=("dp", "fsdp")))
    print("GRAD_SYNC_HBM " + json.dumps({
        "mesh": "dp8xfsdp8", "model": "llama3-8b",
        "opt_state_gib_inherited": round(base / gib, 3),
        "opt_state_gib_sharded_update": round(sharded / gib, 3),
        "cut_factor": round(base / max(sharded, 1), 2),
    }))


def _run_child(target: str, n_devices: int, timeout: int = 1200) -> dict:
    """Run a child bench body on a fresh virtual-CPU platform, parse its
    marker line."""
    import subprocess

    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env["XLA_FLAGS"] = f"--xla_force_host_platform_device_count={n_devices}"
    marker = {"mesh": "GRAD_SYNC_RESULT ", "hbm": "GRAD_SYNC_HBM ",
              "pipeline": "PIPELINE_RESULT ", "rl": "RL_RESULT "}[target]
    fn = {"mesh": "_grad_sync_child", "hbm": "_grad_sync_hbm_child",
          "pipeline": "_pipeline_child", "rl": "_rl_child"}[target]
    proc = subprocess.run(
        [sys.executable, "-c", f"import bench; bench.{fn}()"],
        cwd=os.path.dirname(os.path.abspath(__file__)), env=env,
        capture_output=True, text=True, timeout=timeout)
    if proc.returncode != 0:
        log(proc.stderr[-4000:])
        raise RuntimeError(f"bench child {target} failed rc={proc.returncode}")
    line = next((ln for ln in proc.stdout.splitlines()
                 if ln.startswith(marker)), None)
    if line is None:
        log(proc.stderr[-4000:])
        raise RuntimeError(f"bench child {target} printed no {marker!r}")
    return json.loads(line[len(marker):])


def _loss_parity_section() -> dict:
    """f32 vs int8 grad-sync loss curves on the native backend — llama-500m on
    an accelerator, test-tiny on CPU — plus the analytic payload-bytes cut."""
    import time as _time

    import jax

    from ray_tpu.models import get_config
    from ray_tpu.parallel import MeshSpec, build_mesh, use_mesh
    from ray_tpu.parallel.sharding import named_sharding
    from ray_tpu.train import (GradSyncConfig, grad_sync, init_state,
                               make_optimizer, make_train_step)

    on_cpu = jax.default_backend() == "cpu"
    model = os.environ.get("BENCH_SYNC_PARITY_MODEL",
                           "test-tiny" if on_cpu else "llama-500m")
    batch = int(os.environ.get("BENCH_SYNC_PARITY_BATCH", "8" if on_cpu else "4"))
    seq = int(os.environ.get("BENCH_SYNC_PARITY_SEQ", "64" if on_cpu else "512"))
    steps = int(os.environ.get("BENCH_SYNC_PARITY_STEPS", "10"))
    cfg = get_config(model)
    mesh = build_mesh(MeshSpec(dp=-1).resolve(len(jax.devices())), jax.devices())
    tx = make_optimizer(total_steps=10_000)
    tokens_host = jax.random.randint(
        jax.random.PRNGKey(1), (batch, seq + 1), 0, cfg.vocab_size)

    curves = {}
    payload = {}
    with use_mesh(mesh):
        tokens = jax.device_put(tokens_host, named_sharding(mesh, "batch", None))
        batch_dict = {"tokens": tokens}
        for name, kw in (("f32", {"mode": "bucketed"}),
                         ("int8", {"mode": "bucketed", "compression": "int8",
                                   "stochastic_rounding": True})):
            sync = GradSyncConfig(**kw)
            state = init_state(jax.random.PRNGKey(0), cfg, tx, mesh=mesh, sync=sync)
            step = make_train_step(cfg, tx, donate=False, sync=sync)
            losses = []
            for _ in range(steps):
                state, metrics = step(state, batch_dict)
                losses.append(float(metrics["loss"]))
            curves[name] = losses
            payload[name] = grad_sync.sync_payload_bytes(state.params, sync)
    max_rel = max(abs(a - b) / max(abs(a), 1e-9)
                  for a, b in zip(curves["f32"], curves["int8"]))
    return {
        "model": model, "batch": batch, "seq": seq, "steps": steps,
        "world": len(jax.devices()),
        "loss_f32": [round(v, 5) for v in curves["f32"]],
        "loss_int8": [round(v, 5) for v in curves["int8"]],
        "max_rel_divergence": round(max_rel, 6),
        "payload_f32_bytes": payload["int8"]["f32_bytes"],
        "payload_int8_bytes": payload["int8"]["compressed_bytes"],
        "bytes_cut_factor": round(
            payload["int8"]["f32_bytes"]
            / max(payload["int8"]["compressed_bytes"], 1), 2),
    }


def run_grad_sync_bench() -> None:
    log("grad-sync bench: device-mesh section (8-device virtual-CPU child)")
    mesh_rows = _run_child("mesh", 8)
    log("grad-sync bench: loss-parity section (native backend)")
    parity = _loss_parity_section()
    log("grad-sync bench: sharded-optimizer HBM section (analytic, 64 devices)")
    hbm = _run_child("hbm", 64, timeout=900)

    modes = mesh_rows["modes"]
    mono = modes["monolithic"]
    # f32 modes must track the monolithic loss curve bit-for-bit-ish; int8
    # modes within the documented tolerance
    checks = {
        "bucketed_matches_monolithic": max(
            abs(a - b) for a, b in zip(modes["bucketed"]["losses"],
                                       mono["losses"])) < 1e-5,
        "bucketed_ge_monolithic_tokens_per_sec":
            modes["bucketed"]["tokens_per_sec"]
            >= mono["tokens_per_sec"] * 0.999,
        "int8_halves_payload_bytes":
            modes["bucketed_int8"]["payload_bytes"] * 2
            <= modes["bucketed_int8"]["payload_f32_bytes"],
        "int8_loss_parity": parity["max_rel_divergence"] < 0.02,
        "sharded_update_cuts_opt_hbm_2x": hbm["cut_factor"] >= 2.0,
        "bucketed_reductions_not_sunk":
            not modes["bucketed"]["overlap"]["all_sunk_to_end"],
    }
    ranked = sorted(
        (name for name in modes
         if name in ("monolithic", "bucketed")),  # f32-exact candidates only
        key=lambda n: modes[n]["tokens_per_sec"], reverse=True)
    winning = ranked[0]
    result = {
        "device_mesh": mesh_rows,
        "loss_parity": parity,
        "sharded_hbm": hbm,
        "checks": checks,
        "winning": {"name": winning,
                    "config": GRAD_SYNC_MODES[winning]},
    }
    with open(os.path.join(os.path.dirname(os.path.abspath(__file__)),
                           "TRAIN_SYNC_BENCH.json"), "w") as f:
        json.dump(result, f, indent=2)
    for name, ok in checks.items():
        log(f"grad-sync check {name}: {'PASS' if ok else 'FAIL'}")
    print(json.dumps({
        "metric": "grad_sync_bucketed_tokens_per_sec_dp8",
        "value": modes["bucketed"]["tokens_per_sec"],
        "unit": "tokens/s",
        "vs_baseline": round(modes["bucketed"]["tokens_per_sec"]
                             / max(mono["tokens_per_sec"], 1e-9), 4),
        "secondary": {
            "monolithic_tokens_per_sec": mono["tokens_per_sec"],
            "int8_payload_cut_factor": parity["bytes_cut_factor"],
            "int8_max_rel_loss_divergence": parity["max_rel_divergence"],
            "sharded_opt_hbm_cut_factor": hbm["cut_factor"],
            "checks_passed": sum(checks.values()),
            "checks_total": len(checks),
        },
    }))


def _pipeline_child() -> None:
    """Child body for --pipeline: pp=2 multichip dryrun (2 virtual CPU devices).

    Three measurements on the same 2-stage residual-MLP model and microbatch
    decomposition:
      spmd_baseline       one jitted program — value_and_grad through
                          `pipeline_spmd` on a pure-pp mesh, plus SGD
      mpmd_1f1b           cross-process MPMD runner, 1F1B + prefetch overlap
      mpmd_gpipe_noprefetch  unoverlapped control (all-fwd-then-all-bwd order,
                          prefetch off) — the bubble-fraction gate's baseline
    Tokens/s counts microbatch rows per optimizer step. Prints one
    PIPELINE_RESULT line to stdout."""
    import numpy as np

    import jax
    import jax.numpy as jnp

    d = int(os.environ.get("BENCH_PIPE_D", "512"))
    mb = int(os.environ.get("BENCH_PIPE_MB", "16"))
    m = int(os.environ.get("BENCH_PIPE_M", "4"))
    steps = int(os.environ.get("BENCH_PIPE_STEPS", "5"))
    pp, lr = 2, 1e-2
    rows = m * mb
    log(f"pipeline child: pp={pp} d={d} mb={mb} m={m} steps={steps}")

    def stage_fn(p, x):
        return x + jnp.tanh(x @ p["w"]) @ p["w2"]

    def mb_loss(y):
        return jnp.mean(y ** 2)

    k1, k2 = jax.random.split(jax.random.PRNGKey(0))
    stacked = {"w": jax.random.normal(k1, (pp, d, 2 * d)) * 0.1,
               "w2": jax.random.normal(k2, (pp, 2 * d, d)) * 0.1}
    x = np.asarray(jax.random.normal(jax.random.PRNGKey(1), (rows, d)),
                   np.float32)

    # -- (a) single-program pipeline_spmd baseline ------------------------------
    from jax.sharding import Mesh

    from ray_tpu.parallel import use_mesh
    from ray_tpu.parallel.pipeline import pipeline

    mesh = Mesh(np.array(jax.devices()[:pp]), ("pp",))

    def full_loss(params, xx):
        with use_mesh(mesh):
            y = pipeline(stage_fn, params, xx, num_microbatches=m, mesh=mesh)
        y_mb = y.reshape(m, mb, d)
        return jnp.mean(jnp.stack([mb_loss(y_mb[i]) for i in range(m)]))

    @jax.jit
    def spmd_step(params, xx):
        loss, g = jax.value_and_grad(full_loss)(params, xx)
        new = jax.tree_util.tree_map(
            lambda pv, gv: pv - jnp.float32(lr) * gv, params, g)
        return new, loss

    params = stacked
    params, loss = spmd_step(params, x)
    log(f"spmd baseline compile+first step done loss={float(loss):.5f}")
    t0 = time.perf_counter()
    for _ in range(steps):
        params, loss = spmd_step(params, x)
    final = float(loss)  # fetch = sync point for the whole dependent chain
    dt = (time.perf_counter() - t0) / steps
    spmd_row = {"tokens_per_sec": round(rows / dt, 1),
                "step_ms": round(dt * 1e3, 2), "loss": final}
    log(f"spmd baseline: {spmd_row}")

    # -- (b)/(c) cross-process MPMD runner --------------------------------------
    import ray_tpu
    from ray_tpu.train.mpmd_pipeline import MPMDPipeline, MPMDPipelineConfig

    ray_tpu.init(num_cpus=4, worker_env={"JAX_PLATFORMS": "cpu"})
    stage_params = [jax.tree_util.tree_map(lambda p: np.asarray(p[s]), stacked)
                    for s in range(pp)]

    def measure(schedule: str, prefetch: int, group: str) -> dict:
        cfg = MPMDPipelineConfig(num_microbatches=m, schedule=schedule,
                                 prefetch=prefetch, learning_rate=lr,
                                 group_name=group)
        pipe = MPMDPipeline([stage_fn] * pp, stage_params, loss_fn=mb_loss,
                            microbatch_spec=((mb, d), np.float32), cfg=cfg)
        try:
            pipe.step(0, x)           # compile step
            pipe.reset_timelines()    # bubble gate wants steady state only
            t0 = time.perf_counter()
            out = {}
            for i in range(steps):
                out = pipe.step(i + 1, x)
            dt = (time.perf_counter() - t0) / steps
            fractions = pipe.bubble_fractions()
            admission = pipe.admission()
        finally:
            pipe.shutdown()
        row = {"schedule": schedule, "prefetch": prefetch,
               "tokens_per_sec": round(rows / dt, 1),
               "step_ms": round(dt * 1e3, 2), "loss": out.get("loss"),
               "bubble_mean": round(fractions["mean"], 4),
               "bubble_per_stage": {k: round(v, 4) for k, v in
                                    fractions.items() if k != "mean"},
               "admission": admission}
        log(f"mpmd {schedule}/prefetch={prefetch}: {row}")
        return row

    try:
        r_1f1b = measure("1f1b", 2, "mpmd_bench_1f1b")
        r_gpipe = measure("gpipe", 0, "mpmd_bench_gpipe")
    finally:
        ray_tpu.shutdown()

    print("PIPELINE_RESULT " + json.dumps({
        "pp": pp, "d": d, "microbatch": mb, "num_microbatches": m,
        "steps": steps, "rows_per_step": rows,
        "spmd_baseline": spmd_row,
        "mpmd_1f1b": r_1f1b,
        "mpmd_gpipe_noprefetch": r_gpipe,
    }))


def run_pipeline_bench() -> None:
    """--pipeline: MPMD cross-process pipeline vs the single-program
    pipeline_spmd baseline, multichip-dryrun pp=2 row. Gates (non-zero exit on
    failure): MPMD tokens/s >= the baseline's, and the overlapped schedule's
    measured bubble fraction below the unoverlapped control's."""
    log("pipeline bench: pp=2 multichip-dryrun child (2 virtual CPU devices)")
    row = _run_child("pipeline", 2, timeout=1500)
    checks = {
        "mpmd_tokens_per_sec_ge_spmd_baseline":
            row["mpmd_1f1b"]["tokens_per_sec"]
            >= row["spmd_baseline"]["tokens_per_sec"],
        "overlapped_bubble_below_unoverlapped":
            row["mpmd_1f1b"]["bubble_mean"]
            < row["mpmd_gpipe_noprefetch"]["bubble_mean"],
        "no_leaked_activation_blocks": all(
            c == {"published": 0, "inflight_pulls": 0}
            for r in (row["mpmd_1f1b"], row["mpmd_gpipe_noprefetch"])
            for c in r["admission"]),
    }
    result = {"rows": [dict(row, mesh="pp2_multichip_dryrun")],
              "checks": checks}
    with open(os.path.join(os.path.dirname(os.path.abspath(__file__)),
                           "PIPELINE_BENCH.json"), "w") as f:
        json.dump(result, f, indent=2)
    for name, ok in checks.items():
        log(f"pipeline check {name}: {'PASS' if ok else 'FAIL'}")
    mpmd = row["mpmd_1f1b"]["tokens_per_sec"]
    base = row["spmd_baseline"]["tokens_per_sec"]
    print(json.dumps({
        "metric": "mpmd_pipeline_tokens_per_sec_pp2",
        "value": mpmd,
        "unit": "tokens/s",
        "vs_baseline": round(mpmd / max(base, 1e-9), 4),
        "secondary": {
            "spmd_baseline_tokens_per_sec": base,
            "bubble_1f1b_prefetch": row["mpmd_1f1b"]["bubble_mean"],
            "bubble_gpipe_noprefetch":
                row["mpmd_gpipe_noprefetch"]["bubble_mean"],
            "checks_passed": sum(checks.values()),
            "checks_total": len(checks),
        },
    }))
    if not all(checks.values()):
        sys.exit(1)


# ----------------------------------------------------------- decoupled RL

# One geometry for every --rl row (a lean policy head keeps the bench
# transport-bound — the regime the rollout plane optimizes; both serialized
# rows and the decoupled row train the exact same model and SGD schedule).
_RL_TRAIN = dict(lr=3e-4, gamma=0.99, lambda_=0.95, clip_param=0.3,
                 entropy_coeff=0.01, train_batch_size=512,
                 minibatch_size=128, num_epochs=2)
_RL_MODEL = {"fcnet_hiddens": [8]}


def _rl_ppo_config(env):
    from ray_tpu.rllib.algorithms.ppo import PPOConfig

    return (PPOConfig().environment(env)
            .training(**_RL_TRAIN)
            .rl_module(model_config=dict(_RL_MODEL))
            .debugging(seed=0))


def _rl_serialized(host_slicing: bool, iters: int) -> dict:
    """One serialized PPO cycle: classic sample -> pickle episodes -> GAE ->
    update loop. host_slicing=True is the seed baseline (host re-slice +
    re-upload per minibatch); False is the device-resident gather path
    (`serialized_opt` row)."""
    import ray_tpu
    from bench_rllib import SyntheticAtariEnv

    if host_slicing:
        os.environ["RAY_TPU_RL_HOST_SLICING"] = "1"
    else:
        os.environ.pop("RAY_TPU_RL_HOST_SLICING", None)
    ray_tpu.init(num_cpus=4, worker_env={"JAX_PLATFORMS": "cpu"})
    try:
        cfg = (_rl_ppo_config(SyntheticAtariEnv)
               .env_runners(num_env_runners=2, num_envs_per_env_runner=4,
                            rollout_fragment_length=64))
        algo = cfg.build_algo()
        try:
            algo.train()  # warmup: compiles sampler + learner
            t0 = time.perf_counter()
            rets = []
            for _ in range(iters):
                r = algo.train()
                rets.append(r.get("episode_return_mean") or 0.0)
            dt = time.perf_counter() - t0
            batch = _RL_TRAIN["train_batch_size"]
            mb_per_iter = _RL_TRAIN["num_epochs"] * (
                batch // _RL_TRAIN["minibatch_size"])
            return {
                "env_steps_per_s": round(iters * batch / dt, 1),
                "updates_per_s": round(iters * mb_per_iter / dt, 1),
                "episode_return": round(sum(rets[-2:]) / 2, 2),
            }
        finally:
            algo.cleanup()
    finally:
        ray_tpu.shutdown()


def _rl_decoupled(iters: int) -> dict:
    """Decoupled cycle: 2 vectorized rollout workers (48 envs each) stream
    trajectory blocks over the data plane into the device-resident learner;
    weights broadcast back every 3 updates. Rates are measured at drained
    steady state (post-warmup backlog consumed before the clock starts)."""
    import ray_tpu
    from bench_rllib import SyntheticAtariEnv

    os.environ.pop("RAY_TPU_RL_HOST_SLICING", None)
    ray_tpu.init(num_cpus=4, worker_env={"JAX_PLATFORMS": "cpu"})
    try:
        B = 48
        cfg = (_rl_ppo_config(SyntheticAtariEnv)
               .env_runners(num_env_runners=2, num_envs_per_env_runner=B,
                            rollout_fragment_length=64)
               .decoupled_rollout(enabled=True, blocks_per_update=1,
                                  queue_depth=8, max_block_lag=4,
                                  weight_sync_interval=3))
        algo = cfg.build_algo()
        try:
            algo.train()  # warmup: compiles both sides
            for _ in range(3):  # drain the block backlog built during compile
                algo.train()
            sampled = lambda: sum(  # noqa: E731
                m.get("num_env_steps_sampled") or 0
                for m in algo.rollout_plane.worker_metrics())
            base = sampled()
            t0 = time.perf_counter()
            n_upd = 0
            for _ in range(iters):
                if algo.train().get("num_env_steps_trained"):
                    n_upd += 1
            dt = time.perf_counter() - t0
            steps = sampled() - base
            mb_per_round = _RL_TRAIN["num_epochs"] * (
                (64 * B) // _RL_TRAIN["minibatch_size"])
            rets = [m["episode_return_mean"]
                    for m in algo.rollout_plane.worker_metrics()
                    if m.get("episode_return_mean") is not None]
            out = {
                "env_steps_per_s": round(steps / dt, 1),
                "updates_per_s": round(n_upd * mb_per_round / dt, 1),
                "episode_return": round(sum(rets) / max(len(rets), 1), 2),
                "update_rounds": n_upd,
            }
        finally:
            algo.cleanup()
        out["plane"] = algo.final_plane_stats
        return out
    finally:
        ray_tpu.shutdown()


def _rl_dry() -> dict:
    """--dry-run body: tiny CartPole serialized + decoupled cycles. Proves
    the full path (block transport, staleness filter, weight broadcast,
    release accounting) end-to-end in seconds; rate/return gates are
    meaningless at this size and are skipped by the parent."""
    import ray_tpu
    from ray_tpu.rllib.algorithms.ppo import PPOConfig

    def tiny(decoupled):
        ray_tpu.init(num_cpus=3, worker_env={"JAX_PLATFORMS": "cpu"})
        try:
            cfg = (PPOConfig().environment("CartPole-v1")
                   .env_runners(num_env_runners=1, num_envs_per_env_runner=2,
                                rollout_fragment_length=32)
                   .training(lr=3e-4, train_batch_size=64, minibatch_size=32,
                             num_epochs=1, gamma=0.99, lambda_=0.95)
                   .rl_module(model_config={"fcnet_hiddens": [16]})
                   .debugging(seed=0))
            if decoupled:
                cfg = cfg.decoupled_rollout(
                    enabled=True, blocks_per_update=1, queue_depth=4,
                    max_block_lag=4, weight_sync_interval=1)
            algo = cfg.build_algo()
            n_upd = 0
            try:
                for _ in range(3):
                    if algo.train().get(
                            "num_env_steps_trained" if decoupled
                            else "num_env_steps_sampled"):
                        n_upd += 1
            finally:
                algo.cleanup()
            out = {"update_rounds": n_upd}
            if decoupled:
                out["plane"] = algo.final_plane_stats
            return out
        finally:
            ray_tpu.shutdown()

    ser = tiny(decoupled=False)
    dec = tiny(decoupled=True)
    return {"dry_run": True,
            "serialized": dict(ser, env_steps_per_s=0.0, updates_per_s=0.0,
                               episode_return=0.0),
            "serialized_opt": None,
            "decoupled": dict(dec, env_steps_per_s=0.0, updates_per_s=0.0,
                              episode_return=0.0)}


def _rl_child() -> None:
    """Child body for --rl: three init/shutdown cycles on one process
    (serialized baseline, serialized_opt, decoupled) so every row sees an
    identical platform."""
    if os.environ.get("BENCH_RL_DRY") == "1":
        print("RL_RESULT " + json.dumps(_rl_dry()), flush=True)
        return
    row = {
        "dry_run": False,
        "serialized": _rl_serialized(host_slicing=True, iters=4),
        "serialized_opt": _rl_serialized(host_slicing=False, iters=4),
        "decoupled": _rl_decoupled(iters=10),
    }
    print("RL_RESULT " + json.dumps(row), flush=True)


def run_rl_bench() -> None:
    """--rl: decoupled actor-learner PPO vs the serialized baseline on the
    synthetic-Atari transport workload. Gates (non-zero exit on failure):
    decoupled env-steps/s AND learner-updates/s >= 3x the serialized
    baseline at matched final return, trained-block staleness p99 within
    the configured bound, and zero leaked block admissions after clean
    shutdown. --dry-run swaps in a tiny CartPole config and keeps only the
    structural gates (leaks, staleness, liveness)."""
    dry = "--dry-run" in sys.argv[1:]
    if dry:
        os.environ["BENCH_RL_DRY"] = "1"
    log("rl bench: decoupled rollout/learn plane vs serialized PPO"
        + (" [dry-run]" if dry else ""))
    row = _run_child("rl", 1, timeout=2400)
    ser, dec = row["serialized"], row["decoupled"]
    plane = dec["plane"]
    checks = {
        "learner_made_progress": dec.get("update_rounds", 0) > 0,
        "block_lag_p99_within_bound":
            (plane.get("lag_p99_taken") or 0) <= plane["max_lag"],
        "zero_leaked_block_admissions":
            plane["outstanding"] == 0 and plane["unreleased"] == 0
            and plane.get("worker_outstanding", 0) == 0,
    }
    if not dry:
        checks["env_steps_ge_3x_serialized"] = (
            dec["env_steps_per_s"] >= 3.0 * ser["env_steps_per_s"])
        checks["learner_updates_ge_3x_serialized"] = (
            dec["updates_per_s"] >= 3.0 * ser["updates_per_s"])
        # decoupled trains on ~3x the data in the window; "matched" means
        # it must never come out BELOW the serialized run's return
        checks["matched_final_return"] = (
            dec["episode_return"] >= ser["episode_return"] - 1.0)
        _rl_rewrite_bench_json(row)
    for name, ok in checks.items():
        log(f"rl check {name}: {'PASS' if ok else 'FAIL'}")
    print(json.dumps({
        "metric": "rl_decoupled_env_steps_per_s_atari_synth",
        "value": dec["env_steps_per_s"],
        "unit": "env_steps/s",
        "vs_baseline": round(
            dec["env_steps_per_s"] / max(ser["env_steps_per_s"], 1e-9), 4)
            if not dry else 0.0,
        "secondary": {
            "decoupled_updates_per_s": dec["updates_per_s"],
            "serialized_env_steps_per_s": ser["env_steps_per_s"],
            "serialized_updates_per_s": ser["updates_per_s"],
            "updates_vs_baseline": round(
                dec["updates_per_s"] / max(ser["updates_per_s"], 1e-9), 4)
                if not dry else 0.0,
            "block_lag_p99_taken": plane.get("lag_p99_taken"),
            "checks_passed": sum(checks.values()),
            "checks_total": len(checks),
        },
    }))
    if not all(checks.values()):
        sys.exit(1)


def _rl_rewrite_bench_json(row: dict) -> None:
    """Rewrite RL_BENCH.json in place: refresh the atari-synth PPO rows,
    preserve every other row (data pipeline, shuffle, cartpole, tpu_learner,
    notes) verbatim."""
    path = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                        "RL_BENCH.json")
    try:
        with open(path) as f:
            out = json.load(f)
    except (OSError, ValueError):
        out = {}
    out = {k: v for k, v in out.items()
           if not k.startswith("ppo_atari_synth")
           and not k.startswith("rl_decoupled")}
    for name, r in (("ppo_atari_synth_serialized", row["serialized"]),
                    ("ppo_atari_synth_serialized_opt", row["serialized_opt"]),
                    ("ppo_atari_synth_decoupled", row["decoupled"])):
        out[f"{name}_env_steps_per_s"] = r["env_steps_per_s"]
        out[f"{name}_updates_per_s"] = r["updates_per_s"]
        out[f"{name}_episode_return"] = r["episode_return"]
    out["rl_decoupled_plane_stats"] = row["decoupled"]["plane"]
    out["rl_decoupled_note"] = (
        "atari-synth rows share one geometry: fcnet [8], batch 512, "
        "minibatch 128, 2 epochs. serialized = seed host-slicing loop "
        "(2x4 envs); serialized_opt = device-resident gather SGD; "
        "decoupled = 2x48-env vectorized rollout plane streaming blocks "
        "over the zero-copy data plane, weights broadcast every 3 updates.")
    with open(path, "w") as f:
        json.dump(out, f, indent=2)
    log(f"wrote {path}")


def _winning_grad_sync():
    """The winning --grad-sync config (TRAIN_SYNC_BENCH.json), as a
    GradSyncConfig for the trainer-path MFU row; None when the bench has not
    run or the stock config won."""
    path = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                        "TRAIN_SYNC_BENCH.json")
    try:
        with open(path) as f:
            kw = json.load(f)["winning"]["config"]
        if not kw:
            return None
        from ray_tpu.train import GradSyncConfig

        return GradSyncConfig(**kw)
    except Exception:
        return None


def main() -> None:
    import jax

    if os.environ.get("JAX_PLATFORMS", "").strip().lower() == "cpu":
        # honor the env var even when a sitecustomize PJRT plugin forced the
        # platform at the jax-config level (same dance as __graft_entry__)
        jax.config.update("jax_platforms", "cpu")
    backend = jax.default_backend()
    on_cpu = backend == "cpu"
    dev = jax.devices()[0]
    log(f"backend={backend} device={dev.device_kind if hasattr(dev, 'device_kind') else dev}")

    env_model = os.environ.get("BENCH_MODEL")
    batch = int(os.environ.get("BENCH_BATCH", "4" if on_cpu else "8"))
    seq = int(os.environ.get("BENCH_SEQ", "64" if on_cpu else "2048"))
    steps = int(os.environ.get("BENCH_STEPS", "3" if on_cpu else "20"))
    remat = os.environ.get("BENCH_REMAT", "dots_no_batch")

    if on_cpu or env_model:
        model_name = env_model or "test-tiny"
        mfu, tokens_per_sec = run_one(model_name, batch, seq, steps, remat)
        if on_cpu:
            # smoke the Trainer-path plumbing too (tiny; keeps the TPU-mode code honest)
            _, trainer_tps = run_trainer_path(model_name, batch, seq, steps, remat)
            result = {
                "metric": "train_step_tokens_per_sec_cpu_smoke",
                "value": round(tokens_per_sec, 1),
                "unit": "tokens/s",
                "vs_baseline": 0.0,
                "secondary": {"trainer_fit_tokens_per_sec": round(trainer_tps, 1)},
            }
        else:
            result = {
                "metric": f"train_mfu_{model_name}_b{batch}_s{seq}",
                "value": round(mfu, 4),
                "unit": "mfu_fraction",
                "vs_baseline": round(mfu / 0.40, 4),
            }
        print(json.dumps(result))
        return

    # Headline: llama3-8b LAYER GEOMETRY at single-chip depth — the realistic
    # arithmetic-intensity regime (d_model 4096, GQA 32/8, d_ff 14336). The
    # historical llama-500m number rides along: its 1536-wide matmuls cap MFU
    # near 49% on a v5e regardless of software (geometry-bound, not
    # framework-bound); at 8B geometry the same stack reaches ~66%.
    # sweeps on the chip: remat — dots 66.0% > dots_no_batch 65.7% > full(b8)
    # 65.7%, none OOMs; batch at dots — b4 67.3% < b6 69.7%, b8 OOMs by 296MB
    # (16.04G needed). b6+dots is the HBM-filling sweet spot at this geometry.
    # Trainer-path FIRST (its worker process allocates a full model + optimizer
    # before the in-process bare-step runs fill HBM), then the bare step for
    # comparison. The axon tunnel shares the chip across processes; on a libtpu
    # host with a process-exclusive chip lock the worker may fail to initialize
    # — fall back to the bare-step headline rather than producing no number.
    try:
        mfu_fit, _ = run_trainer_path("llama8b-geom2", 6, 2048, steps, "dots",
                                      grad_sync=_winning_grad_sync())
    except Exception as e:
        log(f"trainer-path failed ({type(e).__name__}: {e}); "
            "falling back to bare-step headline")
        mfu_fit = None
    mfu_8b, _ = run_one("llama8b-geom2", 6, 2048, steps, "dots")
    mfu_500m, _ = run_one("llama-500m", 8, 2048, steps, "dots_no_batch")
    # Headline = the STOCK TRAINER API number — exactly how BASELINE.md:25 words
    # the 40%-MFU north star. The bare step function rides along as secondary.
    headline = mfu_fit if mfu_fit is not None else mfu_8b
    result = {
        "metric": ("train_mfu_llama8b_geometry_trainer_fit_b6_s2048"
                   if mfu_fit is not None
                   else "train_mfu_llama8b_geometry_b6_s2048"),
        "value": round(headline, 4),
        "unit": "mfu_fraction",
        "vs_baseline": round(headline / 0.40, 4),
        "secondary": {
            "train_mfu_llama8b_geometry_bare_step_b6_s2048": round(mfu_8b, 4),
            "train_mfu_llama-500m_b8_s2048": round(mfu_500m, 4),
        },
    }
    print(json.dumps(result))


if __name__ == "__main__":
    if "--grad-sync" in sys.argv[1:]:
        run_grad_sync_bench()
    elif "--pipeline" in sys.argv[1:]:
        run_pipeline_bench()
    elif "--rl" in sys.argv[1:]:
        run_rl_bench()
    else:
        main()
