"""Benchmark: Llama train-step MFU on the available accelerator.

Prints ONE JSON line: {"metric": ..., "value": ..., "unit": ..., "vs_baseline": ...}.
Baseline: the north-star target of 40% MFU via the stock Trainer API (BASELINE.json),
scored here as achieved-MFU / 0.40 on the single-chip flagship-family model.

All diagnostics go to stderr; stdout carries only the JSON line.
"""
import json
import os
import sys
import time


def log(msg: str) -> None:
    print(msg, file=sys.stderr, flush=True)


# TPU bf16 peak FLOP/s per chip by device-kind substring; fallback conservative.
PEAK_FLOPS = {
    "v5 lite": 197e12,
    "v5e": 197e12,
    "v5p": 459e12,
    "v4": 275e12,
    "v6 lite": 918e12,
    "v6e": 918e12,
}


def peak_flops_for(device) -> float:
    kind = getattr(device, "device_kind", "").lower()
    for key, val in PEAK_FLOPS.items():
        if key in kind:
            return val
    return 197e12


def main() -> None:
    import jax
    import jax.numpy as jnp

    backend = jax.default_backend()
    on_cpu = backend == "cpu"
    dev = jax.devices()[0]
    log(f"backend={backend} device={dev.device_kind if hasattr(dev, 'device_kind') else dev}")

    from ray_tpu.models import get_config
    from ray_tpu.train import init_state, make_optimizer, make_train_step

    model_name = os.environ.get("BENCH_MODEL", "test-tiny" if on_cpu else "llama-500m")
    batch = int(os.environ.get("BENCH_BATCH", "4" if on_cpu else "8"))
    seq = int(os.environ.get("BENCH_SEQ", "64" if on_cpu else "2048"))
    steps = int(os.environ.get("BENCH_STEPS", "3" if on_cpu else "20"))

    cfg = get_config(model_name)
    remat_policy = os.environ.get("BENCH_REMAT", "dots_no_batch")
    if remat_policy != cfg.remat_policy:
        import dataclasses

        # save matmul outputs, recompute only elementwise: ~3pp MFU over full
        # remat at this size (HBM still fits b8 s2048 adam states on one v5e)
        cfg = dataclasses.replace(cfg, remat_policy=remat_policy)
    log(f"model={model_name} n_params={cfg.n_params/1e9:.3f}B batch={batch} seq={seq} "
        f"remat={remat_policy}")

    tx = make_optimizer(total_steps=1000)
    state = init_state(jax.random.PRNGKey(0), cfg, tx)
    step = make_train_step(cfg, tx)
    tokens = jax.random.randint(jax.random.PRNGKey(1), (batch, seq + 1), 0, cfg.vocab_size)
    batch_dict = {"tokens": tokens}

    t0 = time.perf_counter()
    state, metrics = step(state, batch_dict)
    # fetch (not block_until_ready): over a remote-device tunnel only a data
    # fetch reliably synchronizes the stream
    first_loss = float(metrics["loss"])
    log(f"compile+first step: {time.perf_counter() - t0:.1f}s loss={first_loss:.3f}")

    t0 = time.perf_counter()
    for _ in range(steps):
        state, metrics = step(state, batch_dict)
    final_loss = float(metrics["loss"])  # fetch = true sync point
    dt = (time.perf_counter() - t0) / steps

    tokens_per_sec = batch * seq / dt
    flops_per_token = 6 * cfg.n_params  # standard fwd+bwd transformer estimate
    mfu = tokens_per_sec * flops_per_token / peak_flops_for(dev)
    log(
        f"step={dt*1e3:.1f}ms tokens/s={tokens_per_sec:,.0f} "
        f"mfu={mfu:.3f} loss={final_loss:.3f}"
    )

    if on_cpu:
        # CPU run is a smoke test; MFU vs TPU peak is meaningless there.
        result = {
            "metric": "train_step_tokens_per_sec_cpu_smoke",
            "value": round(tokens_per_sec, 1),
            "unit": "tokens/s",
            "vs_baseline": 0.0,
        }
    else:
        result = {
            "metric": f"train_mfu_{model_name}_b{batch}_s{seq}",
            "value": round(mfu, 4),
            "unit": "mfu_fraction",
            "vs_baseline": round(mfu / 0.40, 4),
        }
    print(json.dumps(result))


if __name__ == "__main__":
    main()
