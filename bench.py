"""Benchmark: Llama train-step MFU on the available accelerator.

Prints ONE JSON line: {"metric": ..., "value": ..., "unit": ..., "vs_baseline": ...}.
Baseline: the north-star target of 40% MFU via the stock Trainer API (BASELINE.json),
scored here as achieved-MFU / 0.40 on the single-chip flagship-family model.

All diagnostics go to stderr; stdout carries only the JSON line.
"""
import json
import os
import sys
import time


def log(msg: str) -> None:
    print(msg, file=sys.stderr, flush=True)


# TPU bf16 peak FLOP/s per chip by device-kind substring; fallback conservative.
PEAK_FLOPS = {
    "v5 lite": 197e12,
    "v5e": 197e12,
    "v5p": 459e12,
    "v4": 275e12,
    "v6 lite": 918e12,
    "v6e": 918e12,
}


def peak_flops_for(device) -> float:
    kind = getattr(device, "device_kind", "").lower()
    for key, val in PEAK_FLOPS.items():
        if key in kind:
            return val
    return 197e12


def run_one(model_name: str, batch: int, seq: int, steps: int,
            remat_policy: str) -> tuple:
    import jax

    from ray_tpu.models import get_config
    from ray_tpu.train import init_state, make_optimizer, make_train_step

    dev = jax.devices()[0]
    cfg = get_config(model_name)
    if remat_policy != cfg.remat_policy:
        import dataclasses

        # save matmul outputs, recompute only elementwise: a few pp MFU over
        # full remat whenever the saved activations still fit HBM
        cfg = dataclasses.replace(cfg, remat_policy=remat_policy)
    log(f"model={model_name} n_params={cfg.n_params/1e9:.3f}B batch={batch} seq={seq} "
        f"remat={remat_policy}")

    tx = make_optimizer(total_steps=1000)
    state = init_state(jax.random.PRNGKey(0), cfg, tx)
    step = make_train_step(cfg, tx)
    tokens = jax.random.randint(jax.random.PRNGKey(1), (batch, seq + 1), 0, cfg.vocab_size)
    batch_dict = {"tokens": tokens}

    t0 = time.perf_counter()
    state, metrics = step(state, batch_dict)
    # fetch (not block_until_ready): over a remote-device tunnel only a data
    # fetch reliably synchronizes the stream
    first_loss = float(metrics["loss"])
    log(f"compile+first step: {time.perf_counter() - t0:.1f}s loss={first_loss:.3f}")

    t0 = time.perf_counter()
    for _ in range(steps):
        state, metrics = step(state, batch_dict)
    final_loss = float(metrics["loss"])  # fetch = true sync point
    dt = (time.perf_counter() - t0) / steps

    tokens_per_sec = batch * seq / dt
    flops_per_token = 6 * cfg.n_params  # standard fwd+bwd transformer estimate
    mfu = tokens_per_sec * flops_per_token / peak_flops_for(dev)
    log(f"step={dt*1e3:.1f}ms tokens/s={tokens_per_sec:,.0f} "
        f"mfu={mfu:.3f} loss={final_loss:.3f}")
    return mfu, tokens_per_sec


def _trainer_loop(config) -> None:
    """The stock-Trainer-path measurement body: identical model/step/config as
    run_one, but driven inside a JaxTrainer.fit() worker session (BASELINE.md:25
    words the north star as MFU 'via a stock Trainer API' — this measures exactly
    that, not the bare step function)."""
    import dataclasses
    import time

    import jax

    import ray_tpu.train as train
    from ray_tpu.models import get_config
    from ray_tpu.train import init_state, make_optimizer, make_train_step

    cfg = get_config(config["model"])
    if config["remat"] != cfg.remat_policy:
        cfg = dataclasses.replace(cfg, remat_policy=config["remat"])
    batch, seq, steps = config["batch"], config["seq"], config["steps"]
    tx = make_optimizer(total_steps=1000)
    state = init_state(jax.random.PRNGKey(0), cfg, tx)
    step = make_train_step(cfg, tx)
    tokens = jax.random.randint(
        jax.random.PRNGKey(1), (batch, seq + 1), 0, cfg.vocab_size)
    batch_dict = {"tokens": tokens}
    state, metrics = step(state, batch_dict)
    float(metrics["loss"])  # fetch = sync (block_until_ready is unreliable on the tunnel)
    t0 = time.perf_counter()
    for _ in range(steps):
        state, metrics = step(state, batch_dict)
    final_loss = float(metrics["loss"])
    dt = (time.perf_counter() - t0) / steps
    tokens_per_sec = batch * seq / dt
    mfu = tokens_per_sec * 6 * cfg.n_params / peak_flops_for(jax.devices()[0])
    train.report({"mfu": mfu, "tokens_per_sec": tokens_per_sec, "loss": final_loss})


def run_trainer_path(model_name: str, batch: int, seq: int, steps: int,
                     remat_policy: str) -> tuple:
    """Same measurement as run_one but through JaxTrainer.fit() (1 worker owning the
    chip). Returns (mfu, tokens_per_sec) reported from inside the session."""
    import tempfile

    import ray_tpu
    from ray_tpu.air import RunConfig, ScalingConfig
    from ray_tpu.train import JaxConfig, JaxTrainer

    log(f"trainer-path: model={model_name} batch={batch} seq={seq} steps={steps}")
    import jax

    on_cpu = jax.default_backend() == "cpu"
    worker_env = {"JAX_PLATFORMS": "cpu"} if on_cpu else None
    ray_tpu.init(num_cpus=2, worker_env=worker_env)
    try:
        # use_tpu: the worker must be spawned with the "tpu" accel tag — plain
        # CPU workers force JAX_PLATFORMS=cpu and would run the model on host
        scaling = (ScalingConfig(num_workers=1, cpus_per_worker=1.0) if on_cpu
                   else ScalingConfig(num_workers=1, use_tpu=True,
                                      chips_per_worker=1))
        trainer = JaxTrainer(
            _trainer_loop,
            train_loop_config={"model": model_name, "batch": batch, "seq": seq,
                               "steps": steps, "remat": remat_policy},
            backend_config=JaxConfig(collective_group=False),
            scaling_config=scaling,
            run_config=RunConfig(name="bench_trainer_path",
                                 storage_path=tempfile.mkdtemp(prefix="bench_tp_")),
        )
        result = trainer.fit()
        if result.error is not None:
            raise RuntimeError(f"trainer-path bench failed: {result.error}")
        m = result.metrics
        log(f"trainer-path: mfu={m['mfu']:.3f} tokens/s={m['tokens_per_sec']:,.0f} "
            f"loss={m['loss']:.3f}")
        return m["mfu"], m["tokens_per_sec"]
    finally:
        ray_tpu.shutdown()


def main() -> None:
    import jax

    if os.environ.get("JAX_PLATFORMS", "").strip().lower() == "cpu":
        # honor the env var even when a sitecustomize PJRT plugin forced the
        # platform at the jax-config level (same dance as __graft_entry__)
        jax.config.update("jax_platforms", "cpu")
    backend = jax.default_backend()
    on_cpu = backend == "cpu"
    dev = jax.devices()[0]
    log(f"backend={backend} device={dev.device_kind if hasattr(dev, 'device_kind') else dev}")

    env_model = os.environ.get("BENCH_MODEL")
    batch = int(os.environ.get("BENCH_BATCH", "4" if on_cpu else "8"))
    seq = int(os.environ.get("BENCH_SEQ", "64" if on_cpu else "2048"))
    steps = int(os.environ.get("BENCH_STEPS", "3" if on_cpu else "20"))
    remat = os.environ.get("BENCH_REMAT", "dots_no_batch")

    if on_cpu or env_model:
        model_name = env_model or "test-tiny"
        mfu, tokens_per_sec = run_one(model_name, batch, seq, steps, remat)
        if on_cpu:
            # smoke the Trainer-path plumbing too (tiny; keeps the TPU-mode code honest)
            _, trainer_tps = run_trainer_path(model_name, batch, seq, steps, remat)
            result = {
                "metric": "train_step_tokens_per_sec_cpu_smoke",
                "value": round(tokens_per_sec, 1),
                "unit": "tokens/s",
                "vs_baseline": 0.0,
                "secondary": {"trainer_fit_tokens_per_sec": round(trainer_tps, 1)},
            }
        else:
            result = {
                "metric": f"train_mfu_{model_name}_b{batch}_s{seq}",
                "value": round(mfu, 4),
                "unit": "mfu_fraction",
                "vs_baseline": round(mfu / 0.40, 4),
            }
        print(json.dumps(result))
        return

    # Headline: llama3-8b LAYER GEOMETRY at single-chip depth — the realistic
    # arithmetic-intensity regime (d_model 4096, GQA 32/8, d_ff 14336). The
    # historical llama-500m number rides along: its 1536-wide matmuls cap MFU
    # near 49% on a v5e regardless of software (geometry-bound, not
    # framework-bound); at 8B geometry the same stack reaches ~66%.
    # sweeps on the chip: remat — dots 66.0% > dots_no_batch 65.7% > full(b8)
    # 65.7%, none OOMs; batch at dots — b4 67.3% < b6 69.7%, b8 OOMs by 296MB
    # (16.04G needed). b6+dots is the HBM-filling sweet spot at this geometry.
    # Trainer-path FIRST (its worker process allocates a full model + optimizer
    # before the in-process bare-step runs fill HBM), then the bare step for
    # comparison. The axon tunnel shares the chip across processes; on a libtpu
    # host with a process-exclusive chip lock the worker may fail to initialize
    # — fall back to the bare-step headline rather than producing no number.
    try:
        mfu_fit, _ = run_trainer_path("llama8b-geom2", 6, 2048, steps, "dots")
    except Exception as e:
        log(f"trainer-path failed ({type(e).__name__}: {e}); "
            "falling back to bare-step headline")
        mfu_fit = None
    mfu_8b, _ = run_one("llama8b-geom2", 6, 2048, steps, "dots")
    mfu_500m, _ = run_one("llama-500m", 8, 2048, steps, "dots_no_batch")
    # Headline = the STOCK TRAINER API number — exactly how BASELINE.md:25 words
    # the 40%-MFU north star. The bare step function rides along as secondary.
    headline = mfu_fit if mfu_fit is not None else mfu_8b
    result = {
        "metric": ("train_mfu_llama8b_geometry_trainer_fit_b6_s2048"
                   if mfu_fit is not None
                   else "train_mfu_llama8b_geometry_b6_s2048"),
        "value": round(headline, 4),
        "unit": "mfu_fraction",
        "vs_baseline": round(headline / 0.40, 4),
        "secondary": {
            "train_mfu_llama8b_geometry_bare_step_b6_s2048": round(mfu_8b, 4),
            "train_mfu_llama-500m_b8_s2048": round(mfu_500m, 4),
        },
    }
    print(json.dumps(result))


if __name__ == "__main__":
    main()
