"""LLM batch-stage tests (reference batch/stages/: tokenize, detokenize, http)."""
import json
import threading

import numpy as np
import pytest

from ray_tpu.llm import DetokenizeStage, HttpRequestStage, TokenizeStage


def test_tokenize_detokenize_roundtrip():
    tok = TokenizeStage("byte")
    batch = {"prompt": np.array(["hello", "wørld"], dtype=object)}
    out = tok(batch)
    assert out["num_prompt_tokens"][0] == 6  # BOS + 5 bytes
    assert out["num_prompt_tokens"][1] > out["num_prompt_tokens"][0]  # multi-byte chars
    detok = DetokenizeStage("byte")
    back = detok({"generated_tokens": out["tokenized_prompt"]})
    assert list(back["generated_text"]) == ["hello", "wørld"]


def test_http_request_stage_hits_openai_endpoint():
    from http.server import BaseHTTPRequestHandler, HTTPServer

    seen = []

    class Handler(BaseHTTPRequestHandler):
        def do_POST(self):
            body = json.loads(self.rfile.read(int(self.headers["Content-Length"])))
            seen.append(body)
            resp = {"choices": [{"text": body["prompt"].upper()}]}
            data = json.dumps(resp).encode()
            self.send_response(200)
            self.send_header("Content-Type", "application/json")
            self.send_header("Content-Length", str(len(data)))
            self.end_headers()
            self.wfile.write(data)

        def log_message(self, *a):
            pass

    srv = HTTPServer(("127.0.0.1", 0), Handler)
    t = threading.Thread(target=srv.serve_forever, daemon=True)
    t.start()
    try:
        stage = HttpRequestStage(
            f"http://127.0.0.1:{srv.server_port}/v1/completions",
            model="m", sampling_params={"max_tokens": 8})
        out = stage({"prompt": np.array(["abc", "def"], dtype=object)})
        assert list(out["generated_text"]) == ["ABC", "DEF"]
        assert seen[0]["model"] == "m" and seen[0]["max_tokens"] == 8
    finally:
        srv.shutdown()


def test_http_request_stage_chat_response_shape():
    from http.server import BaseHTTPRequestHandler, HTTPServer

    class Handler(BaseHTTPRequestHandler):
        def do_POST(self):
            resp = {"choices": [{"message": {"content": "hi there"}}]}
            data = json.dumps(resp).encode()
            self.send_response(200)
            self.send_header("Content-Length", str(len(data)))
            self.end_headers()
            self.wfile.write(data)

        def log_message(self, *a):
            pass

    srv = HTTPServer(("127.0.0.1", 0), Handler)
    threading.Thread(target=srv.serve_forever, daemon=True).start()
    try:
        stage = HttpRequestStage(f"http://127.0.0.1:{srv.server_port}/v1/chat/completions")
        out = stage({"prompt": np.array(["x"], dtype=object)})
        assert list(out["generated_text"]) == ["hi there"]
    finally:
        srv.shutdown()


def test_prepare_image_stage_sources(tmp_path):
    """Reference prepare_image_stage.py: ndarray / file / data-URI / OpenAI
    vision-message refs all resolve to fixed-size float32 pixel tensors."""
    import base64
    import io

    from PIL import Image

    from ray_tpu.llm import PrepareImageStage

    img = (np.arange(20 * 30 * 3).reshape(20, 30, 3) % 255).astype(np.uint8)
    path = str(tmp_path / "a.png")
    Image.fromarray(img).save(path)
    buf = io.BytesIO()
    Image.fromarray(img).save(buf, format="PNG")
    data_uri = "data:image/png;base64," + base64.b64encode(buf.getvalue()).decode()

    stage = PrepareImageStage(size=(16, 16))
    messages_col = np.empty(1, dtype=object)
    messages_col[0] = [{"role": "user", "content": [
        {"type": "text", "text": "what is this?"},
        {"type": "image_url", "image_url": {"url": data_uri}},
    ]}]
    batch = {
        "image": np.array([img, path, buf.getvalue()], dtype=object),
        "id": np.arange(3),
    }
    out = stage(batch)
    assert out["num_images"].tolist() == [1, 1, 1]
    for t in out["images"]:
        assert t.shape == (1, 16, 16, 3) and t.dtype == np.float32
        assert 0.0 <= float(t.min()) and float(t.max()) <= 1.0
    # vision messages
    out2 = stage({"messages": messages_col, "id": np.arange(1)})
    assert out2["num_images"].tolist() == [1]
    assert out2["images"][0].shape == (1, 16, 16, 3)


class _VLMEngineStub:
    """Engine-shaped stage: consumes the pixel tensors + prompt, returns text
    (the real VLM engine slot-ins here; shapes are already static)."""

    def __call__(self, batch):
        texts = []
        from ray_tpu.llm import PrepareImageStage

        for imgs in batch["images"]:
            imgs = PrepareImageStage.to_tensor(imgs, size=(16, 16))
            assert imgs.shape[1:] == (16, 16, 3)
            texts.append(f"saw {imgs.shape[0]} image(s), mean={imgs.mean():.3f}")
        out = dict(batch)
        out["generated_text"] = np.array(texts, dtype=object)
        return out


def test_vlm_batch_e2e_from_read_images(rt, tmp_path):
    """read_images -> PrepareImageStage -> engine stub, through the real Data
    processor (VERDICT r2 #10 'done' bar)."""
    from PIL import Image

    import ray_tpu.data as rtd
    from ray_tpu.llm import PrepareImageStage, Processor

    for i in range(4):
        arr = np.full((12, 10, 3), i * 40, np.uint8)
        Image.fromarray(arr).save(str(tmp_path / f"im{i}.png"))
    ds = rtd.read_images([str(tmp_path / f"im{i}.png") for i in range(4)])
    proc = Processor([
        lambda d: d.map_batches(PrepareImageStage(size=(16, 16)), batch_size=2),
        lambda d: d.map_batches(_VLMEngineStub(), batch_size=2),
    ])
    rows = proc(ds).take_all()
    assert len(rows) == 4
    for r in rows:
        assert r["num_images"] == 1
        assert r["generated_text"].startswith("saw 1 image")
