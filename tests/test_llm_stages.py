"""LLM batch-stage tests (reference batch/stages/: tokenize, detokenize, http)."""
import json
import threading

import numpy as np
import pytest

from ray_tpu.llm import DetokenizeStage, HttpRequestStage, TokenizeStage


def test_tokenize_detokenize_roundtrip():
    tok = TokenizeStage("byte")
    batch = {"prompt": np.array(["hello", "wørld"], dtype=object)}
    out = tok(batch)
    assert out["num_prompt_tokens"][0] == 6  # BOS + 5 bytes
    assert out["num_prompt_tokens"][1] > out["num_prompt_tokens"][0]  # multi-byte chars
    detok = DetokenizeStage("byte")
    back = detok({"generated_tokens": out["tokenized_prompt"]})
    assert list(back["generated_text"]) == ["hello", "wørld"]


def test_http_request_stage_hits_openai_endpoint():
    from http.server import BaseHTTPRequestHandler, HTTPServer

    seen = []

    class Handler(BaseHTTPRequestHandler):
        def do_POST(self):
            body = json.loads(self.rfile.read(int(self.headers["Content-Length"])))
            seen.append(body)
            resp = {"choices": [{"text": body["prompt"].upper()}]}
            data = json.dumps(resp).encode()
            self.send_response(200)
            self.send_header("Content-Type", "application/json")
            self.send_header("Content-Length", str(len(data)))
            self.end_headers()
            self.wfile.write(data)

        def log_message(self, *a):
            pass

    srv = HTTPServer(("127.0.0.1", 0), Handler)
    t = threading.Thread(target=srv.serve_forever, daemon=True)
    t.start()
    try:
        stage = HttpRequestStage(
            f"http://127.0.0.1:{srv.server_port}/v1/completions",
            model="m", sampling_params={"max_tokens": 8})
        out = stage({"prompt": np.array(["abc", "def"], dtype=object)})
        assert list(out["generated_text"]) == ["ABC", "DEF"]
        assert seen[0]["model"] == "m" and seen[0]["max_tokens"] == 8
    finally:
        srv.shutdown()


def test_http_request_stage_chat_response_shape():
    from http.server import BaseHTTPRequestHandler, HTTPServer

    class Handler(BaseHTTPRequestHandler):
        def do_POST(self):
            resp = {"choices": [{"message": {"content": "hi there"}}]}
            data = json.dumps(resp).encode()
            self.send_response(200)
            self.send_header("Content-Length", str(len(data)))
            self.end_headers()
            self.wfile.write(data)

        def log_message(self, *a):
            pass

    srv = HTTPServer(("127.0.0.1", 0), Handler)
    threading.Thread(target=srv.serve_forever, daemon=True).start()
    try:
        stage = HttpRequestStage(f"http://127.0.0.1:{srv.server_port}/v1/chat/completions")
        out = stage({"prompt": np.array(["x"], dtype=object)})
        assert list(out["generated_text"]) == ["hi there"]
    finally:
        srv.shutdown()
