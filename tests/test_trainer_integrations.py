"""Other-framework trainer integrations (reference python/ray/train/{tensorflow,
xgboost,lightgbm,huggingface,lightning}; SURVEY.md §2.4 "other-framework trainers").

TF and HF transformers are present in this image, so those paths run for real:
- TensorflowTrainer: TF_CONFIG cluster assembly + MultiWorkerMirroredStrategy
  coordinating an actual 2-worker Keras fit.
- huggingface.prepare_trainer: HF Trainer pulling batches from a Data shard,
  RayTrainReportCallback reporting metrics+checkpoint through the session.
xgboost / lightgbm / lightning are optional deps; their trainers must import
cleanly without the library and fail with a clear ImportError at use time.
"""
import json

import numpy as np
import pytest

import ray_tpu.train as train
from ray_tpu.train import ScalingConfig, TensorflowTrainer, TorchTrainer


def _tf_config_loop(config):
    import os

    cfg = json.loads(os.environ["TF_CONFIG"])
    ctx = train.get_context()
    train.report({
        "n_workers": len(cfg["cluster"]["worker"]),
        "index": cfg["task"]["index"],
        "rank": ctx.get_world_rank(),
        "type": cfg["task"]["type"],
    })


def test_tf_config_cluster_spec(rt):
    """Every worker sees the full worker list and its own index == train rank
    (reference tensorflow/config.py:24)."""
    pytest.importorskip("tensorflow")
    result = TensorflowTrainer(
        _tf_config_loop, scaling_config=ScalingConfig(num_workers=2)).fit()
    per_worker = result.all_metrics
    assert len(per_worker) == 2
    for m in per_worker:
        assert m["n_workers"] == 2 and m["type"] == "worker"
        assert m["index"] == m["rank"]


def _tf_mwms_loop(config):
    import tensorflow as tf

    strategy = tf.distribute.MultiWorkerMirroredStrategy()
    rng = np.random.default_rng(0)
    X = rng.normal(size=(64, 4)).astype("float32")
    y = X.sum(axis=1, keepdims=True)
    with strategy.scope():
        w = tf.Variable(tf.zeros((4, 1)))
        b = tf.Variable(tf.zeros((1,)))
        opt = tf.keras.optimizers.SGD(0.1)

    @tf.function
    def dist_step(xb, yb):
        def replica_step(x, yy):
            with tf.GradientTape() as tape:
                loss = tf.reduce_mean(tf.square(tf.matmul(x, w) + b - yy))
            grads = tape.gradient(loss, [w, b])
            opt.apply_gradients(zip(grads, [w, b]))
            return loss

        per_replica = strategy.run(replica_step, args=(xb, yb))
        return strategy.reduce(tf.distribute.ReduceOp.MEAN, per_replica, axis=None)

    ds = tf.data.Dataset.from_tensor_slices((X, y)).batch(16).repeat()
    dist_ds = iter(strategy.experimental_distribute_dataset(ds))
    loss = None
    for _ in range(20):
        xb, yb = next(dist_ds)
        loss = dist_step(xb, yb)
    train.report({
        "loss": float(loss),
        "replicas": int(strategy.num_replicas_in_sync),
        "weights": w.numpy().ravel().tolist(),
    })


def test_tf_multiworker_mirrored_fit(rt):
    """MultiWorkerMirroredStrategy actually syncs over the TF_CONFIG cluster: 2
    replicas allreduce gradients, loss drops, and both workers end with
    identical weights (reference tensorflow_trainer.py e2e)."""
    pytest.importorskip("tensorflow")
    result = TensorflowTrainer(
        _tf_mwms_loop, scaling_config=ScalingConfig(num_workers=2)).fit()
    assert result.metrics["replicas"] == 2
    assert result.metrics["loss"] < 0.5
    per_worker = result.all_metrics
    assert len(per_worker) == 2
    np.testing.assert_allclose(per_worker[0]["weights"], per_worker[1]["weights"],
                               rtol=1e-5)


# ------------------------------------------------------- huggingface (real)

def _hf_loop(config):
    import torch
    import transformers

    from ray_tpu.train.huggingface import RayTrainReportCallback, prepare_trainer

    torch.manual_seed(0)
    cfg = transformers.GPT2Config(
        n_embd=32, n_layer=2, n_head=2, vocab_size=128, n_positions=32)
    model = transformers.GPT2LMHeadModel(cfg)

    shard = train.get_dataset_shard("train")
    args = transformers.TrainingArguments(
        output_dir=config["out"],
        per_device_train_batch_size=4,
        max_steps=4,
        save_strategy="steps",
        save_steps=4,
        logging_steps=2,
        report_to=[],
        use_cpu=True,
        disable_tqdm=True,
    )

    trainer = transformers.Trainer(model=model, args=args, train_dataset=shard)
    trainer = prepare_trainer(trainer)
    trainer.add_callback(RayTrainReportCallback())
    trainer.train()


def test_hf_transformers_trainer_with_data_shard(rt, tmp_path):
    """prepare_trainer reroutes the HF dataloader through the Data shard, the
    report callback surfaces loss + an HF checkpoint dir
    (reference _transformers_utils.py:30,104)."""
    pytest.importorskip("transformers")
    import ray_tpu.data as data

    ids = np.arange(32 * 16, dtype=np.int64).reshape(32, 16) % 128
    ds = data.from_items([{"input_ids": row, "labels": row} for row in ids])
    trainer = TorchTrainer(
        _hf_loop,
        train_loop_config={"out": str(tmp_path / "hf_out")},
        scaling_config=ScalingConfig(num_workers=1),
        datasets={"train": ds},
    )
    result = trainer.fit()
    assert "loss" in result.metrics or "train_loss" in result.metrics
    assert result.checkpoint is not None
    import os

    with result.checkpoint.as_directory() as d:
        assert "checkpoint" in os.listdir(d)


# --------------------------------------- optional-dep gating (xgb/lgbm/pl)

def test_gbdt_trainers_importable_without_libs():
    """Modules import and configs construct with the library absent; only the
    backend's first real call raises the install hint."""
    from ray_tpu.train import LightGBMTrainer, XGBoostTrainer
    from ray_tpu.train.gbdt import get_network_params, get_rabit_args

    assert get_rabit_args() == {} and get_network_params() == {}
    for cls in (XGBoostTrainer, LightGBMTrainer):
        t = cls(lambda c: None, scaling_config=ScalingConfig(num_workers=1))
        assert t.backend_config is not None

    try:
        import xgboost  # noqa: F401
    except ImportError:
        from ray_tpu.train.gbdt import XGBoostBackend

        with pytest.raises(ImportError, match="xgboost"):
            XGBoostBackend().on_training_start(_FakeGroup(), None)


class _FakeGroup:
    workers = []

    def __len__(self):
        return 1


def test_lightning_gated_import():
    import ray_tpu.train.lightning as L

    try:
        import pytorch_lightning  # noqa: F401
        has_pl = True
    except ImportError:
        has_pl = False
    if not has_pl:
        with pytest.raises(ImportError, match="lightning"):
            L.RayDDPStrategy()
    else:
        assert L.RayDDPStrategy() is not None
