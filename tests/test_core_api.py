"""Core API tests: tasks, objects, actors, wait, errors, retries.

Mirrors the reference's python/ray/tests/test_basic*.py coverage at round-1 scope.
"""
import time

import numpy as np
import pytest


def test_put_get_small(rt):
    ref = rt.put({"a": 1, "b": [1, 2, 3]})
    assert rt.get(ref) == {"a": 1, "b": [1, 2, 3]}


def test_put_get_large_numpy_zero_copy(rt):
    arr = np.arange(1_000_000, dtype=np.float32)
    ref = rt.put(arr)
    out = rt.get(ref)
    np.testing.assert_array_equal(arr, out)
    # Large objects go through shared memory; the result is a view, not a copy.
    assert not out.flags["OWNDATA"]


def test_simple_task(rt):
    @rt.remote
    def add(a, b):
        return a + b

    assert rt.get(add.remote(1, 2)) == 3


def test_task_with_ref_args(rt):
    @rt.remote
    def mul(a, b):
        return a * b

    x = rt.put(6)
    y = mul.remote(x, 7)
    assert rt.get(y) == 42


def test_chained_tasks(rt):
    @rt.remote
    def inc(x):
        return x + 1

    ref = inc.remote(0)
    for _ in range(4):
        ref = inc.remote(ref)
    assert rt.get(ref) == 5


def test_num_returns(rt):
    @rt.remote(num_returns=3)
    def three():
        return 1, 2, 3

    a, b, c = three.remote()
    assert rt.get([a, b, c]) == [1, 2, 3]


def test_task_error_propagates(rt):
    @rt.remote
    def boom():
        raise ValueError("kapow")

    with pytest.raises(rt.TaskError) as ei:
        rt.get(boom.remote())
    assert "kapow" in str(ei.value)


def test_error_propagates_through_chain(rt):
    @rt.remote
    def boom():
        raise ValueError("origin")

    @rt.remote
    def passthrough(x):
        return x

    with pytest.raises(rt.TaskError):
        rt.get(passthrough.remote(boom.remote()))


def test_nested_tasks(rt):
    @rt.remote
    def inner(x):
        return x * 2

    @rt.remote
    def outer(x):
        import ray_tpu

        return ray_tpu.get(inner.remote(x)) + 1

    assert rt.get(outer.remote(10)) == 21


def test_nested_put_get(rt):
    @rt.remote
    def roundtrip():
        import ray_tpu

        ref = ray_tpu.put(np.ones(200_000, dtype=np.float32))
        return float(ray_tpu.get(ref).sum())

    assert rt.get(roundtrip.remote()) == 200_000.0


def test_wait(rt):
    @rt.remote
    def fast():
        return "fast"

    @rt.remote
    def slow():
        time.sleep(1.5)
        return "slow"

    f, s = fast.remote(), slow.remote()
    ready, pending = rt.wait([f, s], num_returns=1, timeout=10)
    assert ready == [f]
    assert pending == [s]
    assert rt.get(s) == "slow"


def test_get_timeout(rt):
    @rt.remote
    def sleepy():
        time.sleep(30)

    ref = sleepy.remote()
    with pytest.raises(rt.GetTimeoutError):
        rt.get(ref, timeout=0.2)
    rt.cancel(ref, force=True)


def test_large_arg_auto_put(rt):
    @rt.remote
    def total(arr):
        return float(arr.sum())

    big = np.ones(500_000, dtype=np.float32)
    assert rt.get(total.remote(big)) == 500_000.0


def test_options_override(rt):
    @rt.remote
    def whoami():
        return "ok"

    assert rt.get(whoami.options(num_cpus=0.5, name="renamed").remote()) == "ok"


def test_retry_exceptions(rt):
    @rt.remote(max_retries=3, retry_exceptions=True)
    def flaky(key):
        import os
        import tempfile

        marker = os.path.join(tempfile.gettempdir(), f"flaky_{key}")
        if not os.path.exists(marker):
            with open(marker, "w") as f:
                f.write("1")
            raise RuntimeError("first attempt fails")
        return "recovered"

    key = str(time.time()).replace(".", "")
    assert rt.get(flaky.remote(key)) == "recovered"


def test_cluster_resources(rt):
    res = rt.cluster_resources()
    assert res.get("CPU", 0) >= 4
