"""Paged P/D KV handoff tests (per-page overlapped streaming over the striped
data plane — core/device_plane.py PagedKVHandle/PagedKVFetch + llm/engine.py
admission overlap).

Tier-1 budget: every test shares ONE module-scoped set of compiled paged
engines (`pd_engines`) — the paged burst program compiles once. Load-shaped
scenarios live in bench_serve.py --pd, not here.
"""
import time

import pytest

from ray_tpu.llm import JaxLLMEngine, LLMConfig, SamplingParams

PROMPT = [1, 7, 42, 99, 5]


def _params(max_tokens=6):
    return SamplingParams(max_tokens=max_tokens, temperature=0.0,
                          stop_token_ids=[-1])


def _cfg():
    return LLMConfig(model_id="pd-paged", model_source="test-tiny",
                     max_num_seqs=2, max_model_len=64)


@pytest.fixture(scope="module")
def pd_engines(rt):
    """(prefill, decode, colocated-reference) — compiled once for the module.

    Needs the session cluster (`rt`): the device plane's control channel
    authenticates against the cluster authkey, and paged handoff requires it.
    """
    prefill = JaxLLMEngine(_cfg())
    decode = JaxLLMEngine(_cfg())
    colo = JaxLLMEngine(_cfg())
    yield prefill, decode, colo
    for e in (prefill, decode, colo):
        e.shutdown()


def _decode_all(decode, pre, params):
    ids = []
    for chunk in decode.generate_from_prefill(pre, params):
        ids.extend(chunk.token_ids)
    return ids


def test_paged_handoff_matches_colocated(pd_engines):
    """The paged per-page pull path reproduces the colocated greedy output,
    and the consumer's release ack — not the TTL backstop — drains the
    prefill engine's export bookkeeping."""
    from ray_tpu.core.device_plane import PagedKVHandle, plane

    prefill, decode, colo = pd_engines
    params = _params()
    want = colo.generate_sync(PROMPT, params).token_ids

    pre = prefill.prefill_only(PROMPT, params)
    assert isinstance(pre["kv_handle"], PagedKVHandle)
    assert pre["kv_handle"].n_pages >= 1
    assert _decode_all(decode, pre, params) == want

    # release-ack propagation is async (arm channel + listener); the TTL
    # backstop is minutes out, so draining within seconds proves the ack path
    deadline = time.monotonic() + 10
    while time.monotonic() < deadline:
        if (prefill.metrics()["pd_exports_live"] == 0
                and plane().stats()["exports_live"] == 0):
            break
        time.sleep(0.05)
    assert prefill.metrics()["pd_exports_live"] == 0
    assert plane().stats()["exports_live"] == 0


def test_first_token_streams_before_pages_land(pd_engines):
    """Overlap contract: the prefill-sampled first token rides the ~1 KB
    handle and is emitted immediately, while the page pulls (here delayed by
    an armed fail point) are still in flight."""
    from ray_tpu.util import fault_injection as fi

    prefill, decode, _ = pd_engines
    params = _params(max_tokens=4)
    pre = prefill.prefill_only(PROMPT, params)
    n_pages = pre["kv_handle"].n_pages
    fi.arm("llm.pd.handoff", "delay", delay_s=2.0, count=1)
    try:
        t0 = time.monotonic()
        gen = decode.generate_from_prefill(pre, params)
        first = next(iter(gen))
        ttft = time.monotonic() - t0
        ids = list(first.token_ids)
        for chunk in gen:
            ids.extend(chunk.token_ids)
    finally:
        fi.disarm("llm.pd.handoff")
    assert first.token_ids, "first chunk must carry the prefill-sampled token"
    assert ttft < 1.5, (
        f"first token took {ttft:.2f}s — it must not wait on the armed "
        f"2s page delay ({n_pages} pages)")
    assert len(ids) == 4  # transfer completed and decode finished the request


def test_midtransfer_fault_is_typed_and_host_fallback_recovers(pd_engines):
    """An injected pull failure surfaces as DevicePlaneError (the class the
    router's fallback matches on), and the host-path retry — release the
    orphaned export, re-prefill with force_host — still matches colocated."""
    from ray_tpu.core.device_plane import DevicePlaneError
    from ray_tpu.util import fault_injection as fi

    prefill, decode, colo = pd_engines
    params = _params()
    want = colo.generate_sync(PROMPT, params).token_ids

    pre = prefill.prefill_only(PROMPT, params)
    fi.arm("llm.pd.handoff", "error", count=1)
    try:
        with pytest.raises(DevicePlaneError):
            _decode_all(decode, pre, params)
    finally:
        fi.disarm("llm.pd.handoff")
    # router fallback choreography at engine level
    prefill.release_prefill_export(pre["kv_key"])
    assert prefill.metrics()["pd_exports_live"] == 0
    pre2 = prefill.prefill_only(PROMPT, params, force_host=True)
    assert "kv_handle" not in pre2
    assert _decode_all(decode, pre2, params) == want


def test_released_export_raises_eagerly(pd_engines):
    """A dead export (producer released/pruned it) must fail the decode-side
    fetch at the liveness probe — a typed error in milliseconds, not a
    timeout burn."""
    from ray_tpu.core.device_plane import DevicePlaneError

    prefill, decode, _ = pd_engines
    params = _params(max_tokens=4)
    pre = prefill.prefill_only(PROMPT, params)
    prefill.release_prefill_export(pre["kv_key"])
    t0 = time.monotonic()
    with pytest.raises(DevicePlaneError, match="released"):
        _decode_all(decode, pre, params)
    assert time.monotonic() - t0 < 5.0  # eager stat probe, no timeout burn


def test_build_pd_app_pool_autoscaling_configs():
    """build_pd_openai_app wires independent slo-mode autoscaling per pool:
    prefill pinned to the TTFT SLO, decode driven by queue depth."""
    from ray_tpu.llm.server import build_pd_openai_app

    cfg = LLMConfig(model_id="pd-as", model_source="byte-tiny",
                    max_num_seqs=2, max_model_len=64)
    app = build_pd_openai_app(
        cfg, num_prefill=1, max_prefill=3, num_decode=2, max_decode=5,
        ttft_slo_name="llm-ttft")
    prefill_app, decode_app = app.args[0], app.args[1]
    pa = prefill_app.deployment.config.autoscaling_config
    da = decode_app.deployment.config.autoscaling_config
    assert pa.mode == "slo" and pa.slo_names == ["llm-ttft"]
    assert (pa.min_replicas, pa.max_replicas) == (1, 3)
    assert da.mode == "slo" and da.slo_names is None
    assert (da.min_replicas, da.max_replicas) == (2, 5)
    # without caps the pools stay pinned — no autoscaling config
    pinned = build_pd_openai_app(cfg)
    assert pinned.args[0].deployment.config.autoscaling_config is None
    assert pinned.args[1].deployment.config.autoscaling_config is None


@pytest.mark.slow
def test_chaos_prefill_killed_mid_handoff(rt):
    """SIGKILL the prefill replica while the decode side is mid-pull (a fail
    point holds the transfer open): the stream fails with a typed error well
    inside the stall bound, the router's host fallback completes the request
    against the replacement replica, and no KV export is left pinned."""
    from ray_tpu import serve
    from ray_tpu.llm import build_pd_openai_app
    from ray_tpu.util.fault_injection import ChaosController

    cfg = LLMConfig(model_id="pd-chaos", model_source="byte-tiny",
                    max_num_seqs=2, max_model_len=64)
    body = {"messages": [{"role": "user", "content": "hi"}], "max_tokens": 6,
            "temperature": 0.0}
    try:
        serve.run(build_pd_openai_app(cfg), name="pd-chaos",
                  route_prefix="/pd-chaos")
        h = serve.get_app_handle("pd-chaos")
        want = h.options(method_name="chat").remote(dict(body)).result()
        chaos = ChaosController()
        # hold every page pull open 3s so the kill lands mid-handoff
        assert chaos.arm_replica("pd-chaos", "llm-pd:decode",
                                 "llm.pd.handoff", mode="delay",
                                 delay_s=3.0) >= 1

        import threading

        got, err = {}, {}

        def run():
            try:
                got["resp"] = h.options(method_name="chat").remote(
                    dict(body)).result()
            except Exception as e:  # surfaced to the main thread's asserts
                err["e"] = e

        t = threading.Thread(target=run, daemon=True)
        t0 = time.monotonic()
        t.start()
        time.sleep(1.0)  # prefill done, decode stuck inside the armed delay
        assert chaos.kill_replica("pd-chaos", "llm-pd:prefill", index=0)
        t.join(timeout=120)
        assert not t.is_alive(), "request did not complete after the kill"
        assert "e" not in err, f"request lost: {err.get('e')!r}"
        assert time.monotonic() - t0 < 90
        resp = got["resp"]
        assert resp["choices"][0]["message"]["content"] == \
            want["choices"][0]["message"]["content"]
        # replacement prefill replica must pin nothing: the fallback path
        # released the orphan and host-path prefills never export
        chaos.disarm_replica("pd-chaos", "llm-pd:decode")
        pre_h = serve.get_deployment_handle("llm-pd:prefill", "pd-chaos")
        deadline = time.monotonic() + 15
        live = None
        while time.monotonic() < deadline:
            live = pre_h.options(method_name="metrics").remote().result()[
                "pd_exports_live"]
            if live == 0:
                break
            time.sleep(0.25)
        assert live == 0, f"leaked {live} prefill KV exports past recovery"
    finally:
        serve.delete("pd-chaos")
