"""serve local testing mode (reference _private/local_testing_mode.py): no cluster."""
from ray_tpu import serve


def test_local_testing_class_deployment_no_cluster():
    @serve.deployment
    class Doubler:
        def __call__(self, x):
            return x * 2

        def triple(self, x):
            return x * 3

    h = serve.run(Doubler.bind(), _local_testing_mode=True)
    assert h.remote(21).result() == 42
    assert h.options(method_name="triple").remote(5).result() == 15
    assert h.triple.remote(4).result() == 12


def test_local_testing_composed_graph():
    @serve.deployment
    class Adder:
        def __init__(self, offset):
            self.offset = offset

        def __call__(self, x):
            return x + self.offset

    @serve.deployment
    class Ingress:
        def __init__(self, adder):
            self.adder = adder

        def __call__(self, x):
            return self.adder.remote(x).result() * 10

    h = serve.run(Ingress.bind(Adder.bind(5)), _local_testing_mode=True)
    assert h.remote(1).result() == 60


def test_local_testing_function_deployment_and_async():
    @serve.deployment
    def double(x):
        return x * 2

    h = serve.run(double.bind(), _local_testing_mode=True)
    assert h.remote(3).result() == 6

    @serve.deployment
    class AsyncD:
        async def __call__(self, x):
            return x + 1

    h2 = serve.run(AsyncD.bind(), _local_testing_mode=True)
    assert h2.remote(41).result() == 42
