"""Device-resident object fast path (reference: experimental/gpu_object_manager;
SURVEY.md §2.3 GPU objects row)."""
import numpy as np

import jax
import jax.numpy as jnp


def test_same_process_get_returns_original_array(rt):
    x = jnp.arange(1024, dtype=jnp.float32) * 2.0
    ref = rt.put(x)
    y = rt.get(ref)
    assert y is x  # the literal same device array — zero copies
    del ref


def test_fast_path_degrades_to_durable_copy(rt):
    x = jnp.ones((256,), jnp.float32) * 3.0
    ref = rt.put(x)
    del x  # producer drops its reference: weak registry entry dies
    import gc

    gc.collect()
    y = rt.get(ref)  # falls back to the serialized host copy
    np.testing.assert_array_equal(np.asarray(y), np.full((256,), 3.0, np.float32))
    del ref


def test_donated_array_falls_back_to_durable_copy(rt):
    """jit donation deletes buffers but keeps the Python object alive: the fast
    path must detect it and use the serialized copy."""
    import pytest

    x = jnp.ones((512,), jnp.float32) * 7.0
    ref = rt.put(x)
    jax.jit(lambda a: a + 1, donate_argnums=0)(x)  # x's buffers are now deleted
    if not x.is_deleted():
        # jax version drift, not our donation plumbing: the XLA CPU backend
        # ignores donate_argnums (jax 0.4.x warns "Some donated buffers were
        # not usable"), so the donation never happens and there is no deleted
        # buffer to fall back from. On TPU (and newer jax CPU) donation is
        # honored and the assertion below runs.
        pytest.skip("this jax/backend ignores buffer donation on CPU "
                    "(donated buffer unused); deleted-buffer fallback covered "
                    "on donation-capable backends")
    y = rt.get(ref)
    np.testing.assert_array_equal(np.asarray(y), np.full((512,), 7.0, np.float32))
    del ref


def test_cross_process_task_receives_value(rt):
    x = jnp.arange(64, dtype=jnp.int32)

    @rt.remote
    def consume(a):
        import numpy as _np

        return int(_np.asarray(a).sum())

    assert rt.get(consume.remote(rt.put(x))) == int(np.arange(64).sum())


def test_worker_returned_array_roundtrip(rt):
    @rt.remote
    def produce():
        import jax.numpy as _jnp

        return _jnp.ones((128,), _jnp.float32) * 5.0

    ref = produce.remote()
    out = rt.get(ref)
    np.testing.assert_array_equal(np.asarray(out), np.full((128,), 5.0, np.float32))
