"""IMPALA/APPO tests (reference strategy: rllib regression configs on CartPole)."""
import numpy as np
import pytest

from ray_tpu.rllib.algorithms.appo import APPOConfig
from ray_tpu.rllib.algorithms.impala import IMPALAConfig, pad_time_major


@pytest.fixture(autouse=True)
def _cluster(rt):
    yield


def _fake_episode(T, terminated=True, obs_dim=4):
    return {
        "obs": np.random.randn(T, obs_dim).astype(np.float32),
        "next_obs_last": np.random.randn(obs_dim).astype(np.float32),
        "actions": np.random.randint(0, 2, size=T),
        "rewards": np.ones(T, np.float32),
        "terminated": terminated,
        "truncated": False,
        "action_logp": np.full(T, -0.69, np.float32),
        "vf_preds": np.zeros(T, np.float32),
    }


def test_pad_time_major_shapes_and_split():
    eps = [_fake_episode(10), _fake_episode(70, terminated=False)]
    batch = pad_time_major(eps, max_T=32, b_bucket=4)
    # 70 splits into 32+32+6 -> 4 pieces total, bucketed to 4
    assert batch["obs_ext"].shape == (4, 33, 4)
    assert batch["mask"].sum() == 80
    assert batch["lens"].tolist() == [10, 32, 32, 6]
    # only the 10-step piece terminated; split interior pieces must bootstrap
    assert batch["terminated"].tolist() == [1.0, 0.0, 0.0, 0.0]
    # bootstrap obs sits at row lens[b]
    np.testing.assert_allclose(batch["obs_ext"][1, 32], eps[1]["obs"][32].reshape(-1))


def test_vtrace_matches_one_step_td():
    """With on-policy logp (rho=c=1) and T=1, the V-trace target is exactly
    r + gamma*V(next): vf_loss == 0.5*(r + gamma*V(next) - V(s))^2."""
    from ray_tpu.rllib.algorithms.impala import IMPALAConfig, IMPALALearner
    from ray_tpu.rllib.core.rl_module import Columns, RLModuleSpec
    import gymnasium as gym

    env = gym.make("CartPole-v1")
    spec = RLModuleSpec(module_class=None, observation_space=env.observation_space,
                        action_space=env.action_space, model_config={})
    cfg = IMPALAConfig().environment("CartPole-v1")
    learner = IMPALALearner(cfg, spec)
    learner.build()
    ep = _fake_episode(1, terminated=False)
    # make the behaviour logp exactly on-policy so rho = c = 1
    out = learner.module.apply_np(
        learner.params, ep["obs"].reshape(1, -1).astype(np.float32))
    dist = learner.module.action_dist_cls
    ep["action_logp"] = dist.logp_np(out["action_dist_inputs"], ep["actions"])
    batch = pad_time_major([ep], max_T=1, b_bucket=1)
    loss, aux = learner.compute_losses(learner.params, batch)
    v_s = float(out[Columns.VF_PREDS][0])
    out_next = learner.module.apply_np(
        learner.params, ep["next_obs_last"].reshape(1, -1).astype(np.float32))
    v_next = float(out_next[Columns.VF_PREDS][0])
    expected_vf_loss = 0.5 * (1.0 + cfg.gamma * v_next - v_s) ** 2
    np.testing.assert_allclose(float(aux["vf_loss"]), expected_vf_loss, rtol=1e-4)
    np.testing.assert_allclose(float(aux["mean_rho"]), 1.0, rtol=1e-5)
    env.close()


def test_impala_improves_cartpole(rt):
    config = (
        IMPALAConfig()
        .environment("CartPole-v1")
        .env_runners(num_env_runners=2, num_envs_per_env_runner=4, rollout_fragment_length=32)
        .training(lr=1e-3, train_batch_size=512, gamma=0.99, entropy_coeff=0.005,
                  max_seq_len=64, broadcast_interval=1, num_epochs=4)
        .debugging(seed=0)
    )
    algo = config.build_algo()
    try:
        returns = []
        for _ in range(25):
            result = algo.train()
            returns.append(result.get("episode_return_mean") or 0.0)
        assert max(returns[3:]) > returns[0] + 15, returns
    finally:
        algo.cleanup()


def test_impala_with_aggregator_actors(rt):
    config = (
        IMPALAConfig()
        .environment("CartPole-v1")
        .env_runners(num_env_runners=1, num_envs_per_env_runner=2, rollout_fragment_length=32)
        .training(train_batch_size=128, num_aggregator_actors_per_learner=1)
        .debugging(seed=0)
    )
    algo = config.build_algo()
    try:
        result = algo.train()
        assert "total_loss" in result
        assert len(algo._aggregators) == 1
    finally:
        algo.cleanup()


def test_appo_runs_and_checkpoint_roundtrip(rt):
    config = (
        APPOConfig()
        .environment("CartPole-v1")
        .env_runners(num_env_runners=1, num_envs_per_env_runner=2, rollout_fragment_length=32)
        .training(train_batch_size=128, clip_param=0.3, use_kl_loss=True)
        .debugging(seed=0)
    )
    algo = config.build_algo()
    try:
        algo.train()
        state = algo.save_checkpoint()
        w_before = algo.get_weights()
        algo.train()
        algo.load_checkpoint(state)
        np.testing.assert_allclose(w_before["pi"][0]["w"], algo.get_weights()["pi"][0]["w"])
    finally:
        algo.cleanup()
