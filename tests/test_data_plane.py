"""Data plane unit tests: chunked pulls, admission control, error paths.

Reference: src/ray/object_manager/object_manager.h:119 (direct node-to-node
transfer), push_manager.h:27 (chunked push), pull_manager.h:49 (admission).
"""
import os
import threading
import time

import pytest

from ray_tpu.core.data_plane import Admission, DataClient, DataServer

KEY = b"data-plane-test"


def _store(objs):
    def read_fn(loc):
        if loc not in objs:
            raise KeyError(f"no object at {loc!r}")
        return objs[loc]
    return read_fn


@pytest.fixture()
def plane():
    objs = {}
    server = DataServer(KEY, _store(objs), host="127.0.0.1")
    client = DataClient(KEY)
    yield objs, server, client
    client.close()
    server.close()


def _addr(server):
    return ("127.0.0.1", server.port)


def test_pull_roundtrip(plane):
    objs, server, client = plane
    objs["a"] = (b"hello world", False)
    objs["err"] = (b"boom-bytes", True)
    assert client.pull(_addr(server), "a") == (b"hello world", False)
    # is_error flag survives the transfer
    assert client.pull(_addr(server), "err") == (b"boom-bytes", True)


def test_pull_zero_and_multi_chunk(plane, monkeypatch):
    objs, server, client = plane
    monkeypatch.setenv("RAY_TPU_TRANSFER_CHUNK_BYTES", "1024")
    objs["zero"] = (b"", False)
    big = os.urandom(10_000)  # ~10 chunks at 1 KiB
    objs["big"] = (big, False)
    assert client.pull(_addr(server), "zero") == (b"", False)
    assert client.pull(_addr(server), "big") == (big, False)


def test_pull_missing_object_raises_and_conn_survives(plane):
    objs, server, client = plane
    objs["a"] = (b"x" * 100, False)
    with pytest.raises(OSError, match="no object"):
        client.pull(_addr(server), "nope")
    # the server connection keeps serving after a read error
    assert client.pull(_addr(server), "a") == (b"x" * 100, False)


def test_connection_reuse(plane):
    objs, server, client = plane
    objs["a"] = (b"y" * 10, False)
    for _ in range(5):
        assert client.pull(_addr(server), "a")[0] == b"y" * 10
    # sequential pulls reuse one pooled connection
    assert len(client._pool[("127.0.0.1", server.port)]) == 1


def test_concurrent_pulls(plane):
    objs, server, client = plane
    payload = os.urandom(300_000)
    for i in range(8):
        objs[f"o{i}"] = (payload, False)
    out = [None] * 8
    def work(i):
        out[i] = client.pull(_addr(server), f"o{i}")
    ts = [threading.Thread(target=work, args=(i,)) for i in range(8)]
    [t.start() for t in ts]
    [t.join(timeout=30) for t in ts]
    assert all(o == (payload, False) for o in out)


def test_admission_oversize_object_admits_alone():
    adm = Admission(max_bytes=100, max_pulls=4)
    got = adm.acquire(1000)  # clamped to the whole budget
    assert got == 100
    # a second pull cannot start until the oversize one releases
    started = threading.Event()
    def second():
        adm.acquire(10)
        started.set()
        adm.release(10)
    t = threading.Thread(target=second)
    t.start()
    time.sleep(0.3)
    assert not started.is_set()
    adm.release(got)
    assert started.wait(timeout=5)
    t.join()


def test_admission_bounds_concurrency():
    adm = Admission(max_bytes=10_000, max_pulls=2)
    a, b = adm.acquire(10), adm.acquire(10)
    blocked = threading.Event()
    def third():
        n = adm.acquire(10)
        blocked.set()
        adm.release(n)
    t = threading.Thread(target=third)
    t.start()
    time.sleep(0.2)
    assert not blocked.is_set()  # pull-slot cap, not bytes, is the binding limit
    adm.release(a)
    assert blocked.wait(timeout=5)
    adm.release(b)
    t.join()


def test_read_raw_slice_locations():
    """Ranged reads of stored objects return exactly the requested bytes
    without materializing the rest (ring steps pull per-chunk ranges)."""
    from ray_tpu.core import object_store
    from ray_tpu.core.ids import ObjectID

    payload = os.urandom(300_000)
    # inline: small object
    small = ObjectID.generate()
    loc = object_store.write_raw(b"0123456789", small)
    assert object_store.read_raw_slice(loc, 2, 5) == (b"23456", False)
    # shm/arena: large object
    big = ObjectID.generate()
    loc = object_store.write_raw(payload, big)
    try:
        assert object_store.read_raw_slice(loc, 0, 16) == (payload[:16], False)
        got, is_err = object_store.read_raw_slice(loc, 100_000, 50_000)
        assert got == payload[100_000:150_000] and not is_err
        # clamped at the tail; zero-length past the end
        assert object_store.read_raw_slice(loc, 299_990, 1000)[0] == payload[299_990:]
        assert object_store.read_raw_slice(loc, 400_000, 10)[0] == b""
        # the dispatcher understands both plain and ("slice", ...) locations
        assert object_store.read_raw_any(("slice", loc, 5, 7)) == (payload[5:12], False)
        assert object_store.read_raw_any(loc) == (payload, False)
    finally:
        object_store.free_local(loc)


def test_slice_pull_through_data_server():
    """A DataServer wired to read_raw_any serves byte ranges of store objects —
    the node/agent data planes use exactly this read fn."""
    from ray_tpu.core import object_store
    from ray_tpu.core.ids import ObjectID

    payload = os.urandom(200_000)
    oid = ObjectID.generate()
    loc = object_store.write_raw(payload, oid)
    server = DataServer(KEY, object_store.read_raw_any, host="127.0.0.1")
    client = DataClient(KEY)
    try:
        assert client.pull(_addr(server), loc) == (payload, False)
        got, is_err = client.pull(_addr(server), ("slice", loc, 50_000, 10_000))
        assert got == payload[50_000:60_000] and not is_err
    finally:
        client.close()
        server.close()
        object_store.free_local(loc)


def test_wrong_authkey_rejected(plane):
    objs, server, _ = plane
    objs["a"] = (b"secret", False)
    bad = DataClient(b"wrong-key")
    with pytest.raises(Exception):
        bad.pull(_addr(server), "a")
    bad.close()
    # a failed handshake must not kill the accept loop
    good = DataClient(KEY)
    assert good.pull(_addr(server), "a") == (b"secret", False)
    good.close()
