"""TPU accelerator manager tests (reference _private/accelerators/tpu.py)."""
import pytest

from ray_tpu.core.accelerators import TPUAcceleratorManager, TPUInfo


def test_detect_none_without_env(monkeypatch):
    for var in ("TPU_VISIBLE_CHIPS", "TPU_CHIPS_PER_HOST",
                "TPU_CHIPS_PER_HOST_BOUNDS", "TPU_ACCELERATOR_TYPE"):
        monkeypatch.delenv(var, raising=False)
    assert TPUAcceleratorManager.detect() is None
    assert TPUAcceleratorManager.node_resources() == {}


def test_detect_from_env(monkeypatch):
    monkeypatch.setenv("TPU_CHIPS_PER_HOST_BOUNDS", "2,2,1")
    monkeypatch.setenv("TPU_ACCELERATOR_TYPE", "v5e-8")
    monkeypatch.setenv("TPU_WORKER_ID", "0")
    info = TPUAcceleratorManager.detect()
    assert info.chips_per_host == 4
    assert info.accelerator_type == "v5e-8"
    assert info.pod_head_resource == "TPU-v5e-8-head"
    res = TPUAcceleratorManager.node_resources()
    assert res["TPU"] == 4.0
    assert res["TPU-v5e-8-head"] == 1.0


def test_non_head_worker_has_no_head_resource(monkeypatch):
    monkeypatch.setenv("TPU_CHIPS_PER_HOST", "4")
    monkeypatch.setenv("TPU_ACCELERATOR_TYPE", "v5e-16")
    monkeypatch.setenv("TPU_WORKER_ID", "1")
    res = TPUAcceleratorManager.node_resources()
    assert res["TPU"] == 4.0
    assert "TPU-v5e-16-head" not in res  # only worker 0 anchors the slice


def test_visible_chips_override(monkeypatch):
    monkeypatch.setenv("TPU_CHIPS_PER_HOST", "8")
    TPUAcceleratorManager.set_visible_chips([0, 1])
    try:
        assert TPUAcceleratorManager.get_current_node_num_accelerators() == 2
    finally:
        monkeypatch.delenv("TPU_VISIBLE_CHIPS", raising=False)


def test_slice_spanning_placement_group(rt):
    """The TPU-{pod}-head trick: a PG anchored on the head resource reserves the
    slice atomically (reference tpu.py:376 + SURVEY.md §7 phase 2)."""
    from ray_tpu.core import global_state
    from ray_tpu.util import placement_group_api as pg_api

    cluster = global_state.try_cluster()
    node = cluster.add_node({"CPU": 4.0, "TPU": 8.0, "TPU-v5e-8-head": 1.0})
    try:
        pg = pg_api.placement_group(
            [{"TPU-v5e-8-head": 1.0, "TPU": 4.0}, {"TPU": 4.0}], strategy="STRICT_PACK")
        assert pg.wait(timeout_seconds=30)
        bundles = cluster.pg_manager.bundles(pg.id)
        assert all(b.node_id == node.node_id for b in bundles)  # whole slice, one host
        pg_api.remove_placement_group(pg)
    finally:
        cluster.remove_node(node.node_id)