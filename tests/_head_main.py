"""Standalone head process for head-restart tests (tests/test_head_restart.py).

Runs the cluster head with a node server (agents join), a client server
(drivers join), and GCS journal persistence — all on fixed ports so a restarted
incarnation is reachable at the same addresses.
"""
import os
import sys
import time

os.environ["JAX_PLATFORMS"] = "cpu"

if __name__ == "__main__":
    import jax

    jax.config.update("jax_platforms", "cpu")
    import ray_tpu

    node_port, client_port = int(sys.argv[1]), int(sys.argv[2])
    # optional third arg: head-local CPUs (0 = pure control plane; every
    # actor/replica schedules onto agents — the head-chaos bench topology)
    num_cpus = int(sys.argv[3]) if len(sys.argv) > 3 else 1
    ray_tpu.init(num_cpus=num_cpus, node_server_port=node_port,
                 client_server_port=client_port,
                 worker_env={"JAX_PLATFORMS": "cpu"})
    print("HEAD_READY", flush=True)
    while True:
        time.sleep(0.5)
