"""Streaming generator tasks (num_returns="streaming") + SSE serving.

Reference: dynamic-return object generators (python/ray/_raylet.pyx:1138) and
Serve/LLM streaming responses (proxy.py:699, OpenAI stream:true).
"""
import json
import time
import urllib.request

import pytest


def test_task_streaming_generator(rt):
    @rt.remote(num_returns="streaming")
    def gen(n):
        for i in range(n):
            yield i * i

    refs = list(gen.remote(5))
    assert len(refs) == 5
    assert rt.get(refs) == [0, 1, 4, 9, 16]


def test_streaming_chunks_arrive_before_completion(rt):
    @rt.remote(num_returns="streaming")
    def slow():
        for i in range(3):
            yield (i, time.time())
            time.sleep(0.4)

    g = slow.remote()
    first = rt.get(next(g))
    t_first = time.time()
    rest = [rt.get(r) for r in g]
    assert first[0] == 0 and [r[0] for r in rest] == [1, 2]
    # the first chunk was consumed before the producer yielded the last one
    assert t_first < rest[-1][1]


def test_streaming_error_mid_stream(rt):
    @rt.remote(num_returns="streaming")
    def bad():
        yield "ok"
        raise ValueError("boom")

    g = bad.remote()
    assert rt.get(next(g)) == "ok"
    with pytest.raises(Exception, match="boom"):
        next(g)


def test_streaming_actor_method(rt):
    @rt.remote
    class S:
        def chunks(self, n):
            for i in range(n):
                yield f"c{i}"

        def plain(self):
            return {"not": "streamed"}

    s = S.remote()
    got = [rt.get(r) for r in s.chunks.options(num_returns="streaming").remote(4)]
    assert got == ["c0", "c1", "c2", "c3"]
    # a non-iterator return under a streaming call is a one-item stream
    one = [rt.get(r) for r in s.plain.options(num_returns="streaming").remote()]
    assert one == [{"not": "streamed"}]


def test_serve_streaming_handle(rt):
    from ray_tpu import serve

    @serve.deployment
    class Chunker:
        def stream_out(self, n):
            for i in range(n):
                yield {"i": i}

    try:
        serve.run(Chunker.bind(), name="chunker", route_prefix="/chunker")
        h = serve.get_app_handle("chunker")
        gen = h.options(method_name="stream_out", stream=True).remote(3)
        assert list(gen) == [{"i": 0}, {"i": 1}, {"i": 2}]
    finally:
        serve.shutdown()


def test_openai_sse_through_http_proxy(rt):
    """VERDICT bar: chunk-by-chunk SSE arrival through the real HTTP proxy."""
    from ray_tpu import serve
    from ray_tpu.llm import LLMConfig
    from ray_tpu.llm.server import build_openai_app

    try:
        app = build_openai_app([LLMConfig(
            model_id="tiny", model_source="byte-tiny",
            max_num_seqs=2, max_model_len=64)])
        serve.run(app, name="llm-sse", route_prefix="/v1")
        serve.start(http_options={"port": 8123})

        body = json.dumps({
            "model": "tiny", "stream": True, "max_tokens": 6,
            "temperature": 0.0,
            "messages": [{"role": "user", "content": "hi"}],
        }).encode()
        req = urllib.request.Request(
            "http://127.0.0.1:8123/v1/chat/completions", data=body,
            headers={"Content-Type": "application/json"})
        resp = urllib.request.urlopen(req, timeout=120)
        assert resp.headers.get("Content-Type", "").startswith("text/event-stream")
        frames = []
        arrival = []
        buf = b""
        while True:
            chunk = resp.read(1)
            if not chunk:
                break
            buf += chunk
            while b"\n\n" in buf:
                frame, buf = buf.split(b"\n\n", 1)
                frames.append(frame.decode())
                arrival.append(time.time())
        assert frames[-1] == "data: [DONE]"
        datas = [json.loads(f[len("data: "):]) for f in frames[:-1]]
        # first chat chunk carries the role delta; at least one content delta
        assert datas[0]["choices"][0]["delta"].get("role") == "assistant"
        assert datas[0]["object"] == "chat.completion.chunk"
        contents = [d["choices"][0]["delta"].get("content", "") for d in datas[1:]]
        assert any(contents)
        # finish chunk present
        assert datas[-1]["choices"][0]["finish_reason"] is not None
        assert len(frames) >= 4  # role + >=1 content + finish + [DONE]
    finally:
        serve.shutdown()


def test_abandoned_stream_releases_items(rt):
    """Dropping the generator mid-stream must not pin unconsumed items forever
    (SSE client disconnects are this path)."""
    import gc

    from ray_tpu.core import global_state
    from ray_tpu.core.object_ref import stream_item_id

    @rt.remote(num_returns="streaming")
    def gen():
        for i in range(5):
            yield bytes(200_000)  # big enough to live in shm/arena

    g = gen.remote()
    first_ref = next(g)
    task_id = g._task_id
    assert rt.get(first_ref) is not None
    # let the producer finish registering all items
    rt.get(g.completed)
    cluster = global_state.try_cluster()
    assert cluster.store.contains(stream_item_id(task_id, 3))
    del g
    gc.collect()
    deadline = time.time() + 15
    while cluster.store.contains(stream_item_id(task_id, 3)):
        assert time.time() < deadline, "unconsumed stream items were never freed"
        time.sleep(0.1)
    # the consumed item's ref still pins item 0
    assert cluster.store.contains(stream_item_id(task_id, 0))
    del first_ref
    gc.collect()
    deadline = time.time() + 15
    while cluster.store.contains(stream_item_id(task_id, 0)):
        assert time.time() < deadline
        time.sleep(0.1)


def test_abandoned_stream_cancels_producer(rt, tmp_path):
    """Dropping a generator must stop the PRODUCER early (cancel_stream at the
    next yield boundary), not just free unconsumed items — an abandoned SSE
    client must release engine resources, not generate to max_tokens."""
    import gc
    import os

    marker = str(tmp_path / "stopped_at.txt")

    @rt.remote(num_returns="streaming")
    def slow_gen(path):
        i = -1
        try:
            for i in range(200):
                yield i
                time.sleep(0.05)
        finally:
            with open(path, "w") as f:
                f.write(str(i))

    g = slow_gen.remote(marker)
    assert rt.get(next(g)) == 0
    assert rt.get(next(g)) == 1
    del g
    gc.collect()
    deadline = time.time() + 20
    while not os.path.exists(marker):
        assert time.time() < deadline, "producer never stopped"
        time.sleep(0.1)
    assert int(open(marker).read()) < 100


def test_generator_pickle_preserves_position(rt):
    """A serialized generator resumes at the sender's position as a BORROW:
    its refs don't own items and its GC never drop_stream's — ownership stays
    with the first consumer (each item carries exactly one registration
    incref)."""
    import pickle

    @rt.remote(num_returns="streaming")
    def gen():
        yield from range(5)

    import gc

    g = gen.remote()
    assert rt.get(next(g)) == 0
    rt.get(g.completed)  # all items registered
    g2 = pickle.loads(pickle.dumps(g))
    assert g2._i == 1 and g2._owner is False
    assert [rt.get(r) for r in g2] == [1, 2, 3, 4]
    # the borrowed copy's GC must not free items the owner can still consume
    del g2
    gc.collect()
    time.sleep(0.5)
    assert [rt.get(r) for r in g] == [1, 2, 3, 4]
