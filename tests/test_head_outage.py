"""Head-death survivability (PR 18): typed HeadUnavailableError, degraded-mode
serving state, replayable control channels, and reattach idempotency.

The fast tier splits in two. Pure-logic tests drive the serve retry plane and
the long-poll pinning with monkeypatched controller calls; reattach
idempotency drives the head's `_reattach_agent` directly with a fake agent
stream (no subprocesses, deterministic double delivery). The two subprocess
tests bound the wall-clock cost: one spawns a standalone head and kills it to
prove every client entry point surfaces the typed error after a BOUNDED
reconnect window, the other arms the `head.control.recv` fail point in a real
node agent so the reconnect + reregister machinery runs against the LIVE head
— a simulated outage with no process ever dying, which is what keeps it out
of the slow tier. The real SIGKILL end-to-end lives in test_head_restart.py
and the head-chaos bench gate (core_bench.py --head-chaos).
"""
import os
import pickle
import signal
import socket
import subprocess
import sys
import time

import pytest


def _free_port():
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    p = s.getsockname()[1]
    s.close()
    return p


def _spawn_head(env, node_port, client_port):
    proc = subprocess.Popen(
        [sys.executable, os.path.join(os.path.dirname(__file__), "_head_main.py"),
         str(node_port), str(client_port)],
        env=env, stdout=subprocess.PIPE, text=True)
    deadline = time.time() + 60
    while True:
        line = proc.stdout.readline()
        if "HEAD_READY" in line:
            return proc
        assert proc.poll() is None and time.time() < deadline, "head never started"


@pytest.fixture()
def outage_env(rt, tmp_path):
    """Standalone-head sandbox: shared session dir + journal, session cluster
    parked for the duration (the test_head_restart.py idiom)."""
    import ray_tpu

    ray_tpu.shutdown()
    repo_root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    env = {**os.environ,
           "JAX_PLATFORMS": "cpu",
           "PYTHONPATH": repo_root + os.pathsep + os.environ.get("PYTHONPATH", ""),
           "RAY_TPU_SESSION_DIR": str(tmp_path / "session"),
           "RAY_TPU_GCS_PERSISTENCE_PATH": str(tmp_path / "gcs.journal")}
    saved = {k: os.environ.get(k) for k in
             ("RAY_TPU_SESSION_DIR", "RAY_TPU_GCS_PERSISTENCE_PATH")}
    os.environ.update({k: env[k] for k in saved})
    procs = []
    try:
        yield env, procs
    finally:
        for p in procs:
            if p.poll() is None:
                p.terminate()
        for p in procs:
            try:
                p.wait(timeout=10)
            except subprocess.TimeoutExpired:
                p.kill()
        for k, v in saved.items():
            if v is None:
                os.environ.pop(k, None)
            else:
                os.environ[k] = v
        ray_tpu.shutdown()
        ray_tpu.init(num_cpus=4, worker_env={"JAX_PLATFORMS": "cpu"},
                     max_workers_per_node=8)


# ---------------------------------------------------------------- typed error

class TestHeadUnavailableError:
    def test_pickle_round_trip_keeps_fields(self):
        from ray_tpu.core.exceptions import HeadUnavailableError

        t0 = time.time() - 5.0
        err = HeadUnavailableError(t0, 7, "reconnect window expired",
                                   cause=ConnectionError("boom"))
        back = pickle.loads(pickle.dumps(err))
        assert back.outage_started_at == t0
        assert back.attempts == 7
        assert back.reason == "reconnect window expired"
        assert isinstance(back.cause, ConnectionError)
        assert back.outage_age_s >= 5.0
        assert "7 reconnect attempt" in str(back)

    def test_classified_retryable_but_not_replica_blame(self):
        from ray_tpu.core.exceptions import HeadUnavailableError, TaskError
        from ray_tpu.serve.handle import is_head_unavailable, is_replica_failure

        err = HeadUnavailableError(time.time(), 1, "x")
        assert is_replica_failure(err)
        assert is_head_unavailable(err)
        wrapped = TaskError(err)
        assert is_replica_failure(wrapped)
        assert is_head_unavailable(wrapped)
        assert not is_head_unavailable(ConnectionError("plain socket death"))


def test_retry_session_head_outage_spares_replica_budget(monkeypatch):
    """A head outage must not consume the replica retry budget or suspect the
    replica, but must still be BOUNDED by its own deadline."""
    from ray_tpu.core.exceptions import HeadUnavailableError
    from ray_tpu.serve.handle import _RetrySession

    monkeypatch.setenv("RAY_TPU_SERVE_RETRY_BACKOFF_S", "0.01")
    monkeypatch.setenv("RAY_TPU_SERVE_RETRY_BACKOFF_MAX_S", "0.02")
    monkeypatch.setenv("RAY_TPU_HEAD_RECONNECT_TIMEOUT_S", "30")

    class _Router:
        def suspect(self, *a):  # must never be called for a head failure
            raise AssertionError("head outage suspected a replica")

    class _Handle:
        app_name, deployment_name = "app", "dep"
        _router = _Router()

    sess = _RetrySession(_Handle(), (), {}, retryable=False, trace_id=None)
    assert sess.attempts_left == 0  # retryable=False: no replica budget at all
    sess.replica = object()  # a suspect() call would blow up via _Router
    err = HeadUnavailableError(time.time(), 1, "blip")
    sess.prepare_retry(err)  # retries despite the empty replica budget
    assert sess.attempts_left == 0 and sess.attempt == 1
    assert sess.head_deadline is not None
    # past the head deadline the SAME error surfaces instead of looping
    sess.head_deadline = time.monotonic() - 1.0
    with pytest.raises(HeadUnavailableError):
        sess.prepare_retry(err)


def test_long_poll_pins_view_through_outage(monkeypatch):
    """Controller gone: the long-poll loop PINS the last replica view (stamped
    stale) instead of dropping it, and clears the stamp on recovery."""
    import ray_tpu
    from ray_tpu.serve.handle import _LongPollClient

    from ray_tpu.serve.handle import _LongPollEntry

    lp = _LongPollClient()
    entry = _LongPollEntry()
    entry.replicas = ["r1", "r2"]
    lp.entries[("app", "dep")] = entry
    lp.versions["replicas::app/dep"] = 3

    state = {"mode": "down"}

    class _Ref:
        pass

    class _Controller:
        class listen_for_change:  # noqa: N801 — mimics .remote() shape
            @staticmethod
            def remote(watched, timeout):
                return _Ref()

    def fake_get_actor(name, *a, **k):
        if state["mode"] == "down":
            raise ConnectionError("head gone")
        return _Controller()

    def fake_get(ref, *a, **k):
        return {"replicas::app/dep": (4, ["r1", "r2", "r3"])}

    monkeypatch.setattr(ray_tpu, "get_actor", fake_get_actor)
    monkeypatch.setattr(ray_tpu, "get", fake_get)

    import threading
    t = threading.Thread(target=lp._loop, daemon=True)
    lp._thread = t
    t.start()
    deadline = time.time() + 5
    while entry.stale_since is None:
        assert time.time() < deadline, "outage never stamped the entry stale"
        time.sleep(0.02)
    assert entry.replicas == ["r1", "r2"]  # PINNED, not dropped
    assert entry.staleness_s() is not None and entry.staleness_s() >= 0.0
    state["mode"] = "up"  # head restarts: next poll refreshes and unpins
    deadline = time.time() + 5
    while entry.stale_since is not None:
        assert time.time() < deadline, "recovery never cleared the stale stamp"
        time.sleep(0.02)
    assert entry.replicas == ["r1", "r2", "r3"]
    with lp.lock:
        lp.entries.clear()  # lets the loop retire


def test_handle_refresh_keeps_last_known_view(monkeypatch):
    """Controller RPC failing must not strand a handle that already has a
    replica view — degraded mode serves from the last-known set."""
    from ray_tpu.serve.handle import DeploymentHandle

    h = DeploymentHandle("app", "dep")
    h._replicas = ["r1"]

    def boom():
        raise ConnectionError("head gone")

    monkeypatch.setattr(h, "_controller", boom)
    h._refresh(force=True)  # must NOT raise
    assert h._replicas == ["r1"]
    # a handle with NO view has nothing to serve from: the error surfaces
    h._replicas = []
    with pytest.raises(ConnectionError):
        h._refresh(force=True)


# ------------------------------------------------------- reattach idempotency

class _FakeAgentStream:
    """Just enough of the agent-side stream for _reattach_agent: reattach
    assigns the callbacks, sends welcome-back, and uses the object as the
    conn-table key."""

    def __init__(self):
        self.peer_ip = None
        self.on_message = None
        self.on_disconnect = None
        self.welcomed = []

    def send_welcome_back(self, payload):
        self.welcomed.append(payload)


def _reattach(cluster, node_hex, extras):
    stream = _FakeAgentStream()
    ok = cluster._reattach_agent(
        stream, ("reregister", node_hex, {"CPU": 2.0}, {}, 4, extras))
    return ok, stream


def test_reattach_double_replay_is_a_noop(rt):
    """The journal replay must be idempotent: a doubly-delivered reregister
    (reconnect racing the death detection) rebinds the same actor once, holds
    ONE arena pin, and leaves the journal record in place for a third replay."""
    import cloudpickle

    from dataclasses import replace

    from ray_tpu.core import global_state
    from ray_tpu.core.ids import ActorID, NodeID, ObjectID, WorkerID

    c = global_state.try_cluster()
    assert c is not None

    @rt.remote(name="journal-donor", lifetime="detached", max_restarts=0)
    class Donor:
        def ping(self):
            return "pong"

    d = Donor.remote()
    assert rt.get(d.ping.remote(), timeout=30) == "pong"
    donor_st = next(st for st in c.actors.values() if st.name == "journal-donor")

    node_hex = NodeID.generate().hex()
    wid_hex = WorkerID.generate().hex()
    spec = replace(donor_st.creation_spec, actor_id=ActorID.generate(),
                   actor_name="fake-survivor", node_id=None)
    rec = cloudpickle.dumps({
        "name": "fake-survivor", "namespace": "", "detached": True,
        "host": node_hex, "wid": wid_hex,
        "method_meta": donor_st.method_meta, "creation_spec": spec})
    c.gcs.kv.put(spec.actor_id.binary(), rec, namespace="@actors")

    oid = ObjectID(os.urandom(ObjectID.SIZE))
    extras = {"workers": ((wid_hex, None),), "data_port": None,
              "arena": "fake-arena", "objects": ((oid.binary(), 128, 0),)}

    ok, s1 = _reattach(c, node_hex, extras)
    assert ok and s1.welcomed[0]["keep_workers"] == [wid_hex]
    st = c.actors[spec.actor_id]
    assert st.state == "alive" and st.worker is not None
    refs_after_first = c.store._refcounts.get(oid, 0) \
        if hasattr(c.store, "_refcounts") else None

    # live mutation between deliveries: unrelated actors keep working
    assert rt.get(d.ping.remote(), timeout=30) == "pong"

    ok, s2 = _reattach(c, node_hex, extras)  # the double delivery
    assert ok and s2.welcomed[0]["keep_workers"] == [wid_hex]
    st = c.actors[spec.actor_id]
    assert st.state == "alive" and st.worker is not None
    # exactly one node entry for the host, bound to the NEWEST stream
    assert c._agents_by_key[node_hex].conn is s2
    alive = [n for n in rt.nodes()
             if n["Alive"] and n["NodeID"] == node_hex]
    assert len(alive) == 1
    # the journal record survived the replay (a third restart can rebind)
    assert c.gcs.kv.get(spec.actor_id.binary(), namespace="@actors") is not None
    # the arena pin was taken ONCE, not once per delivery
    if refs_after_first is not None:
        assert c.store._refcounts.get(oid, 0) == refs_after_first
    # the interleaved live actor still works after the second replay
    assert rt.get(d.ping.remote(), timeout=30) == "pong"

    # teardown: detach the fake node so later tests see a clean view
    agent = c._agents_by_key.get(node_hex)
    if agent is not None:
        c._on_agent_death(agent)
    c.gcs.kv.delete(spec.actor_id.binary(), namespace="@actors")
    rt.kill(d, no_restart=True)


def test_reattach_skips_corrupt_journal_records(rt):
    """A corrupt/unpicklable record in the @actors journal must be skipped —
    the reattach still lands and rebinds nothing from it."""
    from ray_tpu.core import global_state
    from ray_tpu.core.ids import NodeID

    c = global_state.try_cluster()
    c.gcs.kv.put(b"corrupt-record", b"\x00this is not a pickle",
                 namespace="@actors")
    try:
        node_hex = NodeID.generate().hex()
        ok, stream = _reattach(c, node_hex, {"workers": (), "data_port": None})
        assert ok and stream.welcomed[0]["keep_workers"] == []
        agent = c._agents_by_key.get(node_hex)
        assert agent is not None
        c._on_agent_death(agent)
    finally:
        c.gcs.kv.delete(b"corrupt-record", namespace="@actors")


def test_fn_registration_lands_in_the_gcs_journal(rt):
    """Function/class bytes must reach the @fns KV namespace when registered:
    workers and clients dedup register_fn per head lifetime, so a restarted
    head can only serve fetch_fn (actor restarts, replica replacements) from
    what the journal kept."""
    from ray_tpu.core import global_state

    c = global_state.try_cluster()
    fn_id, fn_bytes = b"\xabtest-fn-rec\x01\x02\x03\x04", b"not-really-a-pickle"
    try:
        c._register_fn(fn_id, fn_bytes)
        assert c.fn_table[fn_id] == fn_bytes
        assert c.gcs.kv.get(fn_id, namespace="@fns") == fn_bytes
        # idempotent under double delivery (a reconnecting worker may replay
        # its register_fn): second call is a no-op, not a journal rewrite
        c._register_fn(fn_id, b"different-bytes-must-not-win")
        assert c.fn_table[fn_id] == fn_bytes
        assert c.gcs.kv.get(fn_id, namespace="@fns") == fn_bytes
    finally:
        c.fn_table.pop(fn_id, None)
        c.gcs.kv.delete(fn_id, namespace="@fns")


# ----------------------------------------------------- client bounded typed raise

def test_client_entry_points_raise_typed_after_bounded_reconnect(
        outage_env, monkeypatch):
    """Kill the head with NO restart: get / wait / actor creation must each
    surface HeadUnavailableError once the (tiny) reconnect window expires —
    never a hang, never a raw socket error."""
    import ray_tpu
    from ray_tpu.core.exceptions import HeadUnavailableError

    monkeypatch.setenv("RAY_TPU_HEAD_RECONNECT_TIMEOUT_S", "1.5")
    monkeypatch.setenv("RAY_TPU_HEAD_RECONNECT_BACKOFF_S", "0.1")
    env, procs = outage_env
    node_port, client_port = _free_port(), _free_port()
    head = _spawn_head(env, node_port, client_port)
    procs.append(head)

    ray_tpu.init(address=f"ray-tpu://127.0.0.1:{client_port}")
    try:
        @ray_tpu.remote
        def echo(x):
            return x

        ref = echo.remote(41)
        assert ray_tpu.get(ref, timeout=30) == 41

        os.kill(head.pid, signal.SIGKILL)
        head.wait(timeout=10)

        t0 = time.monotonic()
        with pytest.raises(HeadUnavailableError) as ei:
            ray_tpu.get(echo.remote(1), timeout=30)
        assert time.monotonic() - t0 < 15, "reconnect window was not bounded"
        assert ei.value.outage_started_at > 0

        with pytest.raises(HeadUnavailableError):
            ray_tpu.wait([ref], timeout=5)

        @ray_tpu.remote
        class A:
            def f(self):
                return 1

        with pytest.raises(HeadUnavailableError):
            A.remote()  # actor creation is a head-requiring op
    finally:
        ray_tpu.shutdown()


# --------------------------------------------- fail-point simulated outage

def test_agent_failpoint_outage_reattaches_to_live_head(outage_env):
    """Deterministic outage with no process death: the agent's
    head.control.recv fail point errors its recv loop twice, forcing two full
    reconnect + reregister cycles against the LIVE head. The node must keep
    its identity (one alive entry, same NodeID) and serve actors afterwards."""
    import ray_tpu

    env, procs = outage_env
    node_port, client_port = _free_port(), _free_port()
    head = _spawn_head(env, node_port, client_port)
    procs.append(head)
    agent_env = {**env,
                 "RAY_TPU_FAULT_INJECTION": "head.control.recv=error@n=2",
                 "RAY_TPU_AGENT_RECONNECT_TIMEOUT_S": "30"}
    agent = subprocess.Popen(
        [sys.executable, "-m", "ray_tpu.core.node_agent",
         "--address", f"127.0.0.1:{node_port}", "--num-cpus", "2"],
        env=agent_env)
    procs.append(agent)

    ray_tpu.init(address=f"ray-tpu://127.0.0.1:{client_port}")
    try:
        deadline = time.time() + 40
        remote_nodes = []
        while time.time() < deadline:
            remote_nodes = [n for n in ray_tpu.nodes()
                            if n["Alive"] and n["Labels"].get("agent") == "remote"]
            if remote_nodes:
                break
            time.sleep(0.2)
        assert remote_nodes, "agent never (re)joined through the fail point"
        node_id = remote_nodes[0]["NodeID"]

        from ray_tpu.core.task_spec import NodeAffinitySchedulingStrategy

        @ray_tpu.remote(scheduling_strategy=NodeAffinitySchedulingStrategy(
            node_id=node_id), max_restarts=0)
        class Counter:
            def __init__(self):
                self.n = 0

            def bump(self):
                self.n += 1
                return self.n

        # actor creation may race the second injected outage: retry briefly
        deadline = time.time() + 40
        got = None
        while time.time() < deadline:
            try:
                a = Counter.remote()
                got = ray_tpu.get(a.bump.remote(), timeout=20)
                break
            except Exception:
                time.sleep(0.5)
        assert got == 1, "actor never served after the simulated outages"
        # the storm did not duplicate the node or change its identity
        alive = [n for n in ray_tpu.nodes()
                 if n["Alive"] and n["Labels"].get("agent") == "remote"]
        assert len(alive) == 1 and alive[0]["NodeID"] == node_id
    finally:
        ray_tpu.shutdown()


# ------------------------------------------------------------ lint regression

def test_unbounded_reconnect_check_fires_and_clears(tmp_path):
    from ray_tpu.tools.analysis.base import Project, SourceFile
    from ray_tpu.tools.analysis.checks.blocking_control import UnboundedReconnect

    bad = ("import time\n"
           "def loop(self):\n"
           "    while True:\n"
           "        try:\n"
           "            return transport.dial(self.addr)\n"
           "        except Exception:\n"
           "            time.sleep(1)\n")
    good = ("import time\n"
            "def loop(self):\n"
            "    deadline = time.monotonic() + 30\n"
            "    while True:\n"
            "        if time.monotonic() >= deadline:\n"
            "            raise RuntimeError('head gone')\n"
            "        try:\n"
            "            return transport.dial(self.addr)\n"
            "        except Exception:\n"
            "            time.sleep(1)\n")
    check = UnboundedReconnect()
    out = {}
    for label, src in (("bad", bad), ("good", good)):
        p = tmp_path / f"{label}.py"
        p.write_text(src)
        f = SourceFile(str(tmp_path), f"{label}.py")
        out[label] = list(check.run(f, Project(str(tmp_path), [f])))
    assert len(out["bad"]) == 1 and "no deadline/attempt bound" in out["bad"][0].message
    assert out["good"] == []


def test_unbounded_reconnect_check_is_registered():
    """The tree-wide zero-violation gate lives in test_lint.py
    (test_ray_tpu_tree_is_lint_clean); a second full-tree walk here would
    double-pay ~5s of tier-1 budget. What that gate can't prove is that the
    new check participates at all — assert registration so the gate's
    'no failures' includes 'no unbounded reconnect loops'."""
    from ray_tpu.tools.analysis.checks import ALL_CHECKS
    from ray_tpu.tools.analysis.checks.blocking_control import UnboundedReconnect

    assert any(isinstance(c, UnboundedReconnect) for c in ALL_CHECKS)


# ------------------------------------------------------------ bench harness

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def test_head_chaos_bench_dry_run(tmp_path):
    """HEAD_CHAOS_BENCH smoke inside the tier-1 budget: the mode is wired and
    the gate file lands where pointed — no processes spawned, nothing killed."""
    import json

    out = tmp_path / "HEAD_CHAOS_BENCH.json"
    r = subprocess.run(
        [sys.executable, os.path.join(_REPO, "core_bench.py"),
         "--head-chaos", "--dry-run", "--out", str(out)],
        capture_output=True, text=True, timeout=120,
        env={**os.environ, "JAX_PLATFORMS": "cpu"})
    assert r.returncode == 0, r.stderr
    doc = json.loads(out.read_text())
    assert doc["dry_run"] is True
    assert set(doc["gates"]) == {
        "outage_within_10s", "zero_failed_unary", "streaming_never_hangs",
        "zero_healthy_nodes_reaped", "train_completed",
        "autoscaler_resumed_within_5_ticks", "passed"}


def test_head_chaos_checked_in_gates_pass():
    """The committed HEAD_CHAOS_BENCH.json evidence must show passing gates."""
    import json

    doc = json.loads(open(os.path.join(_REPO, "HEAD_CHAOS_BENCH.json")).read())
    g = doc["gates"]
    assert g["passed"] is True
    assert g["zero_failed_unary"] and g["streaming_never_hangs"]
    assert g["zero_healthy_nodes_reaped"] and g["train_completed"]
    assert doc["unary"]["failed"] == 0 and doc["unary"]["hung"] == 0
    assert doc["measured_outage_s"] <= 10.0
