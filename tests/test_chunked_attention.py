"""Chunked (online-softmax) XLA attention vs the one-shot reference.

attention_chunked is the long-sequence fallback when the Pallas kernel can't tile a
shape; it must match attention_reference bit-for-tolerance across the full masking
surface (causal offsets, GQA, packing segment ids, padded-cache valid lengths).
"""
import jax
import jax.numpy as jnp
import numpy as np
import pytest

from ray_tpu.ops.attention import attention_chunked, attention_reference


def _rand(shape, key):
    return jax.random.normal(jax.random.PRNGKey(key), shape, jnp.float32)


@pytest.mark.parametrize("causal", [True, False])
@pytest.mark.parametrize("skv", [96, 128, 130])  # non-multiple exercises padding
def test_matches_reference(causal, skv):
    b, sq, h, d = 2, 96, 4, 32
    q = _rand((b, sq, h, d), 0)
    k, v = _rand((b, skv, h, d), 1), _rand((b, skv, h, d), 2)
    out = attention_chunked(q, k, v, causal=causal, block_kv=64)
    ref = attention_reference(q, k, v, causal=causal)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), rtol=2e-5, atol=2e-5)


def test_gqa():
    b, s, h, hkv, d = 1, 128, 8, 2, 32
    q = _rand((b, s, h, d), 0)
    k, v = _rand((b, s, hkv, d), 1), _rand((b, s, hkv, d), 2)
    out = attention_chunked(q, k, v, causal=True, block_kv=64)
    ref = attention_reference(q, k, v, causal=True)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), rtol=2e-5, atol=2e-5)


@pytest.mark.parametrize("s", [128, 130])  # 130: pad path with segment ids
def test_segment_ids(s):
    b, h, d = 2, 2, 32
    q, k, v = _rand((b, s, h, d), 0), _rand((b, s, h, d), 1), _rand((b, s, h, d), 2)
    seg = jnp.concatenate(
        [jnp.zeros((b, 48), jnp.int32), jnp.ones((b, s - 48), jnp.int32)], axis=1
    )
    out = attention_chunked(q, k, v, causal=True, segment_ids=seg, block_kv=64)
    ref = attention_reference(q, k, v, causal=True, segment_ids=seg)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), rtol=2e-5, atol=2e-5)


def test_decode_offsets_and_valid_len():
    """Decode-with-cache shape: 1 query row, padded cache tail masked out."""
    b, h, d, cache = 2, 4, 32, 160
    q = _rand((b, 1, h, d), 0)
    k, v = _rand((b, cache, h, d), 1), _rand((b, cache, h, d), 2)
    q_offset = jnp.asarray(70)
    valid = q_offset + 1
    out = attention_chunked(
        q, k, v, causal=True, q_offset=q_offset, kv_valid_len=valid, block_kv=64)
    ref = attention_reference(
        q, k, v, causal=True, q_offset=q_offset, kv_valid_len=valid)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), rtol=2e-5, atol=2e-5)


def test_fully_masked_rows_are_zero():
    """Rows with no visible kv (q_offset past valid len) return 0, not mean(v)."""
    b, h, d = 1, 2, 32
    q = _rand((b, 4, h, d), 0)
    k, v = _rand((b, 64, h, d), 1), _rand((b, 64, h, d), 2)
    out = attention_chunked(q, k, v, causal=True, kv_valid_len=jnp.asarray(0), block_kv=32)
    np.testing.assert_array_equal(np.asarray(out), np.zeros_like(out))


def test_grads_match_reference():
    b, s, h, d = 1, 128, 4, 32
    q, k, v = _rand((b, s, h, d), 0), _rand((b, s, h, d), 1), _rand((b, s, h, d), 2)

    g_chunk = jax.grad(
        lambda q, k, v: attention_chunked(q, k, v, causal=True, block_kv=64).sum(),
        argnums=(0, 1, 2))(q, k, v)
    g_ref = jax.grad(
        lambda q, k, v: attention_reference(q, k, v, causal=True).sum(),
        argnums=(0, 1, 2))(q, k, v)
    for a, b_ in zip(g_chunk, g_ref):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b_), rtol=1e-4, atol=1e-4)
