"""Model multiplexing tests (reference python/ray/serve/multiplex.py +
tests/test_multiplex.py; SURVEY.md §2.6 batching/multiplex row)."""
import pytest

from ray_tpu import serve
from ray_tpu.serve.multiplex import _MultiplexWrapper


def test_lru_eviction_unit():
    loads = []

    @serve.multiplexed(max_num_models_per_replica=2)
    def get_model(model_id):
        loads.append(model_id)
        return f"model-{model_id}"

    assert get_model("a") == "model-a"
    assert get_model("b") == "model-b"
    assert get_model("a") == "model-a"  # cache hit, no reload
    assert loads == ["a", "b"]
    get_model("c")  # evicts b (LRU)
    assert sorted(get_model.loaded_model_ids()) == ["a", "c"]
    get_model("b")  # reload after eviction
    assert loads == ["a", "b", "c", "b"]


def test_method_decorator_binds_per_instance():
    class Host:
        def __init__(self, tag):
            self.tag = tag

        @serve.multiplexed(max_num_models_per_replica=2)
        def get_model(self, model_id):
            return f"{self.tag}:{model_id}"

    h1, h2 = Host("x"), Host("y")
    assert h1.get_model("m") == "x:m"
    assert h2.get_model("m") == "y:m"
    # per-instance LRUs persist across alternating access (no thrash)
    assert h1.get_model.loaded_model_ids() == ["m"]
    assert h2.get_model.loaded_model_ids() == ["m"]
    assert h1.get_model("m2") == "x:m2"
    assert sorted(h1.get_model.loaded_model_ids()) == ["m", "m2"]
    assert h2.get_model.loaded_model_ids() == ["m"]


def test_multiplexed_serving_end_to_end(rt):
    @serve.deployment(num_replicas=2, max_ongoing_requests=4)
    class MultiModel:
        def __init__(self):
            self.loads = 0

        @serve.multiplexed(max_num_models_per_replica=2)
        def get_model(self, model_id):
            self.loads += 1
            return f"weights-of-{model_id}"

        def __call__(self, body):
            model_id = serve.get_multiplexed_model_id()
            model = self.get_model(model_id)
            return {"model": model_id, "weights": model, "loads": self.loads}

    serve.run(MultiModel.bind(), name="mux-app", route_prefix="/mux")
    try:
        h = serve.get_app_handle("mux-app")
        out = h.options(multiplexed_model_id="m1").remote({}).result()
        assert out == {"model": "m1", "weights": "weights-of-m1", "loads": 1}
        # repeated m1 requests ride the same replica's cache: loads stays 1
        for _ in range(5):
            out = h.options(multiplexed_model_id="m1").remote({}).result()
        assert out["loads"] == 1
        out2 = h.options(multiplexed_model_id="m2").remote({}).result()
        assert out2["weights"] == "weights-of-m2"
        # a request WITHOUT a model id must not inherit the previous one
        import pytest as _pytest

        with _pytest.raises(Exception, match="no multiplexed model id"):
            h.remote({}).result()
    finally:
        serve.delete("mux-app")


def test_missing_model_id_is_an_error():
    @serve.multiplexed
    def get_model(model_id):
        return model_id

    with pytest.raises(ValueError, match="no multiplexed model id"):
        get_model()
