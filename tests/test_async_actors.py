"""Async actors (per-actor event loop) + ASGI serve replicas.

Reference: python/ray/actor.py:2352 (async actor methods),
python/ray/serve/_private/replica.py:72 (ASGIAppReplicaWrapper / serve.ingress).
"""
import json
import time
import urllib.request

import ray_tpu


def test_async_actor_methods_interleave(rt):
    """Many in-flight async calls overlap on one event loop: total wall time is
    ~one sleep, not the sum."""

    @rt.remote
    class A:
        def __init__(self):
            self.n = 0

        async def slow_incr(self):
            import asyncio

            self.n += 1
            before = self.n
            await asyncio.sleep(0.5)
            return (before, self.n)

        async def peek(self):
            return self.n

    a = A.remote()
    rt.get(a.peek.remote(), timeout=60)  # warm-up: exclude worker spawn time
    t0 = time.time()
    refs = [a.slow_incr.remote() for _ in range(8)]
    out = rt.get(refs, timeout=30)
    elapsed = time.time() - t0
    assert elapsed < 2.5, f"async calls serialized ({elapsed:.1f}s)"
    # all 8 entered before any finished: every `before` is < final count seen after sleep
    assert {b for b, _ in out} == set(range(1, 9))
    assert all(after == 8 for _, after in out)
    assert rt.get(a.peek.remote(), timeout=10) == 8


def test_async_actor_error_propagates(rt):
    @rt.remote
    class A:
        async def boom(self):
            raise ValueError("async-boom")

    a = A.remote()
    try:
        rt.get(a.boom.remote(), timeout=20)
        raise AssertionError("expected error")
    except Exception as e:
        assert "async-boom" in str(e)


def test_async_generator_streaming(rt):
    @rt.remote
    class A:
        async def agen(self, n):
            import asyncio

            for i in range(n):
                await asyncio.sleep(0.01)
                yield i * 10

    a = A.remote()
    vals = [rt.get(r) for r in a.agen.options(num_returns="streaming").remote(4)]
    assert vals == [0, 10, 20, 30]


def test_serve_async_deployment_concurrent(rt):
    from ray_tpu import serve

    @serve.deployment(max_ongoing_requests=8)
    class AsyncD:
        async def __call__(self, body):
            import asyncio

            await asyncio.sleep(0.4)
            return {"x": body["x"] * 2}

    try:
        serve.run(AsyncD.bind(), name="asyncd", route_prefix="/asyncd")
        h = serve.get_app_handle("asyncd")
        h.remote({"x": 0}).result()  # warm-up: exclude replica startup
        t0 = time.time()
        resps = [h.remote({"x": i}) for i in range(6)]
        out = [r.result() for r in resps]
        elapsed = time.time() - t0
        assert [o["x"] for o in out] == [0, 2, 4, 6, 8, 10]
        assert elapsed < 2.0, f"async deployment serialized requests ({elapsed:.1f}s)"
    finally:
        serve.shutdown()


def _tiny_asgi_app(scope, receive, send):
    """Hand-rolled ASGI 3.0 app (FastAPI-shaped behavior without the dep)."""

    async def run():
        assert scope["type"] == "http"
        msg = await receive()
        body = msg.get("body", b"")
        if scope["path"] == "/hello":
            payload = json.dumps({"hello": "world", "method": scope["method"]}).encode()
            status = 200
        elif scope["path"] == "/echo":
            data = json.loads(body or b"{}")
            payload = json.dumps({"echo": data, "q": scope["query_string"].decode()}).encode()
            status = 200
        else:
            payload, status = b"nope", 404
        await send({"type": "http.response.start", "status": status,
                    "headers": [(b"content-type", b"application/json"),
                                (b"x-served-by", b"ray-tpu-asgi")]})
        await send({"type": "http.response.body", "body": payload})

    return run()


def test_asgi_app_through_proxy(rt):
    from ray_tpu import serve

    @serve.deployment
    @serve.ingress(_tiny_asgi_app)
    class Ingress:
        pass

    try:
        serve.run(Ingress.bind(), name="asgi", route_prefix="/asgi")
        serve.start(http_options={"port": 8124})

        resp = urllib.request.urlopen("http://127.0.0.1:8124/asgi/hello", timeout=60)
        assert resp.status == 200
        assert resp.headers["x-served-by"] == "ray-tpu-asgi"
        assert json.loads(resp.read()) == {"hello": "world", "method": "GET"}

        req = urllib.request.Request(
            "http://127.0.0.1:8124/asgi/echo?k=v", data=b'{"a": 1}',
            headers={"Content-Type": "application/json"})
        out = json.loads(urllib.request.urlopen(req, timeout=60).read())
        assert out == {"echo": {"a": 1}, "q": "k=v"}

        # 404 passes through with the app's status
        try:
            urllib.request.urlopen("http://127.0.0.1:8124/asgi/missing", timeout=60)
            raise AssertionError("expected 404")
        except urllib.error.HTTPError as e:
            assert e.code == 404
    finally:
        serve.shutdown()
