"""Chaos suite: workloads must survive injected worker/node kills.

Reference analog: python/ray/tests/chaos/ + setup_chaos.py kill policies
(SURVEY.md §4 fault-tolerance tests). The collective cases inject rank death
mid-op (ChaosController.kill_collective_rank — util/fault_injection.py, the
unified chaos API) and assert the abort path: survivors fail fast with a
typed CollectiveAbortError — never by burning the full op timeout — and
elastic Train recovers from its last checkpoint."""
import time

import pytest

import ray_tpu
from ray_tpu.test_utils import NodeKiller, WorkerKiller, wait_for_condition
from ray_tpu.util.fault_injection import ChaosController




def test_retryable_tasks_survive_worker_kills(rt):
    @rt.remote(max_retries=5)
    def slow_add(a, b):
        time.sleep(0.3)
        return a + b

    refs = [slow_add.remote(i, 1000) for i in range(12)]
    killer = WorkerKiller(kill_interval_s=0.25, max_kills=3)
    killer.run_policy()
    try:
        results = rt.get(refs, timeout=120)
    finally:
        killer.stop()
    assert sorted(results) == [i + 1000 for i in range(12)]
    assert killer.kills_done >= 1  # chaos actually happened


def test_restartable_actor_survives_kills(rt):
    @rt.remote(max_restarts=10, max_task_retries=10)
    class Counter:
        def __init__(self):
            self.n = 0

        def bump(self):
            self.n += 1
            return self.n

    c = Counter.remote()
    assert rt.get(c.bump.remote()) == 1
    killer = WorkerKiller(kill_interval_s=0.3, max_kills=2)
    killer.run_policy()
    ok = 0
    try:
        for _ in range(20):
            try:
                rt.get(c.bump.remote(), timeout=30)
                ok += 1
            except Exception:
                pass  # a call may race the restart window
            time.sleep(0.1)
    finally:
        killer.stop()
    # actor must keep serving after restarts
    assert ok >= 10
    assert rt.get(c.bump.remote(), timeout=30) >= 1


def test_node_kill_reschedules_tasks(rt):
    cluster = __import__("ray_tpu.core.global_state", fromlist=["x"]).try_cluster()
    extra = cluster.add_node({"CPU": 2.0})

    @rt.remote(max_retries=3)
    def work(i):
        time.sleep(0.2)
        return i * 2

    refs = [work.remote(i) for i in range(10)]
    time.sleep(0.3)
    nk = NodeKiller()
    killed = nk.kill_node(extra.node_id)
    assert killed is not None
    assert sorted(rt.get(refs, timeout=120)) == [i * 2 for i in range(10)]


# -- collective abort propagation ------------------------------------------------------
def _make_collective_members(rt, n):
    @rt.remote(num_cpus=0)
    class ChaosMember:
        def __init__(self, rank):
            self.rank = rank

        def _ray_tpu_collective_init(self, world_size, rank, backend, group_name):
            from ray_tpu.util import collective as col

            col.init_collective_group(world_size, rank, backend, group_name)

        def timed_allreduce(self, group_name, nelem):
            """Returns (status, elapsed_s, failed_rank): survivors of a rank
            death must observe a typed abort, fast."""
            import numpy as np

            from ray_tpu.util import collective as col
            from ray_tpu.util.collective import CollectiveAbortError

            x = np.full((nelem,), float(self.rank + 1), dtype=np.float32)
            t0 = time.monotonic()
            try:
                col.allreduce(x, group_name)
                return ("ok", time.monotonic() - t0, None)
            except CollectiveAbortError as e:
                return ("abort", time.monotonic() - t0, e.failed_rank)
            except TimeoutError:
                return ("timeout", time.monotonic() - t0, None)

        def abort_then_destroy(self, group_name):
            """Block in an op until the group is aborted, then destroy the
            group — twice — while it is still mid-abort. Must not hang."""
            import numpy as np

            from ray_tpu.util import collective as col
            from ray_tpu.util.collective import CollectiveAbortError

            t0 = time.monotonic()
            try:
                col.allreduce(np.ones(4, np.float32), group_name)
                return ("ok", time.monotonic() - t0)
            except CollectiveAbortError:
                col.destroy_collective_group(group_name)
                col.destroy_collective_group(group_name)  # idempotent
                return ("abort", time.monotonic() - t0)

        def destroy(self, group_name):
            from ray_tpu.util import collective as col

            col.destroy_collective_group(group_name)
            return True

    return [ChaosMember.remote(i) for i in range(n)]


@pytest.mark.parametrize("nelem", [64, 200_000])  # board path / ring path
def test_rank_death_mid_allreduce_aborts_survivors_fast(rt, nelem):
    """Acceptance: kill a rank mid-allreduce at world size 4 — every surviving
    rank observes CollectiveAbortError naming the dead rank, well inside 25%
    of collective_op_timeout_s (worker death propagates through the head's
    membership registry to the group coordinator's poison flag; nobody burns
    the deadline)."""
    from ray_tpu.config import CONFIG
    from ray_tpu.util import collective as col

    group = f"chaos_ar_{nelem}"
    members = _make_collective_members(rt, 4)
    try:
        col.create_collective_group(members, 4, [0, 1, 2, 3],
                                    backend="shm", group_name=group)
        chaos = ChaosController()
        assert chaos.collective_rank_registered(group, rank=3)
        # survivors enter the op; rank 3 never does, then dies
        refs = [w.timed_allreduce.remote(group, nelem) for w in members[:3]]
        time.sleep(0.3)
        assert chaos.kill_collective_rank(group, rank=3)
        results = rt.get(refs, timeout=60)
        budget = 0.25 * CONFIG.collective_op_timeout_s
        for status, elapsed, failed_rank in results:
            assert status == "abort", results
            assert elapsed < budget, (elapsed, budget)
            assert failed_rank == 3
    finally:
        for w in members:
            try:
                rt.kill(w)
            except Exception:
                pass
        col.kill_coordinator(group)


def test_destroy_during_abort_idempotent_then_reinit(rt):
    """destroy_collective_group while the group is mid-abort returns promptly
    (no peer waits), double-destroy is a no-op, and the same group name
    re-initializes cleanly on a fresh epoch afterwards."""
    import numpy as np

    from ray_tpu.util import collective as col

    group = "chaos_destroy"
    members = _make_collective_members(rt, 2)
    try:
        col.create_collective_group(members, 2, [0, 1],
                                    backend="shm", group_name=group)
        # rank 0 blocks in an allreduce rank 1 never joins; then the group is
        # aborted out from under it
        ref = members[0].abort_then_destroy.remote(group)
        time.sleep(0.2)
        assert col.abort_collective_group(group, reason="operator abort")
        status, elapsed = rt.get(ref, timeout=20)
        assert status == "abort"
        assert elapsed < 10  # failed fast, did not burn the op deadline
        # the idle member's destroy must not hang either — and is idempotent
        assert rt.get(members[1].destroy.remote(group), timeout=10)
        assert rt.get(members[1].destroy.remote(group), timeout=10)
        # same name, same actors, fresh epoch: the aborted incarnation's state
        # must not leak into the new group
        col.create_collective_group(members, 2, [0, 1],
                                    backend="shm", group_name=group)
        out = rt.get([w.timed_allreduce.remote(group, 8) for w in members],
                     timeout=30)
        assert [s for s, _, _ in out] == ["ok", "ok"]
    finally:
        for w in members:
            try:
                rt.kill(w)
            except Exception:
                pass
        col.kill_coordinator(group)


def _chaos_train_loop(config):
    import json
    import os
    import tempfile

    import numpy as np

    import ray_tpu.train as train
    from ray_tpu.train import Checkpoint
    from ray_tpu.util import collective as col
    from ray_tpu.util.collective import CollectiveAbortError

    ctx = train.get_context()
    group = os.environ["RAY_TPU_TRAIN_COLLECTIVE_GROUP"]
    ckpt = train.get_checkpoint()
    start = 0
    if ckpt is not None:
        with ckpt.as_directory() as d:
            start = json.load(open(os.path.join(d, "state.json")))["step"] + 1
    for step in range(start, config["steps"]):
        try:
            out = col.allreduce(np.ones(8, np.float32), group)
        except CollectiveAbortError:
            # survivors of the injected rank death see the typed abort, not a
            # bare timeout; leave a marker so the driver can assert it
            open(os.path.join(config["marker_dir"],
                              f"abort_rank{ctx.get_world_rank()}"), "w").close()
            raise
        assert float(out[0]) == float(ctx.get_world_size())
        checkpoint = None
        if ctx.get_world_rank() == 0:
            d = tempfile.mkdtemp(prefix="chaos_ckpt_")
            json.dump({"step": step}, open(os.path.join(d, "state.json"), "w"))
            checkpoint = Checkpoint.from_directory(d)
        train.report({"step": step, "start": start}, checkpoint=checkpoint)
        time.sleep(config["step_s"])


def test_train_v2_recovers_from_rank_death(rt, tmp_path):
    """Acceptance: a Train v2 run with max_failures=1 whose rank 1 is killed
    mid-run restarts automatically and finishes with correct results from its
    last checkpoint."""
    import threading

    from ray_tpu.air.config import CheckpointConfig, FailureConfig, RunConfig, ScalingConfig
    from ray_tpu.train import JaxConfig, TrainController
    from ray_tpu.train.checkpoint_manager import CheckpointManager

    group = "chaos_train"
    marker_dir = tmp_path / "markers"
    marker_dir.mkdir()
    mgr = CheckpointManager(str(tmp_path / "run"), CheckpointConfig())
    ctl = TrainController(
        _chaos_train_loop,
        backend_config=JaxConfig(collective_group=True,
                                 collective_group_name=group),
        scaling_config=ScalingConfig(num_workers=2, cpus_per_worker=0.5),
        run_config=RunConfig(storage_path=str(tmp_path),
                             failure_config=FailureConfig(max_failures=1)),
        checkpoint_manager=mgr,
        train_loop_config={"steps": 10, "step_s": 0.2,
                           "marker_dir": str(marker_dir)},
    )
    done = {}

    def run():
        done["result"] = ctl.run()

    t = threading.Thread(target=run, daemon=True)
    t.start()
    # kill rank 1 only after a checkpoint is durable, so "resume from latest
    # checkpoint" is the path under test
    chaos = ChaosController()
    wait_for_condition(
        lambda: (chaos.collective_rank_registered(group, rank=1)
                 and mgr.latest_checkpoint is not None),
        timeout=30, message="no checkpoint before injection window closed")
    assert chaos.kill_collective_rank(group, rank=1)
    t.join(timeout=90)
    assert not t.is_alive(), "controller hung after rank death"
    result = done["result"]
    assert result.error is None, result.error
    assert ctl.failure_count == 1
    assert result.metrics["step"] == 9  # ran to completion
    # the second attempt resumed from a checkpoint, not from scratch
    assert any(m.get("start", 0) > 0 for m in result.metrics_dataframe)
    # the surviving rank observed the typed abort (not a bare timeout)
    assert (marker_dir / "abort_rank0").exists()


def test_unretryable_task_fails_cleanly(rt):
    from ray_tpu.test_utils import kill_worker_running

    @rt.remote(max_retries=0)
    def stuck():
        time.sleep(30)
        return "nope"

    ref = stuck.remote()
    wait_for_condition(lambda: kill_worker_running("stuck"), timeout=10,
                       message="never saw the stuck task running")
    with pytest.raises(Exception):
        rt.get(ref, timeout=60)


def _mpmd_pipeline_train_loop(config):
    """pp-stage MPMD pipeline train_fn: rank == stage, blocks ride the
    backend-created Train collective group (stage_runner_from_train_context),
    so a stage death flows through Train's failure policy unchanged."""
    import json
    import os
    import tempfile

    import jax
    import jax.numpy as jnp
    import numpy as np

    import ray_tpu.train as train
    from ray_tpu.train import Checkpoint
    from ray_tpu.train.mpmd_pipeline import (MPMDPipelineConfig,
                                             stage_runner_from_train_context)
    from ray_tpu.util.collective import CollectiveAbortError

    ctx = train.get_context()
    stage, pp = ctx.get_world_rank(), ctx.get_world_size()
    d, mb = int(config["d"]), int(config["mb"])
    m = int(config["microbatches"])

    def stage_fn(params, x):
        return x + jnp.tanh(x @ params["w"]) @ params["w2"]

    k1, k2 = jax.random.split(jax.random.PRNGKey(100 + stage))
    params = {"w": np.asarray(jax.random.normal(k1, (d, 2 * d)) * 0.1),
              "w2": np.asarray(jax.random.normal(k2, (2 * d, d)) * 0.1)}
    start = 0
    ckpt = train.get_checkpoint()
    if ckpt is not None:
        with ckpt.as_directory() as cd:
            state = json.load(open(os.path.join(cd, "state.json")))
            start = state["step"] + 1
            if stage == 0:  # rank 0's params ride the durable checkpoint
                params = {k: np.asarray(v, np.float32)
                          for k, v in state["params"].items()}
    runner = stage_runner_from_train_context(
        stage_fn, params,
        MPMDPipelineConfig(num_microbatches=m,
                           group_name=os.environ["RAY_TPU_TRAIN_COLLECTIVE_GROUP"]),
        loss_fn=(lambda y: jnp.mean(y ** 2)) if stage == pp - 1 else None,
        in_spec=((mb, d), np.float32), out_spec=((mb, d), np.float32))
    try:
        for step in range(start, config["steps"]):
            batch = None
            if stage == 0:
                batch = np.random.default_rng(step).standard_normal(
                    (m * mb, d)).astype(np.float32)
            try:
                metrics = runner.run_step(step, batch)
            except CollectiveAbortError:
                # survivors observe the typed abort (not a bare timeout) and
                # leak nothing: run_step's cleanup retracted in-flight blocks
                with open(os.path.join(config["marker_dir"],
                                       f"abort_rank{stage}"), "w") as f:
                    json.dump(runner.comm.admission_counters(), f)
                raise
            checkpoint = None
            if stage == 0:
                cd = tempfile.mkdtemp(prefix="mpmd_ckpt_")
                json.dump(
                    {"step": step,
                     "params": {k: np.asarray(v).tolist()
                                for k, v in runner.params_host().items()}},
                    open(os.path.join(cd, "state.json"), "w"))
                checkpoint = Checkpoint.from_directory(cd)
            train.report({"step": step, "start": start,
                          "loss": metrics.get("loss")}, checkpoint=checkpoint)
            time.sleep(config["step_s"])
    finally:
        runner.close()


def test_mpmd_pipeline_survives_stage_kill(rt, tmp_path):
    """Acceptance (ISSUE 19): SIGKILL the MIDDLE stage of a pp=3 MPMD pipeline
    mid-schedule. Survivors must raise the typed CollectiveAbortError within
    the abort-poll bound (their markers appear), admission counters must read
    zero after cleanup (no leaked in-flight activation blocks), and Train's
    max_failures=1 restart must complete the run from the latest checkpoint."""
    import json
    import threading

    from ray_tpu.air.config import (CheckpointConfig, FailureConfig, RunConfig,
                                    ScalingConfig)
    from ray_tpu.train import JaxConfig, TrainController
    from ray_tpu.train.checkpoint_manager import CheckpointManager

    group = "chaos_mpmd"
    marker_dir = tmp_path / "markers"
    marker_dir.mkdir()
    mgr = CheckpointManager(str(tmp_path / "run"), CheckpointConfig())
    ctl = TrainController(
        _mpmd_pipeline_train_loop,
        backend_config=JaxConfig(collective_group=True,
                                 collective_group_name=group),
        scaling_config=ScalingConfig(num_workers=3, cpus_per_worker=0.5),
        run_config=RunConfig(storage_path=str(tmp_path),
                             failure_config=FailureConfig(max_failures=1)),
        checkpoint_manager=mgr,
        train_loop_config={"steps": 8, "step_s": 0.2, "d": 4, "mb": 2,
                           "microbatches": 2, "marker_dir": str(marker_dir)},
    )
    done = {}

    def run():
        done["result"] = ctl.run()

    t = threading.Thread(target=run, daemon=True, name="mpmd-chaos-driver")
    t.start()
    chaos = ChaosController()
    # kill the middle stage only once a checkpoint is durable, so "resume from
    # latest checkpoint" is the path under test
    wait_for_condition(
        lambda: (chaos.collective_rank_registered(group, rank=1)
                 and mgr.latest_checkpoint is not None),
        timeout=60, message="no checkpoint before injection window closed")
    assert chaos.kill_collective_rank(group, rank=1)
    t.join(timeout=180)
    assert not t.is_alive(), "controller hung after stage death"
    result = done["result"]
    assert result.error is None, result.error
    assert ctl.failure_count == 1
    assert result.metrics["step"] == 7  # ran to completion
    # the second attempt resumed from a checkpoint, not from scratch
    assert any(m.get("start", 0) > 0 for m in result.metrics_dataframe)
    # at least one surviving stage observed the typed abort; its admission
    # counters (published blocks + in-flight pulls) read zero after cleanup
    markers = [marker_dir / f"abort_rank{r}" for r in (0, 2)]
    seen = [p for p in markers if p.exists()]
    assert seen, "no survivor observed the typed CollectiveAbortError"
    for p in seen:
        assert json.load(open(p)) == {"published": 0, "inflight_pulls": 0}
