"""Chaos suite: workloads must survive injected worker/node kills.

Reference analog: python/ray/tests/chaos/ + setup_chaos.py kill policies
(SURVEY.md §4 fault-tolerance tests)."""
import time

import pytest

import ray_tpu
from ray_tpu.test_utils import NodeKiller, WorkerKiller, wait_for_condition




def test_retryable_tasks_survive_worker_kills(rt):
    @rt.remote(max_retries=5)
    def slow_add(a, b):
        time.sleep(0.3)
        return a + b

    refs = [slow_add.remote(i, 1000) for i in range(12)]
    killer = WorkerKiller(kill_interval_s=0.25, max_kills=3)
    killer.run_policy()
    try:
        results = rt.get(refs, timeout=120)
    finally:
        killer.stop()
    assert sorted(results) == [i + 1000 for i in range(12)]
    assert killer.kills_done >= 1  # chaos actually happened


def test_restartable_actor_survives_kills(rt):
    @rt.remote(max_restarts=10, max_task_retries=10)
    class Counter:
        def __init__(self):
            self.n = 0

        def bump(self):
            self.n += 1
            return self.n

    c = Counter.remote()
    assert rt.get(c.bump.remote()) == 1
    killer = WorkerKiller(kill_interval_s=0.3, max_kills=2)
    killer.run_policy()
    ok = 0
    try:
        for _ in range(20):
            try:
                rt.get(c.bump.remote(), timeout=30)
                ok += 1
            except Exception:
                pass  # a call may race the restart window
            time.sleep(0.1)
    finally:
        killer.stop()
    # actor must keep serving after restarts
    assert ok >= 10
    assert rt.get(c.bump.remote(), timeout=30) >= 1


def test_node_kill_reschedules_tasks(rt):
    cluster = __import__("ray_tpu.core.global_state", fromlist=["x"]).try_cluster()
    extra = cluster.add_node({"CPU": 2.0})

    @rt.remote(max_retries=3)
    def work(i):
        time.sleep(0.2)
        return i * 2

    refs = [work.remote(i) for i in range(10)]
    time.sleep(0.3)
    nk = NodeKiller()
    killed = nk.kill_node(extra.node_id)
    assert killed is not None
    assert sorted(rt.get(refs, timeout=120)) == [i * 2 for i in range(10)]


def test_unretryable_task_fails_cleanly(rt):
    from ray_tpu.test_utils import kill_worker_running

    @rt.remote(max_retries=0)
    def stuck():
        time.sleep(30)
        return "nope"

    ref = stuck.remote()
    wait_for_condition(lambda: kill_worker_running("stuck"), timeout=10,
                       message="never saw the stuck task running")
    with pytest.raises(Exception):
        rt.get(ref, timeout=60)
