"""Dataset.join tests (reference operators/join.py over hash_shuffle.py)."""
import numpy as np
import pytest

from ray_tpu import data as rtd


@pytest.fixture(autouse=True)
def _cluster(rt):
    yield


def _left():
    return rtd.from_items([
        {"id": 1, "x": 10}, {"id": 2, "x": 20}, {"id": 3, "x": 30}, {"id": 2, "x": 21},
    ])


def _right():
    return rtd.from_items([
        {"id": 2, "y": "b"}, {"id": 3, "y": "c"}, {"id": 4, "y": "d"},
    ])


def test_inner_join():
    rows = _left().join(_right(), on="id").take_all()
    got = sorted((r["id"], r["x"], r["y"]) for r in rows)
    assert got == [(2, 20, "b"), (2, 21, "b"), (3, 30, "c")]


def test_left_outer_join():
    rows = _left().join(_right(), on="id", how="left_outer").take_all()
    by_id = sorted((r["id"], r["x"], r["y"]) for r in rows)
    assert (1, 10, None) in by_id
    assert len(by_id) == 4


def test_right_outer_join():
    rows = _left().join(_right(), on="id", how="right_outer").take_all()
    ids = sorted(r["id"] for r in rows)
    assert ids == [2, 2, 3, 4]
    d4 = next(r for r in rows if r["id"] == 4)
    # numeric nulls surface as None (arrow rows) or NaN (numpy batch path)
    assert d4["x"] is None or np.isnan(d4["x"])
    assert d4["y"] == "d"


def test_full_outer_join():
    rows = _left().join(_right(), on="id", how="full_outer").take_all()
    ids = sorted(r["id"] for r in rows)
    assert ids == [1, 2, 2, 3, 4]


def test_join_column_name_collision_and_partitions():
    left = rtd.from_items([{"k": i, "v": i * 2} for i in range(50)])
    right = rtd.from_items([{"k": i, "v": i * 3} for i in range(0, 50, 2)])
    rows = left.join(right, on="k", num_partitions=4).take_all()
    assert len(rows) == 25
    for r in rows:
        assert r["v"] == r["k"] * 2
        assert r["v_1"] == r["k"] * 3


def test_join_then_map_batches_composes():
    left = rtd.from_items([{"k": i, "a": float(i)} for i in range(20)])
    right = rtd.from_items([{"k": i, "b": float(i * i)} for i in range(20)])
    out = (
        left.join(right, on="k")
        .map_batches(lambda b: {"s": b["a"] + b["b"]}, batch_format="numpy")
        .take_all()
    )
    assert len(out) == 20
    assert sorted(r["s"] for r in out) == sorted(float(i + i * i) for i in range(20))


def test_bad_join_type_raises():
    with pytest.raises(ValueError):
        _left().join(_right(), on="id", how="cross")


def test_null_keys_never_match():
    left = rtd.from_items([{"id": 1, "x": 1}, {"id": None, "x": 2}])
    right = rtd.from_items([{"id": 1, "y": 1}, {"id": None, "y": 2}])
    inner = left.join(right, on="id").take_all()
    assert [r["id"] for r in inner] == [1]  # null keys drop from inner joins
    full = left.join(right, on="id", how="full_outer").take_all()
    # null-keyed rows appear null-extended on each side, never matched
    assert len(full) == 3


def test_join_rename_collision_uniquified():
    """Left already has v and v_1; right's v must rename to v_2, not silently
    drop a column via a duplicate dict key."""
    left = rtd.from_items([{"k": 1, "v": 10, "v_1": 11}])
    right = rtd.from_items([{"k": 1, "v": 20}])
    out = left.join(right, on="k").take_all()
    assert len(out) == 1
    row = out[0]
    assert set(row.keys()) == {"k", "v", "v_1", "v_2"}
    assert row["v"] == 10 and row["v_1"] == 11 and row["v_2"] == 20
