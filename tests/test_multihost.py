"""Multi-host control plane: head + node agent as a separate OS process tree.

Reference analog: ray.cluster_utils.Cluster multi-raylet fixture (SURVEY.md §4) —
but here the second "host" really is a separate process tree joined over
localhost TCP (core/node_agent.py), exercising registration, heartbeats, remote
worker spawn/dispatch, cross-host object transfer, and agent-death recovery.

Note: both "hosts" share one machine, so a wrong-host shm location would still
resolve in-process; tests therefore also assert directory-level host tagging
where the distinction matters.
"""
import os
import signal
import subprocess
import sys
import time

import numpy as np
import pytest

import ray_tpu
from ray_tpu.core import global_state
from ray_tpu.core.task_spec import NodeAffinitySchedulingStrategy


def _spawn_agent(port, num_cpus=2.0):
    return subprocess.Popen(
        [sys.executable, "-m", "ray_tpu.core.node_agent",
         "--address", f"127.0.0.1:{port}", "--num-cpus", str(num_cpus)],
        env={**os.environ, "JAX_PLATFORMS": "cpu"},
    )


def _wait_nodes(n, timeout=30):
    deadline = time.time() + timeout
    while len([x for x in ray_tpu.nodes() if x["Alive"]]) < n:
        assert time.time() < deadline, "node agent never registered"
        time.sleep(0.2)


def _remote_node_id():
    return next(n["NodeID"] for n in ray_tpu.nodes()
                if n["Alive"] and n["Labels"].get("agent") == "remote")


@pytest.fixture()
def two_hosts(rt):
    """Head (this process) + one node agent (separate process tree over TCP)."""
    ray_tpu.shutdown()
    ray_tpu.init(num_cpus=2, node_server_port=0,
                 worker_env={"JAX_PLATFORMS": "cpu"})
    cluster = global_state.try_cluster()
    agent = _spawn_agent(cluster.node_server_port)
    try:
        _wait_nodes(2)
        yield cluster, agent
    finally:
        if agent.poll() is None:
            agent.terminate()
            try:
                agent.wait(timeout=10)
            except subprocess.TimeoutExpired:
                agent.kill()
        ray_tpu.shutdown()
        ray_tpu.init(num_cpus=4, worker_env={"JAX_PLATFORMS": "cpu"},
                     max_workers_per_node=8)


def _on_node(node_id):
    return NodeAffinitySchedulingStrategy(node_id=node_id)


def test_agent_registers_and_runs_tasks(two_hosts):
    remote_id = _remote_node_id()

    @ray_tpu.remote(scheduling_strategy=_on_node(remote_id))
    def where():
        return ray_tpu.get_runtime_context().node_id

    assert ray_tpu.get(where.remote(), timeout=60) == remote_id


def test_cross_host_object_transfer(two_hosts):
    cluster, _ = two_hosts
    remote_id = _remote_node_id()

    @ray_tpu.remote(scheduling_strategy=_on_node(remote_id))
    def produce():
        return np.arange(200_000, dtype=np.float64)  # > inline threshold

    ref = produce.remote()
    # directory holds a host-tagged location before the driver localizes it
    deadline = time.time() + 60
    while cluster.store.try_location(ref.id) is None:
        assert time.time() < deadline
        time.sleep(0.05)
    loc = cluster.store.try_location(ref.id)
    assert loc[0] == "remote" and loc[1] == remote_id
    arr = ray_tpu.get(ref, timeout=60)
    assert arr.shape == (200_000,) and float(arr[12345]) == 12345.0

    # driver -> remote direction
    big = np.ones(150_000, dtype=np.float64) * 3.0
    bref = ray_tpu.put(big)

    @ray_tpu.remote(scheduling_strategy=_on_node(remote_id))
    def consume(x):
        return float(x.sum())

    assert ray_tpu.get(consume.remote(bref), timeout=60) == 450_000.0


def test_remote_to_remote_between_workers(two_hosts):
    """Object produced on the remote host consumed by a head-host worker."""
    remote_id = _remote_node_id()
    head_id = next(n["NodeID"] for n in ray_tpu.nodes()
                   if n["Alive"] and not n["Labels"].get("agent"))

    @ray_tpu.remote(scheduling_strategy=_on_node(remote_id))
    def produce():
        return np.full(120_000, 7.0)

    @ray_tpu.remote(scheduling_strategy=_on_node(head_id))
    def consume(x):
        return float(x[0]), ray_tpu.get_runtime_context().node_id

    val, nid = ray_tpu.get(consume.remote(produce.remote()), timeout=60)
    assert val == 7.0 and nid == head_id


def test_remote_actor_and_named_lookup(two_hosts):
    remote_id = _remote_node_id()

    @ray_tpu.remote(scheduling_strategy=_on_node(remote_id), name="mh-counter")
    class Counter:
        def __init__(self):
            self.n = 0

        def incr(self, k=1):
            self.n += k
            return self.n

    c = Counter.remote()
    assert ray_tpu.get(c.incr.remote(), timeout=60) == 1
    h = ray_tpu.get_actor("mh-counter")
    assert ray_tpu.get(h.incr.remote(5), timeout=60) == 6


def test_remote_worker_crash_retries(two_hosts):
    remote_id = _remote_node_id()

    @ray_tpu.remote(scheduling_strategy=_on_node(remote_id), max_retries=2)
    def crash_once(key):
        import tempfile

        marker = os.path.join(tempfile.gettempdir(), f"mh_crash_{key}")
        if not os.path.exists(marker):
            open(marker, "w").write("1")
            os._exit(1)
        return ray_tpu.get_runtime_context().node_id

    key = str(time.time()).replace(".", "")
    nid = ray_tpu.get(crash_once.remote(key), timeout=90)
    assert nid == remote_id  # retried on the same (affine) node


def test_agent_sigkill_task_retries_on_survivor(two_hosts):
    """Chaos: SIGKILL the whole agent process tree mid-task; a retryable task
    lands on the surviving head node."""
    _, agent = two_hosts
    remote_id = _remote_node_id()

    @ray_tpu.remote(max_retries=2,
                    scheduling_strategy=NodeAffinitySchedulingStrategy(
                        node_id=remote_id, soft=True))
    def slow():
        time.sleep(3.0)
        return ray_tpu.get_runtime_context().node_id

    ref = slow.remote()
    time.sleep(1.0)  # let it dispatch to the remote node
    os.kill(agent.pid, signal.SIGKILL)
    nid = ray_tpu.get(ref, timeout=90)
    # retried on the surviving head node (soft affinity falls through)
    assert nid != remote_id
    alive = [n for n in ray_tpu.nodes() if n["Alive"]]
    assert len(alive) == 1


def test_agent_death_lineage_reconstruction(two_hosts):
    """An object living only on the dead host is reconstructed from lineage."""
    _, agent = two_hosts
    remote_id = _remote_node_id()

    @ray_tpu.remote(max_retries=2,
                    scheduling_strategy=NodeAffinitySchedulingStrategy(
                        node_id=remote_id, soft=True))
    def produce(seed):
        return np.full(150_000, float(seed))

    ref = produce.remote(9)
    # wait for completion WITHOUT fetching (no local replica)
    ready, _ = ray_tpu.wait([ref], num_returns=1, timeout=60)
    assert ready
    os.kill(agent.pid, signal.SIGKILL)
    arr = ray_tpu.get(ref, timeout=90)  # reconstructed via lineage on the head
    assert float(arr[0]) == 9.0


def test_trainer_spans_both_hosts(two_hosts, tmp_path):
    """JaxTrainer worker group spans head + agent in one jax.distributed universe
    (the VERDICT round-2 'done' bar for the control plane)."""
    from ray_tpu.air import RunConfig, ScalingConfig
    from ray_tpu.train import JaxConfig, JaxTrainer

    def loop(config):
        import jax

        import ray_tpu
        import ray_tpu.train as train

        train.report({
            "node": ray_tpu.get_runtime_context().node_id,
            "nprocs": jax.process_count(),
        })

    trainer = JaxTrainer(
        loop,
        backend_config=JaxConfig(distributed=True, platform="cpu",
                                 collective_group=False),
        scaling_config=ScalingConfig(num_workers=2, cpus_per_worker=2.0,
                                     placement_strategy="STRICT_SPREAD"),
        run_config=RunConfig(name="t_mh", storage_path=str(tmp_path)),
    )
    result = trainer.fit()
    assert result.error is None, result.error
    assert result.metrics["nprocs"] == 2
    assert len(result.all_metrics) == 2
    all_nodes = {m["node"] for m in result.all_metrics}
    assert len(all_nodes) == 2  # STRICT_SPREAD really put one worker per host


def test_trainer_survives_agent_death(two_hosts, tmp_path):
    """Chaos: kill the agent mid-training; FailureConfig restarts the group from
    the checkpoint on the surviving host."""
    import json

    from ray_tpu.air import CheckpointConfig, FailureConfig, RunConfig, ScalingConfig
    from ray_tpu.train import Checkpoint, JaxConfig, JaxTrainer

    _, agent = two_hosts

    def loop(config):
        import tempfile

        import ray_tpu.train as train

        ctx = train.get_context()
        ckpt = train.get_checkpoint()
        start = 0
        if ckpt is not None:
            with ckpt.as_directory() as d:
                start = json.load(open(os.path.join(d, "state.json")))["step"] + 1
        for step in range(start, 6):
            if step == 3 and ckpt is None:
                time.sleep(8.0)  # window for the chaos kill
            checkpoint = None
            if ctx.get_world_rank() == 0:
                d = tempfile.mkdtemp(prefix="mh_ckpt_")
                json.dump({"step": step}, open(os.path.join(d, "state.json"), "w"))
                checkpoint = Checkpoint.from_directory(d)
            train.report({"step": step}, checkpoint=checkpoint)

    def chaos():
        time.sleep(4.0)
        os.kill(agent.pid, signal.SIGKILL)

    import threading

    killer = threading.Thread(target=chaos, daemon=True)
    killer.start()
    trainer = JaxTrainer(
        loop,
        backend_config=JaxConfig(collective_group=False),
        scaling_config=ScalingConfig(num_workers=2, cpus_per_worker=1.0,
                                     placement_strategy="SPREAD"),
        run_config=RunConfig(
            name="t_mh_chaos",
            storage_path=str(tmp_path),
            checkpoint_config=CheckpointConfig(num_to_keep=2),
            failure_config=FailureConfig(max_failures=2),
        ),
    )
    result = trainer.fit()
    killer.join(timeout=1)
    assert result.error is None, result.error
    assert result.metrics["step"] == 5


def test_serve_deployment_scheduler_spreads_replicas(two_hosts):
    """Reference _private/deployment_scheduler.py: replica->node packing.
    SPREAD places replicas across both hosts; PACK keeps them together."""
    from ray_tpu import serve
    from ray_tpu.serve.controller import CONTROLLER_NAME

    @serve.deployment(num_replicas=4, placement_strategy="SPREAD",
                      ray_actor_options={"num_cpus": 0.5})
    class D:
        def __call__(self, body):
            return {"ok": True}

    try:
        serve.run(D.bind(), name="spreaded", route_prefix="/spreaded")
        h = serve.get_app_handle("spreaded")
        assert h.remote({}).result()["ok"]
        controller = ray_tpu.get_actor(CONTROLLER_NAME)

        def assignments(app, dep):
            def read(inst):
                ds = inst.deployments[f"{app}/{dep}"]
                return [r.node_id for r in ds.replicas]

            return ray_tpu.get(controller.__ray_call__.remote(read))

        nodes = assignments("spreaded", "D")
        assert len(nodes) == 4
        assert len({n for n in nodes if n}) == 2, f"not spread: {nodes}"
    finally:
        serve.shutdown()


def test_autoscaler_scales_real_node_agents(two_hosts):
    """Autoscaler + NodeAgentProvider: pending demand spawns a REAL node-agent
    process; idle timeout terminates it (reference autoscaler v2 over the
    fake_multi_node provider — but with actual capacity)."""
    from ray_tpu.autoscaler import Autoscaler, NodeAgentProvider, NodeType
    from ray_tpu.autoscaler.autoscaler import AutoscalingConfig

    cluster, _ = two_hosts
    provider = NodeAgentProvider(
        [NodeType(name="cpu-agent", resources={"CPU": 2.0}, max_nodes=2)],
        address=f"127.0.0.1:{cluster.node_server_port}")
    scaler = Autoscaler(provider, AutoscalingConfig(idle_timeout_s=3.0))
    try:
        # saturate the existing 2 hosts (4 CPUs) and queue more work
        @ray_tpu.remote(num_cpus=2)
        def hold(sec):
            time.sleep(sec)
            return ray_tpu.get_runtime_context().node_id

        refs = [hold.remote(8.0) for _ in range(3)]  # 6 CPU demand > 4 available
        time.sleep(0.5)
        deadline = time.time() + 60
        while len([n for n in ray_tpu.nodes() if n["Alive"]]) < 3:
            scaler.step()
            provider.poll()
            assert time.time() < deadline, "autoscaler never added an agent node"
            time.sleep(0.5)
        nodes = {ray_tpu.get(r, timeout=120) for r in refs}
        # scale-up is the guarantee; WHERE the queued task lands races agent
        # startup against task completion on a loaded machine
        assert len(nodes) >= 2
        # drain -> idle timeout terminates the scaled node
        deadline = time.time() + 60
        while provider.non_terminated_nodes():
            scaler.step()
            provider.poll()
            assert time.time() < deadline, "idle agent never terminated"
            time.sleep(0.5)
    finally:
        provider.shutdown()


@pytest.fixture()
def three_hosts(rt):
    """Head + two node agents: exercises true agent<->agent transfers."""
    ray_tpu.shutdown()
    ray_tpu.init(num_cpus=2, node_server_port=0,
                 worker_env={"JAX_PLATFORMS": "cpu"})
    cluster = global_state.try_cluster()
    agents = [_spawn_agent(cluster.node_server_port) for _ in range(2)]
    try:
        _wait_nodes(3)
        yield cluster, agents
    finally:
        for agent in agents:
            if agent.poll() is None:
                agent.terminate()
                try:
                    agent.wait(timeout=10)
                except subprocess.TimeoutExpired:
                    agent.kill()
        ray_tpu.shutdown()
        ray_tpu.init(num_cpus=4, worker_env={"JAX_PLATFORMS": "cpu"},
                     max_workers_per_node=8)


def _head_rss_mb():
    with open("/proc/self/status") as f:
        for line in f:
            if line.startswith("VmRSS:"):
                return int(line.split()[1]) / 1024.0
    return 0.0


def test_direct_agent_to_agent_transfer_head_rss_flat(three_hosts):
    """A large object moves agent->agent over the DATA plane: the head brokers
    metadata only, so its RSS must stay flat while ~120 MB crosses hosts
    (reference object_manager.h:119 — bytes never transit the GCS)."""
    cluster, _ = three_hosts
    remote_ids = [n["NodeID"] for n in ray_tpu.nodes()
                  if n["Alive"] and n["Labels"].get("agent") == "remote"]
    assert len(remote_ids) == 2
    src_id, dst_id = remote_ids
    # both agents advertised a data server
    for nid in remote_ids:
        assert cluster._agents_by_key[nid].data_addr is not None

    @ray_tpu.remote(scheduling_strategy=_on_node(src_id))
    def produce():
        return np.ones(15_000_000, dtype=np.float64)  # 120 MB

    @ray_tpu.remote(scheduling_strategy=_on_node(dst_id))
    def consume(x):
        return float(x[0]), float(x.sum()), ray_tpu.get_runtime_context().node_id

    ref = produce.remote()
    ready, _ = ray_tpu.wait([ref], num_returns=1, timeout=120)
    assert ready
    rss_before = _head_rss_mb()
    first, total, nid = ray_tpu.get(consume.remote(ref), timeout=180)
    rss_after = _head_rss_mb()
    assert nid == dst_id and first == 1.0 and total == 15_000_000.0
    # relay would have pulled all 120 MB through this process; direct pull
    # leaves head RSS flat (generous slack for allocator noise)
    assert rss_after - rss_before < 60.0, (
        f"head RSS grew {rss_after - rss_before:.0f} MB — bytes transited the head")


def test_broadcast_direct_pulls(three_hosts):
    """One head-resident object consumed on every agent: each destination pulls
    straight from the head's data server, chunked."""
    _, _ = three_hosts
    remote_ids = [n["NodeID"] for n in ray_tpu.nodes()
                  if n["Alive"] and n["Labels"].get("agent") == "remote"]
    payload = np.full(2_000_000, 3.0)  # 16 MB
    ref = ray_tpu.put(payload)

    @ray_tpu.remote
    def consume(x):
        return float(x.sum()), ray_tpu.get_runtime_context().node_id

    refs = [consume.options(scheduling_strategy=_on_node(nid)).remote(ref)
            for nid in remote_ids]
    out = ray_tpu.get(refs, timeout=120)
    assert {nid for _, nid in out} == set(remote_ids)
    assert all(s == 6_000_000.0 for s, _ in out)


def test_trainer_chaos_restart_with_remote_storage(two_hosts):
    """VERDICT r2 #3 'done' bar: the chaos-restart path must not depend on a
    shared local disk. Checkpoints go to mock:// storage (upload on report,
    download on restore through train/storage.py); the agent is killed
    mid-training and the group restarts from the URI checkpoint."""
    import json
    import threading
    import uuid as _uuid

    from ray_tpu.air import CheckpointConfig, FailureConfig, RunConfig, ScalingConfig
    from ray_tpu.train import Checkpoint, JaxConfig, JaxTrainer

    _, agent = two_hosts

    def loop(config):
        import tempfile

        import ray_tpu.train as train

        ctx = train.get_context()
        ckpt = train.get_checkpoint()
        start = 0
        if ckpt is not None:
            assert ckpt.is_remote  # restore must stream DOWN from storage
            with ckpt.as_directory() as d:
                start = json.load(open(os.path.join(d, "state.json")))["step"] + 1
        for step in range(start, 6):
            if step == 3 and ckpt is None:
                time.sleep(8.0)  # window for the chaos kill
            checkpoint = None
            if ctx.get_world_rank() == 0:
                d = tempfile.mkdtemp(prefix="mh_rs_ckpt_")
                json.dump({"step": step}, open(os.path.join(d, "state.json"), "w"))
                checkpoint = Checkpoint.from_directory(d)
            train.report({"step": step}, checkpoint=checkpoint)

    def chaos():
        time.sleep(4.0)
        os.kill(agent.pid, signal.SIGKILL)

    killer = threading.Thread(target=chaos, daemon=True)
    killer.start()
    trainer = JaxTrainer(
        loop,
        backend_config=JaxConfig(collective_group=False),
        scaling_config=ScalingConfig(num_workers=2, cpus_per_worker=1.0,
                                     placement_strategy="SPREAD"),
        run_config=RunConfig(
            name=f"t_mh_rs_{_uuid.uuid4().hex[:8]}",
            storage_path="mock://chaos",
            checkpoint_config=CheckpointConfig(num_to_keep=2),
            failure_config=FailureConfig(max_failures=2),
        ),
    )
    result = trainer.fit()
    killer.join(timeout=1)
    assert result.error is None, result.error
    assert result.metrics["step"] == 5
    assert result.checkpoint is not None and result.checkpoint.path.startswith("mock://")


def test_remote_worker_logs_stream_to_driver(two_hosts, capsys):
    """Worker log plane (reference log_monitor.py:105): a remote worker's
    print() is captured by its agent, streamed to the head, re-printed on the
    driver with a (worker, host) prefix, and exposed via the state API."""
    from ray_tpu.util import state as rs

    remote_id = _remote_node_id()
    marker = f"hello-from-remote-{int(time.time())}"

    @ray_tpu.remote(scheduling_strategy=_on_node(remote_id))
    def chatty(m):
        import sys as _sys

        print(m)
        print(m + "-err", file=_sys.stderr)
        return ray_tpu.get_runtime_context().node_id

    assert ray_tpu.get(chatty.remote(marker), timeout=60) == remote_id
    deadline = time.time() + 30
    found = None
    while found is None:
        assert time.time() < deadline, "remote worker print never reached the head"
        for entry in rs.list_logs():
            lines = rs.get_log(entry["worker_id"])
            if any(marker in ln for ln in lines):
                found = (entry, lines)
                break
        time.sleep(0.3)
    entry, lines = found
    assert entry["node_id"] == remote_id
    assert any(ln.startswith("out: ") and marker in ln for ln in lines)
    assert any(ln.startswith("err: ") and marker + "-err" in ln for ln in lines)
    # ... and the driver console shows the prefixed re-print
    captured = capsys.readouterr()
    assert any(marker in ln and f"node={remote_id[:8]}" in ln
               for ln in captured.out.splitlines())


def test_node_label_scheduling(rt):
    """NodeLabelSchedulingStrategy (reference scheduling_strategies.py:135):
    hard label terms filter nodes, soft terms rank; an unmatched hard term
    leaves the task pending until a matching node joins."""
    from ray_tpu.util.scheduling_strategies import (
        DoesNotExist, In, NodeLabelSchedulingStrategy)

    ray_tpu.shutdown()
    ray_tpu.init(num_cpus=2, node_server_port=0,
                 worker_env={"JAX_PLATFORMS": "cpu"})
    cluster = global_state.try_cluster()
    agent = subprocess.Popen(
        [sys.executable, "-m", "ray_tpu.core.node_agent",
         "--address", f"127.0.0.1:{cluster.node_server_port}",
         "--num-cpus", "2", "--label", "zone=eu", "--label", "tier=batch"],
        env={**os.environ, "JAX_PLATFORMS": "cpu"})
    try:
        _wait_nodes(2)
        labeled_id = _remote_node_id()

        @ray_tpu.remote(num_cpus=0.1)
        def where():
            return ray_tpu.get_runtime_context().node_id

        on_eu = where.options(scheduling_strategy=NodeLabelSchedulingStrategy(
            hard={"zone": In("eu", "eu-west")}))
        assert ray_tpu.get(on_eu.remote(), timeout=60) == labeled_id
        off_eu = where.options(scheduling_strategy=NodeLabelSchedulingStrategy(
            hard={"zone": DoesNotExist()}))
        assert ray_tpu.get(off_eu.remote(), timeout=60) != labeled_id
        # soft preference ranks the labeled node first but never blocks
        soft = where.options(scheduling_strategy=NodeLabelSchedulingStrategy(
            soft={"tier": In("batch")}))
        assert ray_tpu.get(soft.remote(), timeout=60) == labeled_id
        # unmatched hard term -> pending until a matching node joins
        mars = where.options(scheduling_strategy=NodeLabelSchedulingStrategy(
            hard={"zone": In("mars")}))
        ref = mars.remote()
        ready, _ = ray_tpu.wait([ref], num_returns=1, timeout=2.0)
        assert not ready  # still pending, not failed
        agent2 = subprocess.Popen(
            [sys.executable, "-m", "ray_tpu.core.node_agent",
             "--address", f"127.0.0.1:{cluster.node_server_port}",
             "--num-cpus", "2", "--label", "zone=mars"],
            env={**os.environ, "JAX_PLATFORMS": "cpu"})
        try:
            assert ray_tpu.get(ref, timeout=60)  # lands once the node exists
        finally:
            agent2.terminate()
            agent2.wait(timeout=10)
    finally:
        agent.terminate()
        try:
            agent.wait(timeout=10)
        except subprocess.TimeoutExpired:
            agent.kill()
        ray_tpu.shutdown()
        ray_tpu.init(num_cpus=4, worker_env={"JAX_PLATFORMS": "cpu"},
                     max_workers_per_node=8)
