"""ray_tpu.data tests (reference test strategy: python/ray/data/tests/)."""
import os

import numpy as np
import pytest

from ray_tpu import data as rd


@pytest.fixture(autouse=True)
def _cluster(rt):
    yield


def test_range_count_take(rt):
    ds = rd.range(100, parallelism=4)
    assert ds.count() == 100
    rows = ds.take(5)
    assert rows == [{"id": i} for i in range(5)]
    assert ds.num_blocks() == 4


def test_from_items_and_schema(rt):
    ds = rd.from_items([{"a": 1, "b": "x"}, {"a": 2, "b": "y"}], parallelism=1)
    assert ds.count() == 2
    assert set(ds.columns()) == {"a", "b"}
    assert ds.take_all()[1]["b"] == "y"


def test_map_batches_numpy(rt):
    ds = rd.range(32, parallelism=2).map_batches(lambda b: {"id": b["id"] * 2})
    out = ds.take_all()
    assert [r["id"] for r in out] == [i * 2 for i in range(32)]


def test_map_batches_fusion(rt):
    ds = (
        rd.range(16, parallelism=2)
        .map_batches(lambda b: {"id": b["id"] + 1})
        .map_batches(lambda b: {"id": b["id"] * 10})
    )
    ds.materialize()
    # two map stages fused into one physical stage
    names = [op.name for op in ds._stats.ops]
    assert any("->" in n for n in names), names
    assert [r["id"] for r in ds.take(3)] == [10, 20, 30]


def test_map_filter_flat_map(rt):
    ds = rd.range(10, parallelism=2).map(lambda r: {"v": r["id"] + 1})
    assert ds.sum("v") == sum(range(1, 11))
    ds2 = rd.range(10, parallelism=2).filter(lambda r: r["id"] % 2 == 0)
    assert ds2.count() == 5
    ds3 = rd.from_items([{"x": 1}], parallelism=1).flat_map(lambda r: [{"x": r["x"]}, {"x": r["x"] + 1}])
    assert [r["x"] for r in ds3.take_all()] == [1, 2]


def test_actor_pool_map(rt):
    class AddConst:
        def __init__(self, c):
            self.c = c

        def __call__(self, batch):
            return {"id": batch["id"] + self.c}

    ds = rd.range(20, parallelism=4).map_batches(
        AddConst, fn_constructor_args=(100,), concurrency=2
    )
    assert sorted(r["id"] for r in ds.take_all()) == [i + 100 for i in range(20)]


def test_sort_and_shuffle(rt):
    ds = rd.from_items([{"k": v} for v in [5, 3, 8, 1, 9, 2, 7, 0, 4, 6]], parallelism=3)
    assert [r["k"] for r in ds.sort("k").take_all()] == list(range(10))
    assert [r["k"] for r in ds.sort("k", descending=True).take_all()] == list(range(9, -1, -1))
    shuffled = ds.random_shuffle(seed=42)
    assert sorted(r["k"] for r in shuffled.take_all()) == list(range(10))


def test_groupby_aggregate(rt):
    ds = rd.from_items([{"g": i % 3, "v": i} for i in range(12)], parallelism=3)
    out = {r["g"]: r["sum(v)"] for r in ds.groupby("g").sum("v").take_all()}
    assert out == {0: 0 + 3 + 6 + 9, 1: 1 + 4 + 7 + 10, 2: 2 + 5 + 8 + 11}
    assert ds.mean("v") == pytest.approx(5.5)
    assert ds.max("v") == 11


def test_limit_union_zip(rt):
    ds = rd.range(100, parallelism=4).limit(7)
    assert ds.count() == 7
    u = rd.range(3, parallelism=1).union(rd.range(3, parallelism=1))
    assert u.count() == 6
    z = rd.range(4, parallelism=2).zip(rd.range(4, parallelism=2).map_batches(lambda b: {"y": b["id"] * 3}))
    rows = z.take_all()
    assert all(r["y"] == r["id"] * 3 for r in rows)


def test_split_and_iteration(rt):
    ds = rd.range(30, parallelism=6)
    shards = ds.split(3)
    assert sum(s.count() for s in shards) == 30
    batches = list(ds.iter_batches(batch_size=8, batch_format="numpy"))
    assert sum(len(b["id"]) for b in batches) == 30
    assert all(isinstance(b["id"], np.ndarray) for b in batches)
    # batch boundary spanning blocks
    assert len(batches[0]["id"]) == 8


def test_tensor_columns_roundtrip(rt):
    arr = np.arange(24, dtype=np.float32).reshape(6, 2, 2)
    ds = rd.from_numpy({"x": arr}, parallelism=2)
    out = np.concatenate([b["x"] for b in ds.iter_batches(batch_size=3)])
    np.testing.assert_array_equal(out, arr)


def test_parquet_roundtrip(rt, tmp_path):
    ds = rd.range(50, parallelism=2).map_batches(lambda b: {"id": b["id"], "sq": b["id"] ** 2})
    paths = ds.write_parquet(str(tmp_path / "out"))
    assert len(paths) == 2
    back = rd.read_parquet(str(tmp_path / "out"))
    assert back.count() == 50
    assert back.sum("sq") == sum(i * i for i in range(50))


def test_csv_json_roundtrip(rt, tmp_path):
    ds = rd.from_items([{"a": i, "s": f"row{i}"} for i in range(10)], parallelism=1)
    ds.write_csv(str(tmp_path / "csv"))
    assert rd.read_csv(str(tmp_path / "csv")).count() == 10
    ds.write_json(str(tmp_path / "json"))
    back = rd.read_json(str(tmp_path / "json"))
    assert back.sort("a").take(2) == [{"a": 0, "s": "row0"}, {"a": 1, "s": "row1"}]


def test_iter_jax_batches(rt):
    ds = rd.range(16, parallelism=2)
    batches = list(ds.iter_jax_batches(batch_size=8))
    assert len(batches) == 2
    import jax

    assert isinstance(batches[0]["id"], jax.Array)


def test_random_sample_and_train_test_split(rt):
    ds = rd.range(100, parallelism=4)
    train, test = ds.train_test_split(0.2)
    assert train.count() == 80 and test.count() == 20
    s = ds.random_sample(0.5, seed=0)
    assert 20 < s.count() < 80


def test_sort_few_distinct_values_empty_partitions(rt):
    """Sorting few rows across many single-row blocks creates all-empty merge
    partitions; they must keep their schema (regression: ArrowInvalid)."""
    import ray_tpu.data as rtd

    ds = rtd.from_items([{"x": v} for v in [5, 3, 9, 1]]).sort("x")
    assert [r["x"] for r in ds.take_all()] == [1, 3, 5, 9]


def test_read_images(rt, tmp_path):
    """reference read_images: image files -> tensor-column rows."""
    from PIL import Image

    import ray_tpu.data as rtd

    for i in range(3):
        arr = np.full((8 + i, 6, 3), i * 40, np.uint8)
        Image.fromarray(arr).save(tmp_path / f"img_{i}.png")
    ds = rtd.read_images(str(tmp_path), size=(4, 4))
    rows = ds.take_all()
    assert len(rows) == 3
    for r in sorted(rows, key=lambda r: r["path"]):
        assert np.asarray(r["image"]).shape == (4, 4, 3)
    # without resize, original sizes survive through the tensor column
    sizes = {r["height"] for r in rtd.read_images(str(tmp_path)).take_all()}
    assert sizes == {8, 9, 10}
