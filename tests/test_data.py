"""ray_tpu.data tests (reference test strategy: python/ray/data/tests/)."""
import os

import numpy as np
import pytest

from ray_tpu import data as rd


@pytest.fixture(autouse=True)
def _cluster(rt):
    yield


def test_range_count_take(rt):
    ds = rd.range(100, parallelism=4)
    assert ds.count() == 100
    rows = ds.take(5)
    assert rows == [{"id": i} for i in range(5)]
    assert ds.num_blocks() == 4


def test_from_items_and_schema(rt):
    ds = rd.from_items([{"a": 1, "b": "x"}, {"a": 2, "b": "y"}], parallelism=1)
    assert ds.count() == 2
    assert set(ds.columns()) == {"a", "b"}
    assert ds.take_all()[1]["b"] == "y"


def test_map_batches_numpy(rt):
    ds = rd.range(32, parallelism=2).map_batches(lambda b: {"id": b["id"] * 2})
    out = ds.take_all()
    assert [r["id"] for r in out] == [i * 2 for i in range(32)]


def test_map_batches_fusion(rt):
    ds = (
        rd.range(16, parallelism=2)
        .map_batches(lambda b: {"id": b["id"] + 1})
        .map_batches(lambda b: {"id": b["id"] * 10})
    )
    ds.materialize()
    # two map stages fused into one physical stage
    names = [op.name for op in ds._stats.ops]
    assert any("->" in n for n in names), names
    assert [r["id"] for r in ds.take(3)] == [10, 20, 30]


def test_map_filter_flat_map(rt):
    ds = rd.range(10, parallelism=2).map(lambda r: {"v": r["id"] + 1})
    assert ds.sum("v") == sum(range(1, 11))
    ds2 = rd.range(10, parallelism=2).filter(lambda r: r["id"] % 2 == 0)
    assert ds2.count() == 5
    ds3 = rd.from_items([{"x": 1}], parallelism=1).flat_map(lambda r: [{"x": r["x"]}, {"x": r["x"] + 1}])
    assert [r["x"] for r in ds3.take_all()] == [1, 2]


def test_actor_pool_map(rt):
    class AddConst:
        def __init__(self, c):
            self.c = c

        def __call__(self, batch):
            return {"id": batch["id"] + self.c}

    ds = rd.range(20, parallelism=4).map_batches(
        AddConst, fn_constructor_args=(100,), concurrency=2
    )
    assert sorted(r["id"] for r in ds.take_all()) == [i + 100 for i in range(20)]


def test_sort_and_shuffle(rt):
    ds = rd.from_items([{"k": v} for v in [5, 3, 8, 1, 9, 2, 7, 0, 4, 6]], parallelism=3)
    assert [r["k"] for r in ds.sort("k").take_all()] == list(range(10))
    assert [r["k"] for r in ds.sort("k", descending=True).take_all()] == list(range(9, -1, -1))
    shuffled = ds.random_shuffle(seed=42)
    assert sorted(r["k"] for r in shuffled.take_all()) == list(range(10))


def test_push_based_shuffle_matches_pull(rt):
    """DataContext.use_push_based_shuffle (reference push_based_shuffle_task_
    scheduler.py): staged map rounds + eager per-partition merges must produce
    the SAME sort/shuffle/groupby results as the pull-based exchange, with a
    merge factor small enough that multiple rounds actually run."""
    from ray_tpu.data.context import DataContext

    ctx = DataContext.get_current()
    vals = [(i * 37) % 50 for i in range(50)]
    ds = rd.from_items([{"k": v} for v in vals], parallelism=10)
    want_sorted = sorted(vals)
    want_groups = {g: sum(v for v in vals if v % 3 == g) for g in range(3)}

    saved = (ctx.use_push_based_shuffle, ctx.push_shuffle_merge_factor)
    ctx.use_push_based_shuffle = True
    ctx.push_shuffle_merge_factor = 3  # 10 inputs -> 4 merge rounds
    try:
        assert [r["k"] for r in ds.sort("k").take_all()] == want_sorted
        shuffled = ds.random_shuffle(seed=7)
        assert sorted(r["k"] for r in shuffled.take_all()) == want_sorted
        gds = rd.from_items([{"g": v % 3, "v": v} for v in vals], parallelism=10)
        out = {r["g"]: r["sum(v)"] for r in gds.groupby("g").sum("v").take_all()}
        assert out == want_groups
        # few distinct keys -> repeated boundaries -> all-empty partitions:
        # the merge stage must keep block schemas for the downstream sort
        few = rd.from_items([{"k": v % 2} for v in range(24)], parallelism=8)
        assert [r["k"] for r in few.sort("k").take_all()] == sorted(
            v % 2 for v in range(24))
    finally:
        ctx.use_push_based_shuffle, ctx.push_shuffle_merge_factor = saved


def test_groupby_aggregate(rt):
    ds = rd.from_items([{"g": i % 3, "v": i} for i in range(12)], parallelism=3)
    out = {r["g"]: r["sum(v)"] for r in ds.groupby("g").sum("v").take_all()}
    assert out == {0: 0 + 3 + 6 + 9, 1: 1 + 4 + 7 + 10, 2: 2 + 5 + 8 + 11}
    assert ds.mean("v") == pytest.approx(5.5)
    assert ds.max("v") == 11


def test_limit_union_zip(rt):
    ds = rd.range(100, parallelism=4).limit(7)
    assert ds.count() == 7
    u = rd.range(3, parallelism=1).union(rd.range(3, parallelism=1))
    assert u.count() == 6
    z = rd.range(4, parallelism=2).zip(rd.range(4, parallelism=2).map_batches(lambda b: {"y": b["id"] * 3}))
    rows = z.take_all()
    assert all(r["y"] == r["id"] * 3 for r in rows)


def test_split_and_iteration(rt):
    ds = rd.range(30, parallelism=6)
    shards = ds.split(3)
    assert sum(s.count() for s in shards) == 30
    batches = list(ds.iter_batches(batch_size=8, batch_format="numpy"))
    assert sum(len(b["id"]) for b in batches) == 30
    assert all(isinstance(b["id"], np.ndarray) for b in batches)
    # batch boundary spanning blocks
    assert len(batches[0]["id"]) == 8


def test_tensor_columns_roundtrip(rt):
    arr = np.arange(24, dtype=np.float32).reshape(6, 2, 2)
    ds = rd.from_numpy({"x": arr}, parallelism=2)
    out = np.concatenate([b["x"] for b in ds.iter_batches(batch_size=3)])
    np.testing.assert_array_equal(out, arr)


def test_parquet_roundtrip(rt, tmp_path):
    ds = rd.range(50, parallelism=2).map_batches(lambda b: {"id": b["id"], "sq": b["id"] ** 2})
    paths = ds.write_parquet(str(tmp_path / "out"))
    assert len(paths) == 2
    back = rd.read_parquet(str(tmp_path / "out"))
    assert back.count() == 50
    assert back.sum("sq") == sum(i * i for i in range(50))


def test_csv_json_roundtrip(rt, tmp_path):
    ds = rd.from_items([{"a": i, "s": f"row{i}"} for i in range(10)], parallelism=1)
    ds.write_csv(str(tmp_path / "csv"))
    assert rd.read_csv(str(tmp_path / "csv")).count() == 10
    ds.write_json(str(tmp_path / "json"))
    back = rd.read_json(str(tmp_path / "json"))
    assert back.sort("a").take(2) == [{"a": 0, "s": "row0"}, {"a": 1, "s": "row1"}]


def test_iter_jax_batches(rt):
    ds = rd.range(16, parallelism=2)
    batches = list(ds.iter_jax_batches(batch_size=8))
    assert len(batches) == 2
    import jax

    assert isinstance(batches[0]["id"], jax.Array)


def test_random_sample_and_train_test_split(rt):
    ds = rd.range(100, parallelism=4)
    train, test = ds.train_test_split(0.2)
    assert train.count() == 80 and test.count() == 20
    s = ds.random_sample(0.5, seed=0)
    assert 20 < s.count() < 80


def test_sort_few_distinct_values_empty_partitions(rt):
    """Sorting few rows across many single-row blocks creates all-empty merge
    partitions; they must keep their schema (regression: ArrowInvalid)."""
    import ray_tpu.data as rtd

    ds = rtd.from_items([{"x": v} for v in [5, 3, 9, 1]]).sort("x")
    assert [r["x"] for r in ds.take_all()] == [1, 3, 5, 9]


def test_read_images(rt, tmp_path):
    """reference read_images: image files -> tensor-column rows."""
    from PIL import Image

    import ray_tpu.data as rtd

    for i in range(3):
        arr = np.full((8 + i, 6, 3), i * 40, np.uint8)
        Image.fromarray(arr).save(tmp_path / f"img_{i}.png")
    ds = rtd.read_images(str(tmp_path), size=(4, 4))
    rows = ds.take_all()
    assert len(rows) == 3
    for r in sorted(rows, key=lambda r: r["path"]):
        assert np.asarray(r["image"]).shape == (4, 4, 3)
    # without resize, original sizes survive through the tensor column
    sizes = {r["height"] for r in rtd.read_images(str(tmp_path)).take_all()}
    assert sizes == {8, 9, 10}


def test_webdataset_roundtrip(rt, tmp_path):
    """write_webdataset -> read_webdataset round-trip: key-grouped tar members
    decode by extension (reference webdataset_datasource.py)."""
    import numpy as np

    import ray_tpu.data as data

    rows = [{"__key__": f"s{i:03d}", "cls": i, "txt": f"caption {i}",
             "npy": np.arange(4, dtype=np.float32) + i}
            for i in range(6)]
    paths = data.from_items(rows).write_webdataset(str(tmp_path / "shards"))
    assert all(p.endswith(".tar") for p in paths)

    back = data.read_webdataset([str(tmp_path / "shards" / "*.tar")])
    got = sorted(back.take_all(), key=lambda r: r["__key__"])
    assert len(got) == 6
    assert got[2]["__key__"] == "s002" and got[2]["cls"] == 2
    assert got[3]["txt"] == "caption 3"
    np.testing.assert_allclose(got[1]["npy"], np.arange(4, dtype=np.float32) + 1)


def test_tfrecords_roundtrip(rt, tmp_path):
    """write_tfrecords -> read_tfrecords over tf.train.Example protos
    (reference tfrecords_datasource.py)."""
    import numpy as np
    import pytest as _pytest

    _pytest.importorskip("tensorflow")
    import ray_tpu.data as data

    rows = [{"id": i, "name": f"row-{i}", "score": float(i) / 2} for i in range(5)]
    paths = data.from_items(rows).write_tfrecords(str(tmp_path / "tfr"))
    assert all(p.endswith(".tfrecords") for p in paths)

    back = data.read_tfrecords([str(tmp_path / "tfr" / "*.tfrecords")])
    got = sorted(back.take_all(), key=lambda r: r["id"])
    assert [r["id"] for r in got] == list(range(5))
    assert got[3]["name"] == b"row-3"  # bytes_list features read back as bytes
    assert abs(got[4]["score"] - 2.0) < 1e-6


def test_tfrecords_multivalue_roundtrip(rt, tmp_path):
    """read -> transform -> write of MULTI-VALUE features: the reader returns
    them as lists, so the datasink must re-encode list values (ADVICE r3)."""
    import pytest as _pytest

    _pytest.importorskip("tensorflow")
    import ray_tpu.data as data

    rows = [{"id": i, "vec": [float(i), float(i) * 2, 0.5],
             "tags": [i, i + 1], "blobs": [b"a", b"bb"]} for i in range(4)]
    paths = data.from_items(rows).write_tfrecords(str(tmp_path / "tfr1"))
    back = data.read_tfrecords([str(tmp_path / "tfr1" / "*.tfrecords")])
    # round-trip AGAIN: the read form (lists) must be writable as-is
    paths2 = back.write_tfrecords(str(tmp_path / "tfr2"))
    got = sorted(data.read_tfrecords(
        [str(tmp_path / "tfr2" / "*.tfrecords")]).take_all(),
        key=lambda r: r["id"])
    assert [r["id"] for r in got] == list(range(4))
    assert list(got[2]["vec"]) == [2.0, 4.0, 0.5]
    assert list(got[3]["tags"]) == [3, 4]
    assert list(got[1]["blobs"]) == [b"a", b"bb"]


def test_lance_bigquery_gated(rt):
    """Optional-dep sources raise a clear install hint when the lib is absent."""
    import pytest as _pytest

    import ray_tpu.data as data

    try:
        import lance  # noqa: F401
    except ImportError:
        with _pytest.raises(ImportError, match="lance"):
            data.read_lance("/nonexistent.lance")
    try:
        from google.cloud import bigquery  # noqa: F401
    except ImportError:
        with _pytest.raises(ImportError, match="bigquery"):
            data.read_bigquery("proj", query="select 1")
    with _pytest.raises(ValueError, match="exactly one"):
        data.read_bigquery("proj")


def test_webdataset_ndarray_and_ragged(rt, tmp_path):
    """ndarray columns round-trip under their own name via the .npy extension
    chain; shards with ragged members (a column missing in some samples) read
    as object columns instead of crashing."""
    import io
    import tarfile

    import numpy as np

    import ray_tpu.data as data

    rows = [{"__key__": f"r{i}", "img": np.full((2, 3), i, np.float32)}
            for i in range(3)]
    data.from_items(rows).write_webdataset(str(tmp_path / "nd"))
    back = sorted(data.read_webdataset([str(tmp_path / "nd" / "*.tar")]).take_all(),
                  key=lambda r: r["__key__"])
    assert isinstance(back[1]["img"], np.ndarray)
    np.testing.assert_allclose(back[1]["img"], np.full((2, 3), 1, np.float32))

    # hand-built ragged shard: s1 lacks the npy member s0 has
    shard = tmp_path / "ragged.tar"
    with tarfile.open(shard, "w") as tf:
        for name, payload in (("s0.cls", b"0"), ("s1.cls", b"1")):
            info = tarfile.TarInfo(name)
            info.size = len(payload)
            tf.addfile(info, io.BytesIO(payload))
        buf = io.BytesIO()
        np.save(buf, np.arange(3))
        payload = buf.getvalue()
        info = tarfile.TarInfo("s0.npy")
        info.size = len(payload)
        tf.addfile(info, io.BytesIO(payload))
    got = sorted(data.read_webdataset(str(shard)).take_all(),
                 key=lambda r: r["__key__"])
    assert got[0]["cls"] == 0 and got[1]["cls"] == 1
    np.testing.assert_array_equal(got[0]["npy"], np.arange(3))
    assert got[1]["npy"] is None


def test_read_sql_roundtrip(rt, tmp_path):
    """read_sql over a real DBAPI-2 connection (reference read_sql /
    sql_datasource.py) — sqlite3 satisfies the protocol out of the box."""
    import sqlite3

    import ray_tpu.data as rtd

    db = str(tmp_path / "t.db")
    conn = sqlite3.connect(db)
    conn.execute("CREATE TABLE metrics (step INTEGER, loss REAL)")
    conn.executemany("INSERT INTO metrics VALUES (?, ?)",
                     [(i, 10.0 - i * 0.5) for i in range(20)])
    conn.commit()
    conn.close()

    ds = rtd.read_sql("SELECT step, loss FROM metrics WHERE step >= 5",
                      lambda: sqlite3.connect(db))
    rows = ds.take_all()
    assert len(rows) == 15
    assert rows[0]["step"] == 5 and abs(rows[0]["loss"] - 7.5) < 1e-9


def test_optional_datasources_raise_actionable_importerrors():
    """mongo/iceberg/delta-sharing follow the lance/bigquery gating pattern:
    missing optional deps raise with install hints at construction."""
    import ray_tpu.data as rtd

    for fn, kwargs, pkg in (
            (rtd.read_mongo, dict(uri="mongodb://x", database="d",
                                  collection="c"), "pymongo"),
            (rtd.read_iceberg, dict(table_identifier="db.t"), "pyiceberg"),
            (rtd.read_delta_sharing_tables, dict(url="profile#share.schema.t"),
             "delta"),
    ):
        try:
            __import__(pkg if pkg != "delta" else "delta_sharing")
            continue  # installed here: the gate is a no-op, read paths differ
        except ImportError:
            pass
        with pytest.raises(ImportError, match=pkg):
            fn(**kwargs)
