"""Tracing tests (reference python/ray/util/tracing; SURVEY.md §5 tracing row)."""
import time

import pytest

from ray_tpu.util import tracing


@pytest.fixture(autouse=True)
def _enable():
    import os

    tracing.enable_tracing()
    yield
    os.environ.pop("RAY_TPU_TRACING", None)
    tracing._enabled = False


def test_span_nesting_and_timing():
    with tracing.span("outer", {"k": "v"}) as outer:
        time.sleep(0.02)
        with tracing.span("inner") as inner:
            time.sleep(0.01)
    spans = tracing.drain_local_spans()
    by_name = {s["name"]: s for s in spans}
    assert by_name["inner"]["parent_span_id"] == by_name["outer"]["span_id"]
    assert by_name["inner"]["trace_id"] == by_name["outer"]["trace_id"]
    assert by_name["outer"]["end_time"] - by_name["outer"]["start_time"] >= 0.025
    assert by_name["outer"]["attributes"] == {"k": "v"}


def test_task_spans_propagate_trace(rt):
    from ray_tpu.util import state as rs

    @rt.remote
    def traced_work(x):
        from ray_tpu.util import tracing as wtracing

        with wtracing.span("user-span-in-task"):
            return x + 1

    with tracing.span("driver-root"):
        assert rt.get(traced_work.remote(1)) == 2

    deadline = time.time() + 10
    while time.time() < deadline:
        spans = rs.get_trace()
        names = {s["name"] for s in spans}
        if {"driver-root", "task::traced_work", "user-span-in-task"} <= names:
            break
        time.sleep(0.1)
    by_name = {s["name"]: s for s in spans}
    root = by_name["driver-root"]
    task_span = by_name["task::traced_work"]
    user = by_name["user-span-in-task"]
    # one trace across process boundaries, correctly parented
    assert task_span["trace_id"] == root["trace_id"]
    assert task_span["parent_span_id"] == root["span_id"]
    assert user["parent_span_id"] == task_span["span_id"]


def test_disabled_tracing_is_free(rt):
    import os

    os.environ.pop("RAY_TPU_TRACING", None)
    tracing._enabled = False
    with tracing.span("nope") as s:
        assert s is None
    assert tracing.drain_local_spans() == []
    assert tracing.get_trace_context() is None
