"""LLM engine + server tests (reference: python/ray/llm tests; SURVEY.md §2.7).

Engine correctness is checked against the model's full-sequence forward: greedy
continuous-batched decode must reproduce greedy full-recompute decode.
"""
import threading
import time

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from ray_tpu.llm import JaxLLMEngine, LLMConfig, SamplingParams
from ray_tpu.llm.engine import llama_init_cached
from ray_tpu.llm import sampling
from ray_tpu.models import llama
from ray_tpu.models.config import get_config

CFG = get_config("test-tiny")


_REF_CACHE = {}


def reference_greedy(params, prompt_ids, n_tokens):
    """Greedy decode by full recompute each step — the trusted slow path.
    Memoized: every call pays one full forward PER SEQUENCE LENGTH (a fresh
    XLA compile each), and the [slot]/[paged] parametrizations ask for the
    same continuations."""
    key = (id(params), tuple(prompt_ids), n_tokens)
    if key not in _REF_CACHE:
        ids = list(prompt_ids)
        for _ in range(n_tokens):
            logits, _ = llama.forward(params, jnp.asarray([ids]), CFG)
            ids.append(int(jnp.argmax(logits[0, -1])))
        _REF_CACHE[key] = ids[len(prompt_ids):]
    return _REF_CACHE[key]


@pytest.fixture(scope="module")
def engine():
    cfg = LLMConfig(
        model_id="tiny", model_source="test-tiny", max_num_seqs=4, max_model_len=64,
        tokenizer="byte",
    )
    eng = JaxLLMEngine(cfg)
    eng.start()
    yield eng
    eng.shutdown()


@pytest.fixture(scope="module")
def fused_engine():
    """ONE compiled fused paged engine shared by the continuous-batching
    tests — same geometry as test_multi_step_decode[paged] so the decode
    burst programs are reused, holding the tier-1 timing budget."""
    cfg = LLMConfig(model_id="tiny-fused", model_source="test-tiny",
                    max_num_seqs=4, max_model_len=64, tokenizer="byte",
                    kv_layout="paged", kv_block_size=16, num_decode_steps=4)
    eng = JaxLLMEngine(cfg)
    eng.start()
    yield eng
    eng.shutdown()


def test_greedy_matches_full_forward(engine):
    params = llama_init_cached(CFG)
    prompt = [1, 7, 42, 99, 5]
    want = reference_greedy(params, prompt, 8)
    out = engine.generate_sync(prompt, SamplingParams(max_tokens=8, temperature=0.0,
                                                     stop_token_ids=[-1]))
    assert out.token_ids == want
    assert out.num_prompt_tokens == len(prompt)
    assert out.num_generated_tokens == 8
    assert out.finish_reason == "length"


def test_continuous_batching_concurrent_requests(engine):
    """Concurrent requests through shared slots must each match their solo
    run on the same engine (solo-vs-reference is test_greedy's job; solo
    oracles here skip ~36 per-length reference forwards — tier-1 budget)."""
    prompts = [[1, 2, 3], [1, 9, 8, 7, 6, 5], [1, 50], [1, 3, 3, 3, 3, 3, 3, 3],
               [1, 100, 101], [1, 60, 61, 62]]  # 6 requests > 4 slots
    want = [engine.generate_sync(p, SamplingParams(
        max_tokens=6, temperature=0.0, stop_token_ids=[-1])).token_ids
        for p in prompts]
    got = [None] * len(prompts)

    def run(i):
        got[i] = engine.generate_sync(
            prompts[i], SamplingParams(max_tokens=6, temperature=0.0, stop_token_ids=[-1])
        ).token_ids

    threads = [threading.Thread(target=run, args=(i,)) for i in range(len(prompts))]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert got == want


def test_streaming_and_stop_tokens(engine):
    params = llama_init_cached(CFG)
    prompt = [1, 20, 30]
    ref = reference_greedy(params, prompt, 12)
    stop = ref[5]  # force an early stop at the 6th generated token
    chunks = list(engine.generate(prompt, SamplingParams(
        max_tokens=12, temperature=0.0, stop_token_ids=[stop])))
    ids = [t for c in chunks for t in c.token_ids]
    assert ids == ref[:5]
    assert chunks[-1].finished and chunks[-1].finish_reason == "stop"


def test_sampling_params_validation():
    with pytest.raises(ValueError):
        SamplingParams(max_tokens=0)
    with pytest.raises(ValueError):
        SamplingParams(top_p=0.0)


def test_sampler_top_k_top_p():
    logits = jnp.asarray([[1.0, 2.0, 3.0, 4.0], [4.0, 3.0, 2.0, 1.0]])
    rng = jax.random.PRNGKey(0)
    # top_k=1 == greedy regardless of temperature
    toks = sampling.sample(rng, logits, jnp.asarray([5.0, 5.0]),
                           jnp.asarray([1.0, 1.0]), jnp.asarray([1, 1]))
    assert list(np.asarray(toks)) == [3, 0]
    # top_p tiny -> nucleus is just the max token
    toks = sampling.sample(rng, logits, jnp.asarray([5.0, 5.0]),
                           jnp.asarray([1e-6, 1e-6]), jnp.asarray([0, 0]))
    assert list(np.asarray(toks)) == [3, 0]
    # temperature 0 -> greedy
    toks = sampling.sample(rng, logits, jnp.asarray([0.0, 0.0]),
                           jnp.asarray([1.0, 1.0]), jnp.asarray([0, 0]))
    assert list(np.asarray(toks)) == [3, 0]


def test_llm_server_openai_shapes():
    from ray_tpu.llm.server import LLMServer

    cfg = LLMConfig(model_id="tiny-srv", model_source="byte-tiny", max_num_seqs=2,
                    max_model_len=64)
    srv = LLMServer(cfg)
    try:
        resp = srv.chat({"messages": [{"role": "user", "content": "hi"}],
                         "max_tokens": 4, "temperature": 0.0})
        assert resp["object"] == "chat.completion"
        assert resp["choices"][0]["message"]["role"] == "assistant"
        assert resp["usage"]["completion_tokens"] <= 4
        resp = srv.completions({"prompt": "abc", "max_tokens": 4})
        assert resp["object"] == "text_completion"
        assert isinstance(resp["choices"][0]["text"], str)
    finally:
        srv.shutdown()


def test_openai_app_over_serve(rt):
    from ray_tpu import serve
    from ray_tpu.llm import build_openai_app

    cfg = LLMConfig(model_id="m1", model_source="byte-tiny", max_num_seqs=2,
                    max_model_len=64)
    app = build_openai_app([cfg])
    serve.run(app, name="llm-app", route_prefix="/v1")
    try:
        h = serve.get_app_handle("llm-app")
        models = h.options(method_name="handle_http").remote(
            {"path": "/v1/models", "method": "GET", "body": None}).result()
        assert [m["id"] for m in models["data"]] == ["m1"]
        resp = h.options(method_name="chat").remote(
            {"messages": [{"role": "user", "content": "yo"}], "max_tokens": 3,
             "temperature": 0.0}).result()
        assert resp["object"] == "chat.completion"
    finally:
        serve.delete("llm-app")


def test_batch_processor(rt):
    import ray_tpu.data as rdata
    from ray_tpu.llm import build_llm_processor

    cfg = LLMConfig(model_id="b1", model_source="byte-tiny", max_num_seqs=2,
                    max_model_len=64)
    proc = build_llm_processor(cfg, sampling_params={"max_tokens": 3, "temperature": 0.0},
                               batch_size=4)
    ds = rdata.from_items([{"prompt": f"item {i}"} for i in range(6)])
    rows = proc(ds).take_all()
    assert len(rows) == 6
    assert all("generated_text" in r and r["num_generated_tokens"] <= 3 for r in rows)


def test_abort_releases_slot(engine):
    """abort() mid-generation ends the request with finish_reason="abort" and
    frees its slot instead of decoding to max_tokens (reference: vllm
    abort_request). Runs on the shared module engine (tier-1 budget: a
    private 512-len engine compiled its own decode programs for nothing —
    the subject is abort, not capacity)."""
    rid = "abort-me"
    gen = engine.generate([1, 2, 3], SamplingParams(
        max_tokens=56, temperature=0.0, stop_token_ids=[-1]), request_id=rid)
    first = next(gen)
    assert not first.finished
    engine.abort(rid)
    outs = list(gen)
    assert outs[-1].finished
    assert outs[-1].finish_reason == "abort"
    deadline = time.time() + 10
    while engine.num_active:
        assert time.time() < deadline, "aborted request still holds a slot"
        time.sleep(0.05)


def test_sse_generator_close_aborts_engine_request():
    """Closing the SSE generator (client disconnect) must release the engine
    slot early via the abort path."""
    from ray_tpu.llm.server import LLMServer

    # max_model_len matches the other byte-tiny server tests so the decode
    # programs are shared (the subject is stream-close abort, not capacity)
    cfg = LLMConfig(model_id="tiny-abort2", model_source="byte-tiny",
                    max_num_seqs=2, max_model_len=64)
    srv = LLMServer(cfg)
    try:
        g = srv.chat({"messages": [{"role": "user", "content": "hi"}],
                      "stream": True, "max_tokens": 56, "temperature": 1.0})
        next(g)  # role frame
        next(g)  # first delta
        g.close()
        deadline = time.time() + 10
        while srv.engine.num_active:
            assert time.time() < deadline, "disconnected stream still holds a slot"
            time.sleep(0.05)
    finally:
        srv.shutdown()


@pytest.mark.parametrize("kv_layout", ["slot", "paged"])
def test_multi_step_decode_matches_single_step(kv_layout):
    """num_decode_steps>1 fuses K decode+sample iterations per host sync
    (vLLM multi-step scheduling); greedy output must be IDENTICAL to
    single-step decode, including EOS-mid-burst and max_tokens cut-offs."""
    params = llama_init_cached(CFG)
    prompt = [1, 7, 42, 99, 5]
    want = reference_greedy(params, prompt, 11)

    cfg = LLMConfig(model_id=f"tiny-ms-{kv_layout}", model_source="test-tiny",
                    max_num_seqs=4, max_model_len=64, tokenizer="byte",
                    kv_layout=kv_layout, num_decode_steps=4)
    eng = JaxLLMEngine(cfg)
    eng.start()
    try:
        # 11 tokens with K=4: two full bursts + a 3-step burst (max_tokens cap)
        out = eng.generate_sync(prompt, SamplingParams(
            max_tokens=11, temperature=0.0, stop_token_ids=[-1]))
        assert out.token_ids == want
        assert out.num_generated_tokens == 11
        assert out.finish_reason == "length"

        # mid-burst stop: cut the budget so EOS-style stop lands inside a burst
        stop_tok = want[5]
        out2 = eng.generate_sync(prompt, SamplingParams(
            max_tokens=11, temperature=0.0, stop_token_ids=[stop_tok]))
        assert out2.token_ids == want[:5]
        assert out2.finish_reason == "stop"

        # concurrent requests with different lengths share bursts correctly
        prompts = [[1, 2, 3], [1, 9, 8, 7, 6, 5], [1, 50]]
        wants = [reference_greedy(params, p, 6) for p in prompts]
        outs = [None] * len(prompts)

        def run(i):
            outs[i] = eng.generate_sync(prompts[i], SamplingParams(
                max_tokens=6, temperature=0.0, stop_token_ids=[-1]))

        ts = [threading.Thread(target=run, args=(i,)) for i in range(len(prompts))]
        for t in ts:
            t.start()
        for t in ts:
            t.join()
        for got, want_i in zip(outs, wants):
            assert got.token_ids == want_i
    finally:
        eng.shutdown()


def test_engine_metrics_export_to_prometheus(engine):
    """engine.metrics() mirrors its counters into the cluster metric registry
    (reference: vllm stat loggers -> Ray metrics -> dashboard)."""
    engine.generate_sync([1, 2, 3], SamplingParams(max_tokens=2, temperature=0.0,
                                                   stop_token_ids=[-1]))
    snap = engine.metrics()
    assert snap["total_generated"] >= 2
    from ray_tpu.util import metrics as m

    merged = m.merge_snapshots([m._registry.snapshot()])
    assert "llm_total_generated" in merged
    assert merged["llm_num_active"]["type"] == "gauge"


@pytest.mark.parametrize("kv_layout", ["slot", "paged"])
def test_ngram_speculative_decode_matches_greedy(kv_layout):
    """Speculative decoding (reference: vLLM ngram spec decode): drafts are
    verified in one forward pass and greedy output must be IDENTICAL to plain
    decode whatever the draft quality — for both cache layouts (the paged
    verify writes the window through pre-grown block tables). An untrained
    model generates novel tokens, so prompt-lookup rarely fires on its own —
    the acceptance path is driven with oracle (and deliberately wrong) drafts
    via the proposer seam."""
    params = llama_init_cached(CFG)
    prompt = [1, 10, 11, 12, 13]
    want = reference_greedy(params, prompt, 12)

    cfg = LLMConfig(model_id=f"tiny-spec-{kv_layout}", model_source="test-tiny",
                    max_num_seqs=2, max_model_len=64, tokenizer="byte",
                    kv_layout=kv_layout, num_speculative_tokens=4)
    eng = JaxLLMEngine(cfg)
    eng.start()
    try:
        # 1. real ngram proposer end-to-end (drafts mostly miss; output exact)
        out = eng.generate_sync(prompt, SamplingParams(
            max_tokens=12, temperature=0.0, stop_token_ids=[-1]))
        assert out.token_ids == want
        assert out.num_generated_tokens == 12

        # 2. oracle speculator: always drafts the true continuation -> every
        # draft accepted, output still exact, finishes in ~3 verify steps
        oracle = {tuple(prompt + want[:i]): want[i:i + 4]
                  for i in range(len(want))}

        def oracle_propose(req, cap):
            return list(oracle.get(tuple(req.token_history), []))[:cap]

        eng._propose_ngram = oracle_propose
        out2 = eng.generate_sync(prompt, SamplingParams(
            max_tokens=12, temperature=0.0, stop_token_ids=[-1]))
        assert out2.token_ids == want
        m = eng.metrics()
        assert m["num_spec_accepted"] >= 8, m  # bulk of tokens via acceptance

        # 3. adversarial speculator: all drafts wrong -> all rejected, output
        # STILL exact (rejection rolls the window back correctly)
        eng._propose_ngram = lambda req, cap: [7] * cap
        accepted_before = eng.metrics()["num_spec_accepted"]
        out3 = eng.generate_sync(prompt, SamplingParams(
            max_tokens=12, temperature=0.0, stop_token_ids=[-1]))
        assert out3.token_ids == want
        # wrong drafts may collide with the true token by chance; near-zero
        assert eng.metrics()["num_spec_accepted"] - accepted_before <= 2

        # 4. sampled (temperature>0) requests ride along un-speculated AND
        # actually sample: at temperature 5 an untrained model is near-uniform
        # over 256 byte tokens, so matching the greedy continuation would be
        # astronomically unlikely (regression: spec path silently going argmax)
        eng._propose_ngram = lambda req, cap: []
        out4 = eng.generate_sync(prompt, SamplingParams(
            max_tokens=6, temperature=5.0, stop_token_ids=[-1]))
        assert out4.num_generated_tokens == 6
        assert out4.token_ids != want[:6]
    finally:
        eng.shutdown()


def test_ngram_proposer_lookup():
    """Prompt-lookup proposes the continuation of the most recent earlier
    occurrence of the trailing n-gram (longest n first)."""
    from ray_tpu.llm.engine import JaxLLMEngine, _Request

    eng = JaxLLMEngine(LLMConfig(model_id="pl", model_source="test-tiny",
                                 num_speculative_tokens=4))
    req = _Request("r", [1, 10, 11, 12, 13, 10, 11, 12, 13, 10, 11, 12, 13],
                   SamplingParams(max_tokens=4))
    assert eng._propose_ngram(req, 4) == [10, 11, 12, 13]
    req2 = _Request("r2", [1, 2, 3, 4, 5], SamplingParams(max_tokens=4))
    assert eng._propose_ngram(req2, 4) == []


def test_speculative_config_validation():
    from ray_tpu.llm import JaxLLMEngine, LLMConfig

    # only ngram (prompt-lookup) proposers exist; a draft-model config refuses
    eng = JaxLLMEngine(LLMConfig(model_id="sv2", model_source="test-tiny",
                                 num_speculative_tokens=4,
                                 speculative_method="draft_model"))
    with pytest.raises(NotImplementedError, match="ngram"):
        eng.start()
    # spec composes with paged, fused multi-step, and pp on BOTH layouts now —
    # no composition fence remains in the serving matrix


def test_device_ngram_proposer_matches_host():
    """The on-device prompt-lookup (fused-spec path) proposes the same drafts
    as the host proposer on the same history."""
    import jax.numpy as jnp
    import numpy as np

    from ray_tpu.llm import model_runner

    ctx = [1, 10, 11, 12, 13, 10, 11, 12, 13, 10, 11, 12]
    L = 32
    hist = np.zeros((2, L), np.int32)
    hist[0, :len(ctx)] = ctx
    hist[1, :5] = [1, 2, 3, 4, 5]  # no repeated n-gram -> no drafts
    hlen = np.asarray([len(ctx), 5], np.int32)
    last = np.asarray([ctx[-1], 5], np.int32)
    window, dlen = model_runner.propose_ngram_device(
        jnp.asarray(hist), jnp.asarray(hlen), jnp.asarray(last), k=4, nmax=3)
    window, dlen = np.asarray(window), np.asarray(dlen)
    assert window[0, 0] == ctx[-1]
    # trailing [10,11,12] last occurred at position 9; continuation is 13,10,11,12
    assert list(window[0, 1:1 + dlen[0]]) == [13, 10, 11, 12]
    assert dlen[1] == 0


@pytest.mark.parametrize("kv_layout", ["slot", "paged"])
def test_spec_fused_multi_step_matches_greedy(kv_layout):
    """spec + fused multi-step (the composed mode), on BOTH cache layouts
    (paged: spec_multi_paged writes windows through pre-grown block tables):
    output is EXACTLY the plain greedy continuation. An untrained model emits
    novel tokens, so the real n-gram proposer rarely fires (same caveat as the
    host-path test) — exact equivalence across misses IS the correctness
    property here; acceptance inside fused bursts is driven by the oracle test
    below."""
    params = llama_init_cached(CFG)
    prompt = [1, 10, 11, 12, 13, 10, 11, 12, 13, 10, 11, 12, 13]
    want = reference_greedy(params, prompt, 12)

    from ray_tpu.llm import SpecConfig

    # constructed through the first-class SpecConfig mode (resolves into the
    # scalar engine knobs), composing with fused bursts
    eng = JaxLLMEngine(LLMConfig(
        model_id=f"spec-fused-{kv_layout}", model_source="test-tiny",
        max_num_seqs=2, max_model_len=64, tokenizer="byte", kv_layout=kv_layout,
        speculative=SpecConfig(num_tokens=4), num_decode_steps=4))
    eng.start()
    try:
        out = eng.generate_sync(prompt, SamplingParams(
            max_tokens=12, temperature=0.0, stop_token_ids=[-1]))
        assert out.token_ids == want
        assert out.num_generated_tokens == 12

        # sampled requests ride along per-window (regression: silent argmax)
        out2 = eng.generate_sync(prompt, SamplingParams(
            max_tokens=6, temperature=5.0, stop_token_ids=[-1]))
        assert out2.num_generated_tokens == 6
        assert out2.token_ids != want[:6]
    finally:
        eng.shutdown()


def test_spec_fused_oracle_accepts_inside_burst():
    """Oracle proposer through spec_multi's seam: every draft is the true
    continuation, so fused windows must ACCEPT (k+1 tokens per window) and the
    output must still be exactly greedy."""
    import jax
    import jax.numpy as jnp
    import numpy as np

    from jax.sharding import Mesh
    from ray_tpu.llm import model_runner

    params_tree = llama_init_cached(CFG)
    prompt = [1, 10, 11, 12, 13]
    n_gen = 12
    want = reference_greedy(params_tree, prompt, n_gen)

    mesh = Mesh(np.asarray(jax.devices()[:1]).reshape(1, 1, 1), ("dp", "ep", "tp"))
    params = model_runner.shard_params(params_tree, CFG, mesh)
    S, L = 2, 64
    state = model_runner.init_state(CFG, slots=S, max_len=L, mesh=mesh)
    toks = jnp.asarray([prompt], jnp.int32)
    state, last_logits = model_runner.prefill(
        params, state, toks, jnp.asarray(len(prompt)), jnp.asarray(0), CFG)
    first = int(np.argmax(np.asarray(last_logits)))
    assert first == want[0]

    # oracle: full true continuation laid out per slot; drafts = the next k
    # tokens after the current history length
    oracle = np.zeros((S, L), np.int32)
    oracle[0, len(prompt):len(prompt) + n_gen] = want

    def oracle_propose(h, hl, last, k, nmax):
        table = jnp.asarray(oracle)
        starts = jnp.clip(hl, 0, L - k)
        drafts = jax.vmap(
            lambda row, s: jax.lax.dynamic_slice(row, (s,), (k,)))(table, starts)
        dlen = jnp.where(jnp.arange(S) == 0, k, 0).astype(jnp.int32)
        window = jnp.zeros((S, k + 1), jnp.int32).at[:, 0].set(last)
        window = window.at[:, 1:].set(drafts)
        return window, dlen

    hist = np.zeros((S, L), np.int32)
    hist[0, :len(prompt) + 1] = prompt + [first]
    hlen = np.asarray([len(prompt) + 1, 0], np.int32)
    active = jnp.asarray([True, False])
    m, k = 2, 4
    rngs = jax.random.split(jax.random.PRNGKey(0), m)
    zeros = jnp.zeros((S,), jnp.float32)
    state, toks_m, acc_m, drafted_m = model_runner.spec_multi(
        params, state, jnp.asarray(hist), jnp.asarray(hlen), active, CFG,
        rngs, zeros, jnp.ones((S,), jnp.float32), jnp.zeros((S,), jnp.int32),
        m, k, 3, propose_fn=oracle_propose)
    acc_m, toks_m = np.asarray(acc_m), np.asarray(toks_m)
    # every window accepted all k drafts -> k+1 tokens per window
    assert list(acc_m[:, 0]) == [k, k]
    emitted = [int(toks_m[s, 0, t]) for s in range(m) for t in range(k + 1)]
    assert emitted == want[1:1 + m * (k + 1)]


@pytest.mark.parametrize("parallel", [
    dict(pipeline_parallel_size=2),
    dict(pipeline_parallel_size=2, data_parallel_size=2),
    dict(pipeline_parallel_size=2, kv_layout="paged", kv_block_size=16),
    dict(pipeline_parallel_size=2, data_parallel_size=2, kv_layout="paged",
         kv_block_size=16),
])
def test_spec_decode_through_pipeline_matches_greedy(parallel):
    """Speculative verify rides the pp schedule on BOTH cache layouts: the
    verify window is the microbatch payload; greedy output is IDENTICAL to
    plain decode with oracle drafts (all accepted) and adversarial drafts
    (all rejected), with or without dp replicas. Paged bubbles write the
    scratch block; slot bubbles are discarded by the valid mask."""
    params = llama_init_cached(CFG)
    prompt = [1, 10, 11, 12, 13]
    want = reference_greedy(params, prompt, 12)

    eng = JaxLLMEngine(LLMConfig(
        model_id=f"spec-pp-{hash(tuple(sorted(parallel))) & 0xffff}",
        model_source="test-tiny",
        max_num_seqs=4, max_model_len=64, tokenizer="byte",
        num_speculative_tokens=4, **parallel), params=params)
    eng.start()
    try:
        out = eng.generate_sync(prompt, SamplingParams(
            max_tokens=12, temperature=0.0, stop_token_ids=[-1]))
        assert out.token_ids == want

        oracle = {tuple(prompt + want[:i]): want[i:i + 4]
                  for i in range(len(want))}
        eng._propose_ngram = lambda req, cap: list(
            oracle.get(tuple(req.token_history), []))[:cap]
        out2 = eng.generate_sync(prompt, SamplingParams(
            max_tokens=12, temperature=0.0, stop_token_ids=[-1]))
        assert out2.token_ids == want
        assert eng.metrics()["num_spec_accepted"] >= 8

        eng._propose_ngram = lambda req, cap: [7] * cap
        out3 = eng.generate_sync(prompt, SamplingParams(
            max_tokens=12, temperature=0.0, stop_token_ids=[-1]))
        assert out3.token_ids == want
    finally:
        eng.shutdown()


# -- continuous batching on the fused default path (barrier-free scheduling) --


def test_burst_plan_per_slot_budgets():
    """The fused burst width is capped by the LONGEST-running slot; a request
    one step from its max_tokens rides along with its own on-device budget
    instead of collapsing the whole batch to K=1 (the old min-over-slots
    barrier)."""
    from ray_tpu.llm.engine import _Request

    eng = JaxLLMEngine(LLMConfig(model_id="bp", model_source="test-tiny",
                                 max_num_seqs=4, max_model_len=64))
    eng._fused_auto, eng._fused_fixed, eng._fused_max = False, 4, 4
    r_long = _Request("a", [1, 2, 3], SamplingParams(max_tokens=30))
    r_long.generated, r_long.slot = 2, 0
    r_short = _Request("b", [1, 2], SamplingParams(max_tokens=5))
    r_short.generated, r_short.slot = 4, 1
    eng._active = {0: r_long, 1: r_short, 2: None, 3: None}
    k, steps = eng._burst_plan()
    assert k == 4, "short request must not cap the batch's burst width"
    assert steps[0] == 4 and steps[1] == 1
    # kv_room caps too: a slot one write from max_model_len gets 1 step
    r_edge = _Request("c", [1] * 60, SamplingParams(max_tokens=30))
    r_edge.generated, r_edge.slot = 2, 2
    eng._active[2] = r_edge
    k, steps = eng._burst_plan()
    assert k == 4 and steps[2] == (64 - 1) - (60 + 2 - 1)


def test_continuous_batching_admit_during_decode(fused_engine):
    """A request arriving while others are mid-generation admits at the next
    burst boundary: its completion is not gated on the longest active
    request, and both token streams stay exact."""
    # oracles from the module engines (themselves reference_greedy-validated
    # above): a fresh reference_greedy sweep pays one full forward per
    # sequence length — the single biggest tier-1 cost in this file
    long_prompt, short_prompt = [1, 5, 6], [1, 9, 4]
    sp24 = SamplingParams(max_tokens=24, temperature=0.0, stop_token_ids=[-1])
    want_long = fused_engine.generate_sync(long_prompt, sp24).token_ids
    want_short = fused_engine.generate_sync(short_prompt, SamplingParams(
        max_tokens=4, temperature=0.0, stop_token_ids=[-1])).token_ids
    rid = "cb-long"
    gen = fused_engine.generate(long_prompt, SamplingParams(
        max_tokens=24, temperature=0.0, stop_token_ids=[-1]), request_id=rid)
    first = next(gen)
    assert not first.finished
    out = fused_engine.generate_sync(short_prompt, SamplingParams(
        max_tokens=4, temperature=0.0, stop_token_ids=[-1]))
    assert out.token_ids == want_short
    # the long request is still mid-flight when the late arrival finished
    long_req = fused_engine._requests.get(rid)
    assert long_req is not None and long_req.generated < 24, \
        "short request's completion was gated on the long one draining"
    ids = list(first.token_ids)
    for chunk in gen:
        ids.extend(chunk.token_ids)
    assert ids == want_long


def test_continuous_batching_finish_and_refill(fused_engine):
    """More requests than slots with MIXED budgets: slots refill as their
    occupants finish (no global drain), every stream exactly matching its
    solo run on the same engine (which test_multi_step checks against
    reference_greedy — solo oracles here keep the tier-1 budget)."""
    prompts = [[1, 2, 3], [1, 9, 8, 7], [1, 50], [1, 3, 3, 3],
               [1, 100, 101], [1, 60, 61, 62]]  # 6 requests > 4 slots
    budgets = [3, 9, 5, 12, 4, 7]
    want = [fused_engine.generate_sync(p, SamplingParams(
        max_tokens=b, temperature=0.0, stop_token_ids=[-1])).token_ids
        for p, b in zip(prompts, budgets)]
    got = [None] * len(prompts)

    def run(i):
        got[i] = fused_engine.generate_sync(prompts[i], SamplingParams(
            max_tokens=budgets[i], temperature=0.0, stop_token_ids=[-1])
        ).token_ids

    threads = [threading.Thread(target=run, args=(i,)) for i in range(len(prompts))]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert got == want
    assert fused_engine.num_active == 0 and fused_engine.num_pending == 0


def test_abort_mid_burst_frees_blocks(fused_engine):
    """abort() while a fused burst is in flight: the stream ends with
    finish_reason="abort" at the next burst boundary (the burst tail is
    discarded, never emitted) and the paged blocks free immediately."""
    blocks = fused_engine._blocks
    free0 = blocks.num_free
    rid = "abort-burst"
    gen = fused_engine.generate([2, 40, 41, 42], SamplingParams(
        max_tokens=56, temperature=0.0, stop_token_ids=[-1]), request_id=rid)
    first = next(gen)
    assert not first.finished
    fused_engine.abort(rid)
    outs = list(gen)
    assert outs[-1].finished and outs[-1].finish_reason == "abort"
    deadline = time.time() + 10
    while fused_engine.num_active or blocks.num_free < free0:
        assert time.time() < deadline, "aborted request still holds blocks"
        time.sleep(0.02)


def test_preemption_inside_fused_burst(fused_engine):
    """Pool exhaustion while reserving a fused burst's block headroom:
    the youngest request is preempted (recompute), the survivors keep
    decoding in full-width bursts, and everyone completes exactly. The
    oracle streams come from the shared ample-pool engine (itself checked
    against reference_greedy above) — recompute preemption must reproduce
    them bit-for-bit."""
    prompts = [[1, 10, 11], [1, 20, 21], [1, 30, 31]]
    sp = SamplingParams(max_tokens=16, temperature=0.0, stop_token_ids=[-1])
    want = [fused_engine.generate_sync(p, sp).token_ids for p in prompts]
    cfg = LLMConfig(model_id="tiny-preempt-burst", model_source="test-tiny",
                    max_num_seqs=2, max_model_len=64, tokenizer="byte",
                    kv_layout="paged", kv_block_size=8, num_kv_blocks=4,
                    num_decode_steps=4, enable_prefix_caching=False)
    eng = JaxLLMEngine(cfg)
    eng.start()
    try:
        # 16 generated tokens write KV positions up to 17: three 8-token
        # blocks per slot, and prefill's 16-padded install already takes two
        # — two slots need 6 > the 4-block pool, so a burst's block headroom
        # must preempt
        got = [None] * len(prompts)

        def run(i):
            got[i] = eng.generate_sync(prompts[i], sp).token_ids

        threads = [threading.Thread(target=run, args=(i,)) for i in range(3)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        assert got == want
        assert eng.num_preemptions >= 1, \
            "pool was sized to force preemption inside a burst"
    finally:
        eng.shutdown()


def test_pp_fused_downgrade_logs_once(caplog):
    """pp>1 with fused decode (the default) auto-downgrades to per-step
    scheduling with ONE structured log line — not a UserWarning about an
    inert user knob."""
    import logging
    import warnings

    eng = JaxLLMEngine(LLMConfig(
        model_id="pp-downgrade", model_source="test-tiny", max_num_seqs=4,
        max_model_len=64, tokenizer="byte", pipeline_parallel_size=2,
        num_decode_steps=4), params=llama_init_cached(CFG))
    try:
        with caplog.at_level(logging.INFO, logger="ray_tpu.llm.engine"):
            with warnings.catch_warnings():
                warnings.simplefilter("error")  # any warning fails the test
                eng.start()
        msgs = [r for r in caplog.records if "downgraded" in r.getMessage()]
        assert len(msgs) == 1
        assert eng.decode_steps_target() == 1
    finally:
        eng.shutdown()


def test_spec_config_first_class():
    """SpecConfig on LLMConfig is the first-class speculation mode: it
    resolves into the scalar engine knobs (dict form included, for JSON
    deployment configs) and validates its fields."""
    from ray_tpu.llm import SpecConfig

    cfg = LLMConfig(model_id="sc", model_source="test-tiny",
                    speculative=SpecConfig(num_tokens=3, ngram_max=2))
    assert cfg.num_speculative_tokens == 3
    assert cfg.ngram_prompt_lookup_max == 2
    cfg2 = LLMConfig(model_id="sc2", model_source="test-tiny",
                     speculative={"num_tokens": 5})
    assert cfg2.num_speculative_tokens == 5
    assert isinstance(cfg2.speculative, SpecConfig)
    with pytest.raises(ValueError):
        SpecConfig(num_tokens=0)


def test_prefix_cache_pay_or_skip(fused_engine):
    """The warm prefill path skips the prefix cache entirely when the
    predicted saving (hit tokens x measured per-token prefill time) is below
    the measured dispatch round trip, and uses it when it pays."""
    eng = fused_engine
    p = [3] + [7, 8, 9, 10] * 7  # 29 tokens: one full cacheable block
    sp = SamplingParams(max_tokens=2, temperature=0.0, stop_token_ids=[-1])
    rt0, pt0 = eng._host_rt_s, eng._prefill_per_tok_s
    try:
        # never pays: a 10s dispatch round trip dwarfs any prefill saving —
        # even the matching/registration hashing is skipped
        eng._host_rt_s, eng._prefill_per_tok_s = 10.0, 1e-6
        skipped0 = eng.num_prefix_skipped
        eng.generate_sync(p, sp)
        assert eng.num_prefix_skipped > skipped0
        # always pays: free dispatch -> the cache is used again
        eng._host_rt_s = 1e-9
        eng.generate_sync(p, sp)  # cold: nothing was registered while skipped
        hits0 = eng._blocks.hit_tokens
        eng.generate_sync(p, sp)  # warm: real hit through the fused gather
        assert eng._blocks.hit_tokens > hits0
    finally:
        eng._host_rt_s, eng._prefill_per_tok_s = rt0, pt0
