"""LLM engine + server tests (reference: python/ray/llm tests; SURVEY.md §2.7).

Engine correctness is checked against the model's full-sequence forward: greedy
continuous-batched decode must reproduce greedy full-recompute decode.
"""
import threading
import time

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from ray_tpu.llm import JaxLLMEngine, LLMConfig, SamplingParams
from ray_tpu.llm.engine import llama_init_cached
from ray_tpu.llm import sampling
from ray_tpu.models import llama
from ray_tpu.models.config import get_config

CFG = get_config("test-tiny")


def reference_greedy(params, prompt_ids, n_tokens):
    """Greedy decode by full recompute each step — the trusted slow path."""
    ids = list(prompt_ids)
    for _ in range(n_tokens):
        logits, _ = llama.forward(params, jnp.asarray([ids]), CFG)
        ids.append(int(jnp.argmax(logits[0, -1])))
    return ids[len(prompt_ids):]


@pytest.fixture(scope="module")
def engine():
    cfg = LLMConfig(
        model_id="tiny", model_source="test-tiny", max_num_seqs=4, max_model_len=64,
        tokenizer="byte",
    )
    eng = JaxLLMEngine(cfg)
    eng.start()
    yield eng
    eng.shutdown()


def test_greedy_matches_full_forward(engine):
    params = llama_init_cached(CFG)
    prompt = [1, 7, 42, 99, 5]
    want = reference_greedy(params, prompt, 8)
    out = engine.generate_sync(prompt, SamplingParams(max_tokens=8, temperature=0.0,
                                                     stop_token_ids=[-1]))
    assert out.token_ids == want
    assert out.num_prompt_tokens == len(prompt)
    assert out.num_generated_tokens == 8
    assert out.finish_reason == "length"


def test_continuous_batching_concurrent_requests(engine):
    """Concurrent requests through shared slots must each match the sequential result."""
    params = llama_init_cached(CFG)
    prompts = [[1, 2, 3], [1, 9, 8, 7, 6, 5], [1, 50], [1, 3, 3, 3, 3, 3, 3, 3],
               [1, 100, 101], [1, 60, 61, 62]]  # 6 requests > 4 slots
    want = [reference_greedy(params, p, 6) for p in prompts]
    got = [None] * len(prompts)

    def run(i):
        got[i] = engine.generate_sync(
            prompts[i], SamplingParams(max_tokens=6, temperature=0.0, stop_token_ids=[-1])
        ).token_ids

    threads = [threading.Thread(target=run, args=(i,)) for i in range(len(prompts))]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert got == want


def test_streaming_and_stop_tokens(engine):
    params = llama_init_cached(CFG)
    prompt = [1, 20, 30]
    ref = reference_greedy(params, prompt, 12)
    stop = ref[5]  # force an early stop at the 6th generated token
    chunks = list(engine.generate(prompt, SamplingParams(
        max_tokens=12, temperature=0.0, stop_token_ids=[stop])))
    ids = [t for c in chunks for t in c.token_ids]
    assert ids == ref[:5]
    assert chunks[-1].finished and chunks[-1].finish_reason == "stop"


def test_sampling_params_validation():
    with pytest.raises(ValueError):
        SamplingParams(max_tokens=0)
    with pytest.raises(ValueError):
        SamplingParams(top_p=0.0)


def test_sampler_top_k_top_p():
    logits = jnp.asarray([[1.0, 2.0, 3.0, 4.0], [4.0, 3.0, 2.0, 1.0]])
    rng = jax.random.PRNGKey(0)
    # top_k=1 == greedy regardless of temperature
    toks = sampling.sample(rng, logits, jnp.asarray([5.0, 5.0]),
                           jnp.asarray([1.0, 1.0]), jnp.asarray([1, 1]))
    assert list(np.asarray(toks)) == [3, 0]
    # top_p tiny -> nucleus is just the max token
    toks = sampling.sample(rng, logits, jnp.asarray([5.0, 5.0]),
                           jnp.asarray([1e-6, 1e-6]), jnp.asarray([0, 0]))
    assert list(np.asarray(toks)) == [3, 0]
    # temperature 0 -> greedy
    toks = sampling.sample(rng, logits, jnp.asarray([0.0, 0.0]),
                           jnp.asarray([1.0, 1.0]), jnp.asarray([0, 0]))
    assert list(np.asarray(toks)) == [3, 0]


def test_llm_server_openai_shapes():
    from ray_tpu.llm.server import LLMServer

    cfg = LLMConfig(model_id="tiny-srv", model_source="byte-tiny", max_num_seqs=2,
                    max_model_len=64)
    srv = LLMServer(cfg)
    try:
        resp = srv.chat({"messages": [{"role": "user", "content": "hi"}],
                         "max_tokens": 4, "temperature": 0.0})
        assert resp["object"] == "chat.completion"
        assert resp["choices"][0]["message"]["role"] == "assistant"
        assert resp["usage"]["completion_tokens"] <= 4
        resp = srv.completions({"prompt": "abc", "max_tokens": 4})
        assert resp["object"] == "text_completion"
        assert isinstance(resp["choices"][0]["text"], str)
    finally:
        srv.shutdown()


def test_openai_app_over_serve(rt):
    from ray_tpu import serve
    from ray_tpu.llm import build_openai_app

    cfg = LLMConfig(model_id="m1", model_source="byte-tiny", max_num_seqs=2,
                    max_model_len=64)
    app = build_openai_app([cfg])
    serve.run(app, name="llm-app", route_prefix="/v1")
    try:
        h = serve.get_app_handle("llm-app")
        models = h.options(method_name="handle_http").remote(
            {"path": "/v1/models", "method": "GET", "body": None}).result()
        assert [m["id"] for m in models["data"]] == ["m1"]
        resp = h.options(method_name="chat").remote(
            {"messages": [{"role": "user", "content": "yo"}], "max_tokens": 3,
             "temperature": 0.0}).result()
        assert resp["object"] == "chat.completion"
    finally:
        serve.delete("llm-app")


def test_batch_processor(rt):
    import ray_tpu.data as rdata
    from ray_tpu.llm import build_llm_processor

    cfg = LLMConfig(model_id="b1", model_source="byte-tiny", max_num_seqs=2,
                    max_model_len=64)
    proc = build_llm_processor(cfg, sampling_params={"max_tokens": 3, "temperature": 0.0},
                               batch_size=4)
    ds = rdata.from_items([{"prompt": f"item {i}"} for i in range(6)])
    rows = proc(ds).take_all()
    assert len(rows) == 6
    assert all("generated_text" in r and r["num_generated_tokens"] <= 3 for r in rows)


def test_abort_releases_slot():
    """abort() mid-generation ends the request with finish_reason="abort" and
    frees its slot instead of decoding to max_tokens (reference: vllm
    abort_request)."""
    cfg = LLMConfig(model_id="tiny-abort", model_source="test-tiny",
                    max_num_seqs=2, max_model_len=512, tokenizer="byte")
    eng = JaxLLMEngine(cfg)
    eng.start()
    try:
        rid = "abort-me"
        gen = eng.generate([1, 2, 3], SamplingParams(
            max_tokens=400, temperature=0.0, stop_token_ids=[-1]), request_id=rid)
        first = next(gen)
        assert not first.finished
        eng.abort(rid)
        outs = list(gen)
        assert outs[-1].finished
        assert outs[-1].finish_reason == "abort"
        deadline = time.time() + 10
        while eng.num_active:
            assert time.time() < deadline, "aborted request still holds a slot"
            time.sleep(0.05)
    finally:
        eng.shutdown()


def test_sse_generator_close_aborts_engine_request():
    """Closing the SSE generator (client disconnect) must release the engine
    slot early via the abort path."""
    from ray_tpu.llm.server import LLMServer

    cfg = LLMConfig(model_id="tiny-abort2", model_source="byte-tiny",
                    max_num_seqs=2, max_model_len=512)
    srv = LLMServer(cfg)
    try:
        g = srv.chat({"messages": [{"role": "user", "content": "hi"}],
                      "stream": True, "max_tokens": 400, "temperature": 1.0})
        next(g)  # role frame
        next(g)  # first delta
        g.close()
        deadline = time.time() + 10
        while srv.engine.num_active:
            assert time.time() < deadline, "disconnected stream still holds a slot"
            time.sleep(0.05)
    finally:
        srv.shutdown()


@pytest.mark.parametrize("kv_layout", ["slot", "paged"])
def test_multi_step_decode_matches_single_step(kv_layout):
    """num_decode_steps>1 fuses K decode+sample iterations per host sync
    (vLLM multi-step scheduling); greedy output must be IDENTICAL to
    single-step decode, including EOS-mid-burst and max_tokens cut-offs."""
    params = llama_init_cached(CFG)
    prompt = [1, 7, 42, 99, 5]
    want = reference_greedy(params, prompt, 11)

    cfg = LLMConfig(model_id=f"tiny-ms-{kv_layout}", model_source="test-tiny",
                    max_num_seqs=4, max_model_len=64, tokenizer="byte",
                    kv_layout=kv_layout, num_decode_steps=4)
    eng = JaxLLMEngine(cfg)
    eng.start()
    try:
        # 11 tokens with K=4: two full bursts + a 3-step burst (max_tokens cap)
        out = eng.generate_sync(prompt, SamplingParams(
            max_tokens=11, temperature=0.0, stop_token_ids=[-1]))
        assert out.token_ids == want
        assert out.num_generated_tokens == 11
        assert out.finish_reason == "length"

        # mid-burst stop: cut the budget so EOS-style stop lands inside a burst
        stop_tok = want[5]
        out2 = eng.generate_sync(prompt, SamplingParams(
            max_tokens=11, temperature=0.0, stop_token_ids=[stop_tok]))
        assert out2.token_ids == want[:5]
        assert out2.finish_reason == "stop"

        # concurrent requests with different lengths share bursts correctly
        prompts = [[1, 2, 3], [1, 9, 8, 7, 6, 5], [1, 50]]
        wants = [reference_greedy(params, p, 6) for p in prompts]
        outs = [None] * len(prompts)

        def run(i):
            outs[i] = eng.generate_sync(prompts[i], SamplingParams(
                max_tokens=6, temperature=0.0, stop_token_ids=[-1]))

        ts = [threading.Thread(target=run, args=(i,)) for i in range(len(prompts))]
        for t in ts:
            t.start()
        for t in ts:
            t.join()
        for got, want_i in zip(outs, wants):
            assert got.token_ids == want_i
    finally:
        eng.shutdown()


def test_engine_metrics_export_to_prometheus(engine):
    """engine.metrics() mirrors its counters into the cluster metric registry
    (reference: vllm stat loggers -> Ray metrics -> dashboard)."""
    engine.generate_sync([1, 2, 3], SamplingParams(max_tokens=2, temperature=0.0,
                                                   stop_token_ids=[-1]))
    snap = engine.metrics()
    assert snap["total_generated"] >= 2
    from ray_tpu.util import metrics as m

    merged = m.merge_snapshots([m._registry.snapshot()])
    assert "llm_total_generated" in merged
    assert merged["llm_num_active"]["type"] == "gauge"


@pytest.mark.parametrize("kv_layout", ["slot", "paged"])
def test_ngram_speculative_decode_matches_greedy(kv_layout):
    """Speculative decoding (reference: vLLM ngram spec decode): drafts are
    verified in one forward pass and greedy output must be IDENTICAL to plain
    decode whatever the draft quality — for both cache layouts (the paged
    verify writes the window through pre-grown block tables). An untrained
    model generates novel tokens, so prompt-lookup rarely fires on its own —
    the acceptance path is driven with oracle (and deliberately wrong) drafts
    via the proposer seam."""
    params = llama_init_cached(CFG)
    prompt = [1, 10, 11, 12, 13]
    want = reference_greedy(params, prompt, 12)

    cfg = LLMConfig(model_id=f"tiny-spec-{kv_layout}", model_source="test-tiny",
                    max_num_seqs=2, max_model_len=64, tokenizer="byte",
                    kv_layout=kv_layout, num_speculative_tokens=4)
    eng = JaxLLMEngine(cfg)
    eng.start()
    try:
        # 1. real ngram proposer end-to-end (drafts mostly miss; output exact)
        out = eng.generate_sync(prompt, SamplingParams(
            max_tokens=12, temperature=0.0, stop_token_ids=[-1]))
        assert out.token_ids == want
        assert out.num_generated_tokens == 12

        # 2. oracle speculator: always drafts the true continuation -> every
        # draft accepted, output still exact, finishes in ~3 verify steps
        oracle = {tuple(prompt + want[:i]): want[i:i + 4]
                  for i in range(len(want))}

        def oracle_propose(req, cap):
            return list(oracle.get(tuple(req.token_history), []))[:cap]

        eng._propose_ngram = oracle_propose
        out2 = eng.generate_sync(prompt, SamplingParams(
            max_tokens=12, temperature=0.0, stop_token_ids=[-1]))
        assert out2.token_ids == want
        m = eng.metrics()
        assert m["num_spec_accepted"] >= 8, m  # bulk of tokens via acceptance

        # 3. adversarial speculator: all drafts wrong -> all rejected, output
        # STILL exact (rejection rolls the window back correctly)
        eng._propose_ngram = lambda req, cap: [7] * cap
        accepted_before = eng.metrics()["num_spec_accepted"]
        out3 = eng.generate_sync(prompt, SamplingParams(
            max_tokens=12, temperature=0.0, stop_token_ids=[-1]))
        assert out3.token_ids == want
        # wrong drafts may collide with the true token by chance; near-zero
        assert eng.metrics()["num_spec_accepted"] - accepted_before <= 2

        # 4. sampled (temperature>0) requests ride along un-speculated AND
        # actually sample: at temperature 5 an untrained model is near-uniform
        # over 256 byte tokens, so matching the greedy continuation would be
        # astronomically unlikely (regression: spec path silently going argmax)
        eng._propose_ngram = lambda req, cap: []
        out4 = eng.generate_sync(prompt, SamplingParams(
            max_tokens=6, temperature=5.0, stop_token_ids=[-1]))
        assert out4.num_generated_tokens == 6
        assert out4.token_ids != want[:6]
    finally:
        eng.shutdown()


def test_ngram_proposer_lookup():
    """Prompt-lookup proposes the continuation of the most recent earlier
    occurrence of the trailing n-gram (longest n first)."""
    from ray_tpu.llm.engine import JaxLLMEngine, _Request

    eng = JaxLLMEngine(LLMConfig(model_id="pl", model_source="test-tiny",
                                 num_speculative_tokens=4))
    req = _Request("r", [1, 10, 11, 12, 13, 10, 11, 12, 13, 10, 11, 12, 13],
                   SamplingParams(max_tokens=4))
    assert eng._propose_ngram(req, 4) == [10, 11, 12, 13]
    req2 = _Request("r2", [1, 2, 3, 4, 5], SamplingParams(max_tokens=4))
    assert eng._propose_ngram(req2, 4) == []


def test_speculative_config_validation():
    from ray_tpu.llm import JaxLLMEngine, LLMConfig

    # only ngram (prompt-lookup) proposers exist; a draft-model config refuses
    eng = JaxLLMEngine(LLMConfig(model_id="sv2", model_source="test-tiny",
                                 num_speculative_tokens=4,
                                 speculative_method="draft_model"))
    with pytest.raises(NotImplementedError, match="ngram"):
        eng.start()
    # spec composes with paged, fused multi-step, and pp on BOTH layouts now —
    # no composition fence remains in the serving matrix


def test_device_ngram_proposer_matches_host():
    """The on-device prompt-lookup (fused-spec path) proposes the same drafts
    as the host proposer on the same history."""
    import jax.numpy as jnp
    import numpy as np

    from ray_tpu.llm import model_runner

    ctx = [1, 10, 11, 12, 13, 10, 11, 12, 13, 10, 11, 12]
    L = 32
    hist = np.zeros((2, L), np.int32)
    hist[0, :len(ctx)] = ctx
    hist[1, :5] = [1, 2, 3, 4, 5]  # no repeated n-gram -> no drafts
    hlen = np.asarray([len(ctx), 5], np.int32)
    last = np.asarray([ctx[-1], 5], np.int32)
    window, dlen = model_runner.propose_ngram_device(
        jnp.asarray(hist), jnp.asarray(hlen), jnp.asarray(last), k=4, nmax=3)
    window, dlen = np.asarray(window), np.asarray(dlen)
    assert window[0, 0] == ctx[-1]
    # trailing [10,11,12] last occurred at position 9; continuation is 13,10,11,12
    assert list(window[0, 1:1 + dlen[0]]) == [13, 10, 11, 12]
    assert dlen[1] == 0


@pytest.mark.parametrize("kv_layout", ["slot", "paged"])
def test_spec_fused_multi_step_matches_greedy(kv_layout):
    """spec + fused multi-step (the composed mode), on BOTH cache layouts
    (paged: spec_multi_paged writes windows through pre-grown block tables):
    output is EXACTLY the plain greedy continuation. An untrained model emits
    novel tokens, so the real n-gram proposer rarely fires (same caveat as the
    host-path test) — exact equivalence across misses IS the correctness
    property here; acceptance inside fused bursts is driven by the oracle test
    below."""
    params = llama_init_cached(CFG)
    prompt = [1, 10, 11, 12, 13, 10, 11, 12, 13, 10, 11, 12, 13]
    want = reference_greedy(params, prompt, 12)

    eng = JaxLLMEngine(LLMConfig(
        model_id=f"spec-fused-{kv_layout}", model_source="test-tiny",
        max_num_seqs=2, max_model_len=64, tokenizer="byte", kv_layout=kv_layout,
        num_speculative_tokens=4, num_decode_steps=4))
    eng.start()
    try:
        out = eng.generate_sync(prompt, SamplingParams(
            max_tokens=12, temperature=0.0, stop_token_ids=[-1]))
        assert out.token_ids == want
        assert out.num_generated_tokens == 12

        # sampled requests ride along per-window (regression: silent argmax)
        out2 = eng.generate_sync(prompt, SamplingParams(
            max_tokens=6, temperature=5.0, stop_token_ids=[-1]))
        assert out2.num_generated_tokens == 6
        assert out2.token_ids != want[:6]
    finally:
        eng.shutdown()


def test_spec_fused_oracle_accepts_inside_burst():
    """Oracle proposer through spec_multi's seam: every draft is the true
    continuation, so fused windows must ACCEPT (k+1 tokens per window) and the
    output must still be exactly greedy."""
    import jax
    import jax.numpy as jnp
    import numpy as np

    from jax.sharding import Mesh
    from ray_tpu.llm import model_runner

    params_tree = llama_init_cached(CFG)
    prompt = [1, 10, 11, 12, 13]
    n_gen = 12
    want = reference_greedy(params_tree, prompt, n_gen)

    mesh = Mesh(np.asarray(jax.devices()[:1]).reshape(1, 1, 1), ("dp", "ep", "tp"))
    params = model_runner.shard_params(params_tree, CFG, mesh)
    S, L = 2, 64
    state = model_runner.init_state(CFG, slots=S, max_len=L, mesh=mesh)
    toks = jnp.asarray([prompt], jnp.int32)
    state, last_logits = model_runner.prefill(
        params, state, toks, jnp.asarray(len(prompt)), jnp.asarray(0), CFG)
    first = int(np.argmax(np.asarray(last_logits)))
    assert first == want[0]

    # oracle: full true continuation laid out per slot; drafts = the next k
    # tokens after the current history length
    oracle = np.zeros((S, L), np.int32)
    oracle[0, len(prompt):len(prompt) + n_gen] = want

    def oracle_propose(h, hl, last, k, nmax):
        table = jnp.asarray(oracle)
        starts = jnp.clip(hl, 0, L - k)
        drafts = jax.vmap(
            lambda row, s: jax.lax.dynamic_slice(row, (s,), (k,)))(table, starts)
        dlen = jnp.where(jnp.arange(S) == 0, k, 0).astype(jnp.int32)
        window = jnp.zeros((S, k + 1), jnp.int32).at[:, 0].set(last)
        window = window.at[:, 1:].set(drafts)
        return window, dlen

    hist = np.zeros((S, L), np.int32)
    hist[0, :len(prompt) + 1] = prompt + [first]
    hlen = np.asarray([len(prompt) + 1, 0], np.int32)
    active = jnp.asarray([True, False])
    m, k = 2, 4
    rngs = jax.random.split(jax.random.PRNGKey(0), m)
    zeros = jnp.zeros((S,), jnp.float32)
    state, toks_m, acc_m, drafted_m = model_runner.spec_multi(
        params, state, jnp.asarray(hist), jnp.asarray(hlen), active, CFG,
        rngs, zeros, jnp.ones((S,), jnp.float32), jnp.zeros((S,), jnp.int32),
        m, k, 3, propose_fn=oracle_propose)
    acc_m, toks_m = np.asarray(acc_m), np.asarray(toks_m)
    # every window accepted all k drafts -> k+1 tokens per window
    assert list(acc_m[:, 0]) == [k, k]
    emitted = [int(toks_m[s, 0, t]) for s in range(m) for t in range(k + 1)]
    assert emitted == want[1:1 + m * (k + 1)]


@pytest.mark.parametrize("parallel", [
    dict(pipeline_parallel_size=2),
    dict(pipeline_parallel_size=2, data_parallel_size=2),
    dict(pipeline_parallel_size=2, kv_layout="paged", kv_block_size=16),
    dict(pipeline_parallel_size=2, data_parallel_size=2, kv_layout="paged",
         kv_block_size=16),
])
def test_spec_decode_through_pipeline_matches_greedy(parallel):
    """Speculative verify rides the pp schedule on BOTH cache layouts: the
    verify window is the microbatch payload; greedy output is IDENTICAL to
    plain decode with oracle drafts (all accepted) and adversarial drafts
    (all rejected), with or without dp replicas. Paged bubbles write the
    scratch block; slot bubbles are discarded by the valid mask."""
    params = llama_init_cached(CFG)
    prompt = [1, 10, 11, 12, 13]
    want = reference_greedy(params, prompt, 12)

    eng = JaxLLMEngine(LLMConfig(
        model_id=f"spec-pp-{hash(tuple(sorted(parallel))) & 0xffff}",
        model_source="test-tiny",
        max_num_seqs=4, max_model_len=64, tokenizer="byte",
        num_speculative_tokens=4, **parallel), params=params)
    eng.start()
    try:
        out = eng.generate_sync(prompt, SamplingParams(
            max_tokens=12, temperature=0.0, stop_token_ids=[-1]))
        assert out.token_ids == want

        oracle = {tuple(prompt + want[:i]): want[i:i + 4]
                  for i in range(len(want))}
        eng._propose_ngram = lambda req, cap: list(
            oracle.get(tuple(req.token_history), []))[:cap]
        out2 = eng.generate_sync(prompt, SamplingParams(
            max_tokens=12, temperature=0.0, stop_token_ids=[-1]))
        assert out2.token_ids == want
        assert eng.metrics()["num_spec_accepted"] >= 8

        eng._propose_ngram = lambda req, cap: [7] * cap
        out3 = eng.generate_sync(prompt, SamplingParams(
            max_tokens=12, temperature=0.0, stop_token_ids=[-1]))
        assert out3.token_ids == want
    finally:
        eng.shutdown()
