"""tqdm_ray + usage stats tests (reference experimental/tqdm_ray.py, _private/usage)."""
import sys

import pytest


def test_tqdm_local_render(capsys):
    from ray_tpu.experimental.tqdm_ray import tqdm

    out = list(tqdm(range(5), desc="work", total=5))
    assert out == [0, 1, 2, 3, 4]
    err = capsys.readouterr().err
    assert "work" in err and "5/5" in err


def test_tqdm_from_worker_relays(rt, capsys):
    @rt.remote
    def work():
        from ray_tpu.experimental.tqdm_ray import tqdm

        bar = tqdm(desc="remote-bar", total=3)
        for _ in range(3):
            bar.update(1)
        bar.close()
        return True

    assert rt.get(work.remote())
    import time

    deadline = time.time() + 5
    while time.time() < deadline:
        err = capsys.readouterr().err
        if "remote-bar" in err:
            break
        time.sleep(0.1)
    else:
        raise AssertionError("worker bar never rendered on the driver")


@pytest.fixture(autouse=True)
def _reset_usage():
    from ray_tpu import usage

    usage.reset()
    yield
    usage.reset()


def test_usage_stats_disabled_by_default(monkeypatch):
    monkeypatch.delenv("RAY_TPU_USAGE_STATS", raising=False)
    from ray_tpu import usage

    usage.record_library_usage("train")
    assert usage.usage_report() == {}


def test_usage_stats_record_and_flush(monkeypatch, tmp_path):
    monkeypatch.setenv("RAY_TPU_USAGE_STATS", "1")
    monkeypatch.setenv("RAY_TPU_SESSION_DIR", str(tmp_path))
    from ray_tpu import usage

    usage.record_library_usage("serve")
    usage.record_library_usage("serve")
    usage.record_library_usage("data")
    report = usage.usage_report()
    assert report["serve"] == 2 and report["data"] == 1
    path = usage.flush_to_session_dir()
    import json

    with open(path) as f:
        saved = json.load(f)
    assert saved["features"]["serve"] == 2