"""Compiled Graph (aDAG) tests (reference: python/ray/dag/tests; SURVEY.md §2.3)."""
import os
import time

import pytest

from ray_tpu.dag import InputNode, MultiOutputNode
from ray_tpu.dag.channel import ChannelFullError, ShmChannel


def test_channel_roundtrip_and_backpressure():
    import os

    c = ShmChannel(f"rtch_{os.getpid()}", 1024, create=True)
    try:
        r = ShmChannel(c.name, 1024)
        c.write({"a": 1})
        assert r.read(timeout=1) == {"a": 1}
        c.write([1, 2, 3])
        assert r.read(timeout=1) == [1, 2, 3]
        # backpressure: unread value blocks the next write
        c.write("x")
        with pytest.raises(TimeoutError):
            c.write("y", timeout=0.2)
        assert r.read(timeout=1) == "x"
        c.write("y", timeout=1)  # ack arrived, slot reusable
        assert r.read(timeout=1) == "y"
        with pytest.raises(ChannelFullError):
            c.write(b"z" * 2048)
        r.close()
    finally:
        c.destroy()


@pytest.fixture(scope="module")
def actors(rt):
    @rt.remote
    class Adder:
        def __init__(self, inc):
            self.inc = inc
            self.calls = 0

        def add(self, x):
            self.calls += 1
            return x + self.inc

        def boom(self, x):
            raise ValueError(f"boom on {x}")

        def num_calls(self):
            return self.calls

    a = Adder.remote(1)
    b = Adder.remote(10)
    yield a, b
    for h in (a, b):
        rt.kill(h)


def test_linear_dag(rt, actors):
    a, b = actors
    with InputNode() as inp:
        x = a.add.bind(inp)
        y = b.add.bind(x)
    dag = y.experimental_compile()
    try:
        assert dag.execute(5).get() == 16  # (5+1)+10
        assert dag.execute(100).get() == 111
        # repeated dispatch reuses the compiled loops — no new tasks per call
        for i in range(20):
            assert dag.execute(i).get() == i + 11
    finally:
        dag.teardown()


def test_multi_output_fanout(rt, actors):
    a, b = actors
    with InputNode() as inp:
        x = a.add.bind(inp)
        y = b.add.bind(inp)
    dag = MultiOutputNode([x, y]).experimental_compile()
    try:
        assert dag.execute(7).get() == [8, 17]
    finally:
        dag.teardown()


def test_input_attribute_nodes(rt, actors):
    a, b = actors
    with InputNode() as inp:
        x = a.add.bind(inp["left"])
        y = b.add.bind(inp["right"])
    dag = MultiOutputNode([x, y]).experimental_compile()
    try:
        assert dag.execute({"left": 1, "right": 2}).get() == [2, 12]
    finally:
        dag.teardown()


def test_dag_pipelining_multiple_inflight(rt, actors):
    a, b = actors
    with InputNode() as inp:
        y = b.add.bind(a.add.bind(inp))
    dag = y.experimental_compile(max_inflight_executions=4)
    try:
        # window of 2: submit ahead of reads, results stay ordered
        r0, r1 = dag.execute(0), dag.execute(1)
        out = []
        for i in range(2, 8):
            out.append(r0.get())
            r0, r1 = r1, dag.execute(i)
        out += [r0.get(), r1.get()]
        assert out == [i + 11 for i in range(8)]
        # exceeding the declared depth raises instead of deadlocking
        refs = [dag.execute(i) for i in range(4)]
        with pytest.raises(RuntimeError, match="in flight"):
            dag.execute(99)
        assert [r.get() for r in refs] == [i + 11 for i in range(4)]
    finally:
        dag.teardown()


def test_dag_exception_propagates(rt, actors):
    a, b = actors
    with InputNode() as inp:
        y = a.boom.bind(inp)
    dag = y.experimental_compile()
    try:
        with pytest.raises(ValueError, match="boom"):
            dag.execute(1).get()
    finally:
        dag.teardown()


def test_actor_usable_after_teardown(rt, actors):
    a, _ = actors
    with InputNode() as inp:
        y = a.add.bind(inp)
    dag = y.experimental_compile()
    assert dag.execute(1).get() == 2
    dag.teardown()
    # after teardown the exec loop exits and normal method calls work again
    deadline = time.time() + 10
    while time.time() < deadline:
        try:
            assert rt.get(a.add.remote(5), timeout=5) == 6
            break
        except Exception:
            time.sleep(0.2)
    else:
        raise AssertionError("actor still blocked after teardown")


def test_dag_repeated_dispatch_correct(rt, actors):
    """Correctness half of the old throughput row (tier-1): repeated compiled
    dispatch returns the right answers — no wall-clock assertion, so CI load
    cannot flake it."""
    a, _ = actors
    with InputNode() as inp:
        y = a.add.bind(inp)
    dag = y.experimental_compile()
    try:
        for i in range(50):
            assert dag.execute(i).get() == i + 1
    finally:
        dag.teardown()


@pytest.mark.slow
@pytest.mark.skipif((os.cpu_count() or 1) < 2, reason=(
    "compiled dispatch beats per-call submission only when driver and actor "
    "can run concurrently; on one core the dag path's shm spin-wait handoff "
    "is scheduler-bound while the task path blocks in the selector"))
def test_dag_throughput_beats_task_path(rt, actors):
    """Timing half (slow marker — load-flaky under a saturated box since PR 8):
    paired relative measurement, best-of-3 per path, compiled dispatch must
    beat per-call task submission."""
    a, _ = actors
    n = 50

    def task_path_once():
        t0 = time.perf_counter()
        for i in range(n):
            rt.get(a.add.remote(i))
        return time.perf_counter() - t0

    # task path FIRST: while a compiled dag is active the actor's exec loop
    # owns the actor, and normal method calls block until teardown
    rt.get(a.add.remote(0))  # warm
    # best-of-3 per path: each side keeps its least-loaded run, so a
    # background spike must hit all three of one side to flip the verdict
    task_path = min(task_path_once() for _ in range(3))

    with InputNode() as inp:
        y = a.add.bind(inp)
    dag = y.experimental_compile()
    try:
        dag.execute(0).get()  # warm

        def dag_path_once():
            t0 = time.perf_counter()
            for i in range(n):
                dag.execute(i).get()
            return time.perf_counter() - t0

        dag_path = min(dag_path_once() for _ in range(3))
    finally:
        dag.teardown()
    assert dag_path < task_path, (dag_path, task_path)


def test_accelerator_context_registry():
    from ray_tpu.dag.accelerator_context import (
        Communicator,
        DeviceCommunicator,
        SharedMemoryCommunicator,
        get_accelerator_context,
        register_accelerator_context,
    )

    assert isinstance(get_accelerator_context("cpu"), SharedMemoryCommunicator)
    assert isinstance(get_accelerator_context("tpu"), DeviceCommunicator)
    with pytest.raises(ValueError, match="no communicator"):
        get_accelerator_context("npu")

    class Custom(SharedMemoryCommunicator):
        pass

    register_accelerator_context("npu", Custom)
    assert isinstance(get_accelerator_context("npu"), Custom)
    with pytest.raises(TypeError):
        register_accelerator_context("bad", int)


def test_device_channel_zero_copy_same_process():
    import jax.numpy as jnp

    from ray_tpu.dag.accelerator_context import DeviceCommunicator

    comm = DeviceCommunicator()
    ch = comm.create_channel(f"rtdag_test_{os.getpid()}", 1 << 16, create=True)
    try:
        arr = jnp.arange(8.0)
        ch.write(arr)
        out = ch.read()
        assert out is arr  # same-process fast path returns THE device array
        ch.write({"plain": 1})
        assert ch.read() == {"plain": 1}
    finally:
        ch.destroy()


def test_compiled_dag_with_device_channels(rt):
    @rt.remote
    class Scaler:
        def scale(self, x):
            import jax.numpy as jnp

            return jnp.asarray(x) * 2.0

    a = Scaler.remote()
    with InputNode() as inp:
        node = a.scale.bind(inp)
    dag = node.experimental_compile(channel_type="device")
    try:
        import numpy as np

        out = dag.execute(np.ones(4)).get()
        np.testing.assert_allclose(np.asarray(out), 2 * np.ones(4))
        out2 = dag.execute(np.full(4, 3.0)).get()
        np.testing.assert_allclose(np.asarray(out2), np.full(4, 6.0))
    finally:
        dag.teardown()


def test_device_channel_unwraps_status_pairs():
    """Exec loops wrap payloads as (status, value); the device fast path must
    still splice the resident array back in."""
    import jax.numpy as jnp

    from ray_tpu.dag.accelerator_context import DeviceCommunicator

    comm = DeviceCommunicator()
    ch = comm.create_channel(f"rtdag_pair_{os.getpid()}", 1 << 16, create=True)
    try:
        arr = jnp.arange(4.0)
        ch.write(("ok", arr))
        status, out = ch.read()
        assert status == "ok" and out is arr  # THE array, through the pair wrapper
    finally:
        ch.destroy()
