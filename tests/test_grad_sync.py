"""Device-plane gradient sync (train/grad_sync.py): bucketed overlapped
allreduce, on-device int8 block-quantized reduction, cross-replica sharded
optimizer update. Runs on the conftest 8-device virtual-CPU mesh."""
import numpy as np
import pytest

import jax
import jax.numpy as jnp

from ray_tpu.models import get_config
from ray_tpu.parallel import MeshSpec, build_mesh, use_mesh
from ray_tpu.parallel.sharding import named_sharding
from ray_tpu.train import (GradSyncConfig, grad_sync, init_state,
                           make_optimizer, make_train_step)


@pytest.fixture(scope="module")
def env():
    """Shared tiny-model dp=8 training setup + the stock-step reference run
    (one compile amortized over every parity test)."""
    cfg = get_config("test-tiny")
    mesh = build_mesh(MeshSpec(dp=-1).resolve(8), jax.devices()[:8])
    tx = make_optimizer(total_steps=100)
    state = init_state(jax.random.PRNGKey(0), cfg, tx, mesh=mesh)
    tokens = jax.random.randint(jax.random.PRNGKey(1), (16, 17), 0, cfg.vocab_size)
    with use_mesh(mesh):
        tokens = jax.device_put(tokens, named_sharding(mesh, "batch", None))
    batch = {"tokens": tokens}
    ref_step = make_train_step(cfg, tx, donate=False)
    with use_mesh(mesh):
        ref_state, ref_metrics = ref_step(state, batch)
    return dict(cfg=cfg, mesh=mesh, tx=tx, state=state, batch=batch,
                ref_state=ref_state, ref_metrics=ref_metrics)


def _run(env, sync, state=None):
    step = make_train_step(env["cfg"], env["tx"], donate=False, sync=sync)
    with use_mesh(env["mesh"]):
        new_state, metrics = step(state or env["state"], env["batch"])
    return step, new_state, metrics


def _max_abs_diff(a, b):
    return max(jax.tree_util.tree_leaves(jax.tree_util.tree_map(
        lambda x, y: float(jnp.max(jnp.abs(x - y))), a, b)))


# ------------------------------------------------------------ bucketing unit

def test_partition_buckets_bounds_and_coverage():
    tree = {
        "scalar": jnp.zeros(()),               # scalar leaf
        "odd": jnp.zeros((7, 13)),             # odd shape
        "big": jnp.zeros((4096,)),             # larger than the bucket alone
        "mid": [jnp.zeros((100,)), jnp.zeros((101,))],
    }
    buckets = grad_sync.partition_buckets(tree, bucket_bytes=1024)
    leaves = jax.tree_util.tree_leaves(tree)
    flat = [i for b in buckets for i in b]
    assert sorted(flat) == list(range(len(leaves)))  # every leaf exactly once
    for b in buckets:
        nbytes = sum(int(np.prod(leaves[i].shape or (1,))) * 4 for i in b)
        # a bucket only exceeds the bound when a single leaf does
        assert nbytes <= 1024 or len(b) == 1
    # deterministic
    assert buckets == grad_sync.partition_buckets(tree, bucket_bytes=1024)


def test_partition_buckets_single_bucket_when_large():
    tree = [jnp.zeros((8,)), jnp.zeros((8,))]
    assert grad_sync.partition_buckets(tree, bucket_bytes=1 << 30) == [[0, 1]]


def test_sync_payload_bytes_int8_halves():
    tree = {"w": jnp.zeros((4096, 8)), "tiny": jnp.zeros((3,))}
    sync = GradSyncConfig(mode="bucketed", compression="int8")
    p = grad_sync.sync_payload_bytes(tree, sync)
    assert p["compressed_bytes"] * 2 < p["f32_bytes"]
    # the tiny leaf stays f32 (scales would dominate)
    assert p["compressed_bytes"] >= 3 * 4


# ----------------------------------------------------------- f32 parity

def test_bucketed_matches_monolithic_bit_exact(env):
    step, new_state, metrics = _run(env, GradSyncConfig(mode="bucketed",
                                                        bucket_bytes=64 << 10))
    assert len(step.buckets) > 1  # actually bucketed
    assert _max_abs_diff(new_state.params, env["ref_state"].params) == 0.0
    assert float(metrics["loss"]) == float(env["ref_metrics"]["loss"])
    assert float(metrics["tokens"]) == float(env["ref_metrics"]["tokens"])
    env["bucketed_step"] = step  # reused by the HLO overlap test (one compile)


def test_bucket_boundaries_do_not_change_result(env):
    # tiny buckets: every leaf its own collective, boundaries cross odd
    # shapes and scalar-adjacent leaves; reference = the monolithic step
    step, tiny, _ = _run(env, GradSyncConfig(mode="bucketed", bucket_bytes=1))
    assert len(step.buckets) == len(jax.tree_util.tree_leaves(tiny.params))
    assert _max_abs_diff(tiny.params, env["ref_state"].params) == 0.0


# ------------------------------------------------------------- int8 path

def test_int8_within_documented_tolerance(env):
    _, new_state, metrics = _run(env, GradSyncConfig(
        mode="bucketed", compression="int8", min_quant_elems=1))
    assert np.isfinite(float(metrics["loss"]))
    # loss of step 1 is computed before the sync touches params
    assert float(metrics["loss"]) == float(env["ref_metrics"]["loss"])
    # params after one update: within the block-quantization contract —
    # per-element error <= mean over ranks of amax_block/254, scaled through
    # Adam; a generous end-to-end envelope is 5% relative on the update
    ref = env["ref_state"].params
    rel = jax.tree_util.tree_map(
        lambda a, b, p: float(jnp.max(jnp.abs(a - b))
                              / (jnp.max(jnp.abs(p - b)) + 1e-12)),
        new_state.params, ref, env["state"].params)
    # updates themselves are tiny (warmup); compare update deltas not params
    assert max(jax.tree_util.tree_leaves(rel)) < 0.25


def test_stochastic_rounding_unbiased():
    x = jnp.full((512,), 0.3)  # 0.3/scale is not representable exactly
    from ray_tpu.ops.quant import dequant_blockwise, quantize_blockwise

    acc = np.zeros((512,), np.float64)
    n = 64
    for i in range(n):
        q, s = quantize_blockwise(x, 128, key=jax.random.PRNGKey(i))
        acc += np.asarray(dequant_blockwise(q, s, 512, jnp.float32))
    mean = acc / n
    # round-nearest would give a constant offset; stochastic converges to x
    assert abs(float(mean.mean()) - 0.3) < 2e-3


def test_quantize_blockwise_roundtrip_tolerance():
    from ray_tpu.ops.quant import dequant_blockwise, quantize_blockwise

    x = jax.random.normal(jax.random.PRNGKey(0), (1000,))
    q, s = quantize_blockwise(x, 256)
    y = dequant_blockwise(q, s, 1000, jnp.float32)
    blocks = jnp.pad(x, (0, 24)).reshape(4, 256)
    amax = jnp.max(jnp.abs(blocks), axis=1)
    bound = np.repeat(np.asarray(amax), 256)[:1000] / 254 + 1e-6
    assert np.all(np.abs(np.asarray(y - x)) <= bound)


# ------------------------------------------------- sharded optimizer update

def test_sharded_update_bit_exact_and_sharded(env):
    sync = GradSyncConfig(sharded_update=True)
    state = init_state(jax.random.PRNGKey(0), env["cfg"], env["tx"],
                       mesh=env["mesh"], sync=sync)
    _, new_state, _ = _run(env, sync, state=state)
    assert _max_abs_diff(new_state.params, env["ref_state"].params) == 0.0
    # the Adam moments actually live sharded over dp
    embed_shape = env["state"].params["embed"].shape
    moment_specs = [leaf.sharding.spec
                    for leaf in jax.tree_util.tree_leaves(new_state.opt_state)
                    if getattr(leaf, "shape", None) == embed_shape]
    assert moment_specs and all("dp" in str(s) for s in moment_specs)


def test_sharded_update_composes_with_bucketed(env):
    sync = GradSyncConfig(mode="bucketed", sharded_update=True)
    state = init_state(jax.random.PRNGKey(0), env["cfg"], env["tx"],
                       mesh=env["mesh"], sync=sync)
    _, new_state, _ = _run(env, sync, state=state)
    assert _max_abs_diff(new_state.params, env["ref_state"].params) == 0.0


def test_build_update_specs(env):
    from jax.sharding import PartitionSpec as P

    mesh = env["mesh"]
    specs = grad_sync.build_update_specs(env["state"].params, mesh, axes=("dp",))
    # embed [256, 64]: dim0 divisible by dp=8 -> gains dp
    assert "dp" in str(specs["embed"])
    # scalars/non-divisible leaves keep their base spec
    tiny = jax.ShapeDtypeStruct((3,), jnp.float32)
    out = grad_sync.build_update_specs({"t": tiny}, mesh, axes=("dp",))
    assert out["t"] == P()


def test_opt_state_bytes_per_shard(env):
    tx, mesh = env["tx"], env["mesh"]
    base = grad_sync.abstract_sharded_opt_state(
        tx, jax.eval_shape(lambda p: p, env["state"].params), mesh, axes=())
    sharded = grad_sync.abstract_sharded_opt_state(
        tx, jax.eval_shape(lambda p: p, env["state"].params), mesh, axes=("dp",))
    b0 = grad_sync.opt_state_bytes_per_shard(base)
    b1 = grad_sync.opt_state_bytes_per_shard(sharded)
    assert b1 * 2 <= b0  # dp=8 sharding cuts the dominant moments >= 2x


# ------------------------------------------------------------ HLO overlap

def test_bucketed_reductions_not_sunk_to_end(env):
    step = env.get("bucketed_step")
    if step is None:  # parity test not run first (e.g. -k selection)
        step = make_train_step(env["cfg"], env["tx"], donate=False,
                               sync=GradSyncConfig(mode="bucketed",
                                                   bucket_bytes=64 << 10))
    with use_mesh(env["mesh"]):
        rep = grad_sync.overlap_report(
            step.lower(env["state"], env["batch"]).compile())
    assert rep["n_reductions"] >= len(step.buckets)
    assert not rep["all_sunk_to_end"]
    assert rep["n_compute_after_first_reduction"] > 0


# ----------------------------------------------------------- config plumbing

def test_config_env_roundtrip():
    sync = GradSyncConfig(mode="bucketed", compression="int8",
                          stochastic_rounding=True, sharded_update=True,
                          bucket_bytes=123456, telemetry=True,
                          quant_block_elems=512, min_quant_elems=64,
                          update_axes=("dp",))
    env = sync.to_env()
    import os

    old = {k: os.environ.get(k) for k in env}
    os.environ.update(env)
    try:
        back = GradSyncConfig.from_env()
    finally:
        for k, v in old.items():
            if v is None:
                os.environ.pop(k, None)
            else:
                os.environ[k] = v
    assert back == sync  # every field round-trips (frozen dataclass equality)


def test_monolithic_alias_and_default():
    assert GradSyncConfig(mode="monolithic").mode == "gspmd"
    assert GradSyncConfig().is_default
    assert not GradSyncConfig(mode="bucketed").is_default
    with pytest.raises(ValueError):
        GradSyncConfig(mode="nope")
    with pytest.raises(ValueError):
        GradSyncConfig(mode="bucketed", compression="fp4")
    with pytest.raises(ValueError, match="bucketed"):
        GradSyncConfig(compression="int8")  # silently-ignored int8 forbidden


def test_incompatible_model_rejected(env):
    cfg = get_config("test-tiny", attention_impl="ring")
    step = make_train_step(cfg, env["tx"], donate=False,
                           sync=GradSyncConfig(mode="bucketed"))
    with pytest.raises(ValueError, match="ring"):
        with use_mesh(env["mesh"]):
            step(env["state"], env["batch"])


# ------------------------------------------------------- telemetry phases

def test_instrumented_step_records_phases(env):
    from ray_tpu.util import telemetry

    telemetry.enable()
    try:
        sync = GradSyncConfig(mode="bucketed", bucket_bytes=64 << 10,
                              telemetry=True)
        step = make_train_step(env["cfg"], env["tx"], donate=False, sync=sync)
        with use_mesh(env["mesh"]):
            state, metrics = step(env["state"], env["batch"])
            state, metrics = step(state, env["batch"])
        assert float(metrics["loss"]) > 0
        from ray_tpu.util import metrics as M

        snap = M.merge_snapshots([M._registry.snapshot()])
        hist = snap.get("train_grad_sync_seconds", {}).get("values", {})
        phases = {dict(k).get("phase") for k in hist}
        assert "grad_sync.forward_backward" in phases
        assert "grad_sync.bucket_wait" in phases
        assert "grad_sync.optimizer" in phases
        # the generic step-phase histogram carries the spans too
        sp = snap.get("train_step_phase_seconds", {}).get("values", {})
        assert any(dict(k).get("phase", "").startswith("grad_sync.")
                   for k in sp)
    finally:
        telemetry.disable()


def test_cluster_status_exposes_grad_sync_phases(rt):
    from ray_tpu.util import state as state_api

    status = state_api.cluster_status()
    assert "grad_sync_phases_s" in status["train"]


# ------------------------------------------------------ jax_backend satellites

def test_pick_port_race_retries_once(monkeypatch):
    from ray_tpu.train import jax_backend as jb

    assert jb._is_bind_failure(OSError(98, "Address already in use"))
    assert jb._is_bind_failure(RuntimeError("Failed to bind to port 4242"))
    assert not jb._is_bind_failure(RuntimeError("NCCL timeout"))
    # an unrelated OSError (dead worker, broken pipe) must NOT look like a
    # port race — the retry would bury the real failure
    assert not jb._is_bind_failure(OSError(32, "Broken pipe"))


def test_shutdown_warning_throttled(caplog):
    import logging

    from ray_tpu.train import jax_backend as jb

    jb._last_shutdown_warning[0] = 0.0
    with caplog.at_level(logging.WARNING, logger=jb.__name__):
        jb._warn_shutdown_failure("test path", RuntimeError("boom"))
        jb._warn_shutdown_failure("test path", RuntimeError("boom2"))  # throttled
    msgs = [r for r in caplog.records if "on_shutdown" in r.getMessage()]
    assert len(msgs) == 1
    assert "boom" in msgs[0].getMessage()
