"""graftlint (ray_tpu/tools/analysis): the project-invariant analyzer.

Two layers:

1. fixture snippets per check — violating and clean variants, allowlist
   parsing (mandatory reason, stale-allow detection, comments-only), and
   knob-registry drift — run against throwaway tree roots;
2. the real gate: the FULL analyzer over ray_tpu/ must report zero
   unallowlisted violations and zero allowlist problems.

Everything here is pure AST (the analyzer never imports the analyzed code),
so this file stays cheap against the tier-1 budget.
"""
import os
import textwrap

import pytest

from ray_tpu.tools.analysis import runner
from ray_tpu.tools.analysis.checks import (
    ALL_CHECKS,
    BlockingControlPath,
    HostSyncInHotPath,
    KnobRegistry,
    LockHygiene,
    NoPrint,
    SwallowedException,
    ThreadHygiene,
)

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def lint_snippet(tmp_path, code, checks=None, filename="pkg/mod.py"):
    path = tmp_path / filename
    path.parent.mkdir(parents=True, exist_ok=True)
    path.write_text(textwrap.dedent(code))
    return runner.run_lint(str(tmp_path), subdirs=(filename.split("/")[0],),
                           checks=checks, readme=None)


def names(res):
    return [(v.check, v.line) for v in res.violations]


# -- swallowed-exception ---------------------------------------------------------------

def test_swallowed_exception_flags_silent_broad_handlers(tmp_path):
    res = lint_snippet(tmp_path, """
        def f():
            try:
                work()
            except Exception:
                pass
            try:
                work()
            except:
                return None
    """, checks=[SwallowedException()])
    assert [c for c, _ in names(res)] == ["swallowed-exception"] * 2


def test_swallowed_exception_accepts_raise_log_or_use(tmp_path):
    res = lint_snippet(tmp_path, """
        import logging

        LOGGER = logging.getLogger(__name__)

        def f():
            try:
                work()
            except Exception:
                raise RuntimeError("wrapped")

        def g():
            try:
                work()
            except Exception:
                LOGGER.warning("failed")

        def h():
            try:
                work()
            except Exception as e:
                record(e)  # the error goes somewhere

        def narrow():
            try:
                work()
            except ValueError:
                pass  # narrow catches are a deliberate decision, not flagged
    """, checks=[SwallowedException()])
    assert res.violations == []


# -- no-print --------------------------------------------------------------------------

def test_no_print_flags_runtime_print_and_spares_scripts(tmp_path):
    code = """
        def f():
            print("hi")
    """
    res = lint_snippet(tmp_path, code, checks=[NoPrint()])
    assert [c for c, _ in names(res)] == ["no-print"]
    res = lint_snippet(tmp_path, code, checks=[NoPrint()],
                       filename="app/ray_tpu/scripts/cli.py")
    assert res.violations == []


# -- thread-hygiene / lock-hygiene -----------------------------------------------------

def test_thread_hygiene_requires_daemon_and_name(tmp_path):
    res = lint_snippet(tmp_path, """
        import threading

        def f():
            threading.Thread(target=f).start()              # both missing
            threading.Thread(target=f, daemon=True).start() # name missing
            threading.Thread(target=f, daemon=False, name="ok").start()
    """, checks=[ThreadHygiene()])
    assert [c for c, _ in names(res)] == ["thread-hygiene"] * 2


def test_lock_hygiene_flags_mixed_locked_unlocked_writes(tmp_path):
    res = lint_snippet(tmp_path, """
        import threading

        class Spawner:
            def __init__(self):
                self.x = 0          # construction: never flagged
                self._lock = threading.Lock()

            def start(self):
                threading.Thread(target=self.run, daemon=True, name="t").start()

            def run(self):
                with self._lock:
                    self.x = 1      # declares x lock-protected

            def poke(self):
                self.x = 2          # unlocked write -> flagged

            def _poke_locked(self):
                self.x = 3          # *_locked convention: caller holds it
    """, checks=[LockHygiene()])
    assert len(res.violations) == 1
    assert res.violations[0].check == "lock-hygiene"


def test_lock_hygiene_ignores_threadless_classes_and_start_gates(tmp_path):
    res = lint_snippet(tmp_path, """
        import threading

        class NoThreads:
            def a(self):
                with self._lock:
                    self.x = 1

            def b(self):
                self.x = 2   # no threads spawned anywhere in the class

        class StartGate:
            def start(self):
                with self._start_lock:
                    self.state = init()   # one-time init, not a guard
                threading.Thread(target=self.run, daemon=True, name="t").start()

            def run(self):
                self.state = step(self.state)
    """, checks=[LockHygiene()])
    assert res.violations == []


# -- host-sync-in-hot-path -------------------------------------------------------------

def test_host_sync_flags_syncs_in_hot_paths_and_one_level_callees(tmp_path):
    res = lint_snippet(tmp_path, """
        import numpy as np
        from ray_tpu.util.hot_path import hot_path

        class Engine:
            @hot_path
            def step(self):
                x = self.compute()
                v = float(x)            # scalarize in hot fn
                self.emit(x)

            def emit(self, x):
                return np.asarray(x)    # one-level callee

            def cold(self, x):
                return x.item()         # unregistered: not flagged
    """, checks=[HostSyncInHotPath()])
    assert len(res.violations) == 2
    assert all(v.check == "host-sync-in-hot-path" for v in res.violations)


def test_host_sync_quiet_without_hot_path_registration(tmp_path):
    res = lint_snippet(tmp_path, """
        import numpy as np

        def anywhere(x):
            return np.asarray(x).item()
    """, checks=[HostSyncInHotPath()])
    assert res.violations == []


# -- blocking-control-path -------------------------------------------------------------

def test_blocking_control_flags_async_control_group_and_control_path(tmp_path):
    res = lint_snippet(tmp_path, """
        import time
        import ray_tpu
        from ray_tpu.util.hot_path import control_path

        async def handler():
            time.sleep(1)                   # blocks the event loop

        class Replica:
            def _actor_method(**kw):
                pass

            @_actor_method(concurrency_group="control")
            def check_health(self):
                return ray_tpu.get(self.ref)  # blocks the control lane

        @control_path
        def drain_poll(sock):
            sock.recv(1)                    # blocks a health/drain path

        def data_plane(sock):
            time.sleep(1)                   # ordinary code: not flagged
            sock.recv(1)
    """, checks=[BlockingControlPath()])
    assert [c for c, _ in names(res)] == ["blocking-control-path"] * 3


def test_blocking_control_skips_nested_defs(tmp_path):
    res = lint_snippet(tmp_path, """
        import time

        async def spawn():
            def worker():       # runs on its own thread, not the event loop
                time.sleep(1)
            return worker
    """, checks=[BlockingControlPath()])
    assert res.violations == []


# -- allowlist mechanics ---------------------------------------------------------------

def test_allow_suppresses_with_reason_same_line_or_above(tmp_path):
    res = lint_snippet(tmp_path, """
        def f():
            try:
                work()
            except Exception:  # graftlint: allow[swallowed-exception] probe only
                pass
            try:
                work()
            # graftlint: allow[swallowed-exception] second probe
            except Exception:
                pass
    """, checks=[SwallowedException()])
    assert res.violations == [] and res.problems == []
    assert len(res.allowed) == 2


def test_allow_without_reason_is_a_problem(tmp_path):
    res = lint_snippet(tmp_path, """
        def f():
            try:
                work()
            except Exception:  # graftlint: allow[swallowed-exception]
                pass
    """, checks=[SwallowedException()])
    assert res.violations == []
    assert [p.check for p in res.problems] == ["allowlist"]
    assert "no reason" in res.problems[0].message


def test_stale_and_unknown_allows_are_problems(tmp_path):
    res = lint_snippet(tmp_path, """
        # graftlint: allow[swallowed-exception] nothing fires here
        X = 1
        # graftlint: allow[not-a-check] bogus
        Y = 2
    """, checks=[SwallowedException()])
    msgs = sorted(p.message for p in res.problems)
    assert len(msgs) == 2
    assert any("stale" in m for m in msgs)
    assert any("no known check" in m for m in msgs)


def test_allow_inside_string_literal_does_not_count(tmp_path):
    res = lint_snippet(tmp_path, '''
        DOC = "# graftlint: allow[swallowed-exception] inside a string"

        def f():
            try:
                work()
            except Exception:
                pass
    ''', checks=[SwallowedException()])
    # the string is not a comment: the violation fires, no stale-allow problem
    assert [c for c, _ in names(res)] == ["swallowed-exception"]
    assert res.problems == []


# -- knob-registry ---------------------------------------------------------------------

KNOBS_FIXTURE = '''
import dataclasses
from typing import Any, Dict, List, Optional


@dataclasses.dataclass(frozen=True)
class Knob:
    env: str
    type: str
    default: Any
    doc: str
    subsystem: str
    attr: Optional[str] = None
    internal: bool = False


KNOBS: List[Knob] = [
    Knob("RAY_TPU_FIXTURE_USED", "int", 1, "used knob", "core"),
    Knob("RAY_TPU_FIXTURE_STALE", "int", 2, "stale knob", "core"),
]
REGISTRY: Dict[str, Knob] = {k.env: k for k in KNOBS}
SUBSYSTEMS = ["core"]


def generate_readme(text):
    return text
'''


def knob_tree(tmp_path, reader_code):
    pkg = tmp_path / "ray_tpu"
    pkg.mkdir(parents=True, exist_ok=True)
    (pkg / "knobs.py").write_text(KNOBS_FIXTURE)
    (pkg / "reader.py").write_text(textwrap.dedent(reader_code))
    return runner.run_lint(str(tmp_path), subdirs=("ray_tpu",),
                           checks=[KnobRegistry()], readme=None)


def test_knob_registry_flags_unregistered_and_stale(tmp_path):
    res = knob_tree(tmp_path, """
        import os

        A = os.environ.get("RAY_TPU_FIXTURE_USED")
        B = os.environ.get("RAY_TPU_FIXTURE_UNKNOWN")
    """)
    msgs = sorted(v.message for v in res.violations)
    assert len(msgs) == 2
    assert any("RAY_TPU_FIXTURE_UNKNOWN is not registered" in m for m in msgs)
    assert any("RAY_TPU_FIXTURE_STALE is registered but nothing references"
               in m for m in msgs)


def test_knob_registry_clean_when_all_used(tmp_path):
    res = knob_tree(tmp_path, """
        import os

        A = os.environ.get("RAY_TPU_FIXTURE_USED")
        B = os.environ.get("RAY_TPU_FIXTURE_STALE")
    """)
    assert res.violations == []


# -- the real registry + README --------------------------------------------------------

def test_registry_covers_every_knob_in_the_tree():
    """Every RAY_TPU_* literal in the package resolves in ray_tpu/knobs.py,
    and the registry's own accounting matches CONFIG."""
    from ray_tpu import knobs
    from ray_tpu.config import CONFIG

    assert len(knobs.KNOBS) >= 123
    flags = {f.env: f for f in CONFIG.flags()}
    regd = {k.env: k for k in knobs.KNOBS if k.attr}
    assert set(flags) == set(regd)
    for env, f in flags.items():
        assert f.type == regd[env].type and f.default == regd[env].default
    # every knob carries the registry contract
    for k in knobs.KNOBS:
        assert k.doc and k.subsystem and k.type in ("int", "float", "bool", "str")


def test_readme_tables_are_generated_and_current():
    from ray_tpu import knobs

    text = open(os.path.join(REPO_ROOT, "README.md"), encoding="utf-8").read()
    assert knobs.generate_readme(text) == text, (
        "README knob tables drifted from ray_tpu/knobs.py — run "
        "`ray-tpu lint --write-docs`")
    for sub in knobs.SUBSYSTEMS:
        assert f"<!-- knobs:{sub} " in text


# -- the gate: the whole package is clean ----------------------------------------------

def test_ray_tpu_tree_is_lint_clean():
    res = runner.run_lint(REPO_ROOT, subdirs=("ray_tpu",))
    rendered = "\n".join(v.render() for v in res.failures[:25])
    assert not res.failures, f"graftlint violations:\n{rendered}"
    assert res.files > 150  # the walk actually saw the package


def test_cli_lint_entrypoint_runs_clean():
    # scoped to the analyzer's own package: exercises the CLI surface without
    # a second full-tree walk (the gate above already did one; tier-1 budget)
    assert runner.main(["--root", REPO_ROOT, "ray_tpu/tools"]) == 0
