"""ray_tpu.rllib tests (reference strategy: rllib regression configs on CartPole)."""
import numpy as np
import pytest

from ray_tpu import rllib
from ray_tpu.rllib.algorithms.ppo import PPO, PPOConfig


@pytest.fixture(autouse=True)
def _cluster(rt):
    yield


def test_env_runner_samples_episodes(rt):
    cfg = PPOConfig().environment("CartPole-v1").env_runners(
        num_env_runners=1, num_envs_per_env_runner=2, rollout_fragment_length=50
    )
    runner = rllib.SingleAgentEnvRunner(cfg, 0)
    eps = runner.sample(100)
    assert sum(len(e["rewards"]) for e in eps) >= 100
    for e in eps:
        assert e["obs"].shape[0] == len(e["rewards"]) == len(e["actions"])
        assert "action_logp" in e and "vf_preds" in e
    runner.stop()


def test_gae_connector():
    from ray_tpu.rllib.connectors import GeneralAdvantageEstimation
    from ray_tpu.rllib.core.rl_module import MLPModule
    import gymnasium as gym

    env = gym.make("CartPole-v1")
    mod = MLPModule(env.observation_space, env.action_space, {})
    params = mod.init_params(0)
    ep = {
        "obs": np.random.randn(5, 4).astype(np.float32),
        "next_obs_last": np.random.randn(4).astype(np.float32),
        "actions": np.zeros(5, np.int64),
        "rewards": np.ones(5, np.float32),
        "terminated": True,
        "truncated": False,
        "action_logp": np.zeros(5, np.float32),
        "vf_preds": np.zeros(5, np.float32),
    }
    gae = GeneralAdvantageEstimation(gamma=0.99, lambda_=0.95)
    batch = gae([ep], module=mod, params=params)
    assert batch["advantages"].shape == (5,)
    assert abs(batch["advantages"].mean()) < 1e-5  # standardized
    env.close()


def test_ppo_improves_cartpole(rt):
    config = (
        PPOConfig()
        .environment("CartPole-v1")
        .env_runners(num_env_runners=2, num_envs_per_env_runner=4, rollout_fragment_length=64)
        .training(lr=3e-4, train_batch_size=1024, minibatch_size=256, num_epochs=6,
                  gamma=0.99, lambda_=0.95, clip_param=0.3, entropy_coeff=0.01)
        .debugging(seed=0)
    )
    algo = config.build_algo()
    try:
        first = algo.train()
        returns = [first.get("episode_return_mean") or 0.0]
        for _ in range(7):
            result = algo.train()
            returns.append(result.get("episode_return_mean") or 0.0)
        # CartPole random policy ~20-25 return; PPO should clearly improve
        assert max(returns[2:]) > returns[0] + 15, returns
    finally:
        algo.cleanup()


def test_multi_learner_group_grad_sync(rt):
    config = (
        PPOConfig()
        .environment("CartPole-v1")
        .env_runners(num_env_runners=1, num_envs_per_env_runner=2, rollout_fragment_length=32)
        .training(train_batch_size=128, minibatch_size=32, num_epochs=1)
        .learners(num_learners=2)
        .debugging(seed=0)
    )
    algo = config.build_algo()
    try:
        result = algo.train()
        assert "total_loss" in result
        # both learners hold identical params after allreduced updates
        import ray_tpu

        p0, p1 = ray_tpu.get([l.get_weights.remote() for l in algo.learner_group.learners])
        np.testing.assert_allclose(p0["pi"][0]["w"], p1["pi"][0]["w"], rtol=1e-5)
    finally:
        algo.cleanup()


def test_algorithm_checkpoint_roundtrip(rt):
    config = (
        PPOConfig()
        .environment("CartPole-v1")
        .env_runners(num_env_runners=1, num_envs_per_env_runner=2, rollout_fragment_length=32)
        .training(train_batch_size=128, minibatch_size=64, num_epochs=1)
        .debugging(seed=0)
    )
    algo = config.build_algo()
    try:
        algo.train()
        state = algo.save_checkpoint()
        w_before = algo.get_weights()
        algo.train()
        algo.load_checkpoint(state)
        w_after = algo.get_weights()
        np.testing.assert_allclose(w_before["pi"][0]["w"], w_after["pi"][0]["w"])
    finally:
        algo.cleanup()
