"""Pluggable checkpoint storage (reference python/ray/train/_internal/storage.py:358).

The mock:// scheme is a directory-backed remote store reachable only through
explicit upload/download — code passing these tests never relied on workers
and controller sharing a filesystem.
"""
import json
import os
import uuid

import pytest

from ray_tpu.air.config import CheckpointConfig
from ray_tpu.train import Checkpoint
from ray_tpu.train import storage
from ray_tpu.train.checkpoint_manager import CheckpointManager


@pytest.fixture()
def mock_root(tmp_path, monkeypatch):
    root = str(tmp_path / "bucket")
    monkeypatch.setenv("RAY_TPU_MOCK_FS_ROOT", root)
    return root


def _make_local_ckpt(tmp_path, step):
    d = tmp_path / f"src_{step}"
    d.mkdir()
    (d / "state.json").write_text(json.dumps({"step": step}))
    (d / "nested").mkdir()
    (d / "nested" / "w.bin").write_bytes(b"\x01" * 100)
    return str(d)


def test_upload_download_roundtrip(mock_root, tmp_path):
    src = _make_local_ckpt(tmp_path, 1)
    storage.upload_dir(src, "mock://exp/ck")
    assert storage.exists("mock://exp/ck")
    assert sorted(storage.listdir("mock://exp")) == ["ck"]
    dst = str(tmp_path / "down")
    storage.download_dir("mock://exp/ck", dst)
    assert json.load(open(os.path.join(dst, "state.json")))["step"] == 1
    assert open(os.path.join(dst, "nested", "w.bin"), "rb").read() == b"\x01" * 100
    storage.delete("mock://exp/ck")
    assert not storage.exists("mock://exp/ck")


def test_persist_dir_all_directions(mock_root, tmp_path):
    # local -> remote consumes the local copy (worker-side upload)
    src = _make_local_ckpt(tmp_path, 2)
    storage.persist_dir(src, "mock://p/a")
    assert not os.path.exists(src) and storage.exists("mock://p/a")
    # remote -> remote is a rename (controller moving staging -> durable)
    storage.persist_dir("mock://p/a", "mock://p/b")
    assert storage.exists("mock://p/b") and not storage.exists("mock://p/a")
    # remote -> local downloads
    dst = str(tmp_path / "out")
    storage.persist_dir("mock://p/b", dst)
    assert json.load(open(os.path.join(dst, "state.json")))["step"] == 2


def test_remote_checkpoint_metadata_and_as_directory(mock_root, tmp_path):
    src = _make_local_ckpt(tmp_path, 3)
    storage.upload_dir(src, "mock://ck3")
    ckpt = Checkpoint("mock://ck3")
    assert ckpt.is_remote
    ckpt.update_metadata({"index": 7})
    assert ckpt.get_metadata() == {"index": 7}
    with ckpt.as_directory() as d:
        assert d != "mock://ck3" and os.path.isdir(d)
        assert json.load(open(os.path.join(d, "state.json")))["step"] == 3
        local = d
    assert not os.path.exists(local)  # temp download cleaned up


def test_checkpoint_manager_remote_retention_and_resume_scan(mock_root, tmp_path):
    uri = "mock://runs/exp1"
    mgr = CheckpointManager(uri, CheckpointConfig(num_to_keep=2))
    for step in range(3):
        src = _make_local_ckpt(tmp_path, step)
        mgr.register(Checkpoint(src), {"step": step})
    names = storage.listdir(uri)
    assert "checkpoint_000001" in names and "checkpoint_000002" in names
    assert "checkpoint_000000" not in names  # retention pruned via the fs
    assert mgr.latest_checkpoint.path == storage.join(uri, "checkpoint_000002")
    # a fresh manager (head restart / rerun) rebuilds its index from the URI
    mgr2 = CheckpointManager(uri, CheckpointConfig(num_to_keep=2))
    assert mgr2.latest_checkpoint.path.endswith("checkpoint_000002")
    with mgr2.latest_checkpoint.as_directory() as d:
        assert json.load(open(os.path.join(d, "state.json")))["step"] == 2


def test_trainer_with_remote_storage_and_resume(rt, tmp_path):
    """End-to-end: workers UPLOAD checkpoints to mock:// storage on report;
    the result carries URIs; a rerun under the same name resumes from the URI
    (downloaded on whatever host runs the worker)."""
    from ray_tpu.air import CheckpointConfig as CC
    from ray_tpu.air import RunConfig, ScalingConfig
    from ray_tpu.train import JaxConfig, JaxTrainer
    import ray_tpu.train as train

    name = f"exp_{uuid.uuid4().hex[:8]}"

    def loop(config):
        import json as _json
        import os as _os
        import tempfile

        ctx = train.get_context()
        ckpt = train.get_checkpoint()
        start = 0
        if ckpt is not None:
            assert ckpt.is_remote  # resume streams DOWN from storage
            with ckpt.as_directory() as d:
                start = _json.load(open(_os.path.join(d, "s.json")))["step"] + 1
        for step in range(start, start + 2):
            checkpoint = None
            if ctx.get_world_rank() == 0:
                d = tempfile.mkdtemp()
                _json.dump({"step": step}, open(_os.path.join(d, "s.json"), "w"))
                checkpoint = train.Checkpoint.from_directory(d)
            train.report({"step": step}, checkpoint=checkpoint)

    def make_trainer():
        return JaxTrainer(
            loop,
            backend_config=JaxConfig(collective_group=False),
            scaling_config=ScalingConfig(num_workers=2, cpus_per_worker=1.0),
            run_config=RunConfig(name=name, storage_path="mock://results",
                                 checkpoint_config=CC(num_to_keep=2)),
        )

    result = make_trainer().fit()
    assert result.error is None, result.error
    assert result.metrics["step"] == 1
    assert result.checkpoint is not None and result.checkpoint.path.startswith("mock://")
    # second run resumes from the URI checkpoint
    result2 = make_trainer().fit()
    assert result2.error is None, result2.error
    assert result2.metrics["step"] == 3


def test_file_uri_is_local(tmp_path):
    """file:// URIs strip to plain local paths (no garbage ./file: dirs)."""
    target = tmp_path / "nfs" / "exp"
    target.mkdir(parents=True)
    ckpt = Checkpoint(f"file://{target}")
    assert not ckpt.is_remote
    assert ckpt.path == str(target)
    mgr = CheckpointManager(f"file://{tmp_path}/nfs/exp2")
    assert mgr.storage_dir == str(tmp_path / "nfs" / "exp2")
    assert os.path.isdir(mgr.storage_dir)


def test_empty_dirs_roundtrip(mock_root, tmp_path):
    src = tmp_path / "src"
    (src / "empty").mkdir(parents=True)
    (src / "f.txt").write_text("x")
    storage.upload_dir(str(src), "mock://ed")
    dst = str(tmp_path / "dst")
    storage.download_dir("mock://ed", dst)
    assert os.path.isdir(os.path.join(dst, "empty"))
    assert open(os.path.join(dst, "f.txt")).read() == "x"
