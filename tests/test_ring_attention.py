"""Ring / Ulysses sequence-parallel attention vs the XLA reference (8-device CPU mesh)."""
import jax
import jax.numpy as jnp
import numpy as np
import pytest

from ray_tpu.ops.attention import attention_reference
from ray_tpu.ops.ring_attention import ring_attention_sharded
from ray_tpu.parallel import local_mesh, use_mesh


def _qkv(b=2, s=32, h=4, hkv=4, d=8, seed=0):
    ks = jax.random.split(jax.random.PRNGKey(seed), 3)
    q = jax.random.normal(ks[0], (b, s, h, d), jnp.float32)
    k = jax.random.normal(ks[1], (b, s, hkv, d), jnp.float32)
    v = jax.random.normal(ks[2], (b, s, hkv, d), jnp.float32)
    return q, k, v


@pytest.mark.parametrize("causal", [True, False])
def test_ring_matches_reference(causal):
    mesh = local_mesh(dp=2, sp=2, tp=2)
    q, k, v = _qkv()
    ref = attention_reference(q, k, v, causal=causal)
    with use_mesh(mesh):
        out = ring_attention_sharded(q, k, v, mesh=mesh, causal=causal)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-5, rtol=2e-5)


def test_ring_gqa():
    mesh = local_mesh(sp=4, tp=2)
    q, k, v = _qkv(h=4, hkv=2)
    ref = attention_reference(q, k, v, causal=True)
    with use_mesh(mesh):
        out = ring_attention_sharded(q, k, v, mesh=mesh, causal=True)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-5, rtol=2e-5)


def test_ring_segment_ids():
    mesh = local_mesh(dp=2, sp=4)
    q, k, v = _qkv(b=2, s=32, h=2, hkv=2)
    seg = jnp.concatenate(
        [jnp.zeros((2, 16), jnp.int32), jnp.ones((2, 16), jnp.int32)], axis=1
    )
    ref = attention_reference(q, k, v, causal=True, segment_ids=seg)
    with use_mesh(mesh):
        out = ring_attention_sharded(q, k, v, mesh=mesh, causal=True, segment_ids=seg)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-5, rtol=2e-5)


def test_ulysses_matches_reference():
    mesh = local_mesh(dp=2, sp=4)
    q, k, v = _qkv(h=4, hkv=4)
    ref = attention_reference(q, k, v, causal=True)
    with use_mesh(mesh):
        out = ring_attention_sharded(q, k, v, mesh=mesh, causal=True, impl="ulysses")
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-5, rtol=2e-5)


def test_ring_grads_flow():
    """AD through the ring (ppermute + scan) must produce finite grads."""
    mesh = local_mesh(dp=2, sp=2, tp=2)
    q, k, v = _qkv(s=16)

    def loss(q, k, v):
        return jnp.sum(ring_attention_sharded(q, k, v, mesh=mesh, causal=True) ** 2)

    with use_mesh(mesh):
        g = jax.grad(loss)(q, k, v)
    assert all(bool(jnp.isfinite(x).all()) for x in g)

    # Matches reference grads.
    def loss_ref(q, k, v):
        return jnp.sum(attention_reference(q, k, v, causal=True) ** 2)

    g_ref = jax.grad(loss_ref)(q, k, v)
    np.testing.assert_allclose(np.asarray(g), np.asarray(g_ref), atol=1e-4, rtol=1e-4)
