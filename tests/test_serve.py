"""ray_tpu.serve tests (reference strategy: serve local_testing_mode + e2e suites)."""
import time

import pytest

from ray_tpu import serve


@pytest.fixture(autouse=True)
def _cleanup(rt):
    yield
    serve.shutdown()


def test_deploy_and_call(rt):
    @serve.deployment
    class Greeter:
        def __call__(self, name):
            return f"hello {name}"

    handle = serve.run(Greeter.bind(), name="greet")
    assert handle.remote("world").result() == "hello world"
    st = serve.status()
    assert st["greet"]["deployments"]["Greeter"]["num_running"] == 1


def test_multi_replica_routing(rt):
    import os

    @serve.deployment(num_replicas=2)
    class Who:
        def __call__(self, _):
            return os.getpid()

    handle = serve.run(Who.bind(), name="who")
    # p2c routing spreads load across both replicas. Sequential calls can
    # legitimately stick to one replica while the other is still cold/slow on
    # a loaded machine, so keep issuing batches until both have answered.
    pids = set()
    deadline = time.time() + 30
    while len(pids) < 2 and time.time() < deadline:
        pids |= {handle.remote(None).result() for _ in range(20)}
    assert len(pids) == 2  # p2c router spreads load across both replicas


def test_composed_deployments(rt):
    @serve.deployment
    class Adder:
        def __init__(self, inc):
            self.inc = inc

        def __call__(self, x):
            return x + self.inc

    @serve.deployment
    class Ingress:
        def __init__(self, adder):
            self.adder = adder

        def __call__(self, x):
            return self.adder.remote(x).result() * 10

    app = Ingress.bind(Adder.bind(3))
    handle = serve.run(app, name="composed")
    assert handle.remote(4).result() == 70


def test_method_call_and_user_config(rt):
    @serve.deployment(user_config={"threshold": 5})
    class Svc:
        def __init__(self):
            self.threshold = 0

        def reconfigure(self, cfg):
            self.threshold = cfg["threshold"]

        def over(self, x):
            return x > self.threshold

    handle = serve.run(Svc.bind(), name="svc")
    assert handle.over.remote(10).result() is True
    assert handle.over.remote(3).result() is False


def test_replica_failure_recovery(rt):
    import ray_tpu

    @serve.deployment(num_replicas=1, health_check_period_s=0.5)
    class Fragile:
        def __call__(self, x):
            return x * 2

    handle = serve.run(Fragile.bind(), name="fragile")
    assert handle.remote(2).result() == 4
    # kill the replica behind serve's back; the controller must replace it
    controller = ray_tpu.get_actor("SERVE_CONTROLLER")
    replicas = ray_tpu.get(controller.get_replicas.remote("fragile", "Fragile"))
    ray_tpu.kill(replicas[0])
    deadline = time.time() + 30
    ok = False
    while time.time() < deadline:
        try:
            h = serve.get_deployment_handle("Fragile", "fragile")
            if h.remote(3).result() == 6:
                ok = True
                break
        except Exception:
            time.sleep(0.3)
    assert ok, "replica was not replaced after kill"


def test_http_proxy(rt):
    import json
    import urllib.request

    @serve.deployment
    class Echo:
        def __call__(self, payload):
            return {"got": payload}

    serve.start(http_options={"port": 18123})
    serve.run(Echo.bind(), name="echo", route_prefix="/echo")
    req = urllib.request.Request(
        "http://127.0.0.1:18123/echo",
        data=json.dumps({"a": 1}).encode(),
        headers={"Content-Type": "application/json"},
    )
    body = json.loads(urllib.request.urlopen(req, timeout=30).read())
    assert body == {"got": {"a": 1}}


def test_serve_batch(rt):
    calls = []

    @serve.deployment(max_ongoing_requests=8)
    class Batched:
        @serve.batch(max_batch_size=4, batch_wait_timeout_s=0.2)
        def handle_batch(self, xs):
            return [x * 2 for x in xs]

        def __call__(self, x):
            return self.handle_batch(x)

    handle = serve.run(Batched.bind(), name="batched")
    t0 = time.time()
    resps = [handle.remote(i) for i in range(8)]
    results = sorted(r.result() for r in resps)
    assert results == [i * 2 for i in range(8)]


def test_autoscaling_scales_up(rt):
    @serve.deployment(
        autoscaling_config=serve.AutoscalingConfig(
            min_replicas=1, max_replicas=3, target_ongoing_requests=1.0,
            upscale_delay_s=0.5, metrics_interval_s=0.5,
        ),
        max_ongoing_requests=2,
    )
    class Slow:
        def __call__(self, _):
            time.sleep(0.4)
            return 1

    handle = serve.run(Slow.bind(), name="auto")
    import ray_tpu

    controller = ray_tpu.get_actor("SERVE_CONTROLLER")
    # sustained concurrent load
    resps = []
    deadline = time.time() + 15
    scaled = False
    while time.time() < deadline:
        resps = [handle.remote(None) for _ in range(6)]
        for r in resps:
            r.result()
        info = ray_tpu.get(controller.get_deployment_info.remote("auto", "Slow"))
        if info["target_num_replicas"] >= 2:
            scaled = True
            break
    assert scaled, "autoscaler never scaled up under sustained load"


def test_long_poll_listen_for_change(rt):
    """Reference LongPollHost: listeners block until a watched key's version moves."""
    import time

    import ray_tpu
    from ray_tpu import serve

    @serve.deployment(num_replicas=1)
    class D:
        def __call__(self, x):
            return x

    serve.run(D.bind(), name="lp-app")
    try:
        controller = serve.api._get_or_create_controller()
        key = "replicas::lp-app/D"
        # initial listen from version -1 returns immediately with the snapshot
        res = ray_tpu.get(controller.listen_for_change.remote({key: -1}, 5.0))
        assert key in res
        version, replicas = res[key]
        assert version >= 1 and len(replicas) == 1
        # same version: no change -> timeout -> {}
        t0 = time.time()
        res2 = ray_tpu.get(controller.listen_for_change.remote({key: version}, 1.0))
        assert res2 == {} and time.time() - t0 >= 0.9
        # scale up -> the parked listener is woken with the new set
        ref = controller.listen_for_change.remote({key: version}, 30.0)
        serve.run(D.options(num_replicas=2).bind(), name="lp-app")
        res3 = ray_tpu.get(ref)
        assert key in res3
        v3, replicas3 = res3[key]
        assert v3 > version and len(replicas3) == 2
    finally:
        serve.delete("lp-app")


def test_handle_sees_scale_up_via_push(rt):
    import time

    from ray_tpu import serve

    @serve.deployment(num_replicas=1)
    class E:
        def __call__(self, x):
            return x * 2

    h = serve.run(E.bind(), name="push-app")
    try:
        assert h.remote(2).result() == 4  # starts the long-poll listener
        from ray_tpu.serve.handle import _lp_registry

        serve.run(E.options(num_replicas=3).bind(), name="push-app")
        deadline = time.time() + 15
        while time.time() < deadline:
            entry = _lp_registry.get(("push-app", "E"))
            if entry is not None and entry.replicas is not None and len(entry.replicas) == 3:
                break
            time.sleep(0.2)
        else:
            raise AssertionError("push update never arrived")
        assert h.remote(3).result() == 6
    finally:
        serve.delete("push-app")


def test_controller_crash_recovers_apps_from_kv(rt):
    """Reference: serve app target state persists in the GCS KV, so a crashed
    controller restores every app instead of forgetting the cluster's serving."""
    import time

    import ray_tpu
    from ray_tpu import serve

    @serve.deployment(num_replicas=1)
    class Persisted:
        def __call__(self, x):
            return x + 100

    h = serve.run(Persisted.bind(), name="crash-app")
    assert h.remote(1).result() == 101
    # crash the controller (NOT serve.shutdown — that's intentional teardown)
    ctrl = ray_tpu.get_actor(serve.api.CONTROLLER_NAME)
    ray_tpu.kill(ctrl)
    time.sleep(0.5)
    # a fresh controller must restore the app from the KV checkpoint
    ctrl2 = serve.api._get_or_create_controller()
    deadline = time.time() + 60
    while time.time() < deadline:
        info = ray_tpu.get(ctrl2.get_deployment_info.remote("crash-app", "Persisted"))
        if info and info["num_running"] >= 1:
            break
        time.sleep(0.2)
    else:
        raise AssertionError(f"app not restored: {info}")
    h2 = serve.get_app_handle("crash-app")
    assert h2.remote(5).result() == 105


def test_grpc_proxy_ingress(rt, monkeypatch):
    """Reference gRPCProxy (proxy.py:523): gRPC ingress routed to handles."""
    from ray_tpu.serve.grpc_proxy import grpc_call, start_grpc_proxy

    # tier-1 budget: the no-such-app error path below otherwise burns the
    # full RAY_TPU_SERVE_REPLICA_WAIT_S default (30s) before surfacing —
    # the behavior under test is THAT it surfaces, not the wait's length
    monkeypatch.setenv("RAY_TPU_SERVE_REPLICA_WAIT_S", "1.5")

    @serve.deployment(num_replicas=1)
    class Calc:
        def __call__(self, x):
            return x + 1

        def mul(self, a, b):
            return a * b

    info = serve.start(grpc_options={"port": 0})
    port = info["grpc_port"]
    assert port > 0  # ephemeral bind reported back
    serve.run(Calc.bind(), name="calc")
    addr = f"127.0.0.1:{port}"
    assert grpc_call(addr, "calc", 41) == 42
    assert grpc_call(addr, "calc", 6, 7, method="mul") == 42
    # errors surface, not hang
    import pytest as _pytest

    with _pytest.raises(RuntimeError, match="serve grpc call failed"):
        grpc_call(addr, "no-such-app", 1)
    # redeploy with a different ingress class: the stale handle cache must heal
    @serve.deployment(num_replicas=1)
    class Calc2:
        def __call__(self, x):
            return x + 2

    serve.delete("calc")
    serve.run(Calc2.bind(), name="calc")
    assert grpc_call(addr, "calc", 40) == 42
    assert start_grpc_proxy(port=0)[1] == port  # get-or-create returns the live port


def test_grpc_user_protobuf_service(rt):
    """Reference proxy.py:523 parity: a USER-DEFINED protobuf service served by
    the gRPC ingress — each RPC routes the typed request message to the
    deployment method of the same name; the app rides call metadata."""
    import grpc

    from ray_tpu.protos import serve_demo_pb2 as pb
    from ray_tpu.protos.serve_demo_pb2_grpc import (
        EchoServiceStub, add_EchoServiceServicer_to_server)

    @serve.deployment(num_replicas=1)
    class Echoer:
        def Echo(self, req):
            return pb.EchoReply(text=f"echo:{req.text}", n=req.n)

        def Double(self, req):
            return pb.EchoReply(text=req.text, n=req.n * 2)

    info = serve.start(grpc_options={
        "port": 0,
        "grpc_servicer_functions": [add_EchoServiceServicer_to_server]})
    serve.run(Echoer.bind(), name="echoer")
    with grpc.insecure_channel(f"127.0.0.1:{info['grpc_port']}") as ch:
        stub = EchoServiceStub(ch)
        # explicit application metadata
        reply = stub.Echo(pb.EchoRequest(text="hi", n=3),
                          metadata=(("application", "echoer"),), timeout=60)
        assert reply.text == "echo:hi" and reply.n == 3
        # single running app: metadata optional
        reply2 = stub.Double(pb.EchoRequest(text="x", n=21), timeout=60)
        assert reply2.n == 42
        # unknown app -> gRPC error status, not a hang
        with pytest.raises(grpc.RpcError):
            stub.Echo(pb.EchoRequest(text="x"),
                      metadata=(("application", "nope"),), timeout=60)
