"""TorchTrainer tests (reference python/ray/train/torch; SURVEY.md §2.4/§3.3).

DDP-correctness anchor: with the gloo group up, gradients allreduce — every
worker ends with identical weights, and the 2-worker DDP run must match a
1-worker run on the same data (averaged gradients)."""
import numpy as np
import pytest

import ray_tpu.train as train
from ray_tpu.train import ScalingConfig, TorchTrainer


def _torch_loop(config):
    import numpy as np
    import torch
    import torch.distributed as dist

    torch.manual_seed(0)  # same init on every worker
    model = torch.nn.Linear(4, 1)
    model = train.torch.prepare_model(model)
    opt = torch.optim.SGD(model.parameters(), lr=0.1)

    ctx = train.get_context()
    rank, world = ctx.get_world_rank(), ctx.get_world_size()
    rng = np.random.default_rng(42)
    X = torch.tensor(rng.normal(size=(16, 4)), dtype=torch.float32)
    y = X.sum(dim=1, keepdim=True)
    # each worker trains on its shard (DDP averages gradients)
    shard_x = X[rank::world]
    shard_y = y[rank::world]

    for _ in range(config["steps"]):
        opt.zero_grad()
        loss = torch.nn.functional.mse_loss(model(shard_x), shard_y)
        loss.backward()
        opt.step()

    w = (model.module if hasattr(model, "module") else model).weight.detach()
    train.report({
        "loss": float(loss),
        "world_size": world,
        "is_ddp": hasattr(model, "module"),
        "dist_initialized": dist.is_initialized(),
        "weights": w.numpy().tolist(),
    })


def test_torch_trainer_ddp_two_workers(rt):
    trainer = TorchTrainer(
        _torch_loop,
        train_loop_config={"steps": 20},
        scaling_config=ScalingConfig(num_workers=2),
    )
    result = trainer.fit()
    m = result.metrics
    assert m["dist_initialized"] and m["is_ddp"] and m["world_size"] == 2
    assert m["loss"] < 0.1


def test_torch_ddp_matches_single_worker(rt):
    results = {}
    for n in (1, 2):
        trainer = TorchTrainer(
            _torch_loop,
            train_loop_config={"steps": 10},
            scaling_config=ScalingConfig(num_workers=n),
        )
        results[n] = trainer.fit().metrics
    # gradient averaging over shards == full-batch gradient: weights must match
    np.testing.assert_allclose(
        np.asarray(results[1]["weights"]), np.asarray(results[2]["weights"]),
        rtol=1e-4, atol=1e-5,
    )