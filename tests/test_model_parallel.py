"""Model-level parallelism correctness: losses under pp/sp/tp sharded configs must match
the plain single-config forward bit-for-bit-ish (f32 tolerances)."""
import jax
import jax.numpy as jnp
import numpy as np

from ray_tpu.models import llama
from ray_tpu.models.config import get_config
from ray_tpu.parallel import local_mesh, use_mesh
from ray_tpu.train import init_state, make_optimizer, make_train_step


def _loss_for(cfg, mesh, tokens):
    tx = make_optimizer(total_steps=10)
    state = init_state(jax.random.PRNGKey(0), cfg, tx, mesh=mesh)
    step = make_train_step(cfg, tx, donate=False)
    with use_mesh(mesh):
        _, metrics = step(state, {"tokens": tokens})
    return float(metrics["loss"]), float(metrics["grad_norm"])


def test_pp_ring_tp_matches_plain():
    tokens = jax.random.randint(jax.random.PRNGKey(7), (4, 33), 0, 256)

    plain_cfg = get_config("test-tiny", dtype="float32")
    plain_mesh = local_mesh(dp=8)
    loss_plain, gn_plain = _loss_for(plain_cfg, plain_mesh, tokens)

    sharded_cfg = get_config(
        "test-tiny", dtype="float32", attention_impl="ring", pipeline_stages=2,
        pipeline_microbatches=2,
    )
    sharded_mesh = local_mesh(pp=2, sp=2, tp=2)
    loss_sharded, gn_sharded = _loss_for(sharded_cfg, sharded_mesh, tokens)

    np.testing.assert_allclose(loss_sharded, loss_plain, rtol=1e-5)
    np.testing.assert_allclose(gn_sharded, gn_plain, rtol=1e-4)


def test_ulysses_in_model():
    tokens = jax.random.randint(jax.random.PRNGKey(8), (4, 33), 0, 256)
    plain_cfg = get_config("test-tiny", dtype="float32")
    loss_plain, _ = _loss_for(plain_cfg, local_mesh(dp=8), tokens)
    uly_cfg = get_config("test-tiny", dtype="float32", attention_impl="ulysses")
    loss_uly, _ = _loss_for(uly_cfg, local_mesh(dp=2, sp=2, tp=2), tokens)
    np.testing.assert_allclose(loss_uly, loss_plain, rtol=1e-5)


def test_pp_moe_matches_plain(monkeypatch):
    """MoE composes with pipeline parallelism: the stage-threaded aux loss (bubble
    ticks masked, psum over stages, mean over microbatches) reproduces the plain
    run's loss exactly. Group size pinned to 32 so microbatch boundaries align
    with dispatch-group boundaries — the two paths then partition tokens
    identically and every capacity decision matches."""
    monkeypatch.setenv("RAY_TPU_MOE_GROUP_SIZE", "32")
    tokens = jax.random.randint(jax.random.PRNGKey(9), (4, 33), 0, 256)

    plain_cfg = get_config("moe-tiny", dtype="float32")
    loss_plain, gn_plain = _loss_for(plain_cfg, local_mesh(dp=4, ep=2), tokens)

    pp_cfg = get_config("moe-tiny", dtype="float32", pipeline_stages=2,
                        pipeline_microbatches=2)
    loss_pp, gn_pp = _loss_for(pp_cfg, local_mesh(pp=2, ep=2, tp=2), tokens)

    np.testing.assert_allclose(loss_pp, loss_plain, rtol=1e-5)
    np.testing.assert_allclose(gn_pp, gn_plain, rtol=1e-4)

    # the aux loss is genuinely nonzero (the fence used to drop it silently)
    cfg = get_config("moe-tiny", dtype="float32", pipeline_stages=2,
                     pipeline_microbatches=2)
    mesh = local_mesh(pp=2, ep=2, tp=2)
    state = init_state(jax.random.PRNGKey(0), cfg, make_optimizer(total_steps=10),
                       mesh=mesh)
    step = make_train_step(cfg, make_optimizer(total_steps=10), donate=False)
    with use_mesh(mesh):
        _, metrics = step(state, {"tokens": tokens})
    assert float(metrics["moe_aux_loss"]) > 0.0


def test_pp_segment_ids_matches_plain():
    """Packed sequences (segment_ids) ride the pipeline as microbatched side
    inputs; pp2(ring)/sp2/tp2 loss matches the plain run on the same packed
    batch. Exercises both the side-input plumbing and local-chunk slicing of
    the packing mask under sp."""
    import jax.numpy as jnp

    tokens = jax.random.randint(jax.random.PRNGKey(11), (4, 33), 0, 256)
    # two packed documents per row: ids 1 then 2
    seg = jnp.concatenate([jnp.full((4, 17), 1, jnp.int32),
                           jnp.full((4, 16), 2, jnp.int32)], axis=1)

    def loss_with_seg(cfg, mesh):
        tx = make_optimizer(total_steps=10)
        state = init_state(jax.random.PRNGKey(0), cfg, tx, mesh=mesh)
        step = make_train_step(cfg, tx, donate=False)
        with use_mesh(mesh):
            _, metrics = step(state, {"tokens": tokens, "segment_ids": seg})
        return float(metrics["loss"])

    plain = loss_with_seg(get_config("test-tiny", dtype="float32"), local_mesh(dp=8))
    pp = loss_with_seg(
        get_config("test-tiny", dtype="float32", attention_impl="ring",
                   pipeline_stages=2, pipeline_microbatches=2),
        local_mesh(pp=2, sp=2, tp=2))
    np.testing.assert_allclose(pp, plain, rtol=1e-5)
    # packing must actually matter (the mask isn't being dropped somewhere)
    tx = make_optimizer(total_steps=10)
    cfg = get_config("test-tiny", dtype="float32")
    state = init_state(jax.random.PRNGKey(0), cfg, tx, mesh=local_mesh(dp=8))
    step = make_train_step(cfg, tx, donate=False)
    with use_mesh(local_mesh(dp=8)):
        _, m_noseg = step(state, {"tokens": tokens})
    assert abs(float(m_noseg["loss"]) - plain) > 1e-7


def test_pp_moe_token_mask_matches_plain(monkeypatch):
    """token_mask (MoE capacity masking for padded batches) rides the pipeline
    as a side input: pp2/ep2 logits match the plain forward bit-for-bit when
    dispatch groups align, and masked tokens genuinely change routing."""
    import jax.numpy as jnp

    from ray_tpu.models import llama as L

    monkeypatch.setenv("RAY_TPU_MOE_GROUP_SIZE", "32")
    cfg_plain = get_config("moe-tiny", dtype="float32", remat=False)
    cfg_pp = get_config("moe-tiny", dtype="float32", remat=False,
                        pipeline_stages=2, pipeline_microbatches=2)
    params = L.init(jax.random.PRNGKey(3), cfg_plain)
    tokens = jax.random.randint(jax.random.PRNGKey(4), (4, 32), 0, 256)
    mask = jnp.ones((4, 32), jnp.float32).at[:, 24:].set(0.0)  # tail padding

    with use_mesh(local_mesh(dp=4, ep=2)):
        ref, _, aux_ref = L.forward(params, tokens, cfg_plain,
                                    token_mask=mask, return_aux=True)
        ref, aux_ref = np.asarray(ref), float(aux_ref)
    with use_mesh(local_mesh(pp=2, ep=2, tp=2)):
        got, _, aux_got = L.forward(params, tokens, cfg_pp,
                                    token_mask=mask, return_aux=True)
        got, aux_got = np.asarray(got), float(aux_got)
    np.testing.assert_allclose(got, ref, rtol=2e-5, atol=2e-5)
    np.testing.assert_allclose(aux_got, aux_ref, rtol=1e-5)


def test_pp_positions_honored():
    """Caller-supplied RoPE position offsets reach pipeline stages (they ride as
    a side input); pp logits match the plain forward at the same offsets."""
    import jax.numpy as jnp

    from ray_tpu.models import llama as L

    cfg_plain = get_config("test-tiny", dtype="float32", remat=False)
    cfg_pp = get_config("test-tiny", dtype="float32", remat=False,
                        pipeline_stages=2, pipeline_microbatches=2)
    params = L.init(jax.random.PRNGKey(5), cfg_plain)
    tokens = jax.random.randint(jax.random.PRNGKey(6), (4, 16), 0, 256)
    # numpy (not jax) array: eager forwards under two different mesh contexts
    # would otherwise pin the first mesh's sharding onto the array. Non-uniform
    # spacing (x2), not a constant offset — RoPE is shift-invariant, so a
    # uniform offset would leave causal attention unchanged and prove nothing.
    pos = np.broadcast_to(np.arange(16, dtype=np.int32)[None, :] * 2,
                          (4, 16)).copy()

    with use_mesh(local_mesh(dp=4, tp=2)):
        ref, _ = L.forward(params, tokens, cfg_plain, positions=pos)
        ref = np.asarray(ref)
        base, _ = L.forward(params, tokens, cfg_plain)
        base = np.asarray(base)
    with use_mesh(local_mesh(pp=2, tp=2, dp=2)):
        got, _ = L.forward(params, tokens, cfg_pp, positions=pos)
        got = np.asarray(got)
    np.testing.assert_allclose(got, ref, rtol=2e-5, atol=2e-5)
    # the offset genuinely changes the result (otherwise this test proves nothing)
    assert np.abs(ref - base).max() > 1e-3
