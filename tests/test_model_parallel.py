"""Model-level parallelism correctness: losses under pp/sp/tp sharded configs must match
the plain single-config forward bit-for-bit-ish (f32 tolerances)."""
import jax
import jax.numpy as jnp
import numpy as np

from ray_tpu.models import llama
from ray_tpu.models.config import get_config
from ray_tpu.parallel import local_mesh, use_mesh
from ray_tpu.train import init_state, make_optimizer, make_train_step


def _loss_for(cfg, mesh, tokens):
    tx = make_optimizer(total_steps=10)
    state = init_state(jax.random.PRNGKey(0), cfg, tx, mesh=mesh)
    step = make_train_step(cfg, tx, donate=False)
    with use_mesh(mesh):
        _, metrics = step(state, {"tokens": tokens})
    return float(metrics["loss"]), float(metrics["grad_norm"])


def test_pp_ring_tp_matches_plain():
    tokens = jax.random.randint(jax.random.PRNGKey(7), (4, 33), 0, 256)

    plain_cfg = get_config("test-tiny", dtype="float32")
    plain_mesh = local_mesh(dp=8)
    loss_plain, gn_plain = _loss_for(plain_cfg, plain_mesh, tokens)

    sharded_cfg = get_config(
        "test-tiny", dtype="float32", attention_impl="ring", pipeline_stages=2,
        pipeline_microbatches=2,
    )
    sharded_mesh = local_mesh(pp=2, sp=2, tp=2)
    loss_sharded, gn_sharded = _loss_for(sharded_cfg, sharded_mesh, tokens)

    np.testing.assert_allclose(loss_sharded, loss_plain, rtol=1e-5)
    np.testing.assert_allclose(gn_sharded, gn_plain, rtol=1e-4)


def test_ulysses_in_model():
    tokens = jax.random.randint(jax.random.PRNGKey(8), (4, 33), 0, 256)
    plain_cfg = get_config("test-tiny", dtype="float32")
    loss_plain, _ = _loss_for(plain_cfg, local_mesh(dp=8), tokens)
    uly_cfg = get_config("test-tiny", dtype="float32", attention_impl="ulysses")
    loss_uly, _ = _loss_for(uly_cfg, local_mesh(dp=2, sp=2, tp=2), tokens)
    np.testing.assert_allclose(loss_uly, loss_plain, rtol=1e-5)


def test_pp_moe_matches_plain(monkeypatch):
    """MoE composes with pipeline parallelism: the stage-threaded aux loss (bubble
    ticks masked, psum over stages, mean over microbatches) reproduces the plain
    run's loss exactly. Group size pinned to 32 so microbatch boundaries align
    with dispatch-group boundaries — the two paths then partition tokens
    identically and every capacity decision matches."""
    monkeypatch.setenv("RAY_TPU_MOE_GROUP_SIZE", "32")
    tokens = jax.random.randint(jax.random.PRNGKey(9), (4, 33), 0, 256)

    plain_cfg = get_config("moe-tiny", dtype="float32")
    loss_plain, gn_plain = _loss_for(plain_cfg, local_mesh(dp=4, ep=2), tokens)

    pp_cfg = get_config("moe-tiny", dtype="float32", pipeline_stages=2,
                        pipeline_microbatches=2)
    loss_pp, gn_pp = _loss_for(pp_cfg, local_mesh(pp=2, ep=2, tp=2), tokens)

    np.testing.assert_allclose(loss_pp, loss_plain, rtol=1e-5)
    np.testing.assert_allclose(gn_pp, gn_plain, rtol=1e-4)

    # the aux loss is genuinely nonzero (the fence used to drop it silently)
    cfg = get_config("moe-tiny", dtype="float32", pipeline_stages=2,
                     pipeline_microbatches=2)
    mesh = local_mesh(pp=2, ep=2, tp=2)
    state = init_state(jax.random.PRNGKey(0), cfg, make_optimizer(total_steps=10),
                       mesh=mesh)
    step = make_train_step(cfg, make_optimizer(total_steps=10), donate=False)
    with use_mesh(mesh):
        _, metrics = step(state, {"tokens": tokens})
    assert float(metrics["moe_aux_loss"]) > 0.0
