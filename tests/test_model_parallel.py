"""Model-level parallelism correctness: losses under pp/sp/tp sharded configs must match
the plain single-config forward bit-for-bit-ish (f32 tolerances)."""
import jax
import jax.numpy as jnp
import numpy as np

from ray_tpu.models import llama
from ray_tpu.models.config import get_config
from ray_tpu.parallel import local_mesh, use_mesh
from ray_tpu.train import init_state, make_optimizer, make_train_step


def _loss_for(cfg, mesh, tokens):
    tx = make_optimizer(total_steps=10)
    state = init_state(jax.random.PRNGKey(0), cfg, tx, mesh=mesh)
    step = make_train_step(cfg, tx, donate=False)
    with use_mesh(mesh):
        _, metrics = step(state, {"tokens": tokens})
    return float(metrics["loss"]), float(metrics["grad_norm"])


def test_pp_ring_tp_matches_plain():
    tokens = jax.random.randint(jax.random.PRNGKey(7), (4, 33), 0, 256)

    plain_cfg = get_config("test-tiny", dtype="float32")
    plain_mesh = local_mesh(dp=8)
    loss_plain, gn_plain = _loss_for(plain_cfg, plain_mesh, tokens)

    sharded_cfg = get_config(
        "test-tiny", dtype="float32", attention_impl="ring", pipeline_stages=2,
        pipeline_microbatches=2,
    )
    sharded_mesh = local_mesh(pp=2, sp=2, tp=2)
    loss_sharded, gn_sharded = _loss_for(sharded_cfg, sharded_mesh, tokens)

    np.testing.assert_allclose(loss_sharded, loss_plain, rtol=1e-5)
    np.testing.assert_allclose(gn_sharded, gn_plain, rtol=1e-4)


def test_ulysses_in_model():
    tokens = jax.random.randint(jax.random.PRNGKey(8), (4, 33), 0, 256)
    plain_cfg = get_config("test-tiny", dtype="float32")
    loss_plain, _ = _loss_for(plain_cfg, local_mesh(dp=8), tokens)
    uly_cfg = get_config("test-tiny", dtype="float32", attention_impl="ulysses")
    loss_uly, _ = _loss_for(uly_cfg, local_mesh(dp=2, sp=2, tp=2), tokens)
    np.testing.assert_allclose(loss_uly, loss_plain, rtol=1e-5)
