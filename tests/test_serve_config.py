"""Declarative serve config tests (reference serve deploy + schema.py)."""
import sys
import types

import pytest

from ray_tpu import serve


def _install_fake_module():
    mod = types.ModuleType("fake_serve_targets")

    @serve.deployment
    class Greeter:
        def __init__(self, greeting="hello"):
            self.greeting = greeting

        def __call__(self, body):
            return f"{self.greeting} {body.get('who', 'world')}"

    mod.app = Greeter.bind()

    def build_app(greeting="hey"):
        return Greeter.options(name="Greeter").bind(greeting)

    mod.build_app = build_app
    mod.Greeter = Greeter
    sys.modules["fake_serve_targets"] = mod
    return mod


def test_apply_config_app_and_builder(rt):
    _install_fake_module()
    config = {
        "applications": [
            {"name": "cfg-app", "route_prefix": "/cfg",
             "import_path": "fake_serve_targets:app"},
            {"name": "cfg-built", "route_prefix": "/built",
             "import_path": "fake_serve_targets:build_app",
             "args": {"greeting": "yo"}},
        ]
    }
    names = serve.apply_config(config)
    try:
        assert names == ["cfg-app", "cfg-built"]
        h = serve.get_app_handle("cfg-app")
        assert h.remote({"who": "cfg"}).result() == "hello cfg"
        h2 = serve.get_app_handle("cfg-built")
        assert h2.remote({}).result() == "yo world"
    finally:
        serve.delete("cfg-app")
        serve.delete("cfg-built")


def test_apply_config_deployment_overrides(rt):
    _install_fake_module()
    config = {
        "applications": [{
            "name": "cfg-ovr", "route_prefix": "/ovr",
            "import_path": "fake_serve_targets:app",
            "deployments": [{"name": "Greeter", "num_replicas": 2}],
        }]
    }
    serve.apply_config(config)
    try:
        import time

        deadline = time.time() + 30
        while time.time() < deadline:
            st = serve.status()
            info = st["cfg-ovr"]["deployments"]["Greeter"]
            if info and info["num_running"] == 2:
                break
            time.sleep(0.2)
        assert info["target_num_replicas"] == 2
    finally:
        serve.delete("cfg-ovr")


def test_apply_config_file_json(rt, tmp_path):
    _install_fake_module()
    import json

    p = tmp_path / "serve.json"
    p.write_text(json.dumps({"applications": [
        {"name": "cfg-file", "route_prefix": "/f",
         "import_path": "fake_serve_targets:app"}]}))
    names = serve.apply_config_file(str(p))
    try:
        assert names == ["cfg-file"]
    finally:
        serve.delete("cfg-file")


def test_declarative_replaces_previous_apps(rt):
    _install_fake_module()
    serve.apply_config({"applications": [
        {"name": "decl-a", "route_prefix": "/a", "import_path": "fake_serve_targets:app"}]})
    serve.apply_config({"applications": [
        {"name": "decl-b", "route_prefix": "/b", "import_path": "fake_serve_targets:app"}]})
    try:
        st = serve.status()
        assert "decl-b" in st and "decl-a" not in st  # config is the full desired state
    finally:
        serve.delete("decl-b")


def test_config_validation_errors(rt):
    _install_fake_module()
    with pytest.raises(ValueError, match="applications"):
        serve.apply_config({"bogus": []})
    with pytest.raises(ValueError, match="route_prefix"):
        serve.apply_config({"applications": [
            {"name": "x", "import_path": "fake_serve_targets:app"},
            {"name": "y", "import_path": "fake_serve_targets:app"},
        ]})  # both default to "/"
    with pytest.raises(ValueError, match="match no deployment"):
        serve.apply_config({"applications": [
            {"name": "z", "route_prefix": "/z",
             "import_path": "fake_serve_targets:app",
             "deployments": [{"name": "Typo", "num_replicas": 2}]},
        ]})


def test_bad_import_paths():
    from ray_tpu.serve.schema import _load_target

    with pytest.raises(ValueError, match="module:attr"):
        _load_target("no_colon_here")
    _install_fake_module()
    with pytest.raises(TypeError, match="neither"):
        _load_target("fake_serve_targets:Greeter")  # a Deployment, not an Application