"""Autoscaler tests against the fake provider (reference:
python/ray/tests/autoscaler/ + fake_multi_node; SURVEY.md §2.10)."""
import time

import pytest

from ray_tpu.autoscaler import (
    Autoscaler,
    AutoscalingConfig,
    FakeNodeProvider,
    NodeType,
)
from ray_tpu.autoscaler.autoscaler import bin_pack


CPU4 = NodeType("cpu-4", {"CPU": 4.0}, max_nodes=5)
SLICE8 = NodeType("v5e-8", {"CPU": 8.0, "TPU": 8.0, "TPU-v5e-8-head": 1.0}, max_nodes=4)


def test_bin_pack_basic():
    # 6 CPUs of demand, empty cluster -> needs two cpu-4 nodes
    out = bin_pack([{"CPU": 3.0}, {"CPU": 3.0}], [CPU4], [])
    assert out == {"cpu-4": 2}
    # fits in existing headroom -> nothing to launch
    out = bin_pack([{"CPU": 3.0}], [CPU4], [{"CPU": 4.0}])
    assert out == {}
    # TPU demand picks the slice type, not the cpu type
    out = bin_pack([{"TPU": 8.0}], [CPU4, SLICE8], [])
    assert out == {"v5e-8": 1}
    # infeasible demand is skipped
    out = bin_pack([{"TPU": 64.0}], [CPU4, SLICE8], [])
    assert out == {}


def test_bin_pack_packs_multiple_small_demands():
    out = bin_pack([{"CPU": 1.0}] * 6, [CPU4], [])
    assert out == {"cpu-4": 2}  # 4 + 2 packed onto two nodes


def test_autoscaler_scales_up_for_pending_tasks(rt):
    provider = FakeNodeProvider([NodeType("big", {"CPU": 4.0, "bigmem": 4.0})])
    scaler = Autoscaler(provider, AutoscalingConfig(idle_timeout_s=3600))

    @rt.remote(resources={"bigmem": 2.0})
    def needs_big():
        return 42

    refs = [needs_big.remote() for _ in range(4)]
    # demand is visible while tasks are unplaceable
    deadline = time.time() + 5
    while time.time() < deadline and not scaler.pending_demands():
        time.sleep(0.05)
    assert scaler.pending_demands(), "pending demand never registered"

    launched = scaler.step()
    assert launched.get("big", 0) >= 1
    scaler.step()  # provider poll: requested -> running joins the cluster
    assert rt.get(refs, timeout=60) == [42, 42, 42, 42]
    # cleanup: drop the extra nodes
    for inst in provider.non_terminated_nodes():
        provider.terminate_node(inst.instance_id)


def test_autoscaler_respects_max_nodes(rt):
    provider = FakeNodeProvider([NodeType("cap", {"CPU": 1.0, "capres": 1.0}, max_nodes=2)])
    scaler = Autoscaler(provider, AutoscalingConfig(idle_timeout_s=3600))

    @rt.remote(resources={"capres": 1.0})
    def f():
        return 1

    refs = [f.remote() for _ in range(5)]  # demand for 5 nodes, cap 2
    deadline = time.time() + 5
    while time.time() < deadline and len(scaler.pending_demands()) < 5:
        time.sleep(0.05)
    scaler.step()
    assert len(provider.non_terminated_nodes()) == 2
    scaler.step()
    assert len(provider.non_terminated_nodes()) == 2  # no over-launch
    # all five eventually run by cycling through the two capped nodes
    assert rt.get(refs, timeout=60) == [1] * 5
    assert len(provider.non_terminated_nodes()) == 2
    for inst in provider.non_terminated_nodes():
        provider.terminate_node(inst.instance_id)


def test_autoscaler_terminates_idle_nodes(rt):
    provider = FakeNodeProvider([NodeType("idle-type", {"idleres": 2.0})])
    scaler = Autoscaler(provider, AutoscalingConfig(idle_timeout_s=0.2))
    provider.create_node("idle-type")
    scaler.step()  # joins cluster
    assert len(provider.non_terminated_nodes()) == 1
    time.sleep(0.3)
    scaler.step()  # idle past timeout -> terminated
    assert len(provider.non_terminated_nodes()) == 0


def test_autoscaler_min_nodes_floor(rt):
    provider = FakeNodeProvider([NodeType("floor", {"floorres": 1.0}, min_nodes=2)])
    scaler = Autoscaler(provider, AutoscalingConfig(idle_timeout_s=3600))
    scaler.step()
    assert len(provider.non_terminated_nodes()) == 2
    for inst in provider.non_terminated_nodes():
        provider.terminate_node(inst.instance_id)


def test_launch_delay_counts_as_pending(rt):
    """Requested-but-not-joined nodes must suppress duplicate launches."""
    provider = FakeNodeProvider([NodeType("slow", {"CPU": 2.0, "slowres": 2.0})],
                                launch_delay_steps=3)
    scaler = Autoscaler(provider, AutoscalingConfig(idle_timeout_s=3600))

    @rt.remote(resources={"slowres": 1.0})
    def g():
        return 7

    ref = g.remote()
    deadline = time.time() + 5
    while time.time() < deadline and not scaler.pending_demands():
        time.sleep(0.05)
    scaler.step()
    assert len(provider.non_terminated_nodes()) == 1
    for _ in range(5):  # while provisioning, no duplicate launch
        scaler.step()
    assert len(provider.non_terminated_nodes()) == 1
    assert rt.get(ref, timeout=60) == 7
    for inst in provider.non_terminated_nodes():
        provider.terminate_node(inst.instance_id)
