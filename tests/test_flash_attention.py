"""Flash attention kernel vs XLA reference, CPU interpret mode."""
import jax
import jax.numpy as jnp
import numpy as np
import pytest

from ray_tpu.ops.attention import attention_reference
from ray_tpu.ops.flash_attention import flash_attention


def _rand(shape, key):
    return jax.random.normal(jax.random.PRNGKey(key), shape, jnp.float32)


@pytest.mark.parametrize("causal", [True, False])
def test_fwd_matches_reference(causal):
    b, s, h, d = 2, 128, 4, 64
    q, k, v = _rand((b, s, h, d), 0), _rand((b, s, h, d), 1), _rand((b, s, h, d), 2)
    out = flash_attention(q, k, v, causal=causal, block_q=64, block_kv=64)
    ref = attention_reference(q, k, v, causal=causal)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), rtol=2e-3, atol=2e-3)


def test_fwd_gqa():
    b, s, h, hkv, d = 1, 128, 8, 2, 64
    q = _rand((b, s, h, d), 0)
    k, v = _rand((b, s, hkv, d), 1), _rand((b, s, hkv, d), 2)
    out = flash_attention(q, k, v, causal=True, block_q=64, block_kv=64)
    ref = attention_reference(q, k, v, causal=True)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), rtol=2e-3, atol=2e-3)


def test_fwd_segment_ids():
    b, s, h, d = 1, 128, 2, 64
    q, k, v = _rand((b, s, h, d), 0), _rand((b, s, h, d), 1), _rand((b, s, h, d), 2)
    seg = jnp.concatenate(
        [jnp.zeros((b, 64), jnp.int32), jnp.ones((b, 64), jnp.int32)], axis=1
    )
    out = flash_attention(q, k, v, causal=True, segment_ids=seg, block_q=64, block_kv=64)
    ref = attention_reference(q, k, v, causal=True, segment_ids=seg)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), rtol=2e-3, atol=2e-3)


@pytest.mark.parametrize("gqa", [False, True])
def test_grads_match_reference(gqa):
    b, s, h, d = 1, 128, 4, 64
    hkv = 2 if gqa else h
    q = _rand((b, s, h, d), 0)
    k, v = _rand((b, s, hkv, d), 1), _rand((b, s, hkv, d), 2)

    def loss_flash(q, k, v):
        return flash_attention(q, k, v, causal=True, block_q=64, block_kv=64).sum()

    def loss_ref(q, k, v):
        return attention_reference(q, k, v, causal=True).sum()

    g_flash = jax.grad(loss_flash, argnums=(0, 1, 2))(q, k, v)
    g_ref = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
    for a, b_ in zip(g_flash, g_ref):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b_), rtol=5e-3, atol=5e-3)
