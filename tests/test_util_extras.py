"""ActorPool, Queue, internal_kv tests (reference ray.util tests)."""
import threading
import time

import pytest

from ray_tpu.util.actor_pool import ActorPool
from ray_tpu.util.queue import Empty, Full, Queue


@pytest.fixture(scope="module")
def pool_actors(rt):
    @rt.remote(num_cpus=0.5)
    class Doubler:
        def double(self, x):
            return x * 2

        def slow_double(self, x):
            time.sleep(0.2 if x == 0 else 0.01)
            return x * 2

    actors = [Doubler.remote() for _ in range(3)]
    yield actors
    for a in actors:
        rt.kill(a)


def test_actor_pool_map_ordered(rt, pool_actors):
    pool = ActorPool(pool_actors)
    out = list(pool.map(lambda a, v: a.double.remote(v), range(10)))
    assert out == [2 * i for i in range(10)]


def test_actor_pool_map_unordered(rt, pool_actors):
    pool = ActorPool(pool_actors)
    out = list(pool.map_unordered(lambda a, v: a.slow_double.remote(v), range(8)))
    assert sorted(out) == [2 * i for i in range(8)]


def test_actor_pool_submit_get_next(rt, pool_actors):
    pool = ActorPool(pool_actors)
    pool.submit(lambda a, v: a.double.remote(v), 5)
    pool.submit(lambda a, v: a.double.remote(v), 6)
    assert pool.has_next()
    assert pool.get_next() == 10
    assert pool.get_next() == 12
    assert not pool.has_next()
    with pytest.raises(StopIteration):
        pool.get_next()


def test_queue_fifo_and_nowait(rt):
    q = Queue(maxsize=2)
    try:
        q.put("a")
        q.put("b")
        with pytest.raises(Full):
            q.put_nowait("c")
        assert q.qsize() == 2 and q.full()
        assert q.get() == "a"
        assert q.get() == "b"
        assert q.empty()
        with pytest.raises(Empty):
            q.get_nowait()
    finally:
        q.shutdown()


def test_queue_cross_task_producer_consumer(rt):
    q = Queue()
    try:
        @rt.remote
        def producer(queue_handle, n):
            for i in range(n):
                queue_handle.put(i)
            return "done"

        ref = producer.remote(q, 5)
        got = [q.get(timeout=30) for _ in range(5)]
        assert got == list(range(5))
        assert rt.get(ref) == "done"
    finally:
        q.shutdown()


def test_actor_pool_get_next_timeout_preserves_state(rt, pool_actors):
    pool = ActorPool(pool_actors)
    pool.submit(lambda a, v: a.slow_double.remote(v), 0)  # the slow one (0.2s)
    with pytest.raises(TimeoutError):
        pool.get_next(timeout=0.01)
    # state intact: retrying yields the result, nothing was lost
    assert pool.get_next(timeout=30) == 0


def test_queue_batch_ops_are_atomic(rt):
    q = Queue(maxsize=4)
    try:
        q.put_nowait_batch([1, 2, 3])
        with pytest.raises(Full):
            q.put_nowait_batch([4, 5])  # would exceed maxsize: nothing inserted
        assert q.qsize() == 3
        with pytest.raises(Empty):
            q.get_nowait_batch(5)  # more than present: nothing consumed
        assert q.qsize() == 3
        assert q.get_nowait_batch(3) == [1, 2, 3]
        assert q.empty()
    finally:
        q.shutdown()


def test_queue_many_blocked_consumers_no_deadlock(rt):
    q = Queue()
    try:
        results = []
        lock = threading.Lock()

        def consumer():
            v = q.get(timeout=30)
            with lock:
                results.append(v)

        threads = [threading.Thread(target=consumer) for _ in range(20)]
        for t in threads:
            t.start()
        time.sleep(0.2)  # all 20 blocked client-side
        for i in range(20):
            q.put(i)
        for t in threads:
            t.join(timeout=30)
        assert sorted(results) == list(range(20))
    finally:
        q.shutdown()


def test_queue_blocking_get_with_timeout(rt):
    q = Queue()
    try:
        t0 = time.monotonic()
        with pytest.raises(Empty):
            q.get(timeout=0.3)
        assert time.monotonic() - t0 >= 0.25
    finally:
        q.shutdown()


def test_internal_kv_driver_and_worker(rt):
    from ray_tpu.experimental import internal_kv as kv

    assert kv._internal_kv_put(b"k1", b"v1")
    assert kv._internal_kv_get(b"k1") == b"v1"
    assert kv._internal_kv_exists(b"k1")
    assert not kv._internal_kv_put(b"k1", b"v2", overwrite=False)
    assert kv._internal_kv_get(b"k1") == b"v1"

    @rt.remote
    def from_worker():
        from ray_tpu.experimental import internal_kv as wkv

        wkv._internal_kv_put(b"k2", b"from-worker", True, "ns")
        return (wkv._internal_kv_get(b"k1"), wkv._internal_kv_list(b"k"))

    v, keys = rt.get(from_worker.remote())
    assert v == b"v1"
    assert b"k1" in keys
    assert kv._internal_kv_get(b"k2", namespace="ns") == b"from-worker"
    assert kv._internal_kv_del(b"k1")
    assert kv._internal_kv_get(b"k1") is None
