"""Model correctness tests on CPU (8 virtual devices)."""
import jax
import jax.numpy as jnp
import numpy as np

from ray_tpu.models import get_config, llama
from ray_tpu.parallel import MeshSpec, build_mesh, use_mesh
from ray_tpu.parallel.sharding import TRAIN_RULES, shard_pytree

CFG = get_config("test-tiny")


def _params():
    return llama.init(jax.random.PRNGKey(0), CFG)


def test_forward_shapes():
    params = _params()
    tokens = jnp.arange(32, dtype=jnp.int32).reshape(2, 16) % CFG.vocab_size
    logits, cache = llama.forward(params, tokens, CFG)
    assert logits.shape == (2, 16, CFG.vocab_size)
    assert cache is None
    assert logits.dtype == jnp.float32


def test_loss_and_grad_finite():
    params = _params()
    tokens = jax.random.randint(jax.random.PRNGKey(1), (2, 17), 0, CFG.vocab_size)
    (loss, aux), grads = jax.value_and_grad(llama.loss_fn, has_aux=True)(
        params, {"tokens": tokens}, CFG
    )
    assert np.isfinite(float(loss))
    flat = jax.tree.leaves(grads)
    assert all(np.all(np.isfinite(np.asarray(g))) for g in flat)
    assert float(loss) > 0


def test_decode_matches_full_forward():
    """Prefill+decode through KV cache must reproduce the full-sequence logits."""
    params = _params()
    tokens = jax.random.randint(jax.random.PRNGKey(2), (1, 12), 0, CFG.vocab_size)
    full_logits, _ = llama.forward(params, tokens, CFG)

    cache = llama.init_kv_cache(CFG, batch=1, max_len=16, dtype=jnp.float32)
    prefill_logits, cache = llama.forward(params, tokens[:, :8], CFG, cache=cache)
    np.testing.assert_allclose(
        np.asarray(prefill_logits), np.asarray(full_logits[:, :8]), rtol=2e-4, atol=2e-4
    )
    # Decode one token at a time.
    for i in range(8, 12):
        step_logits, cache = llama.forward(params, tokens[:, i : i + 1], CFG, cache=cache)
        np.testing.assert_allclose(
            np.asarray(step_logits[:, 0]), np.asarray(full_logits[:, i]), rtol=2e-4, atol=2e-4
        )


def test_segment_ids_isolate_packed_sequences():
    """Packed sequences must not attend across segment boundaries."""
    params = _params()
    a = jax.random.randint(jax.random.PRNGKey(3), (1, 6), 0, CFG.vocab_size)
    b = jax.random.randint(jax.random.PRNGKey(4), (1, 6), 0, CFG.vocab_size)
    packed = jnp.concatenate([a, b], axis=1)
    seg = jnp.concatenate([jnp.zeros((1, 6), jnp.int32), jnp.ones((1, 6), jnp.int32)], axis=1)
    packed_logits, _ = llama.forward(params, packed, CFG, segment_ids=seg)
    solo_logits, _ = llama.forward(params, a, CFG)
    # Segment a inside the pack must match running a alone (positions restart not modeled;
    # use same positions explicitly).
    np.testing.assert_allclose(
        np.asarray(packed_logits[:, :6]), np.asarray(solo_logits), rtol=2e-4, atol=2e-4
    )


def test_sharded_train_step():
    mesh = build_mesh(MeshSpec(dp=2, fsdp=2, tp=2))
    params = _params()
    axes = llama.param_axes(CFG)
    params = shard_pytree(params, axes, mesh, TRAIN_RULES)
    tokens = jax.random.randint(jax.random.PRNGKey(5), (8, 17), 0, CFG.vocab_size)

    @jax.jit
    def step(p, batch):
        (loss, _), grads = jax.value_and_grad(llama.loss_fn, has_aux=True)(p, batch, CFG)
        new_p = jax.tree.map(lambda w, g: w - 1e-3 * g, p, grads)
        return loss, new_p

    with use_mesh(mesh):
        loss, new_params = step(params, {"tokens": tokens})
    assert np.isfinite(float(loss))
    # sgd-updated params keep the parameter shardings (grads get resharded to match)
    w = new_params["layers"]["w_gate"]
    assert w.sharding.spec == params["layers"]["w_gate"].sharding.spec


def test_n_params_reasonable():
    cfg8b = get_config("llama3-8b")
    assert 7.5e9 < cfg8b.n_params < 8.6e9


def test_remat_policies_agree():
    """remat_policy changes scheduling, never math: losses must match exactly."""
    import dataclasses

    import jax

    from ray_tpu.models import get_config
    from ray_tpu.models import llama as ll

    losses = {}
    for pol in ("full", "dots", "dots_no_batch"):
        cfg = dataclasses.replace(get_config("test-tiny"), remat_policy=pol)
        params = ll.init(jax.random.PRNGKey(0), cfg)
        tokens = jax.random.randint(jax.random.PRNGKey(1), (2, 17), 0, cfg.vocab_size)
        loss, _ = ll.loss_fn(params, {"tokens": tokens}, cfg)
        losses[pol] = float(loss)
    assert losses["full"] == losses["dots"] == losses["dots_no_batch"], losses
