"""End-to-end: JaxTrainer driving the real jitted Llama train step on a sharded mesh
inside a worker, with orbax checkpoint save + restore (SURVEY.md §7 phase-3 slice)."""
import numpy as np


def _jax_loop(config):
    # Worker process: CPU platform with a virtual 4-device mesh (env set before jax import).
    import jax

    jax.config.update("jax_platforms", "cpu")

    import ray_tpu.train as train
    from ray_tpu.models.config import get_config
    from ray_tpu.parallel import local_mesh
    from ray_tpu.train import init_state, make_optimizer, make_train_step
    from ray_tpu.train.orbax_utils import load_pytree, save_pytree

    cfg = get_config("test-tiny")
    mesh = local_mesh(dp=2, fsdp=2)
    tx = make_optimizer(total_steps=10)
    state = init_state(jax.random.PRNGKey(0), cfg, tx, mesh=mesh)

    ckpt = train.get_checkpoint()
    if ckpt is not None:
        state = load_pytree(ckpt, target=state)

    step_fn = make_train_step(cfg, tx, donate=False)
    tokens = jax.random.randint(jax.random.PRNGKey(1), (4, 16), 0, cfg.vocab_size)
    losses = []
    for i in range(config["steps"]):
        state, metrics = step_fn(state, {"tokens": tokens})
        losses.append(float(metrics["loss"]))
    import tempfile

    d = tempfile.mkdtemp(prefix="jax_ckpt_")
    save_pytree(state, d)
    train.report(
        {"loss": losses[-1], "losses": losses, "step_count": int(state.step)},
        checkpoint=train.Checkpoint.from_directory(d),
    )


def test_jax_trainer_llama_e2e(rt, tmp_path):
    from ray_tpu.air import RunConfig, ScalingConfig
    from ray_tpu.train import JaxConfig, JaxTrainer

    env = {"XLA_FLAGS": "--xla_force_host_platform_device_count=4", "JAX_PLATFORMS": "cpu"}
    trainer = JaxTrainer(
        _jax_loop,
        train_loop_config={"steps": 2},
        backend_config=JaxConfig(collective_group=False, env=env),
        scaling_config=ScalingConfig(num_workers=1),
        run_config=RunConfig(name="jax_e2e", storage_path=str(tmp_path)),
    )
    result = trainer.fit()
    assert result.error is None, result.error
    assert np.isfinite(result.metrics["loss"])
    assert result.metrics["step_count"] == 2
    assert result.checkpoint is not None

    # Resume: restores the step-2 state and keeps counting.
    trainer2 = JaxTrainer(
        _jax_loop,
        train_loop_config={"steps": 1},
        backend_config=JaxConfig(collective_group=False, env=env),
        scaling_config=ScalingConfig(num_workers=1),
        run_config=RunConfig(name="jax_e2e_resume", storage_path=str(tmp_path)),
        resume_from_checkpoint=result.checkpoint,
    )
    result2 = trainer2.fit()
    assert result2.error is None, result2.error
    assert result2.metrics["step_count"] == 3
    # Loss keeps decreasing across the resume on the same batch.
    assert result2.metrics["loss"] < result.metrics["losses"][0]
