"""Metrics + state API + timeline + dashboard tests (reference: ray.util.metrics,
python/ray/util/state, ray.timeline; SURVEY.md §5)."""
import time

import pytest

from ray_tpu.util import metrics as rm
from ray_tpu.util import state as rs


def test_counter_gauge_histogram_local():
    c = rm.Counter("t_requests", description="reqs", tag_keys=("route",))
    c.inc(1.0, tags={"route": "/a"})
    c.inc(2.0, tags={"route": "/a"})
    c.inc(5.0, tags={"route": "/b"})
    with pytest.raises(ValueError):
        c.inc(-1)
    g = rm.Gauge("t_depth")
    g.set(3.0)
    g.set(7.0)
    h = rm.Histogram("t_lat", boundaries=[0.1, 1.0])
    for v in (0.05, 0.5, 5.0, 0.5):
        h.observe(v)
    merged = rm.merge_snapshots([rm._registry.snapshot()])
    assert merged["t_requests"]["values"][(("route", "/a"),)] == 3.0
    assert merged["t_requests"]["values"][(("route", "/b"),)] == 5.0
    assert merged["t_depth"]["values"][()] == 7.0
    hv = merged["t_lat"]["values"][()]
    assert hv["buckets"] == [1, 2, 1] and hv["count"] == 4
    text = rm.prometheus_text(merged)
    assert 'ray_tpu_t_requests{route="/a"} 3.0' in text
    assert "ray_tpu_t_lat_count 4" in text


def test_merge_across_processes_shapes():
    snap_a = [{"name": "m", "type": "counter", "description": "", "values": {(): 2.0}}]
    snap_b = [{"name": "m", "type": "counter", "description": "", "values": {(): 3.0}}]
    merged = rm.merge_snapshots([snap_a, snap_b])
    assert merged["m"]["values"][()] == 5.0


def test_state_api_lists(rt):
    @rt.remote
    class A:
        def ping(self):
            return "pong"

    a = A.options(name="state-test-actor").remote()
    assert rt.get(a.ping.remote()) == "pong"

    nodes = rs.list_nodes()
    assert len(nodes) >= 1 and nodes[0]["alive"]
    assert nodes[0]["resources_total"]["CPU"] > 0

    actors = rs.list_actors()
    mine = [x for x in actors if x["name"] == "state-test-actor"]
    assert len(mine) == 1 and mine[0]["state"] == "ALIVE"

    workers = rs.list_workers()
    assert any(w["state"] in ("busy", "idle") for w in workers)

    big = rt.put(b"x" * 200_000)
    objs = rs.list_objects()
    assert any(o["size_bytes"] >= 200_000 for o in objs)
    del big

    summary = rs.summarize_cluster()
    assert summary["nodes"] >= 1 and summary["actors"] >= 1
    rt.kill(a)


def test_task_timeline(rt):
    @rt.remote
    def timeline_probe_task(x):
        time.sleep(0.05)
        return x

    rt.get([timeline_probe_task.remote(i) for i in range(3)])
    events = rs.timeline()
    mine = [e for e in events if e["name"] == "timeline_probe_task"]
    assert len(mine) >= 3
    for e in mine:
        assert e["ph"] == "X" and e["dur"] >= 0.04e6


def test_worker_metrics_flow_to_driver(rt):
    @rt.remote
    def emit():
        from ray_tpu.util import metrics as m

        c = m.Counter("t_worker_side")
        c.inc(4.0)
        m._registry._ensure_push_thread()
        # force one immediate push (don't wait for the interval)
        from ray_tpu.core import global_state

        global_state.worker().push_metrics(m._registry.snapshot())
        return True

    assert rt.get(emit.remote())
    deadline = time.time() + 10
    while time.time() < deadline:
        merged = rs.get_metrics()
        if "t_worker_side" in merged:
            assert merged["t_worker_side"]["values"][()] == 4.0
            break
        time.sleep(0.1)
    else:
        raise AssertionError("worker metrics never reached the driver")


def test_dashboard_http(rt):
    import json
    import urllib.request

    from ray_tpu.dashboard import Dashboard

    dash = Dashboard(port=18265)
    try:
        with urllib.request.urlopen("http://127.0.0.1:18265/api/summary", timeout=5) as r:
            summary = json.loads(r.read())
        assert summary["nodes"] >= 1
        with urllib.request.urlopen("http://127.0.0.1:18265/api/nodes", timeout=5) as r:
            nodes = json.loads(r.read())
        assert nodes and nodes[0]["alive"]
        with urllib.request.urlopen("http://127.0.0.1:18265/metrics", timeout=5) as r:
            text = r.read().decode()
        assert "# TYPE" in text or text.strip() == ""
        # human-facing web UI (reference: dashboard React client)
        with urllib.request.urlopen("http://127.0.0.1:18265/", timeout=5) as r:
            assert r.headers.get_content_type() == "text/html"
            html = r.read().decode()
        assert "ray_tpu dashboard" in html and "/api/summary" in html
    finally:
        dash.stop()


def test_worker_stack_dumps(rt):
    """py-spy-analog stack introspection of busy workers."""
    import time

    from ray_tpu.util import state as rs

    @rt.remote
    def busy():
        time.sleep(4.0)
        return 1

    ref = busy.remote()
    time.sleep(0.8)  # let it dispatch and enter the sleep
    stacks = rs.get_worker_stacks(timeout_s=10.0)
    assert "driver" in stacks
    joined = "\n".join(stacks.values())
    assert "_execute_body" in joined or "busy" in joined, list(stacks)[:3]
    assert rt.get(ref) == 1


def test_sampling_profiler(rt):
    """py-spy-record analogue: workers self-sample at hz for duration, collapsed
    stacks name the busy frame; speedscope doc round-trips (reference:
    dashboard/modules/reporter profiling endpoints)."""
    @rt.remote
    def busy(n):
        import math

        s = 0.0
        for i in range(n):
            s += math.sin(i)
        return s

    ref = busy.remote(30_000_000)
    profs = rs.profile_workers(duration_s=1.0, hz=100)
    rt.get(ref)

    assert "driver" in profs
    joined = " ".join(k for counts in profs.values() for k in counts)
    assert "busy" in joined, f"busy frame not sampled: {sorted(profs)}"
    doc = rs.profile_to_speedscope(profs)
    assert doc["profiles"] and doc["shared"]["frames"]
    total = sum(sum(p["weights"]) for p in doc["profiles"])
    assert total >= 10  # 1s at 100hz across >=2 procs


def test_dashboard_profile_endpoint(rt):
    import json
    import urllib.request

    from ray_tpu.dashboard import Dashboard

    dash = Dashboard(port=18267)
    try:
        url = "http://127.0.0.1:18267/api/profile?duration=0.3&hz=50"
        with urllib.request.urlopen(url, timeout=15) as r:
            doc = json.loads(r.read())
        assert doc["$schema"].endswith("file-format-schema.json")
        with urllib.request.urlopen(url + "&format=collapsed", timeout=15) as r:
            profs = json.loads(r.read())
        assert "driver" in profs
    finally:
        dash.stop()


def test_system_prometheus_series(rt):
    """Cluster gauges ride the /metrics exposition next to user metrics
    (reference: ray_nodes / ray_object_store_memory from the dashboard agent)."""
    text = rs.prometheus_metrics()
    assert "ray_tpu_cluster_nodes" in text
    assert "ray_tpu_object_store_num_objects" in text


def test_metrics_provisioning(tmp_path):
    """`ray-tpu metrics launch-config` tree: prometheus.yml scrape config +
    Grafana datasource/dashboard provisioning (reference
    dashboard/modules/metrics file layout)."""
    import json
    import os

    from ray_tpu.metrics_provision import provision

    root = provision(session_dir=str(tmp_path))
    with open(os.path.join(root, "prometheus", "prometheus.yml")) as f:
        prom = json.load(f)
    assert prom["scrape_configs"][0]["static_configs"][0]["targets"]
    ds = os.path.join(root, "grafana", "provisioning", "datasources", "default.yml")
    with open(ds) as f:
        assert json.load(f)["datasources"][0]["type"] == "prometheus"
    dash = os.path.join(root, "grafana", "dashboards", "default_grafana_dashboard.json")
    with open(dash) as f:
        panels = json.load(f)["panels"]
    assert len(panels) >= 6
