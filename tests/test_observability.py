"""Metrics + state API + timeline + dashboard + hot-path telemetry tests
(reference: ray.util.metrics, python/ray/util/state, ray.timeline; SURVEY.md
§5). The telemetry-plane tests (ring recorder, cross-worker chrome trace,
abort counters, queue-depth gauges, cluster_status) are all tier-1: the
marker audit at the bottom asserts none of them is marked slow."""
import time

import pytest

from ray_tpu.util import metrics as rm
from ray_tpu.util import state as rs


def test_counter_gauge_histogram_local():
    c = rm.Counter("t_requests", description="reqs", tag_keys=("route",))
    c.inc(1.0, tags={"route": "/a"})
    c.inc(2.0, tags={"route": "/a"})
    c.inc(5.0, tags={"route": "/b"})
    with pytest.raises(ValueError):
        c.inc(-1)
    g = rm.Gauge("t_depth")
    g.set(3.0)
    g.set(7.0)
    h = rm.Histogram("t_lat", boundaries=[0.1, 1.0])
    for v in (0.05, 0.5, 5.0, 0.5):
        h.observe(v)
    merged = rm.merge_snapshots([rm._registry.snapshot()])
    assert merged["t_requests"]["values"][(("route", "/a"),)] == 3.0
    assert merged["t_requests"]["values"][(("route", "/b"),)] == 5.0
    assert merged["t_depth"]["values"][()] == 7.0
    hv = merged["t_lat"]["values"][()]
    assert hv["buckets"] == [1, 2, 1] and hv["count"] == 4
    text = rm.prometheus_text(merged)
    assert 'ray_tpu_t_requests{route="/a"} 3.0' in text
    assert "ray_tpu_t_lat_count 4" in text


def test_merge_across_processes_shapes():
    snap_a = [{"name": "m", "type": "counter", "description": "", "values": {(): 2.0}}]
    snap_b = [{"name": "m", "type": "counter", "description": "", "values": {(): 3.0}}]
    merged = rm.merge_snapshots([snap_a, snap_b])
    assert merged["m"]["values"][()] == 5.0


def test_state_api_lists(rt):
    @rt.remote
    class A:
        def ping(self):
            return "pong"

    a = A.options(name="state-test-actor").remote()
    assert rt.get(a.ping.remote()) == "pong"

    nodes = rs.list_nodes()
    assert len(nodes) >= 1 and nodes[0]["alive"]
    assert nodes[0]["resources_total"]["CPU"] > 0

    actors = rs.list_actors()
    mine = [x for x in actors if x["name"] == "state-test-actor"]
    assert len(mine) == 1 and mine[0]["state"] == "ALIVE"

    workers = rs.list_workers()
    assert any(w["state"] in ("busy", "idle") for w in workers)

    big = rt.put(b"x" * 200_000)
    objs = rs.list_objects()
    assert any(o["size_bytes"] >= 200_000 for o in objs)
    del big

    summary = rs.summarize_cluster()
    assert summary["nodes"] >= 1 and summary["actors"] >= 1
    rt.kill(a)


def test_task_timeline(rt):
    @rt.remote
    def timeline_probe_task(x):
        time.sleep(0.05)
        return x

    rt.get([timeline_probe_task.remote(i) for i in range(3)])
    events = rs.timeline()
    mine = [e for e in events if e["name"] == "timeline_probe_task"]
    assert len(mine) >= 3
    for e in mine:
        assert e["ph"] == "X" and e["dur"] >= 0.04e6


def test_worker_metrics_flow_to_driver(rt):
    @rt.remote
    def emit():
        from ray_tpu.util import metrics as m

        c = m.Counter("t_worker_side")
        c.inc(4.0)
        m._registry._ensure_push_thread()
        # force one immediate push (don't wait for the interval)
        from ray_tpu.core import global_state

        global_state.worker().push_metrics(m._registry.snapshot())
        return True

    assert rt.get(emit.remote())
    deadline = time.time() + 10
    while time.time() < deadline:
        merged = rs.get_metrics()
        if "t_worker_side" in merged:
            assert merged["t_worker_side"]["values"][()] == 4.0
            break
        time.sleep(0.1)
    else:
        raise AssertionError("worker metrics never reached the driver")


def test_dashboard_http(rt):
    import json
    import urllib.request

    from ray_tpu.dashboard import Dashboard

    dash = Dashboard(port=18265)
    try:
        with urllib.request.urlopen("http://127.0.0.1:18265/api/summary", timeout=5) as r:
            summary = json.loads(r.read())
        assert summary["nodes"] >= 1
        with urllib.request.urlopen("http://127.0.0.1:18265/api/nodes", timeout=5) as r:
            nodes = json.loads(r.read())
        assert nodes and nodes[0]["alive"]
        with urllib.request.urlopen("http://127.0.0.1:18265/metrics", timeout=5) as r:
            text = r.read().decode()
        assert "# TYPE" in text or text.strip() == ""
        # human-facing web UI (reference: dashboard React client)
        with urllib.request.urlopen("http://127.0.0.1:18265/", timeout=5) as r:
            assert r.headers.get_content_type() == "text/html"
            html = r.read().decode()
        assert "ray_tpu dashboard" in html and "/api/summary" in html
    finally:
        dash.stop()


def test_worker_stack_dumps(rt):
    """py-spy-analog stack introspection of busy workers."""
    import time

    from ray_tpu.util import state as rs

    @rt.remote
    def busy():
        time.sleep(4.0)
        return 1

    ref = busy.remote()
    time.sleep(0.8)  # let it dispatch and enter the sleep
    stacks = rs.get_worker_stacks(timeout_s=10.0)
    assert "driver" in stacks
    joined = "\n".join(stacks.values())
    assert "_execute_body" in joined or "busy" in joined, list(stacks)[:3]
    assert rt.get(ref) == 1


def test_sampling_profiler(rt):
    """py-spy-record analogue: workers self-sample at hz for duration, collapsed
    stacks name the busy frame; speedscope doc round-trips (reference:
    dashboard/modules/reporter profiling endpoints)."""
    @rt.remote
    def busy(n):
        import math

        s = 0.0
        for i in range(n):
            s += math.sin(i)
        return s

    ref = busy.remote(30_000_000)
    profs = rs.profile_workers(duration_s=1.0, hz=100)
    rt.get(ref)

    assert "driver" in profs
    joined = " ".join(k for counts in profs.values() for k in counts)
    assert "busy" in joined, f"busy frame not sampled: {sorted(profs)}"
    doc = rs.profile_to_speedscope(profs)
    assert doc["profiles"] and doc["shared"]["frames"]
    total = sum(sum(p["weights"]) for p in doc["profiles"])
    assert total >= 10  # 1s at 100hz across >=2 procs


def test_dashboard_profile_endpoint(rt):
    import json
    import urllib.request

    from ray_tpu.dashboard import Dashboard

    dash = Dashboard(port=18267)
    try:
        url = "http://127.0.0.1:18267/api/profile?duration=0.3&hz=50"
        with urllib.request.urlopen(url, timeout=15) as r:
            doc = json.loads(r.read())
        assert doc["$schema"].endswith("file-format-schema.json")
        with urllib.request.urlopen(url + "&format=collapsed", timeout=15) as r:
            profs = json.loads(r.read())
        assert "driver" in profs
    finally:
        dash.stop()


def test_system_prometheus_series(rt):
    """Cluster gauges ride the /metrics exposition next to user metrics
    (reference: ray_nodes / ray_object_store_memory from the dashboard agent)."""
    text = rs.prometheus_metrics()
    assert "ray_tpu_cluster_nodes" in text
    assert "ray_tpu_object_store_num_objects" in text


def test_metrics_provisioning(tmp_path):
    """`ray-tpu metrics launch-config` tree: prometheus.yml scrape config +
    Grafana datasource/dashboard provisioning (reference
    dashboard/modules/metrics file layout)."""
    import json
    import os

    from ray_tpu.metrics_provision import provision

    root = provision(session_dir=str(tmp_path))
    with open(os.path.join(root, "prometheus", "prometheus.yml")) as f:
        prom = json.load(f)
    assert prom["scrape_configs"][0]["static_configs"][0]["targets"]
    ds = os.path.join(root, "grafana", "provisioning", "datasources", "default.yml")
    with open(ds) as f:
        assert json.load(f)["datasources"][0]["type"] == "prometheus"
    dash = os.path.join(root, "grafana", "dashboards", "default_grafana_dashboard.json")
    with open(dash) as f:
        panels = json.load(f)["panels"]
    assert len(panels) >= 6


# -- hot-path telemetry plane ----------------------------------------------------------

def test_telemetry_ring_bounded_and_drop_logged(caplog):
    """The recorder is bounded memory: overflow drops the oldest events, and
    the loss is reported through the LOGGER at drain (never print(), which
    would corrupt tqdm bars / captured worker stdout)."""
    import logging
    import os

    from ray_tpu.util import telemetry

    os.environ["RAY_TPU_TELEMETRY_RING_SIZE"] = "64"
    telemetry.enable()
    try:
        telemetry.drain()  # start from an empty ring
        for i in range(200):
            telemetry.event("t_ring", "test", i=i)
        assert telemetry.pending() <= 64
        with caplog.at_level(logging.WARNING, logger="ray_tpu.telemetry"):
            events = telemetry.drain()
        assert len(events) <= 64
        # the survivors are the NEWEST events
        assert events[-1]["args"]["i"] == 199
        assert any("dropped" in r.message for r in caplog.records)
    finally:
        os.environ.pop("RAY_TPU_TELEMETRY_RING_SIZE", None)
        telemetry.reset_forced()
        telemetry.drain()


def test_telemetry_disabled_is_noop():
    from ray_tpu.util import telemetry

    telemetry.disable()
    try:
        telemetry.drain()
        with telemetry.span("t_off", "test") as sp:
            assert sp is telemetry._NOOP or sp.__class__.__name__ == "_NoopSpan"
        telemetry.event("t_off_event", "test")
        assert telemetry.pending() == 0
    finally:
        telemetry.reset_forced()


def test_histogram_boundaries_survive_push_roundtrip(rt):
    """Satellite check: a worker-side histogram with CUSTOM boundaries keeps
    them (labels included) through the worker->coordinator delta push and the
    driver-side merge — they are not flattened onto the process-wide default."""
    @rt.remote
    def emit():
        from ray_tpu.core import global_state
        from ray_tpu.util import metrics as m

        h = m.Histogram("t_custom_bounds", boundaries=[0.25, 2.5, 25.0],
                        tag_keys=("stage",))
        h.observe(0.1, tags={"stage": "a"})
        h.observe(3.0, tags={"stage": "a"})
        h.observe(100.0, tags={"stage": "b"})
        global_state.worker().push_metrics(m._registry.snapshot())
        return True

    assert rt.get(emit.remote())
    deadline = time.time() + 10
    merged = {}
    while time.time() < deadline:
        merged = rs.get_metrics()
        if "t_custom_bounds" in merged:
            break
        time.sleep(0.1)
    hm = merged["t_custom_bounds"]
    assert hm["boundaries"] == [0.25, 2.5, 25.0]
    va = hm["values"][(("stage", "a"),)]
    assert va["buckets"] == [1, 0, 1, 0] and va["count"] == 2
    vb = hm["values"][(("stage", "b"),)]
    assert vb["buckets"] == [0, 0, 0, 1]
    # p50 of {0.1, 3.0} interpolates inside the custom buckets, not defaults
    q = rm.histogram_quantile({"boundaries": hm["boundaries"], "values": {
        (): {"buckets": [a + b for a, b in zip(va["buckets"], vb["buckets"])],
             "sum": 0, "count": 3}}}, 0.5)
    assert 0.0 < q <= 25.0


def test_histogram_merge_rebins_on_boundary_mismatch():
    """Two processes registering the SAME histogram name with different
    boundaries must merge without zip-truncation corruption: counts re-bin
    onto the first-seen boundary set, totals preserved exactly."""
    snap_a = [{"name": "h", "type": "histogram", "description": "",
               "boundaries": [1.0, 10.0],
               "values": {(): {"buckets": [2, 3, 1], "sum": 30.0, "count": 6}}}]
    snap_b = [{"name": "h", "type": "histogram", "description": "",
               "boundaries": [0.5, 1.0, 5.0, 10.0, 50.0],
               "values": {(): {"buckets": [1, 1, 2, 0, 1, 1],
                               "sum": 60.0, "count": 6}}}]
    merged = rm.merge_snapshots([snap_a, snap_b])["h"]
    assert merged["boundaries"] == [1.0, 10.0]
    v = merged["values"][()]
    assert sum(v["buckets"]) == 12  # every observation survives the re-bin
    assert v["count"] == 12 and v["sum"] == 90.0
    assert len(v["buckets"]) == 3  # shaped like the kept boundaries


def test_telemetry_chrome_trace_cross_worker(rt):
    """Acceptance: the merged chrome-trace timeline carries spans from >= 2
    worker processes with clock-offset-aligned, monotonic timestamps."""
    from ray_tpu.util import telemetry

    @rt.remote
    def emit_spans(i):
        import os as _os

        from ray_tpu.util import telemetry as t

        t.enable()
        try:
            with t.span("t_worker_span", "test", idx=i, seq=0):
                time.sleep(0.05)
            with t.span("t_worker_span", "test", idx=i, seq=1):
                time.sleep(0.01)
            t.flush()
        finally:
            t.reset_forced()
        return _os.getpid()

    telemetry.enable()
    t0_us = time.time() * 1e6
    try:
        with telemetry.span("t_driver_span", "test"):
            pids = rt.get([emit_spans.remote(i) for i in range(4)], timeout=60)
        assert len(set(pids)) >= 2, f"need >=2 worker processes, got {pids}"

        deadline = time.time() + 15
        mine = []
        while time.time() < deadline:
            events = rs.telemetry_timeline()
            mine = [e for e in events if e["name"] == "t_worker_span"]
            if len({e["pid"] for e in mine}) >= 2 and any(
                    e["name"] == "t_driver_span" for e in events):
                break
            time.sleep(0.2)
        t1_us = time.time() * 1e6
        lanes = {e["pid"] for e in mine}
        assert len(lanes) >= 2, f"spans from one process only: {lanes}"
        by_lane = {}
        for e in mine:
            assert e["ph"] == "X" and e["dur"] >= 0
            # aligned clocks: every worker timestamp lands inside the driver's
            # observation window (generous slack for handshake error)
            assert t0_us - 5e6 <= e["ts"] <= t1_us + 5e6, (e["ts"], t0_us, t1_us)
            by_lane.setdefault((e["pid"], e["tid"]), []).append(e)
        for evs in by_lane.values():
            seqs = [e["args"]["seq"] for e in sorted(evs, key=lambda e: e["ts"])]
            assert seqs == sorted(seqs), "timestamps not monotonic within a lane"
        first = [e for e in mine if e["args"]["seq"] == 0]
        assert all(e["dur"] >= 0.04e6 for e in first)  # the 50ms sleep is visible
    finally:
        telemetry.reset_forced()


def test_collective_abort_counter_and_event(rt):
    """Acceptance: a killed rank increments the collective abort counter and
    the abort event carries group/epoch/failed-rank."""
    from ray_tpu.test_utils import CollectiveRankKiller
    from ray_tpu.util import collective as col
    from ray_tpu.util import telemetry

    @rt.remote(num_cpus=0)
    class Member:
        def __init__(self, rank):
            self.rank = rank

        def _ray_tpu_collective_init(self, world_size, rank, backend, group_name):
            col.init_collective_group(world_size, rank, backend, group_name)

        def timed_allreduce(self, group_name, nelem):
            import numpy as np

            from ray_tpu.util.collective import CollectiveAbortError

            x = np.full((nelem,), float(self.rank + 1), dtype=np.float32)
            try:
                col.allreduce(x, group_name)
                return ("ok", None)
            except CollectiveAbortError as e:
                return ("abort", e.failed_rank)

    def aborts_total():
        merged = rs.get_metrics()
        return sum(merged.get("collective_aborts_total",
                              {}).get("values", {}).values())

    group = "obs_abort"
    members = [Member.remote(i) for i in range(2)]
    telemetry.enable()
    try:
        col.create_collective_group(members, 2, [0, 1], backend="shm",
                                    group_name=group)
        before = aborts_total()
        killer = CollectiveRankKiller(group, rank=1)
        assert killer.registered()
        ref = members[0].timed_allreduce.remote(group, 200_000)
        time.sleep(0.3)
        assert killer.kill()
        status, failed_rank = rt.get(ref, timeout=60)
        assert status == "abort" and failed_rank == 1
        deadline = time.time() + 10
        while time.time() < deadline and aborts_total() <= before:
            time.sleep(0.1)
        assert aborts_total() >= before + 1
        events = [e for e in rs.get_telemetry()
                  if e["name"] == "collective.abort"
                  and e["args"].get("group") == group]
        assert events, "no collective.abort telemetry event recorded"
        ev = events[-1]["args"]
        assert ev["failed_rank"] == 1
        assert isinstance(ev["epoch"], int)
        assert ev["group"] == group
    finally:
        telemetry.reset_forced()
        col.kill_coordinator(group)
        for m in members:
            try:
                rt.kill(m)
            except Exception:
                pass


def test_serve_queue_depth_gauge(rt):
    """Acceptance: the serve_queue_depth gauge tracks in-flight requests
    across concurrent handle.remote() calls, and returns to zero."""
    from ray_tpu import serve

    @serve.deployment
    class Slow:
        def __call__(self, _):
            time.sleep(0.8)
            return "done"

    def depth():
        merged = rs.get_metrics()
        vals = merged.get("serve_queue_depth", {}).get("values", {})
        return max((v for k, v in vals.items()
                    if dict(k).get("app") == "obs-slow"), default=0.0)

    try:
        handle = serve.run(Slow.bind(), name="obs-slow")
        assert handle.remote(None).result() == "done"  # warm the replica
        resps = [handle.remote(None) for _ in range(3)]
        deadline = time.time() + 5
        peak = 0.0
        while time.time() < deadline and peak < 2.0:
            peak = max(peak, depth())
            time.sleep(0.02)
        assert peak >= 2.0, f"gauge never saw concurrent in-flight: {peak}"
        assert [r.result() for r in resps] == ["done"] * 3
        deadline = time.time() + 10
        while time.time() < deadline and depth() > 0:
            time.sleep(0.05)
        assert depth() == 0.0
    finally:
        serve.shutdown()


def test_cluster_status_summary(rt):
    """cluster_status() aggregates the live load signals (the `ray-tpu
    status` payload) and the CLI renderer accepts it."""
    status = rs.cluster_status()
    assert status["cluster"]["nodes"] >= 1
    for section in ("transfer", "collective", "serve", "llm", "train"):
        assert section in status
    assert "aborts" in status["collective"]
    assert "queue_depth" in status["serve"]
    from ray_tpu.scripts.cli import _render_status

    text = _render_status(status)
    assert "cluster" in text and "nodes=" in text


def test_dashboard_history_and_slo_endpoints(rt):
    """Satellite smoke: /api/history and /api/slo respond with well-formed
    JSON (time-series shape; SLO status keyed by name)."""
    import json
    import urllib.request

    from ray_tpu.dashboard import Dashboard
    from ray_tpu.util.slo import SLO
    from ray_tpu.util import slo as slo_mod

    dash = Dashboard(port=18269)
    try:
        slo_mod.register(SLO("dash-smoke", metric="serve_ttft_seconds",
                             objective=0.99, threshold=0.5))
        from ray_tpu.core import global_state

        global_state.try_cluster().slo_engine.evaluate()
        with urllib.request.urlopen(
                "http://127.0.0.1:18269/api/history?window=60", timeout=5) as r:
            hist = json.loads(r.read())
        assert isinstance(hist["ts"], list)
        assert "serve_ttft_p99_s" in hist["series"]
        assert all(len(v) == len(hist["ts"]) for v in hist["series"].values())
        with urllib.request.urlopen("http://127.0.0.1:18269/api/slo",
                                    timeout=5) as r:
            slo_doc = json.loads(r.read())
        assert "dash-smoke" in slo_doc
        assert slo_doc["dash-smoke"]["state"] in ("ok", "burning", "no_data")
        assert slo_doc["dash-smoke"]["objective"] == 0.99
    finally:
        slo_mod.remove("dash-smoke")
        dash.stop()


def test_scrape_overhead_dry_run(tmp_path):
    """CI harness smoke: `core_bench.py --scrape-overhead --dry-run` writes
    the scrape_overhead section without clobbering the telemetry rows."""
    import json
    import os
    import subprocess
    import sys

    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    out = tmp_path / "OBS_BENCH.json"
    with open(out, "w") as f:  # pre-existing telemetry evidence must survive
        json.dump({"rows": {"transfer_10mb_wire": {"overhead_pct": 0.4}}}, f)
    proc = subprocess.run(
        [sys.executable, os.path.join(repo, "core_bench.py"),
         "--scrape-overhead", "--dry-run", "--out", str(out)],
        capture_output=True, text=True, timeout=120,
        env={**os.environ, "JAX_PLATFORMS": "cpu"})
    assert proc.returncode == 0, proc.stderr
    with open(out) as f:
        doc = json.load(f)
    assert doc["scrape_overhead"]["dry_run"] is True
    assert doc["scrape_overhead"]["threshold_pct"] > 0
    assert set(doc["scrape_overhead"]["rows"]) == {"transfer_10mb_wire"}
    assert doc["rows"]["transfer_10mb_wire"]["overhead_pct"] == 0.4


def test_telemetry_overhead_dry_run(tmp_path):
    """CI harness smoke: `core_bench.py --telemetry-overhead --dry-run` must
    be invocable without a cluster and write the OBS_BENCH gate file."""
    import json
    import os
    import subprocess
    import sys

    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    out = tmp_path / "OBS_BENCH.json"
    proc = subprocess.run(
        [sys.executable, os.path.join(repo, "core_bench.py"),
         "--telemetry-overhead", "--dry-run", "--out", str(out)],
        capture_output=True, text=True, timeout=120,
        env={**os.environ, "JAX_PLATFORMS": "cpu"})
    assert proc.returncode == 0, proc.stderr
    with open(out) as f:
        doc = json.load(f)
    assert doc["dry_run"] is True
    assert doc["threshold_pct"] > 0
    assert set(doc["rows"]) == {"transfer_10mb_wire", "allreduce_16mb_w4"}


def test_telemetry_tests_are_tier1():
    """Marker audit: every telemetry test in this module runs under the
    tier-1 `-m 'not slow'` selection (none may be marked slow)."""
    import sys

    mod = sys.modules[__name__]
    offenders = []
    for name in dir(mod):
        if not name.startswith("test_"):
            continue
        fn = getattr(mod, name)
        for mark in getattr(fn, "pytestmark", []):
            if mark.name == "slow":
                offenders.append(name)
    assert not offenders, f"telemetry tests excluded from tier-1: {offenders}"
