"""Cluster TLS (reference python/ray/_private/tls_utils.py:6, RAY_USE_TLS).

With RAY_TPU_USE_TLS set: the head<->agent gRPC channel and the data-plane
listeners run mTLS; a real head + agent + remote task flow works end to end,
and plaintext (or wrong-CA) dials are refused at the handshake.
"""
import os
import socket
import subprocess
import sys
import time

import pytest


@pytest.fixture()
def tls_env(rt, tmp_path):
    """Mint certs, park the session cluster, export the TLS env."""
    import ray_tpu
    from ray_tpu.core.tls_utils import generate_self_signed_tls

    paths = generate_self_signed_tls(str(tmp_path / "tls"))
    ray_tpu.shutdown()
    repo_root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    tls_vars = {
        "RAY_TPU_USE_TLS": "1",
        "RAY_TPU_TLS_CA": paths["ca"],
        "RAY_TPU_TLS_CERT": paths["cert"],
        "RAY_TPU_TLS_KEY": paths["key"],
    }
    env = {**os.environ, **tls_vars,
           "JAX_PLATFORMS": "cpu",
           "PYTHONPATH": repo_root + os.pathsep + os.environ.get("PYTHONPATH", "")}
    os.environ.update(tls_vars)
    procs = []
    try:
        yield env, procs, paths
    finally:
        for p in procs:
            if p.poll() is None:
                p.terminate()
        for p in procs:
            try:
                p.wait(timeout=10)
            except subprocess.TimeoutExpired:
                p.kill()
        for k in tls_vars:
            os.environ.pop(k, None)
        ray_tpu.shutdown()
        ray_tpu.init(num_cpus=4, worker_env={"JAX_PLATFORMS": "cpu"},
                     max_workers_per_node=8)


def test_multihost_flow_with_tls(tls_env):
    """Agent joins over mTLS gRPC; remote tasks + a 10MB data-plane transfer
    run; a plaintext gRPC dial and a plaintext data-plane dial are refused."""
    import numpy as np

    import ray_tpu
    from ray_tpu.core import global_state
    from ray_tpu.core.task_spec import NodeAffinitySchedulingStrategy

    env, procs, _ = tls_env
    ray_tpu.init(num_cpus=2, node_server_port=0,
                 worker_env={"JAX_PLATFORMS": "cpu"}, max_workers_per_node=4)
    cluster = global_state.try_cluster()
    agent = subprocess.Popen(
        [sys.executable, "-m", "ray_tpu.core.node_agent",
         "--address", f"127.0.0.1:{cluster.node_server_port}",
         "--num-cpus", "2"], env=env)
    procs.append(agent)
    deadline = time.time() + 45
    while len([n for n in ray_tpu.nodes() if n["Alive"]]) < 2:
        assert time.time() < deadline, "agent never joined over TLS"
        time.sleep(0.2)
    remote_id = next(n["NodeID"] for n in ray_tpu.nodes()
                     if n["Alive"] and n["Labels"].get("agent") == "remote")
    sched = NodeAffinitySchedulingStrategy(node_id=remote_id)

    @ray_tpu.remote(num_cpus=0.5, scheduling_strategy=sched)
    def touch(x):
        return float(x[0]) + x.nbytes

    ref = ray_tpu.put(np.full(1_310_720, 3.0))  # 10 MiB -> data plane transfer
    assert ray_tpu.get(touch.remote(ref), timeout=120) == 3.0 + 10 * 1024 * 1024

    # -- plaintext refused: gRPC ------------------------------------------------
    import grpc

    from ray_tpu.core.agent_rpc import _METHOD
    from ray_tpu.protos import node_agent_pb2 as pb

    ch = grpc.insecure_channel(f"127.0.0.1:{cluster.node_server_port}")
    call = ch.stream_stream(
        _METHOD, request_serializer=pb.AgentMessage.SerializeToString,
        response_deserializer=pb.HeadMessage.FromString)
    with pytest.raises(grpc.RpcError):
        resp = call(iter([pb.AgentMessage(heartbeat=pb.Heartbeat(time=0.0))]),
                    timeout=5)
        next(resp)
    ch.close()

    # -- plaintext refused: data plane ------------------------------------------
    data_port = cluster._data_server.port
    s = socket.create_connection(("127.0.0.1", data_port), timeout=5)
    s.settimeout(5)
    s.sendall(b"\x00\x00\x00\x04plna")  # junk frame, no TLS handshake
    try:
        got = s.recv(64)
        # a TLS server answers a non-TLS client with an alert then closes;
        # it must NOT speak the data-plane protocol
        assert got == b"" or got[:1] == b"\x15", got  # 0x15 = TLS alert record
    except (TimeoutError, OSError):
        pass  # connection dropped without an answer: also a refusal
    finally:
        s.close()
    ray_tpu.shutdown()


def test_wrong_ca_client_refused(tls_env):
    """A peer with certs from a DIFFERENT CA fails the data-plane handshake."""
    import ssl

    env, procs, _ = tls_env
    from ray_tpu.core.secure_transport import make_listener
    from ray_tpu.core.tls_utils import generate_self_signed_tls

    listener = make_listener(("127.0.0.1", 0))
    port = listener.address[1]
    import threading

    def serve():
        try:
            conn = listener.accept()
            conn.recv_bytes()  # drives the (deferred) server-side handshake
        except EOFError:
            pass  # expected: handshake failure surfaces as a bad dial

    t = threading.Thread(target=serve, daemon=True)
    t.start()

    import tempfile

    with tempfile.TemporaryDirectory() as d:
        other = generate_self_signed_tls(d)
        ctx = ssl.SSLContext(ssl.PROTOCOL_TLS_CLIENT)
        ctx.load_cert_chain(other["cert"], other["key"])
        ctx.load_verify_locations(other["ca"])
        ctx.check_hostname = False
        raw = socket.create_connection(("127.0.0.1", port), timeout=5)
        with pytest.raises(ssl.SSLError):
            ctx.wrap_socket(raw)
        raw.close()
    t.join(timeout=10)
    listener.close()
