"""Cluster TLS (reference python/ray/_private/tls_utils.py:6, RAY_USE_TLS).

With RAY_TPU_USE_TLS set: the head<->agent gRPC channel and the data-plane
listeners run mTLS; a real head + agent + remote task flow works end to end,
and plaintext (or wrong-CA) dials are refused at the handshake.
"""
import os
import socket
import subprocess
import sys
import time

import pytest


@pytest.fixture()
def tls_env(rt, tmp_path):
    """Mint certs, park the session cluster, export the TLS env."""
    import ray_tpu
    from ray_tpu.core.tls_utils import generate_self_signed_tls

    paths = generate_self_signed_tls(str(tmp_path / "tls"))
    ray_tpu.shutdown()
    repo_root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    tls_vars = {
        "RAY_TPU_USE_TLS": "1",
        "RAY_TPU_TLS_CA": paths["ca"],
        "RAY_TPU_TLS_CERT": paths["cert"],
        "RAY_TPU_TLS_KEY": paths["key"],
    }
    env = {**os.environ, **tls_vars,
           "JAX_PLATFORMS": "cpu",
           "PYTHONPATH": repo_root + os.pathsep + os.environ.get("PYTHONPATH", "")}
    os.environ.update(tls_vars)
    procs = []
    try:
        yield env, procs, paths
    finally:
        for p in procs:
            if p.poll() is None:
                p.terminate()
        for p in procs:
            try:
                p.wait(timeout=10)
            except subprocess.TimeoutExpired:
                p.kill()
        for k in tls_vars:
            os.environ.pop(k, None)
        ray_tpu.shutdown()
        ray_tpu.init(num_cpus=4, worker_env={"JAX_PLATFORMS": "cpu"},
                     max_workers_per_node=8)


def test_multihost_flow_with_tls(tls_env):
    """Agent joins over mTLS gRPC; remote tasks + a 10MB data-plane transfer
    run; a plaintext gRPC dial and a plaintext data-plane dial are refused."""
    import numpy as np

    import ray_tpu
    from ray_tpu.core import global_state
    from ray_tpu.core.task_spec import NodeAffinitySchedulingStrategy

    env, procs, _ = tls_env
    ray_tpu.init(num_cpus=2, node_server_port=0,
                 worker_env={"JAX_PLATFORMS": "cpu"}, max_workers_per_node=4)
    cluster = global_state.try_cluster()
    agent = subprocess.Popen(
        [sys.executable, "-m", "ray_tpu.core.node_agent",
         "--address", f"127.0.0.1:{cluster.node_server_port}",
         "--num-cpus", "2"], env=env)
    procs.append(agent)
    deadline = time.time() + 45
    while len([n for n in ray_tpu.nodes() if n["Alive"]]) < 2:
        assert time.time() < deadline, "agent never joined over TLS"
        time.sleep(0.2)
    remote_id = next(n["NodeID"] for n in ray_tpu.nodes()
                     if n["Alive"] and n["Labels"].get("agent") == "remote")
    sched = NodeAffinitySchedulingStrategy(node_id=remote_id)

    @ray_tpu.remote(num_cpus=0.5, scheduling_strategy=sched)
    def touch(x):
        return float(x[0]) + x.nbytes

    ref = ray_tpu.put(np.full(1_310_720, 3.0))  # 10 MiB -> data plane transfer
    assert ray_tpu.get(touch.remote(ref), timeout=120) == 3.0 + 10 * 1024 * 1024

    # -- plaintext refused: gRPC ------------------------------------------------
    import grpc

    from ray_tpu.core.agent_rpc import _METHOD
    from ray_tpu.protos import node_agent_pb2 as pb

    ch = grpc.insecure_channel(f"127.0.0.1:{cluster.node_server_port}")
    call = ch.stream_stream(
        _METHOD, request_serializer=pb.AgentMessage.SerializeToString,
        response_deserializer=pb.HeadMessage.FromString)
    with pytest.raises(grpc.RpcError):
        resp = call(iter([pb.AgentMessage(heartbeat=pb.Heartbeat(time=0.0))]),
                    timeout=5)
        next(resp)
    ch.close()

    # -- plaintext refused: data plane ------------------------------------------
    data_port = cluster._data_server.port
    s = socket.create_connection(("127.0.0.1", data_port), timeout=5)
    s.settimeout(5)
    s.sendall(b"\x00\x00\x00\x04plna")  # junk frame, no TLS handshake
    try:
        got = s.recv(64)
        # a TLS server answers a non-TLS client with an alert then closes;
        # it must NOT speak the data-plane protocol
        assert got == b"" or got[:1] == b"\x15", got  # 0x15 = TLS alert record
    except (TimeoutError, OSError):
        pass  # connection dropped without an answer: also a refusal
    finally:
        s.close()
    ray_tpu.shutdown()


def test_wrong_ca_client_refused(tls_env):
    """A peer with certs from a DIFFERENT CA fails the data-plane handshake."""
    import ssl

    env, procs, _ = tls_env
    from ray_tpu.core.secure_transport import make_listener
    from ray_tpu.core.tls_utils import generate_self_signed_tls

    listener = make_listener(("127.0.0.1", 0))
    port = listener.address[1]
    import threading

    def serve():
        try:
            conn = listener.accept()
            conn.recv_bytes()  # drives the (deferred) server-side handshake
        except EOFError:
            pass  # expected: handshake failure surfaces as a bad dial

    t = threading.Thread(target=serve, daemon=True)
    t.start()

    import tempfile

    with tempfile.TemporaryDirectory() as d:
        other = generate_self_signed_tls(d)
        ctx = ssl.SSLContext(ssl.PROTOCOL_TLS_CLIENT)
        ctx.load_cert_chain(other["cert"], other["key"])
        ctx.load_verify_locations(other["ca"])
        ctx.check_hostname = False
        raw = socket.create_connection(("127.0.0.1", port), timeout=5)
        with pytest.raises(ssl.SSLError):
            ctx.wrap_socket(raw)
        raw.close()
    t.join(timeout=10)
    listener.close()


def test_client_port_tls_and_plaintext_refused(tls_env):
    """The ray-tpu:// client-driver port under RAY_TPU_USE_TLS (VERDICT r4
    item 6; reference: the gRPC client proxy inherits RAY_USE_TLS,
    python/ray/_private/tls_utils.py:68): a TLS client drives the cluster
    end to end; a plaintext mp.connection dial is refused at the handshake."""
    import ray_tpu
    from ray_tpu.util.client import server as client_server

    env, procs, _ = tls_env
    ray_tpu.init(num_cpus=2, client_server_port=0,
                 worker_env={"JAX_PLATFORMS": "cpu"}, max_workers_per_node=4)
    port = client_server._server.port
    # driver in a separate PROCESS over ray-tpu:// with the TLS env
    code = (
        "import ray_tpu\n"
        f"ray_tpu.init(address='ray-tpu://127.0.0.1:{port}')\n"
        "@ray_tpu.remote\n"
        "def double(x):\n"
        "    return 2 * x\n"
        "assert ray_tpu.get(double.remote(21)) == 42\n"
        "print('CLIENT_OK')\n"
    )
    proc = subprocess.run([sys.executable, "-c", code], env=env,
                          capture_output=True, text=True, timeout=120)
    assert proc.returncode == 0, proc.stderr
    assert "CLIENT_OK" in proc.stdout

    # plaintext dial: refused — the server's TLS handshake fails on the mp
    # protocol bytes (or times out waiting for a ClientHello) and closes the
    # socket; the dialer sees EOF/reset, never a served connection. The socket
    # timeout bounds the wait for the server's 15 s handshake deadline.
    from multiprocessing.connection import Client as PlainClient

    from ray_tpu.util.client.server import load_authkey

    prev = socket.getdefaulttimeout()
    socket.setdefaulttimeout(30)
    try:
        with pytest.raises((OSError, EOFError, ConnectionError)):
            PlainClient(("127.0.0.1", port), authkey=load_authkey())
    finally:
        socket.setdefaulttimeout(prev)


def test_serve_ingress_https(tls_env):
    """RAY_TPU_SERVE_INGRESS_TLS: the HTTP proxy serves over TLS with the
    cluster cert (server-side TLS — external clients verify against ca.crt,
    no client cert needed); plain-HTTP requests to the same port fail."""
    import json
    import ssl
    import urllib.request

    import ray_tpu
    from ray_tpu import serve

    env, procs, paths = tls_env
    os.environ["RAY_TPU_SERVE_INGRESS_TLS"] = "1"
    try:
        ray_tpu.init(num_cpus=2, worker_env={"JAX_PLATFORMS": "cpu"},
                     max_workers_per_node=4)

        @serve.deployment(ray_actor_options={"num_cpus": 0.5})
        class Hello:
            def __call__(self, body):
                return {"hello": "tls"}

        serve.start(http_options={"port": 18127})
        serve.run(Hello.bind(), name="tls-app", route_prefix="/hello")
        http_port = 18127
        ctx = ssl.create_default_context(cafile=paths["ca"])
        ctx.check_hostname = False  # cert SANs cover localhost/IPs; belt+braces
        out = json.loads(urllib.request.urlopen(
            f"https://127.0.0.1:{http_port}/hello", context=ctx,
            timeout=30).read())
        assert out == {"hello": "tls"}
        # plain HTTP against the TLS port fails
        with pytest.raises(Exception):
            urllib.request.urlopen(
                f"http://127.0.0.1:{http_port}/hello", timeout=10).read()
        serve.shutdown()
    finally:
        os.environ.pop("RAY_TPU_SERVE_INGRESS_TLS", None)


def test_dashboard_https(tls_env):
    """RAY_TPU_SERVE_INGRESS_TLS also covers the dashboard: /api/summary and
    /metrics serve over TLS with the cluster cert; plain HTTP to the same
    port fails (reference: dashboard behind RAY_USE_TLS)."""
    import json
    import ssl
    import urllib.request

    import ray_tpu
    from ray_tpu.dashboard import Dashboard

    env, procs, paths = tls_env
    os.environ["RAY_TPU_SERVE_INGRESS_TLS"] = "1"
    dash = None
    try:
        ray_tpu.init(num_cpus=2, worker_env={"JAX_PLATFORMS": "cpu"},
                     max_workers_per_node=4)
        dash = Dashboard(port=18269)
        ctx = ssl.create_default_context(cafile=paths["ca"])
        ctx.check_hostname = False
        summary = json.loads(urllib.request.urlopen(
            "https://127.0.0.1:18269/api/summary", context=ctx,
            timeout=30).read())
        assert "nodes" in summary or summary  # state API shape, over TLS
        with pytest.raises(Exception):
            urllib.request.urlopen(
                "http://127.0.0.1:18269/api/summary", timeout=10).read()
    finally:
        os.environ.pop("RAY_TPU_SERVE_INGRESS_TLS", None)
        if dash is not None:
            dash.stop()
