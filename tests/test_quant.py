"""Weight-only int8 serving (W8A16; reference capability: vLLM quantization
pass-through in the serve stack — here native in the JAX engine)."""
import numpy as np
import pytest

import jax
import jax.numpy as jnp

from ray_tpu.models import get_config, llama
from ray_tpu.ops.quant import QTensor, as_weight, dequant, quantize, \
    quantize_llama_params


def test_quantize_dequant_error_bounded():
    w = jax.random.normal(jax.random.PRNGKey(0), (256, 64)) * 0.02
    qt = quantize(w, 0)
    # scales keep the contraction axis as 1 (broadcast-ready for dequant)
    assert qt.q.dtype == jnp.int8 and qt.s.shape == (1, 64)
    back = dequant(qt, jnp.float32)
    # symmetric int8: max error is half a quantization step per channel
    step = np.asarray(qt.s)
    err = np.abs(np.asarray(back) - np.asarray(w))
    assert (err <= 0.5 * step + 1e-8).all()


def test_quantize_expert_stacked_contract_axis():
    """Expert weights [E, d_in, out] quantize over axis 1 with per-(expert,
    out-channel) scales, exactly like vmapping dense per-expert quantization."""
    w = jax.random.normal(jax.random.PRNGKey(2), (4, 32, 16)) * 0.05
    qt = quantize(w, 1)
    assert qt.s.shape == (4, 1, 16)
    per_expert = jax.vmap(lambda e: quantize(e, 0))(w)
    np.testing.assert_array_equal(np.asarray(qt.q), np.asarray(per_expert.q))
    back = dequant(qt, jnp.float32)
    err = np.abs(np.asarray(back) - np.asarray(w))
    assert (err <= 0.5 * np.asarray(qt.s) + 1e-8).all()


def test_as_weight_passthrough():
    w = jnp.ones((4, 4), jnp.float32)
    assert as_weight(w, jnp.bfloat16).dtype == jnp.bfloat16
    assert as_weight(quantize(w, 0), jnp.bfloat16).dtype == jnp.bfloat16


def test_quantized_forward_close_and_greedy_agrees():
    """Logits under int8 weights track fp within tolerance and greedy argmax
    agrees on the overwhelming majority of positions (deterministic seeds)."""
    cfg = get_config("test-tiny", dtype="float32")
    params = llama.init(jax.random.PRNGKey(0), cfg)
    qparams = quantize_llama_params(params)
    # layer matmuls replaced by QTensors, everything else untouched
    assert isinstance(qparams["layers"]["wq"], QTensor)
    assert not isinstance(qparams["layers"]["attn_norm"], QTensor)

    tokens = jax.random.randint(jax.random.PRNGKey(1), (4, 48), 0, 255)
    logits, _ = jax.jit(lambda p, t: llama.forward(p, t, cfg))(params, tokens)
    qlogits, _ = jax.jit(lambda p, t: llama.forward(p, t, cfg))(qparams, tokens)
    lf, lq = np.asarray(logits), np.asarray(qlogits)
    rel = np.abs(lq - lf).max() / (np.abs(lf).max() + 1e-9)
    assert rel < 0.05, f"relative logit error {rel:.4f}"
    agree = (lf.argmax(-1) == lq.argmax(-1)).mean()
    assert agree >= 0.9, f"greedy agreement {agree:.3f}"


def test_engine_int8_generates_and_mostly_matches_bf16():
    from ray_tpu.llm import JaxLLMEngine, LLMConfig, SamplingParams

    params = SamplingParams(max_tokens=8, temperature=0.0, stop_token_ids=[-1])
    prompt = [1, 7, 42, 99, 5]

    base = JaxLLMEngine(LLMConfig(model_id="fp", model_source="test-tiny",
                                  max_num_seqs=2, max_model_len=64,
                                  tokenizer="byte", dtype="float32"))
    base.start()
    try:
        want = base.generate_sync(prompt, params).token_ids
    finally:
        base.shutdown()

    q = JaxLLMEngine(LLMConfig(model_id="q8", model_source="test-tiny",
                               max_num_seqs=2, max_model_len=64,
                               tokenizer="byte", dtype="float32",
                               quantization="int8"))
    q.start()
    try:
        got = q.generate_sync(prompt, params).token_ids
    finally:
        q.shutdown()
    assert len(got) == len(want) == 8
    # greedy under quantization noise on RANDOM weights: require agreement on
    # the first tokens (the trajectory may legitimately fork once logit margins
    # are sub-quantization-step)
    matching = 0
    for a, b in zip(want, got):
        if a != b:
            break
        matching += 1
    assert matching >= 2, (want, got)


def test_engine_rejects_unknown_quantization():
    from ray_tpu.llm import JaxLLMEngine, LLMConfig

    eng = JaxLLMEngine(LLMConfig(model_id="x", model_source="test-tiny",
                                 max_num_seqs=2, max_model_len=64,
                                 tokenizer="byte", quantization="fp4"))
    with pytest.raises(ValueError, match="quantization"):
        eng.start()
    eng.shutdown()


def test_attention_projections_quantize_over_d_in():
    """wq [d_in, heads, head_dim] is rank-3 like an expert stack but must
    quantize over d_in (axis 0) — per-(head, head_dim-unit) scales, NOT
    per-(head-as-expert) (regression: rank-based axis detection)."""
    cfg = get_config("test-tiny", dtype="float32")
    params = llama.init(jax.random.PRNGKey(0), cfg)
    q = quantize_llama_params(params)
    wq = q["layers"]["wq"]  # scanned: [L, d_in, heads, head_dim]
    L, d_in, H, HD = params["layers"]["wq"].shape
    assert wq.s.shape == (L, 1, H, HD), wq.s.shape
    # and MoE expert weights still contract d_in (axis 1 of [E, d_in, out])
    moe_cfg = get_config("moe-tiny")
    moe_params = llama.init(jax.random.PRNGKey(1), moe_cfg)
    mq = quantize_llama_params(moe_params)
    wg = mq["layers"]["w_gate"]  # scanned: [L, E, d_in, d_ff]
    Lm, E, D, F = moe_params["layers"]["w_gate"].shape
    assert wg.s.shape == (Lm, E, 1, F), wg.s.shape
    # attention in the MoE model is dense: contracts d_in
    mwq = mq["layers"]["wq"]
    assert mwq.s.shape[1] == 1, mwq.s.shape
