"""Cluster launcher tests (reference `ray up/down` + fake_multi_node provider)."""
import json
import os

import pytest

from ray_tpu.autoscaler.launcher import ClusterConfig, ClusterLauncher, TPUPodProvider, make_provider
from ray_tpu.autoscaler.node_provider import NodeType


@pytest.fixture(autouse=True)
def _cluster(rt):
    yield


def _config_dict(tmp_path=None, provider=None):
    return {
        "cluster_name": "test-cluster",
        "provider": provider or {"type": "fake"},
        "head_node_type": "head",
        "max_workers": 4,
        "available_node_types": {
            "head": {"resources": {"CPU": 4}, "min_workers": 0, "max_workers": 1},
            "worker": {"resources": {"CPU": 2}, "min_workers": 2, "max_workers": 4},
        },
    }


def test_config_validation():
    with pytest.raises(ValueError, match="missing required"):
        ClusterConfig.from_dict({"cluster_name": "x"})
    bad = _config_dict()
    bad["head_node_type"] = "nope"
    with pytest.raises(ValueError, match="head_node_type"):
        ClusterConfig.from_dict(bad)


def test_yaml_roundtrip(tmp_path):
    import yaml

    path = tmp_path / "cluster.yaml"
    path.write_text(yaml.safe_dump(_config_dict()))
    cfg = ClusterConfig.from_yaml(str(path))
    assert cfg.cluster_name == "test-cluster"
    assert {nt.name for nt in cfg.node_types()} == {"head", "worker"}


def test_up_brings_head_and_min_workers_down_tears_all(rt):
    cfg = ClusterConfig.from_dict(_config_dict())
    launcher = ClusterLauncher(cfg)
    head = launcher.up(start_autoscaler=False)
    nodes = launcher.provider.non_terminated_nodes()
    assert head.node_type == "head"
    by_type = {}
    for n in nodes:
        by_type[n.node_type] = by_type.get(n.node_type, 0) + 1
    assert by_type == {"head": 1, "worker": 2}  # min_workers honored
    assert launcher.down() == 3
    assert launcher.provider.non_terminated_nodes() == []


def test_up_with_autoscaler_loop(rt):
    cfg = ClusterConfig.from_dict(_config_dict())
    launcher = ClusterLauncher(cfg)
    try:
        launcher.up(start_autoscaler=True)
        assert launcher.autoscaler is not None
    finally:
        launcher.down()
        assert launcher.autoscaler is None


def test_tpu_pod_provider_shells_out(tmp_path):
    log = tmp_path / "calls.log"
    provider = TPUPodProvider(
        [NodeType(name="v5e-host", resources={"TPU": 4}, min_nodes=0, max_nodes=2)],
        {
            "create_command": f"echo create {{node_type}} {{instance_id}} >> {log}",
            "terminate_command": f"echo terminate {{instance_id}} >> {log}",
            "terminate_all_command": f"echo terminate-all >> {log}",
        },
    )
    inst = provider.create_node("v5e-host")
    assert len(provider.non_terminated_nodes()) == 1
    provider.terminate_node(inst.instance_id)
    assert provider.non_terminated_nodes() == []
    provider.terminate_all()
    lines = log.read_text().splitlines()
    assert lines[0].startswith("create v5e-host")
    assert lines[1].startswith("terminate v5e-host-1")
    assert lines[2] == "terminate-all"


def test_cli_up_down(rt, tmp_path, monkeypatch):
    import yaml

    from ray_tpu.scripts import cli

    monkeypatch.setenv("RAY_TPU_SESSION_DIR", str(tmp_path))
    path = tmp_path / "cluster.yaml"
    path.write_text(yaml.safe_dump(_config_dict()))
    assert cli.main(["up", str(path), "--no-autoscaler"]) == 0
    assert cli.main(["down", str(path)]) == 0


def test_unknown_provider_raises():
    cfg = ClusterConfig.from_dict(_config_dict(provider={"type": "aws"}))
    with pytest.raises(ValueError, match="unknown provider"):
        make_provider(cfg)


def test_cli_down_adopts_recorded_instances(tmp_path, monkeypatch):
    """A fresh-process down must terminate nodes recorded by up (tpu-pod leak fix)."""
    import yaml

    from ray_tpu.autoscaler.launcher import ClusterConfig, ClusterLauncher

    log = tmp_path / "calls.log"
    cfg = _config_dict(provider={
        "type": "tpu-pod",
        "create_command": f"echo create {{instance_id}} >> {log}",
        "terminate_command": f"echo terminate {{instance_id}} >> {log}",
    })
    cfg["available_node_types"]["worker"]["min_workers"] = 1
    monkeypatch.setenv("RAY_TPU_SESSION_DIR", str(tmp_path))
    path = tmp_path / "cluster.yaml"
    path.write_text(yaml.safe_dump(cfg))
    import json

    from ray_tpu.scripts import cli

    assert cli.main(["up", str(path), "--no-autoscaler"]) == 0
    state = json.loads((tmp_path / "cluster.json").read_text())
    assert len(state["instances"]) == 2  # head + 1 min worker
    # "new process": fresh launcher adopts the recorded instances
    launcher = ClusterLauncher(ClusterConfig.from_dict(cfg))
    launcher.adopt(state["instances"])
    assert launcher.down() == 2
    terminated = [l for l in log.read_text().splitlines() if l.startswith("terminate")]
    assert len(terminated) == 2


def test_gcp_tpu_provider_with_fake_gcloud(tmp_path):
    """First-class GCP TPU-VM provider (reference autoscaler/_private/gcp):
    create/list/delete drive the gcloud CLI; discovery is prefix-scoped JSON so
    the provider only ever adopts its own TPUs."""
    import json as _json
    import os
    import stat

    from ray_tpu.autoscaler.launcher import ClusterConfig, GCPTPUProvider, make_provider

    state = tmp_path / "tpus.json"
    state.write_text("[]")
    shim = tmp_path / "gcloud"
    shim.write_text(f"""#!/usr/bin/env python3
import json, sys
state_path = {str(state)!r}
tpus = json.load(open(state_path))
args = sys.argv[1:]
assert args[:4] == ["compute", "tpus", "tpu-vm", args[3]]
op = args[3]
if op == "create":
    name = args[4]
    assert "--accelerator-type" in args and "--version" in args
    tpus.append({{"name": "projects/p/locations/z/nodes/" + name, "state": "READY"}})
elif op == "delete":
    name = args[4]
    tpus = [t for t in tpus if not t["name"].endswith("/" + name)]
elif op == "list":
    print(json.dumps(tpus))
json.dump(tpus, open(state_path, "w"))
""")
    shim.chmod(shim.stat().st_mode | stat.S_IEXEC)

    cfg = ClusterConfig.from_dict({
        "cluster_name": "g",
        "provider": {"type": "gcp-tpu", "project": "p", "zone": "z",
                     "accelerator_type": "v5litepod-8",
                     "runtime_version": "tpu-ubuntu2204-base",
                     "gcloud_bin": str(shim), "name_prefix": "rtx"},
        "available_node_types": {
            "head": {"resources": {"CPU": 4}, "max_workers": 1},
            "tpu_worker": {"resources": {"CPU": 8, "TPU": 8}, "max_workers": 4},
        },
        "head_node_type": "head",
    })
    provider = make_provider(cfg)
    assert isinstance(provider, GCPTPUProvider)

    a = provider.create_node("tpu_worker")
    b = provider.create_node("tpu_worker")
    # GCP names are RFC1035: underscores sanitized; discovery maps back
    assert a.instance_id.startswith("rtx-tpu-worker-")
    # a foreign TPU in the same zone must be invisible to discovery
    tpus = _json.loads(state.read_text())
    tpus.append({"name": "projects/p/locations/z/nodes/other-team-tpu", "state": "READY"})
    state.write_text(_json.dumps(tpus))

    live = provider.non_terminated_nodes()
    assert {i.instance_id for i in live} == {a.instance_id, b.instance_id}
    assert all(i.node_type == "tpu_worker" for i in live)

    provider.terminate_node(a.instance_id)
    assert {i.instance_id for i in provider.non_terminated_nodes()} == {b.instance_id}
    provider.terminate_all()
    assert provider.non_terminated_nodes() == []
    # the foreign TPU survived our terminate_all
    assert any("other-team-tpu" in t["name"] for t in _json.loads(state.read_text()))


def test_gcp_tpu_provider_validates_config(tmp_path):
    from ray_tpu.autoscaler.launcher import GCPTPUProvider
    from ray_tpu.autoscaler.node_provider import NodeType

    types = [NodeType(name="w", resources={"CPU": 1})]
    with pytest.raises(RuntimeError, match="gcloud"):
        GCPTPUProvider(types, {"gcloud_bin": str(tmp_path / "missing"),
                               "project": "p", "zone": "z",
                               "accelerator_type": "x", "runtime_version": "y"})


# -- provision-failure taxonomy + retry/backoff (reference: autoscaler v2
# instance-manager launch-failure handling; gcp node.py retry loops) ----------

def _retry_shim(tmp_path, fail_times, stderr_msg):
    """gcloud stand-in: fails `create` with stderr_msg the first N calls, then
    succeeds; records every invocation op in calls.log."""
    import stat

    plan = tmp_path / "plan.json"
    plan.write_text(json.dumps({"left": fail_times}))
    calls = tmp_path / "calls.log"
    calls.write_text("")
    shim = tmp_path / "gcloud"
    shim.write_text(f"""#!/usr/bin/env python3
import json, sys
plan_path = {str(plan)!r}
op = sys.argv[4]
with open({str(calls)!r}, "a") as f:
    f.write(op + "\\n")
if op == "list":
    print("[]")
    sys.exit(0)
if op == "create":
    plan = json.load(open(plan_path))
    if plan["left"] > 0:
        plan["left"] -= 1
        json.dump(plan, open(plan_path, "w"))
        sys.stderr.write({stderr_msg!r})
        sys.exit(1)
sys.exit(0)
""")
    shim.chmod(shim.stat().st_mode | stat.S_IEXEC)
    return shim, calls


def _gcp_provider(shim):
    from ray_tpu.autoscaler.launcher import GCPTPUProvider

    return GCPTPUProvider(
        [NodeType(name="tpu_worker", resources={"TPU": 8})],
        {"gcloud_bin": str(shim), "project": "p", "zone": "z",
         "accelerator_type": "v5litepod-8", "runtime_version": "v2",
         "name_prefix": "rtx"})


def test_provision_error_taxonomy():
    from ray_tpu.autoscaler.launcher import classify_provision_error

    cases = {
        "Quota 'TPUV5sLitepodPerProjectPerZone' exceeded": ("quota", False, True),
        "There is no more capacity in the zone \"us-west4-a\"": ("stockout", False, True),
        "ERROR: ZONE_RESOURCE_POOL_EXHAUSTED": ("stockout", False, True),
        "rateLimitExceeded: too many requests": ("rate_limit", True, True),
        # must NOT fall into quota via its "limit exceeded" pattern
        "ERROR: Rate Limit Exceeded": ("rate_limit", True, True),
        "ERROR: gcloud crashed: Deadline Exceeded": ("transient", True, True),
        "HttpError 503 backend error": ("transient", True, True),
        "PERMISSION_DENIED: caller lacks tpu.nodes.create": ("permanent", False, False),
        "Invalid value for field 'acceleratorType'": ("permanent", False, False),
        "gremlins in the datacenter": ("unknown", False, True),
    }
    for stderr, (kind, inline, retryable) in cases.items():
        got = classify_provision_error(stderr)
        assert got[:3] == (kind, inline, retryable), (stderr, got)


def test_gcp_create_retries_transient_inline(tmp_path, monkeypatch):
    monkeypatch.setenv("RAY_TPU_PROVISION_BACKOFF_S", "0.01")
    shim, calls = _retry_shim(tmp_path, fail_times=2,
                              stderr_msg="HttpError 503 backend error")
    provider = _gcp_provider(shim)
    inst = provider.create_node("tpu_worker")
    assert inst.instance_id.startswith("rtx-tpu-worker-")
    assert calls.read_text().split() == ["create"] * 3  # 2 failures + success


def test_gcp_create_quota_escalates_without_inline_retry(tmp_path, monkeypatch):
    from ray_tpu.autoscaler.launcher import NodeLaunchError

    monkeypatch.setenv("RAY_TPU_PROVISION_BACKOFF_S", "0.01")
    shim, calls = _retry_shim(tmp_path, fail_times=99,
                              stderr_msg="Quota 'TPUS_PER_PROJECT' exceeded")
    provider = _gcp_provider(shim)
    with pytest.raises(NodeLaunchError) as ei:
        provider.create_node("tpu_worker")
    assert ei.value.kind == "quota" and ei.value.retryable
    assert ei.value.backoff_hint_s >= 60
    assert calls.read_text().split() == ["create"]  # quota never retries inline


def test_gcp_create_permanent_fails_fast(tmp_path):
    from ray_tpu.autoscaler.launcher import NodeLaunchError

    shim, calls = _retry_shim(tmp_path, fail_times=99,
                              stderr_msg="PERMISSION_DENIED on projects/p")
    provider = _gcp_provider(shim)
    with pytest.raises(NodeLaunchError) as ei:
        provider.create_node("tpu_worker")
    assert ei.value.kind == "permanent" and not ei.value.retryable
    assert calls.read_text().split() == ["create"]


def test_gcp_preempted_nodes_are_reaped(tmp_path):
    """A PREEMPTED TPU of ours is invisible to non_terminated_nodes, reported
    via preempted_nodes, and deleted by poll() so the autoscaler relaunches."""
    import stat

    state = tmp_path / "tpus.json"
    state.write_text(json.dumps([
        {"name": "projects/p/locations/z/nodes/rtx-tpu-worker-1-abc123",
         "state": "PREEMPTED"},
        {"name": "projects/p/locations/z/nodes/rtx-tpu-worker-2-def456",
         "state": "READY"},
    ]))
    shim = tmp_path / "gcloud"
    shim.write_text(f"""#!/usr/bin/env python3
import json, sys
state_path = {str(state)!r}
tpus = json.load(open(state_path))
op = sys.argv[4]
if op == "list":
    print(json.dumps(tpus))
elif op == "delete":
    name = sys.argv[5]
    tpus = [t for t in tpus if not t["name"].endswith("/" + name)]
json.dump(tpus, open(state_path, "w"))
""")
    shim.chmod(shim.stat().st_mode | stat.S_IEXEC)
    provider = _gcp_provider(shim)

    live = provider.non_terminated_nodes()
    assert [i.instance_id for i in live] == ["rtx-tpu-worker-2-def456"]
    assert provider.preempted_nodes() == ["rtx-tpu-worker-1-abc123"]
    provider.poll()
    assert provider.preempted_nodes() == []
    names = [t["name"] for t in json.loads(state.read_text())]
    assert names == ["projects/p/locations/z/nodes/rtx-tpu-worker-2-def456"]


def test_autoscaler_backs_off_failed_node_type(rt, monkeypatch):
    """Quota failures put the node type on capped exponential backoff instead
    of hammering create_node every reconcile tick; success clears it."""
    import time as _time

    from ray_tpu.autoscaler import Autoscaler, AutoscalingConfig, FakeNodeProvider
    from ray_tpu.autoscaler.launcher import NodeLaunchError

    monkeypatch.setenv("RAY_TPU_PROVISION_BACKOFF_S", "0.01")

    class FlakyProvider(FakeNodeProvider):
        def __init__(self):
            super().__init__([NodeType(name="t", resources={"CPU": 1},
                                       min_nodes=1)])
            self.create_calls = 0
            self.fail = True

        def create_node(self, node_type):
            self.create_calls += 1
            if self.fail:
                raise NodeLaunchError("quota exceeded", kind="quota",
                                      retryable=True, backoff_hint_s=0.05)
            return super().create_node(node_type)

    provider = FlakyProvider()
    scaler = Autoscaler(provider, AutoscalingConfig(idle_timeout_s=3600))

    scaler.step()  # min_nodes floor -> first attempt fails
    assert provider.create_calls == 1
    assert "quota" in scaler.launch_failures["t"]
    scaler.step()  # inside the backoff window: no new attempt
    assert provider.create_calls == 1

    _time.sleep(0.06)
    scaler.step()  # window expired -> retry (fails again, backoff doubles)
    assert provider.create_calls == 2

    provider.fail = False
    _time.sleep(0.12)
    scaler.step()  # retry succeeds; failure record cleared
    assert provider.create_calls == 3
    assert "t" not in scaler.launch_failures
    assert len(provider.non_terminated_nodes()) == 1


def test_gcp_preempted_foreign_tpu_never_reaped(tmp_path):
    """The ownership check gates the preemption reaper too: a PREEMPTED TPU
    whose name merely shares our prefix (cluster 'prod' vs 'prod-2') or has an
    unknown node type must never land in the reap set."""
    import stat

    state = tmp_path / "tpus.json"
    state.write_text(json.dumps([
        # shares the "rtx-" prefix but the type segment is not ours
        {"name": "projects/p/locations/z/nodes/rtx-other-team-3-abc123",
         "state": "PREEMPTED"},
        {"name": "projects/p/locations/z/nodes/rtx-tpu-worker-1-def456",
         "state": "PREEMPTED"},
    ]))
    shim = tmp_path / "gcloud"
    shim.write_text(f"""#!/usr/bin/env python3
import json, sys
state_path = {str(state)!r}
tpus = json.load(open(state_path))
op = sys.argv[4]
if op == "list":
    print(json.dumps(tpus))
elif op == "delete":
    name = sys.argv[5]
    tpus = [t for t in tpus if not t["name"].endswith("/" + name)]
json.dump(tpus, open(state_path, "w"))
""")
    shim.chmod(shim.stat().st_mode | stat.S_IEXEC)
    provider = _gcp_provider(shim)
    provider.poll()  # list + reap
    names = [t["name"].rsplit("/", 1)[-1] for t in json.loads(state.read_text())]
    assert names == ["rtx-other-team-3-abc123"]  # ours reaped, foreign kept
