"""Metrics history: windowed rates/quantiles over retained frames, the
label-filtered quantile satellite, and the live head-side scraper
(ISSUE 8 tentpole part 1; util/metrics_history.py)."""
import os
import time

import pytest

from ray_tpu.util import metrics as rm
from ray_tpu.util.metrics_history import MetricsHistory


def _hist_snapshot(name, samples, boundaries, tags=None):
    """Build a merged-metrics dict holding one histogram observed with the
    given samples — the direct-computation side of the bucket-differencing
    acceptance check."""
    h = {"name": name, "type": "histogram", "description": "",
         "boundaries": sorted(boundaries), "values": {}}
    key = tuple(sorted((tags or {}).items()))
    buckets = [0] * (len(boundaries) + 1)
    for v in samples:
        i = 0
        while i < len(boundaries) and v > sorted(boundaries)[i]:
            i += 1
        buckets[i] += 1
    h["values"][key] = {"buckets": buckets, "sum": float(sum(samples)),
                        "count": len(samples)}
    return {name: h}


def _merge_frames(*metric_dicts):
    return rm.merge_snapshots([list(d.values()) for d in metric_dicts])


BOUNDS = [0.01, 0.05, 0.1, 0.5, 1.0]


def test_ring_is_bounded():
    h = MetricsHistory(maxlen=5)
    for i in range(20):
        h.record({}, ts=float(i))
    assert len(h) == 5
    assert [f["ts"] for f in h.frames()] == [15.0, 16.0, 17.0, 18.0, 19.0]


def test_counter_rate_and_delta():
    h = MetricsHistory(maxlen=16)

    def frame(ts, total):
        return h.record({"reqs": {"name": "reqs", "type": "counter",
                                  "description": "",
                                  "values": {(): float(total)}}}, ts=ts)

    frame(0.0, 0)
    frame(10.0, 50)
    frame(20.0, 150)
    # 60s window clamps to the oldest frame: 150 events over 20s
    assert h.delta("reqs", 60.0) == 150.0
    assert h.rate("reqs", 60.0) == pytest.approx(7.5)
    # 10s window differences the last two frames: 100 events over 10s
    assert h.delta("reqs", 10.0) == 100.0
    assert h.rate("reqs", 10.0) == pytest.approx(10.0)
    # unknown metric answers 0-delta (never None once 2 frames exist)
    assert h.delta("nope", 60.0) == 0.0


def test_windowed_quantile_matches_direct_computation():
    """Acceptance: the windowed p99 computed by bucket-DIFFERENCING two
    frames equals histogram_quantile run directly on a histogram holding
    only the window's samples."""
    h = MetricsHistory(maxlen=16)
    before = [0.02] * 40  # lifetime-so-far samples (must NOT leak in)
    window_samples = [0.3] * 30 + [0.7] * 10
    h.record(_hist_snapshot("lat", before, BOUNDS), ts=100.0)
    h.record(_hist_snapshot("lat", before + window_samples, BOUNDS), ts=160.0)

    direct = rm.histogram_quantile(
        _hist_snapshot("lat", window_samples, BOUNDS)["lat"], 0.99)
    windowed = h.quantile("lat", 0.99, 60.0)
    assert windowed == pytest.approx(direct)
    # and the window's count is exactly the injected samples
    diff = h.histogram_delta("lat", 60.0)
    assert sum(v["count"] for v in diff["values"].values()) == len(window_samples)


def test_windowed_p99_tracks_load_shift_lifetime_lags():
    """Satellite: fast-then-slow regime shift. The 60s windowed p99 tracks
    the recent slow regime; the lifetime quantile stays diluted by the fast
    history and lags far below it."""
    h = MetricsHistory(maxlen=64)
    # a long fast history, then a brief slow regime: the slow tail is <1% of
    # lifetime (so the lifetime p99 stays diluted) but 100% of the window
    fast = [0.02] * 10000
    slow = [0.8] * 50
    h.record(_hist_snapshot("lat", fast, BOUNDS), ts=0.0)
    h.record(_hist_snapshot("lat", fast, BOUNDS), ts=60.0)
    h.record(_hist_snapshot("lat", fast + slow, BOUNDS), ts=120.0)

    lifetime = rm.histogram_quantile(
        _hist_snapshot("lat", fast + slow, BOUNDS)["lat"], 0.99)
    windowed = h.quantile("lat", 0.99, 60.0)
    assert windowed > 0.5, f"windowed p99 missed the slow regime: {windowed}"
    assert lifetime < 0.1, f"lifetime p99 unexpectedly jumped: {lifetime}"
    assert windowed > lifetime * 5


def test_histogram_quantile_where_filter():
    """Satellite: the where= label filter quantiles one route's tag set;
    filtered and unfiltered agree when only that tag set exists, and diverge
    once a second route with different latencies lands."""
    one_route = _hist_snapshot("ttft", [0.05] * 10, BOUNDS,
                               tags={"route": "/a"})["ttft"]
    assert (rm.histogram_quantile(one_route, 0.5, where={"route": "/a"})
            == pytest.approx(rm.histogram_quantile(one_route, 0.5)))
    # no tag set matches -> empty -> None
    assert rm.histogram_quantile(one_route, 0.5, where={"route": "/nope"}) is None

    both = _merge_frames(
        _hist_snapshot("ttft", [0.05] * 10, BOUNDS, tags={"route": "/a"}),
        _hist_snapshot("ttft", [0.9] * 10, BOUNDS, tags={"route": "/b"}))["ttft"]
    qa = rm.histogram_quantile(both, 0.5, where={"route": "/a"})
    qb = rm.histogram_quantile(both, 0.5, where={"route": "/b"})
    q_all = rm.histogram_quantile(both, 0.5)
    assert qa < 0.1 < qb
    assert qa < q_all  # the blended quantile sits between the two routes
    assert rm.histogram_quantile(one_route, 0.5) == pytest.approx(qa)


def test_counts_below_interpolates():
    m = _hist_snapshot("lat", [0.3] * 8 + [0.9] * 2, BOUNDS)["lat"]
    good, total = rm.histogram_counts_below(m, 0.5)
    assert total == 10
    assert good == pytest.approx(8.0)  # 0.3s samples sit in (0.1, 0.5]
    good_half, _ = rm.histogram_counts_below(m, 0.3)
    assert 0 < good_half < 8  # interpolated inside the bucket


def test_boundary_drift_rebins_old_frame():
    """A process re-registering the histogram with different boundaries must
    not corrupt the difference: the old frame re-bins onto the new frame's
    boundary set first."""
    h = MetricsHistory(maxlen=8)
    h.record(_hist_snapshot("lat", [0.02] * 5, [0.1, 1.0]), ts=0.0)
    h.record(_hist_snapshot("lat", [0.02] * 5 + [0.3] * 7, BOUNDS), ts=60.0)
    diff = h.histogram_delta("lat", 60.0)
    assert sum(v["count"] for v in diff["values"].values()) == 7


def test_live_scraper_two_frames_and_windowed_p99(rt):
    """Acceptance: after two scrape intervals state.metrics_history() holds
    >=2 frames, and the windowed serve_ttft_seconds p99 (bucket-differenced
    across the injection) matches a direct computation on the injected
    samples."""
    from ray_tpu.core import global_state
    from ray_tpu.util import state as rs
    from ray_tpu.util import telemetry

    os.environ["RAY_TPU_METRICS_SCRAPE_INTERVAL_S"] = "0.2"
    try:
        hist = global_state.try_cluster().metrics_history

        def n_frames():
            return len(rs.metrics_history()["frames"])

        deadline = time.time() + 10
        while time.time() < deadline and n_frames() < 2:
            time.sleep(0.05)
        assert n_frames() >= 2, "scraper produced <2 frames in 10s"

        # baseline frame BEFORE injection, then inject a known sample set
        baseline_n = n_frames()
        baseline_ts = rs.metrics_history()["frames"][-1]["ts"]
        samples = [0.2] * 20 + [0.45] * 19 + [0.9]
        hgram = telemetry.get_histogram(
            "serve_ttft_seconds", "HTTP ingress time-to-first-token/response",
            tag_keys=("route",))
        for s in samples:
            hgram.observe(s, tags={"route": "/hist-test"})
        deadline = time.time() + 10
        while time.time() < deadline and n_frames() < baseline_n + 2:
            time.sleep(0.05)
        doc = rs.metrics_history()
        assert len(doc["frames"]) >= baseline_n + 2

        latest_ts = doc["frames"][-1]["ts"]
        window = latest_ts - baseline_ts  # brackets exactly the injection
        windowed = hist.quantile("serve_ttft_seconds", 0.99, window,
                                 where={"route": "/hist-test"})
        bounds = hist.latest()["metrics"]["serve_ttft_seconds"]["boundaries"]
        direct = rm.histogram_quantile(
            _hist_snapshot("x", samples, bounds)["x"], 0.99)
        assert windowed == pytest.approx(direct), (windowed, direct)
    finally:
        os.environ.pop("RAY_TPU_METRICS_SCRAPE_INTERVAL_S", None)


def test_frame_subscription_guarded_unsubscribe():
    """subscribe_frames: every recorded frame fans out to subscribers on the
    scraper thread; a raising subscriber neither blocks the others nor fails
    record(); unsubscribe stops delivery (ISSUE 15 loop-pacing plumbing)."""
    h = MetricsHistory(maxlen=4)
    seen = []

    def bad(_frame):
        raise RuntimeError("boom")

    unsub_bad = h.subscribe_frames(bad)
    unsub = h.subscribe_frames(lambda f: seen.append(f["ts"]))
    h.record({}, ts=1.0)  # bad subscriber must not block delivery
    assert seen == [1.0]
    unsub_bad()
    unsub()
    h.record({}, ts=2.0)
    assert seen == [1.0]
