"""SLO engine: declarative objectives over the metrics history, multi-window
burn rates, transition callbacks (ISSUE 8 tentpole part 2; util/slo.py)."""
import os
import time

import pytest

from ray_tpu.util.metrics_history import MetricsHistory
from ray_tpu.util.slo import SLO, SLOEngine


def _hist(name, samples, boundaries, tags=None):
    bounds = sorted(boundaries)
    buckets = [0] * (len(bounds) + 1)
    for v in samples:
        i = 0
        while i < len(bounds) and v > bounds[i]:
            i += 1
        buckets[i] += 1
    return {name: {"name": name, "type": "histogram", "description": "",
                   "boundaries": bounds,
                   "values": {tuple(sorted((tags or {}).items())):
                              {"buckets": buckets, "sum": float(sum(samples)),
                               "count": len(samples)}}}}


BOUNDS = [0.01, 0.05, 0.1, 0.5, 1.0]


def test_slo_spec_validation():
    with pytest.raises(ValueError):
        SLO("x", metric="m", objective=1.5)
    with pytest.raises(ValueError):
        SLO("x", metric="m", objective=0.99, kind="nope")
    with pytest.raises(ValueError):
        SLO("x", metric="m", objective=0.99, kind="error_rate")  # no denominator
    s = SLO("ttft", metric="serve_ttft_seconds", objective=0.99, threshold=0.5)
    assert s.budget == pytest.approx(0.01)


def test_latency_slo_ok_then_burning_with_subscriber():
    h = MetricsHistory(maxlen=32)
    eng = SLOEngine(h)
    eng.register(SLO("ttft", metric="lat", objective=0.9, threshold=0.1,
                     window_s=60.0))
    transitions = []
    unsub = eng.subscribe(transitions.append)

    fast = [0.02] * 100
    h.record(_hist("lat", fast, BOUNDS), ts=0.0)
    h.record(_hist("lat", fast + [0.02] * 20, BOUNDS), ts=30.0)
    status = eng.evaluate()
    assert status["ttft"]["state"] == "ok"
    assert status["ttft"]["burn_rate_long"] == pytest.approx(0.0)

    # slow regime: every new sample blows the 100ms threshold
    h.record(_hist("lat", fast + [0.02] * 20 + [0.8] * 50, BOUNDS), ts=60.0)
    status = eng.evaluate()
    assert status["ttft"]["state"] == "burning"
    assert status["ttft"]["burn_rate_long"] > 1.0
    assert status["ttft"]["observed"] > 0.5  # windowed p90 sees the slow tail
    assert [t["to"] for t in transitions] == ["burning"]
    assert transitions[0]["from"] == "ok" and transitions[0]["name"] == "ttft"

    # recovery: a flood of fast samples pushes the windowed bad fraction down
    h.record(_hist("lat", fast + [0.02] * 2000 + [0.8] * 50, BOUNDS), ts=120.0)
    h.record(_hist("lat", fast + [0.02] * 4000 + [0.8] * 50, BOUNDS), ts=150.0)
    status = eng.evaluate()
    assert status["ttft"]["state"] == "ok"
    assert [t["to"] for t in transitions] == ["burning", "ok"]
    unsub()
    h.record(_hist("lat", fast + [0.02] * 4000 + [0.8] * 500, BOUNDS), ts=180.0)
    eng.evaluate()
    assert len(transitions) == 2  # unsubscribed: no more deliveries


def test_error_rate_slo():
    h = MetricsHistory(maxlen=16)
    eng = SLOEngine(h)
    eng.register(SLO("errors", metric="errs", objective=0.95,
                     total_metric="reqs", kind="error_rate", window_s=60.0))

    def frame(ts, errs, reqs):
        h.record({
            "errs": {"name": "errs", "type": "counter", "description": "",
                     "values": {(): float(errs)}},
            "reqs": {"name": "reqs", "type": "counter", "description": "",
                     "values": {(): float(reqs)}},
        }, ts=ts)

    frame(0.0, 0, 0)
    frame(30.0, 1, 100)  # 1% errors, budget 5% -> burn 0.2
    st = eng.evaluate()
    assert st["errors"]["state"] == "ok"
    assert st["errors"]["burn_rate_long"] == pytest.approx(0.2, abs=0.05)
    frame(60.0, 31, 200)  # 30 new errors / 100 new requests -> burn 6
    st = eng.evaluate()
    assert st["errors"]["state"] == "burning"


def test_gauge_saturation_slo():
    h = MetricsHistory(maxlen=16)
    eng = SLOEngine(h)
    eng.register(SLO("queue", metric="depth", objective=0.5, threshold=10.0,
                     kind="gauge", window_s=60.0))

    def frame(ts, depth):
        h.record({"depth": {"name": "depth", "type": "gauge", "description": "",
                            "values": {(): float(depth)}}}, ts=ts)

    for i, d in enumerate([2, 3, 2, 4]):
        frame(i * 10.0, d)
    assert eng.evaluate()["queue"]["state"] == "ok"
    for i, d in enumerate([50, 60, 70, 80]):
        frame(40.0 + i * 10.0, d)
    st = eng.evaluate()
    assert st["queue"]["state"] == "burning"  # most retained frames saturated


def test_no_data_state():
    h = MetricsHistory(maxlen=8)
    eng = SLOEngine(h)
    eng.register(SLO("ttft", metric="lat", objective=0.99, threshold=0.1))
    assert eng.evaluate()["ttft"]["state"] == "no_data"


def test_live_slo_flips_burning_within_one_interval(rt):
    """Acceptance (chaos-style): a TTFT-p99 SLO over the live history flips
    to burning within ~one scrape interval of injected slow requests, and
    subscribe_slo() delivers the transition."""
    from ray_tpu.util import slo as slo_mod
    from ray_tpu.util import state as rs
    from ray_tpu.util import telemetry

    os.environ["RAY_TPU_METRICS_SCRAPE_INTERVAL_S"] = "0.2"
    transitions = []
    unsub = None
    try:
        slo_mod.register(SLO(
            "ttft-p99", metric="serve_ttft_seconds", objective=0.99,
            threshold=0.05, window_s=8.0, where={"route": "/slo-test"}))
        unsub = slo_mod.subscribe_slo(transitions.append)

        # let the engine evaluate the SLO once with no traffic
        deadline = time.time() + 10
        while time.time() < deadline:
            st = rs.slo_status().get("ttft-p99")
            if st is not None:
                assert st["state"] == "no_data"
                break
            time.sleep(0.05)
        else:
            raise AssertionError("SLO never evaluated")

        # inject slow requests: every sample over the 50ms threshold
        hgram = telemetry.get_histogram(
            "serve_ttft_seconds", "HTTP ingress time-to-first-token/response",
            tag_keys=("route",))
        t_inject = time.time()
        for _ in range(30):
            hgram.observe(0.5, tags={"route": "/slo-test"})

        deadline = time.time() + 10
        while time.time() < deadline:
            st = rs.slo_status().get("ttft-p99")
            if st and st["state"] == "burning":
                break
            time.sleep(0.02)
        st = rs.slo_status()["ttft-p99"]
        assert st["state"] == "burning", st
        # "within one scrape interval": generous 5x bound for a loaded box —
        # the mechanism being asserted is frame-granularity detection, and
        # one frame is 0.2s here
        assert time.time() - t_inject < 1.0, "burn detection took >1s at 0.2s scrape"
        assert st["burn_rate_long"] > 1.0
        assert transitions and transitions[-1]["to"] == "burning"
        assert transitions[-1]["name"] == "ttft-p99"
    finally:
        os.environ.pop("RAY_TPU_METRICS_SCRAPE_INTERVAL_S", None)
        if unsub is not None:
            unsub()
        try:
            slo_mod.remove("ttft-p99")
        except Exception:
            pass


def test_bad_subscriber_guarded_and_throttled(caplog):
    """One broken callback must not keep a transition from the other
    subscribers or kill the evaluating (scraper) thread — and its failure
    logs through the shared LogThrottle, one line per window, not per flip."""
    import logging

    h = MetricsHistory(maxlen=32)
    eng = SLOEngine(h)
    eng.register(SLO("ttft", metric="lat", objective=0.9, threshold=0.1,
                     window_s=60.0))
    seen = []

    def bad(_t):
        raise RuntimeError("boom")

    eng.subscribe(bad)
    eng.subscribe(seen.append)
    fast, slow = [0.02] * 100, [0.8] * 50
    h.record(_hist("lat", fast, BOUNDS), ts=0.0)
    h.record(_hist("lat", fast + [0.02] * 20, BOUNDS), ts=30.0)
    with caplog.at_level(logging.WARNING, logger="ray_tpu.slo"):
        eng.evaluate()  # ok (no transition)
        h.record(_hist("lat", fast + [0.02] * 20 + slow, BOUNDS), ts=60.0)
        eng.evaluate()  # -> burning: both subscribers invoked
        h.record(_hist("lat", fast + [0.02] * 4000 + slow, BOUNDS), ts=120.0)
        h.record(_hist("lat", fast + [0.02] * 8000 + slow, BOUNDS), ts=150.0)
        eng.evaluate()  # -> ok: bad raises AGAIN inside the throttle window
    assert [t["to"] for t in seen] == ["burning", "ok"]  # deliveries intact
    warns = [r for r in caplog.records if "slo subscriber" in r.message]
    assert len(warns) == 1  # throttled: one line for two failures
