"""True streaming Data executor: operators pipeline, no inter-stage barrier.

Reference: _internal/execution/streaming_executor.py:52 +
streaming_executor_state.py — downstream operators consume blocks while
upstream operators still produce.
"""
import time

import numpy as np
import pytest

import ray_tpu
import ray_tpu.data as rtd


def _stamp1(batch):
    time.sleep(0.3)
    batch["t1"] = np.full(len(batch["i"]), time.time())
    return batch


def _stamp2(batch):
    batch["t2"] = np.full(len(batch["i"]), time.time())
    return batch


def test_stage2_starts_before_stage1_finishes(rt):
    """Timestamped UDFs prove overlap: some stage-2 processing happens before
    the last stage-1 block is produced (the old executor barriered here)."""
    ds = (
        rtd.from_items([{"i": i} for i in range(8)], parallelism=8)
        # two actor-pool stages never fuse (distinct constructors)
        .map_batches(_stamp1, concurrency=2)
        .map_batches(_stamp2, concurrency=2)
    )
    rows = ds.take_all()
    assert len(rows) == 8
    t1_last = max(r["t1"] for r in rows)
    t2_first = min(r["t2"] for r in rows)
    assert t2_first < t1_last, (
        f"stage 2 never overlapped stage 1 (first t2 {t2_first} >= last t1 {t1_last})"
    )


def test_iter_batches_yields_while_upstream_reads(rt):
    """The first batch arrives in ~one block's latency, not the whole pipeline's."""
    ds = rtd.from_items([{"i": i} for i in range(8)], parallelism=8).map_batches(
        _stamp1, concurrency=1)  # serial stage: full pipeline ~8 x 0.3s
    it = ds.iter_batches(batch_size=None)
    t0 = time.time()
    first = next(iter(it))
    first_latency = time.time() - t0
    assert "t1" in first
    # one block processed (0.3s) + overhead, far below the ~2.4s total
    assert first_latency < 1.8, f"first batch waited for the whole stage ({first_latency:.1f}s)"


def test_take_stops_upstream_work(rt):
    """take(n) consumes lazily: the limit stops pulling and upstream tasks
    beyond the needed blocks never run."""
    ds = rtd.from_items([{"i": i} for i in range(16)], parallelism=16).map_batches(
        _stamp1, concurrency=1)
    t0 = time.time()
    rows = ds.take(1)
    elapsed = time.time() - t0
    assert len(rows) == 1 and "t1" in rows[0]
    # full execution would be ~16 x 0.3s = 4.8s serial; early stop is far under
    assert elapsed < 3.0, f"take(1) executed the whole pipeline ({elapsed:.1f}s)"


def test_streaming_preserves_order_and_results(rt):
    ds = (
        rtd.from_items([{"i": i} for i in range(20)], parallelism=10)
        .map_batches(lambda b: {"i": b["i"], "sq": b["i"] ** 2})
    )
    rows = ds.take_all()
    assert [r["i"] for r in rows] == list(range(20))
    assert all(r["sq"] == r["i"] ** 2 for r in rows)


def test_materialize_then_iterate_still_works(rt):
    ds = rtd.from_items([{"i": i} for i in range(10)]).map_batches(
        lambda b: {"i": b["i"] + 1})
    ds.materialize()
    assert sorted(r["i"] for r in ds.take_all()) == list(range(1, 11))
    # second iteration over the materialized bundles (generators are one-shot)
    assert sorted(r["i"] for r in ds.take_all()) == list(range(1, 11))


def test_early_stop_kills_actor_pool(rt):
    """take() on an actor-pool pipeline must close the execution and free the
    pool (GeneratorExit through every stage's finally)."""

    before = {a["actor_id"] for a in _list_actors()}
    ds = rtd.from_items([{"i": i} for i in range(16)], parallelism=16).map_batches(
        _stamp1, concurrency=2)
    rows = ds.take(1)
    assert len(rows) == 1
    deadline = time.time() + 20
    while True:
        alive_new = [a for a in _list_actors()
                     if a["actor_id"] not in before and a["state"] == "alive"]
        if not alive_new:
            break
        assert time.time() < deadline, f"leaked pool actors: {alive_new}"
        time.sleep(0.2)


def _list_actors():
    from ray_tpu.util.state import list_actors

    return [{"actor_id": a.get("actor_id"), "state": a.get("state")}
            for a in list_actors()]


def test_iterator_reuse_raises(rt):
    ds = rtd.from_items([{"i": i} for i in range(4)]).map_batches(lambda b: b)
    it = ds.iterator()
    assert len(list(it.iter_batches(batch_size=None))) >= 1
    with pytest.raises(RuntimeError, match="already"):
        list(it.iter_batches(batch_size=None))
    # fresh iterators and materialized datasets keep working
    assert len(ds.take_all()) == 4


class _AddOneActor:
    def __call__(self, batch):
        batch["i"] = batch["i"] + 1
        return batch


def test_actor_pools_oversubscribed_no_deadlock(rt):
    """Two actor-pool stages whose requested sizes sum past the cluster's CPUs
    must be budgeted top-down. Pools are created in pull order (downstream
    first) and idle actors hold their CPUs until the pipeline ends, so sizing
    each pool against free-at-creation CPUs leaves the upstream pool's ready()
    barrier waiting forever."""
    ds = (rtd.from_items([{"i": i} for i in range(8)], parallelism=8)
          .map_batches(_AddOneActor, concurrency=4)
          .map_batches(_AddOneActor, concurrency=4))
    rows = sorted(r["i"] for r in ds.take_all())
    assert rows == [i + 2 for i in range(8)]
