"""Parity tests: device `gae_scan` / `vtrace_scan` vs the host-numpy passes.

`gae_scan` must be bit-close (f32) to `connectors.GeneralAdvantageEstimation`
— the existing host advantage pass — across episode boundaries packed into one
block column, truncation bootstraps, and lambda_ in {0, 0.95, 1}.
"""
from __future__ import annotations

import numpy as np
import pytest

from ray_tpu.rllib.connectors import GeneralAdvantageEstimation
from ray_tpu.rllib.core.rl_module import Columns
from ray_tpu.rllib.utils import gae_scan, vtrace_scan

GAMMA = 0.99


class _FakeModule:
    """Deterministic value head so the connector's bootstrap is reproducible."""

    def apply_np(self, params, obs):
        v = obs.reshape(len(obs), -1).astype(np.float32).sum(-1) * 0.1
        return {Columns.VF_PREDS: v}


def _make_episodes(rng, lengths, terminated_flags, obs_dim=4):
    eps = []
    for T, term in zip(lengths, terminated_flags):
        eps.append({
            "obs": rng.standard_normal((T, obs_dim)).astype(np.float32),
            "actions": rng.integers(0, 3, size=T).astype(np.int32),
            Columns.ACTION_LOGP: rng.standard_normal(T).astype(np.float32),
            Columns.VF_PREDS: rng.standard_normal(T).astype(np.float32),
            "rewards": rng.standard_normal(T).astype(np.float32),
            "terminated": bool(term),
            "next_obs_last": rng.standard_normal(obs_dim).astype(np.float32),
        })
    return eps


def _episodes_to_block(episodes, module):
    """Pack episodes back-to-back into one [T_total, 1] block column."""
    rewards, vf, boot, term, trunc = [], [], [], [], []
    for ep in episodes:
        T = len(ep["rewards"])
        v = np.asarray(ep[Columns.VF_PREDS], np.float32)
        rewards.append(np.asarray(ep["rewards"], np.float32))
        vf.append(v)
        if ep["terminated"]:
            bootstrap = 0.0  # gae_scan masks via the terminated flag anyway
        else:
            bootstrap = float(
                module.apply_np(None, ep["next_obs_last"][None])[Columns.VF_PREDS][0])
        boot.append(np.append(v[1:], np.float32(bootstrap)))
        t = np.zeros(T, np.float32)
        tr = np.zeros(T, np.float32)
        (t if ep["terminated"] else tr)[-1] = 1.0
        term.append(t)
        trunc.append(tr)
    col = lambda parts: np.concatenate(parts)[:, None]
    return (col(rewards), col(vf), col(boot), col(term), col(trunc))


@pytest.mark.parametrize("lambda_", [0.0, 0.95, 1.0])
def test_gae_scan_matches_host_connector(lambda_):
    rng = np.random.default_rng(7)
    module = _FakeModule()
    episodes = _make_episodes(
        rng, lengths=[5, 1, 9, 3], terminated_flags=[True, False, False, True])

    host = GeneralAdvantageEstimation(GAMMA, lambda_)(
        episodes, module=module, params=None)

    rewards, vf, boot, term, trunc = _episodes_to_block(episodes, module)
    adv, targets = gae_scan(
        rewards, vf, boot, term, trunc, gamma=GAMMA, lambda_=lambda_)
    adv = np.asarray(adv)[:, 0]
    targets = np.asarray(targets)[:, 0]

    np.testing.assert_allclose(
        targets, host[Columns.VALUE_TARGETS], rtol=1e-5, atol=1e-5)
    adv_std = (adv - adv.mean()) / max(adv.std(), 1e-6)
    np.testing.assert_allclose(
        adv_std, host[Columns.ADVANTAGES], rtol=1e-4, atol=1e-5)


def test_gae_scan_multi_column_episode_boundaries():
    """Independent columns with different internal episode splits."""
    rng = np.random.default_rng(11)
    T, B = 16, 3
    splits = [[6, 10], [16], [4, 4, 8]]
    flags = [[True, False], [False], [False, True, True]]

    rewards = rng.standard_normal((T, B)).astype(np.float32)
    vf = rng.standard_normal((T, B)).astype(np.float32)
    boot = rng.standard_normal((T, B)).astype(np.float32)
    term = np.zeros((T, B), np.float32)
    trunc = np.zeros((T, B), np.float32)
    for b in range(B):
        t = -1
        for L, is_term in zip(splits[b], flags[b]):
            t += L
            (term if is_term else trunc)[t, b] = 1.0
        # interior rows continue the chain: boot[t] must equal vf[t+1]
        for i in range(T - 1):
            if term[i, b] == 0 and trunc[i, b] == 0:
                boot[i, b] = vf[i + 1, b]

    adv, targets = gae_scan(
        rewards, vf, boot, term, trunc, gamma=GAMMA, lambda_=0.95)
    adv = np.asarray(adv)
    targets = np.asarray(targets)

    # host reference: the connector's verbatim per-episode reverse loop
    for b in range(B):
        t0 = 0
        for L, is_term in zip(splits[b], flags[b]):
            seg = slice(t0, t0 + L)
            v = vf[seg, b]
            bootstrap = 0.0 if is_term else boot[t0 + L - 1, b]
            vf_ext = np.append(v, np.float32(bootstrap))
            exp = np.zeros(L, np.float32)
            gae = 0.0
            for t in range(L - 1, -1, -1):
                delta = rewards[t0 + t, b] + GAMMA * vf_ext[t + 1] - vf_ext[t]
                gae = delta + GAMMA * 0.95 * gae
                exp[t] = gae
            np.testing.assert_allclose(adv[seg, b], exp, rtol=1e-5, atol=1e-6)
            np.testing.assert_allclose(
                targets[seg, b], exp + v, rtol=1e-5, atol=1e-6)
            t0 += L


def test_gae_scan_truncation_bootstraps_termination_masks():
    # single row, truncated: adv = r + gamma*boot - v
    adv, targets = gae_scan(
        np.full((1, 1), 1.0, np.float32), np.full((1, 1), 0.5, np.float32),
        np.full((1, 1), 2.0, np.float32), np.zeros((1, 1), np.float32),
        np.ones((1, 1), np.float32), gamma=GAMMA, lambda_=0.95)
    np.testing.assert_allclose(
        np.asarray(adv)[0, 0], 1.0 + GAMMA * 2.0 - 0.5, rtol=1e-6)
    np.testing.assert_allclose(np.asarray(targets)[0, 0],
                               np.asarray(adv)[0, 0] + 0.5, rtol=1e-6)

    # terminated: the bootstrap value must be ignored entirely
    a1, _ = gae_scan(
        np.full((1, 1), 1.0, np.float32), np.full((1, 1), 0.5, np.float32),
        np.full((1, 1), 2.0, np.float32), np.ones((1, 1), np.float32),
        np.zeros((1, 1), np.float32), gamma=GAMMA, lambda_=0.95)
    a2, _ = gae_scan(
        np.full((1, 1), 1.0, np.float32), np.full((1, 1), 0.5, np.float32),
        np.full((1, 1), -37.0, np.float32), np.ones((1, 1), np.float32),
        np.zeros((1, 1), np.float32), gamma=GAMMA, lambda_=0.95)
    np.testing.assert_array_equal(np.asarray(a1), np.asarray(a2))
    np.testing.assert_allclose(np.asarray(a1)[0, 0], 1.0 - 0.5, rtol=1e-6)


def test_vtrace_scan_matches_inline_recursion():
    """Bit-parity with the recursion IMPALALearner previously ran inline."""
    import jax
    import jax.numpy as jnp

    rng = np.random.default_rng(3)
    B, T = 4, 12
    deltas = rng.standard_normal((B, T)).astype(np.float32)
    discounts = (0.99 * rng.integers(0, 2, (B, T))).astype(np.float32)
    cs = rng.uniform(0, 1, (B, T)).astype(np.float32)

    def backward(acc, xs):
        delta_t, disc_t, c_t = xs
        acc = delta_t + disc_t * c_t * acc
        return acc, acc

    _, expected = jax.lax.scan(
        backward, jnp.zeros(B, jnp.float32),
        (deltas.T, discounts.T, cs.T), reverse=True)

    got = vtrace_scan(jnp.asarray(deltas.T), jnp.asarray(discounts.T),
                      jnp.asarray(cs.T))
    np.testing.assert_array_equal(np.asarray(got), np.asarray(expected))
