"""Mesh/sharding layer tests on the 8-device CPU mesh."""
import jax
import numpy as np
import pytest
from jax.sharding import PartitionSpec as P

from ray_tpu.parallel import MeshSpec, build_mesh, named_sharding, use_mesh
from ray_tpu.parallel.sharding import TRAIN_RULES, with_sharding_constraint


def test_mesh_spec_resolve():
    spec = MeshSpec(dp=-1, tp=2).resolve(8)
    assert spec.dp == 4 and spec.tp == 2 and spec.n_devices == 8
    with pytest.raises(ValueError):
        MeshSpec(dp=3).resolve(8)
    with pytest.raises(ValueError):
        MeshSpec(dp=-1, tp=-1).resolve(8)


def test_build_mesh_axes():
    mesh = build_mesh(MeshSpec(dp=2, fsdp=2, tp=2))
    assert mesh.devices.size == 8
    assert mesh.axis_names == ("pp", "dp", "fsdp", "ep", "sp", "tp")


def test_named_sharding_rules():
    mesh = build_mesh(MeshSpec(dp=2, fsdp=2, tp=2))
    s = named_sharding(mesh, "batch", "act_embed")
    assert s.spec == P(("dp", "fsdp"), None)
    s2 = named_sharding(mesh, "embed", "mlp")
    assert s2.spec == P("fsdp", "tp")


def test_sharded_matmul_runs():
    mesh = build_mesh(MeshSpec(dp=2, fsdp=2, tp=2))
    x = jax.device_put(np.ones((8, 16), np.float32), named_sharding(mesh, "batch", "act_embed"))
    w = jax.device_put(np.ones((16, 32), np.float32), named_sharding(mesh, "embed", "mlp"))

    @jax.jit
    def f(x, w):
        y = x @ w
        return with_sharding_constraint(y, "batch", "act_mlp")

    with use_mesh(mesh):
        y = f(x, w)
    assert y.shape == (8, 32)
    np.testing.assert_allclose(np.asarray(y), 16.0)


def test_with_sharding_constraint_noop_outside_mesh():
    x = np.ones((4, 4), np.float32)
    y = with_sharding_constraint(jax.numpy.asarray(x), "batch", "embed")
    assert y.shape == (4, 4)
