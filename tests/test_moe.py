"""MoE / expert-parallel tests (reference gap: SURVEY.md §2.3 "EP/MoE absent —
must be built natively"). Correctness anchor: a 1-expert MoE with capacity >= T
must reproduce the dense MLP exactly."""
import jax
import jax.numpy as jnp
import numpy as np
import pytest

from ray_tpu.models import llama, moe
from ray_tpu.models.config import ModelConfig, get_config

CFG = get_config("moe-tiny")


def test_single_expert_equals_dense():
    """E=1, top-1, capacity >= tokens: the routed path must equal a plain SwiGLU."""
    cfg = ModelConfig(
        name="m1", vocab_size=64, d_model=32, n_layers=1, n_heads=2, n_kv_heads=2,
        d_ff=48, dtype="float32", n_experts=1, moe_top_k=1,
        moe_capacity_factor=4.0, moe_aux_loss_coef=0.0,
    )
    key = jax.random.PRNGKey(0)
    x = jax.random.normal(key, (10, cfg.d_model), jnp.float32)
    w = moe.init_expert_weights(jax.random.PRNGKey(1), cfg)
    y, aux = moe.moe_mlp(x, w["router"], w["w_gate"], w["w_up"], w["w_down"], cfg)
    dense = jnp.einsum(
        "tf,fd->td",
        jax.nn.silu(x @ w["w_gate"][0]) * (x @ w["w_up"][0]),
        w["w_down"][0],
    )
    np.testing.assert_allclose(np.asarray(y), np.asarray(dense), rtol=1e-5, atol=1e-5)
    assert float(aux) == 0.0


def test_moe_forward_and_loss():
    params = llama.init(jax.random.PRNGKey(0), CFG)
    tokens = jax.random.randint(jax.random.PRNGKey(1), (2, 16), 0, CFG.vocab_size)
    logits, cache, aux = llama.forward(params, tokens, CFG, return_aux=True)
    assert logits.shape == (2, 16, CFG.vocab_size)
    assert np.isfinite(np.asarray(logits)).all()
    assert float(aux) > 0.0  # load-balancing loss engaged
    loss, metrics = llama.loss_fn(params, {"tokens": tokens}, CFG)
    assert np.isfinite(float(loss))
    assert float(metrics["moe_aux_loss"]) > 0.0
    assert abs(float(metrics["ce_loss"]) + float(metrics["moe_aux_loss"])
               - float(loss)) < 1e-5


def test_moe_gradients_flow_to_experts():
    params = llama.init(jax.random.PRNGKey(0), CFG)
    tokens = jax.random.randint(jax.random.PRNGKey(1), (2, 16), 0, CFG.vocab_size)

    def loss(p):
        return llama.loss_fn(p, {"tokens": tokens}, CFG)[0]

    grads = jax.grad(loss)(params)
    for name in ("router", "w_gate", "w_up", "w_down"):
        g = np.asarray(grads["layers"][name])
        assert np.isfinite(g).all()
        assert np.abs(g).sum() > 0.0, f"no gradient reached {name}"


def test_moe_capacity_overflow_is_graceful():
    cfg = ModelConfig(
        name="mo", vocab_size=64, d_model=32, n_layers=1, n_heads=2, n_kv_heads=2,
        d_ff=48, dtype="float32", n_experts=2, moe_top_k=2,
        moe_capacity_factor=0.1,  # force heavy token dropping
    )
    w = moe.init_expert_weights(jax.random.PRNGKey(1), cfg)
    x = jax.random.normal(jax.random.PRNGKey(0), (64, cfg.d_model), jnp.float32)
    y, aux = moe.moe_mlp(x, w["router"], w["w_gate"], w["w_up"], w["w_down"], cfg)
    assert np.isfinite(np.asarray(y)).all()
    assert np.isfinite(float(aux))


def test_moe_kv_cache_decode_matches_full_forward():
    # Capacity high enough that no token is ever dropped: with drops, joint-prefill
    # and incremental-decode dispatch legitimately differ (capacity competition is
    # over different token sets) — the no-drop regime must match exactly.
    import dataclasses

    cfg = dataclasses.replace(CFG, moe_capacity_factor=4.0)
    params = llama.init(jax.random.PRNGKey(0), cfg)
    tokens = jax.random.randint(jax.random.PRNGKey(2), (1, 12), 0, cfg.vocab_size)
    full_logits, _ = llama.forward(params, tokens, cfg)
    cache = llama.init_kv_cache(cfg, batch=1, max_len=16, dtype=jnp.float32)
    _, cache = llama.forward(params, tokens[:, :8], cfg, cache=cache)
    for i in range(8, 12):
        step_logits, cache = llama.forward(params, tokens[:, i:i + 1], cfg, cache=cache)
    np.testing.assert_allclose(
        np.asarray(step_logits[0, 0]), np.asarray(full_logits[0, -1]),
        rtol=1e-4, atol=1e-4,
    )


def test_moe_expert_parallel_sharding_compiles():
    """jit the MoE loss over an ep×tp mesh — GSPMD must place the expert axis."""
    from jax.sharding import NamedSharding, PartitionSpec as P

    from ray_tpu.parallel.mesh import local_mesh
    from ray_tpu.parallel.sharding import TRAIN_RULES, shard_pytree

    mesh = local_mesh(dp=2, ep=2, tp=2)
    params = llama.init(jax.random.PRNGKey(0), CFG)
    params = shard_pytree(params, llama.param_axes(CFG), mesh, TRAIN_RULES)
    tokens = jax.device_put(
        jax.random.randint(jax.random.PRNGKey(1), (4, 16), 0, CFG.vocab_size),
        NamedSharding(mesh, P()),
    )

    @jax.jit
    def step(p, t):
        return llama.loss_fn(p, {"tokens": t}, CFG)[0]

    loss = step(params, tokens)
    assert np.isfinite(float(loss))
    # expert weights really are sharded over ep
    sh = params["layers"]["w_gate"].sharding
    assert "ep" in (sh.spec[1] if isinstance(sh.spec[1], str) else "") or \
        sh.spec[1] == "ep"


def test_moe_llm_engine_decode_and_bucket_invariance():
    """MoE serving: results must not depend on the prefill padding bucket —
    pad tokens may not steal expert capacity from real tokens."""
    from ray_tpu.llm import JaxLLMEngine, LLMConfig, SamplingParams

    params = SamplingParams(max_tokens=4, temperature=0.0, stop_token_ids=[-1])
    outs = []
    for buckets in ([8], [24]):  # same prompt padded to 8 vs 24
        eng = JaxLLMEngine(LLMConfig(model_id="moe", model_source="moe-tiny",
                                     max_num_seqs=2, max_model_len=32,
                                     prefill_buckets=buckets))
        try:
            out = eng.generate_sync([1, 5, 9], params)
            assert len(out.token_ids) == 4
            assert all(0 <= t < CFG.vocab_size for t in out.token_ids)
            outs.append(out.token_ids)
        finally:
            eng.shutdown()
    assert outs[0] == outs[1], "generation depends on the padding bucket"


def _moe_engine_cfg(model_id, **kw):
    from ray_tpu.llm import LLMConfig

    return LLMConfig(model_id=model_id, model_source="moe-tiny", max_num_seqs=2,
                     max_model_len=64, tokenizer="byte", **kw)


def _greedy_ids(cfg, prompt, n):
    from ray_tpu.llm import JaxLLMEngine, SamplingParams

    eng = JaxLLMEngine(cfg)
    eng.start()
    try:
        return eng.generate_sync(prompt, SamplingParams(
            max_tokens=n, temperature=0.0, stop_token_ids=[-1])).token_ids
    finally:
        eng.shutdown()


@pytest.mark.parametrize("kv_layout", ["slot", "paged"])
def test_moe_speculative_decode_matches_greedy(kv_layout):
    """spec decoding on an MoE model (fence removed — reference capability:
    vLLM composes spec decode with MoE freely via engine_kwargs): the verify
    window routes through moe_mlp, and greedy output is IDENTICAL to plain
    decode. Needs capacity headroom so window tokens don't overflow experts
    (moe-tiny's capacity_factor covers the tiny batches here)."""
    prompt = [1, 10, 11, 12, 13, 10, 11, 12, 13]
    want = _greedy_ids(_moe_engine_cfg(f"moe-plain-{kv_layout}",
                                       kv_layout=kv_layout), prompt, 10)
    got = _greedy_ids(_moe_engine_cfg(f"moe-spec-{kv_layout}",
                                      kv_layout=kv_layout,
                                      num_speculative_tokens=4), prompt, 10)
    assert got == want
    # fused bursts compose too (spec x multi-step x MoE, both layouts)
    got_fused = _greedy_ids(_moe_engine_cfg(f"moe-specf-{kv_layout}",
                                            kv_layout=kv_layout,
                                            num_speculative_tokens=4,
                                            num_decode_steps=4), prompt, 10)
    assert got_fused == want


def test_moe_int8_engine_generates_and_tracks_fp():
    """int8 weight-only quantization on MoE experts (fence removed): expert
    weights [E,d_in,out] quantize per-(expert, out-channel); the engine serves
    and the greedy trajectory tracks fp for the leading tokens."""
    prompt = [1, 7, 42, 99, 5]
    want = _greedy_ids(_moe_engine_cfg("moe-fp", dtype="float32"), prompt, 8)
    got = _greedy_ids(_moe_engine_cfg("moe-q8", dtype="float32",
                                      quantization="int8"), prompt, 8)
    assert len(got) == len(want) == 8
    matching = 0
    for a, b in zip(want, got):
        if a != b:
            break
        matching += 1
    assert matching >= 2, (want, got)
