"""C++ shared-memory arena tests (reference: plasma store tests,
src/ray/object_manager/plasma/ + test_plasma*; SURVEY.md §2.1)."""
import multiprocessing as mp
import os

import numpy as np
import pytest

from ray_tpu._native.shm_store import Arena


@pytest.fixture()
def arena():
    name = f"/rtpu_t_{os.getpid()}_{os.urandom(2).hex()}"
    a = Arena.create(name, 4 << 20)
    yield a
    a.unlink()
    a.close()


def test_create_seal_get_delete(arena):
    oid = b"x" * 20
    buf = arena.create_object(oid, 100)
    buf[:5] = b"hello"
    # unsealed objects are not readable
    assert arena.get(oid) is None
    arena.seal(oid)
    v = arena.get(oid)  # takes a reader pin
    assert bytes(v[:5]) == b"hello"
    del buf, v
    arena.unpin(oid)
    assert arena.delete(oid)
    assert arena.get(oid) is None


def test_delete_defers_while_pinned(arena):
    """delete() under a live reader pin must not recycle the memory."""
    oid = b"p" * 20
    buf = arena.create_object(oid, 1000)
    buf[:6] = b"pinned"
    del buf
    arena.seal(oid)
    v = arena.get(oid)  # pin
    used_before, _, _, _ = arena.stats()
    assert arena.delete(oid)  # logically gone...
    assert arena.get(oid) is None
    used, _, _, _ = arena.stats()
    assert used == used_before  # ...but memory still held for the reader
    assert bytes(v[:6]) == b"pinned"  # view remains valid
    del v
    arena.unpin(oid)  # last pin drops -> block actually freed
    used, _, _, _ = arena.stats()
    assert used < used_before


def test_duplicate_create_rejected(arena):
    oid = b"d" * 20
    assert arena.create_object(oid, 10) is not None
    assert arena.create_object(oid, 10) is None


def test_oom_returns_none_and_free_recovers(arena):
    used0, cap, n0, _ = arena.stats()
    big = b"big" + b"\0" * 17
    assert arena.create_object(big, cap - 64) is not None
    arena.seal(big)
    assert arena.create_object(b"y" * 20, 1024) is None  # full
    assert arena.delete(big)
    assert arena.create_object(b"y" * 20, 1024) is not None  # space reclaimed


def test_fragmentation_coalescing(arena):
    ids = [bytes([i]) * 20 for i in range(64)]
    for oid in ids:
        assert arena.create_object(oid, 16 * 1024) is not None
        arena.seal(oid)
    # free alternating then the rest -> allocator must coalesce to one block
    for oid in ids[::2]:
        assert arena.delete(oid)
    for oid in ids[1::2]:
        assert arena.delete(oid)
    used, cap, n, _ = arena.stats()
    assert (used, n) == (0, 0)
    assert arena.create_object(b"Z" * 20, cap - 64) is not None


def _child_reader(name, oid, q):
    a = Arena.open(name)
    v = a.get(oid)
    q.put(bytes(v[:8]) if v is not None else None)
    a.close()


def _child_writer(name, oid, q):
    a = Arena.open(name)
    buf = a.create_object(oid, 64)
    buf[:8] = b"fromkid!"
    del buf
    a.seal(oid)
    q.put(True)
    a.close()


def test_cross_process_read_write(arena):
    ctx = mp.get_context("spawn")
    oid1, oid2 = b"a" * 20, b"b" * 20
    buf = arena.create_object(oid1, 64)
    buf[:8] = b"frompar!"
    del buf
    arena.seal(oid1)
    q = ctx.Queue()
    p = ctx.Process(target=_child_reader, args=(arena.name, oid1, q))
    p.start()
    assert q.get(timeout=30) == b"frompar!"
    p.join()
    p = ctx.Process(target=_child_writer, args=(arena.name, oid2, q))
    p.start()
    assert q.get(timeout=30) is True
    p.join()
    assert bytes(arena.get(oid2)[:8]) == b"fromkid!"


def _crash_writer(name, oid):
    a = Arena.open(name)
    a.create_object(oid, 64)  # never sealed
    os._exit(1)


def test_sweep_collects_dead_writers(arena):
    ctx = mp.get_context("spawn")
    oid = b"c" * 20
    p = ctx.Process(target=_crash_writer, args=(arena.name, oid))
    p.start()
    p.join()
    assert arena.sweep() == 1
    # slot is reusable again
    assert arena.create_object(oid, 64) is not None


def _churn_until_killed(name, start_q):
    """Hammer alloc/seal/delete so a SIGKILL lands mid-critical-section."""
    a = Arena.open(name)
    start_q.put(True)
    i = 0
    while True:
        oid = i.to_bytes(4, "little") + b"k" * 16
        if a.create_object(oid, 256) is not None:
            a.seal(oid)
            if i % 2:
                a.delete(oid)
        i += 1


def test_heap_rebuild_after_owner_killed_mid_op(arena):
    """SIGKILL a process doing arena ops in a tight loop; the robust-mutex
    EOWNERDEAD path must rebuild the free list so later ops neither crash nor
    leak the heap (regression: segfault in gc_dead_owners after actor kill)."""
    ctx = mp.get_context("spawn")
    for _ in range(5):
        q = ctx.Queue()
        p = ctx.Process(target=_churn_until_killed, args=(arena.name, q))
        p.start()
        assert q.get(timeout=30)
        import time

        time.sleep(0.05)  # let it reach steady-state churn
        p.kill()
        p.join()
        # survivor side: every op class still works on a possibly-rebuilt heap
        assert arena.gc_dead_owners([]) >= 0
        assert arena.sweep() >= 0
        oid = os.urandom(20)
        buf = arena.create_object(oid, 1024)
        assert buf is not None
        buf[:4] = b"okay"
        arena.seal(oid)
        v = arena.get(oid)
        assert bytes(v[:4]) == b"okay"
        del buf, v
        arena.unpin(oid)
        assert arena.delete(oid)
    # heap accounting must still be sane: a big alloc close to capacity succeeds
    big = arena.create_object(b"z" * 20, (4 << 20) - (1 << 20))
    assert big is not None
    del big


def test_store_integration_large_object_roundtrip(rt):
    """ray.put/get of a large array must ride the arena zero-copy path."""
    arr = np.arange(1 << 20, dtype=np.float32)  # 4 MB
    ref = rt.put(arr)
    out = rt.get(ref)
    np.testing.assert_array_equal(arr, out)
    from ray_tpu.core import global_state

    cluster = global_state.try_cluster()
    if cluster.arena_name:  # arena active: the object must be accounted there
        stats = cluster.store.stats()
        assert stats["arena_bytes"] >= arr.nbytes


def test_store_integration_worker_returns_large(rt):
    @rt.remote
    def make(n):
        return np.ones(n, dtype=np.float64)

    refs = [make.remote(1 << 17) for _ in range(4)]  # 1 MB each, from workers
    for r in refs:
        v = rt.get(r)
        assert v.shape == (1 << 17,) and v[0] == 1.0


def test_stress_binary_clean():
    """The standalone concurrency stress driver (the ASan/TSan CI seam,
    _native/shm_store_stress.cc) passes un-instrumented too: 8 threads of
    alloc/seal/pin/delete churn with no leaks or integrity failures."""
    import subprocess
    import sys
    import tempfile

    src = os.path.join(os.path.dirname(__file__), "..", "ray_tpu", "_native",
                       "shm_store_stress.cc")
    with tempfile.TemporaryDirectory() as d:
        exe = os.path.join(d, "stress")
        build = subprocess.run(
            ["g++", "-std=c++17", "-O1", src, "-o", exe, "-lpthread", "-lrt"],
            capture_output=True, text=True)
        if build.returncode != 0:
            pytest.skip(f"no native toolchain: {build.stderr[:200]}")
        run = subprocess.run([exe], capture_output=True, text=True, timeout=120)
        assert run.returncode == 0, run.stderr
        assert "no leaks" in run.stdout


def test_sanitizer_build_seam(tmp_path, monkeypatch):
    """RAY_TPU_SANITIZE routes load_library to a separate instrumented artifact
    without touching the cached production .so (build.py sanitizer seam)."""
    from ray_tpu._native import build

    monkeypatch.setenv("RAY_TPU_SANITIZE", "bogus")
    with pytest.raises(build.NativeBuildError, match="bogus"):
        build.load_library("shm_store")
