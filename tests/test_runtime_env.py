"""runtime_env tests: env_vars/py_modules/working_dir + the pip venv plugin
(reference _private/runtime_env/ pip.py, uri_cache.py)."""
import os
import textwrap

import pytest

import ray_tpu
from ray_tpu.runtime_env import RuntimeEnv, ensure_pip_env


@pytest.fixture(autouse=True)
def _cluster(rt):
    yield


def test_validation():
    env = RuntimeEnv(env_vars={"A": "1"}, pip=["somepkg"])
    assert env["pip"] == {"packages": ["somepkg"]}
    with pytest.raises(ValueError, match="conda"):
        RuntimeEnv(conda={"dependencies": []})
    with pytest.raises(ValueError, match="unknown"):
        RuntimeEnv(nonsense=1)
    with pytest.raises(TypeError):
        RuntimeEnv(pip={"no_index": True})  # no packages


def _write_dummy_pkg(tmp_path, name="rtenv_dummy", version="1.0"):
    pkg = tmp_path / name
    (pkg / name).mkdir(parents=True)
    (pkg / name / "__init__.py").write_text(f'MAGIC = "{name}-{version}"\n')
    (pkg / "setup.py").write_text(textwrap.dedent(f"""
        from setuptools import setup, find_packages
        setup(name="{name}", version="{version}", packages=find_packages())
    """))
    return str(pkg)


def test_pip_env_builds_and_caches(tmp_path, monkeypatch):
    monkeypatch.setenv("RAY_TPU_SESSION_DIR", str(tmp_path / "session"))
    pkg = _write_dummy_pkg(tmp_path)
    spec = {"packages": [pkg], "no_index": True}
    site = ensure_pip_env(spec)
    assert os.path.isdir(os.path.join(site, "rtenv_dummy"))  # --target overlay dir
    # second call returns the cached env without rebuilding
    import time

    t0 = time.monotonic()
    assert ensure_pip_env(spec) == site
    assert time.monotonic() - t0 < 0.5


def test_task_with_pip_runtime_env(rt, tmp_path, monkeypatch):
    monkeypatch.setenv("RAY_TPU_SESSION_DIR", str(tmp_path / "session"))
    pkg = _write_dummy_pkg(tmp_path, name="rtenv_taskpkg")

    @ray_tpu.remote(runtime_env={"pip": {"packages": [pkg], "no_index": True}})
    def uses_pkg():
        import rtenv_taskpkg

        return rtenv_taskpkg.MAGIC

    # driver process does NOT have the package
    with pytest.raises(ImportError):
        import rtenv_taskpkg  # noqa: F401
    assert ray_tpu.get(uses_pkg.remote(), timeout=120) == "rtenv_taskpkg-1.0"


def test_gcs_kv_persistence_survives_restart(tmp_path):
    """Reference: GCS tables persist to Redis and survive a GCS restart."""
    from ray_tpu.core.gcs import KVStore

    path = str(tmp_path / "gcs" / "kv.journal")
    kv = KVStore(path)
    kv.put(b"app-config", b"v1", namespace="serve")
    kv.put(b"doomed", b"x")
    kv.delete(b"doomed")
    kv.put(b"app-config", b"v2", namespace="serve")  # overwrite persists too
    kv.close()

    fresh = KVStore(path)
    assert fresh.get(b"app-config", namespace="serve") == b"v2"
    assert fresh.get(b"doomed") is None
    # journal keeps appending across generations
    fresh.put(b"next", b"gen2")
    fresh.close()
    gen3 = KVStore(path)
    assert gen3.get(b"next") == b"gen2"
    assert gen3.get(b"app-config", namespace="serve") == b"v2"
    gen3.close()


def test_cluster_kv_persistence_end_to_end(tmp_path, monkeypatch):
    import ray_tpu
    from ray_tpu.experimental import internal_kv

    path = str(tmp_path / "cluster_kv.journal")
    monkeypatch.setenv("RAY_TPU_GCS_PERSISTENCE_PATH", path)
    ray_tpu.shutdown()
    try:
        ray_tpu.init(num_cpus=2, worker_env={"JAX_PLATFORMS": "cpu"})
        internal_kv._internal_kv_put(b"persisted-key", b"persisted-value")
        ray_tpu.shutdown()
        # a new cluster (same persistence path) restores the KV table
        ray_tpu.init(num_cpus=2, worker_env={"JAX_PLATFORMS": "cpu"})
        assert internal_kv._internal_kv_get(b"persisted-key") == b"persisted-value"
    finally:
        # always restore the session cluster, or later rt tests cascade-fail
        ray_tpu.shutdown()
        monkeypatch.delenv("RAY_TPU_GCS_PERSISTENCE_PATH")
        ray_tpu.init(num_cpus=4, worker_env={"JAX_PLATFORMS": "cpu"},
                     max_workers_per_node=8)


def test_uv_env_builds(tmp_path, monkeypatch):
    """uv plugin: same overlay contract as pip, built by the uv binary
    (reference _private/runtime_env/uv.py)."""
    import shutil

    if shutil.which("uv") is None:
        pytest.skip("no uv binary")
    monkeypatch.setenv("RAY_TPU_SESSION_DIR", str(tmp_path / "session"))
    pkg = _write_dummy_pkg(tmp_path, name="rtenv_uv_dummy")
    site = ensure_pip_env({"packages": [pkg], "no_index": True}, tool="uv")
    assert os.path.isdir(os.path.join(site, "rtenv_uv_dummy"))


def test_task_with_uv_runtime_env(rt, tmp_path, monkeypatch):
    import shutil

    if shutil.which("uv") is None:
        pytest.skip("no uv binary")
    monkeypatch.setenv("RAY_TPU_SESSION_DIR", str(tmp_path / "session"))
    pkg = _write_dummy_pkg(tmp_path, name="rtenv_uv_task")

    @ray_tpu.remote(runtime_env={"uv": {"packages": [pkg], "no_index": True}})
    def probe():
        import rtenv_uv_task

        return rtenv_uv_task.MAGIC

    assert ray_tpu.get(probe.remote()) == "rtenv_uv_task-1.0"
    with pytest.raises(ImportError):
        import rtenv_uv_task  # noqa: F401  (driver env stays clean)


def test_conda_still_rejected():
    """conda remains unsupported (no conda in this environment); container and
    image_uri are real features now (see the container tests below)."""
    from ray_tpu.runtime_env import RuntimeEnv

    with pytest.raises(ValueError, match="infrastructure"):
        RuntimeEnv(conda={"x": 1})


def test_merge_runtime_envs():
    from ray_tpu.runtime_env import merge_runtime_envs

    base = {"env_vars": {"A": "1", "B": "1"}, "pip": {"packages": ["x"]}}
    over = {"env_vars": {"B": "2"}, "working_dir": "/w"}
    m = merge_runtime_envs(base, over)
    assert m["env_vars"] == {"A": "1", "B": "2"}  # dict-merge, override wins
    assert m["pip"] == {"packages": ["x"]} and m["working_dir"] == "/w"
    assert merge_runtime_envs(None, over) == over
    assert merge_runtime_envs(base, None) == base
    assert merge_runtime_envs(None, None) is None


@pytest.fixture()
def default_renv_cluster():
    """Own cluster with a job-level runtime_env (reference ray.init(runtime_env=...)).
    Restores the session cluster afterwards."""
    from ray_tpu.core import global_state

    was_up = global_state.is_initialized()
    ray_tpu.shutdown()
    ray_tpu.init(num_cpus=4, worker_env={"JAX_PLATFORMS": "cpu"},
                 runtime_env={"env_vars": {"RTENV_JOB_DEFAULT": "yes",
                                           "RTENV_SHARED": "base"}})
    yield
    ray_tpu.shutdown()
    if was_up:
        ray_tpu.init(num_cpus=4, worker_env={"JAX_PLATFORMS": "cpu"},
                     max_workers_per_node=8)


def test_job_level_default_runtime_env(default_renv_cluster):
    """init(runtime_env=...) applies to every task; per-call env_vars dict-merge
    over it; nested worker->task submissions inherit the default too."""
    @ray_tpu.remote
    def read():
        import os

        return os.environ.get("RTENV_JOB_DEFAULT"), os.environ.get("RTENV_SHARED")

    assert ray_tpu.get(read.remote()) == ("yes", "base")

    @ray_tpu.remote(runtime_env={"env_vars": {"RTENV_SHARED": "override"}})
    def read_override():
        import os

        return os.environ.get("RTENV_JOB_DEFAULT"), os.environ.get("RTENV_SHARED")

    assert ray_tpu.get(read_override.remote()) == ("yes", "override")

    @ray_tpu.remote
    def outer():
        @ray_tpu.remote
        def inner():
            import os

            return os.environ.get("RTENV_JOB_DEFAULT")

        return ray_tpu.get(inner.remote())

    assert ray_tpu.get(outer.remote()) == "yes"


def test_container_runtime_env_records_invocation_and_runs(rt, tmp_path, monkeypatch):
    """container/image_uri runtime env (reference
    _private/runtime_env/image_uri.py): the worker is launched through the
    container runtime with the session dir mounted and dials back over the
    socket protocol. A recording fake runtime (RAY_TPU_CONTAINER_RUNTIME — the
    documented test seam) captures the exact docker-style invocation, then
    execs the worker command so the task completes end to end."""
    import json
    import stat
    import sys

    fake = tmp_path / "fake_docker.py"
    log = tmp_path / "invocations.jsonl"
    fake.write_text(f"""#!{sys.executable}
import json, os, sys
args = sys.argv[1:]
with open({str(log)!r}, "a") as f:
    f.write(json.dumps(args) + "\\n")
assert args[0] == "run"
i = 1
env = {{}}
while i < len(args):
    a = args[i]
    if a == "--rm":
        i += 1
    elif a in ("--network",):
        i += 2
    elif a == "-v":
        i += 2
    elif a == "--env":
        k, _, v = args[i + 1].partition("=")
        env[k] = v
        i += 2
    elif a.startswith("--"):
        i += 1
    else:
        break
image = args[i]
cmd = args[i + 1:]
os.environ.update(env)
os.execvp(cmd[0], cmd)
""")
    fake.chmod(fake.stat().st_mode | stat.S_IXUSR)
    monkeypatch.setenv("RAY_TPU_CONTAINER_RUNTIME", str(fake))

    @rt.remote(num_cpus=0.5, runtime_env={
        "image_uri": "example.com/tpu-image:1",
        "env_vars": {"CONTAINER_MARK": "inside"}})
    def inside():
        import os

        return os.environ.get("CONTAINER_MARK"), os.getpid()

    mark, pid = rt.get(inside.remote(), timeout=120)
    assert mark == "inside" and pid != 0

    # the recorded invocation is a real docker/podman-shaped command line
    lines = [json.loads(ln) for ln in log.read_text().splitlines()]
    assert len(lines) == 1
    argv = lines[0]
    assert argv[0] == "run" and "--rm" in argv and "--network" in argv
    assert "example.com/tpu-image:1" in argv
    from ray_tpu.job.manager import default_session_dir

    sess = default_session_dir()
    assert f"{sess}:{sess}" in argv  # session dir mounted
    img_i = argv.index("example.com/tpu-image:1")
    assert argv[img_i + 1:img_i + 4] == ["python", "-m", "ray_tpu.core.worker"]

    # container/conda validation: conda still refused, bad container rejected
    import pytest as _pytest

    from ray_tpu.runtime_env import RuntimeEnv

    with _pytest.raises(ValueError, match="conda"):
        RuntimeEnv(conda={"dependencies": ["x"]})
    with _pytest.raises(ValueError, match="container"):
        RuntimeEnv(container={"run_options": ["--gpus=all"]})  # no image
    RuntimeEnv(image_uri="img:1")  # accepted


def test_container_runtime_missing_fails_task_cleanly(rt, monkeypatch):
    """No docker/podman anywhere: the task fails with a clear error instead of
    pending forever (reference: runtime-env agent setup errors fail the task)."""
    monkeypatch.setenv("RAY_TPU_CONTAINER_RUNTIME", "")
    monkeypatch.setenv("PATH", "/nonexistent")
    try:
        @rt.remote(num_cpus=0.5, runtime_env={"image_uri": "img:1"})
        def f():
            return 1

        with pytest.raises(Exception, match="container runtime"):
            rt.get(f.remote(), timeout=60)
    finally:
        pass


def test_container_runtime_env_on_remote_agent(rt, tmp_path):
    """The container path on a REMOTE node agent (separate OS process tree):
    the agent — not the head — launches the containerized worker via its own
    container runtime, the worker dials back into the agent's relay, and the
    task completes. The recorded invocation proves the agent's session-dir
    mount and worker command line (reference: per-node runtime-env agents in
    _private/runtime_env/agent.py launching image_uri workers on their host)."""
    import json
    import stat
    import subprocess
    import sys
    import time as _time

    import ray_tpu
    from ray_tpu.core import global_state
    from ray_tpu.core.task_spec import NodeAffinitySchedulingStrategy

    fake = tmp_path / "fake_docker.py"
    log = tmp_path / "agent_invocations.jsonl"
    fake.write_text(f"""#!{sys.executable}
import json, os, sys
args = sys.argv[1:]
with open({str(log)!r}, "a") as f:
    f.write(json.dumps(args) + "\\n")
assert args[0] == "run"
i = 1
env = {{}}
while i < len(args):
    a = args[i]
    if a == "--rm":
        i += 1
    elif a in ("--network",):
        i += 2
    elif a == "-v":
        i += 2
    elif a == "--env":
        k, _, v = args[i + 1].partition("=")
        env[k] = v
        i += 2
    elif a.startswith("--"):
        i += 1
    else:
        break
cmd = args[i + 1:]
os.environ.update(env)
os.execvp(cmd[0], cmd)
""")
    fake.chmod(fake.stat().st_mode | stat.S_IXUSR)

    ray_tpu.shutdown()
    ray_tpu.init(num_cpus=1, node_server_port=0,
                 worker_env={"JAX_PLATFORMS": "cpu"})
    cluster = global_state.try_cluster()
    head_id = next(n["NodeID"] for n in ray_tpu.nodes())
    agent = subprocess.Popen(
        [sys.executable, "-m", "ray_tpu.core.node_agent",
         "--address", f"127.0.0.1:{cluster.node_server_port}",
         "--num-cpus", "2"],
        # the CONTAINER RUNTIME override rides the AGENT's environment: the
        # head process never sees the shim, so a head-side launch would fail
        env={**os.environ, "JAX_PLATFORMS": "cpu",
             "RAY_TPU_CONTAINER_RUNTIME": str(fake)},
    )
    try:
        deadline = _time.time() + 30
        while len([x for x in ray_tpu.nodes() if x["Alive"]]) < 2:
            assert _time.time() < deadline, "node agent never registered"
            _time.sleep(0.2)
        remote_id = next(n["NodeID"] for n in ray_tpu.nodes()
                         if n["Alive"] and n["NodeID"] != head_id)

        @ray_tpu.remote(
            num_cpus=1,
            scheduling_strategy=NodeAffinitySchedulingStrategy(node_id=remote_id),
            runtime_env={"image_uri": "example.com/tpu-image:2",
                         "env_vars": {"CONTAINER_MARK": "on-agent"}})
        def inside():
            import os

            return (os.environ.get("CONTAINER_MARK"),
                    ray_tpu.get_runtime_context().node_id)

        mark, node_id = ray_tpu.get(inside.remote(), timeout=120)
        assert mark == "on-agent"
        assert node_id == remote_id  # ran on the agent's node, not the head

        lines = [json.loads(ln) for ln in log.read_text().splitlines()]
        assert len(lines) == 1
        argv = lines[0]
        assert argv[0] == "run" and "example.com/tpu-image:2" in argv
        img_i = argv.index("example.com/tpu-image:2")
        assert argv[img_i + 1:img_i + 4] == ["python", "-m", "ray_tpu.core.worker"]
        assert "--connect" in argv  # dial-back into the AGENT's relay
    finally:
        if agent.poll() is None:
            agent.terminate()
            try:
                agent.wait(timeout=10)
            except subprocess.TimeoutExpired:
                agent.kill()
        ray_tpu.shutdown()
        ray_tpu.init(num_cpus=4, worker_env={"JAX_PLATFORMS": "cpu"},
                     max_workers_per_node=8)


def test_idle_env_worker_evicted_at_cap():
    """A node whose worker cap is entirely held by IDLE env-pinned workers must
    still admit a task with a NEW runtime env: the scheduler evicts one idle
    worker to free the slot (reference: raylet WorkerPool idle eviction).
    Regression: before the fix the new-env task queued forever and a full-suite
    session deadlocked at test_pd_disagg_unequal_pools_device_path."""
    ray_tpu.shutdown()
    ray_tpu.init(num_cpus=4, worker_env={"JAX_PLATFORMS": "cpu"},
                 max_workers_per_node=2)
    try:
        @ray_tpu.remote
        def probe():
            return os.getpid()

        # fill BOTH slots with idle workers from two distinct env pools
        for i in range(2):
            env = {"env_vars": {"RAY_TPU_TEST_POOL": str(i)}}
            pid = ray_tpu.get(probe.options(runtime_env=env).remote(),
                              timeout=60)
            assert pid

        # a third, NEW env must still run (one idle env worker gets evicted)
        out = ray_tpu.get(
            probe.options(runtime_env={"env_vars": {
                "RAY_TPU_TEST_POOL": "fresh"}}).remote(),
            timeout=60)
        assert out
    finally:
        ray_tpu.shutdown()
        ray_tpu.init(num_cpus=4, worker_env={"JAX_PLATFORMS": "cpu"},
                     max_workers_per_node=8)
