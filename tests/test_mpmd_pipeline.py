"""MPMD cross-process pipeline: schedule core (pure functions), bubble-fraction
timeline analysis, and loss/grad BIT-EXACT (f32) parity of the cross-process
runner vs the in-program `pipeline_spmd` on a CPU 2-stage toy model.

The parity contract (train/mpmd_pipeline.py module docstring): per-microbatch
grads fold in REVERSE microbatch order from zeros — the float-add chain
lax.scan's transpose emits — and the last stage seeds each microbatch
cotangent with exactly 1/M (exact in f32 for power-of-two M).
"""
import jax
import jax.numpy as jnp
import numpy as np
import pytest

from ray_tpu.train.mpmd_pipeline import (
    MPMDPipelineConfig,
    build_1f1b_schedule,
    build_gpipe_schedule,
    build_schedule,
    bubble_fraction,
    validate_schedule,
    warmup_len,
)


# ------------------------------------------------------------- schedule core
@pytest.mark.parametrize("pp", [2, 3, 4])
@pytest.mark.parametrize("m", [1, 3, 4, 7])
def test_1f1b_schedule_shape(pp, m):
    """Every stage touches every microbatch once per direction; warmup depth
    is the fill distance below the stage; cooldown mirrors warmup."""
    for stage in range(pp):
        evs = build_1f1b_schedule(stage, pp, m)
        assert len(evs) == 2 * m
        assert sorted(i for k, i in evs if k == "fwd") == list(range(m))
        assert sorted(i for k, i in evs if k == "bwd") == list(range(m))
        w = warmup_len(stage, pp, m)
        assert w == min(pp - 1 - stage, m)
        # warmup: the first w events are forwards 0..w-1
        assert evs[:w] == [("fwd", i) for i in range(w)]
        # cooldown: the last w events are the final backwards
        assert evs[len(evs) - w:] == [("bwd", i) for i in range(m - w, m)]


@pytest.mark.parametrize("pp,m", [(2, 4), (3, 5), (4, 2), (4, 7)])
def test_1f1b_steady_state_alternates(pp, m):
    """Between warmup and cooldown, events strictly alternate fwd/bwd (the
    one-forward-one-backward invariant that bounds live activations at
    warmup_len + 1 instead of m)."""
    for stage in range(pp):
        evs = build_1f1b_schedule(stage, pp, m)
        w = warmup_len(stage, pp, m)
        steady = evs[w:len(evs) - w]
        kinds = [k for k, _ in steady]
        assert kinds == ["fwd", "bwd"] * ((len(evs) - 2 * w) // 2)


def test_last_stage_has_no_warmup():
    # the last stage can run its first backward immediately after its first
    # forward — depth-0 fill
    for pp in (2, 3, 4):
        assert warmup_len(pp - 1, pp, 8) == 0
        evs = build_1f1b_schedule(pp - 1, pp, 3)
        assert evs == [("fwd", 0), ("bwd", 0), ("fwd", 1), ("bwd", 1),
                       ("fwd", 2), ("bwd", 2)]


def test_gpipe_schedule_shape():
    evs = build_gpipe_schedule(0, 3, 4)
    assert evs == [("fwd", i) for i in range(4)] + [("bwd", i) for i in range(4)]


@pytest.mark.parametrize("schedule", ["1f1b", "gpipe"])
@pytest.mark.parametrize("pp,m", [(2, 1), (2, 4), (3, 5), (4, 3), (4, 8)])
def test_build_schedule_validates(schedule, pp, m):
    scheds = build_schedule(pp, m, schedule)
    assert len(scheds) == pp
    validate_schedule(scheds, pp, m)  # idempotent — already ran inside build


def test_build_schedule_rejects_bad_input():
    with pytest.raises(ValueError, match="unknown pipeline schedule"):
        build_schedule(2, 4, "interleaved")
    with pytest.raises(ValueError, match="pp >= 1"):
        build_schedule(0, 4)
    with pytest.raises(ValueError, match="pp >= 1"):
        build_schedule(2, 0)


def test_validate_schedule_catches_deadlock():
    # stage 1 demands bwd(0) before running fwd(0): cyclic wait
    bad = [[("fwd", 0), ("bwd", 0)], [("bwd", 0), ("fwd", 0)]]
    with pytest.raises(ValueError, match="deadlock"):
        validate_schedule(bad, 2, 1)


def test_validate_schedule_catches_duplicates():
    bad = [[("fwd", 0), ("fwd", 0)], [("fwd", 0), ("bwd", 0)]]
    with pytest.raises(ValueError, match="exactly once"):
        validate_schedule(bad, 2, 1)


def test_pipeline_config_validation():
    with pytest.raises(ValueError, match="schedule"):
        MPMDPipelineConfig(schedule="zigzag")
    with pytest.raises(ValueError, match="transport"):
        MPMDPipelineConfig(transport="carrier-pigeon")
    with pytest.raises(ValueError):
        MPMDPipelineConfig(num_microbatches=0)
    cfg = MPMDPipelineConfig.from_env(num_microbatches=8, prefetch=0)
    assert cfg.num_microbatches == 8 and cfg.prefetch == 0


def test_resolve_stage_transport_cpu_fallback():
    from ray_tpu.dag.accelerator_context import resolve_stage_transport

    assert resolve_stage_transport("host") == "host"
    # no device plane on the CPU test box: auto degrades to host, an explicit
    # device request refuses loudly
    assert resolve_stage_transport("auto") in ("host", "device")
    with pytest.raises(ValueError, match="unknown stage transport"):
        resolve_stage_transport("tcp")


# ------------------------------------------------------------- bubble fraction
def _span(stage, ts, dur):
    return {"name": "train.pipeline_stage", "ph": "X", "ts": ts, "dur": dur,
            "args": {"stage": stage, "kind": "fwd", "mb": 0, "step": 0}}


def test_bubble_fraction_known_gaps():
    # stage 0: busy [0,10] and [30,40] in a [0,40] window -> 50% idle
    events = [_span(0, 0, 10), _span(0, 30, 10),
              # stage 1: back-to-back spans -> 0% idle
              _span(1, 5, 10), _span(1, 15, 10)]
    out = bubble_fraction(events)
    assert out["stage0"] == pytest.approx(0.5)
    assert out["stage1"] == pytest.approx(0.0)
    assert out["mean"] == pytest.approx(0.25)


def test_bubble_fraction_unions_overlaps():
    # nested/overlapping spans must not double-count busy time (which would
    # push the fraction negative)
    events = [_span(0, 0, 20), _span(0, 5, 10), _span(0, 30, 10)]
    out = bubble_fraction(events)
    assert out["stage0"] == pytest.approx(0.25)  # idle [20,30] of [0,40]


def test_bubble_fraction_ignores_foreign_events():
    events = [{"name": "other.span", "ph": "X", "ts": 0, "dur": 5, "args": {"stage": 0}},
              {"name": "train.pipeline_stage", "ph": "X", "ts": 0, "dur": 5, "args": {}}]
    assert bubble_fraction(events) == {}


# ------------------------------------------------------------- parity (2-stage)
def _stage_fn(params, x):
    return x + jnp.tanh(x @ params["w"]) @ params["w2"]


def _stacked_params(pp, d, seed=0):
    k1, k2 = jax.random.split(jax.random.PRNGKey(seed))
    return {
        "w": jax.random.normal(k1, (pp, d, 2 * d)) * 0.1,
        "w2": jax.random.normal(k2, (pp, 2 * d, d)) * 0.1,
    }


def _mb_loss(y):
    return jnp.mean(y ** 2)


def test_cross_process_runner_bit_exact_vs_pipeline_spmd(rt):
    """The acceptance row: one optimizer step of the cross-process MPMD runner
    vs the in-program `pipeline_spmd` — same microbatch decomposition, f32 —
    must agree BITWISE on per-stage grads, the total loss, and the updated
    params. M=4 (power of two) keeps the 1/M cotangent seed exact."""
    from jax.sharding import Mesh

    from ray_tpu.parallel import use_mesh
    from ray_tpu.parallel.pipeline import pipeline
    from ray_tpu.train.mpmd_pipeline import MPMDPipeline

    pp, d, m, mb = 2, 8, 4, 4
    lr = 1e-2
    stacked = _stacked_params(pp, d)
    stage_params = [jax.tree_util.tree_map(lambda p: np.asarray(p[s]), stacked)
                    for s in range(pp)]
    x = np.asarray(jax.random.normal(jax.random.PRNGKey(1), (m * mb, d)),
                   np.float32)

    # -- reference: in-program pipeline on a pure-pp mesh, the SAME loss
    # decomposition the runner uses (mean over per-microbatch means)
    mesh = Mesh(np.array(jax.devices()[:pp]), ("pp",))

    def ref_loss(params, xx):
        with use_mesh(mesh):
            y = pipeline(_stage_fn, params, xx, num_microbatches=m, mesh=mesh)
        y_mb = y.reshape(m, mb, d)
        return jnp.mean(jnp.stack([_mb_loss(y_mb[i]) for i in range(m)]))

    _, g_ref = jax.jit(jax.value_and_grad(ref_loss))(stacked, x)
    # loss reference: pipeline_spmd's outputs reduced by the SAME standalone
    # per-microbatch program shape the runner compiles — fusing the reduction
    # into the big traced program instead lets XLA round the mean differently
    # (~1 ulp), which is a harness artifact, not a pipeline difference
    y_ref = jax.jit(lambda params, xx: pipeline(
        _stage_fn, params, xx, num_microbatches=m, mesh=mesh))(stacked, x)
    y_ref_mb = np.asarray(y_ref).reshape(m, mb, d)
    l_ref = jnp.mean(jnp.stack([jax.jit(_mb_loss)(y_ref_mb[i])
                                for i in range(m)]))
    # same jitted update formula the runner compiles, applied to the reference
    # grads — with bit-identical params and grads this must stay bit-identical
    sgd = jax.jit(lambda p, g: jax.tree_util.tree_map(
        lambda pv, gv: pv - jnp.float32(lr) * gv, p, g))
    p_ref = [sgd({k: v[s] for k, v in stacked.items()},
                 {k: v[s] for k, v in g_ref.items()}) for s in range(pp)]

    # -- cross-process runner
    cfg = MPMDPipelineConfig(num_microbatches=m, learning_rate=lr,
                             group_name="mpmd_parity")
    pipe = MPMDPipeline([_stage_fn] * pp, stage_params, loss_fn=_mb_loss,
                        microbatch_spec=((mb, d), np.float32), cfg=cfg)
    try:
        out = pipe.step(0, x)
        grads = pipe.grads_host()
        params_after = pipe.params_host()
        admission = pipe.admission()
        fractions = pipe.bubble_fractions()
    finally:
        pipe.shutdown()

    assert out["loss"] == float(l_ref)
    for s in range(pp):
        for name in ("w", "w2"):
            assert np.array_equal(np.asarray(grads[s][name]),
                                  np.asarray(g_ref[name][s])), \
                f"stage{s}.{name} grads not bit-exact"
            assert np.array_equal(np.asarray(params_after[s][name]),
                                  np.asarray(p_ref[s][name])), \
                f"stage{s}.{name} updated params not bit-exact"
    # a clean step leaves no published-but-unconsumed blocks and no pulls in
    # flight (expected_read_bytes auto-retract did its job)
    for counters in admission:
        assert counters == {"published": 0, "inflight_pulls": 0}
    # both stages produced spans; fractions land in [0, 1]
    assert set(fractions) == {"stage0", "stage1", "mean"}
    assert all(0.0 <= v <= 1.0 for v in fractions.values())


def test_cross_process_runner_multi_step(rt):
    """Steps advance the deterministic block keys: two consecutive steps run
    clean (no cross-step key collisions) and training reduces the loss."""
    from ray_tpu.train.mpmd_pipeline import MPMDPipeline

    pp, d, m, mb = 2, 8, 2, 4
    stacked = _stacked_params(pp, d, seed=3)
    stage_params = [jax.tree_util.tree_map(lambda p: np.asarray(p[s]), stacked)
                    for s in range(pp)]
    x = np.asarray(jax.random.normal(jax.random.PRNGKey(5), (m * mb, d)),
                   np.float32)
    cfg = MPMDPipelineConfig(num_microbatches=m, learning_rate=5e-2,
                             group_name="mpmd_steps")
    pipe = MPMDPipeline([_stage_fn] * pp, stage_params, loss_fn=_mb_loss,
                        microbatch_spec=((mb, d), np.float32), cfg=cfg)
    try:
        losses = [pipe.step(i, x)["loss"] for i in range(3)]
        admission = pipe.admission()
    finally:
        pipe.shutdown()
    assert losses[2] < losses[0]  # SGD on a fixed batch descends
    for counters in admission:
        assert counters == {"published": 0, "inflight_pulls": 0}
