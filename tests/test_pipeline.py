"""Pipeline-parallel combinator vs sequential stage application (8-device CPU mesh)."""
import jax
import jax.numpy as jnp
import numpy as np

from ray_tpu.parallel import local_mesh, use_mesh
from ray_tpu.parallel.pipeline import pipeline


def _stage_fn(params, x):
    # One residual MLP "layer" per stage.
    return x + jnp.tanh(x @ params["w"]) @ params["w2"]


def _stacked_params(pp, d, seed=0):
    k1, k2 = jax.random.split(jax.random.PRNGKey(seed))
    return {
        "w": jax.random.normal(k1, (pp, d, 2 * d)) * 0.1,
        "w2": jax.random.normal(k2, (pp, 2 * d, d)) * 0.1,
    }


def _sequential(params, x, pp):
    for i in range(pp):
        x = _stage_fn(jax.tree_util.tree_map(lambda p: p[i], params), x)
    return x


def test_pipeline_matches_sequential():
    pp, d, b, m = 4, 8, 8, 4
    mesh = local_mesh(pp=pp, dp=2)
    params = _stacked_params(pp, d)
    x = jax.random.normal(jax.random.PRNGKey(1), (b, d))
    ref = _sequential(params, x, pp)
    with use_mesh(mesh):
        out = pipeline(_stage_fn, params, x, num_microbatches=m, mesh=mesh)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=1e-5, rtol=1e-5)


def test_pipeline_under_jit_with_grads():
    pp, d, b, m = 2, 4, 4, 2
    mesh = local_mesh(pp=pp, dp=2, tp=2)
    params = _stacked_params(pp, d)
    x = jax.random.normal(jax.random.PRNGKey(2), (b, d))

    def loss(params, x):
        y = pipeline(_stage_fn, params, x, num_microbatches=m, mesh=mesh)
        return jnp.mean(y**2)

    with use_mesh(mesh):
        l, g = jax.jit(jax.value_and_grad(loss))(params, x)

    def loss_ref(params, x):
        return jnp.mean(_sequential(params, x, pp) ** 2)

    l_ref, g_ref = jax.value_and_grad(loss_ref)(params, x)
    np.testing.assert_allclose(float(l), float(l_ref), atol=1e-6)
    for a, b_ in zip(jax.tree_util.tree_leaves(g), jax.tree_util.tree_leaves(g_ref)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b_), atol=1e-5, rtol=1e-5)


def test_pipeline_single_stage_degenerates():
    mesh = local_mesh(dp=8)
    params = _stacked_params(1, 4)
    x = jax.random.normal(jax.random.PRNGKey(3), (4, 4))
    with use_mesh(mesh):
        out = pipeline(_stage_fn, params, x, num_microbatches=2, mesh=mesh)
    ref = _sequential(params, x, 1)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=1e-6)
