"""Prefill/decode disaggregation tests (reference: llm/_internal/serve/
deployments/prefill_decode_disagg/; SURVEY.md §2.7).

Correctness anchor: the disaggregated path (prefill on engine A, KV transfer,
decode on engine B) must reproduce the colocated engine's greedy output."""
import numpy as np
import pytest

from ray_tpu.llm import JaxLLMEngine, LLMConfig, SamplingParams


def _cfg(**kw):
    return LLMConfig(model_id="pd", model_source="test-tiny", max_num_seqs=2,
                     max_model_len=64, **kw)


def test_pd_matches_colocated_greedy():
    prompt = [1, 7, 42, 99, 5]
    params = SamplingParams(max_tokens=6, temperature=0.0, stop_token_ids=[-1])

    colo = JaxLLMEngine(_cfg())
    try:
        want = colo.generate_sync(prompt, params).token_ids
    finally:
        colo.shutdown()

    prefill_eng = JaxLLMEngine(_cfg())
    decode_eng = JaxLLMEngine(_cfg())
    try:
        pre = prefill_eng.prefill_only(prompt, params)
        assert pre["k"].shape[1] == 1 and isinstance(pre["k"], np.ndarray)
        ids = []
        for chunk in decode_eng.generate_from_prefill(pre, params):
            ids.extend(chunk.token_ids)
        assert ids == want
    finally:
        prefill_eng.shutdown()
        decode_eng.shutdown()


def test_pd_concurrent_decodes_share_slots():
    params = SamplingParams(max_tokens=4, temperature=0.0, stop_token_ids=[-1])
    prefill_eng = JaxLLMEngine(_cfg())
    decode_eng = JaxLLMEngine(_cfg())
    try:
        colo = JaxLLMEngine(_cfg())
        prompts = [[1, 2, 3], [1, 9, 8, 7], [1, 50, 51]]
        try:
            want = [colo.generate_sync(p, params).token_ids for p in prompts]
        finally:
            colo.shutdown()
        import threading

        got = [None] * len(prompts)

        def run(i):
            pre = prefill_eng.prefill_only(prompts[i], params)
            ids = []
            for chunk in decode_eng.generate_from_prefill(pre, params):
                ids.extend(chunk.token_ids)
            got[i] = ids

        ts = [threading.Thread(target=run, args=(i,)) for i in range(len(prompts))]
        for t in ts:
            t.start()
        for t in ts:
            t.join()
        assert got == want
    finally:
        prefill_eng.shutdown()
        decode_eng.shutdown()


def test_pd_serve_app(rt):
    from ray_tpu import serve
    from ray_tpu.llm import build_pd_openai_app

    cfg = LLMConfig(model_id="pd-app", model_source="byte-tiny", max_num_seqs=2,
                    max_model_len=64)
    serve.run(build_pd_openai_app(cfg), name="pd-app", route_prefix="/pd")
    try:
        h = serve.get_app_handle("pd-app")
        resp = h.options(method_name="chat").remote(
            {"messages": [{"role": "user", "content": "hi"}], "max_tokens": 3,
             "temperature": 0.0}).result()
        assert resp["object"] == "chat.completion"
        assert resp["usage"]["completion_tokens"] >= 1
    finally:
        serve.delete("pd-app")
