"""Prefill/decode disaggregation tests (reference: llm/_internal/serve/
deployments/prefill_decode_disagg/; SURVEY.md §2.7).

Correctness anchor: the disaggregated path (prefill on engine A, KV transfer,
decode on engine B) must reproduce the colocated engine's greedy output."""
import numpy as np
import pytest

from ray_tpu.llm import JaxLLMEngine, LLMConfig, SamplingParams


def _cfg(**kw):
    return LLMConfig(model_id="pd", model_source="test-tiny", max_num_seqs=2,
                     max_model_len=64, **kw)


def test_pd_matches_colocated_greedy(monkeypatch):
    # Pin the HOST transfer path (device plane off): the device-plane handoff has
    # its own coverage in test_device_plane.py::test_pd_disagg_kv_rides_device_plane.
    monkeypatch.setenv("RAY_TPU_DEVICE_PLANE", "0")
    prompt = [1, 7, 42, 99, 5]
    params = SamplingParams(max_tokens=6, temperature=0.0, stop_token_ids=[-1])

    colo = JaxLLMEngine(_cfg())
    try:
        want = colo.generate_sync(prompt, params).token_ids
    finally:
        colo.shutdown()

    prefill_eng = JaxLLMEngine(_cfg())
    decode_eng = JaxLLMEngine(_cfg())
    try:
        pre = prefill_eng.prefill_only(prompt, params)
        assert pre["k"].shape[1] == 1 and isinstance(pre["k"], np.ndarray)
        ids = []
        for chunk in decode_eng.generate_from_prefill(pre, params):
            ids.extend(chunk.token_ids)
        assert ids == want
    finally:
        prefill_eng.shutdown()
        decode_eng.shutdown()


def test_pd_concurrent_decodes_share_slots():
    params = SamplingParams(max_tokens=4, temperature=0.0, stop_token_ids=[-1])
    prefill_eng = JaxLLMEngine(_cfg())
    decode_eng = JaxLLMEngine(_cfg())
    try:
        colo = JaxLLMEngine(_cfg())
        prompts = [[1, 2, 3], [1, 9, 8, 7], [1, 50, 51]]
        try:
            want = [colo.generate_sync(p, params).token_ids for p in prompts]
        finally:
            colo.shutdown()
        import threading

        got = [None] * len(prompts)

        def run(i):
            pre = prefill_eng.prefill_only(prompts[i], params)
            ids = []
            for chunk in decode_eng.generate_from_prefill(pre, params):
                ids.extend(chunk.token_ids)
            got[i] = ids

        ts = [threading.Thread(target=run, args=(i,)) for i in range(len(prompts))]
        for t in ts:
            t.start()
        for t in ts:
            t.join()
        assert got == want
    finally:
        prefill_eng.shutdown()
        decode_eng.shutdown()


def test_pd_serve_app(rt):
    from ray_tpu import serve
    from ray_tpu.llm import build_pd_openai_app

    cfg = LLMConfig(model_id="pd-app", model_source="byte-tiny", max_num_seqs=2,
                    max_model_len=64)
    serve.run(build_pd_openai_app(cfg), name="pd-app", route_prefix="/pd")
    try:
        h = serve.get_app_handle("pd-app")
        resp = h.options(method_name="chat").remote(
            {"messages": [{"role": "user", "content": "hi"}], "max_tokens": 3,
             "temperature": 0.0}).result()
        assert resp["object"] == "chat.completion"
        assert resp["usage"]["completion_tokens"] >= 1
    finally:
        serve.delete("pd-app")


def test_pd_streaming_through_http_proxy(rt):
    """VERDICT r2 #6 bar: stream=true through the PDRouter — prefill returns
    transferable KV, the decode replica streams tokens, SSE frames arrive
    chunk-by-chunk through the real HTTP proxy."""
    import json
    import urllib.request

    from ray_tpu import serve
    from ray_tpu.llm import build_pd_openai_app

    cfg = LLMConfig(model_id="pd-sse", model_source="byte-tiny", max_num_seqs=2,
                    max_model_len=64)
    try:
        serve.run(build_pd_openai_app(cfg), name="pd-sse", route_prefix="/pdv1")
        serve.start(http_options={"port": 8127})
        # non-streaming reference for the same greedy request
        h = serve.get_app_handle("pd-sse")
        want = h.options(method_name="chat").remote(
            {"messages": [{"role": "user", "content": "hi"}], "max_tokens": 6,
             "temperature": 0.0}).result()["choices"][0]["message"]["content"]

        body = json.dumps({
            "model": "pd-sse", "stream": True, "max_tokens": 6,
            "temperature": 0.0,
            "messages": [{"role": "user", "content": "hi"}],
        }).encode()
        req = urllib.request.Request(
            "http://127.0.0.1:8127/pdv1/chat/completions", data=body,
            headers={"Content-Type": "application/json"})
        resp = urllib.request.urlopen(req, timeout=120)
        assert resp.headers.get("Content-Type", "").startswith("text/event-stream")
        frames = []
        buf = b""
        while True:
            chunk = resp.read(1)
            if not chunk:
                break
            buf += chunk
            while b"\n\n" in buf:
                frame, buf = buf.split(b"\n\n", 1)
                frames.append(frame.decode())
        assert frames[-1] == "data: [DONE]"
        datas = [json.loads(f[len("data: "):]) for f in frames[:-1]]
        assert datas[0]["choices"][0]["delta"].get("role") == "assistant"
        contents = [d["choices"][0]["delta"].get("content", "") for d in datas[1:]]
        # streamed deltas assemble to the non-streaming P/D answer
        assert "".join(c for c in contents if c) == want
        assert datas[-1]["choices"][0]["finish_reason"] is not None
        assert len(frames) >= 4  # role + >=1 content + finish + [DONE]
    finally:
        serve.shutdown()
