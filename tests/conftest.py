"""Test configuration.

JAX tests run on a virtual 8-device CPU mesh (reference analog: ray.cluster_utils.Cluster
single-machine multi-node simulation; SURVEY.md §4). Env vars must be set before anything
imports jax, hence module level here.
"""
import os

os.environ["JAX_PLATFORMS"] = "cpu"
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = _flags + " --xla_force_host_platform_device_count=8"

import jax  # noqa: E402

# The environment's sitecustomize may register an accelerator PJRT plugin and force the
# platform at the jax-config level, which ignores the env var — override after import.
jax.config.update("jax_platforms", "cpu")

import pytest  # noqa: E402


@pytest.fixture(scope="session")
def rt():
    """Session-wide ray_tpu cluster. Worker pool recovers from destructive tests."""
    import ray_tpu

    ray_tpu.init(num_cpus=4, worker_env={"JAX_PLATFORMS": "cpu"}, max_workers_per_node=8)
    yield ray_tpu
    ray_tpu.shutdown()
