"""Paged KV cache + chunked prefill (llm/paged.py).

Reference capability: vLLM PagedAttention block tables + chunked prefill —
the slot cache reserves max_model_len per slot; paging shares one pool.
"""
import threading
import time

import pytest

from ray_tpu.llm import JaxLLMEngine, LLMConfig, SamplingParams
from ray_tpu.models.config import ModelConfig

TINY = dict(vocab_size=64, d_model=32, n_layers=2, n_heads=4, n_kv_heads=2,
            d_ff=48, max_seq_len=512, remat=False, dtype="float32")


def _cfg():
    return ModelConfig(name="tiny-paged", **TINY)


def _greedy(engine, prompt, n=8):
    out = engine.generate_sync(prompt, SamplingParams(
        max_tokens=n, temperature=0.0, stop_token_ids=[-1]))
    return out.token_ids


COMMON = dict(max_num_seqs=4, max_model_len=128, dtype="float32")


def test_paged_matches_slot_layout():
    cfg = _cfg()
    slot_engine = JaxLLMEngine(LLMConfig(model_source=cfg, kv_layout="slot", **COMMON))
    paged_engine = JaxLLMEngine(LLMConfig(model_source=cfg, kv_layout="paged", **COMMON))
    for prompt in ("hello paged world", "a", "the quick brown fox"):
        assert _greedy(slot_engine, prompt) == _greedy(paged_engine, prompt)
    slot_engine.shutdown()
    paged_engine.shutdown()


def test_paged_capacity_beats_slot_at_equal_hbm():
    """Same KV HBM budget: the paged engine runs >2x the concurrent sequences.

    Slot layout: 4 slots x 128 tokens = 512 tokens of HBM, concurrency cap 4.
    Paged: the same 512-token pool (32 blocks x 16) shared by 16 slots admits
    every short request at once."""
    cfg = _cfg()
    engine = JaxLLMEngine(LLMConfig(
        model_source=cfg, kv_layout="paged", max_num_seqs=16, max_model_len=128,
        num_kv_blocks=32, kv_block_size=16, dtype="float32"))
    engine.start()
    peak = [0]
    done = []

    def run(i):
        out = engine.generate_sync(f"req {i}", SamplingParams(
            max_tokens=12, temperature=0.0, stop_token_ids=[-1]))
        done.append(out)

    threads = [threading.Thread(target=run, args=(i,)) for i in range(12)]
    for t in threads:
        t.start()
    deadline = time.time() + 120
    while any(t.is_alive() for t in threads):
        peak[0] = max(peak[0], engine.num_active)
        assert time.time() < deadline
        time.sleep(0.005)
    assert len(done) == 12
    assert all(len(o.token_ids) == 12 for o in done)
    # slot layout with this HBM caps at 4 concurrent; paged must exceed 2x that
    assert peak[0] > 8, f"peak concurrency {peak[0]} (expected > 8)"
    engine.shutdown()


def test_preemption_recomputes_correctly():
    """Pool far too small for all requests: the youngest gets preempted
    (recompute) and still produces exactly the unconstrained output."""
    cfg = _cfg()
    ref_engine = JaxLLMEngine(LLMConfig(model_source=cfg, kv_layout="slot", **COMMON))
    want = {p: _greedy(ref_engine, p, n=24) for p in ("first request here",
                                                      "second one", "third prompt x")}
    ref_engine.shutdown()

    engine = JaxLLMEngine(LLMConfig(
        model_source=cfg, kv_layout="paged", max_num_seqs=4, max_model_len=128,
        num_kv_blocks=6, kv_block_size=16, dtype="float32"))  # 96 tokens total
    engine.start()
    results = {}

    def run(p):
        results[p] = _greedy(engine, p, n=24)

    threads = [threading.Thread(target=run, args=(p,)) for p in want]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=180)
    assert not any(t.is_alive() for t in threads), "paged engine deadlocked"
    assert results == want
    # all blocks returned to the pool after completion
    assert engine._blocks.num_free == 6
    m = engine.metrics()
    assert m["num_preemptions"] >= 1  # the pool WAS too small; surfaced
    assert m["kv_blocks_total"] == 6 and m["kv_pool_occupancy"] == 0.0
    engine.shutdown()


def test_chunked_prefill_matches_whole_prompt():
    cfg = _cfg()
    long_prompt = "word " * 60  # ~300 byte-tokens, > one 64-token chunk
    whole = JaxLLMEngine(LLMConfig(
        model_source=cfg, kv_layout="paged", max_num_seqs=2, max_model_len=512,
        dtype="float32"))
    chunked = JaxLLMEngine(LLMConfig(
        model_source=cfg, kv_layout="paged", max_num_seqs=2, max_model_len=512,
        prefill_chunk=64, dtype="float32"))
    assert _greedy(whole, long_prompt) == _greedy(chunked, long_prompt)
    whole.shutdown()
    chunked.shutdown()


def test_paged_pd_disaggregation_transfer():
    """P/D transfer installs into blocks on the decode side."""
    cfg = _cfg()
    prefill_engine = JaxLLMEngine(LLMConfig(model_source=cfg, kv_layout="slot", **COMMON))
    decode_engine = JaxLLMEngine(LLMConfig(model_source=cfg, kv_layout="paged", **COMMON))
    ref_engine = JaxLLMEngine(LLMConfig(model_source=cfg, kv_layout="slot", **COMMON))

    params = SamplingParams(max_tokens=8, temperature=0.0, stop_token_ids=[-1])
    pre = prefill_engine.prefill_only("transfer me", params)
    ids = []
    for chunk in decode_engine.generate_from_prefill(pre, params):
        ids.extend(chunk.token_ids)
    assert ids[:8] == _greedy(ref_engine, "transfer me", n=8)
    for e in (prefill_engine, decode_engine, ref_engine):
        e.shutdown()


def test_pipeline_parallel_decode_matches_single():
    """pp=2 on the CPU mesh: layer stack + KV split across stages, microbatched
    decode produces exactly the single-device tokens (VERDICT: engine test with
    pp=2 on CPU mesh)."""
    cfg = ModelConfig(name="tiny-pp", **TINY)
    import jax

    from ray_tpu.models import llama

    params = llama.init(jax.random.PRNGKey(0), cfg)
    ref = JaxLLMEngine(LLMConfig(model_source=cfg, **COMMON), params=params)
    pp = JaxLLMEngine(LLMConfig(model_source=cfg, pipeline_parallel_size=2, **COMMON),
                      params=params)
    for prompt in ("pipeline me", "another prompt"):
        assert _greedy(ref, prompt) == _greedy(pp, prompt)
    # cache sharding really spans the pp axis
    assert len(pp.state.k.sharding.device_set) == 2
    ref.shutdown()
    pp.shutdown()


def test_pipeline_parallel_with_tp():
    cfg = ModelConfig(name="tiny-pp-tp", **TINY)
    import jax

    from ray_tpu.models import llama

    params = llama.init(jax.random.PRNGKey(1), cfg)
    ref = JaxLLMEngine(LLMConfig(model_source=cfg, **COMMON), params=params)
    pptp = JaxLLMEngine(LLMConfig(model_source=cfg, pipeline_parallel_size=2,
                                  tensor_parallel_size=2, **COMMON), params=params)
    assert _greedy(ref, "compose pp with tp") == _greedy(pptp, "compose pp with tp")
    assert len(pptp.state.k.sharding.device_set) == 4
    ref.shutdown()
    pptp.shutdown()


def test_chunked_prefill_slot_layout_matches():
    cfg = _cfg()
    long_prompt = "tok " * 50
    whole = JaxLLMEngine(LLMConfig(model_source=cfg, kv_layout="slot",
                                   max_num_seqs=2, max_model_len=256, dtype="float32"))
    chunked = JaxLLMEngine(LLMConfig(model_source=cfg, kv_layout="slot",
                                     max_num_seqs=2, max_model_len=256,
                                     prefill_chunk=64, dtype="float32"))
    assert _greedy(whole, long_prompt) == _greedy(chunked, long_prompt)
    whole.shutdown()
    chunked.shutdown()


def test_oversized_pd_transfer_fails_cleanly():
    """A P/D transfer padded past the decode engine's table width must finish
    with 'length', not crash the loop or hang the client."""
    cfg = _cfg()
    prefill_engine = JaxLLMEngine(LLMConfig(
        model_source=cfg, kv_layout="slot", max_num_seqs=2, max_model_len=512,
        dtype="float32"))
    decode_engine = JaxLLMEngine(LLMConfig(
        model_source=cfg, kv_layout="paged", max_num_seqs=2, max_model_len=64,
        dtype="float32"))
    params = SamplingParams(max_tokens=4, temperature=0.0, stop_token_ids=[-1])
    pre = prefill_engine.prefill_only("x " * 100, params)  # pads past 64
    outs = list(decode_engine.generate_from_prefill(pre, params))
    assert outs[-1].finished and outs[-1].finish_reason == "length"
    prefill_engine.shutdown()
    decode_engine.shutdown()


def test_bad_prefill_chunk_rejected():
    cfg = _cfg()
    eng = JaxLLMEngine(LLMConfig(model_source=cfg, kv_layout="paged",
                                 max_num_seqs=2, max_model_len=128,
                                 prefill_chunk=96, dtype="float32"))
    with pytest.raises(ValueError, match="multiple of prefill_chunk"):
        eng.start()


def test_prefix_cache_reuses_blocks():
    """vLLM-style automatic prefix caching: an identical prompt's full blocks
    are served from the cache (no recomputation), and generation is unchanged."""
    cfg = _cfg()
    engine = JaxLLMEngine(LLMConfig(
        model_source=cfg, kv_layout="paged", max_num_seqs=4, max_model_len=128,
        kv_block_size=16, dtype="float32"))
    prompt = "x" * 40  # 41 byte-tokens -> 2 full blocks cacheable
    first = _greedy(engine, prompt)
    assert engine._blocks.hit_tokens == 0
    second = _greedy(engine, prompt)
    assert second == first
    assert engine._blocks.hit_tokens >= 32  # two full blocks reused
    m = engine.metrics()
    assert m["prefix_cache_hit_tokens"] >= 32  # surfaced in engine.metrics()
    assert m["prefix_cached_blocks"] >= 2
    # a fresh engine agrees (the context-prefill path is numerically faithful)
    ref = JaxLLMEngine(LLMConfig(model_source=cfg, kv_layout="slot", **COMMON))
    assert _greedy(ref, prompt) == first
    ref.shutdown()
    engine.shutdown()


def test_prefix_cache_shared_prefix_different_suffixes():
    cfg = _cfg()
    engine = JaxLLMEngine(LLMConfig(
        model_source=cfg, kv_layout="paged", max_num_seqs=4, max_model_len=128,
        kv_block_size=16, dtype="float32"))
    ref = JaxLLMEngine(LLMConfig(model_source=cfg, kv_layout="slot", **COMMON))
    base = "shared prefix " * 3  # 43 tokens incl. bos
    for tail in ("alpha", "beta gamma", "z"):
        assert _greedy(engine, base + tail) == _greedy(ref, base + tail), tail
    assert engine._blocks.hit_tokens >= 32
    ref.shutdown()
    engine.shutdown()


def test_prefix_cache_eviction_under_pressure():
    """Unreferenced cached blocks are reclaimable: a pool-filling request evicts
    them rather than failing."""
    cfg = _cfg()
    engine = JaxLLMEngine(LLMConfig(
        model_source=cfg, kv_layout="paged", max_num_seqs=2, max_model_len=128,
        num_kv_blocks=10, kv_block_size=16, dtype="float32"))  # 160 tokens
    _greedy(engine, "c" * 60)  # leaves ~4 cached blocks at ref 0
    assert engine._blocks.cached
    out = _greedy(engine, "d" * 100, n=16)  # needs ~8 blocks: forces eviction
    assert len(out) == 16
    engine.shutdown()


def test_prefix_cache_with_chunked_long_prompts():
    """A chunked long prompt seeds the cache; a sibling sharing its prefix with
    a short new suffix takes the cached-context path."""
    cfg = _cfg()
    engine = JaxLLMEngine(LLMConfig(
        model_source=cfg, kv_layout="paged", max_num_seqs=2, max_model_len=256,
        kv_block_size=16, prefill_chunk=64, dtype="float32"))
    ref = JaxLLMEngine(LLMConfig(
        model_source=cfg, kv_layout="slot", max_num_seqs=2, max_model_len=256,
        dtype="float32"))
    base = "common system preamble " * 6  # ~139 tokens > chunk
    a = _greedy(engine, base + "one")
    assert a == _greedy(ref, base + "one")
    hits_before = engine._blocks.hit_tokens
    b = _greedy(engine, base + "two!")
    assert b == _greedy(ref, base + "two!")
    assert engine._blocks.hit_tokens > hits_before
    ref.shutdown()
    engine.shutdown()


# ------------------------------------------------- paged x data-parallel (dp)

def test_paged_dp_matches_dp1():
    """kv_layout='paged' with data_parallel_size=2 (per-replica pool partitions
    under one shard_map'd SPMD program — paged.py dp section): greedy output is
    IDENTICAL to the dp=1 paged engine and to the slot layout, including with
    enough concurrency that both replicas hold active slots."""
    cfg = _cfg()
    ref = JaxLLMEngine(LLMConfig(model_source=cfg, kv_layout="paged", **COMMON))
    dp = JaxLLMEngine(LLMConfig(model_source=cfg, kv_layout="paged",
                                data_parallel_size=2, **COMMON))
    try:
        prompts = ["hello paged world", "a", "the quick brown fox", "zz top"]
        wants = [_greedy(ref, p) for p in prompts]

        # sequential equivalence
        for p, want in zip(prompts, wants):
            assert _greedy(dp, p) == want

        # concurrent: 4 requests over 4 slots = 2 per replica
        outs = [None] * len(prompts)

        def run(i):
            outs[i] = _greedy(dp, prompts[i])

        threads = [threading.Thread(target=run, args=(i,)) for i in range(4)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        assert outs == wants
        assert dp._blocks.dp == 2  # really ran the sharded manager
    finally:
        ref.shutdown()
        dp.shutdown()


def test_paged_dp_fused_and_spec_match():
    """The full composition: paged x dp x fused multi-step x speculative
    decoding in one engine — still exactly greedy."""
    cfg = _cfg()
    ref = JaxLLMEngine(LLMConfig(model_source=cfg, kv_layout="slot", **COMMON))
    eng = JaxLLMEngine(LLMConfig(model_source=cfg, kv_layout="paged",
                                 data_parallel_size=2, num_decode_steps=4,
                                 num_speculative_tokens=3, **COMMON))
    try:
        for prompt in ("spec dp prompt", "ababab ababab"):
            want = _greedy(ref, prompt, n=10)
            assert _greedy(eng, prompt, n=10) == want
    finally:
        ref.shutdown()
        eng.shutdown()


def test_paged_dp_preemption_stays_in_replica():
    """Pool pressure inside one replica preempts ONLY that replica's requests
    (recompute preemption per partition) and every output is still exact."""
    cfg = _cfg()
    ref = JaxLLMEngine(LLMConfig(model_source=cfg, kv_layout="slot",
                                 max_num_seqs=4, max_model_len=128,
                                 dtype="float32"))
    eng = JaxLLMEngine(LLMConfig(
        model_source=cfg, kv_layout="paged", data_parallel_size=2,
        max_num_seqs=4, max_model_len=128, num_kv_blocks=16, kv_block_size=8,
        dtype="float32"))  # 8 blocks (64 tokens) per replica partition
    try:
        prompts = [f"pressure request {i} " * 2 for i in range(4)]
        wants = [_greedy(ref, p, n=24) for p in prompts]
        outs = [None] * 4

        def run(i):
            outs[i] = _greedy(eng, prompts[i], n=24)

        threads = [threading.Thread(target=run, args=(i,)) for i in range(4)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        assert outs == wants
    finally:
        ref.shutdown()
        eng.shutdown()


def test_paged_dp_prefix_cache_per_replica():
    """The prefix cache is per-replica partition: a repeat of a prompt admitted
    to the same replica reuses its blocks (hit_tokens grows)."""
    cfg = _cfg()
    eng = JaxLLMEngine(LLMConfig(model_source=cfg, kv_layout="paged",
                                 data_parallel_size=2, max_num_seqs=4,
                                 max_model_len=128, kv_block_size=8,
                                 dtype="float32"))
    try:
        prompt = "shared prefix payload " * 3
        first = _greedy(eng, prompt)
        # all slots free again; the ranked free-slot order re-admits into the
        # same replica (ties keep slot order), where the blocks are cached
        again = _greedy(eng, prompt)
        assert again == first
        assert eng._blocks.hit_tokens > 0
    finally:
        eng.shutdown()


def test_pipeline_parallel_with_ep_moe():
    """pp composes with expert parallelism: a MoE model decodes through the
    pp2/ep2 microbatched schedule (experts stay a GSPMD auto axis inside each
    stage, like tp) and reproduces the single-device tokens exactly."""
    import jax

    from ray_tpu.models import llama
    from ray_tpu.models.config import ModelConfig

    cfg = ModelConfig(name="tiny-pp-ep", **{**TINY, "max_seq_len": 128},
                      n_experts=4, moe_top_k=2)
    params = llama.init(jax.random.PRNGKey(2), cfg)
    ref = JaxLLMEngine(LLMConfig(model_source=cfg, **COMMON), params=params)
    ppep = JaxLLMEngine(LLMConfig(model_source=cfg, pipeline_parallel_size=2,
                                  expert_parallel_size=2, **COMMON),
                        params=params)
    for prompt in ("mixture pipeline", "experts in stages"):
        assert _greedy(ref, prompt) == _greedy(ppep, prompt)
    assert len(ppep.state.k.sharding.device_set) == 4
    ref.shutdown()
    ppep.shutdown()


def test_pipeline_parallel_with_dp():
    """pp composes with dp on the slot layout: slots shard over dp replicas
    (contiguous ranges, matching the cache's slot axis), each replica runs the
    pp microbatch schedule on its slots; tokens match the single-device run."""
    import jax

    from ray_tpu.models import llama
    from ray_tpu.models.config import ModelConfig

    cfg = ModelConfig(name="tiny-pp-dp", **TINY)
    params = llama.init(jax.random.PRNGKey(3), cfg)
    ref = JaxLLMEngine(LLMConfig(model_source=cfg, **COMMON), params=params)
    ppdp = JaxLLMEngine(LLMConfig(model_source=cfg, pipeline_parallel_size=2,
                                  data_parallel_size=2, **COMMON),
                        params=params)
    for prompt in ("pipeline with replicas", "slots across dp"):
        assert _greedy(ref, prompt) == _greedy(ppdp, prompt)
    assert len(ppdp.state.k.sharding.device_set) == 4
    # concurrent requests fill slots across both replicas
    outs = []
    threads = [threading.Thread(target=lambda p=p: outs.append(_greedy(ppdp, p)))
               for p in ("a b c", "d e f", "g h i", "j k l")]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert len(outs) == 4 and all(len(o) == 8 for o in outs)
    ref.shutdown()
    ppdp.shutdown()


def test_pipeline_parallel_paged_matches_single():
    """pp composes with the PAGED layout: each stage holds its layers' slice of
    the block pool (POOL_SPEC_PP), slots microbatch through the schedule, and
    bubble-tick writes land in the scratch block. Tokens match the
    single-device slot engine exactly."""
    import jax

    from ray_tpu.models import llama
    from ray_tpu.models.config import ModelConfig

    cfg = ModelConfig(name="tiny-pp-paged", **TINY)
    params = llama.init(jax.random.PRNGKey(4), cfg)
    ref = JaxLLMEngine(LLMConfig(model_source=cfg, kv_layout="slot", **COMMON),
                       params=params)
    pp = JaxLLMEngine(LLMConfig(model_source=cfg, kv_layout="paged",
                                pipeline_parallel_size=2, kv_block_size=16,
                                **COMMON), params=params)
    for prompt in ("paged pipeline", "stage pools"):
        assert _greedy(ref, prompt) == _greedy(pp, prompt)
    # the pool genuinely spans the pp axis
    assert len(pp.state.k.sharding.device_set) == 2
    ref.shutdown()
    pp.shutdown()


def test_pipeline_parallel_paged_with_tp_long_decode():
    """pp2 x tp2 paged decode across a block boundary (decode appends blocks
    mid-generation) still matches the single-device run."""
    import jax

    from ray_tpu.models import llama
    from ray_tpu.models.config import ModelConfig

    cfg = ModelConfig(name="tiny-pp-paged-tp", **TINY)
    params = llama.init(jax.random.PRNGKey(5), cfg)
    ref = JaxLLMEngine(LLMConfig(model_source=cfg, kv_layout="slot", **COMMON),
                       params=params)
    pptp = JaxLLMEngine(LLMConfig(model_source=cfg, kv_layout="paged",
                                  pipeline_parallel_size=2,
                                  tensor_parallel_size=2, kv_block_size=16,
                                  **COMMON), params=params)
    prompt = "long decode across block boundaries " * 2
    assert _greedy(ref, prompt, n=24) == _greedy(pptp, prompt, n=24)
    ref.shutdown()
    pptp.shutdown()


def test_pipeline_parallel_paged_with_dp():
    """The full pp x dp x paged composition: layers+pool slices over pp stages,
    slots + independent pool partitions over dp replicas, one manual region.
    Tokens match the single-device slot engine; concurrent requests land on
    both replicas."""
    import jax

    from ray_tpu.models import llama
    from ray_tpu.models.config import ModelConfig

    cfg = ModelConfig(name="tiny-pp-dp-paged", **TINY)
    params = llama.init(jax.random.PRNGKey(6), cfg)
    ref = JaxLLMEngine(LLMConfig(model_source=cfg, kv_layout="slot", **COMMON),
                       params=params)
    eng = JaxLLMEngine(LLMConfig(model_source=cfg, kv_layout="paged",
                                 pipeline_parallel_size=2,
                                 data_parallel_size=2, kv_block_size=16,
                                 **COMMON), params=params)
    for prompt in ("full composition", "replica stage pools"):
        assert _greedy(ref, prompt) == _greedy(eng, prompt)
    # long decode crosses a block boundary (kv_block_size=16): mid-generation
    # append_block under the pp x dp pool layout still matches
    long_prompt = "decode across block boundaries in both axes " * 2
    assert _greedy(ref, long_prompt, n=24) == _greedy(eng, long_prompt, n=24)
    assert len(eng.state.k.sharding.device_set) == 4
    outs = []
    threads = [threading.Thread(target=lambda p=p: outs.append(_greedy(eng, p)))
               for p in ("aa bb", "cc dd", "ee ff", "gg hh")]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert len(outs) == 4 and all(len(o) == 8 for o in outs)
    ref.shutdown()
    eng.shutdown()
