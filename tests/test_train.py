"""Train library tests (reference analogue: python/ray/train tests; SURVEY.md §2.4).

Training loops here are numpy-cheap — the jitted TPU step core has its own tests
(test_llama.py); these cover the Trainer/session/checkpoint/failure machinery.
"""
import json
import os

import pytest

from ray_tpu.air import CheckpointConfig, FailureConfig, RunConfig, ScalingConfig
from ray_tpu.train import Checkpoint, JaxConfig, JaxTrainer
from ray_tpu.train.checkpoint_manager import CheckpointManager


def _loop_basic(config):
    import ray_tpu.train as train

    ctx = train.get_context()
    for step in range(config["steps"]):
        train.report({"step": step, "rank": ctx.get_world_rank(), "ws": ctx.get_world_size()})


def test_jax_trainer_reports(rt, tmp_path):
    trainer = JaxTrainer(
        _loop_basic,
        train_loop_config={"steps": 3},
        backend_config=JaxConfig(collective_group=False),
        scaling_config=ScalingConfig(num_workers=2, cpus_per_worker=0.5),
        run_config=RunConfig(name="t_basic", storage_path=str(tmp_path)),
    )
    result = trainer.fit()
    assert result.error is None
    assert result.metrics["step"] == 2
    assert result.metrics["ws"] == 2
    assert result.metrics["rank"] == 0  # rank-0 metrics are canonical
    steps = [m["step"] for m in result.metrics_dataframe]
    assert steps == [0, 1, 2]
    assert result.path == str(tmp_path / "t_basic")


def _loop_ckpt(config):
    import tempfile

    import ray_tpu.train as train

    ctx = train.get_context()
    ckpt = train.get_checkpoint()
    start = 0
    if ckpt is not None:
        with ckpt.as_directory() as d:
            start = json.load(open(os.path.join(d, "state.json")))["step"] + 1
    for step in range(start, config["steps"]):
        if step == 3 and ckpt is None and config.get("fail_once"):
            raise RuntimeError("injected worker failure")
        checkpoint = None
        if ctx.get_world_rank() == 0:
            d = tempfile.mkdtemp(prefix="wkr_ckpt_")
            json.dump({"step": step}, open(os.path.join(d, "state.json"), "w"))
            checkpoint = Checkpoint.from_directory(d)
        train.report({"step": step}, checkpoint=checkpoint)


def test_checkpoint_and_restart_on_failure(rt, tmp_path):
    trainer = JaxTrainer(
        _loop_ckpt,
        train_loop_config={"steps": 6, "fail_once": True},
        backend_config=JaxConfig(collective_group=False),
        scaling_config=ScalingConfig(num_workers=2, cpus_per_worker=0.5),
        run_config=RunConfig(
            name="t_restart",
            storage_path=str(tmp_path),
            failure_config=FailureConfig(max_failures=1),
        ),
    )
    result = trainer.fit()
    assert result.error is None, result.error
    # Resumed at step 3 from the step-2 checkpoint and ran to 5.
    assert result.metrics["step"] == 5
    assert result.checkpoint is not None
    meta = result.checkpoint.get_metadata()
    assert meta["metrics"]["step"] == 5


def test_failure_budget_exhausted(rt, tmp_path):
    trainer = JaxTrainer(
        lambda config: (_ for _ in ()).throw(RuntimeError("always fails")),
        backend_config=JaxConfig(collective_group=False),
        scaling_config=ScalingConfig(num_workers=1),
        run_config=RunConfig(name="t_fail", storage_path=str(tmp_path)),
    )
    result = trainer.fit()
    assert result.error is not None
    assert "always fails" in result.error


def test_checkpoint_manager_topk(tmp_path):
    import tempfile

    mgr = CheckpointManager(
        str(tmp_path / "run"),
        CheckpointConfig(num_to_keep=2, checkpoint_score_attribute="acc", checkpoint_score_order="max"),
    )
    for i, acc in enumerate([0.1, 0.9, 0.5, 0.2]):
        d = tempfile.mkdtemp()
        open(os.path.join(d, "w.txt"), "w").write(str(i))
        mgr.register(Checkpoint(d), {"acc": acc})
    kept = mgr.list()
    # top-2 by acc = (0.9, 0.5) plus the latest (acc 0.2) is never deleted
    accs = sorted(c.get_metadata()["metrics"]["acc"] for c in kept)
    assert accs == [0.2, 0.5, 0.9]
    assert mgr.best_checkpoint.get_metadata()["metrics"]["acc"] == 0.9
    assert mgr.latest_checkpoint.get_metadata()["metrics"]["acc"] == 0.2


def _loop_fast(config):
    import ray_tpu.train as train

    for step in range(100):
        train.report({"step": step})


def test_fast_loop_reports_not_dropped(rt, tmp_path):
    """A loop that finishes within one poll interval must not lose trailing reports."""
    trainer = JaxTrainer(
        _loop_fast,
        backend_config=JaxConfig(collective_group=False),
        scaling_config=ScalingConfig(num_workers=1),
        run_config=RunConfig(name="t_fast", storage_path=str(tmp_path)),
    )
    result = trainer.fit()
    assert result.error is None
    assert [m["step"] for m in result.metrics_dataframe] == list(range(100))


def test_session_api_outside_worker_raises():
    import ray_tpu.train as train

    with pytest.raises(RuntimeError):
        train.report({})
    with pytest.raises(RuntimeError):
        train.get_context()
