"""DQN tests (reference rllib/algorithms/dqn tests; SURVEY.md §2.5 algorithms row)."""
import numpy as np
import pytest

from ray_tpu.rllib.core.distributions import EpsilonGreedyQ
from ray_tpu.rllib.utils.replay_buffer import PrioritizedReplayBuffer, ReplayBuffer


def _fake_episode(t, obs_dim=4, terminated=True, offset=0.0):
    obs = np.arange((t + 1) * obs_dim, dtype=np.float32).reshape(t + 1, obs_dim) + offset
    return {
        "obs": obs[:-1],
        "next_obs_last": obs[-1],
        "actions": np.arange(t) % 2,
        "rewards": np.ones(t, np.float32),
        "terminated": terminated,
        "truncated": False,
    }


def test_replay_buffer_transitions_and_dones():
    buf = ReplayBuffer(capacity=100)
    assert buf.add_episodes([_fake_episode(5)]) == 5
    assert len(buf) == 5
    batch = buf.sample(32, np.random.default_rng(0))
    assert batch["obs"].shape == (32, 4)
    # next_obs must be obs shifted by one step
    st = buf._storage
    np.testing.assert_array_equal(st["next_obs"][0], st["obs"][1])
    assert st["dones"][4] == 1.0 and st["dones"][:4].sum() == 0
    # truncation does not set done (bootstrap continues)
    buf2 = ReplayBuffer(capacity=100)
    buf2.add_episodes([{**_fake_episode(3), "terminated": False, "truncated": True}])
    assert buf2._storage["dones"][:3].sum() == 0


def test_replay_buffer_ring_wraps():
    buf = ReplayBuffer(capacity=8)
    buf.add_episodes([_fake_episode(20)])
    assert len(buf) == 8


def test_prioritized_replay_weights_and_updates():
    buf = PrioritizedReplayBuffer(capacity=64, alpha=0.6, beta=0.4)
    buf.add_episodes([_fake_episode(16)])
    rng = np.random.default_rng(0)
    batch = buf.sample(8, rng)
    assert "weights" in batch and "batch_indexes" in batch
    assert batch["weights"].max() <= 1.0 + 1e-6
    buf.update_priorities(batch["batch_indexes"], np.full(8, 100.0))
    # heavily-prioritized samples dominate subsequent draws
    counts = np.zeros(len(buf))
    for _ in range(50):
        b = buf.sample(4, rng)
        for i in b["batch_indexes"]:
            counts[i] += 1
    hot = set(batch["batch_indexes"].tolist())
    cold = [i for i in range(len(buf)) if i not in hot]
    assert counts[list(hot)].sum() > counts[cold].sum()


def test_replay_buffer_n_step():
    """3-step transitions: discounted reward sums, obs[t+3] targets, γ³ bootstrap."""
    g = 0.9
    buf = ReplayBuffer(capacity=100, n_step=3, gamma=g)
    ep = _fake_episode(6, terminated=True)
    ep["rewards"] = np.arange(1, 7, dtype=np.float32)  # 1..6
    buf.add_episodes([ep])
    st = buf._storage
    # transition 0: r = 1 + g*2 + g^2*3
    assert abs(st["rewards"][0] - (1 + g * 2 + g * g * 3)) < 1e-5
    # next_obs of transition 0 is obs[3]
    np.testing.assert_array_equal(st["next_obs"][0], st["obs"][3])
    # window clips at the end: transition 5 only sees reward 6
    assert abs(st["rewards"][5] - 6.0) < 1e-5
    # terminal reaches the last n transitions
    np.testing.assert_array_equal(st["dones"][:6], [0, 0, 0, 1, 1, 1])


def test_epsilon_greedy_dist():
    q = np.array([[0.0, 5.0, 1.0]] * 1000, np.float32)
    rng = np.random.default_rng(0)
    # epsilon 0 -> always greedy
    inp = np.concatenate([q, np.zeros((1000, 1), np.float32)], axis=1)
    assert (EpsilonGreedyQ.sample_np(inp, rng) == 1).all()
    assert (EpsilonGreedyQ.greedy_np(inp) == 1).all()
    # epsilon 1 -> roughly uniform
    inp = np.concatenate([q, np.ones((1000, 1), np.float32)], axis=1)
    acts = EpsilonGreedyQ.sample_np(inp, rng)
    assert len(np.unique(acts)) == 3
    assert 200 < (acts == 0).sum() < 500


def test_dqn_learns_cartpole(rt):
    """DQN must improve over random on CartPole within a few iterations."""
    from ray_tpu.rllib import DQNConfig

    config = (
        DQNConfig()
        .environment("CartPole-v1")
        .env_runners(num_env_runners=2, num_envs_per_env_runner=4,
                     rollout_fragment_length=64)
        .training(
            lr=1e-3, gamma=0.99, train_batch_size=128,
            replay_buffer_capacity=20_000,
            num_steps_sampled_before_learning_starts=500,
            target_network_update_freq=50,
            epsilon=(1.0, 0.05), epsilon_timesteps=3000,
            num_updates_per_iteration=64,
            sample_timesteps_per_iteration=512,
        )
    )
    algo = config.build_algo()
    try:
        first_return = None
        best = -np.inf
        for i in range(20):
            result = algo.step()
            ret = result.get("episode_return_mean")
            if ret is not None:
                if first_return is None:
                    first_return = ret
                best = max(best, ret)
        assert result["epsilon"] < 0.5  # schedule actually decayed
        assert result["mean_q"] > 5.0  # Q-values moved well off init
        assert best >= 28.0, (first_return, best)
        assert best > first_return + 6.0, (first_return, best)
    finally:
        algo.stop()


def test_dqn_prioritized_replay_runs(rt):
    from ray_tpu.rllib import DQNConfig

    config = (
        DQNConfig()
        .environment("CartPole-v1")
        .env_runners(num_env_runners=1, num_envs_per_env_runner=2,
                     rollout_fragment_length=32)
        .training(prioritized_replay=True, train_batch_size=32,
                  num_steps_sampled_before_learning_starts=100,
                  num_updates_per_iteration=4,
                  sample_timesteps_per_iteration=128)
    )
    algo = config.build_algo()
    try:
        for _ in range(3):
            result = algo.step()
        assert np.isfinite(result.get("total_loss", 0.0))
    finally:
        algo.stop()
