"""Head fault tolerance: SIGKILL the head process, restart it on the same
ports, and keep using the cluster.

Reference: GCS server restart backed by Redis while raylets buffer and re-sync
(src/ray/gcs/gcs_server/gcs_redis_failure_detector.h, NotifyGCSRestart in
src/ray/protobuf/node_manager.proto:316). Here: the head journals detached/
named actor placements into the GCS KV journal; the surviving node agent
redials with backoff and re-registers its node id, its still-running worker
processes, and its arena contents; the restarted head rebinds the actors and
rebuilds the object directory.
"""
import os
import signal
import socket
import subprocess
import sys
import time

import pytest


def _free_port():
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    p = s.getsockname()[1]
    s.close()
    return p


def _spawn_head(env, node_port, client_port):
    proc = subprocess.Popen(
        [sys.executable, os.path.join(os.path.dirname(__file__), "_head_main.py"),
         str(node_port), str(client_port)],
        env=env, stdout=subprocess.PIPE, text=True)
    deadline = time.time() + 60
    while True:
        line = proc.stdout.readline()
        if "HEAD_READY" in line:
            return proc
        assert proc.poll() is None and time.time() < deadline, "head never started"


@pytest.fixture()
def restart_env(rt, tmp_path):
    """Shared session dir + GCS journal for all processes in the test; the
    session cluster is parked for the duration."""
    import ray_tpu

    ray_tpu.shutdown()
    repo_root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    env = {**os.environ,
           "JAX_PLATFORMS": "cpu",
           "PYTHONPATH": repo_root + os.pathsep + os.environ.get("PYTHONPATH", ""),
           "RAY_TPU_SESSION_DIR": str(tmp_path / "session"),
           "RAY_TPU_GCS_PERSISTENCE_PATH": str(tmp_path / "gcs.journal"),
           "RAY_TPU_AGENT_RECONNECT_TIMEOUT_S": "60"}
    saved = {k: os.environ.get(k) for k in
             ("RAY_TPU_SESSION_DIR", "RAY_TPU_GCS_PERSISTENCE_PATH")}
    os.environ.update({k: env[k] for k in saved})
    procs = []
    try:
        yield env, procs
    finally:
        for p in procs:
            if p.poll() is None:
                p.terminate()
        for p in procs:
            try:
                p.wait(timeout=10)
            except subprocess.TimeoutExpired:
                p.kill()
        for k, v in saved.items():
            if v is None:
                os.environ.pop(k, None)
            else:
                os.environ[k] = v
        ray_tpu.shutdown()
        ray_tpu.init(num_cpus=4, worker_env={"JAX_PLATFORMS": "cpu"},
                     max_workers_per_node=8)


def test_head_restart_actor_and_object_survive(restart_env):
    import ray_tpu
    from ray_tpu.core.ids import ObjectID
    from ray_tpu.core.object_ref import ObjectRef
    from ray_tpu.core.task_spec import NodeAffinitySchedulingStrategy

    env, procs = restart_env
    node_port, client_port = _free_port(), _free_port()
    head = _spawn_head(env, node_port, client_port)
    procs.append(head)
    agent = subprocess.Popen(
        [sys.executable, "-m", "ray_tpu.core.node_agent",
         "--address", f"127.0.0.1:{node_port}", "--num-cpus", "2"], env=env)
    procs.append(agent)

    # -- before: a detached named actor + a big object, both on the agent -------
    ray_tpu.init(address=f"ray-tpu://127.0.0.1:{client_port}")
    deadline = time.time() + 30
    while len([n for n in ray_tpu.nodes() if n["Alive"]]) < 2:
        assert time.time() < deadline, "agent never joined"
        time.sleep(0.2)
    remote_id = next(n["NodeID"] for n in ray_tpu.nodes()
                     if n["Alive"] and n["Labels"].get("agent") == "remote")
    sched = NodeAffinitySchedulingStrategy(node_id=remote_id)

    @ray_tpu.remote(scheduling_strategy=sched, lifetime="detached",
                    name="survivor", max_restarts=0)
    class Survivor:
        def __init__(self):
            self.n = 0

        def bump(self):
            self.n += 1
            return self.n

        def make_data(self, k):
            import numpy as np

            import ray_tpu as rt

            # the actor OWNS the object (instance-held ref): it stays pinned in
            # this host's arena across client disconnects and head restarts
            self.keep = rt.put(np.full(100_000, float(k)))
            return self.keep.id.hex()

    s = Survivor.remote()
    assert ray_tpu.get(s.bump.remote(), timeout=60) == 1
    oid_hex = ray_tpu.get(s.make_data.remote(7.0), timeout=60)
    ref0 = ObjectRef(ObjectID.from_hex(oid_hex))
    assert float(ray_tpu.get(ref0, timeout=60)[0]) == 7.0
    del ref0
    ray_tpu.shutdown()  # drop the client cleanly; detached actor must survive

    # -- kill the head, restart it on the same ports ---------------------------
    os.kill(head.pid, signal.SIGKILL)
    head.wait(timeout=10)
    time.sleep(1.0)
    head2 = _spawn_head(env, node_port, client_port)
    procs.append(head2)

    # -- after: agent re-attached; actor state + object survived ----------------
    ray_tpu.init(address=f"ray-tpu://127.0.0.1:{client_port}")
    deadline = time.time() + 60
    while True:
        alive = [n for n in ray_tpu.nodes()
                 if n["Alive"] and n["Labels"].get("agent") == "remote"]
        if alive:
            assert alive[0]["NodeID"] == remote_id  # SAME node id re-registered
            break
        assert time.time() < deadline, "agent never re-attached to the new head"
        time.sleep(0.3)
    h = ray_tpu.get_actor("survivor")
    # in-memory actor state (n=1) survived: the WORKER PROCESS was never
    # restarted, only rebound to the new head
    assert ray_tpu.get(h.bump.remote(), timeout=60) == 2
    # the pre-restart object is still addressable through the rebuilt directory
    ref = ObjectRef(ObjectID.from_hex(oid_hex))
    arr = ray_tpu.get(ref, timeout=60)
    assert float(arr[0]) == 7.0 and arr.shape == (100_000,)
    ray_tpu.shutdown()


def test_head_restart_new_address_external_journal(restart_env, tmp_path):
    """HA variant (reference: GCS behind EXTERNAL Redis, restartable anywhere,
    gcs_redis_failure_detector.h): the journal lives in a URI store (mock://
    — reachable only through the fs abstraction), the replacement head starts
    on a DIFFERENT node+client port, and the agent finds it via its candidate
    address list."""
    import ray_tpu

    env, procs = restart_env
    env = dict(env)
    mock_root = str(tmp_path / "bucket")
    env["RAY_TPU_MOCK_FS_ROOT"] = mock_root
    env["RAY_TPU_GCS_PERSISTENCE_PATH"] = "mock://gcs-ha/journal"
    os.environ["RAY_TPU_MOCK_FS_ROOT"] = mock_root
    try:
        port_a, client_a = _free_port(), _free_port()
        port_b, client_b = _free_port(), _free_port()
        head = _spawn_head(env, port_a, client_a)
        procs.append(head)
        agent = subprocess.Popen(
            [sys.executable, "-m", "ray_tpu.core.node_agent",
             "--address", f"127.0.0.1:{port_a},127.0.0.1:{port_b}",
             "--num-cpus", "2"], env=env)
        procs.append(agent)

        ray_tpu.init(address=f"ray-tpu://127.0.0.1:{client_a}")
        deadline = time.time() + 30
        while len([n for n in ray_tpu.nodes() if n["Alive"]]) < 2:
            assert time.time() < deadline, "agent never joined"
            time.sleep(0.2)
        remote_id = next(n["NodeID"] for n in ray_tpu.nodes()
                         if n["Alive"] and n["Labels"].get("agent") == "remote")
        from ray_tpu.core.task_spec import NodeAffinitySchedulingStrategy

        sched = NodeAffinitySchedulingStrategy(node_id=remote_id)

        @ray_tpu.remote(scheduling_strategy=sched, lifetime="detached",
                        name="ha-survivor", max_restarts=0)
        class Survivor:
            def __init__(self):
                self.n = 0

            def bump(self):
                self.n += 1
                return self.n

        s = Survivor.remote()
        assert ray_tpu.get(s.bump.remote(), timeout=60) == 1
        ray_tpu.shutdown()

        # journal segments exist ONLY behind the mock:// scheme
        assert os.path.isdir(os.path.join(mock_root, "gcs-ha", "journal"))

        # -- kill head A; replacement comes up at a DIFFERENT address ------------
        os.kill(head.pid, signal.SIGKILL)
        head.wait(timeout=10)
        time.sleep(1.0)
        head2 = _spawn_head(env, port_b, client_b)
        procs.append(head2)

        ray_tpu.init(address=f"ray-tpu://127.0.0.1:{client_b}")
        deadline = time.time() + 60
        while True:
            alive = [n for n in ray_tpu.nodes()
                     if n["Alive"] and n["Labels"].get("agent") == "remote"]
            if alive:
                assert alive[0]["NodeID"] == remote_id
                break
            assert time.time() < deadline, "agent never found the new head"
            time.sleep(0.3)
        h = ray_tpu.get_actor("ha-survivor")
        assert ray_tpu.get(h.bump.remote(), timeout=60) == 2  # state survived
        ray_tpu.shutdown()
    finally:
        os.environ.pop("RAY_TPU_MOCK_FS_ROOT", None)


def test_uri_journal_split_brain_fence():
    """Two heads pointed at one journal URI (split-brain during failover):
    segment names embed writer tokens so appends can never overwrite each
    other, and the OLD writer is fenced out loudly (JournalFencedError) once a
    newer head claims the owner marker (ADVICE r4: no silent corruption)."""
    import uuid

    from ray_tpu.core.gcs import JournalFencedError, _UriJournal

    uri = f"mock://fence-{uuid.uuid4().hex[:8]}"
    j1 = _UriJournal(uri)
    j1.append(b"from-j1")
    j2 = _UriJournal(uri)  # replacement head: newest-writer-wins claim
    j2.append(b"from-j2")
    # j1 hits the fence at its next periodic owner check, not silently
    with pytest.raises(JournalFencedError):
        for _ in range(j1.owner_check_every + 1):
            j1.append(b"stale")
    # nothing was overwritten: every append from BOTH writers is a distinct
    # segment object (names carry the writer token)
    names = j2._segments()
    assert len(names) == len(set(names))
    assert any(j1.token in n for n in names)
    assert any(j2.token in n for n in names)
    # the surviving writer's compaction (destructive) also re-checks ownership
    j2.compact([b"snapshot"])
    assert len(j2._segments()) == 1
    # ...and a fenced writer may NOT compact
    with pytest.raises(JournalFencedError):
        j1.compact([b"bad"])
