"""util/fault_injection.py unit tests: deterministic, in-process, no cluster."""
import time

import pytest

from ray_tpu.core.exceptions import FaultInjectedError
from ray_tpu.util import fault_injection as fi


@pytest.fixture(autouse=True)
def _clean():
    fi.disarm()
    yield
    fi.disarm()


def test_unarmed_fail_point_is_noop():
    fi.fail_point("nowhere")  # nothing armed: must not raise
    assert fi.fired("nowhere") == 0


def test_error_mode_raises_typed_with_context():
    fi.arm("site.a", mode="error")
    with pytest.raises(FaultInjectedError) as ei:
        fi.fail_point("site.a", replica="r1", attempt=2)
    assert ei.value.site == "site.a"
    assert ei.value.context == {"replica": "r1", "attempt": 2}
    assert fi.fired("site.a") == 1


def test_count_budget_limits_firings():
    fi.arm("site.b", mode="error", count=2)
    for _ in range(2):
        with pytest.raises(FaultInjectedError):
            fi.fail_point("site.b")
    fi.fail_point("site.b")  # budget burned: no-op again
    assert fi.fired("site.b") == 2


def test_delay_mode_sleeps():
    fi.arm("site.c", mode="delay", delay_s=0.15)
    t0 = time.monotonic()
    fi.fail_point("site.c")
    assert time.monotonic() - t0 >= 0.14


def test_seeded_probability_is_deterministic():
    def run():
        fi.arm("site.d", mode="error", prob=0.5, seed=7, count=None)
        hits = []
        for i in range(20):
            try:
                fi.fail_point("site.d")
                hits.append(i)
            except FaultInjectedError:
                pass
        fi.disarm("site.d")
        return hits

    first, second = run(), run()
    assert first == second  # same seed -> same hit/miss sequence
    assert 0 < len(first) < 20  # probabilistic: some fired, some passed


def test_disarm_single_and_all():
    fi.arm("x", mode="error")
    fi.arm("y", mode="error")
    fi.disarm("x")
    fi.fail_point("x")  # disarmed
    with pytest.raises(FaultInjectedError):
        fi.fail_point("y")
    fi.disarm()
    fi.fail_point("y")
    assert set(fi.armed()) == set()


def test_env_var_arming(monkeypatch):
    monkeypatch.setenv(fi.ENV_VAR,
                       "env.site=error@n=1; env.slow=delay@delay=0.01")
    with pytest.raises(FaultInjectedError):
        fi.fail_point("env.site")
    fi.fail_point("env.site")  # n=1 budget burned (state cached per raw string)
    fi.fail_point("env.slow")  # delay mode parses and runs
    assert "env.slow" in fi.armed()
    # API spec wins over env for the same site
    fi.arm("env.slow", mode="error")
    with pytest.raises(FaultInjectedError):
        fi.fail_point("env.slow")


def test_env_var_bad_entry_skipped(monkeypatch):
    monkeypatch.setenv(fi.ENV_VAR, "broken=nosuchmode; ok=error")
    with pytest.raises(FaultInjectedError):
        fi.fail_point("ok")
    fi.fail_point("broken")  # unparseable entry ignored, not fatal
