"""Decoupled rollout/learn plane: queue accounting unit tests + chaos gates.

The chaos cases are the decoupled-RL fault contract: SIGKILL one env worker
(learner keeps pacing off the survivors, the driver reaps the dead worker's
block admissions, the pool backfills) and SIGKILL one learner rank (typed
abort surfaces through update_from_blocks, max_failures=1 restarts the group
from the last checkpoint with weight-version continuity). Both end with the
leak gate: zero outstanding / unreleased / worker-outstanding admissions
after a clean shutdown.
"""
import os
import subprocess
import sys
import time

import pytest

from ray_tpu.rllib.rollout_plane import BlockHandle, BlockQueue, TrajectoryBlockSpec
from ray_tpu.util.fault_injection import ChaosController

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture(autouse=True)
def _cluster(rt):
    yield


def _handle(worker=0, seq=0, version=0):
    spec = TrajectoryBlockSpec(T=2, B=1, obs_shape=(4,), obs_dtype="float32",
                               act_shape=(), act_dtype="int32")
    return BlockHandle(worker_index=worker, generation=0, seq=seq,
                       location=("x", seq), addr=("127.0.0.1", 0),
                       key=f"b{worker}.{seq}", spec=spec, policy_version=version,
                       env_steps=2, episode_returns=())


def _tiny_config(num_runners=2, num_learners=1, blocks_per_update=1):
    from ray_tpu.rllib.algorithms.ppo import PPOConfig

    return (PPOConfig().environment("CartPole-v1")
            .env_runners(num_env_runners=num_runners,
                         num_envs_per_env_runner=2,
                         rollout_fragment_length=16)
            .learners(num_learners=num_learners)
            .training(lr=3e-4, train_batch_size=32, minibatch_size=16,
                      num_epochs=1, gamma=0.99, lambda_=0.95)
            .rl_module(model_config={"fcnet_hiddens": [16]})
            .decoupled_rollout(enabled=True, queue_depth=4, max_block_lag=4,
                               blocks_per_update=blocks_per_update,
                               weight_sync_interval=1, take_timeout_s=20.0)
            .debugging(seed=0))


def _train_until_update(algo, rounds=20):
    """Drive train() until one round actually consumed blocks."""
    for _ in range(rounds):
        if algo.train().get("num_env_steps_trained"):
            return True
    return False


# ------------------------------------------------------------ queue (unit)

def test_block_queue_accounting_and_lag():
    q = BlockQueue(max_depth=3, max_lag=2)
    # depth bound: announcing a 4th evicts (expires) the oldest
    for s in range(4):
        resp = q.announce(_handle(seq=s, version=0))
    assert resp["depth"] == 3
    # stale learner version: lag 5 > max_lag 2 expires every queued block
    assert q.take(4, learner_version=5) == []
    s = q.stats()
    assert s["expired"] == 4 and s["taken"] == 0 and s["outstanding"] == 0
    assert s["lag_p99_taken"] is None  # nothing trained on yet
    # fresh blocks at mixed lags: p99 over taken lags is exact, not a bound
    for s_, v in ((10, 5), (11, 5), (12, 4)):
        q.announce(_handle(seq=s_, version=v))
    taken = q.take(4, learner_version=5)
    assert [h.seq for h in taken] == [10, 11, 12]
    q.release([h.uid for h in taken])
    s = q.stats()
    assert s["lag_max_taken"] == 1 and s["lag_p99_taken"] == 1
    assert s["released"] == 3 and s["unreleased"] == 0 and s["outstanding"] == 0
    # release routes seqs home per worker on the next announce
    resp = q.announce(_handle(seq=13, version=5))
    assert sorted(resp["released"]) == [10, 11, 12]


def test_block_queue_reap_and_stop():
    q = BlockQueue(max_depth=4, max_lag=2)
    q.announce(_handle(worker=0, seq=0))
    q.announce(_handle(worker=1, seq=0))
    dead = q.reap_worker(1)
    assert [h.uid for h in dead] == [(1, 0, 0)]
    assert [h.worker_index for h in q.take(4, 0)] == [0]
    q.request_stop()
    resp = q.announce(_handle(worker=0, seq=1))  # post-stop: rejected, freed
    assert resp["stop"] and 1 in resp["released"]
    s = q.stats()
    assert s["outstanding"] == 0 and s["depth"] == 0


# ------------------------------------------------------------- chaos gates

def test_env_worker_sigkill_reap_restart_zero_leaks(rt):
    algo = _tiny_config(num_runners=2).build_algo()
    try:
        assert _train_until_update(algo)
        chaos = ChaosController()
        assert chaos.kill_actor(algo.rollout_plane.workers[1])
        # learner keeps pacing off the surviving worker
        assert _train_until_update(algo)
        reaped = algo.rollout_plane.reap_worker(1)
        assert reaped >= 0 and algo.rollout_plane.workers[1] is None
        # pool backfills the slot with a new generation and training continues
        algo.rollout_plane.restart_worker(1)
        assert algo.rollout_plane.workers[1] is not None
        assert _train_until_update(algo)
    finally:
        algo.cleanup()
    s = algo.final_plane_stats
    assert s["outstanding"] == 0
    assert s["unreleased"] == 0
    assert s["worker_outstanding"] == 0


def test_learner_rank_sigkill_restarts_group_zero_leaks(rt):
    algo = _tiny_config(num_runners=1, num_learners=2,
                        blocks_per_update=2).build_algo()
    try:
        assert _train_until_update(algo)
        chaos = ChaosController()
        assert chaos.kill_actor(algo.learner_group.learners[1])
        # the dead rank surfaces as a typed abort inside a later train();
        # max_failures=1 rebuilds the group from the last checkpoint
        deadline = time.monotonic() + 60
        while algo._learner_failures == 0 and time.monotonic() < deadline:
            algo.train()
        assert algo._learner_failures == 1
        from ray_tpu.core.exceptions import (ActorError, CollectiveAbortError,
                                             WorkerCrashedError)
        assert isinstance(algo._last_failure,
                          (CollectiveAbortError, ActorError,
                           WorkerCrashedError, ConnectionError))
        # restarted group trains again and workers accept its newer weights
        assert _train_until_update(algo)
    finally:
        algo.cleanup()
    s = algo.final_plane_stats
    assert s["outstanding"] == 0
    assert s["unreleased"] == 0
    assert s["worker_outstanding"] == 0


# -------------------------------------------------------------- bench smoke

def test_bench_rl_dry_run_smoke():
    """bench.py --rl --dry-run must exercise the full decoupled path and pass
    its structural gates (liveness, staleness bound, zero leaks) end-to-end."""
    proc = subprocess.run(
        [sys.executable, os.path.join(REPO, "bench.py"), "--rl", "--dry-run"],
        cwd=REPO, env={**os.environ, "JAX_PLATFORMS": "cpu"},
        capture_output=True, text=True, timeout=900)
    assert proc.returncode == 0, proc.stdout[-2000:] + proc.stderr[-2000:]
    for gate in ("learner_made_progress", "block_lag_p99_within_bound",
                 "zero_leaked_block_admissions"):
        # gate verdicts go to the bench log stream (stderr)
        assert f"rl check {gate}: PASS" in proc.stderr, proc.stderr[-2000:]
