"""Collective API tests (reference analogue: python/ray/util/collective tests).

Host-plane (SHM backend) collectives across actor processes, on both transports:
the coordinator-board fast path (small tensors) and the ring path (large tensors
move rank-to-rank over the data plane; the coordinator carries metadata only —
asserted here via the board instrumentation). The XLA backend's cross-process
path — jax.distributed bootstrap + device-path psum over a mesh spanning two OS
processes — is exercised in test_spmd_multiprocess.py (the trainer loop runs
init_collective_group(backend="xla") inside a real 2-process universe).

Every test kills its detached coordinators and member actors on exit: each one
pins a worker-pool slot, and the session cluster caps workers per node.
"""
import contextlib

import numpy as np
import pytest

# Force-everything-over-the-board threshold: payloads below the threshold ride
# the coordinator, so a huge threshold pins a group to the legacy path.
BOARD_ONLY = 1 << 62
_NS = "ray_tpu.collective"


def _make_workers(rt, n):
    @rt.remote(num_cpus=0)
    class Member:
        def __init__(self, rank):
            self.rank = rank

        def _ray_tpu_collective_init(self, world_size, rank, backend, group_name,
                                     compression=None, ring_threshold_bytes=None):
            from ray_tpu.util import collective as col

            col.init_collective_group(world_size, rank, backend, group_name,
                                      compression=compression,
                                      ring_threshold_bytes=ring_threshold_bytes)

        def do_allreduce(self, group_name):
            from ray_tpu.util import collective as col

            x = np.full((4,), float(self.rank + 1), dtype=np.float32)
            return col.allreduce(x, group_name)

        def do_broadcast(self, group_name):
            from ray_tpu.util import collective as col

            x = np.full((3,), float(self.rank), dtype=np.float32)
            return col.broadcast(x, src_rank=1, group_name=group_name)

        def do_allgather(self, group_name):
            from ray_tpu.util import collective as col

            x = np.array([self.rank], dtype=np.int64)
            return col.allgather(x, group_name)

        def do_reducescatter(self, group_name):
            from ray_tpu.util import collective as col

            x = np.arange(4, dtype=np.float32) + self.rank
            return col.reducescatter(x, group_name)

        def do_sendrecv(self, group_name):
            from ray_tpu.util import collective as col

            if self.rank == 0:
                col.send(np.array([42.0]), dst_rank=1, group_name=group_name)
                return None
            buf = np.zeros(1)
            return col.recv(buf, src_rank=0, group_name=group_name)

        def do_barrier(self, group_name):
            from ray_tpu.util import collective as col

            col.barrier(group_name)
            return col.get_rank(group_name), col.get_collective_group_size(group_name)

        # -- parametrized ops for ring-vs-board parity -----------------------
        def _data(self, n, integer=False):
            rng = np.random.default_rng(1000 + 31 * self.rank)
            if integer:
                return rng.integers(-50, 50, size=n).astype(np.int64)
            # [0.5, 1.5): PRODUCT across ranks stays in float32 range
            return (rng.random(n, dtype=np.float32) + 0.5)

        def op_allreduce(self, group_name, n, op_name, integer=False):
            from ray_tpu.util import collective as col

            op = getattr(col.ReduceOp, op_name)
            return col.allreduce(self._data(n, integer=integer), group_name, op=op)

        def op_reduce(self, group_name, n, op_name, dst):
            from ray_tpu.util import collective as col

            op = getattr(col.ReduceOp, op_name)
            out = col.reduce(self._data(n), dst_rank=dst, group_name=group_name, op=op)
            return out if self.rank == dst else None

        def op_broadcast(self, group_name, n, src):
            from ray_tpu.util import collective as col

            return col.broadcast(self._data(n), src_rank=src, group_name=group_name)

        def op_allgather(self, group_name, n):
            from ray_tpu.util import collective as col

            return col.allgather(self._data(n), group_name)

        def op_allgather_mixed(self, group_name, base_n):
            """Per-rank payload sizes: rank r gathers base_n * 4**r elements, so
            some ranks ride the board and some the ring in the SAME op."""
            from ray_tpu.util import collective as col

            return col.allgather(self._data(base_n * 4 ** self.rank), group_name)

        def op_reducescatter(self, group_name, n, op_name):
            from ray_tpu.util import collective as col

            op = getattr(col.ReduceOp, op_name)
            return col.reducescatter(self._data(n), group_name, op=op)

        def op_sendrecv(self, group_name, n):
            from ray_tpu.util import collective as col

            if self.rank == 0:
                col.send(self._data(n), dst_rank=1, group_name=group_name)
                return None
            if self.rank == 1:
                return col.recv(np.zeros(n, np.float32), src_rank=0,
                                group_name=group_name)
            return None

        def op_p2p_fanout(self, group_name, n):
            """Rank 0 sends DIFFERENT payloads to ranks 1 and 2, twice each,
            interleaved — p2p keys must advance per (src,dst) pair or the
            streams cross."""
            from ray_tpu.util import collective as col

            if self.rank == 0:
                for i in range(2):
                    col.send(np.full(n, 10.0 + i), dst_rank=1, group_name=group_name)
                    col.send(np.full(n, 20.0 + i), dst_rank=2, group_name=group_name)
                return None
            buf = np.zeros(n)
            return [float(col.recv(buf.copy(), src_rank=0, group_name=group_name)[0])
                    for _ in range(2)]

        def op_allreduce_cheap(self, group_name, n):
            """Big-payload allreduce with O(1)-verifiable exact data: every
            per-element sum is a small integer, exact in float32 regardless of
            association order."""
            from ray_tpu.util import collective as col

            x = (np.arange(n, dtype=np.int32) % 1000 + self.rank).astype(np.float32)
            return col.allreduce(x, group_name)

    return [Member.remote(i) for i in range(n)]


@pytest.fixture(scope="module")
def members(rt):
    """One pool of 4 member actors shared by every test in this module —
    worker-process spawns are the dominant cost of these tests, not the
    collectives themselves. Each member's rank equals its pool index, so any
    prefix members[:w] forms a valid world of size w."""
    workers = _make_workers(rt, 4)
    yield workers
    for w in workers:
        try:
            rt.kill(w)
        except Exception:
            pass


@contextlib.contextmanager
def _collective(rt, members, n, *group_specs):
    """Create one group per (name, kwargs) spec over members[:n]; always kill
    the detached coordinators on exit (each pins a worker-pool slot)."""
    from ray_tpu.util import collective as col

    workers = members[:n]
    names = []
    try:
        for name, kwargs in group_specs:
            col.create_collective_group(workers, n, list(range(n)),
                                        backend="shm", group_name=name, **kwargs)
            names.append(name)
        yield workers
    finally:
        for name in names:
            col.kill_coordinator(name)


def _board_stats(rt, group):
    coord = rt.get_actor(f"coordinator.{group}", namespace=_NS)
    return rt.get(coord.board_stats.remote())


def test_allreduce_and_barrier(rt, members):
    with _collective(rt, members, 2, ("g1", {})) as workers:
        out = rt.get([w.do_allreduce.remote("g1") for w in workers])
        np.testing.assert_allclose(out[0], np.full((4,), 3.0))
        np.testing.assert_allclose(out[1], np.full((4,), 3.0))
        ranks = rt.get([w.do_barrier.remote("g1") for w in workers])
        assert sorted(ranks) == [(0, 2), (1, 2)]


def test_broadcast_allgather_reducescatter_p2p(rt, members):
    with _collective(rt, members, 2, ("g2", {})) as workers:
        out = rt.get([w.do_broadcast.remote("g2") for w in workers])
        np.testing.assert_allclose(out[0], np.full((3,), 1.0))  # src_rank=1's value
        np.testing.assert_allclose(out[1], np.full((3,), 1.0))

        gathered = rt.get([w.do_allgather.remote("g2") for w in workers])
        assert [int(g[0]) for g in gathered[0]] == [0, 1]

        rs = rt.get([w.do_reducescatter.remote("g2") for w in workers])
        # reduced = arange(4)+0 + arange(4)+1 = [1,3,5,7]; rank0 [1,3], rank1 [5,7]
        np.testing.assert_allclose(rs[0], [1.0, 3.0])
        np.testing.assert_allclose(rs[1], [5.0, 7.0])

        sr = rt.get([w.do_sendrecv.remote("g2") for w in workers])
        np.testing.assert_allclose(sr[1], [42.0])


def test_unsupported_backends():
    from ray_tpu.util.collective.types import Backend

    with pytest.raises(ValueError):
        Backend.parse("nccl")
    with pytest.raises(NotImplementedError):
        Backend.parse("mpi")


def test_bad_compression_rejected():
    from ray_tpu.util.collective.types import Compression

    with pytest.raises(ValueError):
        Compression.parse("fp4")
    assert Compression.parse(None) is Compression.NONE
    assert Compression.parse("int8") is Compression.INT8


# -- ring path -------------------------------------------------------------------------
@pytest.mark.parametrize("world", [2, 3])  # odd world exercises uneven chunks
def test_ring_board_parity_all_ops(rt, members, world):
    """The same actors in two groups — one pinned to the board path, one with
    threshold 0 so every payload takes the ring. Identical per-rank inputs in
    both groups ⇒ bit-exact results prove transport parity (compression off)."""
    tag = f"par{world}"
    board, ring = f"board_{tag}", f"ring_{tag}"
    n = 40_000  # 160 KB float32: above the default ring threshold too
    with _collective(rt, members, world,
                     (board, {"ring_threshold_bytes": BOARD_ONLY}),
                     (ring, {"ring_threshold_bytes": 0})) as workers:
        for op_name in ("SUM", "PRODUCT", "MIN", "MAX"):
            b = rt.get([w.op_allreduce.remote(board, n, op_name) for w in workers])
            r = rt.get([w.op_allreduce.remote(ring, n, op_name) for w in workers])
            for bb, rr in zip(b, r):
                np.testing.assert_array_equal(bb, rr, err_msg=f"allreduce {op_name}")
            b = rt.get([w.op_reducescatter.remote(board, world * 5_000, op_name)
                        for w in workers])
            r = rt.get([w.op_reducescatter.remote(ring, world * 5_000, op_name)
                        for w in workers])
            for bb, rr in zip(b, r):
                np.testing.assert_array_equal(bb, rr, err_msg=f"reducescatter {op_name}")

        b = rt.get([w.op_reduce.remote(board, n, "SUM", world - 1) for w in workers])
        r = rt.get([w.op_reduce.remote(ring, n, "SUM", world - 1) for w in workers])
        np.testing.assert_array_equal(b[world - 1], r[world - 1])

        b = rt.get([w.op_broadcast.remote(board, n, world - 1) for w in workers])
        r = rt.get([w.op_broadcast.remote(ring, n, world - 1) for w in workers])
        for bb, rr in zip(b, r):
            np.testing.assert_array_equal(bb, rr)

        b = rt.get([w.op_allgather.remote(board, n) for w in workers])
        r = rt.get([w.op_allgather.remote(ring, n) for w in workers])
        for bb, rr in zip(b, r):
            for bpart, rpart in zip(bb, rr):
                np.testing.assert_array_equal(bpart, rpart)

        b = rt.get([w.op_sendrecv.remote(board, n) for w in workers])
        r = rt.get([w.op_sendrecv.remote(ring, n) for w in workers])
        np.testing.assert_array_equal(b[1], r[1])

        # integer payloads: the ring moves them raw (never quantized)
        b = rt.get([w.op_allreduce.remote(board, n, "SUM", True) for w in workers])
        r = rt.get([w.op_allreduce.remote(ring, n, "SUM", True) for w in workers])
        np.testing.assert_array_equal(b[0], r[0])

        # tiny tensor, fewer elements than ranks: some ranks own empty chunks
        b = rt.get([w.op_allreduce.remote(board, 2, "SUM") for w in workers])
        r = rt.get([w.op_allreduce.remote(ring, 2, "SUM") for w in workers])
        for bb, rr in zip(b, r):
            np.testing.assert_array_equal(bb, rr)


def test_ring_multi_group_same_actors(rt, members):
    """Two ring groups over the same actors stay isolated (distinct
    coordinators, authkeys, and buffer stores)."""
    with _collective(rt, members, 2,
                     ("mg_a", {"ring_threshold_bytes": 0}),
                     ("mg_b", {"ring_threshold_bytes": 0})) as workers:
        ra = [w.op_allreduce.remote("mg_a", 30_000, "SUM") for w in workers]
        rb = [w.op_allreduce.remote("mg_b", 30_000, "MAX") for w in workers]
        a, b = rt.get(ra), rt.get(rb)
        np.testing.assert_array_equal(a[0], a[1])
        np.testing.assert_array_equal(b[0], b[1])
        assert not np.array_equal(a[0], b[0])  # SUM vs MAX of the same inputs


def test_ring_allgather_mixed_paths(rt, members):
    """Different payload sizes per rank: small ranks ride the board, large
    ranks the ring, inside one allgather."""
    with _collective(rt, members, 3, ("mix", {"ring_threshold_bytes": 64 * 1024})) as workers:
        # rank payload bytes: 16 KB (board), 64 KB (ring), 256 KB (ring)
        outs = rt.get([w.op_allgather_mixed.remote("mix", 4_096) for w in workers])
        for out in outs:
            assert [len(p) for p in out] == [4_096, 16_384, 65_536]
            for r, p in enumerate(out):
                np.testing.assert_array_equal(p, outs[0][r])


def test_p2p_fanout_per_pair_counters(rt, members):
    """One sender, two receivers, interleaved sends on the ring path: the p2p
    sequence counters are per (src,dst) pair, so each receiver sees its own
    stream in order."""
    with _collective(rt, members, 3,
                     ("p2p3", {"ring_threshold_bytes": 0})) as workers:
        res = rt.get([w.op_p2p_fanout.remote("p2p3", 30_000) for w in workers])
        assert res[1] == [10.0, 11.0], res[1]
        assert res[2] == [20.0, 21.0], res[2]


def test_compression_roundtrip_tolerance(rt, members):
    """int8 wire compression is opt-in and lossy within the blockwise-symmetric
    bound: |err| <= block_amax/127 per quantization stage (allreduce has two)."""
    world, n = 3, 50_000
    with _collective(rt, members, world,
                     ("q_ref", {"ring_threshold_bytes": BOARD_ONLY}),
                     ("q_int8", {"ring_threshold_bytes": 0,
                                 "compression": "int8"})) as workers:
        exact = rt.get([w.op_allreduce.remote("q_ref", n, "SUM") for w in workers])
        lossy = rt.get([w.op_allreduce.remote("q_int8", n, "SUM") for w in workers])
        # inputs in [0.5, 1.5): stage-1 amax ~1.5 per input (x W inputs summed),
        # stage-2 amax ~W*1.5 → bound ~(W*1.5 + W*1.5)/127; doubled for slack
        tol = 2 * 2 * world * 1.5 / 127
        for e, l in zip(exact, lossy):
            assert np.abs(e - l).max() <= tol
            assert not np.array_equal(e, l)  # it IS lossy (guards a silent raw path)
        # lossy but IDENTICAL on every rank: chunk owners must use the same
        # quantize->dequantize round trip they serve, or replicas synced
        # through a compressed group drift apart
        for l in lossy[1:]:
            np.testing.assert_array_equal(lossy[0], l)

        exact = rt.get([w.op_broadcast.remote("q_ref", n, 0) for w in workers])
        lossy = rt.get([w.op_broadcast.remote("q_int8", n, 0) for w in workers])
        for e, l in zip(exact, lossy):
            assert np.abs(e - l).max() <= 2 * 1.5 / 127  # single stage

        # integer payloads bypass quantization entirely: still bit-exact
        exact = rt.get([w.op_allreduce.remote("q_ref", n, "SUM", True) for w in workers])
        lossy = rt.get([w.op_allreduce.remote("q_int8", n, "SUM", True) for w in workers])
        np.testing.assert_array_equal(exact[0], lossy[0])


def test_board_carries_metadata_only_above_threshold(rt, members):
    """Above the ring threshold NO tensor-sized payload may transit the
    coordinator actor — the board holds only addresses/keys/dtypes."""
    threshold = 32 * 1024
    with _collective(rt, members, 3,
                     ("meta_only", {"ring_threshold_bytes": threshold})) as workers:
        n = 500_000  # 2 MB float32 — 60x the threshold
        rt.get([w.op_allreduce.remote("meta_only", n, "SUM") for w in workers])
        rt.get([w.op_broadcast.remote("meta_only", n, 0) for w in workers])
        rt.get([w.op_allgather.remote("meta_only", n) for w in workers])
        rt.get([w.op_reducescatter.remote("meta_only", 3 * (n // 4), "SUM")
                for w in workers])
        rt.get([w.op_sendrecv.remote("meta_only", n) for w in workers])
        stats = _board_stats(rt, "meta_only")
        assert stats["num_contribs"] > 0
        assert stats["max_contrib_bytes"] < threshold, stats
        # metadata records are O(100) bytes, nowhere near tensor-sized
        assert stats["max_contrib_bytes"] < 4_096, stats


# -- failure authority: epochs, aborts, debuggable timeouts ----------------------------
def test_coordinator_epoch_rejects_stale_board_entries():
    """Regression for board reuse across group incarnations: after an abort
    and a re-init, entries tagged with the old epoch must be dropped (never
    satisfying a retried op that reuses the same key), stale pollers must get
    an abort verdict (not data), and a late abort for the old epoch must not
    poison the new group."""
    from ray_tpu.util.collective.coordinator import GroupCoordinator

    coord = GroupCoordinator(2, "epoch_g")
    e = coord.join(0, "w0")
    assert coord.join(1, "w1") == e

    # rank 0 contributes, then rank 1 "dies": the abort poisons this epoch
    coord.contribute("allreduce:0", 0, 1.0, e)
    assert coord.abort("rank 1 died", failed_rank=1, epoch=e) is True
    status, verdict = coord.poll("allreduce:0", 0, None, e)
    assert status == "abort" and verdict["failed_rank"] == 1

    # re-init: the first join after an abort starts a fresh epoch and clears
    # the poison flag and every board
    e2 = coord.join(0, "w0")
    assert e2 == e + 1
    assert coord.join(1, "w2") == e2
    assert coord.check_abort(e2) is None

    # a late death notice scoped to the retired epoch is rejected
    assert coord.abort("late death notice", failed_rank=0, epoch=e) is False
    assert coord.check_abort(e2) is None

    # a stale contribution reusing the SAME key cannot satisfy the retried op
    coord.contribute("allreduce:0", 0, "stale", e)
    status, arrived = coord.poll("allreduce:0", 1, None, e2)
    assert status == "pending" and arrived == []

    # fresh contributions complete; a poller from the old epoch gets an abort
    # verdict even though the current epoch is healthy
    coord.contribute("allreduce:0", 0, "x", e2)
    coord.contribute("allreduce:0", 1, "y", e2)
    status, verdict = coord.poll("allreduce:0", 1, None, e)
    assert status == "abort" and verdict.get("stale")
    status, out = coord.poll("allreduce:0", 0, None, e2)
    assert status == "ready" and out == ["x", "y"]
    status, out = coord.poll("allreduce:0", 1, None, e2)
    assert status == "ready" and out == ["x", "y"]
    assert coord.board_keys() == []  # fully fetched boards are reaped


def test_join_rollover_on_rejoin_clears_boards():
    """A rank re-joining (crash-restart re-init without destroy) rolls the
    epoch even with no abort: half-finished boards of the previous incarnation
    must not leak into the new one."""
    from ray_tpu.util.collective.coordinator import GroupCoordinator

    coord = GroupCoordinator(2, "rejoin_g")
    e = coord.join(0, "w0")
    coord.join(1, "w1")
    coord.contribute("barrier:0", 0, None, e)
    assert coord.board_keys() == ["barrier:0"]
    e2 = coord.join(0, "w0b")  # rank 0 again: new incarnation
    assert e2 == e + 1
    assert coord.board_keys() == []


def test_recreated_coordinator_rejects_previous_generation_abort():
    """Kill-and-recreate of the coordinator under the same name (Train group
    restart) starts the epoch at a fresh nonce: a delayed death notice scoped
    to the RETIRED incarnation's epoch must not poison the healthy new group
    (with max_failures=1 a spurious abort would consume the whole budget)."""
    from ray_tpu.util.collective.coordinator import GroupCoordinator

    old = GroupCoordinator(2, "gen_g")
    e_old = old.join(0, "w0")
    old.join(1, "w1")
    new = GroupCoordinator(2, "gen_g")  # same name, fresh incarnation
    e_new = new.join(0, "w0b")
    new.join(1, "w1b")
    assert e_new != e_old
    # the old generation's late death notice misses the new epoch space
    assert new.abort("late death notice from old generation", 1, e_old) is False
    assert new.check_abort(e_new) is None
    st, _ = new.poll("op:0", 0, None, e_new)
    assert st == "pending"  # healthy: no abort verdict


def test_timeout_message_is_debuggable(rt):
    """A genuine collective timeout names the group, world size, epoch, and
    the ranks that HAD arrived — a stuck op is diagnosable from the exception
    alone."""
    import types

    from ray_tpu.util.collective.coordinator import (GroupCoordinator,
                                                     wait_poll, wait_poll_one)

    coord = rt.remote(GroupCoordinator).options(num_cpus=0).remote(3, "slowgrp")
    try:
        # the epoch starts at a per-incarnation nonce: fetch it, don't assume 0
        epoch = rt.get(coord.current_epoch.remote())
        st = types.SimpleNamespace(coordinator=coord, rank=0, name="slowgrp",
                                   world_size=3, epoch=epoch)
        coord.contribute.remote("op:0", 0, 1.0, epoch)
        with pytest.raises(TimeoutError) as ei:
            wait_poll(st, "op:0", timeout_s=0.4)
        msg = str(ei.value)
        assert "slowgrp" in msg and "world_size 3" in msg
        assert f"epoch {epoch}" in msg and "arrived ranks: [0]" in msg
        with pytest.raises(TimeoutError) as ei:
            wait_poll_one(st, "p2p:0", src_rank=2, timeout_s=0.3)
        msg = str(ei.value)
        assert "slowgrp" in msg and "rank 2" in msg
    finally:
        rt.kill(coord)


def test_abort_check_raises_typed_error(rt):
    """The ring path's throttled abort probe converts a coordinator verdict
    into CollectiveAbortError with the failed rank attached."""
    import types

    from ray_tpu.util.collective import CollectiveAbortError, ring
    from ray_tpu.util.collective.coordinator import GroupCoordinator

    coord = rt.remote(GroupCoordinator).options(num_cpus=0).remote(2, "ac_g")
    try:
        epoch = rt.get(coord.current_epoch.remote())
        st = types.SimpleNamespace(coordinator=coord, rank=0, name="ac_g",
                                   world_size=2, epoch=epoch)
        chk = ring._AbortCheck(st)
        chk.check(force=True)  # healthy group: no raise
        rt.get(coord.abort.remote("injected fault", 1, epoch))
        with pytest.raises(CollectiveAbortError) as ei:
            chk.check(force=True)
        assert ei.value.failed_rank == 1
        assert ei.value.group_name == "ac_g"
        assert "injected fault" in str(ei.value)
    finally:
        rt.kill(coord)


def test_allreduce_64mb_world4_routes_peer_to_peer(rt, members):
    """Acceptance: a 64 MB float32 allreduce at world_size 4 moves tensor bytes
    rank-to-rank over the data plane; the coordinator carries metadata only."""
    with _collective(rt, members, 4, ("big4", {})) as workers:
        n = 16 * 1024 * 1024  # 64 MiB of float32
        outs = rt.get([w.op_allreduce_cheap.remote("big4", n) for w in workers],
                      timeout=240)
        stats = _board_stats(rt, "big4")
    # every per-element sum is a small integer (exact in float32), so the
    # reference is O(n) position-dependent arithmetic — chunk misrouting or
    # offset bugs would show up immediately
    want = ((np.arange(n, dtype=np.int32) % 1000) * 4 + 6).astype(np.float32)
    for out in outs:
        np.testing.assert_array_equal(out, want)
    assert stats["max_contrib_bytes"] < 4_096, stats
