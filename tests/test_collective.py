"""Collective API tests (reference analogue: python/ray/util/collective tests).

Host-plane (SHM backend) collectives across actor processes. The XLA backend's
cross-process path — jax.distributed bootstrap + device-path psum over a mesh spanning
two OS processes — is exercised in test_spmd_multiprocess.py (the trainer loop runs
init_collective_group(backend="xla") inside a real 2-process universe).
"""
import numpy as np
import pytest


def _make_workers(rt, n, group="g_test"):
    @rt.remote(num_cpus=0)
    class Member:
        def __init__(self, rank):
            self.rank = rank

        def _ray_tpu_collective_init(self, world_size, rank, backend, group_name):
            from ray_tpu.util import collective as col

            col.init_collective_group(world_size, rank, backend, group_name)

        def do_allreduce(self, group_name):
            from ray_tpu.util import collective as col

            x = np.full((4,), float(self.rank + 1), dtype=np.float32)
            return col.allreduce(x, group_name)

        def do_broadcast(self, group_name):
            from ray_tpu.util import collective as col

            x = np.full((3,), float(self.rank), dtype=np.float32)
            return col.broadcast(x, src_rank=1, group_name=group_name)

        def do_allgather(self, group_name):
            from ray_tpu.util import collective as col

            x = np.array([self.rank], dtype=np.int64)
            return col.allgather(x, group_name)

        def do_reducescatter(self, group_name):
            from ray_tpu.util import collective as col

            x = np.arange(4, dtype=np.float32) + self.rank
            return col.reducescatter(x, group_name)

        def do_sendrecv(self, group_name):
            from ray_tpu.util import collective as col

            if self.rank == 0:
                col.send(np.array([42.0]), dst_rank=1, group_name=group_name)
                return None
            buf = np.zeros(1)
            return col.recv(buf, src_rank=0, group_name=group_name)

        def do_barrier(self, group_name):
            from ray_tpu.util import collective as col

            col.barrier(group_name)
            return col.get_rank(group_name), col.get_collective_group_size(group_name)

    return [Member.remote(i) for i in range(n)]


def test_allreduce_and_barrier(rt):
    from ray_tpu.util import collective as col

    workers = _make_workers(rt, 2)
    col.create_collective_group(workers, 2, [0, 1], backend="shm", group_name="g1")
    out = rt.get([w.do_allreduce.remote("g1") for w in workers])
    np.testing.assert_allclose(out[0], np.full((4,), 3.0))
    np.testing.assert_allclose(out[1], np.full((4,), 3.0))
    ranks = rt.get([w.do_barrier.remote("g1") for w in workers])
    assert sorted(ranks) == [(0, 2), (1, 2)]


def test_broadcast_allgather_reducescatter_p2p(rt):
    from ray_tpu.util import collective as col

    workers = _make_workers(rt, 2)
    col.create_collective_group(workers, 2, [0, 1], backend="shm", group_name="g2")

    out = rt.get([w.do_broadcast.remote("g2") for w in workers])
    np.testing.assert_allclose(out[0], np.full((3,), 1.0))  # src_rank=1's value
    np.testing.assert_allclose(out[1], np.full((3,), 1.0))

    gathered = rt.get([w.do_allgather.remote("g2") for w in workers])
    assert [int(g[0]) for g in gathered[0]] == [0, 1]

    rs = rt.get([w.do_reducescatter.remote("g2") for w in workers])
    # reduced = arange(4)+0 + arange(4)+1 = [1,3,5,7]; rank0 chunk [1,3], rank1 [5,7]
    np.testing.assert_allclose(rs[0], [1.0, 3.0])
    np.testing.assert_allclose(rs[1], [5.0, 7.0])

    sr = rt.get([w.do_sendrecv.remote("g2") for w in workers])
    np.testing.assert_allclose(sr[1], [42.0])


def test_unsupported_backends():
    from ray_tpu.util.collective.types import Backend

    with pytest.raises(ValueError):
        Backend.parse("nccl")
    with pytest.raises(NotImplementedError):
        Backend.parse("mpi")
