"""HyperBand / PB2 / TPE searcher tests (reference tune schedulers + search)."""
import random

import pytest

from ray_tpu import tune
from ray_tpu.tune.schedulers import CONTINUE, STOP, HyperBandScheduler, PB2


class _T:
    def __init__(self, tid, config=None):
        self.trial_id = tid
        self.config = config or {}
        self._pbt_exploit = None


def test_hyperband_halves_synchronously():
    sched = HyperBandScheduler(metric="loss", mode="min", max_t=27, reduction_factor=3.0)
    # force a single bracket so the whole cohort shares rungs
    sched._brackets = sched._brackets[:1]
    sched._next_bracket = 0
    trials = [_T(f"t{i}") for i in range(9)]
    decisions = {}
    for it in range(1, 27):
        for i, t in enumerate(trials):
            if decisions.get(t.trial_id) == STOP:
                continue
            d = sched.on_trial_result(t, {"training_iteration": it, "loss": float(i)})
            decisions[t.trial_id] = d
    stopped = {tid for tid, d in decisions.items() if d == STOP}
    # successive halving with eta=3 must stop the bottom ~2/3 of the cohort
    assert len(stopped) >= 5, decisions
    # the best trial survives, the worst is stopped
    assert "t0" not in stopped
    assert "t8" in stopped


def test_hyperband_round_robin_brackets_balanced():
    sched = HyperBandScheduler(metric="loss", mode="min", max_t=9, reduction_factor=3.0)
    n_brackets = len(sched._brackets)
    trials = [_T(f"t{i}") for i in range(2 * n_brackets)]
    for rep in range(3):  # repeated reports must not skew assignment
        for i, t in enumerate(trials[: n_brackets]):
            sched.on_trial_result(t, {"training_iteration": rep + 1, "loss": float(i)})
    for t in trials[n_brackets:]:
        sched.on_trial_result(t, {"training_iteration": 1, "loss": 0.5})
    from collections import Counter

    counts = Counter(sched._assignment.values())
    assert max(counts.values()) - min(counts.values()) <= 1, counts


def test_pb2_gp_suggestion_within_bounds():
    sched = PB2(metric="reward", mode="max", perturbation_interval=1,
                hyperparam_bounds={"lr": [1e-4, 1e-1]}, seed=0)
    rng = random.Random(0)
    trials = [_T(f"t{i}", {"lr": 10 ** rng.uniform(-4, -1)}) for i in range(4)]
    # feed results: reward correlates with lr (higher better in this fake)
    for step in range(1, 6):
        for t in trials:
            sched.on_trial_result(t, {"training_iteration": step,
                                      "reward": t.config["lr"] * 100})
    exploited = [t for t in trials if t._pbt_exploit]
    assert exploited, "bottom-quantile trials should receive an exploit"
    new_cfg = exploited[0]._pbt_exploit["perturb"](exploited[0].config)
    assert 1e-4 <= new_cfg["lr"] <= 1e-1
    # GP has data -> suggestion should not be degenerate
    assert isinstance(new_cfg["lr"], float)


def test_tpe_searcher_converges_toward_good_region():
    space = {"x": tune.uniform(0.0, 1.0)}
    s = tune.TPESearcher(space, metric="loss", mode="min", n_startup=6, seed=1)
    # loss = (x - 0.8)^2: good region near 0.8
    for i in range(30):
        cfg = s.suggest(f"t{i}")
        assert 0.0 <= cfg["x"] <= 1.0
        s.on_trial_complete(f"t{i}", {"loss": (cfg["x"] - 0.8) ** 2})
    late = [s.suggest(f"late{i}")["x"] for i in range(10)]
    assert sum(abs(x - 0.8) < 0.25 for x in late) >= 6, late


def test_tpe_handles_choice_and_loguniform():
    space = {"opt": tune.choice(["adam", "sgd"]), "lr": tune.loguniform(1e-5, 1e-1)}
    s = tune.TPESearcher(space, metric="loss", mode="min", n_startup=4, seed=2)
    for i in range(20):
        cfg = s.suggest(f"t{i}")
        assert cfg["opt"] in ("adam", "sgd")
        assert 1e-5 <= cfg["lr"] <= 1e-1
        loss = (0.0 if cfg["opt"] == "adam" else 1.0) + abs(cfg["lr"] - 1e-3)
        s.on_trial_complete(f"t{i}", {"loss": loss})
    picks = [s.suggest(f"late{i}")["opt"] for i in range(10)]
    assert picks.count("adam") >= 6, picks


def test_tuner_with_tpe_searcher_end_to_end(rt):
    def objective(config):
        tune.report({"loss": (config["x"] - 0.5) ** 2})

    tuner = tune.Tuner(
        objective,
        param_space={"x": tune.uniform(0, 1)},
        tune_config=tune.TuneConfig(
            num_samples=8, metric="loss", mode="min",
            search_alg=tune.TPESearcher({"x": tune.uniform(0, 1)}, metric="loss",
                                        mode="min", n_startup=4, seed=0),
        ),
    )
    results = tuner.fit()
    best = results.get_best_result(metric="loss", mode="min")
    assert best.metrics["loss"] < 0.2


def test_optuna_search_adapter_end_to_end(rt):
    """OptunaSearch (reference search/optuna/optuna_search.py): the external
    searcher drives Tuner suggestions via study.ask()/tell()."""
    pytest.importorskip("optuna", reason="optuna not installed "
                        "(optional external-searcher dependency)")
    space = {"x": tune.uniform(0.0, 1.0),
             "opt": tune.choice(["adam", "sgd"]),
             "lr": tune.loguniform(1e-5, 1e-1),
             "layers": tune.randint(1, 4)}
    s = tune.OptunaSearch(space, metric="loss", mode="min", seed=3)
    for i in range(15):
        cfg = s.suggest(f"t{i}")
        assert 0.0 <= cfg["x"] <= 1.0 and cfg["opt"] in ("adam", "sgd")
        assert 1e-5 <= cfg["lr"] <= 1e-1 and cfg["layers"] in (1, 2, 3)
        s.on_trial_complete(f"t{i}", {"loss": (cfg["x"] - 0.7) ** 2})
    assert len(s.study.trials) >= 15

    def objective(config):
        tune.report({"loss": (config["x"] - 0.5) ** 2})

    tuner = tune.Tuner(
        objective, param_space=space,
        tune_config=tune.TuneConfig(
            num_samples=6, metric="loss", mode="min",
            search_alg=tune.OptunaSearch(space, metric="loss", mode="min",
                                         seed=4)))
    results = tuner.fit()
    best = results.get_best_result(metric="loss", mode="min")
    assert best.metrics["loss"] < 0.25


def test_hyperopt_search_adapter_end_to_end(rt):
    """HyperOptSearch (reference search/hyperopt/hyperopt_search.py): the
    second external searcher seam, driving hyperopt.tpe.suggest ask/tell."""
    pytest.importorskip("hyperopt", reason="hyperopt not installed "
                        "(optional external-searcher dependency)")
    space = {"x": tune.uniform(0.0, 1.0),
             "opt": tune.choice(["adam", "sgd"]),
             "lr": tune.loguniform(1e-5, 1e-1),
             "layers": tune.randint(1, 4)}
    s = tune.HyperOptSearch(space, metric="loss", mode="min", seed=3,
                            n_initial_points=4)
    for i in range(15):
        cfg = s.suggest(f"t{i}")
        assert 0.0 <= cfg["x"] <= 1.0 and cfg["opt"] in ("adam", "sgd")
        assert 1e-5 <= cfg["lr"] <= 1e-1 and cfg["layers"] in (1, 2, 3)
        s.on_trial_complete(f"t{i}", {"loss": (cfg["x"] - 0.7) ** 2})
    assert len(s.trials.trials) >= 15

    def objective(config):
        tune.report({"loss": (config["x"] - 0.5) ** 2})

    tuner = tune.Tuner(
        objective, param_space=space,
        tune_config=tune.TuneConfig(
            num_samples=6, metric="loss", mode="min",
            search_alg=tune.HyperOptSearch(space, metric="loss", mode="min",
                                           seed=4, n_initial_points=4)))
    results = tuner.fit()
    best = results.get_best_result(metric="loss", mode="min")
    assert best.metrics["loss"] < 0.25


def test_hyperopt_search_import_error_message():
    """Without hyperopt installed the adapter raises a clear install hint."""
    try:
        import hyperopt  # noqa: F401

        pytest.skip("hyperopt installed; error-path not reachable")
    except ImportError:
        pass
    with pytest.raises(ImportError, match="hyperopt"):
        tune.HyperOptSearch({"x": tune.uniform(0, 1)})
