"""Fleet-scale control-plane observability (ISSUE 17): node-agent
pre-aggregation wire codecs, bounded-cardinality guards, head inlet
backpressure, metrics-history journaling, bounded /api/history payloads, and
the CONTROL_BENCH harness smoke check.

Most tests here are head-side unit tests on synthetic fleets — the live
agent relay path is already exercised by test_multihost.py (node aggregation
is on by default), and the slow-marked e2e test below drives a real agent
subprocess through the delta path end to end.
"""
import json
import os
import subprocess
import sys
import threading
import time
import types

import pytest

from ray_tpu.util import metrics as M
from ray_tpu.util import telemetry
from ray_tpu.util.metrics_history import MetricsHistory

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _key(**tags):
    return tuple(sorted(tags.items()))


def _worker_snapshot(wid, boundaries, ndep=4):
    """One synthetic worker registry snapshot: shared deployment-tagged
    series plus a per-process gauge (the shape agents pre-aggregate)."""
    deps = [f"app/d{j}" for j in range(ndep)]
    return [
        {"name": "serve_requests_total", "type": "counter", "description": "",
         "values": {_key(deployment=d): float(10 + j) for j, d in enumerate(deps)}},
        {"name": "serve_queue_depth", "type": "gauge", "description": "",
         "values": {_key(deployment=d): float(j) for j, d in enumerate(deps)}},
        {"name": "serve_ttft_seconds", "type": "histogram", "description": "",
         "boundaries": list(boundaries),
         "values": {_key(deployment=d): {
             "buckets": [1] * (len(boundaries) + 1),
             "sum": 0.2 * (len(boundaries) + 1),
             "count": len(boundaries) + 1} for d in deps}},
        {"name": "worker_rss_bytes", "type": "gauge", "description": "",
         "values": {_key(proc=f"w{wid:04d}"): 1e8 + wid}},
    ]


# ----------------------------------------------------------------- wire codec

def test_snapshot_wire_roundtrip():
    snap = _worker_snapshot(7, [0.1, 0.5, 1.0])
    back = M.snapshot_from_wire(json.loads(json.dumps(M.snapshot_to_wire(snap))))
    assert [m["name"] for m in back] == [m["name"] for m in snap]
    for a, b in zip(snap, back):
        assert a["values"] == b["values"]
        if "boundaries" in a:
            assert b["boundaries"] == a["boundaries"]


def test_snapshot_from_wire_skips_malformed():
    wire = M.snapshot_to_wire(_worker_snapshot(0, [0.5]))
    wire.insert(1, {"garbage": True})          # no name/type/series
    wire.insert(0, {"name": "x", "type": "counter", "series": "not-a-list"})
    back = M.snapshot_from_wire(wire)
    assert [m["name"] for m in back][-4:] == [
        "serve_requests_total", "serve_queue_depth", "serve_ttft_seconds",
        "worker_rss_bytes"]


def test_agent_rpc_node_metrics_roundtrip():
    from ray_tpu.core import agent_rpc

    metrics_json = json.dumps(
        M.snapshot_to_wire(_worker_snapshot(3, [0.5]))).encode()
    msg = ("node_metrics", 17, 123.25, 8, metrics_json, b"[]", 2.5)
    out = agent_rpc.decode_agent_msg(agent_rpc.encode_agent_msg(msg))
    assert out == msg


def test_agent_rpc_control_backpressure_roundtrip():
    from ray_tpu.core import agent_rpc

    msg = ("control_backpressure", 3, 8.0)
    assert agent_rpc.decode_head_msg(agent_rpc.encode_head_msg(msg)) == msg


# ----------------------------------------------- merge/align at fleet scale

def test_merge_64_workers_mismatched_boundaries_bounded_time():
    """64 workers, half of them on a DIFFERENT histogram boundary set (a
    mid-rollout fleet): the merge re-bins instead of corrupting, counter
    totals stay exact, and the whole merge is comfortably sub-second."""
    snaps = [_worker_snapshot(w, [0.1, 0.5, 1.0] if w % 2 else [0.25, 1.0])
             for w in range(64)]
    t0 = time.perf_counter()
    merged = M.merge_snapshots(snaps)
    dt = time.perf_counter() - t0
    assert dt < 1.0, f"64-worker merge took {dt:.2f}s"
    # counters: 64 workers x 4 deployments x (10 + j)
    total = sum(merged["serve_requests_total"]["values"].values())
    assert total == 64 * sum(10 + j for j in range(4))
    # histograms: re-binning preserves observation counts exactly
    hist = merged["serve_ttft_seconds"]["values"]
    for v in hist.values():
        assert sum(v["buckets"]) == v["count"]
    dst_len = len(merged["serve_ttft_seconds"]["boundaries"]) + 1
    assert all(len(v["buckets"]) == dst_len for v in hist.values())
    # per-process series all survive (distinct keys)
    assert len(merged["worker_rss_bytes"]["values"]) == 64


def test_align_batch_64_workers_drifted_clocks():
    """64 workers each with a different measured clock offset: after
    align_batch every event sits on the head's single timeline and carries
    its producer tag."""
    base_ns = 1_000_000_000_000
    aligned = []
    for w in range(64):
        off = (w - 32) * 1_000_000  # -32ms .. +31ms drift
        batch = {"clock_offset_ns": -off,
                 "events": [{"name": "e", "ts_ns": base_ns + w + off}]}
        aligned.extend(telemetry.align_batch(batch, proc=f"worker-{w:04d}"))
    assert len(aligned) == 64
    assert [ev["ts_ns"] for ev in aligned] == [base_ns + w for w in range(64)]
    assert {ev["proc"] for ev in aligned} == {f"worker-{w:04d}" for w in range(64)}


# ----------------------------------------------------------- cardinality guard

def test_cardinality_guard_live_metrics(monkeypatch):
    monkeypatch.setenv("RAY_TPU_CONTROL_MAX_SERIES", "5")
    c = M.Counter("test_guard_counter_a", "x", tag_keys=("k",))
    for i in range(12):
        c.inc(1.0, tags={"k": f"v{i}"})
    assert len(c._values) == 5
    # existing keys keep updating after the cap is hit
    c.inc(2.0, tags={"k": "v0"})
    assert c._values[_key(k="v0")] == 3.0
    g = M.Gauge("test_guard_gauge_a", "x", tag_keys=("k",))
    for i in range(9):
        g.set(float(i), tags={"k": f"v{i}"})
    assert len(g._values) == 5
    h = M.Histogram("test_guard_hist_a", "x", boundaries=[1.0], tag_keys=("k",))
    for i in range(9):
        h.observe(0.5, tags={"k": f"v{i}"})
    assert len(h._buckets) == 5
    # drops are visible: the synthetic counter reports per-metric drop counts
    dropped = M.dropped_series_snapshot()
    assert dropped is not None and dropped["name"] == M.DROPPED_SERIES_METRIC
    by_metric = {dict(k)["metric"]: v for k, v in dropped["values"].items()}
    assert by_metric["test_guard_counter_a"] >= 7
    assert by_metric["test_guard_gauge_a"] >= 4
    assert by_metric["test_guard_hist_a"] >= 4


def test_cardinality_guard_merge(monkeypatch):
    monkeypatch.setenv("RAY_TPU_CONTROL_MAX_SERIES", "5")
    snaps = [[{"name": "exploding", "type": "counter", "description": "",
               "values": {_key(k=f"w{w}_v{i}"): 1.0 for i in range(4)}}]
             for w in range(8)]
    merged = M.merge_snapshots(snaps)
    assert len(merged["exploding"]["values"]) == 5
    drops = merged[M.DROPPED_SERIES_METRIC]["values"]
    assert drops[(("metric", "exploding"),)] == 8 * 4 - 5


def test_cardinality_guard_off_when_unset(monkeypatch):
    monkeypatch.setenv("RAY_TPU_CONTROL_MAX_SERIES", "0")
    snaps = [[{"name": "wide_ok", "type": "counter", "description": "",
               "values": {_key(k=f"v{w}_{i}"): 1.0 for i in range(10)}}]
             for w in range(8)]
    merged = M.merge_snapshots(snaps)
    assert len(merged["wide_ok"]["values"]) == 80
    assert M.DROPPED_SERIES_METRIC not in merged


# ------------------------------------------------------------- backpressure

def _fake_head(bound_agents=2):
    """Minimal stand-in carrying exactly the state Cluster's inlet/
    backpressure methods touch, so the unbound methods run against it."""
    sent = []

    class _Agent:
        def send(self, msg):
            sent.append(msg)

    return types.SimpleNamespace(
        _inlet_lock=threading.Lock(), _inlet_frames=0, _bp_level=0,
        _lock=threading.RLock(),
        _agent_conns={i: _Agent() for i in range(bound_agents)},
        _sent=sent)


def test_backpressure_escalates_and_clears(monkeypatch):
    from ray_tpu.core.node import Cluster

    monkeypatch.setenv("RAY_TPU_CONTROL_INLET_BOUND", "10")
    monkeypatch.setenv("RAY_TPU_CONTROL_NODE_FLUSH_S", "1.0")
    monkeypatch.setenv("RAY_TPU_CONTROL_BACKPRESSURE_MAX_S", "8.0")
    head = _fake_head()
    # window 1: 25 frames > bound -> level 1, agents told min interval 2.0
    for _ in range(25):
        Cluster._note_inlet_frame(head)
    Cluster._evaluate_inlet_backpressure(head)
    assert head._bp_level == 1
    assert head._sent[-2:] == [("control_backpressure", 1, 2.0)] * 2
    # window 2: still hot -> level 2 (doubling), interval 4.0
    head._inlet_frames = 25
    Cluster._evaluate_inlet_backpressure(head)
    assert head._bp_level == 2
    assert head._sent[-1] == ("control_backpressure", 2, 4.0)
    # widened intervals cap at control_backpressure_max_s
    for _ in range(4):
        head._inlet_frames = 25
        Cluster._evaluate_inlet_backpressure(head)
    assert head._sent[-1][2] == 8.0
    # quiet windows (< bound // 2) step the level back down one at a time
    head._inlet_frames = 2
    Cluster._evaluate_inlet_backpressure(head)
    assert head._bp_level == 5
    while head._bp_level > 0:
        head._inlet_frames = 0
        Cluster._evaluate_inlet_backpressure(head)
    assert head._sent[-1] == ("control_backpressure", 0, 0.0)


def test_backpressure_disabled_when_bound_zero(monkeypatch):
    from ray_tpu.core.node import Cluster

    monkeypatch.setenv("RAY_TPU_CONTROL_INLET_BOUND", "0")
    head = _fake_head()
    head._inlet_frames = 10_000
    Cluster._evaluate_inlet_backpressure(head)
    assert head._bp_level == 0 and head._sent == []
    assert Cluster._inlet_shed_ceiling(head) == 0
    monkeypatch.setenv("RAY_TPU_CONTROL_INLET_BOUND", "100")
    assert Cluster._inlet_shed_ceiling(head) == 400


def test_agent_widens_flush_interval_on_backpressure():
    """The agent side of the typed signal: a control_backpressure message
    raises the flush loop's effective minimum interval."""
    from ray_tpu.core.node_agent import NodeAgent

    agent = types.SimpleNamespace(_bp_min_interval_s=0.0)
    NodeAgent._handle_head_message(agent, ("control_backpressure", 2, 4.0))
    assert agent._bp_min_interval_s == 4.0
    NodeAgent._handle_head_message(agent, ("control_backpressure", 0, 0.0))
    assert agent._bp_min_interval_s == 0.0


# ------------------------------------------------------- history durability

def _frame(ts, reqs=1.0):
    return {"ts": float(ts), "metrics": {
        "serve_requests_total": {"name": "serve_requests_total",
                                 "type": "counter", "description": "",
                                 "values": {(): reqs}}}}


def test_history_restore_prepends_only_older_frames():
    h = MetricsHistory(maxlen=16)
    h.record(_frame(100.0)["metrics"], ts=100.0)
    h.record(_frame(101.0)["metrics"], ts=101.0)
    # journaled frames: two older (accepted), one newer (must be dropped —
    # a restore can never reorder or clobber live scrapes), one malformed
    n = h.restore([_frame(99.0), _frame(98.0), _frame(100.5),
                   {"ts": "bad"}, "junk"])
    assert n == 2
    assert [f["ts"] for f in h.frames()] == [98.0, 99.0, 100.0, 101.0]
    assert h.restore([_frame(99.5)]) == 0  # nothing older than the oldest


def test_history_journal_roundtrip_through_kv(monkeypatch):
    """_journal_history -> KV -> _restore_history_journal on a fresh history:
    the restart warm-start path, against an in-memory KV."""
    from ray_tpu.core.node import Cluster

    monkeypatch.setenv("RAY_TPU_CONTROL_HISTORY_JOURNAL_FRAMES", "3")
    store = {}
    kv = types.SimpleNamespace(
        put=lambda k, v, namespace=None: store.__setitem__((namespace, k), v),
        get=lambda k, namespace=None: store.get((namespace, k)))
    def head(history):
        # _HISTORY_JOURNAL_* are Cluster class attributes; the fake needs
        # them as instance attributes
        return types.SimpleNamespace(
            metrics_history=history, gcs=types.SimpleNamespace(kv=kv),
            _HISTORY_JOURNAL_KEY=Cluster._HISTORY_JOURNAL_KEY,
            _HISTORY_JOURNAL_NS=Cluster._HISTORY_JOURNAL_NS)

    old = head(MetricsHistory(maxlen=16))
    for ts in (10.0, 11.0, 12.0, 13.0, 14.0):
        old.metrics_history.record(_frame(ts)["metrics"], ts=ts)
    Cluster._journal_history(old)
    assert store  # journal landed in the KV

    new = head(MetricsHistory(maxlen=16))
    Cluster._restore_history_journal(new)
    # only the last N=3 frames were journaled; all restore into cold history
    assert [f["ts"] for f in new.metrics_history.frames()] == [12.0, 13.0, 14.0]


def test_history_journal_disabled(monkeypatch):
    from ray_tpu.core.node import Cluster

    monkeypatch.setenv("RAY_TPU_CONTROL_HISTORY_JOURNAL_FRAMES", "0")
    boom = types.SimpleNamespace()  # any attribute access would raise
    Cluster._journal_history(boom)
    Cluster._restore_history_journal(boom)


# ------------------------------------------------------ bounded /api/history

def _series_fixture(n_frames):
    h = MetricsHistory(maxlen=max(n_frames + 4, 8))
    for i in range(n_frames):
        h.record({"serve_requests_total": {
            "name": "serve_requests_total", "type": "counter",
            "description": "", "values": {(): float(i)}}}, ts=1000.0 + i)
    return types.SimpleNamespace(metrics_history=h)


def test_history_series_downsamples_and_flags(monkeypatch):
    from ray_tpu.util import state

    monkeypatch.setattr(state, "_cluster", lambda: _series_fixture(50))
    monkeypatch.setenv("RAY_TPU_CONTROL_HISTORY_MAX_POINTS", "10")
    out = state.history_series(window_s=1e6)
    assert out["truncated"] is True
    assert len(out["ts"]) <= 10
    assert out["ts"][-1] == 1049.0  # the newest frame is always retained
    assert all(len(v) == len(out["ts"]) for v in out["series"].values())


def test_history_series_unbounded_below_cap(monkeypatch):
    from ray_tpu.util import state

    monkeypatch.setattr(state, "_cluster", lambda: _series_fixture(20))
    monkeypatch.setenv("RAY_TPU_CONTROL_HISTORY_MAX_POINTS", "500")
    out = state.history_series(window_s=1e6)
    assert out["truncated"] is False and len(out["ts"]) == 20


def test_history_series_caps_series_count(monkeypatch):
    from ray_tpu.util import state

    monkeypatch.setattr(state, "_cluster", lambda: _series_fixture(5))
    monkeypatch.setenv("RAY_TPU_CONTROL_HISTORY_MAX_SERIES", "2")
    out = state.history_series(window_s=1e6)
    assert out["truncated"] is True and len(out["series"]) == 2


# ------------------------------------------------------------ bench harness

def test_control_bench_dry_run(tmp_path):
    """CONTROL_BENCH smoke check inside the tier-1 budget: the mode is wired,
    the gate file lands, and the gate thresholds come from the env knobs."""
    out = tmp_path / "CONTROL_BENCH.json"
    r = subprocess.run(
        [sys.executable, os.path.join(REPO, "core_bench.py"),
         "--control-plane", "--dry-run", "--out", str(out)],
        capture_output=True, text=True, timeout=120,
        env={**os.environ, "JAX_PLATFORMS": "cpu",
             "RAY_TPU_CONTROL_P99_MS": "123.0"})
    assert r.returncode == 0, r.stderr
    doc = json.loads(out.read_text())
    assert doc["dry_run"] is True
    assert doc["gates"]["p99_threshold_ms"] == 123.0
    assert doc["gates"]["agg_speedup_threshold"] == 4.0


def test_control_bench_checked_in_gates_pass():
    """The committed CONTROL_BENCH.json evidence must show passing gates."""
    path = os.path.join(REPO, "CONTROL_BENCH.json")
    doc = json.loads(open(path).read())
    assert doc["passed"] is True
    assert doc["gates"]["p99_passed"] and doc["gates"]["agg_passed"]
    assert set(doc["fleets"]) == {"64", "256", "1024"}


# ----------------------------------------------------------------- slow e2e

@pytest.mark.slow
def test_e2e_node_delta_aggregation():
    """Full path with a real agent subprocess: workers on the remote node
    push metrics, the agent coalesces them into ONE node delta, and the head
    lands them in metrics_by_node (per-worker entries replaced)."""
    import ray_tpu
    from ray_tpu.core import global_state

    ray_tpu.shutdown()
    os.environ["RAY_TPU_CONTROL_NODE_FLUSH_S"] = "0.5"
    os.environ["RAY_TPU_METRICS_REPORT_INTERVAL_S"] = "0.25"
    try:
        ray_tpu.init(num_cpus=2, node_server_port=0,
                     worker_env={"JAX_PLATFORMS": "cpu"})
        cluster = global_state.try_cluster()
        agent = subprocess.Popen(
            [sys.executable, "-m", "ray_tpu.core.node_agent",
             "--address", f"127.0.0.1:{cluster.node_server_port}",
             "--num-cpus", "2"],
            env={**os.environ, "JAX_PLATFORMS": "cpu"})
        try:
            deadline = time.time() + 30
            while len([x for x in ray_tpu.nodes() if x["Alive"]]) < 2:
                assert time.time() < deadline, "agent never registered"
                time.sleep(0.2)
            from ray_tpu.core.task_spec import NodeAffinitySchedulingStrategy

            remote_id = next(n["NodeID"] for n in ray_tpu.nodes()
                             if n["Alive"] and n["Labels"].get("agent") == "remote")

            @ray_tpu.remote(scheduling_strategy=NodeAffinitySchedulingStrategy(
                node_id=remote_id))
            def bump():
                from ray_tpu.util import metrics as m
                m.Counter("e2e_agg_total", "x").inc(5)
                return True

            assert all(ray_tpu.get([bump.remote() for _ in range(2)],
                                   timeout=60))
            deadline = time.time() + 20
            while time.time() < deadline:
                merged = M.merge_snapshots(
                    list(cluster.metrics_by_node.values())
                    + list(cluster.metrics_by_worker.values()))
                if cluster.metrics_by_node and \
                        sum(merged.get("e2e_agg_total", {}).get(
                            "values", {}).values()) >= 10:
                    break
                time.sleep(0.25)
            assert cluster.metrics_by_node, "node delta never reached the head"
            assert sum(merged["e2e_agg_total"]["values"].values()) == 10
        finally:
            if agent.poll() is None:
                agent.terminate()
                try:
                    agent.wait(timeout=10)
                except subprocess.TimeoutExpired:
                    agent.kill()
    finally:
        os.environ.pop("RAY_TPU_CONTROL_NODE_FLUSH_S", None)
        os.environ.pop("RAY_TPU_METRICS_REPORT_INTERVAL_S", None)
        ray_tpu.shutdown()
