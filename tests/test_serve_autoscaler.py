"""Serve autoscaling control loop (ISSUE 15 tentpole; serve/autoscaler.py):
policy hysteresis/cooldowns, controller target plumbing, fail-point
robustness, stuck-scale-up demand hand-off. The load-generating end-to-end
chaos run is slow-marked (tier-1 covers the deterministic pieces)."""
import dataclasses
import os
import time

import pytest

import ray_tpu
from ray_tpu import serve
from ray_tpu.serve.autoscaler import (
    AutoscalePolicy,
    DeploymentSnapshot,
    ensure_serve_autoscaler,
    get_serve_autoscaler,
    shutdown_serve_autoscaler,
)
from ray_tpu.test_utils import wait_for_condition
from ray_tpu.util import fault_injection as fi


def _snap(now, **kw):
    d = dict(key="a/D", target=1, running=1, starting=0, draining=0,
             min_replicas=1, max_replicas=4, queue_depth=0.0,
             queue_target=4.0, burning=False, now=float(now))
    d.update(kw)
    return DeploymentSnapshot(**d)


def _policy(**kw):
    d = dict(burn_ticks=2, clean_ticks=2, up_cooldown_s=1.0,
             down_cooldown_s=5.0, startup_timeout_s=3.0)
    d.update(kw)
    return AutoscalePolicy(**d)


# ------------------------------------------------------------ policy (pure)

def test_policy_burn_scales_up_after_hysteresis():
    p = _policy()
    assert p.decide(_snap(0, burning=True)).reason == "hold"  # 1 tick: hold
    d = p.decide(_snap(1, burning=True))  # sustained: scale up
    assert d.changed and d.desired == 2 and d.reason == "slo_burn"


def test_policy_queue_depth_scales_toward_demand():
    p = _policy()
    # 9 in flight at target 2/replica -> the fleet needs ceil(9/2) = 5,
    # capped by max_replicas = 4
    p.decide(_snap(0, queue_depth=9.0, queue_target=2.0))
    d = p.decide(_snap(1, queue_depth=9.0, queue_target=2.0))
    assert d.desired == 4 and d.reason == "queue_depth"


def test_policy_up_cooldown_blocks_repeat_up():
    p = _policy(burn_ticks=1, up_cooldown_s=10.0)
    d = p.decide(_snap(0, burning=True))
    assert d.desired == 2
    p.commit(d, 0)
    d2 = p.decide(_snap(1, burning=True, target=2, running=2))
    assert not d2.changed and d2.reason == "up_cooldown"
    d3 = p.decide(_snap(11, burning=True, target=2, running=2))
    assert d3.desired == 3  # cooldown elapsed, burn still sustained


def test_policy_clean_scale_down_gated_by_cooldown_and_drains():
    p = _policy(clean_ticks=2, down_cooldown_s=5.0)
    base = dict(target=3, running=3)
    assert p.decide(_snap(0, **base)).reason == "hold"  # clean tick 1
    d = p.decide(_snap(6, **base))  # clean tick 2, cooldown long past
    assert d.desired == 2 and d.reason == "clean_scale_down"
    p.commit(d, 6)
    # next down inside the cooldown window: held
    p.decide(_snap(7, target=2, running=2))
    assert p.decide(_snap(8, target=2, running=2)).reason == "down_cooldown"
    # a replica still DRAINING means capacity is already leaving: no new down
    p2 = _policy(clean_ticks=1, down_cooldown_s=0.0)
    assert p2.decide(_snap(0, target=3, running=3, draining=1)).reason == "hold"


def test_policy_never_below_min_or_last_replica():
    p = _policy(clean_ticks=1, down_cooldown_s=0.0)
    # at the floor: clean windows never push below min_replicas
    assert not p.decide(_snap(0, target=2, running=2, min_replicas=2)).changed
    # min_replicas=0 still floors at 1 (never kill the last healthy replica)
    d = p.decide(_snap(1, target=1, running=1, min_replicas=0))
    assert d.desired == 1 and not d.changed
    # a single running replica is never drained even when target allows it
    assert not p.decide(_snap(2, target=2, running=1, min_replicas=0)).changed
    # bounds correction applies immediately (shrunk max)
    d = p.decide(_snap(3, target=6, running=6, max_replicas=4))
    assert d.desired == 4 and d.reason == "max_ceiling"


def test_policy_flapping_slo_holds_steady():
    p = _policy(burn_ticks=2, clean_ticks=3, down_cooldown_s=0.0)
    for i in range(12):  # burn/clean alternating: neither side sustains
        d = p.decide(_snap(i, target=2, running=2, burning=(i % 2 == 0)))
        assert not d.changed, d


def test_policy_stuck_deficit_timer():
    p = _policy(startup_timeout_s=2.0)
    assert not p.stuck_deficit(_snap(0, target=3, running=1))  # timer starts
    assert not p.stuck_deficit(_snap(1, target=3, running=1))
    assert p.stuck_deficit(_snap(2.5, target=3, running=1))
    # deficit closes: timer resets
    assert not p.stuck_deficit(_snap(3, target=3, running=3))
    assert not p.stuck_deficit(_snap(10, target=3, running=1))


# ------------------------------------------------- controller + loop (cluster)

@pytest.fixture()
def fast_loop(rt):
    """Fast scrape cadence + a FRESH loop built under it (the session loop
    may have been created with default knobs)."""
    env = {"RAY_TPU_METRICS_SCRAPE_INTERVAL_S": "0.2",
           "RAY_TPU_SERVE_AUTOSCALE_UP_COOLDOWN_S": "0.5"}
    saved = {k: os.environ.get(k) for k in env}
    os.environ.update(env)
    shutdown_serve_autoscaler()
    fi.disarm()
    yield
    fi.disarm()
    serve.shutdown()
    for k, v in saved.items():
        if v is None:
            os.environ.pop(k, None)
        else:
            os.environ[k] = v
    shutdown_serve_autoscaler()


@serve.deployment
class SlowEcho:
    def __call__(self, x):
        time.sleep(0.25)
        return x


def test_autoscale_state_and_target_clamping(fast_loop):
    app = SlowEcho.options(
        max_ongoing_requests=2,
        autoscaling_config=serve.AutoscalingConfig(
            min_replicas=1, max_replicas=2, mode="slo",
            target_queue_depth=2.0)).bind()
    serve.run(app, name="asc-clamp")
    controller = ray_tpu.get_actor("SERVE_CONTROLLER")
    state = ray_tpu.get(controller.get_autoscale_state.remote())
    row = state["asc-clamp/SlowEcho"]
    assert row["min_replicas"] == 1 and row["max_replicas"] == 2
    assert row["target"] == 1 and row["target_queue_depth"] == 2.0
    # clamped above max, and below the never-below-one floor
    assert ray_tpu.get(controller.set_autoscale_target.remote(
        "asc-clamp", "SlowEcho", 99, reason="test")) == 2
    assert ray_tpu.get(controller.set_autoscale_target.remote(
        "asc-clamp", "SlowEcho", 0, reason="test")) == 1
    # an ongoing-mode (default) deployment never enters the slo-loop view
    serve.run(SlowEcho.options(autoscaling_config=serve.AutoscalingConfig(
        min_replicas=1, max_replicas=2)).bind(), name="asc-legacy",
        route_prefix="/asc-legacy")
    state = ray_tpu.get(controller.get_autoscale_state.remote())
    assert "asc-clamp/SlowEcho" in state
    assert "asc-legacy/SlowEcho" not in state


def test_loop_scales_up_on_queue_pressure_and_survives_faults(fast_loop):
    """One cluster round-trip covers three tier-1 behaviors: a decide-path
    crash is absorbed and journaled, a lost controller scale RPC is retried
    next tick, and sustained queue pressure still scales the deployment up."""
    app = SlowEcho.options(
        max_ongoing_requests=2,
        autoscaling_config=serve.AutoscalingConfig(
            min_replicas=1, max_replicas=3, mode="slo",
            target_queue_depth=2.0)).bind()
    h = serve.run(app, name="asc-up")
    loop = get_serve_autoscaler()
    assert loop is not None and loop.alive()
    # 1) crash the decision path: the loop must absorb + journal, not die
    fi.ChaosController.arm_serve_autoscaler(mode="error", count=2)
    wait_for_condition(
        lambda: any(d.get("event") == "decide_error"
                    for d in loop.status()["decisions"]),
        timeout=10, message="decide-path crash never journaled")
    assert loop.alive()
    # 2) lose the first scale RPC in the controller process
    fi.ChaosController().arm_serve_controller(count=1)
    # 3) sustained queue pressure: concurrent slow calls pile in-flight depth
    deadline = time.time() + 25
    target = 1
    while time.time() < deadline and target < 2:
        resps = [h.remote(i) for i in range(8)]
        for r in resps:
            r.result()
        target = loop.status()["deployments"].get(
            "asc-up/SlowEcho", {}).get("target", 1)
    assert target >= 2, loop.status()
    events = [d["event"] for d in loop.status()["decisions"]]
    assert "scale_rpc_error" in events  # the lost RPC was journaled...
    assert "scale" in events  # ...and the next tick's retry landed
    st = loop.status()["deployments"]["asc-up/SlowEcho"]
    assert st["running"] >= 1 and st["reason"]


def test_stuck_scale_up_posts_demand_hint_and_clears(rt):
    """A deficit older than the startup timeout hands the missing replicas'
    shapes to the node autoscaler's bin-packing and journals the episode;
    closing the deficit clears the hint."""
    from ray_tpu import autoscaler as node_autoscaler

    loop = ensure_serve_autoscaler()
    loop.policy.startup_timeout_s = 0.5

    class _DeadController:  # restart RPC must be best-effort
        pass

    now = time.monotonic()
    row = {"resource_shape": {"CPU": 2.0}}
    snap = _snap(now, key="a/Stuck", target=3, running=1)
    loop._handle_deficit(_DeadController(), "a", "Stuck", row, snap)
    snap2 = dataclasses.replace(snap, now=now + 1.0)
    loop._handle_deficit(_DeadController(), "a", "Stuck", row, snap2)
    hints = node_autoscaler.demand_hints()
    assert hints.get("serve:a/Stuck") == [{"CPU": 2.0}, {"CPU": 2.0}]
    assert any(d.get("event") == "scale_up_stuck"
               for d in loop.status()["decisions"])
    # deficit closes -> hint cleared
    snap3 = dataclasses.replace(snap, now=now + 2.0, running=3)
    loop._handle_deficit(_DeadController(), "a", "Stuck", row, snap3)
    assert "serve:a/Stuck" not in node_autoscaler.demand_hints()


def test_legacy_ongoing_mode_still_owned_by_controller(rt):
    """mode="ongoing" (default) deployments stay with the controller's
    request-rate rule and never appear in the slo-loop view."""
    cfg = serve.AutoscalingConfig(min_replicas=1, max_replicas=2)
    assert cfg.mode == "ongoing"
    with pytest.raises(ValueError):
        serve.AutoscalingConfig(mode="nope")


@pytest.mark.slow
def test_e2e_chaos_kill_and_load_step_closed_loop(rt):
    """The full closed loop under open-loop HTTP load (slow: tier-1 runs the
    deterministic variants above): SIGKILL a replica — the loop restores the
    running count to target with no operator action and the burning SLO
    returns to ok within the scrape-interval budget; a 2x load step scales
    the fleet up with goodput recovering >= 1.2x."""
    import os as _os
    import sys as _sys

    _sys.path.insert(0, _os.path.dirname(_os.path.dirname(__file__)))
    import bench_serve

    serve.start(http_options={"port": 18446})
    try:
        out = bench_serve.run_chaos_autoscale(
            18446, service_s=0.06, warm_s=4.0, step_s=10.0, app="asc-e2e")
    finally:
        serve.shutdown()
    assert out["gates"]["replica_replaced_by_loop"], out
    assert out["gates"]["slo_recovered_within_budget"], out
    assert out["gates"]["scale_up_observed"], out
    assert out["goodput_ratio"] >= 1.2, out
