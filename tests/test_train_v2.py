"""Train v2 controller tests (reference python/ray/train/v2/)."""
import os

import pytest

import ray_tpu
from ray_tpu import train
from ray_tpu.train import (
    DefaultFailurePolicy,
    ElasticScalingPolicy,
    FailureDecision,
    FixedScalingPolicy,
    TrainController,
    TrainControllerState,
)
from ray_tpu.air.config import FailureConfig, RunConfig, ScalingConfig


@pytest.fixture(autouse=True)
def _cluster(rt):
    yield


def test_default_failure_policy_decisions():
    p = DefaultFailurePolicy(max_failures=2)
    assert p.make_decision(RuntimeError(), 1) == FailureDecision.RETRY
    assert p.make_decision(RuntimeError(), 2) == FailureDecision.RETRY
    assert p.make_decision(RuntimeError(), 3) == FailureDecision.RAISE
    unlimited = DefaultFailurePolicy(max_failures=-1)
    assert unlimited.make_decision(RuntimeError(), 99) == FailureDecision.RETRY


def test_elastic_policy_fits_available_cpus(rt):
    sc = ScalingConfig(num_workers=1, cpus_per_worker=1.0)
    pol = ElasticScalingPolicy(min_workers=1, max_workers=64, scaling_config=sc)
    d = pol.make_decision_for_non_running_worker_group()
    total = ray_tpu.cluster_resources().get("CPU", 0)
    assert 1 <= d.num_workers <= min(64, int(total))


def test_controller_runs_to_finished(rt, tmp_path):
    def loop(config):
        from ray_tpu import train as t

        for i in range(3):
            t.report({"it": i})

    ctl = TrainController(
        loop,
        backend_config=train.BackendConfig(),
        scaling_config=ScalingConfig(num_workers=2),
        run_config=RunConfig(storage_path=str(tmp_path)),
        train_loop_config={},
    )
    result = ctl.run()
    assert result.error is None
    assert ctl.state == TrainControllerState.FINISHED
    assert result.metrics["it"] == 2
    assert TrainControllerState.SCHEDULING in ctl._state_log
    assert TrainControllerState.RUNNING in ctl._state_log


def test_controller_retries_worker_failure(rt, tmp_path):
    marker = tmp_path / "failed_once"

    def loop(config):
        import os

        from ray_tpu import train as t

        ctx = t.get_context()
        if ctx.get_world_rank() == 0 and not os.path.exists(config["marker"]):
            open(config["marker"], "w").write("1")
            os._exit(1)  # hard crash
        t.report({"done": 1})

    ctl = TrainController(
        loop,
        backend_config=train.BackendConfig(),
        scaling_config=ScalingConfig(num_workers=2),
        run_config=RunConfig(storage_path=str(tmp_path),
                             failure_config=FailureConfig(max_failures=2)),
        train_loop_config={"marker": str(marker)},
    )
    result = ctl.run()
    assert result.error is None, result.error
    assert ctl.failure_count == 1
    assert TrainControllerState.RESTARTING in ctl._state_log
    assert ctl.state == TrainControllerState.FINISHED


def test_controller_errors_when_policy_exhausted(rt, tmp_path):
    def loop(config):
        import os

        os._exit(1)

    ctl = TrainController(
        loop,
        backend_config=train.BackendConfig(),
        scaling_config=ScalingConfig(num_workers=1),
        run_config=RunConfig(storage_path=str(tmp_path),
                             failure_config=FailureConfig(max_failures=1)),
    )
    result = ctl.run()
    assert result.error is not None
    assert ctl.state == TrainControllerState.ERRORED
    assert ctl.failure_count == 2  # initial + one retry


def test_v2_env_gate_via_trainer(rt, tmp_path, monkeypatch):
    monkeypatch.setenv("RAY_TPU_TRAIN_V2_ENABLED", "1")

    def loop(config):
        from ray_tpu import train as t

        t.report({"v2": 1})

    trainer = train.JaxTrainer(
        loop,
        scaling_config=ScalingConfig(num_workers=1),
        run_config=RunConfig(storage_path=str(tmp_path)),
    )
    result = trainer.fit()
    assert result.error is None
    assert result.metrics["v2"] == 1
