"""Request-scoped critical-path tracing: W3C traceparent in/out at the serve
HTTP ingress, trace-tagged telemetry, span-tree reconstruction + wall-time
attribution (ISSUE 8 tentpole part 3; util/tracing.py, util/state.py)."""
import os
import time

import pytest

from ray_tpu.util import tracing


@pytest.fixture
def _clean_tracing():
    # clear any context residue a prior test minted on this thread (a bare
    # get_trace_context() sets one that nothing resets)
    tracing._ctx.set(None)
    yield
    tracing._ctx.set(None)
    os.environ.pop("RAY_TPU_TRACING", None)
    tracing._enabled = False


def test_traceparent_parse_and_format():
    tid, sid = "a" * 32, "b" * 16
    hdr = tracing.format_traceparent(tid, sid)
    assert hdr == f"00-{tid}-{sid}-01"
    ctx = tracing.parse_traceparent(hdr)
    assert ctx == {"trace_id": tid, "parent_span_id": sid}
    # case-insensitive + surrounding whitespace tolerated
    assert tracing.parse_traceparent(f"  00-{tid.upper()}-{sid}-00 ") is not None
    # malformed / all-zero ids rejected per spec
    for bad in (None, "", "garbage", f"00-{tid}-{sid}", f"00-{'0' * 32}-{sid}-01",
                f"00-{tid}-{'0' * 16}-01", f"zz-{tid}-{sid}-01"):
        assert tracing.parse_traceparent(bad) is None


def test_current_trace_id_is_pure_read(_clean_tracing):
    tracing._enabled = False
    assert tracing.current_trace_id() is None
    tracing.enable_tracing()
    # unlike get_trace_context, current_trace_id must NOT mint a context
    assert tracing.current_trace_id() is None
    with tracing.span("root"):
        tid = tracing.current_trace_id()
        assert tid and len(tid) == 32
    tracing.drain_local_spans()


def test_telemetry_events_tagged_with_active_trace(_clean_tracing):
    from ray_tpu.util import telemetry

    tracing.enable_tracing()
    telemetry.enable()
    try:
        telemetry.drain()
        with tracing.span("req"):
            tid = tracing.current_trace_id()
            telemetry.event("transfer.pull", "transfer", bytes=10)
            with telemetry.span("llm.prefill", "llm"):
                pass
        telemetry.event("outside", "test")
        evs = {e["name"]: e for e in telemetry.drain()}
        assert evs["transfer.pull"]["args"]["trace_id"] == tid
        assert evs["llm.prefill"]["args"]["trace_id"] == tid
        assert "trace_id" not in evs["outside"]["args"]
    finally:
        telemetry.reset_forced()
        tracing.drain_local_spans()


def test_attribution_sweep_priorities():
    """The sweep charges each instant to the highest-priority covering phase
    (queue > prefill > decode > transfer), remainder to other — overlapping
    phase intervals cannot double-count, so the sum is exactly the window."""
    from ray_tpu.util.state import _attribute

    intervals = [
        (1.0, 2.0, "queue"),
        (2.0, 4.0, "prefill"),
        (4.0, 9.0, "decode"),
        (8.0, 10.0, "transfer"),   # overlaps decode 8..9: decode wins there
    ]
    out = _attribute(intervals, 0.0, 12.0)
    assert out["queue"] == pytest.approx(1.0)
    assert out["prefill"] == pytest.approx(2.0)
    assert out["decode"] == pytest.approx(5.0)
    assert out["transfer"] == pytest.approx(1.0)  # only 9..10 is transfer-only
    assert out["other"] == pytest.approx(3.0)     # 0..1 + 10..12
    assert sum(out.values()) == pytest.approx(12.0)
    # clipping: intervals outside the window cannot inflate the total
    out = _attribute([(5.0, 50.0, "decode")], 0.0, 10.0)
    assert out["decode"] == pytest.approx(5.0)
    assert sum(out.values()) == pytest.approx(10.0)


def test_request_trace_not_found(rt):
    from ray_tpu.util import state as rs

    doc = rs.request_trace("f" * 32)
    assert doc["found"] is False and doc["spans"] == []


def test_http_traceparent_end_to_end(rt, _clean_tracing):
    """Acceptance: a request carrying a traceparent yields a
    state.request_trace whose attribution sums to within 10% of the measured
    end-to-end latency, with spans from >= 2 processes (proxy + replica)."""
    import urllib.request

    from ray_tpu import serve
    from ray_tpu.util import state as rs

    @serve.deployment
    class SleepyEcho:
        def __call__(self, payload):
            time.sleep(0.5)
            return {"got": payload}

    trace_id = os.urandom(16).hex()
    parent_id = os.urandom(8).hex()
    try:
        serve.start(http_options={"port": 18323})
        serve.run(SleepyEcho.bind(), name="traced", route_prefix="/traced")

        # warm the path (replica discovery, executor spin-up) untraced
        req = urllib.request.Request("http://127.0.0.1:18323/traced",
                                     data=b'{"warm": 1}',
                                     headers={"Content-Type": "application/json"})
        urllib.request.urlopen(req, timeout=60).read()

        req = urllib.request.Request(
            "http://127.0.0.1:18323/traced", data=b'{"a": 1}',
            headers={"Content-Type": "application/json",
                     "traceparent": f"00-{trace_id}-{parent_id}-01"})
        t0 = time.perf_counter()
        with urllib.request.urlopen(req, timeout=60) as resp:
            body = resp.read()
            echoed = resp.headers.get("traceparent", "")
        e2e_s = time.perf_counter() - t0
        assert b'"a"' in body
        # the ingress echoes the SAME trace with ITS span as the new parent
        assert echoed.startswith(f"00-{trace_id}-")
        assert parent_id not in echoed

        deadline = time.time() + 15
        doc = {}
        while time.time() < deadline:
            doc = rs.request_trace(trace_id)
            if doc["found"] and len(doc["processes"]) >= 2 and any(
                    s["name"] == "serve.http" for s in doc["spans"]):
                break
            time.sleep(0.2)
        assert doc.get("found"), "trace never reached the head"
        assert len(doc["processes"]) >= 2, doc["processes"]

        names = [s["name"] for s in doc["spans"]]
        assert "serve.http" in names
        assert any(n.startswith("replica.") for n in names), names
        root = doc["spans"][0]
        assert root["name"] == "serve.http" and root["depth"] == 0
        assert root["parent_span_id"] == parent_id  # stitched to the caller
        # the replica span nests under the ingress
        rep = next(s for s in doc["spans"] if s["name"].startswith("replica."))
        assert rep["depth"] >= 1

        # attribution sums to the root window (exact by construction) and the
        # root window is within 10% of the measured end-to-end latency
        total = doc["total_s"]
        assert sum(doc["attribution"].values()) == pytest.approx(total, rel=1e-6)
        assert abs(total - e2e_s) / e2e_s < 0.10, (total, e2e_s)
        # the 0.5s handler sleep dominates: "other" carries it (no llm phases)
        assert doc["attribution"]["other"] >= 0.4
    finally:
        serve.shutdown()


def test_llm_phase_attribution_from_tagged_events(rt):
    """Engine-phase events tagged with a trace id are bucketed into
    queue/prefill/decode on the reconstructed critical path (synthetic events
    through the real telemetry -> head -> request_trace pipeline)."""
    from ray_tpu.util import state as rs
    from ray_tpu.util import telemetry

    tid = os.urandom(16).hex()
    telemetry.enable()
    try:
        t0 = time.time_ns()
        ms = 1_000_000
        telemetry.complete("llm.queue", "llm", t0, 20 * ms,
                           request_id="r1", trace_id=tid)
        telemetry.complete("llm.prefill", "llm", t0 + 20 * ms, 30 * ms,
                           request_id="r1", trace_id=tid)
        telemetry.complete("llm.decode", "llm", t0 + 50 * ms, 100 * ms,
                           request_id="r1", trace_id=tid)
        telemetry.complete("transfer.pull", "transfer", t0 + 60 * ms, 10 * ms,
                           bytes=1 << 20, trace_id=tid)
        doc = rs.request_trace(tid)
        assert doc["found"]
        att = doc["attribution"]
        assert att["queue"] == pytest.approx(0.020, abs=1e-6)
        assert att["prefill"] == pytest.approx(0.030, abs=1e-6)
        # the transfer overlaps decode: decode keeps the overlap
        assert att["decode"] == pytest.approx(0.100, abs=1e-6)
        assert att["transfer"] == pytest.approx(0.0, abs=1e-6)
        assert sum(att.values()) == pytest.approx(doc["total_s"], rel=1e-6)
        phases = {e["name"]: e["phase"] for e in doc["events"]}
        assert phases["llm.queue"] == "queue"
        assert phases["transfer.pull"] == "transfer"
    finally:
        telemetry.reset_forced()


def test_engine_request_captures_trace_id(_clean_tracing):
    """_Request snapshots the caller's trace context at creation — the
    scheduler loop recording queue/prefill/decode has no context of its own."""
    from ray_tpu.llm.config import SamplingParams
    from ray_tpu.llm.engine import _Request

    tracing.enable_tracing()
    with tracing.span("req"):
        tid = tracing.current_trace_id()
        req = _Request("r1", [1, 2, 3], SamplingParams(max_tokens=4))
    assert req.trace_id == tid
    req2 = _Request("r2", [1], SamplingParams(max_tokens=1))
    assert req2.trace_id is None  # no active context -> untraced
    tracing.drain_local_spans()
