"""SAC tests (reference rllib/algorithms/sac; SURVEY.md §2.5 algorithms row)."""
import numpy as np
import pytest

from ray_tpu.rllib.core.distributions import SquashedGaussian


def test_squashed_gaussian_bounds_and_logp():
    rng = np.random.default_rng(0)
    b, a = 512, 2
    mu = rng.normal(size=(b, a)).astype(np.float32)
    log_std = np.full((b, a), -0.5, np.float32)
    low = np.full((b, a), -2.0, np.float32)
    high = np.full((b, a), 2.0, np.float32)
    inputs = np.concatenate([mu, log_std, low, high], axis=1)
    acts = SquashedGaussian.sample_np(inputs, rng)
    assert acts.shape == (b, a)
    assert (acts > -2.0).all() and (acts < 2.0).all()  # squashed into bounds
    greedy = SquashedGaussian.greedy_np(inputs)
    np.testing.assert_allclose(greedy, -2 + (np.tanh(mu) + 1) * 2, rtol=1e-5)
    # logp consistency: numpy and jax agree
    logp_np = SquashedGaussian.logp_np(inputs, acts)
    import jax.numpy as jnp

    logp_jax = np.asarray(SquashedGaussian.logp_jax(jnp.asarray(inputs), jnp.asarray(acts)))
    np.testing.assert_allclose(logp_np, logp_jax, rtol=1e-3, atol=1e-3)
    assert np.isfinite(logp_np).all()


def test_replay_buffer_continuous_actions():
    from ray_tpu.rllib.utils.replay_buffer import ReplayBuffer

    buf = ReplayBuffer(capacity=64)
    t = 6
    ep = {
        "obs": np.random.randn(t, 3).astype(np.float32),
        "next_obs_last": np.random.randn(3).astype(np.float32),
        "actions": np.random.randn(t, 2).astype(np.float32),  # float vectors
        "rewards": np.ones(t, np.float32),
        "terminated": True,
        "truncated": False,
    }
    buf.add_episodes([ep])
    batch = buf.sample(8, np.random.default_rng(0))
    assert batch["actions"].shape == (8, 2)
    assert batch["actions"].dtype == np.float32


def test_sac_learns_pendulum(rt):
    """SAC must clearly beat a random policy on Pendulum within a small budget."""
    from ray_tpu.rllib import SACConfig

    config = (
        SACConfig()
        .environment("Pendulum-v1")
        .env_runners(num_env_runners=2, num_envs_per_env_runner=2,
                     rollout_fragment_length=64)
        .training(lr=1e-3, train_batch_size=256,
                  num_steps_sampled_before_learning_starts=500,
                  num_updates_per_iteration=256,
                  sample_timesteps_per_iteration=256)
    )
    algo = config.build_algo()
    try:
        for _ in range(13):
            result = algo.step()
        assert result["alpha"] < 1.0  # temperature auto-tuning engaged
        assert np.isfinite(result["critic_loss"])
        ev = algo.evaluate(num_timesteps=800)["evaluation"]["episode_return_mean"]
        # random policy: ~-1200; anything better than -800 means real learning
        assert ev is not None and ev > -800.0, ev
    finally:
        algo.stop()