"""Self-healing serve plane: request retries, graceful draining, load
shedding, and fail-point-driven chaos (tier-1: deterministic, no load
generators — bench_serve.py --chaos carries the open-loop SLO-burn runs).

Reference analogs: serve request retries on RayActorError
(_private/router.py), replica draining (deployment_state.py graceful stop),
proxy backpressure (503 + Retry-After)."""
import threading
import time

import pytest

import ray_tpu
from ray_tpu import serve
from ray_tpu.core.exceptions import (BackPressureError, FaultInjectedError,
                                     ReplicaUnavailableError, TaskError)
from ray_tpu.test_utils import wait_for_condition
from ray_tpu.util import fault_injection as fi
from ray_tpu.util import state as rs
from ray_tpu.util.fault_injection import ChaosController


@pytest.fixture(autouse=True)
def _cleanup(rt):
    fi.disarm()
    yield
    fi.disarm()
    serve.shutdown()


@serve.deployment
class Echo:
    def __call__(self, x):
        import os

        return (os.getpid(), x)


def test_retry_on_send_failure(rt):
    """A handle-side send failure (injected at the serve.handle.send fail
    point) is retried transparently: the caller sees the result, not the
    fault."""
    h = serve.run(Echo.options(num_replicas=2).bind(), name="ft-send")
    assert h.remote(1).result()[1] == 1  # warm path, replicas discovered
    fi.arm("serve.handle.send", mode="error", count=1)
    assert h.remote(2).result()[1] == 2  # first send fails, retry succeeds
    assert fi.fired("serve.handle.send") == 1


def test_retry_on_replica_failure_feeds_suspects(rt):
    """An injected replica-side failure (serve.replica.request) is classified
    retryable; the request is resent to a different replica and the failed one
    lands on the router's suspect list."""
    h = serve.run(Echo.options(num_replicas=2).bind(), name="ft-rep")
    assert h.remote(0).result()[1] == 0
    chaos = ChaosController()
    # every replica fails exactly once: whichever gets the request bounces it,
    # the retry lands elsewhere (or re-picks after the budget of exclusions)
    assert chaos.arm_replica("ft-rep", "Echo", "serve.replica.request",
                             mode="error", count=1) == 2
    assert h.remote(5).result()[1] == 5
    assert len(h._router.suspects) >= 1  # failure fed the suspect list
    # subsequent requests keep working (suspects only bias routing)
    assert h.remote(6).result()[1] == 6


def test_retryable_false_surfaces_failure(rt):
    @serve.deployment(num_replicas=2, retryable=False)
    class NoRetry:
        def __call__(self, x):
            return x

    h = serve.run(NoRetry.bind(), name="ft-noretry")
    assert h.remote(1).result() == 1
    ChaosController().arm_replica("ft-noretry", "NoRetry",
                                  "serve.replica.request", mode="error",
                                  count=1)
    with pytest.raises(TaskError) as ei:
        h.remote(2).result()
    assert isinstance(ei.value.cause, FaultInjectedError)


def test_streaming_retries_only_before_first_chunk(rt):
    @serve.deployment(num_replicas=2)
    class Streamer:
        def __call__(self, n):
            for i in range(n):
                yield i

    h = serve.run(Streamer.bind(), name="ft-stream")
    assert list(h.options(stream=True).remote(3)) == [0, 1, 2]
    # failure at request start (no chunk yielded): retried transparently
    chaos = ChaosController()
    chaos.arm_replica("ft-stream", "Streamer", "serve.replica.request",
                      mode="error", count=1)
    assert list(h.options(stream=True).remote(4)) == [0, 1, 2, 3]

    @serve.deployment(num_replicas=2)
    class MidStreamFail:
        def __call__(self, n):
            yield 0
            raise ReplicaUnavailableError("ft-mid", "MidStreamFail",
                                          reason="injected mid-stream")

    h2 = serve.run(MidStreamFail.bind(), name="ft-mid")
    gen = h2.options(stream=True).remote(3)
    assert next(gen) == 0  # first chunk delivered...
    with pytest.raises(Exception):  # ...so a retryable-class failure SURFACES
        next(gen)


def test_replica_process_death_absorbed(rt):
    """SIGKILL one of two replicas' worker processes: in-flight and subsequent
    requests retry against the survivor — zero caller-visible failures."""
    @serve.deployment(num_replicas=2, max_ongoing_requests=4)
    class Sturdy:
        def __call__(self, x):
            time.sleep(0.05)
            return x * 2

    h = serve.run(Sturdy.bind(), name="ft-kill")
    # warm both replicas so the router knows them
    assert {h.remote(i).result() for i in range(4)} == {0, 2, 4, 6}
    resps = [h.remote(i) for i in range(6)]  # in-flight during the kill
    assert ChaosController().kill_replica("ft-kill", "Sturdy", index=0)
    assert sorted(r.result(timeout_s=30) for r in resps) == [0, 2, 4, 6, 8, 10]
    assert h.remote(7).result() == 14  # steady state after the kill


def test_death_push_heals_view_before_health_check(rt):
    """Regression: a replica SIGKILLed right before a scale-down sits
    undetected in the routing view for up to health_check_period_s — long
    enough for the scale-down to drain the HEALTHY replicas and keep the
    corpse. The handle's authoritative death push (report_replica_failure)
    must remove it immediately so traffic keeps flowing."""
    @serve.deployment(num_replicas=3, max_ongoing_requests=4,
                      health_check_period_s=10)  # explicit blind window
    class W:
        def __call__(self, x):
            time.sleep(0.02)
            return x + 1

    h = serve.run(W.bind(), name="ft-deathpush")
    assert {h.remote(i).result() for i in range(6)} == {i + 1 for i in range(6)}
    assert ChaosController().kill_replica("ft-deathpush", "W", index=0)
    # scale down BEFORE any health check can notice the corpse: the drain
    # keeps the first replica — the dead one
    serve.run(W.options(num_replicas=1).bind(), name="ft-deathpush")
    for i in range(20):
        assert h.remote(i).result(timeout_s=30) == i + 1


def test_graceful_drain_scale_down_zero_failures(rt):
    """Acceptance: scale-down 3 -> 1 under concurrent traffic completes with
    ZERO request failures (draining replicas finish their in-flight work and
    leave the routing view before the kill)."""
    @serve.deployment(num_replicas=3, max_ongoing_requests=4)
    class Work:
        def __call__(self, x):
            time.sleep(0.04)
            return x + 1

    serve.run(Work.bind(), name="ft-drain")
    errors, done = [], [0]
    stop = threading.Event()

    def client():
        h = serve.get_deployment_handle("Work", "ft-drain")
        i = 0
        while not stop.is_set():
            try:
                assert h.remote(i).result(timeout_s=30) == i + 1
                done[0] += 1
            except Exception as e:  # noqa: BLE001 — the assertion under test
                errors.append(e)
                return
            i += 1

    threads = [threading.Thread(target=client) for _ in range(6)]
    [t.start() for t in threads]
    time.sleep(0.7)  # traffic across all 3 replicas
    serve.run(Work.options(num_replicas=1).bind(), name="ft-drain")
    time.sleep(1.5)  # traffic THROUGH the scale-down
    stop.set()
    [t.join(timeout=30) for t in threads]
    assert not errors, errors[:3]
    assert done[0] > 50
    controller = ray_tpu.get_actor("SERVE_CONTROLLER")
    wait_for_condition(
        lambda: ray_tpu.get(controller.get_deployment_info.remote(
            "ft-drain", "Work"))["num_running"] == 1,
        timeout=30, message="scale-down never converged to 1 replica")


def test_rolling_update_drains_old_version(rt):
    """Version bump under traffic: old replicas drain (zero failures), new
    version takes over."""
    @serve.deployment(num_replicas=2, version="v1")
    class Ver:
        def __call__(self, x):
            time.sleep(0.02)
            return "v1"

    serve.run(Ver.bind(), name="ft-roll")
    errors, seen = [], set()
    stop = threading.Event()

    def client():
        h = serve.get_deployment_handle("Ver", "ft-roll")
        while not stop.is_set():
            try:
                seen.add(h.remote(0).result(timeout_s=30))
            except Exception as e:  # noqa: BLE001
                errors.append(e)
                return

    threads = [threading.Thread(target=client) for _ in range(4)]
    [t.start() for t in threads]
    time.sleep(0.4)

    @serve.deployment(num_replicas=2, version="v2")
    class Ver2:
        def __call__(self, x):
            time.sleep(0.02)
            return "v2"

    serve.run(Ver2.options(name="Ver").bind(), name="ft-roll")
    deadline = time.time() + 20
    while "v2" not in seen and time.time() < deadline and not errors:
        time.sleep(0.1)
    stop.set()
    [t.join(timeout=30) for t in threads]
    assert not errors, errors[:3]
    assert "v2" in seen  # new version serving; old drained without failures


def test_handle_sheds_beyond_queue_limit(rt):
    @serve.deployment(num_replicas=1, max_ongoing_requests=1,
                      max_queued_requests=1)
    class Slow:
        def __call__(self, x):
            time.sleep(0.5)
            return x

    h = serve.run(Slow.bind(), name="ft-shed")
    admitted = [h.remote(0), h.remote(1)]  # capacity 1 + queue 1
    with pytest.raises(BackPressureError) as ei:
        for i in range(4):  # depth accounting is monotone while Slow sleeps
            admitted.append(h.remote(2 + i))
    assert ei.value.retry_after_s > 0
    assert ei.value.queue_depth >= ei.value.limit == 2
    # admitted requests still complete — shedding protects them
    assert [r.result(timeout_s=30) for r in admitted[:2]] == [0, 1]
    shed = rs.get_metrics().get("serve_requests_shed_total", {}).get("values", {})
    assert any(dict(k).get("app") == "ft-shed" and v >= 1
               for k, v in shed.items())


def test_http_proxy_sheds_503_with_retry_after(rt):
    import urllib.error
    import urllib.request

    @serve.deployment(num_replicas=1, max_ongoing_requests=1,
                      max_queued_requests=0)
    class Slow:
        def __call__(self, x):
            time.sleep(0.6)
            return {"ok": True}

    serve.start(http_options={"port": 18431})
    serve.run(Slow.bind(), name="ft-http", route_prefix="/shed")
    statuses, retry_after = [], []

    def hit():
        try:
            resp = urllib.request.urlopen(
                "http://127.0.0.1:18431/shed?x=1", timeout=30)
            statuses.append(resp.status)
        except urllib.error.HTTPError as e:
            statuses.append(e.code)
            if e.code == 503:
                retry_after.append(e.headers.get("Retry-After"))

    threads = [threading.Thread(target=hit) for _ in range(5)]
    [t.start() for t in threads]
    [t.join(timeout=40) for t in threads]
    assert statuses.count(200) >= 1  # admitted work completed
    assert statuses.count(503) >= 1  # overload shed fast
    assert retry_after and all(int(ra) >= 1 for ra in retry_after)


def test_unhealthy_replica_replaced_and_view_converges(rt):
    """Satellite: failed health check -> kill -> reconcile replaces the
    replica and the long-poll view converges (injection-driven, no real
    crash)."""
    @serve.deployment(num_replicas=1, health_check_period_s=0.3)
    class Healthy:
        def __call__(self, x):
            import os

            return os.getpid()

    h = serve.run(Healthy.bind(), name="ft-heal")
    pid0 = h.remote(None).result()
    old_ids = {r._actor_id for r in h._replicas}
    # the replica now fails every health check; its REPLACEMENT starts clean
    # (arming is per-process state, not config)
    assert ChaosController().arm_replica("ft-heal", "Healthy",
                                         "serve.replica.health") == 1
    wait_for_condition(
        lambda: h.remote(None).result(timeout_s=30) != pid0,
        timeout=30, message="unhealthy replica never replaced")
    # long-poll view converged on the replacement
    from ray_tpu.serve.handle import _lp_registry

    entry = _lp_registry.get(("ft-heal", "Healthy"))
    assert entry is not None and entry.replicas is not None
    assert len(entry.replicas) == 1
    assert {r._actor_id for r in entry.replicas} != old_ids


def test_router_prunes_departed_replicas(rt):
    """Satellite: inflight/model_map/suspect state for replicas that left the
    long-poll view is pruned (no slow leak, no stale p2c counts)."""
    h = serve.run(Echo.options(num_replicas=2).bind(), name="ft-prune")
    pids = set()
    deadline = time.time() + 20
    while len(pids) < 2 and time.time() < deadline:
        pids |= {h.remote(i).result()[0] for i in range(10)}
    assert len(pids) == 2
    router = h._router
    assert len(router.inflight) == 2
    router.model_map["m"] = set(router.inflight)  # simulated affinity state
    serve.run(Echo.options(num_replicas=1).bind(), name="ft-prune")
    wait_for_condition(
        lambda: (h.remote(0).result() is not None
                 and len(router.inflight) == 1),
        timeout=30, message="router state never pruned after scale-down")
    live = {r._actor_id for r in h._replicas}
    assert set(router.inflight) <= live
    assert all(ids <= live for ids in router.model_map.values())


def test_single_shared_completion_waiter(rt):
    """Satellite: one waiter thread per handle batches completion waits (was:
    one daemon thread per request)."""
    @serve.deployment(max_ongoing_requests=8)
    class Pause:
        def __call__(self, x):
            time.sleep(0.3)
            return x

    def nthreads():
        return len(threading.enumerate())

    h = serve.run(Pause.bind(), name="ft-waiter")
    h.remote(0).result()
    before = nthreads()
    resps = [h.remote(i) for i in range(8)]
    assert h._waiter.outstanding() >= 1
    # 8 concurrent in-flight requests share ONE waiter thread for this handle
    # (the old design spawned one daemon thread per request)
    assert nthreads() <= before + 1
    assert sum(t.name == "serve-done-waiter" for t in threading.enumerate()
               if t is h._waiter._thread) == 1
    assert sorted(r.result(timeout_s=30) for r in resps) == list(range(8))
    wait_for_condition(lambda: h._waiter.outstanding() == 0, timeout=10,
                       message="waiter never drained")


@pytest.mark.slow
def test_open_loop_chaos_kill_zero_lost(rt):
    """Load-generating chaos (slow: tier-1 runs the deterministic fail-point
    variants above): open-loop HTTP load, SIGKILL a replica mid-stream — the
    retry plane + reconcile loop must lose ZERO requests and recover p99."""
    import os
    import sys

    sys.path.insert(0, os.path.dirname(os.path.dirname(__file__)))
    import bench_serve

    serve.start(http_options={"port": 18445})
    out = bench_serve.run_chaos_kill(
        18445, replicas=3, moq=2, service_s=0.05, rps=35.0,
        warm_s=2.5, post_kill_s=8.0, app="ft-chaos")
    assert out["kill_zero_lost"], out
    assert out["kill_p99_recovery_s"] is not None, out


def test_drain_deadline_kills_stuck_replica(rt):
    """A replica that cannot finish its in-flight work inside drain_timeout_s
    is killed anyway — draining bounds shutdown, never wedges it."""
    # retryable=False: the doomed request must surface promptly instead of
    # burning serve_replica_wait_s retrying against a deleted app
    @serve.deployment(num_replicas=1, drain_timeout_s=0.5, retryable=False)
    class Stuck:
        def __call__(self, x):
            time.sleep(20)
            return x

    h = serve.run(Stuck.bind(), name="ft-stuck")
    resp = h.remote(1)  # pins the replica's in-flight count at 1
    time.sleep(0.2)
    serve.delete("ft-stuck")
    controller = ray_tpu.get_actor("SERVE_CONTROLLER")
    wait_for_condition(
        lambda: ray_tpu.get(controller.get_deployment_info.remote(
            "ft-stuck", "Stuck")) is None,
        timeout=10, message="drain deadline never reaped the stuck replica")
    with pytest.raises(Exception):
        resp.result(timeout_s=30)  # its request died with it (deadline burned)
