"""Ray Client equivalent: remote driver over TCP (reference python/ray/util/client/)."""
import multiprocessing as mp

import pytest

import ray_tpu


def _remote_driver(port, q):
    """A separate process acting as a remote client driver."""
    import numpy as np

    import ray_tpu

    try:
        ray_tpu.init(address=f"ray-tpu://127.0.0.1:{port}")

        @ray_tpu.remote
        def add(a, b):
            return a + b

        @ray_tpu.remote
        class Counter:
            def __init__(self):
                self.n = 0

            def incr(self, k=1):
                self.n += k
                return self.n

        # tasks
        assert ray_tpu.get(add.remote(2, 3)) == 5
        # large array round-trips through put/get
        arr = np.arange(50_000, dtype=np.float64)
        ref = ray_tpu.put(arr)
        out = ray_tpu.get(ref)
        assert np.array_equal(out, arr)
        # actors (exercises the GC-safe decref/kill fire-and-forget path too)
        c = Counter.remote()
        assert ray_tpu.get(c.incr.remote()) == 1
        assert ray_tpu.get(c.incr.remote(5)) == 6
        # wait
        refs = [add.remote(i, i) for i in range(4)]
        ready, pending = ray_tpu.wait(refs, num_returns=4, timeout=30)
        assert len(ready) == 4 and not pending
        assert sorted(ray_tpu.get(ready)) == [0, 2, 4, 6]
        q.put(("ok", None))
    except BaseException:  # noqa: BLE001
        import traceback

        q.put(("err", traceback.format_exc()))


def _remote_probe(port, q):
    import ray_tpu

    try:
        ray_tpu.init(address=f"ray-tpu://127.0.0.1:{port}")
        h = ray_tpu.get_actor("shared-counter")
        q.put(("ok", ray_tpu.get(h.incr.remote())))
    except BaseException:  # noqa: BLE001
        import traceback

        q.put(("err", traceback.format_exc()))


@pytest.fixture()
def client_cluster():
    from ray_tpu.util.client import server as client_server

    ray_tpu.shutdown()
    ray_tpu.init(num_cpus=4, client_server_port=0,  # ephemeral port
                 worker_env={"JAX_PLATFORMS": "cpu"})
    yield client_server._server.port
    ray_tpu.shutdown()
    ray_tpu.init(num_cpus=4, worker_env={"JAX_PLATFORMS": "cpu"},
                 max_workers_per_node=8)


def test_remote_driver_full_api(rt, client_cluster):
    port = client_cluster
    ctx = mp.get_context("spawn")
    q = ctx.Queue()
    p = ctx.Process(target=_remote_driver, args=(port, q))
    p.start()
    status, err = q.get(timeout=120)
    p.join(timeout=30)
    assert status == "ok", err


def test_client_sees_named_actors_from_head(rt, client_cluster):
    port = client_cluster

    @ray_tpu.remote(name="shared-counter")
    class Counter:
        def __init__(self):
            self.n = 0

        def incr(self):
            self.n += 1
            return self.n

    c = Counter.remote()
    assert ray_tpu.get(c.incr.remote()) == 1

    ctx = mp.get_context("spawn")
    q = ctx.Queue()
    p = ctx.Process(target=_remote_probe, args=(port, q))
    p.start()
    status, val = q.get(timeout=120)
    p.join(timeout=30)
    assert status == "ok", val
    assert val == 2


def _state_probe(port, q):
    import ray_tpu

    try:
        ray_tpu.init(address=f"ray-tpu://127.0.0.1:{port}")
        from ray_tpu.util import state as rs

        q.put(("ok", (rs.summarize_cluster(), len(rs.list_nodes()))))
    except BaseException:  # noqa: BLE001
        import traceback

        q.put(("err", traceback.format_exc()))


def test_state_api_from_remote_client(rt, client_cluster):
    port = client_cluster
    ctx = mp.get_context("spawn")
    q = ctx.Queue()
    p = ctx.Process(target=_state_probe, args=(port, q))
    p.start()
    status, val = q.get(timeout=120)
    p.join(timeout=30)
    assert status == "ok", val
    summary, n_nodes = val
    assert n_nodes >= 1
    assert summary["nodes"] >= 1


def test_cli_list_requires_cluster_or_address():
    import subprocess
    import sys

    # fresh process: no cluster, no address -> exit 1 with guidance
    proc = subprocess.run(
        [sys.executable, "-c",
         "from ray_tpu.scripts import cli; import sys; sys.exit(cli.main(['list', 'nodes']))"],
        capture_output=True, text=True, timeout=60)
    assert proc.returncode == 1
    assert "no cluster" in proc.stdout


def test_default_authkey_refused_on_public_host():
    """Binding a routable interface with the well-known key is driver-level RCE
    for the whole network: the server must refuse (random keys only)."""
    from ray_tpu.util.client.server import DEFAULT_AUTHKEY, ClientServer

    with pytest.raises(ValueError, match="default"):
        ClientServer("0.0.0.0", 0, authkey=DEFAULT_AUTHKEY)


def test_random_authkey_persisted_and_loaded(tmp_path, monkeypatch):
    monkeypatch.setenv("RAY_TPU_SESSION_DIR", str(tmp_path))
    monkeypatch.delenv("RAY_TPU_CLIENT_AUTHKEY", raising=False)
    from ray_tpu.util.client.server import ClientServer, load_authkey

    srv = ClientServer("127.0.0.1", 0)  # no key passed -> generated
    try:
        assert srv.authkey and srv.authkey != b"ray-tpu-client"
        assert load_authkey() == srv.authkey
    finally:
        srv.close()


def _renv_client_driver(port, q):
    try:
        import os

        import ray_tpu

        ray_tpu.init(address=f"ray-tpu://127.0.0.1:{port}",
                     runtime_env={"env_vars": {"CLIENT_JOB_DEFAULT": "set"}})

        @ray_tpu.remote
        def probe():
            import os as _os

            return _os.environ.get("CLIENT_JOB_DEFAULT")

        q.put(("ok", ray_tpu.get(probe.remote())))
    except BaseException:  # noqa: BLE001
        import traceback

        q.put(("err", traceback.format_exc()))


def test_client_driver_job_runtime_env(rt, client_cluster):
    """init(address=..., runtime_env=...) rides every spec the client builds:
    job-scoped default env vars reach head-side workers (reference
    ray.init('ray://...', runtime_env=...))."""
    port = client_cluster
    ctx = mp.get_context("spawn")
    q = ctx.Queue()
    p = ctx.Process(target=_renv_client_driver, args=(port, q))
    p.start()
    status, val = q.get(timeout=120)
    p.join(timeout=30)
    assert status == "ok", val
    assert val == "set"
