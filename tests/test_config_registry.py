"""Central flag registry (ray_tpu/config.py; reference ray_config_def.h)."""
import subprocess
import sys

from ray_tpu.config import CONFIG


def test_defaults_and_env_override(monkeypatch):
    monkeypatch.delenv("RAY_TPU_MAX_WORKERS_PER_NODE", raising=False)
    assert CONFIG.max_workers_per_node == 16
    monkeypatch.setenv("RAY_TPU_MAX_WORKERS_PER_NODE", "4")
    assert CONFIG.max_workers_per_node == 4
    monkeypatch.setenv("RAY_TPU_TRACING", "true")
    assert CONFIG.tracing is True
    monkeypatch.setenv("RAY_TPU_SPILL_THRESHOLD", "0.5")
    assert CONFIG.spill_threshold == 0.5


def test_entries_report_source(monkeypatch):
    monkeypatch.setenv("RAY_TPU_SPILL_TARGET", "0.25")
    rows = {r["name"]: r for r in CONFIG.entries()}
    assert rows["spill_target"]["source"] == "env"
    assert rows["spill_target"]["value"] == 0.25
    assert rows["spill_threshold"]["source"] == "default"
    assert all(r["doc"] for r in rows.values())


def test_unknown_flag_raises():
    import pytest

    with pytest.raises(AttributeError):
        CONFIG.not_a_flag


def test_cli_list_config():
    out = subprocess.run(
        [sys.executable, "-m", "ray_tpu.scripts.cli", "list", "config"],
        capture_output=True, text=True, timeout=120)
    assert out.returncode == 0
    assert "RAY_TPU_MAX_WORKERS_PER_NODE" in out.stdout
    assert "RAY_TPU_OBJECT_STORE_BYTES" in out.stdout
    assert "[default" in out.stdout


def test_task_actor_default_flags(monkeypatch):
    """task_max_retries / actor_max_restarts registry flags feed the @remote
    defaults at decoration time; explicit options still win."""
    from ray_tpu.core.actor import ActorClass
    from ray_tpu.core.task import RemoteFunction

    def f():
        return 1

    class A:
        pass

    assert RemoteFunction(f)._options["max_retries"] == 3
    monkeypatch.setenv("RAY_TPU_TASK_MAX_RETRIES", "7")
    assert RemoteFunction(f)._options["max_retries"] == 7
    assert RemoteFunction(f, max_retries=0)._options["max_retries"] == 0

    assert ActorClass(A)._options["max_restarts"] == 0
    monkeypatch.setenv("RAY_TPU_ACTOR_MAX_RESTARTS", "2")
    assert ActorClass(A)._options["max_restarts"] == 2
    assert ActorClass(A, max_restarts=-1)._options["max_restarts"] == -1
