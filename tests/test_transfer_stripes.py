"""Striped zero-copy data-plane transfers: bit-exactness, pin protection,
admission accounting, and the mid-stripe source-death chaos path.

Reference: pull_manager.h:49 (admission), push_manager.h:27 (chunked push),
ISSUE 4 acceptance criteria (striped pulls bit-exact with single-stream; a
concurrent spill/free during a pull never serves torn bytes; a dead source
raises within the stall bound with no admission-budget leak).
"""
import os
import subprocess
import sys
import threading
import time

import pytest

from ray_tpu.core import object_store
from ray_tpu.core.data_plane import (Admission, DataClient, DataServer,
                                     PinnedRead, plan_stripes, stripe_ranges)
from ray_tpu.core.ids import ObjectID

KEY = b"stripe-test-key"


@pytest.fixture()
def small_chunks(monkeypatch):
    """Small chunk/stripe knobs so every size class exercises multi-frame,
    multi-stripe paths without MB-scale payloads."""
    monkeypatch.setenv("RAY_TPU_TRANSFER_CHUNK_BYTES", "8192")
    monkeypatch.setenv("RAY_TPU_TRANSFER_STRIPE_THRESHOLD_BYTES", "65536")
    monkeypatch.setenv("RAY_TPU_TRANSFER_STRIPES", "4")
    monkeypatch.setenv("RAY_TPU_TRANSFER_STRIPE_MIN_BYTES", "8192")
    # these tests exercise the WIRE path; the same-host map shortcut would
    # short-circuit pull_to_store in a single-process test
    monkeypatch.setenv("RAY_TPU_TRANSFER_SAME_HOST_MAP", "0")


@pytest.fixture()
def plane():
    server = DataServer(KEY, object_store.read_pinned_any, host="127.0.0.1")
    client = DataClient(KEY)
    yield ("127.0.0.1", server.port), client
    client.close()
    server.close()


def _stored(payload: bytes):
    oid = ObjectID.generate()
    loc = object_store.write_raw(payload, oid)
    return loc


# -- stripe planning -------------------------------------------------------------------
def test_stripe_plan_min_bytes_caps_width(monkeypatch):
    monkeypatch.setenv("RAY_TPU_TRANSFER_STRIPE_THRESHOLD_BYTES", "65536")
    monkeypatch.setenv("RAY_TPU_TRANSFER_STRIPES", "16")
    monkeypatch.setenv("RAY_TPU_TRANSFER_STRIPE_MIN_BYTES", "8192")
    # 65536 / 8192 = 8 streams max even though 16 are allowed
    assert plan_stripes(65536) == 8
    monkeypatch.setenv("RAY_TPU_TRANSFER_STRIPE_THRESHOLD_BYTES", "0")
    assert plan_stripes(1 << 30) == 1  # 0 disables striping


def test_stripe_plan(small_chunks):
    assert plan_stripes(None) == 1
    assert plan_stripes(100) == 1           # below threshold
    assert plan_stripes(65535) == 1
    assert plan_stripes(65536) == 4
    for total in (1, 8191, 8192, 65536, 65537, 1_000_001):
        for n in (1, 2, 3, 4, 7):
            ranges = stripe_ranges(total, n)
            assert ranges[0][0] == 0
            # contiguous, disjoint, covering exactly [0, total)
            for (o1, l1), (o2, _) in zip(ranges, ranges[1:]):
                assert o1 + l1 == o2
            assert sum(ln for _, ln in ranges) == total


# -- bit-exactness: striped == single-stream, odd sizes --------------------------------
def test_striped_bit_exact_odd_sizes(small_chunks, plane):
    """Acceptance: the striped path is bit-exact with the single-stream path
    for 0 B, 1 B, chunk±1, and non-stripe-aligned sizes."""
    addr, client = plane
    chunk = 8192
    locs = []
    try:
        for n in (0, 1, chunk - 1, chunk + 1, 65536, 65537, 200_001):
            payload = os.urandom(n)
            loc = _stored(payload)
            locs.append(loc)
            size, _ = object_store.loc_meta(loc)
            assert size == n
            single, e1 = client.pull(addr, loc)                  # no hint: 1 stream
            striped, e2 = client.pull(addr, loc, size_hint=size)
            assert single == payload == striped, f"size {n} mismatch"
            assert e1 is False and e2 is False
    finally:
        for loc in locs:
            object_store.free_local(loc)


def test_pull_into_sink_lands_in_place(small_chunks, plane):
    """pull(into=...) recv's chunk frames straight into the caller's buffer and
    returns no bytes object; striped and single-stream sinks agree."""
    addr, client = plane
    payload = os.urandom(150_000)
    loc = _stored(payload)
    try:
        for hint in (None, len(payload)):
            buf = bytearray(len(payload))
            calls = []

            def sink(total, is_error, buf=buf, calls=calls):
                calls.append((total, is_error))
                return memoryview(buf)

            data, is_error = client.pull(addr, loc, into=sink, size_hint=hint)
            assert data is None and is_error is False
            assert bytes(buf) == payload
            assert calls == [(len(payload), False)]  # sink allocated exactly once
    finally:
        object_store.free_local(loc)


def test_pull_to_store_zero_copy_roundtrip(small_chunks, plane):
    """Destination-side create/seal: the pulled object lands in its final
    backing and admission returns to full."""
    addr, client = plane
    payload = os.urandom(300_000)
    src = _stored(payload)
    try:
        dst = object_store.pull_to_store(client, addr, src, ObjectID.generate())
        try:
            assert dst[0] in ("arena", "shm")
            assert object_store.read_raw(dst) == (payload, False)
        finally:
            object_store.free_local(dst)
        assert client._admission.snapshot() == (
            client._admission.max_bytes, client._admission.max_pulls)
    finally:
        object_store.free_local(src)


def test_pull_to_store_error_flag(small_chunks, plane):
    """is_error survives the zero-copy path (sealed into the new location)."""
    addr, client = plane
    payload = os.urandom(100_000)
    oid = ObjectID.generate()
    src_loc = object_store.write_raw(payload, oid, is_error=True)
    try:
        dst = object_store.pull_to_store(client, addr, src_loc,
                                         ObjectID.generate())
        try:
            got, is_error = object_store.read_raw(dst)
            assert got == payload and is_error is True
        finally:
            object_store.free_local(dst)
    finally:
        object_store.free_local(src_loc)


def test_pull_to_store_same_host_map(plane):
    """With the shortcut enabled (default) a source location readable in this
    process is adopted outright — zero bytes move, the mapping is shared."""
    addr, client = plane
    payload = os.urandom(300_000)
    src = _stored(payload)
    try:
        assert object_store.try_map_local(src)
        dst = object_store.pull_to_store(client, addr, src, ObjectID.generate())
        assert dst == src  # shared mapping, not a copy
        assert object_store.read_raw(dst) == (payload, False)
        # a location naming storage this process can NOT open falls back to
        # the wire (here: a bogus segment name)
        assert not object_store.try_map_local(("shm", "rt_no_such_seg", 10, False))
    finally:
        object_store.free_local(src)


# -- pin protection (acceptance: no torn bytes under concurrent spill/free) ------------
def test_pinned_read_survives_free_shm(tmp_path):
    payload = os.urandom(200_000)
    loc = _stored(payload)
    assert loc[0] in ("shm", "arena")
    pr = object_store.read_pinned(loc)
    object_store.free_local(loc)  # free while the view is in flight
    assert bytes(pr.view) == payload
    pr.release()


def test_pinned_read_survives_spill(tmp_path):
    payload = os.urandom(200_000)
    loc = _stored(payload)
    pr = object_store.read_pinned(loc, 1000, 100_000)
    new_loc = object_store.spill_location(loc, str(tmp_path))
    assert new_loc is not None and new_loc[0] == "disk"
    assert bytes(pr.view) == payload[1000:101_000]
    pr.release()
    # the spilled copy serves pinned reads too (mmap'd)
    with object_store.read_pinned_any(("slice", new_loc, 5, 50)) as pr2:
        assert bytes(pr2.view) == payload[5:55]
    object_store.free_local(new_loc)


def test_pull_not_torn_by_concurrent_free(small_chunks):
    """End-to-end regression: the server pins BEFORE streaming, so a free that
    lands mid-transfer (deterministically forced between the pins and the first
    frame) cannot tear the bytes the puller receives. Every stripe of the
    striped pull pins independently; the free fires once all are in flight —
    a stripe that started AFTER the free would correctly get ObjectLost
    instead, which is loss, not tearing."""
    payload = os.urandom(150_000)
    loc = _stored(payload)
    nstripes = plan_stripes(len(payload))
    assert nstripes > 1  # the small_chunks knobs make this a striped pull
    pin_count = [0]
    pin_lock = threading.Lock()
    pinned = threading.Event()
    freed = threading.Event()

    def pin_then_wait(l):
        pr = object_store.read_pinned_any(l)
        with pin_lock:
            pin_count[0] += 1
            if pin_count[0] == nstripes:
                pinned.set()
        assert freed.wait(10), "freer never ran"
        return pr

    server = DataServer(KEY, pin_then_wait, host="127.0.0.1")
    client = DataClient(KEY)

    def freer():
        assert pinned.wait(10)
        object_store.free_local(loc)
        freed.set()

    t = threading.Thread(target=freer)
    t.start()
    try:
        got, is_error = client.pull(("127.0.0.1", server.port), loc,
                                    size_hint=len(payload))
        assert got == payload and not is_error
    finally:
        t.join(timeout=10)
        client.close()
        server.close()


def test_spill_invalidates_adopted_replicas(tmp_path):
    """A same-host-map adoption shares the SOURCE's mapping, so a later spill
    of the source object would leave the adopted replica pointing at a deleted
    arena entry / unlinked segment: spill_lru must fire on_spill and the
    cluster handler must drop exactly the adopted (loc-identical) replicas,
    leaving physical copies alone."""
    from types import SimpleNamespace

    from ray_tpu.core.node import Cluster

    store = object_store.ObjectStore()
    oid = ObjectID.generate()
    payload = os.urandom(200_000)
    loc = object_store.write_raw(payload, oid)
    assert loc[0] in ("arena", "shm")
    store.add(oid, loc)
    physical = ("shm", "rt_physical_copy", 5, False)
    fake = SimpleNamespace(_transfer_lock=threading.Lock(),
                           _replicas={(oid, "a1"): loc, (oid, "a2"): physical})
    store.on_spill = lambda o, old: Cluster._on_object_spilled(fake, o, old)
    assert store.spill_lru(1, str(tmp_path)) >= len(payload)
    assert (oid, "a1") not in fake._replicas      # adopted replica dropped
    assert fake._replicas[(oid, "a2")] == physical  # physical copy untouched
    new_loc = store.location(oid, timeout=1)
    assert new_loc[0] == "disk"
    assert object_store.read_raw(new_loc) == (payload, False)
    object_store.free_local(new_loc)


# -- admission -------------------------------------------------------------------------
def test_admission_prompt_wakeup_on_release():
    """Satellite: a released budget admits the FIFO head immediately (precise
    notify), not on the next coarse poll tick."""
    adm = Admission(max_bytes=1000, max_pulls=2)
    got = adm.acquire(1000)  # pin the whole byte budget
    admitted_at = []

    def waiter():
        n = adm.acquire(500)
        admitted_at.append(time.monotonic())
        adm.release(n)

    t = threading.Thread(target=waiter)
    t.start()
    time.sleep(0.2)
    assert not admitted_at  # genuinely blocked on budget
    t0 = time.monotonic()
    adm.release(got)
    t.join(timeout=5)
    assert admitted_at, "waiter never admitted"
    # far below Admission._GUARD_TIMEOUT_S: the wakeup was the notify, not a poll
    assert admitted_at[0] - t0 < 0.5
    assert adm.snapshot() == (1000, 2)


def test_striped_pull_single_admission(small_chunks):
    """All stripes of one pull consume ONE pull slot + total bytes, observed
    while the transfer is in flight."""
    payload = os.urandom(150_000)
    loc = _stored(payload)
    observed = []
    gate = threading.Event()

    def slow_read(l):
        pr = object_store.read_pinned_any(l)
        gate.wait(5)  # hold all stripes mid-pull so the observer can sample
        return pr

    server = DataServer(KEY, slow_read, host="127.0.0.1")
    client = DataClient(KEY)

    def observer():
        time.sleep(0.5)  # stripes are now all inside slow_read
        observed.append(client._admission.snapshot())
        gate.set()

    t = threading.Thread(target=observer)
    t.start()
    try:
        got, _ = client.pull(("127.0.0.1", server.port), loc,
                             size_hint=len(payload))
        assert got == payload
        t.join(timeout=10)
        bytes_avail, pulls_avail = observed[0]
        assert pulls_avail == client._admission.max_pulls - 1  # ONE slot
        assert bytes_avail == client._admission.max_bytes - len(payload)
        assert client._admission.snapshot() == (
            client._admission.max_bytes, client._admission.max_pulls)
    finally:
        client.close()
        server.close()
        object_store.free_local(loc)


# -- chaos: source death mid-stripe ----------------------------------------------------
_CHAOS_SERVER = r"""
import os, signal, sys, time
sys.path.insert(0, {repo!r})
os.environ["RAY_TPU_TRANSFER_CHUNK_BYTES"] = "8192"
from ray_tpu.core.data_plane import DataServer

payload = bytes(range(256)) * 4096  # 1 MiB, deterministic
calls = {{"n": 0}}

def read_fn(loc):
    calls["n"] += 1
    if calls["n"] >= 2:  # second stripe request: die mid-pull
        os.kill(os.getpid(), signal.SIGKILL)
    if loc and loc[0] == "slice":
        _, _, off, ln = loc
        return payload[off:off + ln], False
    return payload, False

server = DataServer({key!r}, read_fn, host="127.0.0.1")
print(server.port, flush=True)
time.sleep(120)
"""


@pytest.mark.slow
def test_chaos_source_death_mid_stripe(monkeypatch):
    """Kill the source mid-stripe: the puller raises one of the errors the
    node-level relay/reconstruction fallback catches (PR 3 failure model),
    within the stall bound, and the admission budget returns to full."""
    monkeypatch.setenv("RAY_TPU_TRANSFER_CHUNK_BYTES", "8192")
    monkeypatch.setenv("RAY_TPU_TRANSFER_STRIPE_THRESHOLD_BYTES", "65536")
    monkeypatch.setenv("RAY_TPU_TRANSFER_STRIPES", "4")
    monkeypatch.setenv("RAY_TPU_TRANSFER_STRIPE_MIN_BYTES", "8192")
    monkeypatch.setenv("RAY_TPU_TRANSFER_STALL_TIMEOUT_S", "5")
    monkeypatch.setenv("RAY_TPU_TRANSFER_TIMEOUT_S", "15")
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    proc = subprocess.Popen(
        [sys.executable, "-c", _CHAOS_SERVER.format(repo=repo, key=KEY)],
        stdout=subprocess.PIPE, text=True,
        env={**os.environ, "JAX_PLATFORMS": "cpu"})
    client = DataClient(KEY)
    try:
        port = int(proc.stdout.readline())
        total = 1 << 20
        t0 = time.monotonic()
        with pytest.raises((OSError, EOFError, TimeoutError)):
            client.pull(("127.0.0.1", port), "obj", size_hint=total)
        elapsed = time.monotonic() - t0
        # bounded by the stall/transfer deadline, not hung on dead sockets
        assert elapsed < 30, f"death not detected within bound ({elapsed:.1f}s)"
        # no admission leak: the single striped grant was released
        assert client._admission.snapshot() == (
            client._admission.max_bytes, client._admission.max_pulls)
        # a RETRIED pull against the (still dead) source keeps raising the
        # fallback-trigger error types promptly — the caller's PR 3 path
        # (relay fallback / lineage reconstruction) stays reachable
        t0 = time.monotonic()
        with pytest.raises((OSError, EOFError, TimeoutError)):
            client.pull(("127.0.0.1", port), "obj", size_hint=total)
        assert time.monotonic() - t0 < 30
    finally:
        client.close()
        proc.kill()
        proc.wait(timeout=10)


# -- satellite: to_bytes preallocation -------------------------------------------------
def test_to_bytes_matches_write_into():
    """SerializedObject.to_bytes (preallocated) is bit-identical to write_into
    and round-trips zero-copy deserialization."""
    import numpy as np

    from ray_tpu.core import serialization

    obj = {"arr": np.arange(50_000, dtype=np.float64), "s": "x" * 10}
    ser = serialization.serialize(obj)
    frame = ser.to_bytes()
    assert len(frame) == ser.frame_bytes
    buf = bytearray(ser.frame_bytes)
    ser.write_into(memoryview(buf))
    assert frame == bytes(buf)
    back = serialization.loads(frame)
    assert back["s"] == obj["s"]
    np.testing.assert_array_equal(back["arr"], obj["arr"])
