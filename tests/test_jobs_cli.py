"""Job submission + CLI + runtime_env tests (reference:
python/ray/dashboard/modules/job tests, runtime_env tests; SURVEY.md §2.10)."""
import os
import sys
import time

import pytest

from ray_tpu.job import JobStatus, JobSubmissionClient


@pytest.fixture()
def client(tmp_path):
    return JobSubmissionClient(session_dir=str(tmp_path))


def test_job_submit_succeeds(client, tmp_path):
    job_id = client.submit_job(
        entrypoint=f"{sys.executable} -c \"print('hello from job')\"")
    assert client.wait_job(job_id, timeout=30) == JobStatus.SUCCEEDED
    assert "hello from job" in client.get_job_logs(job_id)
    info = client.get_job_info(job_id)
    assert info.return_code == 0 and info.end_time is not None


def test_job_failure_reported(client):
    job_id = client.submit_job(entrypoint=f"{sys.executable} -c 'raise SystemExit(3)'")
    assert client.wait_job(job_id, timeout=30) == JobStatus.FAILED
    assert client.get_job_info(job_id).return_code == 3


def test_job_runtime_env_vars(client):
    job_id = client.submit_job(
        entrypoint=f"{sys.executable} -c \"import os; print(os.environ['MY_FLAG'])\"",
        runtime_env={"env_vars": {"MY_FLAG": "flag-value-42"}})
    client.wait_job(job_id, timeout=30)
    assert "flag-value-42" in client.get_job_logs(job_id)


def test_job_stop(client):
    job_id = client.submit_job(
        entrypoint=f"{sys.executable} -c 'import time; time.sleep(60)'")
    deadline = time.time() + 10
    while time.time() < deadline and client.get_job_status(job_id) != JobStatus.RUNNING:
        time.sleep(0.05)
    assert client.stop_job(job_id)
    assert client.get_job_status(job_id) == JobStatus.STOPPED


def test_job_list(client):
    a = client.submit_job(entrypoint=f"{sys.executable} -c 'print(1)'")
    b = client.submit_job(entrypoint=f"{sys.executable} -c 'print(2)'")
    client.wait_job(a, timeout=30)
    client.wait_job(b, timeout=30)
    ids = {j.job_id for j in client.list_jobs()}
    assert {a, b} <= ids


def test_cli_job_flow(tmp_path, monkeypatch, capsys):
    from ray_tpu.scripts.cli import main

    monkeypatch.setenv("RAY_TPU_SESSION_DIR", str(tmp_path))
    rc = main(["job", "submit", f"{sys.executable} -c \"print('cli-job-ok')\""])
    out = capsys.readouterr().out
    assert rc == 0 and "cli-job-ok" in out
    rc = main(["job", "list"])
    out = capsys.readouterr().out
    assert rc == 0 and "SUCCEEDED" in out
    rc = main(["status"])
    capsys.readouterr()
    assert rc == 1  # no head session yet
    rc = main(["start", "--num-cpus", "2"])
    capsys.readouterr()
    assert rc == 0
    rc = main(["status"])
    out = capsys.readouterr().out
    assert rc == 0 and "num_cpus" in out
    rc = main(["stop"])
    assert rc == 0


def test_runtime_env_validation():
    import ray_tpu

    # pip is a supported plugin now (per-env --target overlays); conda is not
    assert ray_tpu.RuntimeEnv(pip=["requests"])["pip"] == {"packages": ["requests"]}
    with pytest.raises(ValueError, match="package-manager or image"):
        ray_tpu.RuntimeEnv(conda={"dependencies": []})
    with pytest.raises(ValueError, match="unknown"):
        ray_tpu.RuntimeEnv(bogus_field=1)
    env = ray_tpu.RuntimeEnv(env_vars={"A": "1"}, working_dir="/tmp")
    assert env["env_vars"] == {"A": "1"}


def test_task_runtime_env_vars(rt):
    @rt.remote(runtime_env={"env_vars": {"TASK_RENV": "task-env-val"}})
    def read_env():
        return os.environ.get("TASK_RENV"), os.environ.get("PRESERVED", "absent")

    val, _ = rt.get(read_env.remote())
    assert val == "task-env-val"

    # the env var must not leak into tasks without the runtime_env
    @rt.remote
    def read_plain():
        return os.environ.get("TASK_RENV")

    # may land on the same worker; applied() must have restored the env
    assert rt.get(read_plain.remote()) is None


def test_actor_runtime_env_persists(rt):
    @rt.remote(runtime_env={"env_vars": {"ACTOR_RENV": "actor-env-val"}})
    class A:
        def read(self):
            return os.environ.get("ACTOR_RENV")

    a = A.remote()
    assert rt.get(a.read.remote()) == "actor-env-val"
    rt.kill(a)
