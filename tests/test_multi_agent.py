"""Multi-agent RL tests (reference rllib multi-agent stack on CartPole copies)."""
import numpy as np
import pytest

from ray_tpu.rllib import MultiAgentPPOConfig, make_multi_agent
from ray_tpu.rllib.env.multi_agent_env_runner import MultiAgentEnvRunner


@pytest.fixture(autouse=True)
def _cluster(rt):
    yield


def test_make_multi_agent_env_dict_api():
    env = make_multi_agent("CartPole-v1")({"num_agents": 3})
    obs, infos = env.reset(seed=0)
    assert set(obs) == {0, 1, 2}
    obs, rewards, terms, truncs, _ = env.step({i: 0 for i in range(3)})
    assert set(rewards) == {0, 1, 2}
    assert terms["__all__"] in (False, True)
    env.close()


def test_multi_agent_env_runner_groups_by_module(rt):
    cfg = (
        MultiAgentPPOConfig()
        .environment(make_multi_agent("CartPole-v1"), env_config={"num_agents": 2})
        .multi_agent(
            policies=["left", "right"],
            policy_mapping_fn=lambda aid: "left" if aid == 0 else "right",
        )
        .env_runners(rollout_fragment_length=40)
    )
    runner = MultiAgentEnvRunner(cfg, 0)
    out = runner.sample(80)
    assert set(out) == {"left", "right"}
    total = sum(len(e["rewards"]) for eps in out.values() for e in eps)
    assert total >= 80
    for eps in out.values():
        for e in eps:
            assert "action_logp" in e and "vf_preds" in e
    runner.stop()


def test_multi_agent_ppo_shared_policy_improves(rt):
    config = (
        MultiAgentPPOConfig()
        .environment(make_multi_agent("CartPole-v1"), env_config={"num_agents": 2})
        .multi_agent(policies=["shared"], policy_mapping_fn=lambda aid: "shared")
        .env_runners(num_env_runners=2, rollout_fragment_length=64)
        .training(lr=3e-4, train_batch_size=1024, minibatch_size=256, num_epochs=6,
                  entropy_coeff=0.01)
        .debugging(seed=0)
    )
    algo = config.build_algo()
    try:
        returns = []
        for _ in range(8):
            result = algo.train()
            returns.append(result.get("episode_return_mean") or 0.0)
        # 2 agents => random-policy return ~40 total; must clearly improve
        assert max(returns[2:]) > returns[0] + 20, returns
    finally:
        algo.cleanup()


def test_multi_agent_ppo_separate_policies_checkpoint(rt):
    config = (
        MultiAgentPPOConfig()
        .environment(make_multi_agent("CartPole-v1"), env_config={"num_agents": 2})
        .multi_agent(policies=["p0", "p1"], policy_mapping_fn=lambda aid: f"p{aid}")
        .env_runners(num_env_runners=1, rollout_fragment_length=32)
        .training(train_batch_size=256, minibatch_size=64, num_epochs=1)
        .debugging(seed=0)
    )
    algo = config.build_algo()
    try:
        result = algo.train()
        assert any(k.startswith("p0/") for k in result)
        assert any(k.startswith("p1/") for k in result)
        state = algo.save_checkpoint()
        w_before = algo.get_weights()
        algo.train()
        algo.load_checkpoint(state)
        w_after = algo.get_weights()
        np.testing.assert_allclose(w_before["p0"]["pi"][0]["w"], w_after["p0"]["pi"][0]["w"])
        # p0 and p1 trained independently -> different params
        assert not np.allclose(w_after["p0"]["pi"][0]["w"], w_after["p1"]["pi"][0]["w"])
    finally:
        algo.cleanup()
