"""Offline RL: OfflineData, BC/MARWIL, CQL (reference rllib/offline/ + algorithms)."""
import numpy as np
import pytest

import ray_tpu
from ray_tpu.rllib.offline import OfflineData, OfflinePreLearner, episodes_to_rows


@pytest.fixture(autouse=True)
def _cluster(rt):
    yield


def _expert_cartpole_rows(n_eps=40, seed=0):
    """Record a decent heuristic policy (push toward the pole's lean)."""
    import gymnasium as gym

    rng = np.random.default_rng(seed)
    env = gym.make("CartPole-v1")
    rows, eid = [], 0
    for _ in range(n_eps):
        obs, _ = env.reset(seed=int(rng.integers(1 << 30)))
        t = 0
        while True:
            action = int(obs[2] + 0.4 * obs[3] > 0)  # angle + angular velocity
            nxt, r, term, trunc, _ = env.step(action)
            rows.append({"obs": obs.tolist(), "actions": action, "rewards": float(r),
                         "next_obs": nxt.tolist(), "dones": bool(term), "eps_id": eid, "t": t})
            obs, t = nxt, t + 1
            if term or trunc or t >= 200:
                break
        eid += 1
    env.close()
    return rows


def test_prelearner_returns_to_go():
    rows = [
        {"obs": [0.0], "actions": 0, "rewards": 1.0, "next_obs": [1.0], "dones": False, "eps_id": 0, "t": 0},
        {"obs": [1.0], "actions": 1, "rewards": 2.0, "next_obs": [2.0], "dones": True, "eps_id": 0, "t": 1},
    ]
    batch = OfflinePreLearner(gamma=0.5)(rows)
    np.testing.assert_allclose(batch["returns_to_go"], [1.0 + 0.5 * 2.0, 2.0])


def test_episodes_to_rows_roundtrip():
    ep = {
        "obs": np.arange(6, dtype=np.float32).reshape(3, 2),
        "next_obs_last": np.array([9.0, 9.0], np.float32),
        "actions": np.array([0, 1, 0]),
        "rewards": np.ones(3, np.float32),
        "terminated": True,
        "truncated": False,
    }
    rows = episodes_to_rows([ep])
    assert len(rows) == 3
    assert rows[-1]["dones"] is True and rows[0]["dones"] is False
    np.testing.assert_allclose(rows[1]["next_obs"], ep["obs"][2])
    np.testing.assert_allclose(rows[2]["next_obs"], [9.0, 9.0])


def test_bc_learns_cartpole_from_offline_data(rt, tmp_path):
    import gymnasium as gym

    from ray_tpu import data as rtd
    from ray_tpu.rllib.algorithms.marwil import BCConfig

    rows = _expert_cartpole_rows()
    ds = rtd.from_items(rows)
    env = gym.make("CartPole-v1")
    config = (
        BCConfig()
        .environment(observation_space=env.observation_space, action_space=env.action_space)
        .offline_data(dataset=ds)
        .training(lr=1e-3, train_batch_size=512, num_updates_per_iteration=40)
        .debugging(seed=0)
    )
    algo = config.build_algo()
    try:
        for _ in range(4):
            result = algo.train()
        assert result["mean_logp"] > -0.35, result  # near-deterministic imitation
        # cloned policy actually holds the pole up
        module, params = algo._module, algo.get_weights()
        obs, _ = env.reset(seed=3)
        steps = 0
        for _ in range(300):
            out = module.apply_np(params, obs[None].astype(np.float32))
            action = int(np.argmax(out["action_dist_inputs"][0]))
            obs, _, term, trunc, _ = env.step(action)
            steps += 1
            if term or trunc:
                break
        assert steps > 100, steps
    finally:
        algo.cleanup()
        env.close()


def test_marwil_parquet_input(rt, tmp_path):
    import gymnasium as gym

    from ray_tpu import data as rtd
    from ray_tpu.rllib.algorithms.marwil import MARWILConfig

    rows = _expert_cartpole_rows(n_eps=10)
    rtd.from_items(rows).write_parquet(str(tmp_path / "offline"))
    env = gym.make("CartPole-v1")
    config = (
        MARWILConfig()
        .environment(observation_space=env.observation_space, action_space=env.action_space)
        .offline_data(input_=str(tmp_path / "offline"))
        .training(beta=1.0, train_batch_size=256, num_updates_per_iteration=5)
        .debugging(seed=0)
    )
    algo = config.build_algo()
    try:
        result = algo.train()
        assert np.isfinite(result["policy_loss"])
        assert np.isfinite(result["vf_loss"])
    finally:
        algo.cleanup()
        env.close()


def test_cql_offline_pendulum(rt):
    import gymnasium as gym

    from ray_tpu import data as rtd
    from ray_tpu.rllib.algorithms.cql import CQLConfig

    # random-policy pendulum transitions
    env = gym.make("Pendulum-v1")
    rng = np.random.default_rng(0)
    rows, eid = [], 0
    for _ in range(8):
        obs, _ = env.reset(seed=int(rng.integers(1 << 30)))
        for t in range(50):
            a = rng.uniform(-2, 2, size=(1,)).astype(np.float32)
            nxt, r, term, trunc, _ = env.step(a)
            rows.append({"obs": obs.tolist(), "actions": a.tolist(), "rewards": float(r),
                         "next_obs": nxt.tolist(), "dones": False, "eps_id": eid, "t": t})
            obs = nxt
        eid += 1
    config = (
        CQLConfig()
        .environment(observation_space=env.observation_space, action_space=env.action_space)
        .offline_data(dataset=rtd.from_items(rows))
        .training(train_batch_size=64, num_updates_per_iteration=6, bc_iters=3,
                  min_q_weight=1.0, num_cql_actions=2)
        .debugging(seed=0)
    )
    algo = config.build_algo()
    try:
        r1 = algo.train()  # covers bc_iters warm-start then Q-based actor
        assert np.isfinite(r1["critic_loss"]) and np.isfinite(r1["cql_loss"])
        state = algo.save_checkpoint()
        algo.load_checkpoint(state)
    finally:
        algo.cleanup()
        env.close()
