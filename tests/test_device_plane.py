"""Device-native tensor transfer plane (core/device_plane.py).

Reference parity: python/ray/experimental/gpu_object_manager/gpu_object_manager.py:54
(device-resident objects, transfer on demand) and experimental/channel/
torch_tensor_nccl_channel.py (device channels). These tests prove a jax.Array
crosses actor PROCESS boundaries with zero host-pickle of the payload: the plane's
own byte counters account for every payload byte, and the producer-side export is
observed armed + released.
"""
import numpy as np
import pytest

import jax
import jax.numpy as jnp


@pytest.fixture(autouse=True)
def plane_ok(rt):
    """Plane availability is probed AFTER cluster init so the lazily-started
    transfer endpoint shares the session authkey with the workers."""
    from ray_tpu.core.device_plane import plane

    if not plane().available:
        pytest.skip(f"device plane unavailable: {plane().disabled_reason}")


def test_export_fetch_roundtrip_sharded(rt):
    """A mesh-sharded array crosses to an actor process device-to-device, arriving
    with the producer's sharding rebuilt; payload bytes move only via the plane."""
    from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

    from ray_tpu.core.device_plane import plane

    mesh = Mesh(np.asarray(jax.devices()).reshape(8), ("x",))
    x = jax.device_put(jnp.arange(4096.0).reshape(8, 512), NamedSharding(mesh, P("x")))
    before = plane().stats()
    handle = plane().export({"kv": x})

    @rt.remote
    def consume(h):
        import numpy as _np

        from ray_tpu.core.device_plane import plane as _plane

        tree = _plane().fetch(h)
        arr = tree["kv"]
        st = _plane().stats()
        return {
            "sum": float(_np.asarray(arr).sum()),
            "spec": str(arr.sharding.spec),
            "pulls": st["pulls"],
            "bytes_pulled": st["bytes_pulled"],
        }

    out = rt.get(consume.remote(handle))
    assert out["sum"] == float(np.arange(4096.0).sum())
    assert out["spec"] == "PartitionSpec('x',)"
    assert out["pulls"] == 1
    # every payload byte is accounted for by the plane, none by pickle
    assert out["bytes_pulled"] == x.nbytes
    after = plane().stats()
    assert after["arms"] == before["arms"] + 1
    plane().release(handle.key)


def test_fetch_release_drops_producer_export(rt):
    from ray_tpu.core.device_plane import plane

    h = plane().export(jnp.ones((1024,)))
    assert plane().stats()["exports_live"] >= 1

    @rt.remote
    def pull_and_ack(h):
        from ray_tpu.core.device_plane import plane as _plane

        arr = _plane().fetch(h, release=True)
        return float(np.asarray(arr).sum())

    assert rt.get(pull_and_ack.remote(h)) == 1024.0
    # the consumer's ack released the export (poll briefly: ack is best-effort async)
    import time

    deadline = time.monotonic() + 5
    while time.monotonic() < deadline:
        if not any(k == h.key for k in plane()._exports):
            break
        time.sleep(0.05)
    assert h.key not in plane()._exports


def test_fetch_after_release_raises_and_falls_back(rt):
    from ray_tpu.core.device_plane import DevicePlaneError, plane

    h = plane().export(jnp.ones((2048,)))
    plane().release(h.key)

    @rt.remote
    def try_fetch(h):
        from ray_tpu.core.device_plane import DevicePlaneError as E, plane as _plane

        try:
            _plane().fetch(h)
            return "fetched"
        except E:
            return "error"

    assert rt.get(try_fetch.remote(h)) == "error"


def test_object_store_get_uses_device_plane(rt):
    """ray_tpu.put(jax.Array) + cross-process get: the consumer pulls the payload
    device-to-device (its plane counters show the bytes), host copy untouched."""
    x = jnp.full((131072,), 3.0, jnp.float32)  # 512 KiB < 1 MiB min -> host path
    big = jnp.full((524288,), 2.0, jnp.float32)  # 2 MiB >= min -> device path
    ref_small = rt.put(x)
    ref_big = rt.put(big)

    @rt.remote
    def consume(refs):  # refs nested in a list resolve inside, so counter deltas
        import numpy as _np  # bracket each get (workers are reused across tests)

        import ray_tpu
        from ray_tpu.core.device_plane import plane as _plane

        st0 = _plane().stats()
        a = ray_tpu.get(refs[0])
        st1 = _plane().stats()
        b = ray_tpu.get(refs[1])
        st2 = _plane().stats()
        return {
            "sum_small": float(_np.asarray(a).sum()),
            "sum_big": float(_np.asarray(b).sum()),
            "small_bytes": st1["bytes_pulled"] - st0["bytes_pulled"],
            "big_pulls": st2["pulls"] - st1["pulls"],
            "big_bytes": st2["bytes_pulled"] - st1["bytes_pulled"],
        }

    out = rt.get(consume.remote([ref_small, ref_big]))
    assert out["sum_small"] == 3.0 * 131072
    assert out["sum_big"] == 2.0 * 524288
    assert out["small_bytes"] == 0  # below min size: host path
    assert out["big_pulls"] == 1  # the big array rode the plane
    assert out["big_bytes"] == big.nbytes
    del ref_small, ref_big


def test_device_native_mode_stores_stub_only(rt, monkeypatch):
    """'native' mode: no host copy in the store — the inline frame is tiny and the
    consumer still receives the full array via the plane."""
    from ray_tpu.core import object_store

    monkeypatch.setenv("RAY_TPU_DEVICE_OBJECTS", "native")
    big = jnp.full((524288,), 2.5, jnp.float32)  # 2 MiB
    loc = object_store.materialize(big, _oid())
    # the durable form is a tiny inline stub, not a 2 MiB arena/shm object
    assert loc[0] == "inline", loc[0]
    assert len(loc[1]) < 4096

    ref = rt.put(big)

    @rt.remote
    def consume(a):
        import numpy as _np

        return float(_np.asarray(a).sum())

    assert rt.get(consume.remote(ref)) == 2.5 * 524288
    del ref


def _oid():
    from ray_tpu.core.ids import ObjectID

    return ObjectID.generate()


def test_pd_disagg_kv_rides_device_plane(rt):
    """Prefill -> decode handoff: the prefill result carries a handle (no host
    KV arrays), decode pulls device-to-device and matches the non-disagg output."""
    from ray_tpu.llm import JaxLLMEngine, LLMConfig, SamplingParams

    cfg = LLMConfig(model_id="pd-dev", model_source="test-tiny", max_num_seqs=2,
                    max_model_len=64, tokenizer="byte")
    eng = JaxLLMEngine(cfg)
    eng.start()
    try:
        params = SamplingParams(max_tokens=6, temperature=0.0, stop_token_ids=[-1])
        want = eng.generate_sync([1, 7, 42, 9], params).token_ids

        pre = eng.prefill_only([1, 7, 42, 9], params)
        assert "kv_handle" in pre and "k" not in pre, (
            "device plane up: prefill result must carry a handle, not host arrays")
        ids = []
        for chunk in eng.generate_from_prefill(pre, params):
            ids.extend(chunk.token_ids)
        assert [pre["first_token"]] + ids[1:] == ids  # first token came from prefill
        assert ids == want
    finally:
        eng.shutdown()


def test_pd_force_host_and_dead_handle_fallback(rt):
    """force_host pins the host path even with the plane up; a dead handle makes
    decode raise DevicePlaneError, which the PD router recognizes for fallback."""
    from ray_tpu.core.device_plane import DevicePlaneError, plane
    from ray_tpu.llm import JaxLLMEngine, LLMConfig, SamplingParams
    from ray_tpu.llm.server import _is_device_plane_error

    cfg = LLMConfig(model_id="pd-fb", model_source="test-tiny", max_num_seqs=2,
                    max_model_len=64, tokenizer="byte")
    eng = JaxLLMEngine(cfg)
    eng.start()
    try:
        params = SamplingParams(max_tokens=4, temperature=0.0, stop_token_ids=[-1])
        pre = eng.prefill_only([1, 5, 9], params, force_host=True)
        assert "k" in pre and "kv_handle" not in pre

        pre2 = eng.prefill_only([1, 5, 9], params)
        assert "kv_handle" in pre2
        plane().release(pre2["kv_handle"].key)  # simulate prefill replica loss
        try:
            eng.generate_from_prefill(pre2, params)
            raised = None
        except DevicePlaneError as e:
            raised = e
        assert raised is not None and _is_device_plane_error(raised)
    finally:
        eng.shutdown()


def test_device_channel_cross_process_pull(rt):
    """aDAG device channel: a jax.Array written on one side arrives on the other
    via the plane (device frame has no embedded host copy)."""
    import os

    from ray_tpu.dag.accelerator_context import DeviceChannel
    from ray_tpu.core.device_plane import plane

    name = "devch_" + os.urandom(4).hex()
    ch = DeviceChannel(name, 1 << 20, create=True)
    try:
        arr = jnp.ones((524288,)) * 4.0  # 2 MiB: above the device-native gate
        before = plane().stats()
        ch.write(("ok", arr))

        @rt.remote
        def read_side(chan):
            import numpy as _np

            from ray_tpu.core.device_plane import plane as _plane

            status, got = chan.read(timeout=10)
            st = _plane().stats()
            return status, float(_np.asarray(got).sum()), st["pulls"]

        status, total, pulls = rt.get(read_side.remote(ch))
        assert status == "ok"
        assert total == 4.0 * 524288
        assert pulls >= 1
        assert plane().stats()["arms"] >= before["arms"] + 1
    finally:
        ch.destroy()


def test_same_process_channel_still_zero_copy():
    """Same-process read returns the literal original array (no pull, no copy)."""
    import os

    from ray_tpu.dag.accelerator_context import DeviceChannel

    name = "devch_" + os.urandom(4).hex()
    ch = DeviceChannel(name, 1 << 20, create=True)
    try:
        arr = jnp.ones((128, 128))
        ch.write(arr)
        got = ch.read(timeout=5)
        assert got is arr
    finally:
        ch.destroy()


def test_reshard_fetch_across_unequal_meshes(rt):
    """Producer mesh (4,) -> consumer process with only TWO devices: fetch
    still rides the device plane (per-shard pull + one compiled reassembly
    under a consumer-sized mesh) with zero host pickle of the payload — the
    unequal-size P/D deployment shape (big prefill TP, small decode TP).
    Reference analogue: resharding NCCL channels,
    experimental/channel/torch_tensor_nccl_channel.py."""
    from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

    from ray_tpu.core.device_plane import plane

    mesh = Mesh(np.asarray(jax.devices()[:4]).reshape(4), ("x",))
    x = jax.device_put(jnp.arange(4096.0).reshape(8, 512),
                       NamedSharding(mesh, P("x")))
    handle = plane().export({"kv": x})

    @rt.remote(runtime_env={"env_vars": {
        "JAX_PLATFORMS": "cpu",
        "XLA_FLAGS": "--xla_force_host_platform_device_count=2"}})
    def consume(h):
        import jax as _jax
        import numpy as _np

        from ray_tpu.core.device_plane import plane as _plane

        assert len(_jax.devices()) == 2, len(_jax.devices())
        tree = _plane().fetch(h, release=True)
        arr = tree["kv"]
        st = _plane().stats()
        return {
            "sum": float(_np.asarray(arr).sum()),
            "ndev": len(arr.sharding.device_set),
            "spec": str(arr.sharding.spec),
            "reshard_pulls": st.get("reshard_pulls", 0),
            "bytes_pulled": st["bytes_pulled"],
        }

    out = rt.get(consume.remote(handle))
    assert out["sum"] == float(np.arange(4096.0).sum())
    assert out["reshard_pulls"] == 1
    # every payload byte is accounted for by the plane, none by pickle
    assert out["bytes_pulled"] == x.nbytes
    # arrived sharded over the consumer's OWN 2-device mesh, same logical spec
    assert out["ndev"] == 2 and out["spec"] == "PartitionSpec('x',)"
    # the producer-side export was released by the ack (other tests' exports
    # may still be live in this process — check only OURS is gone)
    deadline = __import__("time").time() + 10
    while plane()._exports.get(handle.key) is not None:
        assert __import__("time").time() < deadline, "export never released"


def test_pd_disagg_unequal_pools_device_path(rt):
    """P/D disaggregation with UNEQUAL pool sizes in separate processes (the
    common deployment: big prefill TP, small decode pool): prefill runs tp=2
    inside a 4-device actor, decode inside a 1-device actor. The KV handoff
    STILL rides the device plane — the decode side takes the reshard-fetch
    path — and the output matches colocated greedy decoding exactly."""
    from ray_tpu.llm import JaxLLMEngine, LLMConfig, SamplingParams

    prompt = [1, 7, 42, 9]
    n_tokens = 6

    ref_eng = JaxLLMEngine(LLMConfig(
        model_id="pd-ref", model_source="test-tiny", max_num_seqs=2,
        max_model_len=64, tokenizer="byte"))
    ref_eng.start()
    try:
        want = ref_eng.generate_sync(prompt, SamplingParams(
            max_tokens=n_tokens, temperature=0.0, stop_token_ids=[-1])).token_ids
    finally:
        ref_eng.shutdown()

    @rt.remote(runtime_env={"env_vars": {
        "JAX_PLATFORMS": "cpu",
        "XLA_FLAGS": "--xla_force_host_platform_device_count=4"}})
    class Prefill:
        def __init__(self):
            from ray_tpu.llm import JaxLLMEngine as Eng, LLMConfig as Cfg

            self.eng = Eng(Cfg(model_id="pd-up", model_source="test-tiny",
                               max_num_seqs=2, max_model_len=64,
                               tokenizer="byte", tensor_parallel_size=2))
            self.eng.start()

        def prefill(self, p, mt):
            from ray_tpu.llm import SamplingParams as SP

            out = self.eng.prefill_only(p, SP(max_tokens=mt, temperature=0.0,
                                              stop_token_ids=[-1]))
            assert "kv_handle" in out and "k" not in out
            return out

    @rt.remote(runtime_env={"env_vars": {
        "JAX_PLATFORMS": "cpu",
        "XLA_FLAGS": "--xla_force_host_platform_device_count=1"}})
    class Decode:
        def __init__(self):
            from ray_tpu.llm import JaxLLMEngine as Eng, LLMConfig as Cfg

            self.eng = Eng(Cfg(model_id="pd-down", model_source="test-tiny",
                               max_num_seqs=2, max_model_len=64,
                               tokenizer="byte"))
            self.eng.start()

        def decode(self, pre, mt):
            import jax as _jax

            from ray_tpu.core.device_plane import plane as _plane
            from ray_tpu.llm import SamplingParams as SP

            assert len(_jax.devices()) == 1
            ids = []
            for chunk in self.eng.generate_from_prefill(
                    pre, SP(max_tokens=mt, temperature=0.0,
                            stop_token_ids=[-1])):
                ids.extend(chunk.token_ids)
            return ids, _plane().stats().get("reshard_pulls", 0)

    pre_actor = Prefill.remote()
    dec_actor = Decode.remote()
    pre = rt.get(pre_actor.prefill.remote(prompt, n_tokens), timeout=180)
    ids, reshards = rt.get(dec_actor.decode.remote(pre, n_tokens), timeout=180)
    assert ids == want
    assert reshards == 1  # the pull really took the reshard path
