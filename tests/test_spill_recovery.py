"""Object spilling, memory-monitor OOM killing, lineage reconstruction
(reference: local_object_manager.h:43, memory_monitor.h:52 +
worker_killing_policy_retriable_fifo.h, object_recovery_manager.h:43)."""
import os
import time

import numpy as np
import pytest

import ray_tpu
from ray_tpu.core import global_state, object_store


@pytest.fixture()
def small_store_cluster():
    """Own cluster with a tiny arena so spilling kicks in fast. Restores the
    session-wide cluster afterwards (conftest rt) so later rt tests keep working."""
    was_up = global_state.is_initialized()
    ray_tpu.shutdown()
    ray_tpu.init(num_cpus=4, object_store_memory=8 * 1024 * 1024,
                 worker_env={"JAX_PLATFORMS": "cpu"})
    yield global_state.worker().cluster
    ray_tpu.shutdown()
    if was_up:
        ray_tpu.init(num_cpus=4, worker_env={"JAX_PLATFORMS": "cpu"},
                     max_workers_per_node=8)


def test_spill_location_roundtrip(tmp_path):
    """spill_location moves bytes to disk; resolve reads them back zero-copy."""
    from ray_tpu.core.ids import ObjectID

    oid = ObjectID.generate()
    arr = np.arange(100_000, dtype=np.float64)  # ~800KB > inline threshold
    loc = object_store.materialize(arr, oid)
    assert loc[0] in ("arena", "shm")
    new_loc = object_store.spill_location(loc, str(tmp_path / "spill"))
    assert new_loc is not None and new_loc[0] == "disk"
    out = object_store.resolve(new_loc)
    np.testing.assert_array_equal(out, arr)
    # original storage is gone: resolving the old location raises ObjectLost
    with pytest.raises(object_store.ObjectLost):
        object_store.resolve(loc)


def test_pressure_spills_lru_and_gets_still_work(small_store_cluster):
    cluster = small_store_cluster
    # fill ~3x the 8MB arena with 1MB objects; the maintenance loop must spill
    refs = [ray_tpu.put(np.full(128 * 1024, i, np.float64)) for i in range(24)]
    # the maintenance loop must spill LRU objects until under the high watermark
    deadline = time.time() + 20
    while time.time() < deadline:
        if cluster.store.memory_bytes() <= 0.9 * cluster._object_store_capacity:
            break
        time.sleep(0.2)
    else:
        pytest.fail(f"memory not relieved: {cluster.store.memory_bytes()} bytes resident")
    with cluster.store._lock:
        kinds = {k[0] for k in cluster.store._locations.values()}
    assert "disk" in kinds
    # every object is still readable (most from disk now)
    for i, r in enumerate(refs):
        v = ray_tpu.get(r)
        assert v[0] == i and len(v) == 128 * 1024


def test_lineage_reconstruction_after_loss(small_store_cluster):
    cluster = small_store_cluster

    @ray_tpu.remote(max_retries=2)
    def produce(seed):
        rng = np.random.default_rng(seed)
        return rng.standard_normal(64 * 1024)  # ~512KB -> arena

    ref = produce.remote(7)
    first = ray_tpu.get(ref)
    # simulate loss: destroy the object's storage behind the directory's back
    loc = cluster.store.try_location(ref.id)
    assert loc[0] in ("arena", "shm")
    if loc[0] == "arena":
        object_store._open_arena(loc[1]).delete(loc[2])
    else:
        from multiprocessing import shared_memory

        seg = shared_memory.SharedMemory(name=loc[1])
        seg.close()
        seg.unlink()
    # driver get triggers reconstruction via lineage resubmit
    again = ray_tpu.get(ref)
    np.testing.assert_array_equal(first, again)


def test_lineage_reconstruction_for_task_args(small_store_cluster):
    cluster = small_store_cluster

    @ray_tpu.remote(max_retries=1)
    def produce():
        return np.ones(64 * 1024)

    @ray_tpu.remote
    def consume(x):
        return float(x.sum())

    ref = produce.remote()
    ray_tpu.get(ref)
    loc = cluster.store.try_location(ref.id)
    if loc[0] == "arena":
        object_store._open_arena(loc[1]).delete(loc[2])
    # worker-side arg resolution must recover through the coordinator
    assert ray_tpu.get(consume.remote(ref)) == 64 * 1024


def test_unreconstructable_object_raises(small_store_cluster):
    cluster = small_store_cluster
    ref = ray_tpu.put(np.zeros(64 * 1024))  # put objects have no lineage
    loc = cluster.store.try_location(ref.id)
    if loc[0] == "arena":
        object_store._open_arena(loc[1]).delete(loc[2])
        with pytest.raises(ray_tpu.ObjectLostError):
            ray_tpu.get(ref)


def test_memory_monitor_kills_newest_retriable_task(small_store_cluster):
    cluster = small_store_cluster
    fired = {"n": 0}

    def fake_sampler():
        # report pressure exactly once; recover afterwards
        fired["n"] += 1
        return 0.99 if fired["n"] < 3 else 0.10

    @ray_tpu.remote(max_retries=3)
    def slow():
        import time as t

        t.sleep(1.5)
        return os.getpid()

    cluster.memory_usage_threshold = 0.9
    cluster._memory_sampler = fake_sampler
    ref = slow.remote()
    time.sleep(0.3)  # let it dispatch, then the monitor kills it
    pid = ray_tpu.get(ref, timeout=60)
    assert isinstance(pid, int)
    assert cluster.num_oom_kills >= 1


def test_oom_error_when_not_retriable(small_store_cluster):
    cluster = small_store_cluster
    always_high = lambda: 0.99  # noqa: E731

    @ray_tpu.remote(max_retries=0)
    def hog():
        import time as t

        t.sleep(5)
        return 1

    ref = hog.remote()
    time.sleep(0.3)
    cluster.memory_usage_threshold = 0.9
    cluster._memory_sampler = always_high
    with pytest.raises((ray_tpu.OutOfMemoryError, ray_tpu.WorkerCrashedError)):
        ray_tpu.get(ref, timeout=30)
    cluster.memory_usage_threshold = 2.0  # stop the killer for teardown
