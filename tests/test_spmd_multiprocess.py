"""Cross-process SPMD: ONE global device mesh spanning worker PROCESSES.

This is the actual multi-host pod execution model (a v5e-64 is 16 hosts x 4 chips
running one SPMD program): here 2 trainer worker processes x 4 virtual CPU devices
form one jax.distributed universe, build a single 8-device mesh, and step the real
llama train step through the stock `JaxTrainer.fit()` with globally-sharded batches.
Losses must match a single-process 8-device run of the identical program.

Reference analog: cross-worker DDP formed by _setup_torch_process_group
(python/ray/train/torch/config.py:66) and exercised end-to-end in
python/ray/train/tests/test_torch_trainer.py — the VERDICT r4 item-1 'done' bar.
"""
import numpy as np
import pytest

import ray_tpu

N_STEPS = 3
_WORKER_XLA_ENV = {
    "JAX_PLATFORMS": "cpu",
    "XLA_FLAGS": "--xla_force_host_platform_device_count=4",
}


# The ONE shared SPMD program both proofs compare against (defined once so the
# dryrun and this test cannot drift apart).
from __graft_entry__ import _spmd_global_losses as _global_mesh_losses  # noqa: E402


@pytest.fixture()
def spmd_cluster(rt):
    """Fresh cluster whose spawned workers see 4 virtual CPU devices each (set at
    process spawn, before any jax import in the worker)."""
    ray_tpu.shutdown()
    ray_tpu.init(num_cpus=4, worker_env=dict(_WORKER_XLA_ENV))
    yield
    ray_tpu.shutdown()
    ray_tpu.init(num_cpus=4, worker_env={"JAX_PLATFORMS": "cpu"},
                 max_workers_per_node=8)


def test_trainer_global_mesh_spans_processes(spmd_cluster, tmp_path):
    """2 processes x 4 devices -> one 8-device mesh via JaxTrainer.fit(); losses match
    the single-process 8-device run of the same program to fp tolerance; an
    XLA-backend collective (device-path psum) runs across the same universe."""
    import sys
    import uuid

    import cloudpickle

    from ray_tpu.air import RunConfig, ScalingConfig
    from ray_tpu.train import JaxConfig, JaxTrainer

    # Workers can't import this test module (or the repo-root graft entry) — ship
    # the loop and _global_mesh_losses by value.
    import __graft_entry__

    cloudpickle.register_pickle_by_value(sys.modules[__name__])
    cloudpickle.register_pickle_by_value(__graft_entry__)

    group = f"spmd_xla_{uuid.uuid4().hex[:8]}"

    def loop(config):
        import jax

        import ray_tpu.train as train
        from ray_tpu.util import collective as col

        rank = train.get_context().get_world_rank()
        assert jax.process_count() == 2, jax.process_count()
        assert len(jax.devices()) == 8 and len(jax.local_devices()) == 4

        # XLA-backend collective op across the universe: compiled device-path
        # psum over a mesh with one device per process (collective.py:287 bootstrap
        # + _xla_device_allreduce) — NOT the host shm coordinator plane.
        col.init_collective_group(2, rank, backend="xla", group_name=config["group"])
        psum = col.allreduce(np.array([float(rank + 1)], dtype=np.float32),
                             group_name=config["group"])

        losses = _global_mesh_losses()
        train.report({
            "losses": losses,
            "psum": float(np.asarray(psum)[0]),
            "nprocs": jax.process_count(),
            "ndev": len(jax.devices()),
        })

    trainer = JaxTrainer(
        loop,
        train_loop_config={"group": group},
        backend_config=JaxConfig(distributed=True, platform="cpu",
                                 collective_group=False,
                                 env=dict(_WORKER_XLA_ENV)),
        scaling_config=ScalingConfig(num_workers=2, cpus_per_worker=1.0),
        run_config=RunConfig(name="t_spmd_mp", storage_path=str(tmp_path)),
    )
    result = trainer.fit()
    assert result.error is None, result.error
    assert len(result.all_metrics) == 2
    # This test process has its own 8 LOCAL devices (conftest) — the single-process
    # reference run of the identical global program.
    ref = _global_mesh_losses()
    for m in result.all_metrics:
        assert m["nprocs"] == 2 and m["ndev"] == 8
        assert m["psum"] == 3.0  # 1 + 2 summed on-device across processes
        np.testing.assert_allclose(m["losses"], ref, rtol=1e-4)
    # training genuinely progressed (not a frozen-step artifact of lr warmup)
    assert ref[0] != ref[-1]
