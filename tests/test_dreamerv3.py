"""DreamerV3 (rllib/algorithms/dreamerv3.py): world model + imagination AC.

Reference: rllib/algorithms/dreamerv3/dreamerv3.py — the one SURVEY-listed
algorithm family absent from round 1.
"""
import numpy as np
import pytest

from ray_tpu.rllib import DreamerV3Config
from ray_tpu.rllib.algorithms.dreamerv3 import _StreamBuffer


def _tiny_config():
    return (
        DreamerV3Config()
        .environment("CartPole-v1")
        .env_runners(num_env_runners=1, num_envs_per_env_runner=4,
                     rollout_fragment_length=50)
        .training(deter_size=128, hidden=128, embed_size=64,
                  stoch_groups=8, stoch_classes=8,
                  batch_size_seqs=8, seq_len=24,
                  num_updates_per_iteration=12,
                  sample_timesteps_per_iteration=300,
                  num_steps_sampled_before_learning_starts=300,
                  imag_horizon=15, entropy_coef=1e-2)
    )


def test_stream_buffer_terminal_rows():
    buf = _StreamBuffer(1000, obs_dim=3)
    ep = {
        "obs": np.arange(12, dtype=np.float32).reshape(4, 3),
        "next_obs_last": np.full(3, 99.0, np.float32),
        "actions": np.array([1, 0, 1, 0]),
        "rewards": np.array([1.0, 1.0, 1.0, 5.0], np.float32),
        "terminated": True,
        "truncated": False,
    }
    added = buf.add_episodes([ep])
    assert added == 5  # 4 action states + the terminal state row
    assert buf.is_first[0] == 1.0 and buf.terms[4] == 1.0
    assert buf.obs[4][0] == 99.0  # terminal observation present
    assert buf.rew_in[4] == 5.0  # reward entering the terminal state
    assert buf.rew_in[1] == 1.0 and buf.rew_in[0] == 0.0
    # truncated episodes also get a final-state row (it carries the episode's
    # LAST reward — otherwise censored) but with cont target 1, not terminal
    ep2 = dict(ep, terminated=False, truncated=True)
    assert buf.add_episodes([ep2]) == 5
    assert buf.terms[9] == 0.0 and buf.rew_in[9] == 5.0


def test_world_model_loss_decreases(rt):
    """A few updates on fixed replayed data drive the world-model loss down."""
    cfg = _tiny_config()
    algo = cfg.build()
    try:
        algo.train()  # fills the replay buffer past warmup
        first, last = None, None
        for _ in range(6):
            r = algo.train()
            if r.get("wm_loss") is not None:
                first = first if first is not None else r["wm_loss"]
                last = r["wm_loss"]
        assert first is not None and last < first
    finally:
        algo.stop()


def test_checkpoint_roundtrip(rt, tmp_path):
    cfg = _tiny_config()
    algo = cfg.build()
    try:
        algo.train()
        state = algo.save_checkpoint()
        algo2 = _tiny_config().build()
        try:
            algo2.load_checkpoint(state)
            w1 = algo.learner_group.get_weights()
            w2 = algo2.learner_group.get_weights()
            np.testing.assert_array_equal(w1["actor"][0]["w"], w2["actor"][0]["w"])
        finally:
            algo2.stop()
    finally:
        algo.stop()


def test_learns_cartpole(rt):
    """VERDICT bar: learns a toy env in a bounded test — mean CartPole return
    must clearly exceed the random policy's (~20) within the budget."""
    cfg = _tiny_config().debugging(seed=0)
    algo = cfg.build()
    try:
        best = 0.0
        baseline = None
        for it in range(45):
            r = algo.train()
            ret = r.get("episode_return_mean")
            if ret is None:
                continue
            if baseline is None:
                baseline = ret
            best = max(best, ret)
            if best >= 30.0 and it >= 10:
                break
        assert baseline is not None
        assert best >= 30.0, (
            f"no learning: best return {best:.1f} (baseline {baseline:.1f})")
    finally:
        algo.stop()
