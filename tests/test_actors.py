"""Actor tests: creation, methods, state, named actors, restart, kill, actor-to-actor."""
import time

import pytest


def test_actor_basics(rt):
    @rt.remote
    class Counter:
        def __init__(self, start=0):
            self.n = start

        def inc(self, k=1):
            self.n += k
            return self.n

        def value(self):
            return self.n

    c = Counter.remote(10)
    assert rt.get(c.inc.remote()) == 11
    assert rt.get(c.inc.remote(5)) == 16
    assert rt.get(c.value.remote()) == 16


def test_actor_method_ordering(rt):
    @rt.remote
    class Appender:
        def __init__(self):
            self.items = []

        def add(self, x):
            self.items.append(x)
            return len(self.items)

        def items_list(self):
            return self.items

    a = Appender.remote()
    for i in range(20):
        a.add.remote(i)
    assert rt.get(a.items_list.remote()) == list(range(20))


def test_actor_error(rt):
    @rt.remote
    class Cranky:
        def fail(self):
            raise RuntimeError("nope")

        def ok(self):
            return "fine"

    c = Cranky.remote()
    with pytest.raises(rt.TaskError):
        rt.get(c.fail.remote())
    # Actor survives method errors.
    assert rt.get(c.ok.remote()) == "fine"


def test_named_actor(rt):
    @rt.remote
    class Registry:
        def ping(self):
            return "pong"

    original = Registry.options(name="reg1").remote()
    h = rt.get_actor("reg1")
    assert rt.get(h.ping.remote()) == "pong"
    with pytest.raises(ValueError):
        rt.get_actor("does-not-exist")


def test_actor_handle_passing(rt):
    @rt.remote
    class Store:
        def __init__(self):
            self.v = None

        def set(self, v):
            self.v = v

        def get(self):
            return self.v

    @rt.remote
    def writer(store):
        import ray_tpu

        ray_tpu.get(store.set.remote(123))
        return "done"

    s = Store.remote()
    assert rt.get(writer.remote(s)) == "done"
    assert rt.get(s.get.remote()) == 123


def test_actor_to_actor(rt):
    @rt.remote
    class Leaf:
        def compute(self, x):
            return x * 10

    @rt.remote
    class Root:
        def __init__(self, leaf):
            self.leaf = leaf

        def go(self, x):
            import ray_tpu

            return ray_tpu.get(self.leaf.compute.remote(x)) + 1

    leaf = Leaf.remote()
    root = Root.remote(leaf)
    assert rt.get(root.go.remote(5)) == 51


def test_kill_actor(rt):
    @rt.remote
    class Victim:
        def ping(self):
            return "alive"

    v = Victim.remote()
    assert rt.get(v.ping.remote()) == "alive"
    rt.kill(v)
    time.sleep(0.3)
    with pytest.raises((rt.ActorError, rt.ActorDiedError, rt.WorkerCrashedError)):
        rt.get(v.ping.remote(), timeout=10)


def test_actor_restart(rt):
    @rt.remote(max_restarts=2)
    class Phoenix:
        def __init__(self):
            self.n = 0

        def inc(self):
            self.n += 1
            return self.n

        def die(self):
            import os

            os._exit(1)

    p = Phoenix.remote()
    assert rt.get(p.inc.remote()) == 1
    p.die.remote()
    time.sleep(1.0)
    # State resets after restart (no checkpoint), but the actor is alive again.
    deadline = time.time() + 30
    while True:
        try:
            assert rt.get(p.inc.remote(), timeout=10) == 1
            break
        except (rt.ActorError, rt.ActorDiedError, rt.WorkerCrashedError, rt.TaskError):
            if time.time() > deadline:
                raise
            time.sleep(0.2)


def test_worker_crash_retry(rt):
    @rt.remote(max_retries=2)
    def crash_once(key):
        import os
        import tempfile

        marker = os.path.join(tempfile.gettempdir(), f"crash_{key}")
        if not os.path.exists(marker):
            with open(marker, "w") as f:
                f.write("1")
            os._exit(1)
        return "survived"

    key = str(time.time()).replace(".", "")
    assert rt.get(crash_once.remote(key), timeout=60) == "survived"


def test_concurrency_groups(rt):
    """Named concurrency groups (reference concurrency_group_manager.h): parked
    calls in one group must not starve methods on the default pool."""
    import threading

    @rt.remote(max_concurrency=2, concurrency_groups={"listen": 0})
    class Host:
        def __init__(self):
            self.ev = threading.Event()

        @rt.method(concurrency_group="listen")
        def park(self):
            self.ev.wait(30)
            return "woke"

        def ping(self):
            return "pong"

        def wake(self):
            self.ev.set()
            return True

    h = Host.remote()
    # park more listeners than max_concurrency: default-pool RPCs must still run
    parked = [h.park.remote() for _ in range(6)]
    assert rt.get(h.ping.remote(), timeout=10) == "pong"
    assert rt.get(h.wake.remote(), timeout=10) is True
    assert rt.get(parked, timeout=30) == ["woke"] * 6


def test_concurrency_group_call_time_override(rt):
    import threading

    @rt.remote(max_concurrency=1, concurrency_groups={"io": 1})
    class A:
        def __init__(self):
            self.ev = threading.Event()

        def block(self):
            self.ev.wait(30)
            return 1

        def unblock(self):
            self.ev.set()
            return 2

    a = A.remote()
    blocked = a.block.remote()  # occupies the single default thread
    # route around it via the io group at call time
    assert rt.get(a.unblock.options(concurrency_group="io").remote(), timeout=10) == 2
    assert rt.get(blocked, timeout=10) == 1
