"""ray_tpu.tune tests (reference strategy: python/ray/tune/tests/)."""
import pytest

from ray_tpu import tune
from ray_tpu.tune.tune_controller import ERROR, TERMINATED


@pytest.fixture(autouse=True)
def _cluster(rt):
    yield


def test_grid_search_function_trainable(rt):
    def objective(config):
        for i in range(3):
            tune.report({"loss": (config["x"] - 2) ** 2 + i * 0.0})

    tuner = tune.Tuner(
        objective,
        param_space={"x": tune.grid_search([0, 1, 2, 3])},
        tune_config=tune.TuneConfig(metric="loss", mode="min"),
    )
    grid = tuner.fit()
    assert len(grid) == 4
    best = grid.get_best_result("loss", "min")
    assert best.config["x"] == 2
    assert best.metrics["loss"] == 0


def test_class_trainable_and_stop_criteria(rt):
    class Quad(tune.Trainable):
        def setup(self, config):
            self.x = config["x"]

        def step(self):
            return {"score": self.x * self._iteration}

    grid = tune.run(Quad, config={"x": tune.grid_search([1, 5])}, stop={"training_iteration": 4})
    assert len(grid) == 2
    for r in grid:
        assert r.metrics["training_iteration"] == 4


def test_random_search_spaces(rt):
    seen = []

    def obj(config):
        seen.append(config)
        tune.report({"v": config["lr"], "done": True})

    grid = tune.Tuner(
        obj,
        param_space={"lr": tune.loguniform(1e-5, 1e-1), "b": tune.choice([8, 16])},
        tune_config=tune.TuneConfig(num_samples=5, seed=0),
    ).fit()
    assert len(grid) == 5
    for r in grid:
        assert 1e-5 <= r.config["lr"] <= 1e-1
        assert r.config["b"] in (8, 16)


def test_asha_stops_bad_trials(rt):
    def objective(config):
        for i in range(20):
            tune.report({"loss": config["x"] + i * 0.001})

    sched = tune.AsyncHyperBandScheduler(metric="loss", mode="min", grace_period=2, max_t=20)
    # sequential trials -> deterministic rung comparisons
    grid = tune.run(
        objective,
        config={"x": tune.grid_search([0.0, 1.0, 2.0, 3.0])},
        scheduler=sched,
        max_concurrent_trials=1,
    )
    iters = {r.config["x"]: r.metrics["training_iteration"] for r in grid}
    assert iters[0.0] == 20  # best trial runs to max_t
    assert iters[1.0] == iters[2.0] == iters[3.0] == 2  # cut at the first rung


def test_checkpoint_restore_on_failure(rt):
    class Flaky(tune.Trainable):
        def setup(self, config):
            self.acc = 0

        def step(self):
            self.acc += 1
            if self.acc == 3 and not getattr(self, "acc_restored", False):
                raise RuntimeError("boom")  # fails until restarted from a checkpoint
            return {"acc": self.acc}

        def save_checkpoint(self):
            return {"acc": self.acc}

        def load_checkpoint(self, state):
            self.acc = state["acc"]
            self.acc_restored = True

    import ray_tpu
    from ray_tpu.air import CheckpointConfig, FailureConfig, RunConfig

    grid = tune.Tuner(
        Flaky,
        param_space={},
        run_config=RunConfig(
            stop={"training_iteration": 6},
            failure_config=FailureConfig(max_failures=2),
            checkpoint_config=CheckpointConfig(checkpoint_frequency=1),
        ),
    ).fit()
    r = grid[0]
    assert r.error is None
    assert r.metrics["training_iteration"] == 6


def test_pbt_exploits(rt):
    def objective(config):
        score = 0.0
        ck = tune.get_checkpoint()
        if ck is not None:
            score = ck["score"]
        lr = config["lr"]
        for i in range(20):
            score += lr  # higher lr -> faster score growth
            tune.report({"score": score}, checkpoint={"score": score})

    sched = tune.PopulationBasedTraining(
        metric="score", mode="max", perturbation_interval=5,
        hyperparam_mutations={"lr": tune.uniform(0.1, 1.0)}, seed=0,
    )
    # PBT restarts exploited function trials, so a stop criterion bounds the run
    grid = tune.run(
        objective,
        config={"lr": tune.grid_search([0.1, 0.9])},
        scheduler=sched,
        max_concurrent_trials=2,
        stop={"training_iteration": 30},
    )
    assert len(grid) == 2
    # exploit copies the strong trial's state; both end with competitive scores
    scores = sorted(r.metrics["score"] for r in grid)
    assert scores[0] > 0.1 * 30 + 0.5  # weak trial was boosted past its pure-0.1-lr ceiling
