"""HF safetensors checkpoint IO (models/checkpoint.py).

The transformers cross-check is the load-bearing test: it proves the weight
mapping matches the real HF Llama convention (not just our own round-trip).
"""
import json
import os

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from ray_tpu.models import checkpoint as ckpt_io
from ray_tpu.models import llama
from ray_tpu.models.config import ModelConfig

TINY = dict(vocab_size=64, d_model=32, n_layers=2, n_heads=4, n_kv_heads=2,
            d_ff=48, max_seq_len=128, remat=False, dtype="float32")


def _cfg(**kw):
    return ModelConfig(name="tiny-ckpt", **{**TINY, **kw})


def test_roundtrip_exact(tmp_path):
    cfg = _cfg()
    params = llama.init(jax.random.PRNGKey(0), cfg)
    ckpt_io.save_llama_params(params, cfg, str(tmp_path / "ckpt"))
    # cfg comes from the written config.json, not passed in
    loaded = ckpt_io.load_llama_params(str(tmp_path / "ckpt"), param_dtype=jnp.float32)
    flat_a = jax.tree.leaves(params)
    flat_b = jax.tree.leaves(loaded)
    assert len(flat_a) == len(flat_b)
    for a, b in zip(flat_a, flat_b):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


def test_roundtrip_unscanned_layers(tmp_path):
    cfg = _cfg(scan_layers=False)
    params = llama.init(jax.random.PRNGKey(1), cfg)
    ckpt_io.save_llama_params(params, cfg, str(tmp_path / "ckpt"))
    loaded = ckpt_io.load_llama_params(
        str(tmp_path / "ckpt"), cfg=cfg, param_dtype=jnp.float32)
    for a, b in zip(jax.tree.leaves(params), jax.tree.leaves(loaded)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


def test_load_sharded_tp2(tmp_path):
    from jax.sharding import Mesh

    cfg = _cfg()
    params = llama.init(jax.random.PRNGKey(2), cfg)
    ckpt_io.save_llama_params(params, cfg, str(tmp_path / "ckpt"))
    devs = np.asarray(jax.devices()[:2]).reshape(1, 1, 2)
    mesh = Mesh(devs, ("dp", "ep", "tp"))
    loaded = ckpt_io.load_llama_params(
        str(tmp_path / "ckpt"), mesh=mesh, param_dtype=jnp.float32)
    # wq is sharded over tp on the heads axis
    wq = loaded["layers"]["wq"]
    assert len(wq.sharding.device_set) == 2
    tokens = jnp.asarray([[1, 5, 9, 3]], jnp.int32)
    ref_logits, _ = llama.forward(params, tokens, cfg)
    got_logits, _ = llama.forward(loaded, tokens, cfg)
    np.testing.assert_allclose(np.asarray(got_logits), np.asarray(ref_logits),
                               rtol=2e-4, atol=2e-4)


def test_hf_transformers_parity(tmp_path):
    """Weights exported by the REAL transformers LlamaForCausalLM load into our
    pytree and reproduce its logits — proves the mapping, not just a roundtrip."""
    torch = pytest.importorskip("torch")
    tr = pytest.importorskip("transformers")

    hf_cfg = tr.LlamaConfig(
        vocab_size=64, hidden_size=32, num_hidden_layers=2,
        num_attention_heads=4, num_key_value_heads=2, intermediate_size=48,
        max_position_embeddings=128, rope_theta=10000.0, rms_norm_eps=1e-5,
        tie_word_embeddings=False, attn_implementation="eager",
    )
    torch.manual_seed(0)
    model = tr.LlamaForCausalLM(hf_cfg).eval()
    src = str(tmp_path / "hf")
    model.save_pretrained(src, safe_serialization=True)

    cfg = ckpt_io.config_from_hf(src, remat=False, dtype="float32")
    assert cfg.n_layers == 2 and cfg.n_kv_heads == 2 and cfg.rope_theta == 10000.0
    params = ckpt_io.load_llama_params(src, cfg, param_dtype=jnp.float32)

    ids = [[1, 7, 23, 40, 5, 61]]
    with torch.no_grad():
        ref = model(torch.tensor(ids)).logits.numpy()
    got, _ = llama.forward(params, jnp.asarray(ids, jnp.int32), cfg)
    np.testing.assert_allclose(np.asarray(got, np.float32), ref, rtol=2e-3, atol=2e-3)


def test_engine_loads_checkpoint_deterministic_tokens(tmp_path, rt):
    """End-to-end VERDICT bar: tiny real safetensors checkpoint -> tp=2 mesh ->
    deterministic greedy tokens, identical to an engine fed the params directly."""
    from ray_tpu.llm import JaxLLMEngine, LLMConfig, SamplingParams

    cfg = _cfg(dtype="float32")
    params = llama.init(jax.random.PRNGKey(3), cfg)
    src = str(tmp_path / "ckpt")
    ckpt_io.save_llama_params(params, cfg, src)

    def greedy(engine):
        engine.start()
        out = engine.generate_sync(
            "hello tpu", SamplingParams(max_tokens=8, temperature=0.0,
                                        stop_token_ids=[-1]))
        return out.token_ids

    common = dict(max_num_seqs=2, max_model_len=64, dtype="float32",
                  tensor_parallel_size=2)
    from_ckpt = greedy(JaxLLMEngine(LLMConfig(model_source=src, **common)))
    from_params = greedy(JaxLLMEngine(
        LLMConfig(model_source=cfg, **common), params=params))
    assert from_ckpt == from_params
    assert len(from_ckpt) == 8
    # determinism across a fresh engine on the same checkpoint
    again = greedy(JaxLLMEngine(LLMConfig(model_source=src, **common)))
    assert again == from_ckpt


def test_sharded_index_file(tmp_path):
    """Checkpoints split across N safetensors files load via the index."""
    from safetensors.numpy import save_file

    cfg = _cfg()
    params = llama.init(jax.random.PRNGKey(4), cfg)
    src = str(tmp_path / "one")
    ckpt_io.save_llama_params(params, cfg, src)
    # re-split the single file into two + an index
    from safetensors import safe_open

    dst = str(tmp_path / "split")
    os.makedirs(dst)
    with safe_open(os.path.join(src, "model.safetensors"), framework="numpy") as h:
        keys = sorted(h.keys())
        half = len(keys) // 2
        parts = [keys[:half], keys[half:]]
        weight_map = {}
        for n, part in enumerate(parts, start=1):
            fname = f"model-{n:05d}-of-00002.safetensors"
            save_file({k: h.get_tensor(k) for k in part}, os.path.join(dst, fname))
            weight_map.update({k: fname for k in part})
    with open(os.path.join(dst, "model.safetensors.index.json"), "w") as f:
        json.dump({"weight_map": weight_map}, f)
    with open(os.path.join(dst, "config.json"), "w") as f:
        json.dump(ckpt_io.config_to_hf(cfg), f)
    loaded = ckpt_io.load_llama_params(dst, param_dtype=jnp.float32)
    for a, b in zip(jax.tree.leaves(params), jax.tree.leaves(loaded)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


def test_train_init_state_from_checkpoint(tmp_path):
    from ray_tpu.train.step import init_state, make_optimizer

    cfg = _cfg()
    params = llama.init(jax.random.PRNGKey(5), cfg)
    src = str(tmp_path / "ckpt")
    ckpt_io.save_llama_params(params, cfg, src)
    state = init_state(jax.random.PRNGKey(0), cfg, make_optimizer(),
                       checkpoint_dir=src)
    np.testing.assert_array_equal(np.asarray(state.params["embed"]),
                                  np.asarray(params["embed"]))
    assert state.opt_state is not None


# ------------------------------------------------------------------------- MoE

MOE_TINY = dict(**TINY, n_experts=4, moe_top_k=2)


def test_moe_roundtrip_exact(tmp_path):
    """Mixtral-layout MoE checkpoints round-trip (router + per-expert w1/w2/w3),
    and config.json carries num_local_experts/num_experts_per_tok."""
    cfg = _cfg(**MOE_TINY)
    params = llama.init(jax.random.PRNGKey(6), cfg)
    src = str(tmp_path / "ckpt")
    ckpt_io.save_llama_params(params, cfg, src)
    with open(os.path.join(src, "config.json")) as f:
        hf = json.load(f)
    assert hf["model_type"] == "mixtral"
    assert hf["num_local_experts"] == 4 and hf["num_experts_per_tok"] == 2
    # trained dispatch semantics survive the round-trip (extension keys beat
    # the dropless mixtral defaults)
    re_cfg = ckpt_io.config_from_hf(src)
    assert re_cfg.moe_capacity_factor == cfg.moe_capacity_factor
    assert re_cfg.moe_top1_renorm == cfg.moe_top1_renorm
    # cfg reconstructed from config.json, not passed in
    loaded = ckpt_io.load_llama_params(src, param_dtype=jnp.float32)
    flat_a = jax.tree.leaves(params)
    flat_b = jax.tree.leaves(loaded)
    assert len(flat_a) == len(flat_b)
    for a, b in zip(flat_a, flat_b):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


def test_moe_roundtrip_unscanned(tmp_path):
    cfg = _cfg(**MOE_TINY, scan_layers=False)
    params = llama.init(jax.random.PRNGKey(7), cfg)
    src = str(tmp_path / "ckpt")
    ckpt_io.save_llama_params(params, cfg, src)
    loaded = ckpt_io.load_llama_params(src, cfg=cfg, param_dtype=jnp.float32)
    for a, b in zip(jax.tree.leaves(params), jax.tree.leaves(loaded)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


@pytest.mark.parametrize("top_k", [1, 2])
def test_hf_mixtral_parity(tmp_path, top_k):
    """Weights exported by the REAL transformers MixtralForCausalLM load into our
    MoE pytree and reproduce its logits WITH DEFAULT load options. Gating parity:
    softmax over all E + top-k renormalization equals Mixtral's softmax over the
    top-k logits (the normalizer cancels); k=1 exercises moe_top1_renorm (the
    Switch convention would underweight every MLP output). Dropless capacity
    (factor E/k) is the config_from_hf default for mixtral checkpoints — no
    override needed, matching how the engine loads a real model dir."""
    torch = pytest.importorskip("torch")
    tr = pytest.importorskip("transformers")

    hf_cfg = tr.MixtralConfig(
        vocab_size=64, hidden_size=32, num_hidden_layers=2,
        num_attention_heads=4, num_key_value_heads=2, intermediate_size=48,
        num_local_experts=4, num_experts_per_tok=top_k,
        max_position_embeddings=128, rope_theta=10000.0, rms_norm_eps=1e-5,
        tie_word_embeddings=False, sliding_window=None,
        attn_implementation="eager",
    )
    torch.manual_seed(0)
    model = tr.MixtralForCausalLM(hf_cfg).eval()
    src = str(tmp_path / "hf")
    model.save_pretrained(src, safe_serialization=True)

    cfg = ckpt_io.config_from_hf(src, remat=False, dtype="float32")
    assert cfg.n_experts == 4 and cfg.moe_top_k == top_k
    assert cfg.moe_top1_renorm and cfg.moe_capacity_factor == 4.0 / top_k
    params = ckpt_io.load_llama_params(src, cfg, param_dtype=jnp.float32)

    ids = [[1, 7, 23, 40, 5, 61]]
    with torch.no_grad():
        ref = model(torch.tensor(ids)).logits.numpy()
    got, _ = llama.forward(params, jnp.asarray(ids, jnp.int32), cfg)
    np.testing.assert_allclose(np.asarray(got, np.float32), ref, rtol=2e-3, atol=2e-3)
