"""Core-plane microbenchmark (reference python/ray/_private/ray_perf.py:95-317).

Measures the task/actor/object hot paths; writes CORE_BENCH.json. Run:
    JAX_PLATFORMS=cpu python core_bench.py
"""
import json
import os
import time

os.environ.setdefault("JAX_PLATFORMS", "cpu")


def timed(fn, n):
    t0 = time.perf_counter()
    fn()
    dt = time.perf_counter() - t0
    return n / dt


def main():
    import numpy as np

    import ray_tpu

    ray_tpu.init(num_cpus=4, worker_env={"JAX_PLATFORMS": "cpu"},
                 max_workers_per_node=8)
    results = {}

    @ray_tpu.remote(num_cpus=0.1, max_retries=0)
    def nop():
        return None

    @ray_tpu.remote(num_cpus=0.1)
    class Counter:
        def nop(self):
            return None

        async def anop(self):
            return None

    # warm-up: spawn workers + import paths
    ray_tpu.get([nop.remote() for _ in range(20)])

    N = 2000
    results["tasks_per_s"] = timed(
        lambda: ray_tpu.get([nop.remote() for _ in range(N)]), N)

    a = Counter.remote()
    ray_tpu.get(a.nop.remote())
    results["actor_calls_per_s"] = timed(
        lambda: ray_tpu.get([a.nop.remote() for _ in range(N)]), N)

    results["actor_calls_sync_per_s"] = timed(
        lambda: [ray_tpu.get(a.nop.remote()) for _ in range(500)], 500)

    results["async_actor_calls_per_s"] = timed(
        lambda: ray_tpu.get([a.anop.remote() for _ in range(N)]), N)

    small = b"x" * 100
    results["put_small_per_s"] = timed(
        lambda: [ray_tpu.put(small) for _ in range(N)], N)

    refs = [ray_tpu.put(small) for _ in range(N)]
    results["get_small_per_s"] = timed(lambda: ray_tpu.get(refs), N)

    big = np.zeros(1_250_000, dtype=np.float64)  # 10 MB
    ray_tpu.put(big)  # warm the arena growth path
    put_times = []
    big_refs = []
    for _ in range(20):
        t0 = time.perf_counter()
        big_refs.append(ray_tpu.put(big))
        put_times.append(time.perf_counter() - t0)
    # best-of-N: byte throughput measures the copy path's capability; on a
    # loaded/few-core machine the median mostly measures scheduler contention
    # from the benchmark's own idle workers
    results["put_10mb_gbps"] = big.nbytes / min(put_times) / 1e9
    get_times = []
    for r in big_refs:
        t0 = time.perf_counter()
        ray_tpu.get(r)
        get_times.append(time.perf_counter() - t0)
    results["get_10mb_gbps"] = big.nbytes / min(get_times) / 1e9

    @ray_tpu.remote(num_cpus=0.1)
    def consume(x):
        return None

    arg_ref = ray_tpu.put(small)
    results["tasks_with_arg_per_s"] = timed(
        lambda: ray_tpu.get([consume.remote(arg_ref) for _ in range(N)]), N)

    ray_tpu.shutdown()
    for k, v in results.items():
        print(f"{k}: {v:,.0f}" if v > 100 else f"{k}: {v:.2f}")
    with open(os.path.join(os.path.dirname(__file__) or ".", "CORE_BENCH.json"), "w") as f:
        json.dump({k: round(v, 2) for k, v in results.items()}, f, indent=2)
    print("wrote CORE_BENCH.json")


if __name__ == "__main__":
    main()
