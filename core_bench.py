"""Core-plane microbenchmark (reference python/ray/_private/ray_perf.py:95-317).

Measures the task/actor/object hot paths; writes CORE_BENCH.json with two
columns: "local" (in-process workers) and "remote" (tasks/actors dispatched
through a real node agent over TCP — the relay hop a multi-host pod pays —
plus 10MB object transfers in both directions over the data plane).
Run:
    JAX_PLATFORMS=cpu python core_bench.py            # both columns
    JAX_PLATFORMS=cpu python core_bench.py --local    # local only
"""
import json
import os
import subprocess
import sys
import time

os.environ.setdefault("JAX_PLATFORMS", "cpu")


def timed(fn, n):
    t0 = time.perf_counter()
    fn()
    dt = time.perf_counter() - t0
    return n / dt


def suite(ray_tpu, np, sched=None, n=2000, object_ops=True):
    """The ray_perf measurement set; sched pins tasks/actors to one node.
    object_ops=False skips the put/get rows — they always run driver-local,
    so they'd be misleading in a remote (agent-dispatched) column."""
    results = {}

    def opt(r):
        return r.options(scheduling_strategy=sched) if sched is not None else r

    @ray_tpu.remote(num_cpus=0.1, max_retries=0)
    def nop():
        return None

    @ray_tpu.remote(num_cpus=0.1)
    class Counter:
        def nop(self):
            return None

        async def anop(self):
            return None

    # warm-up: spawn workers + import paths
    ray_tpu.get([opt(nop).remote() for _ in range(20)])

    results["tasks_per_s"] = timed(
        lambda: ray_tpu.get([opt(nop).remote() for _ in range(n)]), n)

    a = opt(Counter).remote()
    ray_tpu.get(a.nop.remote())
    results["actor_calls_per_s"] = timed(
        lambda: ray_tpu.get([a.nop.remote() for _ in range(n)]), n)

    results["actor_calls_sync_per_s"] = timed(
        lambda: [ray_tpu.get(a.nop.remote()) for _ in range(500)], 500)

    results["async_actor_calls_per_s"] = timed(
        lambda: ray_tpu.get([a.anop.remote() for _ in range(n)]), n)

    small = b"x" * 100
    if object_ops:
        results["put_small_per_s"] = timed(
            lambda: [ray_tpu.put(small) for _ in range(n)], n)

        refs = [ray_tpu.put(small) for _ in range(n)]
        results["get_small_per_s"] = timed(lambda: ray_tpu.get(refs), n)

        big = np.zeros(1_250_000, dtype=np.float64)  # 10 MB
        ray_tpu.put(big)  # warm the arena growth path
        put_times = []
        big_refs = []
        for _ in range(20):
            t0 = time.perf_counter()
            big_refs.append(ray_tpu.put(big))
            put_times.append(time.perf_counter() - t0)
        # best-of-N: byte throughput measures the copy path's capability; on a
        # loaded/few-core machine the median mostly measures scheduler
        # contention from the benchmark's own idle workers
        results["put_10mb_gbps"] = big.nbytes / min(put_times) / 1e9
        get_times = []
        for r in big_refs:
            t0 = time.perf_counter()
            ray_tpu.get(r)
            get_times.append(time.perf_counter() - t0)
        results["get_10mb_gbps"] = big.nbytes / min(get_times) / 1e9

    @ray_tpu.remote(num_cpus=0.1)
    def consume(x):
        return None

    arg_ref = ray_tpu.put(small)
    results["tasks_with_arg_per_s"] = timed(
        lambda: ray_tpu.get([opt(consume).remote(arg_ref) for _ in range(n)]), n)
    return results


def transfer_suite(ray_tpu, np, sched):
    """Cross-host object movement through the DATA plane (direct chunked
    pulls; reference object_manager.h:119). Fresh objects each round — the
    replica cache would otherwise short-circuit the transfer."""
    results = {}
    mb10 = 10 * 1024 * 1024

    @ray_tpu.remote(num_cpus=0.1, scheduling_strategy=sched)
    def touch(x):
        return x.nbytes

    @ray_tpu.remote(num_cpus=0.1, scheduling_strategy=sched)
    def produce(i):
        import numpy as _np

        return _np.zeros(1_310_720, dtype=_np.float64)  # 10 MiB

    # driver -> agent: put here, consume there
    times = []
    for i in range(8):
        ref = ray_tpu.put(np.full(1_310_720, float(i)))
        t0 = time.perf_counter()
        assert ray_tpu.get(touch.remote(ref), timeout=120) == mb10
        times.append(time.perf_counter() - t0)
    results["transfer_10mb_to_agent_gbps"] = mb10 / min(times) / 1e9

    # agent -> driver: produce there, get here
    refs = [produce.remote(i) for i in range(8)]
    ray_tpu.wait(refs, num_returns=len(refs), timeout=120)
    times = []
    for r in refs:
        t0 = time.perf_counter()
        ray_tpu.get(r, timeout=120)
        times.append(time.perf_counter() - t0)
    results["transfer_10mb_from_agent_gbps"] = mb10 / min(times) / 1e9
    return results


def main():
    import numpy as np

    import ray_tpu

    mode = sys.argv[1] if len(sys.argv) > 1 else "--all"
    out = {}

    ray_tpu.init(num_cpus=4, node_server_port=0,
                 worker_env={"JAX_PLATFORMS": "cpu"}, max_workers_per_node=8)
    out["local"] = suite(ray_tpu, np)

    if mode != "--local":
        from ray_tpu.core import global_state
        from ray_tpu.core.task_spec import NodeAffinitySchedulingStrategy

        cluster = global_state.try_cluster()
        agent = subprocess.Popen(
            [sys.executable, "-m", "ray_tpu.core.node_agent",
             "--address", f"127.0.0.1:{cluster.node_server_port}",
             "--num-cpus", "4"],
            env={**os.environ, "JAX_PLATFORMS": "cpu"})
        try:
            deadline = time.time() + 30
            while len([x for x in ray_tpu.nodes() if x["Alive"]]) < 2:
                assert time.time() < deadline, "agent never registered"
                time.sleep(0.2)
            remote_id = next(x["NodeID"] for x in ray_tpu.nodes()
                             if x["Alive"] and x["Labels"].get("agent") == "remote")
            sched = NodeAffinitySchedulingStrategy(node_id=remote_id)
            out["remote"] = suite(ray_tpu, np, sched=sched, n=1000,
                                  object_ops=False)
            out["remote"].update(transfer_suite(ray_tpu, np, sched))
        finally:
            agent.terminate()
            try:
                agent.wait(timeout=10)
            except subprocess.TimeoutExpired:
                agent.kill()
    ray_tpu.shutdown()

    for col, results in out.items():
        print(f"-- {col}")
        for k, v in results.items():
            print(f"  {k}: {v:,.0f}" if v > 100 else f"  {k}: {v:.2f}")
    with open(os.path.join(os.path.dirname(__file__) or ".", "CORE_BENCH.json"), "w") as f:
        json.dump({c: {k: round(v, 2) for k, v in r.items()}
                   for c, r in out.items()}, f, indent=2)
    print("wrote CORE_BENCH.json")


if __name__ == "__main__":
    main()
