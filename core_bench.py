"""Core-plane microbenchmark (reference python/ray/_private/ray_perf.py:95-317).

Measures the task/actor/object hot paths; writes CORE_BENCH.json with two
columns: "local" (in-process workers) and "remote" (tasks/actors dispatched
through a real node agent over TCP — the relay hop a multi-host pod pays —
plus 10MB object transfers in both directions over the data plane).
Run:
    JAX_PLATFORMS=cpu python core_bench.py            # both columns
    JAX_PLATFORMS=cpu python core_bench.py --local    # local only
    JAX_PLATFORMS=cpu python core_bench.py --collective
        # host-plane collective board-vs-ring wall clock -> COLLECTIVE_BENCH.json
    JAX_PLATFORMS=cpu python core_bench.py --transfer
        # data-plane pull sweep (1/10/100 MB x stripe counts)
        # -> TRANSFER_BENCH.json
    JAX_PLATFORMS=cpu python core_bench.py --telemetry-overhead [--dry-run]
        # enabled-vs-disabled telemetry delta on the 10 MB wire transfer and
        # the 16 MB W=4 ring allreduce; asserts the overhead stays under
        # RAY_TPU_TELEMETRY_OVERHEAD_PCT (default 3%) -> OBS_BENCH.json.
        # --dry-run skips cluster+timing (CI harness smoke check).
    JAX_PLATFORMS=cpu python core_bench.py --scrape-overhead [--dry-run]
        # metrics-history scraper on (aggressive 0.25s interval) vs off,
        # paired per-sample, on the 10 MB wire transfer; asserts the scraper
        # costs <= RAY_TPU_SCRAPE_OVERHEAD_PCT (default 1%). Appends the
        # "scrape_overhead" section to OBS_BENCH.json (telemetry rows kept).
    JAX_PLATFORMS=cpu python core_bench.py --control-plane [--dry-run]
        # synthetic 64/256/1024-replica fleet: per-worker vs node-delta head
        # merge cost and p99 of the full merge->record->SLO->autoscale tick;
        # gates RAY_TPU_CONTROL_P99_MS (250ms at N=1024) and
        # RAY_TPU_CONTROL_AGG_SPEEDUP (4x at N=256) -> CONTROL_BENCH.json.
    JAX_PLATFORMS=cpu python core_bench.py --head-chaos [--dry-run]
        # head-death survivability gate: SIGKILL a standalone head under
        # ~50 rps open-loop serve load with a concurrent collective train
        # run, restart it on the same ports, and gate on (1) zero failed
        # unary requests through the <=10s outage, (2) streaming requests
        # recover or fail TYPED (never hang), (3) the restarted head reaps
        # zero healthy nodes (same NodeID alive), (4) the train run
        # completes via abort/restart, (5) the serve autoscaling loop
        # resumes within 5 ticks of the restart -> HEAD_CHAOS_BENCH.json.
"""
import json
import os
import subprocess
import sys
import time

os.environ.setdefault("JAX_PLATFORMS", "cpu")


def timed(fn, n):
    t0 = time.perf_counter()
    fn()
    dt = time.perf_counter() - t0
    return n / dt


def suite(ray_tpu, np, sched=None, n=2000, object_ops=True):
    """The ray_perf measurement set; sched pins tasks/actors to one node.
    object_ops=False skips the put/get rows — they always run driver-local,
    so they'd be misleading in a remote (agent-dispatched) column."""
    results = {}

    def opt(r):
        return r.options(scheduling_strategy=sched) if sched is not None else r

    @ray_tpu.remote(num_cpus=0.1, max_retries=0)
    def nop():
        return None

    @ray_tpu.remote(num_cpus=0.1)
    class Counter:
        def nop(self):
            return None

        async def anop(self):
            return None

    # warm-up: spawn workers + import paths
    ray_tpu.get([opt(nop).remote() for _ in range(20)])

    results["tasks_per_s"] = timed(
        lambda: ray_tpu.get([opt(nop).remote() for _ in range(n)]), n)

    a = opt(Counter).remote()
    ray_tpu.get(a.nop.remote())
    results["actor_calls_per_s"] = timed(
        lambda: ray_tpu.get([a.nop.remote() for _ in range(n)]), n)

    results["actor_calls_sync_per_s"] = timed(
        lambda: [ray_tpu.get(a.nop.remote()) for _ in range(500)], 500)

    results["async_actor_calls_per_s"] = timed(
        lambda: ray_tpu.get([a.anop.remote() for _ in range(n)]), n)

    small = b"x" * 100
    if object_ops:
        results["put_small_per_s"] = timed(
            lambda: [ray_tpu.put(small) for _ in range(n)], n)

        refs = [ray_tpu.put(small) for _ in range(n)]
        results["get_small_per_s"] = timed(lambda: ray_tpu.get(refs), n)

        big = np.zeros(1_250_000, dtype=np.float64)  # 10 MB
        ray_tpu.put(big)  # warm the arena growth path
        put_times = []
        big_refs = []
        for _ in range(20):
            t0 = time.perf_counter()
            big_refs.append(ray_tpu.put(big))
            put_times.append(time.perf_counter() - t0)
        # best-of-N: byte throughput measures the copy path's capability; on a
        # loaded/few-core machine the median mostly measures scheduler
        # contention from the benchmark's own idle workers
        results["put_10mb_gbps"] = big.nbytes / min(put_times) / 1e9
        get_times = []
        for r in big_refs:
            t0 = time.perf_counter()
            ray_tpu.get(r)
            get_times.append(time.perf_counter() - t0)
        results["get_10mb_gbps"] = big.nbytes / min(get_times) / 1e9

    @ray_tpu.remote(num_cpus=0.1)
    def consume(x):
        return None

    arg_ref = ray_tpu.put(small)
    results["tasks_with_arg_per_s"] = timed(
        lambda: ray_tpu.get([opt(consume).remote(arg_ref) for _ in range(n)]), n)
    return results


def transfer_suite(ray_tpu, np, sched):
    """Cross-host object movement through the DATA plane (direct chunked
    pulls; reference object_manager.h:119). Fresh objects each round — the
    replica cache would otherwise short-circuit the transfer."""
    results = {}
    mb10 = 10 * 1024 * 1024

    @ray_tpu.remote(num_cpus=0.1, scheduling_strategy=sched)
    def touch(x):
        return x.nbytes

    @ray_tpu.remote(num_cpus=0.1, scheduling_strategy=sched)
    def produce(i):
        import numpy as _np

        return _np.zeros(1_310_720, dtype=_np.float64)  # 10 MiB

    # driver -> agent: put here, consume there
    times = []
    for i in range(8):
        ref = ray_tpu.put(np.full(1_310_720, float(i)))
        t0 = time.perf_counter()
        assert ray_tpu.get(touch.remote(ref), timeout=120) == mb10
        times.append(time.perf_counter() - t0)
    results["transfer_10mb_to_agent_gbps"] = mb10 / min(times) / 1e9

    # agent -> driver: produce there, get here
    refs = [produce.remote(i) for i in range(8)]
    ray_tpu.wait(refs, num_returns=len(refs), timeout=120)
    times = []
    for r in refs:
        t0 = time.perf_counter()
        ray_tpu.get(r, timeout=120)
        times.append(time.perf_counter() - t0)
    results["transfer_10mb_from_agent_gbps"] = mb10 / min(times) / 1e9
    return results


def transfer_sweep_suite(ray_tpu, np, sched):
    """Data-plane pull sweep: agent-resident objects of 1/10/100 MB pulled to
    the driver (the head's DataClient -> pull_to_store path). Two sections:

    - "wire": the striped zero-copy TCP path at stripe counts 1/2/4/8, with
      the same-host map shortcut disabled so bytes genuinely cross sockets
      (what two real hosts pay).
    - "mapped": the default same-host configuration, where the destination
      adopts the source's shm mapping in place (reference: one plasma store
      per node) — the path this single-host topology actually runs.

    Fresh objects every measurement — the replica cache would otherwise
    short-circuit the transfer. Knobs are env vars read at access time, so the
    sweep just toggles them between rounds."""
    sizes = [("1mb", 1 << 20), ("10mb", 10 << 20), ("100mb", 100 << 20)]
    stripe_counts = [1, 2, 4, 8]
    reps = {"1mb": 8, "10mb": 6, "100mb": 3}

    @ray_tpu.remote(num_cpus=0.1, scheduling_strategy=sched)
    def produce(nbytes, seed):
        import numpy as _np

        return _np.full(nbytes // 8, float(seed))

    def measure(label, nbytes):
        refs = [produce.remote(nbytes, i) for i in range(reps[label])]
        _, pending = ray_tpu.wait(refs, num_returns=len(refs), timeout=300)
        # a produce still running would fold task time into the timed get
        assert not pending, f"{len(pending)} produce tasks missed the deadline"
        times = []
        for r in refs:
            t0 = time.perf_counter()
            ray_tpu.get(r, timeout=300)
            times.append(time.perf_counter() - t0)
        return nbytes / min(times) / 1e9

    results = {"wire": {}, "mapped": {}}
    os.environ["RAY_TPU_TRANSFER_STRIPE_THRESHOLD_BYTES"] = str(512 * 1024)
    # 128 KiB floor so even the 1 MB rows genuinely split into all swept
    # stripe counts (the 2 MiB default would silently cap them at 1 stream)
    os.environ["RAY_TPU_TRANSFER_STRIPE_MIN_BYTES"] = str(128 * 1024)
    os.environ["RAY_TPU_TRANSFER_SAME_HOST_MAP"] = "0"
    try:
        for label, nbytes in sizes:
            row = {}
            for nstripes in stripe_counts:
                os.environ["RAY_TPU_TRANSFER_STRIPES"] = str(nstripes)
                row[f"stripes_{nstripes}_gbps"] = round(
                    measure(label, nbytes), 3)
            best = max(stripe_counts,
                       key=lambda n: row[f"stripes_{n}_gbps"])
            row["best_stripes"] = best
            row["speedup"] = round(
                row[f"stripes_{best}_gbps"] / row["stripes_1_gbps"], 2)
            results["wire"][label] = row
            print(f"  wire {label}: " + "  ".join(
                f"s{n}={row[f'stripes_{n}_gbps']:.2f}GB/s"
                for n in stripe_counts) + f"  ({row['speedup']:.2f}x)")
        os.environ.pop("RAY_TPU_TRANSFER_SAME_HOST_MAP", None)  # default: on
        os.environ.pop("RAY_TPU_TRANSFER_STRIPES", None)
        for label, nbytes in sizes:
            gbps = round(measure(label, nbytes), 3)
            results["mapped"][label] = {"gbps": gbps}
            print(f"  mapped {label}: {gbps:.2f} GB/s")
    finally:
        os.environ.pop("RAY_TPU_TRANSFER_STRIPE_THRESHOLD_BYTES", None)
        os.environ.pop("RAY_TPU_TRANSFER_STRIPE_MIN_BYTES", None)
        os.environ.pop("RAY_TPU_TRANSFER_STRIPES", None)
        os.environ.pop("RAY_TPU_TRANSFER_SAME_HOST_MAP", None)
    return results


def collective_suite(ray_tpu, np):
    """Host-plane allreduce wall clock: the legacy coordinator-board transport
    (every rank's full tensor through one actor, O(W^2) bytes through a single
    process) vs the data-plane ring (coordinator carries metadata only,
    tensor bytes move rank-to-rank chunked). Writes per-size seconds/op for
    world sizes 2 and 4 at 1/16/64 MB float32 payloads."""
    from ray_tpu.util import collective as col

    @ray_tpu.remote(num_cpus=0)
    class Member(col.CollectiveActorMixin):
        def __init__(self, rank):
            self.rank = rank

        def bench_allreduce(self, group, n_elems, iters):
            import numpy as _np
            import time as _time

            x = _np.full(n_elems, float(self.rank + 1), dtype=_np.float32)
            col.allreduce(x.copy(), group)  # warmup (plane dial, pools)
            t0 = _time.perf_counter()
            for _ in range(iters):
                col.allreduce(x.copy(), group)
            return (_time.perf_counter() - t0) / iters

    sizes = [("1mb", 1 << 20), ("16mb", 16 << 20), ("64mb", 64 << 20)]
    results = {}
    for world in (2, 4):
        members = [Member.remote(i) for i in range(world)]
        groups = {"board": 1 << 62, "ring": 0}
        for name, threshold in groups.items():
            col.create_collective_group(
                members, world, list(range(world)), backend="shm",
                group_name=f"bench_{name}_{world}",
                ring_threshold_bytes=threshold)
        col_res = {}
        for label, nbytes in sizes:
            n = nbytes // 4
            iters = 3 if nbytes <= (16 << 20) else 2
            row = {}
            for name in groups:
                per_rank = ray_tpu.get(
                    [m.bench_allreduce.remote(f"bench_{name}_{world}", n, iters)
                     for m in members], timeout=600)
                row[f"{name}_s"] = max(per_rank)  # op completes when ALL ranks do
            row["speedup"] = row["board_s"] / row["ring_s"]
            col_res[label] = row
            print(f"  w{world} {label}: board {row['board_s']:.3f}s  "
                  f"ring {row['ring_s']:.3f}s  ({row['speedup']:.2f}x)")
        results[f"world_{world}"] = col_res
        for name in groups:
            col.kill_coordinator(f"bench_{name}_{world}")
        for m in members:
            ray_tpu.kill(m)
    return results


def _overhead_threshold_pct() -> float:
    return float(os.environ.get("RAY_TPU_TELEMETRY_OVERHEAD_PCT", "3.0"))


def telemetry_overhead_suite(ray_tpu, np, sched):
    """Enabled-vs-disabled telemetry delta on the two hottest instrumented
    rows: a 10 MB forced-wire data-plane pull (agent -> driver) and a 16 MB
    W=4 ring allreduce. Times are best-of-N (the copy path's capability —
    the median would mostly measure benchmark-machine noise), and the
    telemetry toggle flips in-process via util.telemetry.enable()/disable()
    (member actors flip their own processes), so both rounds run the same
    cluster, pools, and jit caches.

    Coverage split: the transfer row toggles the CLIENT-side instrumentation
    (the node-agent's serving process keeps its spawn-time env, so its
    per-serve event stays off in both samples); the allreduce row covers the
    SERVER side too — every ring chunk is served by a member-hosted
    collective-plane DataServer, and the members toggle with set_telemetry."""
    from ray_tpu.util import collective as col
    from ray_tpu.util import telemetry

    mb10 = 10 * 1024 * 1024

    @ray_tpu.remote(num_cpus=0.1, scheduling_strategy=sched)
    def produce(i):
        import numpy as _np

        return _np.full(1_310_720, float(i))  # 10 MiB

    def measure_transfer_pair(reps=8):
        """Paired per-get design: alternate telemetry off/on between
        consecutive gets of identical fresh objects, so both sides sample the
        SAME machine state — batching whole off/then-on rounds was measured
        to carry 4-8% of ordering bias, 50x the actual instrumentation cost."""
        refs = [produce.remote(i) for i in range(2 * reps)]
        _, pending = ray_tpu.wait(refs, num_returns=len(refs), timeout=300)
        assert not pending, "produce tasks missed the deadline"
        pairs, cur = [], None
        for i, r in enumerate(refs):
            on = i % 2 == 1
            telemetry.enable() if on else telemetry.disable()
            t0 = time.perf_counter()
            ray_tpu.get(r, timeout=300)
            dt = time.perf_counter() - t0
            if on:
                pairs.append((cur, dt))
            else:
                cur = dt
        return pairs

    @ray_tpu.remote(num_cpus=0)
    class Member(col.CollectiveActorMixin):
        def __init__(self, rank):
            self.rank = rank

        def set_telemetry(self, on: bool):
            from ray_tpu.util import telemetry as _t

            _t.enable() if on else _t.disable()
            return True

        def bench_allreduce(self, group, n_elems, iters):
            import numpy as _np
            import time as _time

            x = _np.full(n_elems, float(self.rank + 1), dtype=_np.float32)
            col.allreduce(x.copy(), group)  # warmup (plane dial, pools)
            best = float("inf")
            for _ in range(iters):
                t0 = _time.perf_counter()
                col.allreduce(x.copy(), group)
                best = min(best, _time.perf_counter() - t0)
            return best

    world, group = 4, "obs_overhead"
    members = [Member.remote(i) for i in range(world)]
    col.create_collective_group(members, world, list(range(world)),
                                backend="shm", group_name=group,
                                ring_threshold_bytes=0)
    n_elems = (16 << 20) // 4

    def measure_allreduce_once():
        # min-of-3 ops per sample: a single 16 MB op's wall time swings ±25%
        # on a loaded 1-core box (actor scheduling), which would drown the
        # per-pair delta the gate medians over
        per_rank = ray_tpu.get(
            [m.bench_allreduce.remote(group, n_elems, 3) for m in members],
            timeout=600)
        return max(per_rank)  # the op completes when ALL ranks do

    def set_everywhere(on: bool):
        ray_tpu.get([m.set_telemetry.remote(on) for m in members], timeout=60)
        telemetry.enable() if on else telemetry.disable()

    def measure_allreduce_pair(npairs=7):
        """Same paired design as the transfer row: one off op, one on op,
        back to back, per pair (the toggle round-trips are outside the
        per-op timing inside bench_allreduce)."""
        pairs, cur = [], None
        for i in range(2 * npairs):
            on = i % 2 == 1
            set_everywhere(on)
            a = measure_allreduce_once()
            if on:
                pairs.append((cur, a))
            else:
                cur = a
        return pairs

    rows = {}
    try:
        # force the wire path: the mapped shortcut copies nothing, so it
        # cannot show (or hide) instrumentation cost
        os.environ["RAY_TPU_TRANSFER_SAME_HOST_MAP"] = "0"
        set_everywhere(False)
        measure_transfer_pair(reps=1)  # warm pools/paths outside the timing
        measure_allreduce_once()
        t_pairs = measure_transfer_pair()
        a_pairs = measure_allreduce_pair()
    finally:
        os.environ.pop("RAY_TPU_TRANSFER_SAME_HOST_MAP", None)
        try:
            # dead members would block this get for 60s and mask the real
            # error; cleanup below must run regardless
            set_everywhere(False)
        except Exception:
            pass
        # AFTER set_everywhere: that call re-forces the driver's flag, and the
        # intended end state is env-driven, not force-disabled
        telemetry.reset_forced()
        col.kill_coordinator(group)
        for m in members:
            try:
                ray_tpu.kill(m)
            except Exception:
                pass

    def row(label, pairs, nbytes):
        """Overhead = MEDIAN of per-pair deltas: each pair samples the same
        machine state back to back, and the median is robust to the ±10-15%
        single-sample swings a 1-core box shows (min-vs-min amplified them)."""
        import statistics

        overhead = statistics.median(
            (on - off) / off * 100.0 for off, on in pairs)
        off_s, on_s = min(p[0] for p in pairs), min(p[1] for p in pairs)
        r = {
            "disabled_s": round(off_s, 6), "enabled_s": round(on_s, 6),
            "disabled_gbps": round(nbytes / off_s / 1e9, 3),
            "enabled_gbps": round(nbytes / on_s / 1e9, 3),
            "pairs": len(pairs),
            "overhead_pct": round(overhead, 2),
        }
        rows[label] = r
        print(f"  {label}: off={off_s * 1e3:.1f}ms on={on_s * 1e3:.1f}ms "
              f"(median pair delta {overhead:+.2f}%)")
        return overhead

    o1 = row("transfer_10mb_wire", t_pairs, mb10)
    o2 = row("allreduce_16mb_w4", a_pairs, 16 << 20)
    threshold = _overhead_threshold_pct()
    # the assert lives in main(), AFTER the JSON is written: a failing gate
    # must still leave the evidence on disk
    return {"rows": rows, "threshold_pct": threshold,
            "max_overhead_pct": round(max(o1, o2), 2),
            "passed": max(o1, o2) <= threshold}


def _scrape_overhead_threshold_pct() -> float:
    return float(os.environ.get("RAY_TPU_SCRAPE_OVERHEAD_PCT", "1.0"))


def scrape_overhead_suite(ray_tpu, np, sched):
    """Scraper-on vs scraper-off delta on the 10 MB forced-wire pull
    (agent -> driver), the hottest CORE_BENCH transfer row. The scraper runs
    in THIS (head) process, so the toggle is just the interval env var —
    scraper_loop re-reads it every tick: "off" parks the thread, "on" scrapes
    every 0.25 s (20x the default cadence, adversarial on purpose).

    Estimator: the scraper adds NO per-pull code — it can only cost through
    background CPU/GIL competition while a scrape overlaps a pull. Direct
    paired off/on pull timing cannot resolve that: measured null experiments
    (scraper fully off on BOTH sides) showed +1-10% position/ordering bias,
    1000x the scraper's real cost, so a 1% gate on raw pair deltas is a coin
    flip. Instead the suite measures the interference channel where it is
    actually visible and scales it by exposure:

      stress_delta   pull slowdown with a thread scraping CONTINUOUSLY
                     (100% duty — a worst case far beyond any real cadence),
                     min-over-N against interleaved plain pulls
      duty_cycle     measured scrape wall time / the 0.25s adversarial
                     interval (20x the default cadence)
      overhead       max(stress_delta, 0) * duty + duty  — what continuous-
                     scraping interference costs at the real exposure, plus
                     the scraper's own CPU share

    Both factors are measured, the extrapolation is linear in exposure, and
    the raw off/on pair delta is still reported as a diagnostic."""
    import statistics
    import threading

    mb10 = 10 * 1024 * 1024
    scrape_interval_s = 0.25

    @ray_tpu.remote(num_cpus=0.1, scheduling_strategy=sched)
    def produce(i):
        import numpy as _np

        return _np.full(1_310_720, float(i))  # 10 MiB

    from ray_tpu.core import global_state

    cluster = global_state.try_cluster()

    def measure_min(refs):
        times = []
        for r in refs:
            t0 = time.perf_counter()
            ray_tpu.get(r, timeout=300)
            times.append(time.perf_counter() - t0)
        return min(times)

    def fresh(n):
        refs = [produce.remote(i) for i in range(n)]
        _, pending = ray_tpu.wait(refs, num_returns=n, timeout=300)
        assert not pending, "produce tasks missed the deadline"
        return refs

    stress_stop = threading.Event()

    def stress_loop():
        while not stress_stop.is_set():
            cluster._scrape_merged_metrics()

    try:
        # force the wire path — the mapped shortcut copies nothing, so it
        # could neither show nor hide scraper interference
        os.environ["RAY_TPU_TRANSFER_SAME_HOST_MAP"] = "0"
        os.environ["RAY_TPU_METRICS_SCRAPE_INTERVAL_S"] = "0"
        measure_min(fresh(2))  # warm pools/paths outside the timing

        # plain / stressed / plain: the bracketing plain rounds absorb drift
        plain_a = measure_min(fresh(8))
        stress_thread = threading.Thread(target=stress_loop, daemon=True)
        stress_thread.start()
        try:
            stressed = measure_min(fresh(8))
        finally:
            stress_stop.set()
            stress_thread.join(timeout=10)
        plain_b = measure_min(fresh(8))
        plain = min(plain_a, plain_b)
        stress_delta_pct = (stressed - plain) / plain * 100.0

        # the scrape's own wall time against the live registries (driver +
        # every pushed worker snapshot)
        scrape_times = []
        for _ in range(50):
            t0 = time.perf_counter()
            cluster._scrape_merged_metrics()
            scrape_times.append(time.perf_counter() - t0)
        scrape_ms = statistics.median(scrape_times) * 1e3

        # diagnostic only: raw interleaved off/on pairs at the adversarial
        # cadence (noise floor documented above)
        refs = fresh(12)
        pair_deltas, cur = [], None
        for i, r in enumerate(refs):
            on = i % 2 == 1
            os.environ["RAY_TPU_METRICS_SCRAPE_INTERVAL_S"] = (
                str(scrape_interval_s) if on else "0")
            t0 = time.perf_counter()
            ray_tpu.get(r, timeout=300)
            dt = time.perf_counter() - t0
            if on:
                pair_deltas.append((dt - cur) / cur * 100.0)
            else:
                cur = dt
    finally:
        os.environ.pop("RAY_TPU_TRANSFER_SAME_HOST_MAP", None)
        os.environ.pop("RAY_TPU_METRICS_SCRAPE_INTERVAL_S", None)

    duty = scrape_ms / (scrape_interval_s * 1e3)
    overhead = max(stress_delta_pct, 0.0) * duty + duty * 100.0
    threshold = _scrape_overhead_threshold_pct()
    row = {
        "plain_s": round(plain, 6),
        "stressed_s": round(stressed, 6),
        "plain_gbps": round(mb10 / plain / 1e9, 3),
        "stressed_gbps": round(mb10 / stressed / 1e9, 3),
        "stress_delta_pct": round(stress_delta_pct, 2),
        "scrape_cost_ms": round(scrape_ms, 4),
        "scrape_interval_s": scrape_interval_s,
        "duty_cycle_pct": round(duty * 100.0, 4),
        "median_pair_delta_pct": round(statistics.median(pair_deltas), 2),
        "frames_scraped": len(cluster.metrics_history),
        "overhead_pct": round(overhead, 4),
    }
    print(f"  transfer_10mb_wire: plain={plain * 1e3:.1f}ms "
          f"stressed(100% duty)={stressed * 1e3:.1f}ms "
          f"({stress_delta_pct:+.2f}%), scrape {scrape_ms:.3f}ms @ "
          f"{scrape_interval_s}s -> duty {duty * 100:.4f}%, "
          f"overhead {overhead:.4f}% (diag median pair "
          f"{row['median_pair_delta_pct']:+.2f}%)")
    return {"rows": {"transfer_10mb_wire": row}, "threshold_pct": threshold,
            "max_overhead_pct": round(overhead, 4),
            "passed": overhead <= threshold}


def _control_p99_ms() -> float:
    return float(os.environ.get("RAY_TPU_CONTROL_P99_MS", "250.0"))


def _control_agg_speedup() -> float:
    return float(os.environ.get("RAY_TPU_CONTROL_AGG_SPEEDUP", "4.0"))


def control_plane_suite():
    """Head-side control-plane cost at synthetic fleet scale (64 / 256 / 1024
    replicas, 8 deployments of shared series plus per-process series). No
    cluster: the suite builds the exact byte streams the head would receive
    and times the head's own code paths, so the numbers isolate control-plane
    arithmetic from scheduler noise.

    Two measurements per fleet size:

    - aggregation: the recurring scrape-tick merge — the head stores DECODED
      snapshots at receive time (node._handle_message / _on_node_metrics),
      so every scrape pays merge_snapshots over the stored lists: N worker
      lists on the legacy path vs N/8 node lists on the delta path (the
      shared deployment-tagged series collapsed at the agents). Gate: node
      path >= RAY_TPU_CONTROL_AGG_SPEEDUP (default 4x) cheaper at N=256.
      Ingest decode (pickle.loads per worker frame vs json.loads +
      snapshot_from_wire per node delta — paid per arrival, N vs N/8 frames
      per interval) is reported separately. Merged counter totals are
      asserted identical across both paths — aggregation may not change
      the answer.

    - decision chain: merge -> history.record -> SLOEngine.evaluate (24 SLOs:
      latency/error-rate/gauge per deployment) -> AutoscalePolicy.decide per
      deployment, the full per-scrape control tick. Gate: p99 tick latency at
      N=1024 <= RAY_TPU_CONTROL_P99_MS (default 250 ms). CPU share of the
      tick is reported via time.process_time().

    RAY_TPU_CONTROL_MAX_SERIES is raised explicitly for the run: at N=1024
    the per-process series alone exceed the default 1024 cap, and a capped
    merge would silently shrink the work being timed."""
    import pickle

    from ray_tpu.serve.autoscaler import AutoscalePolicy, DeploymentSnapshot
    from ray_tpu.util import metrics as M
    from ray_tpu.util.metrics_history import MetricsHistory
    from ray_tpu.util.slo import SLO, SLOEngine

    ndep, per_node = 8, 8
    deps = [f"bench/d{j}" for j in range(ndep)]
    bounds = [0.05, 0.1, 0.25, 0.5, 1.0, 2.5]

    def key(dep):
        return (("deployment", dep),)

    def worker_snapshot(wid: int, step: int):
        """What one worker's registry ships: deployment-tagged serve series
        (shared across the fleet — they collapse under node aggregation) plus
        per-process series (distinct keys, survive aggregation). Values are
        deterministic in (wid, step) so every run times identical work."""
        base = float(step + 1)
        hval = {}
        for j in range(ndep):
            buckets = [(wid + j + i + step) % 5 + 1 for i in range(len(bounds) + 1)]
            n = sum(buckets)
            hval[key(deps[j])] = {"buckets": buckets, "sum": 0.21 * n, "count": n}
        proc = (("proc", f"w{wid:05d}"),)
        return [
            {"name": "serve_requests_total", "type": "counter", "description": "",
             "values": {key(d): base * (10 + j + wid % 5)
                        for j, d in enumerate(deps)}},
            {"name": "serve_errors_total", "type": "counter", "description": "",
             "values": {key(d): base * (j % 3) for j, d in enumerate(deps)}},
            {"name": "serve_queue_depth", "type": "gauge", "description": "",
             "values": {key(d): float((wid + j) % 7) for j, d in enumerate(deps)}},
            {"name": "serve_ttft_seconds", "type": "histogram", "description": "",
             "boundaries": bounds, "values": hval},
            {"name": "worker_rss_bytes", "type": "gauge", "description": "",
             "values": {proc: 1e8 + wid}},
            {"name": "worker_task_seconds", "type": "histogram", "description": "",
             "boundaries": bounds,
             "values": {proc: {"buckets": [step + 1] * (len(bounds) + 1),
                               "sum": 0.1 * (step + 1),
                               "count": (step + 1) * (len(bounds) + 1)}}},
        ]

    def node_blobs_for(snaps):
        """Agent-side pre-aggregation: merge each node's 8 workers, encode as
        the JSON wire delta node_agent._flush_node_delta ships."""
        blobs = []
        for i in range(0, len(snaps), per_node):
            merged = M.merge_snapshots(snaps[i:i + per_node])
            blobs.append(json.dumps(M.snapshot_to_wire(list(merged.values()))).encode())
        return blobs

    def ingest_per_worker(blobs):
        return [pickle.loads(b) for b in blobs]

    def ingest_node(blobs):
        return [M.snapshot_from_wire(json.loads(b)) for b in blobs]

    def best_of(fn, reps):
        best, out = float("inf"), None
        for _ in range(reps):
            t0 = time.perf_counter()
            out = fn()
            best = min(best, time.perf_counter() - t0)
        return best, out

    def counter_total(merged, name):
        return sum(merged[name]["values"].values())

    def decision_chain(n, iters):
        """p50/p99 of the full per-scrape control tick at fleet size n."""
        history = MetricsHistory(maxlen=256)
        engine = SLOEngine(history)
        for j, d in enumerate(deps):
            engine.register(SLO(f"ttft-{j}", metric="serve_ttft_seconds",
                                objective=0.99, threshold=0.5, window_s=15.0,
                                where={"deployment": d}))
            engine.register(SLO(f"err-{j}", metric="serve_errors_total",
                                objective=0.999, window_s=15.0,
                                total_metric="serve_requests_total",
                                kind="error_rate", where={"deployment": d}))
            engine.register(SLO(f"queue-{j}", metric="serve_queue_depth",
                                objective=0.9, threshold=16.0, kind="gauge",
                                window_s=15.0, where={"deployment": d}))
        policy = AutoscalePolicy()
        lat, cpu_s, wall_s = [], 0.0, 0.0
        ts0 = 1_000_000.0
        for step in range(iters):
            snaps = [worker_snapshot(w, step) for w in range(n)]
            # agent-side pre-merge and head receive-path decode both happen
            # outside the scrape tick being timed
            stored = ingest_node(node_blobs_for(snaps))
            ts = ts0 + step  # 1 s scrape cadence
            c0, t0 = time.process_time(), time.perf_counter()
            merged = M.merge_snapshots(stored)
            history.record(merged, ts=ts)
            status = engine.evaluate()
            for j, d in enumerate(deps):
                burning = any(status[f"{k}-{j}"].get("state") == "burning"
                              for k in ("ttft", "err", "queue"))
                depth = merged["serve_queue_depth"]["values"].get(key(d), 0.0)
                policy.decide(DeploymentSnapshot(
                    key=d, target=4, running=4, starting=0, draining=0,
                    min_replicas=1, max_replicas=64, queue_depth=depth,
                    queue_target=4.0, burning=burning, now=ts))
            dt = time.perf_counter() - t0
            lat.append(dt)
            wall_s += dt
            cpu_s += time.process_time() - c0
        lat.sort()
        return {
            "decision_p50_ms": round(lat[len(lat) // 2] * 1e3, 3),
            "decision_p99_ms": round(lat[int(0.99 * (len(lat) - 1))] * 1e3, 3),
            "decision_ticks": iters,
            "cpu_s_per_tick": round(cpu_s / iters, 6),
            "cpu_share_pct": round(cpu_s / wall_s * 100.0, 1),
        }

    fleets = {}
    os.environ["RAY_TPU_CONTROL_MAX_SERIES"] = "1000000"
    try:
        for n in (64, 256, 1024):
            snaps = [worker_snapshot(w, 0) for w in range(n)]
            worker_blobs = [pickle.dumps(s) for s in snaps]
            node_blobs = node_blobs_for(snaps)
            reps = 5 if n <= 256 else 3
            t_agent, _ = best_of(lambda: node_blobs_for(snaps), 1)
            t_ing_pw, stored_pw = best_of(lambda: ingest_per_worker(worker_blobs), reps)
            t_ing_nd, stored_nd = best_of(lambda: ingest_node(node_blobs), reps)
            t_pw, m_pw = best_of(lambda: M.merge_snapshots(stored_pw), reps)
            t_nd, m_nd = best_of(lambda: M.merge_snapshots(stored_nd), reps)
            for name in ("serve_requests_total", "serve_errors_total"):
                a, b = counter_total(m_pw, name), counter_total(m_nd, name)
                assert abs(a - b) < 1e-6 * max(1.0, a), (
                    f"aggregation changed {name} at N={n}: {a} != {b}")
            row = {
                "nodes": n // per_node,
                "scrape_merge_per_worker_ms": round(t_pw * 1e3, 3),
                "scrape_merge_node_delta_ms": round(t_nd * 1e3, 3),
                "ingest_per_worker_ms_per_interval": round(t_ing_pw * 1e3, 3),
                "ingest_node_delta_ms_per_interval": round(t_ing_nd * 1e3, 3),
                "agent_premerge_ms_per_node": round(
                    t_agent * 1e3 / (n // per_node), 3),
                "agg_speedup": round(t_pw / t_nd, 2),
                "wire_bytes_per_worker": sum(map(len, worker_blobs)) // n,
                "wire_bytes_per_node": sum(map(len, node_blobs)) // (n // per_node),
                "merged_series": sum(len(m["values"]) for m in m_nd.values()),
            }
            row.update(decision_chain(n, iters=40 if n <= 256 else 25))
            fleets[str(n)] = row
            print(f"  N={n}: scrape merge per-worker="
                  f"{row['scrape_merge_per_worker_ms']:.1f}ms "
                  f"node-delta={row['scrape_merge_node_delta_ms']:.1f}ms "
                  f"({row['agg_speedup']:.1f}x)  decision p50={row['decision_p50_ms']:.1f}ms "
                  f"p99={row['decision_p99_ms']:.1f}ms "
                  f"(cpu {row['cpu_share_pct']:.0f}%)")
    finally:
        os.environ.pop("RAY_TPU_CONTROL_MAX_SERIES", None)

    p99_gate, agg_gate = _control_p99_ms(), _control_agg_speedup()
    gates = {
        "p99_ms_at_1024": fleets["1024"]["decision_p99_ms"],
        "p99_threshold_ms": p99_gate,
        "p99_passed": fleets["1024"]["decision_p99_ms"] <= p99_gate,
        "agg_speedup_at_256": fleets["256"]["agg_speedup"],
        "agg_speedup_threshold": agg_gate,
        "agg_passed": fleets["256"]["agg_speedup"] >= agg_gate,
    }
    return {
        "workers_per_node": per_node, "deployments": ndep,
        "slos_registered": 3 * ndep, "fleets": fleets, "gates": gates,
        "passed": gates["p99_passed"] and gates["agg_passed"],
    }


def _write_telemetry_obs_bench(out_path: str, result: dict) -> None:
    """The telemetry gate keeps its historical top-level schema (rows/
    threshold_pct/...); carry the scrape-overhead section across the rewrite
    so the two gates sharing OBS_BENCH.json don't clobber each other."""
    if os.path.exists(out_path):
        try:
            with open(out_path) as f:
                prev = json.load(f)
            if "scrape_overhead" in prev:
                result = {**result, "scrape_overhead": prev["scrape_overhead"]}
        except Exception:
            pass
    with open(out_path, "w") as f:
        json.dump(result, f, indent=2)


def _update_obs_bench(out_path: str, section: str, result: dict) -> None:
    """Merge one gate section into OBS_BENCH.json without clobbering the
    other gates' evidence (telemetry-overhead and scrape-overhead share the
    file)."""
    doc = {}
    if os.path.exists(out_path):
        try:
            with open(out_path) as f:
                doc = json.load(f)
        except Exception:
            doc = {}
    doc[section] = result
    with open(out_path, "w") as f:
        json.dump(doc, f, indent=2)


def _spawn_remote_agent(ray_tpu):
    """Start a real node agent on localhost and return (proc, sched) — the
    relay hop a multi-host pod pays, used by the remote/transfer columns."""
    from ray_tpu.core import global_state
    from ray_tpu.core.task_spec import NodeAffinitySchedulingStrategy

    cluster = global_state.try_cluster()
    agent = subprocess.Popen(
        [sys.executable, "-m", "ray_tpu.core.node_agent",
         "--address", f"127.0.0.1:{cluster.node_server_port}",
         "--num-cpus", "4"],
        env={**os.environ, "JAX_PLATFORMS": "cpu"})
    try:
        deadline = time.time() + 30
        while len([x for x in ray_tpu.nodes() if x["Alive"]]) < 2:
            assert time.time() < deadline, "agent never registered"
            time.sleep(0.2)
        remote_id = next(x["NodeID"] for x in ray_tpu.nodes()
                         if x["Alive"] and x["Labels"].get("agent") == "remote")
    except BaseException:
        agent.terminate()
        raise
    return agent, NodeAffinitySchedulingStrategy(node_id=remote_id)


# ------------------------------------------------------------- head chaos

def _chaos_free_port():
    import socket

    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    p = s.getsockname()[1]
    s.close()
    return p


def _chaos_spawn_head(env, node_port, client_port):
    head_main = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                             "tests", "_head_main.py")
    proc = subprocess.Popen(
        [sys.executable, head_main, str(node_port), str(client_port), "0"],
        env=env, stdout=subprocess.PIPE, text=True)
    deadline = time.time() + 60
    while True:
        line = proc.stdout.readline()
        if "HEAD_READY" in line:
            return proc
        assert proc.poll() is None and time.time() < deadline, \
            "head never started"


class _ChaosUnaryLoad:
    """Open-loop unary load through a DeploymentHandle: one thread per
    request on a fixed schedule, so a stalled request never suppresses the
    offered rate (the property that makes 'zero failed through the outage'
    a real claim and not an artifact of closed-loop backoff)."""

    def __init__(self, handle, rps, duration_s, timeout_s):
        import threading

        self.handle = handle
        self.rps = rps
        self.duration_s = duration_s
        self.timeout_s = timeout_s
        self.results = []  # (t_offered_rel, ok, dur_s, err_type)
        self._lock = threading.Lock()
        self._threads = []
        self.t0 = None

    def _one(self, i):
        t = time.perf_counter()
        try:
            v = self.handle.remote(i).result(timeout_s=self.timeout_s)
            ok, err = (v == i), (None if v == i else "wrong-value")
        except Exception as e:  # noqa: BLE001 — the gate classifies failures
            ok, err = False, type(e).__name__
        with self._lock:
            self.results.append((t - self.t0, ok, time.perf_counter() - t, err))

    def run(self):
        import threading

        self.t0 = time.perf_counter()
        end = self.t0 + self.duration_s
        i = 0
        while True:
            now = time.perf_counter()
            if now >= end:
                break
            target = self.t0 + i / self.rps
            if now < target:
                time.sleep(min(target - now, 0.05))
                continue
            th = threading.Thread(target=self._one, args=(i,), daemon=True)
            th.start()
            self._threads.append(th)
            i += 1

    def join(self, timeout_s):
        deadline = time.time() + timeout_s
        for th in self._threads:
            th.join(timeout=max(0.1, deadline - time.time()))
        return sum(1 for th in self._threads if th.is_alive())


def _chaos_stream_probe(handle, record):
    """One streaming request spanning the outage: counts chunks and
    classifies the ending — completed, typed failure, untyped failure."""
    from ray_tpu.core.exceptions import RayTpuError

    got = 0
    try:
        for v in handle.options(stream=True).stream_nums.remote(60):
            got = v + 1
        record.update(outcome="completed", chunks=got)
    except RayTpuError as e:
        record.update(outcome=f"typed:{type(e).__name__}", chunks=got)
    except Exception as e:  # noqa: BLE001 — untyped failure FAILS the gate
        record.update(outcome=f"untyped:{type(e).__name__}", chunks=got)


def _chaos_train_run(ray_tpu, record):
    """The PR 3 abort/restart choreography as a driver loop: collective train
    workers step through the outage; any failure (abort verdict, stalled get,
    head loss) tears the group down and restarts from scratch."""

    @ray_tpu.remote(num_cpus=1, max_restarts=0)
    class TrainMember:
        def __init__(self, rank):
            self.rank = rank

        def _ray_tpu_collective_init(self, world_size, rank, backend,
                                     group_name):
            from ray_tpu.util import collective as col

            col.init_collective_group(world_size, rank, backend, group_name)

        def run(self, group_name, steps, sleep_s):
            import numpy as np

            from ray_tpu.util import collective as col

            total = 0.0
            for _ in range(steps):
                x = np.full((64,), float(self.rank + 1), dtype=np.float32)
                total = float(col.allreduce(x, group_name)[0])
                time.sleep(sleep_s)
            return total

    from ray_tpu.util import collective as col

    record.update(completed=False, attempts=0, errors=[])
    for attempt in range(1, 5):
        record["attempts"] = attempt
        gname = f"head-chaos-train-{attempt}"
        ws = []
        try:
            ws = [TrainMember.remote(r) for r in range(2)]
            col.create_collective_group(ws, 2, [0, 1], group_name=gname)
            refs = [w.run.remote(gname, 45, 0.2) for w in ws]
            vals = ray_tpu.get(refs, timeout=120)
            assert all(v == 3.0 for v in vals), vals  # sum of ranks 1+2
            record.update(completed=True, values=vals)
            return
        except Exception as e:  # noqa: BLE001 — abort/restart: tear down, retry
            record["errors"].append(f"attempt {attempt}: {type(e).__name__}")
            for w in ws:
                try:
                    ray_tpu.kill(w, no_restart=True)
                except Exception:  # noqa: BLE001 — worker may be gone already
                    pass
            time.sleep(1.0)


def head_chaos_suite(*, rps=50.0, warm_s=4.0, outage_s=6.0, post_s=18.0,
                     autoscale_tick_s=1.0):
    """SIGKILL the head under load, restart it on the same ports, and measure
    what the outage cost. Topology: standalone zero-CPU head (control plane
    only), one node agent carrying every replica/worker, this process as the
    client driver — so the head really is just the control plane, and killing
    it tests exactly the degraded-mode + reattach machinery."""
    import shutil
    import tempfile
    import threading

    import ray_tpu
    from ray_tpu.util.fault_injection import ChaosController

    tmp = tempfile.mkdtemp(prefix="head_chaos_")
    repo_root = os.path.dirname(os.path.abspath(__file__))
    env = {**os.environ,
           "JAX_PLATFORMS": "cpu",
           "PYTHONPATH": repo_root + os.pathsep + os.environ.get("PYTHONPATH", ""),
           "RAY_TPU_SESSION_DIR": os.path.join(tmp, "session"),
           "RAY_TPU_GCS_PERSISTENCE_PATH": os.path.join(tmp, "gcs.journal"),
           "RAY_TPU_AGENT_RECONNECT_TIMEOUT_S": "60",
           "RAY_TPU_SERVE_AUTOSCALE_INTERVAL_S": str(autoscale_tick_s)}
    saved = {k: os.environ.get(k) for k in
             ("RAY_TPU_SESSION_DIR", "RAY_TPU_GCS_PERSISTENCE_PATH",
              "RAY_TPU_HEAD_RECONNECT_TIMEOUT_S")}
    os.environ.update({k: env[k] for k in
                       ("RAY_TPU_SESSION_DIR", "RAY_TPU_GCS_PERSISTENCE_PATH")})
    # the driver must ride through the outage, not give up mid-restart
    os.environ["RAY_TPU_HEAD_RECONNECT_TIMEOUT_S"] = "45"
    procs = []
    result = {"topology": {"rps": rps, "warm_s": warm_s,
                           "planned_outage_s": outage_s, "post_s": post_s}}
    try:
        node_port, client_port = _chaos_free_port(), _chaos_free_port()
        head = _chaos_spawn_head(env, node_port, client_port)
        procs.append(head)
        agent = subprocess.Popen(
            [sys.executable, "-m", "ray_tpu.core.node_agent",
             "--address", f"127.0.0.1:{node_port}", "--num-cpus", "8"],
            env=env)
        procs.append(agent)

        ray_tpu.init(address=f"ray-tpu://127.0.0.1:{client_port}")
        deadline = time.time() + 30
        while len([n for n in ray_tpu.nodes() if n["Alive"]]) < 2:
            assert time.time() < deadline, "agent never joined"
            time.sleep(0.2)
        node_id_before = next(n["NodeID"] for n in ray_tpu.nodes()
                              if n["Alive"] and n["Labels"].get("agent") == "remote")

        from ray_tpu import serve

        @serve.deployment
        class Echo:
            def __call__(self, x):
                time.sleep(0.01)
                return x

            def stream_nums(self, n):
                for i in range(n):
                    time.sleep(0.15)
                    yield i

        handle = serve.run(
            Echo.options(num_replicas=2, max_ongoing_requests=8).bind(),
            name="head-chaos", route_prefix="/head-chaos")
        # warm: the view, the limits cache, and both replicas
        deadline = time.time() + 30
        while True:
            try:
                assert handle.remote(-1).result(timeout_s=5) == -1
                break
            except Exception:  # noqa: BLE001 — replicas still starting
                assert time.time() < deadline, "serve app never came up"
                time.sleep(0.5)

        duration = warm_s + outage_s + post_s
        load = _ChaosUnaryLoad(handle, rps, duration, timeout_s=60.0)
        load_thread = threading.Thread(target=load.run, daemon=True)
        train_rec, stream_rec = {}, {}
        train_thread = threading.Thread(
            target=_chaos_train_run, args=(ray_tpu, train_rec), daemon=True)
        load_thread.start()
        train_thread.start()
        time.sleep(warm_s * 0.75)
        stream_thread = threading.Thread(
            target=_chaos_stream_probe, args=(handle, stream_rec), daemon=True)
        stream_thread.start()
        time.sleep(warm_s * 0.25)

        # -- the kill ---------------------------------------------------------
        t_kill = time.perf_counter()
        ChaosController.kill_head(head)
        head.wait(timeout=10)
        time.sleep(outage_s)
        head2 = _chaos_spawn_head(env, node_port, client_port)
        procs.append(head2)
        t_restart = time.perf_counter()
        result["measured_outage_s"] = round(t_restart - t_kill, 2)

        # autoscaler resumption: the reattach of SERVE_CONTROLLER restarts
        # the head-side loop; it must tick within 5 intervals of the restart
        resumed_s = None
        deadline = time.time() + 30
        while time.time() < deadline:
            try:
                from ray_tpu.util.state import serve_autoscaler_status

                st = serve_autoscaler_status()
                if st.get("alive") and st.get("ticks", 0) > 0:
                    resumed_s = time.perf_counter() - t_restart
                    break
            except Exception:  # noqa: BLE001 — client itself reconnecting
                pass
            time.sleep(0.25)
        result["autoscaler_resumed_s"] = (
            None if resumed_s is None else round(resumed_s, 2))

        load_thread.join(timeout=duration + 30)
        hung_unary = load.join(timeout_s=90)
        stream_thread.join(timeout=90)
        if not stream_rec:
            stream_rec["outcome"] = "hang"
        train_thread.join(timeout=180)

        nodes_after = [n for n in ray_tpu.nodes()
                       if n["Alive"] and n["Labels"].get("agent") == "remote"]
        failed = [r for r in load.results if not r[1]]
        result.update({
            "unary": {
                "offered": len(load.results) + hung_unary,
                "completed": sum(1 for r in load.results if r[1]),
                "failed": len(failed),
                "hung": hung_unary,
                "failure_types": sorted({r[3] for r in failed}),
                "max_latency_s": round(max((r[2] for r in load.results),
                                           default=0.0), 2),
            },
            "streaming": stream_rec,
            "train": {k: train_rec.get(k) for k in
                      ("completed", "attempts", "errors")},
            "nodes": {
                "node_id_before": node_id_before,
                "alive_remote_after": [n["NodeID"] for n in nodes_after],
            },
        })
        gates = {
            "outage_within_10s": result["measured_outage_s"] <= 10.0,
            "zero_failed_unary": len(failed) == 0 and hung_unary == 0,
            "streaming_never_hangs": (
                stream_rec.get("outcome", "hang") != "hang"
                and not stream_rec.get("outcome", "").startswith("untyped")),
            "zero_healthy_nodes_reaped": (
                len(nodes_after) == 1
                and nodes_after[0]["NodeID"] == node_id_before),
            "train_completed": bool(train_rec.get("completed")),
            "autoscaler_resumed_within_5_ticks": (
                resumed_s is not None and resumed_s <= 5 * autoscale_tick_s),
        }
        gates["passed"] = all(gates.values())
        result["gates"] = gates
        ray_tpu.shutdown()
        return result
    finally:
        for p in procs:
            if p.poll() is None:
                p.terminate()
        for p in procs:
            try:
                p.wait(timeout=10)
            except subprocess.TimeoutExpired:
                p.kill()
        for k, v in saved.items():
            if v is None:
                os.environ.pop(k, None)
            else:
                os.environ[k] = v
        shutil.rmtree(tmp, ignore_errors=True)


def main():
    mode = sys.argv[1] if len(sys.argv) > 1 else "--all"

    if mode == "--head-chaos":
        out_path = "HEAD_CHAOS_BENCH.json"
        if "--out" in sys.argv:
            out_path = sys.argv[sys.argv.index("--out") + 1]
        elif not os.path.isabs(out_path):
            out_path = os.path.join(os.path.dirname(__file__) or ".", out_path)
        if "--dry-run" in sys.argv:
            # CI harness smoke check: no processes, no kills — just prove the
            # mode is wired and the gate file lands where expected
            result = {
                "dry_run": True,
                "gates": {k: None for k in (
                    "outage_within_10s", "zero_failed_unary",
                    "streaming_never_hangs", "zero_healthy_nodes_reaped",
                    "train_completed", "autoscaler_resumed_within_5_ticks",
                    "passed")},
            }
            with open(out_path, "w") as f:
                json.dump(result, f, indent=2)
            print(f"dry run: wrote {out_path} (no measurements)")
            return
        result = head_chaos_suite()
        with open(out_path, "w") as f:
            json.dump(result, f, indent=2)
        print(f"wrote {out_path}")
        g = result["gates"]
        assert g["outage_within_10s"], (
            f"measured outage {result['measured_outage_s']}s exceeded the "
            "10s window the zero-failure gate is scoped to")
        assert g["zero_failed_unary"], (
            f"{result['unary']['failed']} unary requests failed "
            f"({result['unary']['failure_types']}) and "
            f"{result['unary']['hung']} hung through the head outage")
        assert g["streaming_never_hangs"], (
            f"streaming request ended badly: {result['streaming']}")
        assert g["zero_healthy_nodes_reaped"], (
            f"restarted head lost healthy nodes: {result['nodes']}")
        assert g["train_completed"], (
            f"train run never completed: {result['train']}")
        assert g["autoscaler_resumed_within_5_ticks"], (
            f"serve autoscaler loop resumed in "
            f"{result['autoscaler_resumed_s']}s (gate: 5 ticks)")
        return

    if mode == "--control-plane":
        out_path = "CONTROL_BENCH.json"
        if "--out" in sys.argv:
            out_path = sys.argv[sys.argv.index("--out") + 1]
        elif not os.path.isabs(out_path):
            out_path = os.path.join(os.path.dirname(__file__) or ".", out_path)
        if "--dry-run" in sys.argv:
            # CI harness smoke check: no measurements — just prove the mode
            # is wired and the gate file lands where expected
            result = {
                "dry_run": True,
                "gates": {"p99_threshold_ms": _control_p99_ms(),
                          "agg_speedup_threshold": _control_agg_speedup()},
                "fleets": {},
            }
            with open(out_path, "w") as f:
                json.dump(result, f, indent=2)
            print(f"dry run: wrote {out_path} (no measurements)")
            return
        result = control_plane_suite()
        with open(out_path, "w") as f:
            json.dump(result, f, indent=2)
        print(f"wrote {out_path}")
        g = result["gates"]
        assert g["p99_passed"], (
            f"control tick p99 at N=1024 {g['p99_ms_at_1024']:.1f}ms exceeds "
            f"the {g['p99_threshold_ms']}ms gate")
        assert g["agg_passed"], (
            f"node aggregation speedup at N=256 {g['agg_speedup_at_256']:.1f}x "
            f"below the {g['agg_speedup_threshold']}x gate")
        return

    if mode == "--scrape-overhead":
        out_path = "OBS_BENCH.json"
        if "--out" in sys.argv:
            out_path = sys.argv[sys.argv.index("--out") + 1]
        elif not os.path.isabs(out_path):
            out_path = os.path.join(os.path.dirname(__file__) or ".", out_path)
        if "--dry-run" in sys.argv:
            result = {
                "dry_run": True,
                "threshold_pct": _scrape_overhead_threshold_pct(),
                "rows": {"transfer_10mb_wire": None},
            }
            _update_obs_bench(out_path, "scrape_overhead", result)
            print(f"dry run: updated {out_path} (no measurements)")
            return
        import numpy as np

        import ray_tpu

        ray_tpu.init(num_cpus=4, node_server_port=0,
                     worker_env={"JAX_PLATFORMS": "cpu"},
                     max_workers_per_node=16)
        agent, sched = _spawn_remote_agent(ray_tpu)
        try:
            result = scrape_overhead_suite(ray_tpu, np, sched)
        finally:
            agent.terminate()
            try:
                agent.wait(timeout=10)
            except subprocess.TimeoutExpired:
                agent.kill()
        ray_tpu.shutdown()
        _update_obs_bench(out_path, "scrape_overhead", result)
        print(f"updated {out_path}")
        assert result["passed"], (
            f"history-scraper overhead {result['max_overhead_pct']:.2f}% "
            f"exceeds the {result['threshold_pct']}% gate")
        return

    if mode == "--telemetry-overhead":
        out_path = "OBS_BENCH.json"
        if "--out" in sys.argv:
            out_path = sys.argv[sys.argv.index("--out") + 1]
        elif not os.path.isabs(out_path):
            out_path = os.path.join(os.path.dirname(__file__) or ".", out_path)
        if "--dry-run" in sys.argv:
            # CI harness smoke check: no cluster, no timing noise — just prove
            # the mode is wired and the gate file lands where expected
            result = {
                "dry_run": True,
                "threshold_pct": _overhead_threshold_pct(),
                "rows": {"transfer_10mb_wire": None, "allreduce_16mb_w4": None},
            }
            _write_telemetry_obs_bench(out_path, result)
            print(f"dry run: wrote {out_path} (no measurements)")
            return
        import numpy as np

        import ray_tpu

        ray_tpu.init(num_cpus=4, node_server_port=0,
                     worker_env={"JAX_PLATFORMS": "cpu"},
                     max_workers_per_node=16)
        agent, sched = _spawn_remote_agent(ray_tpu)
        try:
            result = telemetry_overhead_suite(ray_tpu, np, sched)
        finally:
            agent.terminate()
            try:
                agent.wait(timeout=10)
            except subprocess.TimeoutExpired:
                agent.kill()
        ray_tpu.shutdown()
        _write_telemetry_obs_bench(out_path, result)
        print(f"wrote {out_path}")
        assert result["passed"], (
            f"telemetry overhead {result['max_overhead_pct']:.2f}% exceeds "
            f"the {result['threshold_pct']}% gate")
        return

    import numpy as np

    import ray_tpu

    out = {}

    if mode == "--transfer":
        ray_tpu.init(num_cpus=4, node_server_port=0,
                     worker_env={"JAX_PLATFORMS": "cpu"},
                     max_workers_per_node=8)
        agent, sched = _spawn_remote_agent(ray_tpu)
        try:
            bench = transfer_sweep_suite(ray_tpu, np, sched)
            bench.update(transfer_suite(ray_tpu, np, sched))
        finally:
            agent.terminate()
            try:
                agent.wait(timeout=10)
            except subprocess.TimeoutExpired:
                agent.kill()
        ray_tpu.shutdown()
        path = os.path.join(os.path.dirname(__file__) or ".",
                            "TRANSFER_BENCH.json")
        with open(path, "w") as f:
            json.dump(bench, f, indent=2)
        print("wrote TRANSFER_BENCH.json")
        return

    if mode == "--collective":
        ray_tpu.init(num_cpus=4, worker_env={"JAX_PLATFORMS": "cpu"},
                     max_workers_per_node=16)
        bench = collective_suite(ray_tpu, np)
        ray_tpu.shutdown()
        path = os.path.join(os.path.dirname(__file__) or ".",
                            "COLLECTIVE_BENCH.json")
        with open(path, "w") as f:
            json.dump(bench, f, indent=2)
        print("wrote COLLECTIVE_BENCH.json")
        return

    ray_tpu.init(num_cpus=4, node_server_port=0,
                 worker_env={"JAX_PLATFORMS": "cpu"}, max_workers_per_node=8)
    out["local"] = suite(ray_tpu, np)

    if mode != "--local":
        agent, sched = _spawn_remote_agent(ray_tpu)
        try:
            out["remote"] = suite(ray_tpu, np, sched=sched, n=1000,
                                  object_ops=False)
            out["remote"].update(transfer_suite(ray_tpu, np, sched))
        finally:
            agent.terminate()
            try:
                agent.wait(timeout=10)
            except subprocess.TimeoutExpired:
                agent.kill()
    ray_tpu.shutdown()

    for col, results in out.items():
        print(f"-- {col}")
        for k, v in results.items():
            print(f"  {k}: {v:,.0f}" if v > 100 else f"  {k}: {v:.2f}")
    with open(os.path.join(os.path.dirname(__file__) or ".", "CORE_BENCH.json"), "w") as f:
        json.dump({c: {k: round(v, 2) for k, v in r.items()}
                   for c, r in out.items()}, f, indent=2)
    print("wrote CORE_BENCH.json")


if __name__ == "__main__":
    main()
