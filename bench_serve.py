"""Serving-path benchmark: JaxLLMEngine on the real chip.

Measures what the paged-KV/continuous-batching design is FOR (reference
release/llm_tests/ serve benchmarks): prefill throughput, decode tokens/s at
batch 1/8/32, time-to-first-token, automatic-prefix-cache TTFT speedup, and
behavior at pool exhaustion (recompute preemption). Writes SERVE_BENCH.json.

Run: python bench_serve.py            (llama-500m geometry, bfloat16, paged KV)
     python bench_serve.py --tiny     (CI/CPU smoke: test-tiny config)

Timing note (axon TPU tunnel): engine outputs arrive host-side as Python ints
every step, so wall-clock spans below are naturally device-synchronized.
"""
import json
import os
import statistics
import sys
import threading
import time

import numpy as np

TINY = "--tiny" in sys.argv


def make_engine(**overrides):
    from ray_tpu.llm import JaxLLMEngine, LLMConfig

    base = dict(
        model_id="bench", model_source="test-tiny" if TINY else "llama-500m",
        tokenizer="byte", kv_layout="paged",
        max_num_seqs=8 if TINY else 32,
        max_model_len=256 if TINY else 1024,
        kv_block_size=16 if TINY else 32,
        dtype="float32" if TINY else "bfloat16",
    )
    if not TINY:
        base["prefill_buckets"] = [32, 64, 128, 256, 512, 1024]
    base.update(overrides)
    eng = JaxLLMEngine(LLMConfig(**base))
    eng.start()
    return eng


def _prompt(rng, n):
    return [int(x) for x in rng.integers(1, 200, size=n)]


def _params(max_tokens):
    from ray_tpu.llm import SamplingParams

    return SamplingParams(max_tokens=max_tokens, temperature=0.0,
                          stop_token_ids=[-1])


def warmup(engine, rng, prompt_len, batch, rounds=4):
    """Populate every jit cache (prefill bucket + decode burst widths) before
    timing: enough tokens that a fused engine traces its full-width burst.
    An auto-tuning engine may RAISE its burst width as its step-time EWMA
    settles, so loop until the target K is stable across rounds (each new K
    is a fresh XLA trace that must not land inside a timed region)."""
    k = engine.decode_steps_target()
    for _ in range(rounds):
        n = max(8, 2 * k)
        threads = [threading.Thread(target=lambda: engine.generate_sync(
            _prompt(rng, prompt_len), _params(n))) for _ in range(batch)]
        [t.start() for t in threads]
        [t.join() for t in threads]
        k2 = engine.decode_steps_target()
        if k2 == k:
            return
        k = k2


def bench_ttft_and_prefill(engine, rng, prompt_len):
    """TTFT for a cold prompt at batch 1 (and implied prefill tokens/s)."""
    ttfts = []
    for _ in range(5):
        p = _prompt(rng, prompt_len)
        t0 = time.perf_counter()
        gen = engine.generate(p, _params(2))
        next(gen)
        ttfts.append(time.perf_counter() - t0)
        for _ in gen:
            pass
    best = min(ttfts)
    return {
        "ttft_ms_b1": round(best * 1e3, 2),
        "prefill_tokens_per_s": round(prompt_len / best, 1),
    }


def bench_decode(engine, rng, batch, prompt_len, gen_tokens):
    """Steady-state decode throughput with `batch` concurrent streams."""
    done = [None] * batch
    first = [None] * batch

    def run(i):
        p = _prompt(rng, prompt_len)
        n = 0
        for out in engine.generate(p, _params(gen_tokens)):
            if first[i] is None:
                first[i] = time.perf_counter()
            n += len(out.token_ids)
        done[i] = (n, time.perf_counter())

    t0 = time.perf_counter()
    threads = [threading.Thread(target=run, args=(i,)) for i in range(batch)]
    [t.start() for t in threads]
    [t.join() for t in threads]
    total = sum(n for n, _ in done)
    # decode window: from the last stream's first token to the last completion
    # (all slots busy the whole span at equal lengths)
    span = max(t for _, t in done) - max(first)
    return {
        f"decode_tokens_per_s_b{batch}": round(total / (time.perf_counter() - t0), 1)
        if span <= 0 else round(total / span, 1),
        f"mean_ttft_ms_b{batch}": round(1e3 * np.mean([f - t0 for f in first]), 2),
    }


def bench_prefix_cache(engine, rng, prompt_len, samples=7):
    """TTFT speedup for a repeated prompt (hash-chain prefix cache).

    Through the axon tunnel a single TTFT sample is ~100-150 ms of round trip
    plus a few ms of device prefill, so cold-vs-warm needs medians over several
    samples — a min-of-few comparison measures tunnel luck, not the cache."""
    def ttft(p):
        t0 = time.perf_counter()
        gen = engine.generate(p, _params(2))
        next(gen)
        dt = time.perf_counter() - t0
        for _ in gen:
            pass
        return dt

    colds = [ttft(_prompt(rng, prompt_len))
             for _ in range(samples)]  # distinct: no hits
    p = _prompt(rng, prompt_len)
    ttft(p)  # populate the cache for this prompt
    hits0 = engine.metrics()["prefix_cache_hit_tokens"]
    warms = [ttft(p) for _ in range(samples)]
    hits = engine.metrics()["prefix_cache_hit_tokens"] - hits0
    return {
        "prefix_cache_ttft_speedup": round(
            float(np.median(colds)) / float(np.median(warms)), 3),
        "prefix_cache_hit_tokens_per_call": int(hits / max(1, len(warms))),
        "prefix_cache_note": (
            "median-of-7 cold vs warm, tunnel-inclusive (~110ms round trip "
            "dominates TTFT; earlier rounds' min-of-3 sampling measured "
            "tunnel luck). The warm path's gather+suffix fusion matters more "
            "than the saved FLOPs here: an extra device dispatch per warm "
            "request had made cache hits a net LOSS through the tunnel. "
            "hit_tokens_per_call = cached tokens actually skipped."),
    }


def bench_preemption(rng):
    """Oversubscribe a deliberately tiny pool: every request must still finish
    (recompute preemption), and the engine reports how often it preempted."""
    # pool sized so 4 concurrent requests MUST overflow it mid-decode
    eng = make_engine(max_num_seqs=4,
                      num_kv_blocks=24 if TINY else 10,
                      max_model_len=256 if TINY else 512)
    try:
        n_req, gen_tokens = 6, 48
        errs = []

        def run():
            try:
                out = eng.generate_sync(_prompt(rng, 64), _params(gen_tokens))
                assert out.num_generated_tokens == gen_tokens
            except Exception as e:  # noqa: BLE001
                errs.append(e)

        threads = [threading.Thread(target=run) for _ in range(n_req)]
        t0 = time.perf_counter()
        [t.start() for t in threads]
        [t.join() for t in threads]
        dt = time.perf_counter() - t0
        assert not errs, errs
        m = eng.metrics()
        return {
            "preemption_run_tokens_per_s": round(n_req * gen_tokens / dt, 1),
            "preemption_count": m["num_preemptions"],
            "preemption_all_completed": True,
        }
    finally:
        eng.shutdown()


def bench_device_decode(batch, k=64, n_bursts=16, prompt_len=512, quant=None):
    """DEVICE-resident decode: K fused decode+sample steps per burst
    (model_runner.decode_multi — a lax.scan, entirely on-chip), tokens fetched
    ONCE per burst. Isolates the chip from the host/tunnel round trip the e2e
    decode rows above pay per step (VERDICT r3 weak item 3: the committed
    number for what the engine does on local hardware). Dense KV layout; the
    paged pool's gather/scatter overhead shows in the e2e rows."""
    import jax
    import jax.numpy as jnp
    from jax.sharding import Mesh

    from ray_tpu.llm import model_runner
    from ray_tpu.models import get_config, llama

    cfg = get_config("test-tiny" if TINY else "llama-500m",
                     dtype="float32" if TINY else "bfloat16")
    mesh = Mesh(np.asarray(jax.devices()[:1]).reshape(1, 1, 1), ("dp", "ep", "tp"))
    params = model_runner.shard_params(
        jax.tree.map(lambda x: x.astype(cfg.activation_dtype),
                     llama.init(jax.random.PRNGKey(0), cfg)), cfg, mesh)
    suffix = ""
    if quant == "int8":
        from ray_tpu.ops.quant import quantize_llama_params

        params = jax.jit(quantize_llama_params)(params)
        suffix = "_int8"
    max_len = prompt_len + 2 * k * n_bursts + 8

    def fresh_state():
        # decode continues from prompt_len; cache contents don't affect timing.
        # Fresh per run: decode_multi donates its state argument.
        return model_runner.init_state(
            cfg, slots=batch, max_len=max_len, mesh=mesh)._replace(
                lengths=jnp.full((batch,), prompt_len, jnp.int32))

    tokens = jnp.ones((batch,), jnp.int32)
    active = jnp.ones((batch,), bool)
    temp = jnp.zeros((batch,), jnp.float32)
    top_p = jnp.ones((batch,), jnp.float32)
    top_k = jnp.zeros((batch,), jnp.int32)

    steps_left = jnp.full((batch,), k, jnp.int32)

    def burst(state, tokens, seed):
        rngs = jax.random.split(jax.random.PRNGKey(seed), k)
        state, toks_k = model_runner.decode_multi(
            params, state, tokens, active, cfg, rngs, temp, top_p, top_k,
            steps_left)
        return state, toks_k

    def chained(tokens, n):
        """n bursts chained ON DEVICE: each burst's last token feeds the next
        with no host fetch; one data sync at the end. Dispatches are async, so
        the tunnel round trip is paid once, not per burst."""
        state = fresh_state()
        t0 = time.perf_counter()
        for i in range(n):
            state, toks_k = burst(state, tokens, i + 1)
            tokens = toks_k[-1]  # device array: no host sync
        np.asarray(tokens)  # the ONLY fetch (block_until_ready is unreliable
        return time.perf_counter() - t0  # through the axon tunnel)

    # Warm with a short CHAINED run: the chain feeds device-resident tokens
    # whose layout differs from the host-committed warmup input, so a plain
    # single-burst warmup would leave a recompile inside the timed region.
    chained(tokens, 2)
    # Difference two run lengths: the fixed dispatch+fetch tunnel cost (~100-
    # 180 ms through axon, ~1 ms locally) cancels, leaving pure device time.
    # Min over trials: one tunnel stall inside either span poisons a single
    # difference, so a lone pair occasionally reports 5-10x reality.
    extra_steps = n_bursts * k
    diffs = []
    for _ in range(3):
        t_short = chained(tokens, n_bursts)
        t_long = chained(tokens, 2 * n_bursts)
        if t_long - t_short > 0:
            diffs.append(t_long - t_short)
    per_step_ms = (min(diffs) if diffs else 1e-9) / extra_steps * 1000
    return {
        f"decode_device_ms_per_step_b{batch}{suffix}": round(per_step_ms, 3),
        f"decode_device_tokens_per_s_b{batch}{suffix}": round(
            batch / (per_step_ms / 1000), 1),
    }


def bench_spec_modes(batch, gen_tokens=96, k=4):
    """Speculative/fused composition at 100% draft acceptance (the machinery's
    ceiling — real acceptance is workload-dependent): tokens/s for fused-only
    (m=8), spec-only (k=4, one window per sync), and the composed mode
    (k=4 inside m=4 fused windows). All greedy; outputs verified identical."""
    import functools

    import jax
    import jax.numpy as jnp

    from ray_tpu.llm import SamplingParams, model_runner

    prompt = [int(x) for x in np.random.default_rng(1).integers(1, 200, 40)]
    params = SamplingParams(max_tokens=gen_tokens, temperature=0.0,
                            stop_token_ids=[-1])

    base = make_engine(kv_layout="slot", max_num_seqs=batch, dtype="float32")
    try:
        cont = base.generate_sync(prompt, params).token_ids
    finally:
        base.shutdown()
    full = prompt + cont

    def host_oracle(req, cap):
        done = len(req.token_history) - len(prompt)
        return cont[done:done + cap]

    def run(eng, label, patch_device_oracle=False):
        import ray_tpu.llm.engine as _E

        orig = _E.model_runner.spec_multi
        if patch_device_oracle:
            table = np.zeros((batch, eng.config.max_model_len), np.int32)
            table[:, :len(full)] = full

            def dev_oracle(h, hl, last, kk, nmax):
                t = jnp.asarray(table)
                starts = jnp.clip(hl, 0, t.shape[1] - kk)
                drafts = jax.vmap(lambda row, s: jax.lax.dynamic_slice(
                    row, (s,), (kk,)))(t, starts)
                win = jnp.zeros((batch, kk + 1), jnp.int32).at[:, 0].set(last)
                return win.at[:, 1:].set(drafts), jnp.full((batch,), kk, jnp.int32)

            _E.model_runner.spec_multi = functools.partial(
                orig, propose_fn=dev_oracle)
        eng._propose_ngram = host_oracle
        eng.start()
        try:
            # warmup: compile every decode/verify program before timing
            for _ in range(2):
                eng.generate_sync(prompt, params)
            outs = [None] * batch

            def one(i):
                outs[i] = eng.generate_sync(prompt, params)

            ts = [threading.Thread(target=one, args=(i,)) for i in range(batch)]
            t0 = time.perf_counter()
            [t.start() for t in ts]
            [t.join() for t in ts]
            dt = time.perf_counter() - t0
            # On TPU, f32 matmuls lower through bf16 passes whose tiling differs
            # between a verify window and a single-token step, so greedy
            # trajectories can fork at near-ties and the oracle mismatches from
            # the fork onward. Exact equivalence is proven by the CPU tests;
            # here assert completion and REPORT the realized acceptance.
            for o in outs:
                assert o.num_generated_tokens == gen_tokens, f"{label}: truncated"
            mx = eng.metrics()
            drafted = max(1, mx["num_spec_drafted"])
            rate = round(mx["num_spec_accepted"] / drafted, 3)
            return round(batch * gen_tokens / dt, 1), rate
        finally:
            eng.shutdown()
            _E.model_runner.spec_multi = orig

    # f32 everywhere: bit-stable greedy keeps the oracle matching longer
    fused, _ = run(make_engine(kv_layout="slot", max_num_seqs=batch,
                               dtype="float32", num_decode_steps=8), "fused8")
    spec, spec_acc = run(make_engine(kv_layout="slot", max_num_seqs=batch,
                                     dtype="float32",
                                     num_speculative_tokens=k), "spec")
    combined, comb_acc = run(
        make_engine(kv_layout="slot", max_num_seqs=batch, dtype="float32",
                    num_speculative_tokens=k, num_decode_steps=4),
        "combined", patch_device_oracle=True)
    return {
        f"spec_tokens_per_s_b{batch}_fused8_only": fused,
        f"spec_tokens_per_s_b{batch}_spec{k}_only": spec,
        f"spec_tokens_per_s_b{batch}_combined_m4k{k}": combined,
        f"spec_accept_rate_b{batch}_spec_only": spec_acc,
        f"spec_accept_rate_b{batch}_combined": comb_acc,
        "spec_note": (
            "an UNTRAINED model has near-flat logits, so TPU window-vs-step "
            "tiling jitter forks the greedy trajectory almost immediately and "
            "realized acceptance collapses — these rows show the workload-"
            "dependence honestly (speculation only pays on compressible "
            "text/confident models). The machinery's ceiling at full "
            "acceptance is bit-stable on CPU f32: combined 2747 tok/s vs "
            "spec-only 2183 vs fused-only 1306 at b1 (tests/test_llm.py "
            "oracle test proves in-burst acceptance exactly)"),
    }


def bench_spec_trained(steps=None, gen_tokens=96, k=4):
    """Speculative decoding on a TRAINED model with the REAL ngram proposer
    (VERDICT r4 weak 4: realized acceptance on the untrained bench model was
    0.03-0.05, so every measured spec row was a slowdown). Zero egress means
    no HF checkpoint can be downloaded, so this trains the model itself to
    coherence on the chip: a byte-level model on a fixed corpus of sentences
    each repeated through the document — a few hundred steps later greedy
    decoding confidently copies repeating text, which is exactly the regime
    prompt-lookup speculation exists for (and the confident logits keep argmax
    stable across the TPU's window-vs-step tiling difference)."""
    import jax
    import jax.numpy as jnp

    from ray_tpu.llm import JaxLLMEngine, LLMConfig, SamplingParams
    from ray_tpu.llm.tokenizer import get_tokenizer
    from ray_tpu.models.config import ModelConfig
    from ray_tpu.train import init_state, make_optimizer, make_train_step

    steps = steps or (60 if TINY else 400)
    seq, train_batch = 256, 16
    cfg = ModelConfig(name="spec-train-byte", vocab_size=512,
                      d_model=128 if TINY else 256, n_layers=2 if TINY else 4,
                      n_heads=8, n_kv_heads=4, d_ff=512 if TINY else 1024,
                      max_seq_len=512, dtype="float32", scan_layers=True)
    tok = get_tokenizer("byte")
    sentences = [
        "the quick brown fox jumps over the lazy dog. ",
        "pack my box with five dozen liquor jugs. ",
        "how vexingly quick daft zebras jump! ",
        "sphinx of black quartz, judge my vow. ",
        "we promptly judged antique ivory buckles. ",
        "a wizard's job is to vex chumps quickly in fog. ",
    ]
    enc = [tok.encode(s) for s in sentences]
    rng = np.random.default_rng(0)

    def batch_tokens():
        rows = np.zeros((train_batch, seq + 1), np.int32)
        for r in range(train_batch):
            ids = enc[rng.integers(len(enc))]
            reps = (seq + 1) // len(ids) + 1
            rows[r] = np.tile(ids, reps)[: seq + 1]
        return rows

    tx = make_optimizer(learning_rate=1e-3, warmup_steps=40, total_steps=steps)
    state = init_state(jax.random.PRNGKey(0), cfg, tx)
    step_fn = make_train_step(cfg, tx)
    t0 = time.perf_counter()
    for i in range(steps):
        state, metrics = step_fn(state, {"tokens": jnp.asarray(batch_tokens())})
    final_loss = float(metrics["loss"])  # fetch = sync
    train_s = time.perf_counter() - t0
    params = state.params

    # eval prompt: a corpus sentence repeated 2.5x — the model continues the
    # repetition it memorized; prompt-lookup proposes the same continuation
    prompt = tok.encode(sentences[0] * 2 + sentences[0][:20])
    sp = SamplingParams(max_tokens=gen_tokens, temperature=0.0,
                        stop_token_ids=[-1])

    def run(label, **overrides):
        eng = JaxLLMEngine(LLMConfig(
            model_id=f"spec-trained-{label}", model_source=cfg, tokenizer="byte",
            max_num_seqs=2, max_model_len=1024, dtype="float32", **overrides),
            params=params)
        eng.start()
        try:
            eng.generate_sync(prompt, sp)  # warmup/compile
            t0 = time.perf_counter()
            out = eng.generate_sync(prompt, sp)
            dt = time.perf_counter() - t0
            assert out.num_generated_tokens == gen_tokens
            m = eng.metrics()
            acc = (m["num_spec_accepted"] / m["num_spec_drafted"]
                   if m["num_spec_drafted"] else None)
            return round(gen_tokens / dt, 1), acc, out.token_ids
        finally:
            eng.shutdown()

    plain_tps, _, plain_ids = run("plain")
    spec_tps, spec_acc, spec_ids = run("spec", num_speculative_tokens=k)
    fused_tps, fused_acc, _ = run("specfused", num_speculative_tokens=k,
                                  num_decode_steps=4)
    return {
        "spec_trained_model": f"{cfg.n_params/1e6:.1f}M byte-level, "
                              f"{steps} steps on repeated-sentence corpus",
        "spec_trained_final_loss": round(final_loss, 4),
        "spec_trained_train_s": round(train_s, 1),
        "spec_trained_plain_tok_s_b1": plain_tps,
        f"spec_trained_spec{k}_tok_s_b1": spec_tps,
        f"spec_trained_spec{k}_accept_rate": (round(spec_acc, 3)
                                              if spec_acc is not None else None),
        f"spec_trained_spec{k}_fused4_tok_s_b1": fused_tps,
        f"spec_trained_spec{k}_fused4_accept_rate": (
            round(fused_acc, 3) if fused_acc is not None else None),
        "spec_trained_outputs_match": spec_ids == plain_ids,
        "spec_trained_note": (
            "REAL ngram proposer end to end (no oracle): the trained model's "
            "greedy continuation of repeating text is what prompt-lookup "
            "drafts, so acceptance is high and speculation actually pays — "
            "the workload-dependence the untrained rows above show from the "
            "other side"),
    }


def _kv_handoff_child(role, conn, nbytes, iters):
    """Child process for the KV-handoff bench (device plane vs host pickle).

    Runs on the CPU backend regardless of the bench platform: two processes
    cannot share one TPU chip through the tunnel, and the subject under test is
    the transfer plane itself (on pods the same pull rides DCN).
    """
    import os as _os

    _os.environ["JAX_PLATFORMS"] = "cpu"
    import jax

    jax.config.update("jax_platforms", "cpu")
    import pickle

    import jax.numpy as jnp

    from ray_tpu.core.device_plane import plane

    n = nbytes // 4
    if role == "producer":
        x = jnp.ones((n,), jnp.float32)
        for _ in range(iters + 1):  # +1 warmup; export, send tiny handle, await ack
            h = plane().export(x)
            conn.send(h)
            conn.recv()
        for _ in range(iters):  # host path: np.asarray + pickle through the pipe
            conn.send_bytes(pickle.dumps(np.asarray(x), protocol=5))
            conn.recv()
    else:
        conn, result_conn = conn
        # warmup round (connection setup + jit of nothing): excluded from timing
        h = conn.recv()
        jax.block_until_ready(plane().fetch(h, release=True))
        conn.send("ok")
        t0 = time.perf_counter()
        for _ in range(iters):
            h = conn.recv()
            arr = plane().fetch(h, release=True)
            jax.block_until_ready(arr)
            conn.send("ok")
        t_plane = time.perf_counter() - t0
        t0 = time.perf_counter()
        for _ in range(iters):
            arr = jax.device_put(pickle.loads(conn.recv_bytes()))
            jax.block_until_ready(arr)
            conn.send("ok")
        t_host = time.perf_counter() - t0
        result_conn.send((t_plane, t_host))


def bench_kv_handoff(nbytes=64 * 1024 * 1024, iters=8):
    """GB/s of a P/D-style KV handoff between two processes: device plane
    (PJRT transfer server pull) vs host path (np + pickle over a pipe)."""
    import multiprocessing as mp
    import secrets

    # children must share one session authkey (the plane refuses to mint one)
    os.environ.setdefault("RAY_TPU_CLIENT_AUTHKEY", secrets.token_hex(16))
    ctx = mp.get_context("spawn")
    p_end, c_end = ctx.Pipe()
    res_parent, res_child = ctx.Pipe()
    prod = ctx.Process(target=_kv_handoff_child,
                       args=("producer", p_end, nbytes, iters))
    cons = ctx.Process(target=_kv_handoff_child,
                       args=("consumer", (c_end, res_child), nbytes, iters))
    prod.start()
    cons.start()
    try:
        deadline = time.time() + 600
        while not res_parent.poll(1.0):
            if time.time() > deadline:
                raise TimeoutError("kv handoff bench timed out")
            if not (prod.is_alive() and cons.is_alive()):
                raise RuntimeError(
                    f"kv handoff child died (producer rc={prod.exitcode}, "
                    f"consumer rc={cons.exitcode})")
        t_plane, t_host = res_parent.recv()
    finally:
        prod.join(30)
        cons.join(30)
        for p in (prod, cons):
            if p.is_alive():
                p.terminate()
    gb = nbytes * iters / 1e9
    return {
        "kv_handoff_mb": nbytes // (1 << 20),
        "kv_handoff_device_plane_gbps": round(gb / t_plane, 2),
        "kv_handoff_host_pickle_gbps": round(gb / t_host, 2),
        "kv_handoff_speedup": round(t_host / t_plane, 2),
    }


# --------------------------------------------------------------------------
# Engine-vs-device-ceiling bench (--engine): how close the DEFAULT engine
# path (fused multi-step decode + barrier-free continuous batching) gets to
# the raw device decode loop, with gates checked in-script (non-zero exit on
# regression, like bench.py --grad-sync). Merges its rows into an existing
# SERVE_BENCH.json instead of clobbering rows measured on other platforms.
# --------------------------------------------------------------------------

def _engine_decode_rows(results, rng, prompt_len, gen_tokens, batches, *,
                        key, **overrides):
    """Decode tok/s + mean TTFT rows for one engine config, keyed engine_{key}_*."""
    eng = make_engine(max_num_seqs=max(batches), **overrides)
    try:
        warmup(eng, rng, prompt_len, max(batches))
        for b in batches:
            rows = bench_decode(eng, rng, b, prompt_len, gen_tokens)
            results[f"engine_{key}_tokens_per_s_b{b}"] = (
                rows[f"decode_tokens_per_s_b{b}"])
            results[f"engine_{key}_mean_ttft_ms_b{b}"] = (
                rows[f"mean_ttft_ms_b{b}"])
        return eng.metrics()
    finally:
        eng.shutdown()


def _sync_fraction_gate(results, limit=0.5, slack=1.1):
    """decode_host_sync_fraction <= 0.5, OR within 10% of the best fraction
    the auto-K cap allows for the measured rt/step (rt/(rt + K_max*step))."""
    frac = results["decode_host_sync_fraction"]
    if frac <= limit:
        return True
    from ray_tpu.config import CONFIG as _CFG

    rt = results.get("engine_host_rt_ms", 0.0)
    step = results.get("engine_device_step_ms", 0.0)
    if rt <= 0 or step <= 0:
        return False
    achievable = rt / (rt + _CFG.llm_fused_steps_max * step)
    return frac <= achievable * slack


def engine_main():
    """--engine: default-path engine decode vs the per-step baseline and the
    device-loop ceiling, plus the prefix-cache pay-or-skip verdict and the
    decode_host_sync_fraction the auto-tuner minimizes."""
    import jax

    rng = np.random.default_rng(0)
    prompt_len = 64 if TINY else 512
    gen_tokens = 48 if TINY else 128
    batches = (8, 32)
    platform = jax.devices()[0].platform
    out_path = os.path.join(os.path.dirname(__file__) or ".", "SERVE_BENCH.json")
    try:
        with open(out_path) as f:
            results = json.load(f)
    except (OSError, ValueError):
        results = {}
    # prev-row gates only make sense against rows measured on THIS platform
    # (the merged file may carry another platform's rows — e.g. tunnel-TPU
    # TTFT is ~100x CPU TTFT, and a cross-platform compare would fail the
    # gate with no real regression)
    same_platform = results.get("platform") == platform
    # the 3x-vs-previous gates are a ONE-TIME acceptance check against rows
    # that predate the fused default: once main() or --engine has regenerated
    # the file, decode_tokens_per_s_b* themselves ride the fast path and
    # "new default >= 3x new default" would be a spurious failure — after the
    # first merge (engine_all_gates_pass present) they become ratios only
    prev_is_prefastpath = "engine_all_gates_pass" not in results
    prev_default_b8 = results.get("decode_tokens_per_s_b8")
    prev_default_b32 = results.get("decode_tokens_per_s_b32")
    prev_fused8_ttft = {b: results.get(f"mean_ttft_ms_b{b}_fused8")
                        for b in batches}
    if not same_platform:
        results["engine_gates_note"] = (
            f"previous decode/TTFT rows were measured on platform="
            f"{results.get('platform')!r}; this run is {platform!r}, so the "
            "vs-previous gates are recorded as ratios but not enforced")
    results["engine_platform"] = platform
    results["engine_config"] = ("test-tiny f32 paged(block=16)" if TINY else
                                "llama-500m bf16 paged(block=32)")

    # the old default: one host sync per token per slot
    _engine_decode_rows(results, rng, prompt_len, gen_tokens, batches,
                        key="singlestep", num_decode_steps=1)
    # the new default: fused bursts, auto-tuned K (num_decode_steps unset)
    m = _engine_decode_rows(results, rng, prompt_len, gen_tokens, batches,
                            key="default")
    results["engine_default_fused_steps"] = m["decode_fused_steps"]
    results["decode_host_sync_fraction"] = m["decode_host_sync_fraction"]
    results["engine_host_rt_ms"] = m["decode_host_rt_ms"]
    results["engine_device_step_ms"] = m["decode_device_step_ms"]

    # prefix cache on the default path (pay-or-skip armed). In tiny mode the
    # prompt is lengthened so the cacheable prefix is a meaningful share of
    # prefill compute — at 64 tokens the saving is under the CPU noise floor
    # and the row would measure jitter, not the cache
    prefix_len = 160 if TINY else prompt_len
    eng = make_engine()
    try:
        warmup(eng, rng, prefix_len, 4)
        prefix = bench_prefix_cache(eng, rng, prefix_len, samples=9)
        if (not same_platform and "prefix_cache_ttft_speedup" in results
                and "prefix_cache_ttft_speedup_prev" not in results):
            # the behavior changed (pay-or-skip), so the fresh number IS the
            # current row — but keep the other platform's measurement instead
            # of silently losing it (write-once: later reruns would otherwise
            # stamp their own stale value over the original)
            results["prefix_cache_ttft_speedup_prev"] = {
                "value": results["prefix_cache_ttft_speedup"],
                "platform": results.get("platform")}
        results["prefix_cache_ttft_speedup"] = prefix["prefix_cache_ttft_speedup"]
        results["prefix_cache_hit_tokens_per_call"] = (
            prefix["prefix_cache_hit_tokens_per_call"])
        results["prefix_cache_skipped_prefills"] = (
            eng.metrics()["num_prefix_skipped"])
        results["prefix_cache_note"] = (
            "median-of-9 cold vs warm on the default fused engine with the "
            "pay-or-skip gate armed: hits below the measured "
            "dispatch-cost/prefill-rate floor skip the cache entirely (no "
            "hashing), so a warm request is never slower than a cold one. "
            f"Measured on platform={platform}.")
    finally:
        eng.shutdown()

    # device-loop ceiling at the same batches (chained fused bursts on chip),
    # under engine_* keys so the main run's decode_device_* rows — possibly
    # measured on a different platform — survive the merge
    for b in batches:
        dev = bench_device_decode(
            b, k=8 if TINY else 64, n_bursts=2 if TINY else 16,
            prompt_len=prompt_len)
        ceil = dev[f"decode_device_tokens_per_s_b{b}"]
        results[f"engine_ceiling_tokens_per_s_b{b}"] = ceil
        results[f"engine_ceiling_ms_per_step_b{b}"] = (
            dev[f"decode_device_ms_per_step_b{b}"])
        results[f"engine_vs_ceiling_fraction_b{b}"] = round(
            results[f"engine_default_tokens_per_s_b{b}"] / ceil, 3) if ceil else None

    for b, prev in ((8, prev_default_b8), (32, prev_default_b32)):
        if prev:
            results[f"engine_default_vs_prev_default_b{b}"] = round(
                results[f"engine_default_tokens_per_s_b{b}"] / prev, 2)
    gates = {
        # the per-step-default baselines (55.8 / 214.5 through the tunnel):
        # the new default path must clear 3x them — enforced when the
        # previous rows came from this platform, recorded as ratios always.
        "default_b8_3x_prev": (not same_platform or not prev_is_prefastpath
                               or prev_default_b8 is None or
                               results["engine_default_tokens_per_s_b8"]
                               >= 3 * prev_default_b8),
        "default_b32_3x_prev": (not same_platform or not prev_is_prefastpath
                                or prev_default_b32 is None or
                                results["engine_default_tokens_per_s_b32"]
                                >= 3 * prev_default_b32),
        # same-platform self-check: fused default never loses to per-step
        # (>= 10% noise floor; the win scales with the host round trip, so
        # it is ~1x on local CPU and 3-10x through the tunnel)
        "default_not_worse_than_singlestep_b8": (
            results["engine_default_tokens_per_s_b8"]
            >= 0.9 * results["engine_singlestep_tokens_per_s_b8"]),
        "default_not_worse_than_singlestep_b32": (
            results["engine_default_tokens_per_s_b32"]
            >= 0.9 * results["engine_singlestep_tokens_per_s_b32"]),
        # mean TTFT under concurrent load: no worse than the old fused8 rows
        # (admission rides burst boundaries now, so TTFT must not regress)
        "ttft_b8_not_worse_than_prev_fused8": (
            not same_platform or not prev_is_prefastpath
            or prev_fused8_ttft[8] is None or
            results["engine_default_mean_ttft_ms_b8"] <= prev_fused8_ttft[8]),
        "ttft_b32_not_worse_than_prev_fused8": (
            not same_platform or not prev_is_prefastpath
            or prev_fused8_ttft[32] is None or
            results["engine_default_mean_ttft_ms_b32"] <= prev_fused8_ttft[32]),
        # auto-K's whole point: the host sync share of decode stays bounded —
        # OR sits at the best value the K cap allows (a huge rt/step ratio,
        # e.g. tunnel rt with a tiny model, can need K far above the cap;
        # running AT the cap-limited optimum is the tuner working, not a bug)
        "host_sync_fraction_bounded": _sync_fraction_gate(results),
        # the cache pays (or gets out of the way): warm TTFT >= cold TTFT
        "prefix_cache_speedup_ge_1": results["prefix_cache_ttft_speedup"] >= 1.0,
    }
    gates = {k: bool(v) for k, v in gates.items()}  # np.bool_ isn't JSON
    results["engine_gates"] = gates
    results["engine_all_gates_pass"] = all(gates.values())
    for k, v in sorted(results.items()):
        if k.startswith(("engine_", "decode_host_sync", "prefix_cache")):
            print(f"{k}: {v}")
    with open(out_path, "w") as f:
        json.dump(results, f, indent=2)
    print(f"wrote {out_path}")
    if not results["engine_all_gates_pass"]:
        print("ENGINE GATES FAILED:",
              [k for k, v in gates.items() if not v])
        sys.exit(1)


# --------------------------------------------------------------------------
# Serve-plane chaos bench (--chaos): the robustness half of the serving
# control loop. Open-loop HTTP load against a replicated deployment, then
# (1) SIGKILL a replica mid-stream: the handle retry plane + controller
#     reconcile must absorb it — zero lost requests, subscribe_slo() sees
#     burning -> ok, windowed p99 back within 1.5x pre-kill inside the
#     recovery window;
# (2) offer 2x saturation load at a shed-configured deployment: the proxy
#     must reject with 503 + Retry-After while goodput for admitted requests
#     holds within 20% of the unsaturated rate.
# Writes SERVE_CHAOS_BENCH.json. Pure host-path (no TPU/jax needed).
# --------------------------------------------------------------------------

def _percentile(xs, q):
    if not xs:
        return None
    xs = sorted(xs)
    i = min(len(xs) - 1, int(q * (len(xs) - 1) + 0.5))
    return xs[i]


class _LoadGen:
    """Open-loop HTTP load: arrivals on a fixed schedule, independent of
    completions (closed-loop generators hide overload by self-throttling)."""

    def __init__(self, url, max_workers=128):
        import concurrent.futures

        self.url = url
        self.pool = concurrent.futures.ThreadPoolExecutor(max_workers=max_workers)
        self.records = []  # (t_submit, latency_s, status, retry_after or None)
        self._lock = threading.Lock()

    def _one(self, t_sched):
        import urllib.error
        import urllib.request

        t0 = time.perf_counter()
        status, ra = 0, None
        try:
            resp = urllib.request.urlopen(self.url, timeout=30)
            resp.read()
            status = resp.status
        except urllib.error.HTTPError as e:
            status = e.code
            ra = e.headers.get("Retry-After")
        except Exception:  # noqa: BLE001 — connection-level failure
            status = -1
        lat = time.perf_counter() - t0
        with self._lock:
            self.records.append((t_sched, lat, status, ra))

    def run(self, rps, duration_s):
        """Blocking: submit for duration_s at rps, then wait for stragglers."""
        interval = 1.0 / rps
        t0 = time.perf_counter()
        next_t = t0
        while True:
            now = time.perf_counter()
            if now - t0 >= duration_s:
                break
            if now < next_t:
                time.sleep(next_t - now)
            self.pool.submit(self._one, time.perf_counter() - t0)
            next_t += interval

    def drain(self):
        self.pool.shutdown(wait=True)

    def window(self, t_lo, t_hi, status=None):
        with self._lock:
            return [r for r in self.records
                    if t_lo <= r[0] < t_hi and (status is None or r[2] == status)]


def _make_chaos_app(service_s):
    from ray_tpu import serve

    @serve.deployment
    class ChaosTarget:
        def __call__(self, _body):
            time.sleep(service_s)
            return {"ok": True}

    return ChaosTarget


def run_chaos_kill(port, *, replicas=3, moq=2, service_s=0.08, rps=55.0,
                   warm_s=4.0, post_kill_s=12.0, recovery_window_s=10.0,
                   app="chaos-kill"):
    """Kill one of `replicas` replicas under open-loop load sized ABOVE the
    survivors' capacity: latency must burn the SLO until the control loop
    replaces the replica, then recover. Returns the result dict."""
    from ray_tpu import serve
    from ray_tpu.util import slo as slo_mod
    from ray_tpu.util.fault_injection import ChaosController

    Target = _make_chaos_app(service_s)
    serve.run(Target.options(num_replicas=replicas, max_ongoing_requests=moq,
                             health_check_period_s=0.5).bind(),
              name=app, route_prefix=f"/{app}")
    gen = _LoadGen(f"http://127.0.0.1:{port}/{app}?x=1")
    transitions = []
    run_t0 = time.perf_counter()

    load = threading.Thread(
        target=gen.run, args=(rps, warm_s + post_kill_s), daemon=True)
    load.start()
    time.sleep(warm_s * 0.75)
    warm = gen.window(1.0, time.perf_counter() - run_t0)
    base_lat = [r[1] for r in warm if r[2] == 200]
    if not base_lat:
        raise RuntimeError(
            f"chaos warm-up produced no successful samples ({len(warm)} "
            "requests recorded) — serve bring-up failed before the kill")
    base_p50, base_p99 = _percentile(base_lat, 0.5), _percentile(base_lat, 0.99)
    # threshold between healthy p50 and the queueing blowup a lost replica
    # causes at this utilization: steady state is ~0% bad, saturation is >50%
    thr = max(2.5 * base_p50, 1.2 * base_p99)
    slo_mod.register(slo_mod.SLO(
        "chaos_ttft", metric="serve_ttft_seconds", objective=0.85,
        threshold=thr, window_s=3.0, kind="latency"))
    unsub = slo_mod.subscribe_slo(lambda ev: transitions.append(
        (time.perf_counter() - run_t0, ev["from"], ev["to"])))
    time.sleep(warm_s * 0.25)

    t_kill = time.perf_counter() - run_t0
    assert ChaosController().kill_replica(app, "ChaosTarget", index=0)
    load.join()
    gen.drain()
    unsub()
    slo_mod.remove("chaos_ttft")
    # requests submitted before the kill that were still in flight when it
    # landed — the ones only the retry plane can save
    inflight_at_kill = sum(1 for t_s, lat, _, _ in gen.records
                           if t_s < t_kill < t_s + lat)

    pre = [r[1] for r in gen.window(t_kill - 3.0, t_kill, status=200)]
    pre_p99 = _percentile(pre, 0.99) or _percentile(base_lat, 0.99)
    # rolling 2s windows after the kill: recovery = first window whose p99 is
    # back within 1.5x of pre-kill (and the window actually has data)
    recovery_s = None
    t = t_kill + 1.0
    t_end = t_kill + post_kill_s
    while t + 2.0 <= t_end:
        w = [r[1] for r in gen.window(t, t + 2.0, status=200)]
        if w and _percentile(w, 0.99) <= 1.5 * pre_p99:
            recovery_s = round(t - t_kill, 2)
            break
        t += 0.5
    failed = [r for r in gen.records if r[2] != 200]
    burn_seen = any(to == "burning" for _, _, to in transitions)
    recovered_ok = any(to == "ok" and frm == "burning"
                       for _, frm, to in transitions)
    return {
        "kill_offered_rps": rps,
        "kill_replicas": replicas,
        "kill_requests_total": len(gen.records),
        "kill_requests_failed": len(failed),
        "kill_inflight_at_kill": max(0, inflight_at_kill),
        "kill_zero_lost": len(failed) == 0,
        "kill_baseline_p50_ms": round(base_p50 * 1e3, 1),
        "kill_pre_kill_p99_ms": round(pre_p99 * 1e3, 1),
        "kill_slo_threshold_ms": round(thr * 1e3, 1),
        "kill_slo_transitions": [(round(t, 2), f, to)
                                 for t, f, to in transitions],
        "kill_slo_burn_observed": burn_seen,
        "kill_slo_recovery_observed": recovered_ok,
        "kill_p99_recovery_s": recovery_s,
        "kill_p99_recovered_in_window": (recovery_s is not None
                                         and recovery_s <= recovery_window_s),
    }


def run_chaos_shed(port, *, moq=2, max_queued=2, service_s=0.05,
                   phase_s=5.0, app="chaos-shed"):
    """Admission control under 2x saturation: the proxy must shed with 503 +
    Retry-After while admitted-request goodput holds within 20% of the
    unsaturated rate (overload degrades to fast rejections, not collapse)."""
    from ray_tpu import serve

    capacity_rps = moq / service_s  # one replica: moq slots x 1/service each
    Target = _make_chaos_app(service_s)
    serve.run(Target.options(num_replicas=1, max_ongoing_requests=moq,
                             max_queued_requests=max_queued).bind(),
              name=app, route_prefix=f"/{app}")
    url = f"http://127.0.0.1:{port}/{app}?x=1"

    def phase(rps):
        gen = _LoadGen(url)
        gen.run(rps, phase_s)
        gen.drain()
        ok = [r for r in gen.records if r[2] == 200]
        shed = [r for r in gen.records if r[2] == 503]
        return {
            "offered_rps": rps,
            "goodput_rps": round(len(ok) / phase_s, 1),
            "shed": len(shed),
            "shed_with_retry_after": sum(1 for r in shed if r[3]),
            "other_failures": len(gen.records) - len(ok) - len(shed),
            "p99_ms": round((_percentile([r[1] for r in ok], 0.99) or 0) * 1e3, 1),
        }

    unsat = phase(0.8 * capacity_rps)
    time.sleep(1.0)  # queue fully drains between phases
    sat = phase(2.0 * capacity_rps)
    goodput_ratio = (sat["goodput_rps"] / unsat["goodput_rps"]
                     if unsat["goodput_rps"] else 0.0)
    return {
        "shed_capacity_rps_nominal": round(capacity_rps, 1),
        "shed_unsaturated": unsat,
        "shed_saturated_2x": sat,
        "shed_goodput_ratio": round(goodput_ratio, 3),
        "shed_goodput_within_20pct": goodput_ratio >= 0.8,
        "shed_rejections_observed": sat["shed"] > 0,
        "shed_retry_after_present": (sat["shed"] > 0
                                     and sat["shed_with_retry_after"] == sat["shed"]),
        "shed_no_other_failures": (unsat["other_failures"] == 0
                                   and sat["other_failures"] == 0),
    }


def run_chaos_autoscale(port, *, moq=2, service_s=0.08, scrape_interval_s=1.0,
                        warm_s=6.0, step_s=14.0, app="chaos-auto"):
    """The closed-loop scenario: a mode="slo" autoscaled deployment under
    open-loop load. Part A — SIGKILL a replica: the loop (not an operator)
    must restore the running count to target and the burning SLO must return
    to ok within 5 scrape intervals of the burn. Part B — step the offered
    load to 2x: queue depth over target must scale the fleet up and goodput
    after the scale-up must reach >= 1.2x the pre-scale goodput. Returns the
    `autoscale` section for SERVE_CHAOS_BENCH.json."""
    import ray_tpu
    from ray_tpu import serve
    from ray_tpu.util import slo as slo_mod
    from ray_tpu.util.fault_injection import ChaosController
    from ray_tpu.util.state import serve_autoscaler_status

    prev_scrape = os.environ.get("RAY_TPU_METRICS_SCRAPE_INTERVAL_S")
    # the recovery budget is denominated in scrape intervals, so pin the
    # interval for this scenario (the scraper re-reads it live)
    os.environ["RAY_TPU_METRICS_SCRAPE_INTERVAL_S"] = str(scrape_interval_s)
    unsub = None
    gen = gen2 = None
    try:

        @serve.deployment
        class AutoTarget:
            def __call__(self, _body):
                time.sleep(service_s)
                return {"ok": True}

        replicas0 = 2
        cap_per_replica = moq / service_s
        base_rps = 0.8 * replicas0 * cap_per_replica  # busy but unsaturated at 2
        serve.run(AutoTarget.options(
            num_replicas=replicas0, max_ongoing_requests=moq,
            health_check_period_s=0.5,
            autoscaling_config=serve.AutoscalingConfig(
                min_replicas=replicas0, max_replicas=4, mode="slo",
                target_queue_depth=1.5 * moq)).bind(),
            name=app, route_prefix=f"/{app}")
        url = f"http://127.0.0.1:{port}/{app}?x=1"
        gen = _LoadGen(url, max_workers=256)
        run_t0 = time.perf_counter()
        transitions = []
        controller = ray_tpu.get_actor("SERVE_CONTROLLER")

        def running_count():
            info = ray_tpu.get(controller.get_deployment_info.remote(
                app, "AutoTarget"))
            return (info or {}).get("num_running", 0), \
                (info or {}).get("target_num_replicas", 0)

        # warm-up at base load, then derive the SLO threshold from measured p50
        load = threading.Thread(target=gen.run, args=(base_rps, warm_s),
                                daemon=True, name="bench-autoscale-warm")
        load.start()
        time.sleep(warm_s * 0.75)
        warm = gen.window(1.0, time.perf_counter() - run_t0, status=200)
        if not warm:
            raise RuntimeError("autoscale warm-up produced no successful samples "
                               "— serve bring-up failed before the chaos")
        base_lat = [r[1] for r in warm]
        base_p50 = _percentile(base_lat, 0.5)
        thr = max(2.5 * base_p50, 1.2 * (_percentile(base_lat, 0.99) or base_p50))
        slo_mod.register(slo_mod.SLO(
            "autoscale_ttft", metric="serve_ttft_seconds", objective=0.85,
            threshold=thr, window_s=3.0, kind="latency"))
        unsub = slo_mod.subscribe_slo(lambda ev: transitions.append(
            (time.perf_counter() - run_t0, ev["from"], ev["to"])))
        load.join()

        # -- part A: kill a replica mid-load; the loop must replace it ----------
        load = threading.Thread(target=gen.run, args=(base_rps, 12.0),
                                daemon=True, name="bench-autoscale-kill")
        load.start()
        time.sleep(0.5)
        assert ChaosController().kill_replica(app, "AutoTarget", index=0)
        killed_at = time.perf_counter() - run_t0
        # first observe the death land in the controller's view (running < target),
        # THEN time how long the loop takes to get back to target — otherwise the
        # pre-kill view (2/2) would satisfy the check instantly
        death_seen = False
        replaced_s = None
        t_deadline = time.perf_counter() + 11.0
        while time.perf_counter() < t_deadline:
            n, tgt = running_count()
            if not death_seen:
                death_seen = n < max(tgt, replicas0)
            elif n >= tgt >= replicas0:
                replaced_s = round(time.perf_counter() - run_t0 - killed_at, 2)
                break
            time.sleep(0.1)
        load.join()
        burn = next((t for t, _f, to in transitions
                     if to == "burning" and t >= killed_at), None)
        ok_after = next((t for t, f, to in transitions
                         if f == "burning" and to == "ok"
                         and burn is not None and t > burn), None)
        slo_recovery_s = round(ok_after - burn, 2) if burn and ok_after else None
        recovery_budget_s = 5 * scrape_interval_s

        # -- part B: 2x load step -> queue pressure -> scale-up -> goodput ------
        # a FRESH deployment: part A's burn may have already raised the first
        # app's target, which would pollute the pre-scale baseline
        serve.delete(app)
        step_app = f"{app}-step"
        serve.run(AutoTarget.options(
            num_replicas=replicas0, max_ongoing_requests=moq,
            health_check_period_s=0.5,
            autoscaling_config=serve.AutoscalingConfig(
                min_replicas=replicas0, max_replicas=4, mode="slo",
                target_queue_depth=1.5 * moq)).bind(),
            name=step_app, route_prefix=f"/{step_app}")

        def running_count_step():
            info = ray_tpu.get(controller.get_deployment_info.remote(
                step_app, "AutoTarget"))
            return (info or {}).get("num_running", 0), \
                (info or {}).get("target_num_replicas", 0)

        step_rps = 2.0 * base_rps  # 2x the two-replica operating point
        gen2 = _LoadGen(f"http://127.0.0.1:{port}/{step_app}?x=1", max_workers=256)
        step_started = time.perf_counter()  # gen2 records t_sched relative to this
        load = threading.Thread(target=gen2.run, args=(step_rps, step_s),
                                daemon=True, name="bench-autoscale-step")
        load.start()
        scale_up_at = None  # seconds into the step, gen2's clock
        t_deadline = step_started + step_s
        while time.perf_counter() < t_deadline:
            n, tgt = running_count_step()
            if tgt > replicas0 and n >= tgt:
                scale_up_at = time.perf_counter() - step_started
                break
            time.sleep(0.2)
        load.join()
    finally:
        # any mid-scenario failure must not leak the pinned scrape interval,
        # the derived SLO, or its subscriber into the rest of the process
        for g in (gen, gen2):
            if g is not None:
                g.drain()
        if unsub is not None:
            unsub()
        try:
            slo_mod.remove("autoscale_ttft")
        except Exception:
            pass
        if prev_scrape is None:
            os.environ.pop("RAY_TPU_METRICS_SCRAPE_INTERVAL_S", None)
        else:
            os.environ["RAY_TPU_METRICS_SCRAPE_INTERVAL_S"] = prev_scrape
    n_final, tgt_final = running_count_step()

    # goodput before the scale-up landed vs after, windowed on COMPLETION
    # time (submit + latency): nothing is shed here, so submit-windows would
    # just echo the offered rate — completions are what capacity bounds
    with gen2._lock:
        done_at = [(t + lat) for t, lat, st_, _ in gen2.records if st_ == 200]
    drain_end = max(done_at) if done_at else step_s
    split = scale_up_at if scale_up_at is not None else step_s / 3.0
    split = min(max(split, 1.0), step_s - 2.0)
    pre_goodput = sum(1 for d in done_at if d < split) / split
    post_span = max(drain_end, step_s) - split
    post_goodput = (sum(1 for d in done_at if d >= split) / post_span
                    if post_span > 0 else 0.0)
    ratio = post_goodput / pre_goodput if pre_goodput else 0.0

    status = serve_autoscaler_status()
    scale_events = [d for d in status["decisions"] if d.get("event") == "scale"]
    section = {
        "offered_rps_base": round(base_rps, 1),
        "offered_rps_step": round(step_rps, 1),
        "scrape_interval_s": scrape_interval_s,
        "slo_threshold_ms": round(thr * 1e3, 1),
        "replica_replaced_s": replaced_s,
        "slo_transitions": [(round(t, 2), f, to) for t, f, to in transitions],
        "slo_burn_to_ok_s": slo_recovery_s,
        "recovery_budget_s": recovery_budget_s,
        "scale_up_at_s": round(scale_up_at, 2) if scale_up_at else None,
        "final_running": n_final,
        "final_target": tgt_final,
        "pre_scale_goodput_rps": round(pre_goodput, 1),
        "post_scale_goodput_rps": round(post_goodput, 1),
        "goodput_ratio": round(ratio, 3),
        "decisions": scale_events[-8:],
        "loop_alive": status["alive"],
    }
    section["gates"] = {
        "replica_replaced_by_loop": replaced_s is not None,
        "slo_recovered_within_budget": (
            slo_recovery_s is not None
            and slo_recovery_s <= recovery_budget_s),
        "scale_up_observed": scale_up_at is not None and tgt_final > replicas0,
        "goodput_ratio_ge_1_2": ratio >= 1.2,
    }
    section["all_gates_pass"] = all(section["gates"].values())
    return section


def chaos_main():
    # fast control loop for a ~30s bench: scrape + worker metric pushes at
    # 250ms so the SLO engine sees the burn while it is happening
    os.environ.setdefault("RAY_TPU_METRICS_SCRAPE_INTERVAL_S", "0.25")
    os.environ.setdefault("RAY_TPU_METRICS_REPORT_INTERVAL_S", "0.25")
    import ray_tpu
    from ray_tpu import serve

    ray_tpu.init(num_cpus=8, max_workers_per_node=12)
    port = 18440
    results = {"config": "serve-plane chaos (host path, open-loop HTTP load)"}
    try:
        serve.start(http_options={"port": port})
        if TINY:
            results.update(run_chaos_kill(
                port, rps=30.0, service_s=0.06, warm_s=3.0, post_kill_s=9.0))
            results.update(run_chaos_shed(port, phase_s=3.0))
            results["autoscale"] = run_chaos_autoscale(
                port, service_s=0.06, warm_s=4.0, step_s=10.0)
        else:
            results.update(run_chaos_kill(port))
            results.update(run_chaos_shed(port))
            results["autoscale"] = run_chaos_autoscale(port)
        gates = {
            "zero_lost_requests": results["kill_zero_lost"],
            "slo_burn_and_recovery": (results["kill_slo_burn_observed"]
                                      and results["kill_slo_recovery_observed"]),
            "p99_recovered_within_window": results["kill_p99_recovered_in_window"],
            "shed_503_with_retry_after": (results["shed_rejections_observed"]
                                          and results["shed_retry_after_present"]),
            "goodput_within_20pct_at_2x": results["shed_goodput_within_20pct"],
            "autoscale_loop_closed": results["autoscale"]["all_gates_pass"],
        }
        results["gates"] = gates
        results["all_gates_pass"] = all(gates.values())
    finally:
        try:
            serve.shutdown()
        finally:
            ray_tpu.shutdown()
    for k, v in results.items():
        print(f"{k}: {v}")
    out = os.path.join(os.path.dirname(__file__) or ".", "SERVE_CHAOS_BENCH.json")
    with open(out, "w") as f:
        json.dump(results, f, indent=2)
    print(f"wrote {out}")
    if not results.get("all_gates_pass"):
        print("CHAOS GATES FAILED:",
              [k for k, v in results.get("gates", {}).items() if not v])
        sys.exit(1)
    return results


# --------------------------------------------------------------------------
# P/D disaggregation bench (--pd): the per-page overlapped KV handoff and the
# pooled serving topology it feeds. Three gated sections, merged as a "pd"
# dict into SERVE_BENCH.json (non-zero exit on any gate failure):
#   1. paged handoff GB/s at 256 MB between two processes — must clear 3x the
#      monolithic kv_handoff_device_plane_gbps row (0.58 through the tunnel);
#   2. disaggregated vs colocated streaming HTTP on the same load — median
#      TTFT within 1.15x, goodput within 0.95x, zero leaked KV exports;
#   3. chaos: SIGKILL the prefill replica mid-handoff under concurrent
#      requests — zero lost requests, zero leaked exports after recovery.
# --------------------------------------------------------------------------

def _pd_paged_child(role, conn, nbytes, iters):
    """Paged handoff worker: the paged path host-gathers once and streams
    per-page ranged pulls over the striped collective plane — no PJRT
    transfer server needed, unlike the monolithic _kv_handoff_child."""
    import pickle  # noqa: F401  (spawn children re-import the module)

    from ray_tpu.core.device_plane import plane

    n = nbytes // 4
    if role == "producer":
        x = np.ones((n,), np.float32)
        for _ in range(iters + 1):  # +1 warmup; export, send tiny handle, await ack
            h = plane().export_paged({"kv": x})
            conn.send(h)
            conn.recv()
    else:
        conn, result_conn = conn
        # warmup round (stream connections + puller thread spinup): untimed
        h = conn.recv()
        f = plane().fetch_paged(h, release=True)
        f.wait(timeout=300)
        f.result()
        f.recycle()
        conn.send("ok")
        durs = []
        pages = streams = 0
        for _ in range(iters):
            h = conn.recv()
            t0 = time.perf_counter()
            f = plane().fetch_paged(h, release=True)
            f.wait(timeout=300)
            f.result()  # materialize the arrays like a decode admission would
            durs.append(time.perf_counter() - t0)
            f.recycle()  # staging pool reuse, as a steady-state decode replica does
            pages, streams = f.n_pages, f.streams
            conn.send("ok")
        result_conn.send((durs, pages, streams))


def bench_pd_paged_handoff(nbytes=256 * 1024 * 1024, iters=8):
    """GB/s of the per-page P/D handoff between two processes (same two-process
    harness as bench_kv_handoff, so the rows compare like for like)."""
    import multiprocessing as mp
    import secrets

    os.environ.setdefault("RAY_TPU_CLIENT_AUTHKEY", secrets.token_hex(16))
    ctx = mp.get_context("spawn")
    p_end, c_end = ctx.Pipe()
    res_parent, res_child = ctx.Pipe()
    prod = ctx.Process(target=_pd_paged_child,
                       args=("producer", p_end, nbytes, iters))
    cons = ctx.Process(target=_pd_paged_child,
                       args=("consumer", (c_end, res_child), nbytes, iters))
    prod.start()
    cons.start()
    try:
        deadline = time.time() + 600
        while not res_parent.poll(1.0):
            if time.time() > deadline:
                raise TimeoutError("pd paged handoff bench timed out")
            if not (prod.is_alive() and cons.is_alive()):
                raise RuntimeError(
                    f"pd handoff child died (producer rc={prod.exitcode}, "
                    f"consumer rc={cons.exitcode})")
        durs, pages, streams = res_parent.recv()
    finally:
        prod.join(30)
        cons.join(30)
        for p in (prod, cons):
            if p.is_alive():
                p.terminate()
    # median per-handoff time: one scheduler-noise outlier iteration must not
    # misreport the steady-state transfer rate
    t = statistics.median(durs)
    return {
        "paged_handoff_mb": nbytes // (1 << 20),
        "paged_handoff_gbps": round(nbytes / 1e9 / t, 2),
        "paged_handoff_pages": pages,
        "paged_handoff_streams": streams,
    }


def _pd_stream_request(url, body):
    """(ttft_s, total_s, content_chars) for one streaming chat request; TTFT
    is time to the first CONTENT delta (the role prelude frame is free)."""
    import urllib.request

    req = urllib.request.Request(url, data=json.dumps(body).encode(),
                                 headers={"Content-Type": "application/json"})
    t0 = time.perf_counter()
    resp = urllib.request.urlopen(req, timeout=600)
    ttft, chars, buf = None, 0, b""
    while True:
        chunk = resp.read(4096)
        if not chunk:
            break
        buf += chunk
        while b"\n\n" in buf:
            frame, buf = buf.split(b"\n\n", 1)
            text = frame.decode()
            if not text.startswith("data: ") or text == "data: [DONE]":
                continue
            c = json.loads(text[len("data: "):])["choices"][0][
                "delta"].get("content") or ""
            if c and ttft is None:
                ttft = time.perf_counter() - t0
            chars += len(c)
    return ttft, time.perf_counter() - t0, chars


def _pd_stream_load(url, model, n_requests, concurrency, max_tokens):
    """Median TTFT + goodput for n streaming requests at fixed concurrency."""
    import concurrent.futures

    body = {"model": model, "stream": True, "temperature": 0.0,
            "max_tokens": max_tokens,
            "messages": [{"role": "user", "content": "benchmark me"}]}
    # warm every replica's jit caches before timing
    for _ in range(2):
        _pd_stream_request(url, body)
    t0 = time.perf_counter()
    with concurrent.futures.ThreadPoolExecutor(max_workers=concurrency) as ex:
        recs = list(ex.map(lambda _: _pd_stream_request(url, body),
                           range(n_requests)))
    elapsed = time.perf_counter() - t0
    ttfts = sorted(r[0] for r in recs if r[0] is not None)
    return {
        "requests": n_requests,
        "lost": sum(1 for r in recs if r[2] == 0),
        "ttft_median_ms": round(1e3 * ttfts[len(ttfts) // 2], 1) if ttfts else None,
        "goodput_rps": round(n_requests / elapsed, 2),
    }


def _pd_exports_live(handle) -> int:
    return int(handle.options(method_name="metrics").remote().result()[
        "pd_exports_live"])


def _pd_wait_no_leak(handle, timeout_s=15.0) -> int:
    """Release acks are async: poll the prefill pool's live-export gauge to 0."""
    deadline = time.monotonic() + timeout_s
    live = None
    while time.monotonic() < deadline:
        live = _pd_exports_live(handle)
        if live == 0:
            return 0
        time.sleep(0.25)
    return live


def _pd_run_chaos(serve, body) -> dict:
    """SIGKILL the prefill replica while armed delays hold decode pulls open
    mid-transfer; every in-flight request must complete via host fallback."""
    from ray_tpu.util.fault_injection import ChaosController

    h = serve.get_app_handle("pd-chaos-bench")
    want = h.options(method_name="chat").remote(dict(body)).result()
    chaos = ChaosController()
    armed = chaos.arm_replica("pd-chaos-bench", "pd-chaos:decode",
                              "llm.pd.handoff", mode="delay", delay_s=2.0)
    lost, wrong = 0, 0
    lock = threading.Lock()

    def run():
        nonlocal lost, wrong
        try:
            resp = h.options(method_name="chat").remote(dict(body)).result()
            if (resp["choices"][0]["message"]["content"]
                    != want["choices"][0]["message"]["content"]):
                with lock:
                    wrong += 1
        except Exception:
            with lock:
                lost += 1

    threads = [threading.Thread(target=run, daemon=True) for _ in range(4)]
    t0 = time.perf_counter()
    for t in threads:
        t.start()
    time.sleep(0.8)  # prefills done, decode pulls parked in the armed delay
    killed = chaos.kill_replica("pd-chaos-bench", "pd-chaos:prefill", index=0)
    for t in threads:
        t.join(timeout=180)
    hung = sum(1 for t in threads if t.is_alive())
    chaos.disarm_replica("pd-chaos-bench", "pd-chaos:decode")
    leaked = _pd_wait_no_leak(
        serve.get_deployment_handle("pd-chaos:prefill", "pd-chaos-bench"))
    return {
        "chaos_armed_replicas": armed,
        "chaos_replica_killed": bool(killed),
        "chaos_requests": len(threads),
        "chaos_lost": lost + hung,
        "chaos_wrong_output": wrong,
        "chaos_leaked_exports": leaked,
        "chaos_recovery_s": round(time.perf_counter() - t0, 2),
    }


def pd_main():
    """--pd: gate the per-page overlapped KV handoff and the disaggregated
    serving topology against the colocated baseline."""
    import ray_tpu
    from ray_tpu import serve
    from ray_tpu.llm import LLMConfig, build_openai_app, build_pd_openai_app

    out_path = os.path.join(os.path.dirname(__file__) or ".", "SERVE_BENCH.json")
    try:
        with open(out_path) as f:
            results = json.load(f)
    except (OSError, ValueError):
        results = {}
    section = {"config": "test-tiny byte paged" if TINY else
               "llama-500m bf16 paged(block=32)"}

    # 1 — transfer microbench, always at the baseline row's 256 MB size so the
    # 3x gate compares like for like (pure host loopback, no model involved)
    section.update(bench_pd_paged_handoff(
        nbytes=256 * 1024 * 1024, iters=3 if TINY else 8))
    mono_gbps = (results.get("kv_handoff_device_plane_gbps")
                 if results.get("kv_handoff_mb") == 256 else None) or 0.58
    section["monolithic_baseline_gbps"] = mono_gbps
    section["paged_vs_monolithic"] = round(
        section["paged_handoff_gbps"] / mono_gbps, 2)

    # 2 + 3 — serve-level comparisons need a cluster with engine replicas
    n_req, conc, max_tok = (8, 2, 16) if TINY else (24, 4, 48)
    cfg_kw = dict(model_source="test-tiny" if TINY else "llama-500m",
                  tokenizer="byte", max_num_seqs=4,
                  max_model_len=128 if TINY else 512)
    port = 18460
    ray_tpu.init(num_cpus=8, max_workers_per_node=12,
                 worker_env={"JAX_PLATFORMS": "cpu"} if TINY else None)
    try:
        serve.start(http_options={"port": port})
        serve.run(build_openai_app([LLMConfig(model_id="colo", **cfg_kw)]),
                  name="pd-colo-bench", route_prefix="/colo")
        serve.run(build_pd_openai_app(LLMConfig(model_id="pd", **cfg_kw),
                                      name_prefix="pd-bench"),
                  name="pd-disagg-bench", route_prefix="/pd")
        colo = _pd_stream_load(f"http://127.0.0.1:{port}/colo/chat/completions",
                               "colo", n_req, conc, max_tok)
        disagg = _pd_stream_load(f"http://127.0.0.1:{port}/pd/chat/completions",
                                 "pd", n_req, conc, max_tok)
        section["colocated"] = colo
        section["disaggregated"] = disagg
        section["ttft_ratio"] = round(
            disagg["ttft_median_ms"] / colo["ttft_median_ms"], 3)
        section["goodput_ratio"] = round(
            disagg["goodput_rps"] / colo["goodput_rps"], 3)
        section["leaked_exports_after_load"] = _pd_wait_no_leak(
            serve.get_deployment_handle("pd-bench:prefill", "pd-disagg-bench"))

        serve.run(build_pd_openai_app(
            LLMConfig(model_id="pd-chaos", **cfg_kw), name_prefix="pd-chaos"),
            name="pd-chaos-bench", route_prefix="/pd-chaos")
        section.update(_pd_run_chaos(serve, {
            "model": "pd-chaos", "temperature": 0.0, "max_tokens": max_tok,
            "messages": [{"role": "user", "content": "benchmark me"}]}))
    finally:
        try:
            serve.shutdown()
        finally:
            ray_tpu.shutdown()

    gates = {
        "paged_3x_monolithic": (
            section["paged_handoff_gbps"] >= 3 * mono_gbps),
        "ttft_within_1_15x": section["ttft_ratio"] <= 1.15,
        "goodput_within_0_95x": section["goodput_ratio"] >= 0.95,
        "zero_lost_under_load": (colo["lost"] == 0 and disagg["lost"] == 0),
        "zero_leaked_exports": section["leaked_exports_after_load"] == 0,
        "chaos_zero_lost": (section["chaos_lost"] == 0
                            and section["chaos_wrong_output"] == 0
                            and section["chaos_replica_killed"]),
        "chaos_zero_leaked": section["chaos_leaked_exports"] == 0,
    }
    section["gates"] = {k: bool(v) for k, v in gates.items()}
    section["all_gates_pass"] = all(section["gates"].values())
    results["pd"] = section
    for k, v in sorted(section.items()):
        print(f"pd.{k}: {v}")
    with open(out_path, "w") as f:
        json.dump(results, f, indent=2)
    print(f"wrote {out_path}")
    if not section["all_gates_pass"]:
        print("PD GATES FAILED:",
              [k for k, v in section["gates"].items() if not v])
        sys.exit(1)
    return results


def main():
    import jax

    rng = np.random.default_rng(0)
    prompt_len = 64 if TINY else 512
    gen_tokens = 32 if TINY else 128
    results = {"config": "test-tiny" if TINY else
               "llama-500m bf16 paged(block=32, blocks=auto) max_len=1024",
               "platform": jax.devices()[0].platform,
               "note": ("the DEFAULT engine mode is now fused multi-step "
                        "decode (auto-tuned K, RAY_TPU_LLM_FUSED_STEPS=0): "
                        "the decode rows below ride token bursts, one host "
                        "sync per K tokens. Through the axon tunnel that "
                        "round trip is ~100-150ms, so auto-K grows until the "
                        "sync share is bounded; `python bench_serve.py "
                        "--engine` writes the per-step baseline and the "
                        "engine-vs-device-ceiling gates")}
    engine = make_engine()
    try:
        warmup(engine, rng, prompt_len, 4)
        results.update(bench_ttft_and_prefill(engine, rng, prompt_len))
        for batch in (1, 8) + (() if TINY else (32,)):
            results.update(bench_decode(engine, rng, batch, prompt_len, gen_tokens))
        results.update(bench_prefix_cache(engine, rng, prompt_len))
    finally:
        engine.shutdown()
    # fused multi-step decode (num_decode_steps=8): ONE host sync per 8 tokens
    # amortizes the per-step round trip — the tunnel-dominated numbers above
    # are the honest single-step baseline, this is the deployment setting
    engine = make_engine(num_decode_steps=8)
    try:
        warmup(engine, rng, prompt_len, 4)
        for batch in (1, 8) + (() if TINY else (32,)):
            ms = bench_decode(engine, rng, batch, prompt_len, gen_tokens)
            results.update({f"{k}_fused8": v for k, v in ms.items()})
    finally:
        engine.shutdown()
    results.update(bench_preemption(rng))
    for batch in (1, 8) + (() if TINY else (32,)):
        results.update(bench_device_decode(
            batch, k=8 if TINY else 64, n_bursts=2 if TINY else 16,
            prompt_len=64 if TINY else 512))
    # int8 weight-only decode: same loop, half the weight bytes per step
    for batch in (1, 8):
        results.update(bench_device_decode(
            batch, k=8 if TINY else 64, n_bursts=2 if TINY else 16,
            prompt_len=64 if TINY else 512, quant="int8"))
    for batch in (1, 8):
        results.update(bench_spec_modes(batch, gen_tokens=24 if TINY else 96))
    results.update(bench_spec_trained(gen_tokens=24 if TINY else 96))
    try:
        results.update(bench_kv_handoff(
            nbytes=(8 if TINY else 256) * 1024 * 1024, iters=4))
        results["kv_handoff_note"] = (
            "two CPU-backend processes on one host: both paths are host-memory "
            "loopback, so the device plane's 'speedup' here is pickle/copy "
            "overhead only — on pods the pull rides DCN and skips D2H/H2D entirely")
    except Exception as e:  # noqa: BLE001 — plane unsupported: record why
        results["kv_handoff_error"] = f"{type(e).__name__}: {e}"
    for k, v in results.items():
        print(f"{k}: {v}")
    with open(os.path.join(os.path.dirname(__file__) or ".", "SERVE_BENCH.json"), "w") as f:
        json.dump(results, f, indent=2)
    print("wrote SERVE_BENCH.json")


if __name__ == "__main__":
    if "--chaos" in sys.argv:
        chaos_main()
    elif "--engine" in sys.argv:
        engine_main()
    elif "--pd" in sys.argv:
        pd_main()
    else:
        main()
