"""RLlib + Data benchmarks: the two north-star workloads without committed numbers
until round 4 (VERDICT r3 item 2).

- PPO CartPole: env-steps/s sampled + learner minibatch updates/s, the
  reference's rllib/benchmarks/ppo shape (benchmark_ppo_mujoco.py measures the
  same two rates).
- PPO on a synthetic Atari-shaped env (84x84x4 uint8 obs, Discrete(6)): stresses
  observation transport rollout -> GAE -> learner at Atari payload sizes without
  needing ALE (reference rllib/tuned_examples/ppo/atari_ppo.py geometry).
- Data: rows/s through a two-stage map_batches batch-inference pipeline on the
  pull-based streaming executor (reference release/nightly_tests/dataset/).

Writes RL_BENCH.json. Runs on the CPU sandbox: absolute rates are bounded by the
4-CPU worker pool and Python env stepping, not by the framework's data paths —
the numbers exist to make regressions visible and to prove the pipelines run at
realistic payload sizes.

Run: python bench_rllib.py [--quick]
"""
import json
import os
import sys
import time

import numpy as np

QUICK = "--quick" in sys.argv


class SyntheticAtariEnv:
    """Atari-shaped observations at CartPole cost: random uint8 frames stamped
    from a pre-generated bank, fixed-length episodes, dense random reward."""

    metadata = {"render_modes": []}
    render_mode = None
    spec = None

    def __init__(self, config=None):
        import gymnasium as gym

        config = config or {}
        self.observation_space = gym.spaces.Box(0, 255, (84, 84, 4), np.uint8)
        self.action_space = gym.spaces.Discrete(6)
        self.ep_len = int(config.get("ep_len", 200))
        self._bank = np.random.default_rng(0).integers(
            0, 255, size=(16, 84, 84, 4), dtype=np.uint8)
        self._t = 0

    def reset(self, *, seed=None, options=None):
        self._t = 0
        return self._bank[0], {}

    def step(self, action):
        self._t += 1
        obs = self._bank[self._t % len(self._bank)]
        done = self._t >= self.ep_len
        return obs, float(action == 1), done, False, {}

    def close(self):
        pass


def bench_ppo(env, name, *, train_batch, minibatch, epochs, iters, model_config=None):
    from ray_tpu.rllib.algorithms.ppo import PPOConfig

    cfg = (
        PPOConfig()
        .environment(env)
        .env_runners(num_env_runners=2, num_envs_per_env_runner=4,
                     rollout_fragment_length=64)
        .training(lr=3e-4, train_batch_size=train_batch, minibatch_size=minibatch,
                  num_epochs=epochs, gamma=0.99, lambda_=0.95, clip_param=0.3,
                  entropy_coeff=0.01)
        .debugging(seed=0)
    )
    if model_config:
        cfg.rl_module(model_config=model_config)
    algo = cfg.build_algo()
    try:
        algo.train()  # warmup: jit compiles, env resets — excluded from timing
        t0 = time.perf_counter()
        returns = []
        for _ in range(iters):
            r = algo.train()
            returns.append(r.get("episode_return_mean") or 0.0)
        dt = time.perf_counter() - t0
        env_steps = iters * train_batch
        updates = iters * epochs * (train_batch // minibatch)
        return {
            f"ppo_{name}_env_steps_per_s": round(env_steps / dt, 1),
            f"ppo_{name}_learner_updates_per_s": round(updates / dt, 1),
            f"ppo_{name}_iters": iters,
            f"ppo_{name}_final_return": round(float(returns[-1]), 1),
        }
    finally:
        algo.cleanup()


def bench_data(total_rows):
    """Two-stage batch-inference pipeline: transform -> 'model' matmul, pulled
    through the streaming executor with actor-pool concurrency."""
    import ray_tpu.data as rtd

    w = np.random.default_rng(0).standard_normal((64, 8)).astype(np.float32)

    def featurize(batch):
        x = np.asarray(batch["id"], np.float32)
        feats = np.stack([x * s for s in np.linspace(0.1, 6.4, 64)], axis=1)
        return {"feats": feats}

    def infer(batch):
        return {"pred": np.asarray(batch["feats"], np.float32) @ w}

    # warmup a small pipeline (worker spin-up + import cost out of the timing)
    (rtd.range(1024, parallelism=4).map_batches(featurize, concurrency=2)
        .map_batches(infer, concurrency=2).materialize())

    t0 = time.perf_counter()
    ds = (rtd.range(total_rows, parallelism=16)
          .map_batches(featurize, concurrency=2)
          .map_batches(infer, concurrency=2))
    n = 0
    for batch in ds.iter_batches():
        n += len(batch["pred"])
    dt = time.perf_counter() - t0
    assert n == total_rows, (n, total_rows)
    return {
        "data_pipeline_rows": total_rows,
        "data_pipeline_rows_per_s": round(total_rows / dt, 1),
        "data_pipeline_stages": "range -> featurize(64f) -> matmul(64x8), "
                                "actor concurrency 2+2, streaming executor",
    }


def main():
    import ray_tpu

    ray_tpu.init(num_cpus=4, worker_env={"JAX_PLATFORMS": "cpu"})
    results = {
        "note": ("CPU sandbox, 4-CPU worker pool: PPO rates are bounded by "
                 "Python gym stepping + host GAE, Data rates by pickled block "
                 "transport between actor-pool workers — not by the device "
                 "paths these pipelines feed on TPU hardware.")
    }
    try:
        results.update(bench_ppo(
            "CartPole-v1", "cartpole",
            train_batch=1024, minibatch=256, epochs=4, iters=2 if QUICK else 8))
        results.update(bench_ppo(
            SyntheticAtariEnv, "atari_synth",
            train_batch=512, minibatch=128, epochs=2, iters=1 if QUICK else 4))
        results.update(bench_data(4096 if QUICK else 100_000))
    finally:
        ray_tpu.shutdown()
    for k, v in results.items():
        print(f"{k}: {v}")
    with open(os.path.join(os.path.dirname(__file__) or ".", "RL_BENCH.json"), "w") as f:
        json.dump(results, f, indent=2)
    print("wrote RL_BENCH.json")


if __name__ == "__main__":
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    main()
