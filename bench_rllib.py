"""RLlib + Data benchmarks: the two north-star workloads without committed numbers
until round 4 (VERDICT r3 item 2).

- PPO CartPole: env-steps/s sampled + learner minibatch updates/s, the
  reference's rllib/benchmarks/ppo shape (benchmark_ppo_mujoco.py measures the
  same two rates).
- PPO on a synthetic Atari-shaped env (84x84x4 uint8 obs, Discrete(6)): stresses
  observation transport rollout -> GAE -> learner at Atari payload sizes without
  needing ALE (reference rllib/tuned_examples/ppo/atari_ppo.py geometry).
- Data: rows/s through a two-stage map_batches batch-inference pipeline on the
  pull-based streaming executor (reference release/nightly_tests/dataset/).

Writes RL_BENCH.json. Runs on the CPU sandbox: absolute rates are bounded by the
4-CPU worker pool and Python env stepping, not by the framework's data paths —
the numbers exist to make regressions visible and to prove the pipelines run at
realistic payload sizes.

Run: python bench_rllib.py [--quick]
"""
import json
import os
import sys
import time

import numpy as np

QUICK = "--quick" in sys.argv


class SyntheticAtariEnv:
    """Atari-shaped observations at CartPole cost: random uint8 frames stamped
    from a pre-generated bank, fixed-length episodes, dense random reward."""

    metadata = {"render_modes": []}
    render_mode = None
    spec = None

    def __init__(self, config=None):
        import gymnasium as gym

        config = config or {}
        self.observation_space = gym.spaces.Box(0, 255, (84, 84, 4), np.uint8)
        self.action_space = gym.spaces.Discrete(6)
        self.ep_len = int(config.get("ep_len", 200))
        self._bank = np.random.default_rng(0).integers(
            0, 255, size=(16, 84, 84, 4), dtype=np.uint8)
        self._t = 0

    def reset(self, *, seed=None, options=None):
        self._t = 0
        return self._bank[0], {}

    def step(self, action):
        self._t += 1
        obs = self._bank[self._t % len(self._bank)]
        done = self._t >= self.ep_len
        return obs, float(action == 1), done, False, {}

    def close(self):
        pass

    @classmethod
    def make_vec(cls, num_envs, config=None):
        return SyntheticAtariVectorEnv(num_envs, config)


class SyntheticAtariVectorEnv:
    """Natively-vectorized SyntheticAtariEnv: one numpy-batched step for all
    envs instead of gymnasium SyncVectorEnv's per-env Python loop. Semantics
    match SyncVectorEnv over SyntheticAtariEnv exactly, including gymnasium
    1.x next-step autoreset (a done env's next step ignores the action and
    returns the new episode's first obs with zero reward)."""

    def __init__(self, num_envs, config=None):
        import gymnasium as gym

        config = config or {}
        self.num_envs = int(num_envs)
        self.single_observation_space = gym.spaces.Box(0, 255, (84, 84, 4), np.uint8)
        self.single_action_space = gym.spaces.Discrete(6)
        self.ep_len = int(config.get("ep_len", 200))
        self._bank = np.random.default_rng(0).integers(
            0, 255, size=(16, 84, 84, 4), dtype=np.uint8)
        self._t = np.zeros(self.num_envs, dtype=np.int64)
        self._needs_reset = np.zeros(self.num_envs, dtype=bool)

    def reset(self, *, seed=None, options=None):
        self._t[:] = 0
        self._needs_reset[:] = False
        return np.broadcast_to(
            self._bank[0], (self.num_envs,) + self._bank.shape[1:]).copy(), {}

    def step(self, actions):
        actions = np.asarray(actions)
        resetting = self._needs_reset
        self._t = np.where(resetting, 0, self._t + 1)
        obs = self._bank[self._t % len(self._bank)]
        rewards = np.where(resetting, 0.0, (actions == 1).astype(np.float64))
        term = np.where(resetting, False, self._t >= self.ep_len)
        trunc = np.zeros(self.num_envs, dtype=bool)
        self._needs_reset = term.copy()
        return obs, rewards, term, trunc, {}

    def close(self):
        pass


def bench_ppo(env, name, *, train_batch, minibatch, epochs, iters, model_config=None):
    from ray_tpu.rllib.algorithms.ppo import PPOConfig

    cfg = (
        PPOConfig()
        .environment(env)
        .env_runners(num_env_runners=2, num_envs_per_env_runner=4,
                     rollout_fragment_length=64)
        .training(lr=3e-4, train_batch_size=train_batch, minibatch_size=minibatch,
                  num_epochs=epochs, gamma=0.99, lambda_=0.95, clip_param=0.3,
                  entropy_coeff=0.01)
        .debugging(seed=0)
    )
    if model_config:
        cfg.rl_module(model_config=model_config)
    algo = cfg.build_algo()
    try:
        algo.train()  # warmup: jit compiles, env resets — excluded from timing
        t0 = time.perf_counter()
        returns = []
        for _ in range(iters):
            r = algo.train()
            returns.append(r.get("episode_return_mean") or 0.0)
        dt = time.perf_counter() - t0
        env_steps = iters * train_batch
        updates = iters * epochs * (train_batch // minibatch)
        return {
            f"ppo_{name}_env_steps_per_s": round(env_steps / dt, 1),
            f"ppo_{name}_learner_updates_per_s": round(updates / dt, 1),
            f"ppo_{name}_iters": iters,
            f"ppo_{name}_final_return": round(float(returns[-1]), 1),
        }
    finally:
        algo.cleanup()


def bench_data(total_rows):
    """Two-stage batch-inference pipeline: transform -> 'model' matmul, pulled
    through the streaming executor with actor-pool concurrency."""
    import ray_tpu.data as rtd

    w = np.random.default_rng(0).standard_normal((64, 8)).astype(np.float32)

    def featurize(batch):
        x = np.asarray(batch["id"], np.float32)
        feats = np.stack([x * s for s in np.linspace(0.1, 6.4, 64)], axis=1)
        return {"feats": feats}

    def infer(batch):
        return {"pred": np.asarray(batch["feats"], np.float32) @ w}

    # warmup a small pipeline (worker spin-up + import cost out of the timing)
    (rtd.range(1024, parallelism=4).map_batches(featurize, concurrency=2)
        .map_batches(infer, concurrency=2).materialize())

    t0 = time.perf_counter()
    ds = (rtd.range(total_rows, parallelism=16)
          .map_batches(featurize, concurrency=2)
          .map_batches(infer, concurrency=2))
    n = 0
    for batch in ds.iter_batches():
        n += len(batch["pred"])
    dt = time.perf_counter() - t0
    assert n == total_rows, (n, total_rows)
    return {
        "data_pipeline_rows": total_rows,
        "data_pipeline_rows_per_s": round(total_rows / dt, 1),
        "data_pipeline_stages": "range -> featurize(64f) -> matmul(64x8), "
                                "actor concurrency 2+2, streaming executor",
    }


def bench_shuffle(total_rows, parallelism=16):
    """Sort throughput, PULL vs PUSH shuffle (VERDICT r4 weak 5: the push
    scheduler existed for perf but was only correctness-tested). Reference:
    push_based_shuffle_task_scheduler.py — push bounds reduce fan-in with
    rounds of `merge_factor` eagerly folded into running merges, trading more
    (smaller) merge tasks for never holding every map output at once."""
    import ray_tpu.data as rtd
    from ray_tpu.data.context import DataContext

    rng = np.random.default_rng(0)
    vals = rng.integers(0, 1 << 30, total_rows)

    def run(push, merge_factor=8):
        ctx = DataContext.get_current()
        prev = (ctx.use_push_based_shuffle, ctx.push_shuffle_merge_factor)
        ctx.use_push_based_shuffle = push
        ctx.push_shuffle_merge_factor = merge_factor
        try:
            t0 = time.perf_counter()
            ds = (rtd.range(total_rows, parallelism=parallelism)
                  .map_batches(lambda b: {"key": vals[np.asarray(b["id"])]})
                  .sort("key"))
            n, last = 0, -1
            for batch in ds.iter_batches():
                k = np.asarray(batch["key"])
                assert k.size == 0 or (last <= k[0] and (np.diff(k) >= 0).all())
                if k.size:
                    last = int(k[-1])
                n += k.size
            dt = time.perf_counter() - t0
            assert n == total_rows, (n, total_rows)
            return round(total_rows / dt, 1)
        finally:
            ctx.use_push_based_shuffle, ctx.push_shuffle_merge_factor = prev

    run(False)  # warmup: worker spin-up out of the timing
    pull = run(False)
    push_by_factor = {f: run(True, f) for f in (4, 8, 16)}
    best_factor = max(push_by_factor, key=push_by_factor.get)
    return {
        "shuffle_sort_rows": total_rows,
        "shuffle_sort_pull_rows_per_s": pull,
        "shuffle_sort_push_rows_per_s": push_by_factor[best_factor],
        "shuffle_push_merge_factor": best_factor,
        "shuffle_push_by_merge_factor": push_by_factor,
        "shuffle_note": (
            "single-host sandbox: push's bounded fan-in pays off at map-task "
            "counts >> merge_factor and under memory pressure (its reason to "
            "exist on pods); at small scale the extra merge rounds cost more"),
    }


def _tpu_learner_body(batch=4096, minibatch=1024, iters=20):
    """PPO learner update jitted on THIS process's default jax backend
    (VERDICT r4 weak 6: RL gets a device-side number). Synthetic GAE-processed
    batch + toy MLP — measures the jitted loss->grad->adam path, not gym."""
    import time as _time

    import gymnasium as gym
    import jax
    import numpy as _np

    from ray_tpu.rllib.algorithms.ppo import PPOConfig, PPOLearner
    from ray_tpu.rllib.core.rl_module import Columns, RLModuleSpec

    obs_dim, n_act = 64, 6
    cfg = (PPOConfig().training(lr=3e-4, train_batch_size=batch,
                                minibatch_size=minibatch, num_epochs=1)
           .debugging(seed=0))
    learner = PPOLearner(cfg, RLModuleSpec(
        observation_space=gym.spaces.Box(-1.0, 1.0, (obs_dim,), _np.float32),
        action_space=gym.spaces.Discrete(n_act),
        model_config={"fcnet_hiddens": [256, 256]}))
    learner.build()
    rng = _np.random.default_rng(0)
    b = {
        Columns.OBS: rng.standard_normal((batch, obs_dim)).astype(_np.float32),
        Columns.ACTIONS: rng.integers(0, n_act, batch).astype(_np.int32),
        Columns.ACTION_LOGP: _np.full((batch,), -_np.log(n_act), _np.float32),
        Columns.ADVANTAGES: rng.standard_normal(batch).astype(_np.float32),
        Columns.VALUE_TARGETS: rng.standard_normal(batch).astype(_np.float32),
    }
    learner.update(b)  # warmup: jit compile excluded from timing
    t0 = _time.perf_counter()
    for _ in range(iters):
        learner.update(b)
    dt = _time.perf_counter() - t0
    updates = iters * (batch // minibatch)
    return {
        "tpu_learner_backend": jax.default_backend(),
        "tpu_learner_batch": batch,
        "tpu_learner_minibatch": minibatch,
        "tpu_learner_updates_per_s": round(updates / dt, 1),
        "tpu_learner_update_ms": round(dt / updates * 1e3, 3),
    }


def bench_tpu_learner():
    """Run _tpu_learner_body in a subprocess WITHOUT JAX_PLATFORMS=cpu so the
    real accelerator (axon/libtpu) is visible while the driver stays on CPU."""
    import subprocess
    import sys

    env = {k: v for k, v in os.environ.items() if k != "JAX_PLATFORMS"}
    proc = subprocess.run(
        [sys.executable, "-c",
         "import json, bench_rllib; "
         "print('RESULT ' + json.dumps(bench_rllib._tpu_learner_body()))"],
        cwd=os.path.dirname(os.path.abspath(__file__)), env=env,
        capture_output=True, text=True, timeout=900)
    if proc.returncode != 0:
        return {"tpu_learner_error": proc.stderr.strip()[-400:]}
    line = next(ln for ln in proc.stdout.splitlines() if ln.startswith("RESULT "))
    return json.loads(line[len("RESULT "):])


def main():
    import ray_tpu

    ray_tpu.init(num_cpus=4, worker_env={"JAX_PLATFORMS": "cpu"})
    results = {
        "note": ("CPU sandbox, 4-CPU worker pool: PPO rates are bounded by "
                 "Python gym stepping + host GAE, Data rates by pickled block "
                 "transport between actor-pool workers — not by the device "
                 "paths these pipelines feed on TPU hardware.")
    }
    try:
        results.update(bench_ppo(
            "CartPole-v1", "cartpole",
            train_batch=1024, minibatch=256, epochs=4, iters=2 if QUICK else 8))
        results.update(bench_ppo(
            SyntheticAtariEnv, "atari_synth",
            train_batch=512, minibatch=128, epochs=2, iters=1 if QUICK else 4))
        results.update(bench_data(4096 if QUICK else 100_000))
        results.update(bench_shuffle(8192 if QUICK else 200_000))
        results.update(bench_tpu_learner())
    finally:
        ray_tpu.shutdown()
    for k, v in results.items():
        print(f"{k}: {v}")
    with open(os.path.join(os.path.dirname(__file__) or ".", "RL_BENCH.json"), "w") as f:
        json.dump(results, f, indent=2)
    print("wrote RL_BENCH.json")


if __name__ == "__main__":
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    main()
