"""gRPC registration glue for serve_demo.proto (what grpc_tools.protoc's
grpc_python plugin would emit; the build image has no plugin, so this is
hand-maintained — same shape, nothing more)."""
import grpc

from ray_tpu.protos import serve_demo_pb2 as pb


def add_EchoServiceServicer_to_server(servicer, server):
    rpc_method_handlers = {
        "Echo": grpc.unary_unary_rpc_method_handler(
            servicer.Echo,
            request_deserializer=pb.EchoRequest.FromString,
            response_serializer=pb.EchoReply.SerializeToString,
        ),
        "Double": grpc.unary_unary_rpc_method_handler(
            servicer.Double,
            request_deserializer=pb.EchoRequest.FromString,
            response_serializer=pb.EchoReply.SerializeToString,
        ),
    }
    handler = grpc.method_handlers_generic_handler(
        "rt_serve_demo.EchoService", rpc_method_handlers)
    server.add_generic_rpc_handlers((handler,))


class EchoServiceStub:
    def __init__(self, channel):
        self.Echo = channel.unary_unary(
            "/rt_serve_demo.EchoService/Echo",
            request_serializer=pb.EchoRequest.SerializeToString,
            response_deserializer=pb.EchoReply.FromString,
        )
        self.Double = channel.unary_unary(
            "/rt_serve_demo.EchoService/Double",
            request_serializer=pb.EchoRequest.SerializeToString,
            response_deserializer=pb.EchoReply.FromString,
        )
