"""Hot-path telemetry: a per-process lock-light ring buffer of timeline events.

The metrics registry (util/metrics.py) answers "how much / how fast" with
counters and histograms; this module answers "when and for how long" with
nanosecond-timestamped events that merge into ONE cross-worker chrome-trace
timeline (util/state.telemetry_timeline). Instrumentation points live on the
hottest paths in the system — data-plane pulls, collective phases, serve
request lifecycles, train steps — so the recorder is built around two rules:

  near-zero when disabled   every probe is `if telemetry.enabled():` around a
                            memoized env read (~0.1us) plus nothing. span()
                            returns a shared no-op context manager, never a
                            fresh generator frame.
  bounded when enabled      events land in a deque(maxlen=ring_size): memory
                            is capped, the hot path never blocks on a slow
                            consumer, and overflow silently drops the OLDEST
                            events (the flush thread logs — never print()s —
                            a throttled warning with the drop count so lost
                            history is visible without corrupting worker
                            stdout or tqdm progress bars).

Enablement rides the tracing switch: RAY_TPU_TRACING=1 (or
tracing.enable_tracing() / telemetry.enable()) turns both the span tracer and
this recorder on. Ring capacity: RAY_TPU_TELEMETRY_RING_SIZE.

Transport: worker processes flush their ring to the head over the same
control-pipe push the metrics registry uses (core/worker.py push_telemetry ->
core/node.py "telemetry" message), tagged with a clock offset measured against
the head via an NTP-style state_request("head_clock_ns") handshake — so the
merged timeline's timestamps are comparable across processes. The in-process
driver keeps events local; util/state folds them in on read.

Usage:
    from ray_tpu.util import telemetry
    with telemetry.span("transfer.pull", "transfer", bytes=n):
        ...
    telemetry.event("collective.abort", "collective", group=g, epoch=e)
"""
from __future__ import annotations

import logging
import os
import threading
import time
from collections import deque
from typing import Any, Dict, List, Optional

from ray_tpu.config import memoized_flag

logger = logging.getLogger("ray_tpu.telemetry")

_tracing_flag = memoized_flag("tracing")
_ring_size_flag = memoized_flag("telemetry_ring_size")

# tri-state override: None = the RAY_TPU_TRACING env decides; True/False from
# enable()/disable() wins (bench toggles between rounds without re-spawning)
_forced: Optional[bool] = None

_lock = threading.Lock()
_ring: deque = deque(maxlen=8192)
_dropped = 0  # events lost to ring overflow since the last flush/drain
_flush_thread: Optional[threading.Thread] = None
_clock_offset_ns: Optional[int] = None  # head_clock - local_clock (workers)


def enabled() -> bool:
    """THE hot-path gate: a memoized env read + one comparison."""
    if _forced is not None:
        return _forced
    return bool(_tracing_flag())


def enable() -> None:
    """Force-enable in this process (bench/test toggle; env untouched)."""
    global _forced
    _forced = True


def disable() -> None:
    global _forced
    _forced = False


def reset_forced() -> None:
    """Back to env-driven enablement (RAY_TPU_TRACING)."""
    global _forced
    _forced = None


def _resize_ring_locked() -> None:
    global _ring
    want = max(64, int(_ring_size_flag() or 8192))
    if _ring.maxlen != want:
        _ring = deque(_ring, maxlen=want)


# ------------------------------------------------------------------ recording

def _active_trace_id() -> Optional[str]:
    """The caller's request-scoped trace id (util/tracing.py contextvar), or
    None. Events recorded inside a traced request are tagged with it so
    state.request_trace can attribute data-plane pulls / engine phases to the
    request's critical path. Pure read — never starts a trace."""
    try:
        from ray_tpu.util import tracing

        return tracing.current_trace_id()
    # graftlint: allow[swallowed-exception] degrades to the coded fallback (return None) by design
    except Exception:
        return None


def _tag_trace(args: Dict[str, Any]) -> Dict[str, Any]:
    if "trace_id" not in args:
        tid = _active_trace_id()
        if tid is not None:
            args["trace_id"] = tid
    elif args["trace_id"] is None:
        del args["trace_id"]  # explicit "untraced" from a lifecycle recorder
    return args


def _append(rec: dict) -> None:
    global _dropped
    with _lock:
        _resize_ring_locked()
        if len(_ring) == _ring.maxlen:
            _dropped += 1
        _ring.append(rec)
    _ensure_flush_thread()


def event(name: str, cat: str = "app", **args: Any) -> None:
    """Record an instant event (chrome-trace 'i' phase) at now."""
    if not enabled():
        return
    _append({
        "name": name, "cat": cat, "ts_ns": time.time_ns(), "dur_ns": None,
        "tid": threading.current_thread().name, "args": _tag_trace(args or {}),
    })


class _Span:
    """A lightweight timed region. Duration from perf_counter_ns (monotonic,
    ns resolution); the wall anchor from time_ns at entry places it on the
    shared timeline. Extra attributes may be attached mid-span via set()."""

    __slots__ = ("name", "cat", "args", "_t0_wall", "_t0_perf")

    def __init__(self, name: str, cat: str, args: Dict[str, Any]):
        self.name = name
        self.cat = cat
        self.args = args

    def set(self, **kw: Any) -> None:
        self.args.update(kw)

    def __enter__(self) -> "_Span":
        # the trace tag is captured at ENTRY (the request thread); __exit__
        # may run after the contextvar was reset
        _tag_trace(self.args)
        self._t0_wall = time.time_ns()
        self._t0_perf = time.perf_counter_ns()
        return self

    def __exit__(self, exc_type, exc, tb) -> None:
        dur = time.perf_counter_ns() - self._t0_perf
        if exc_type is not None:
            self.args["error"] = exc_type.__name__
        _append({
            "name": self.name, "cat": self.cat, "ts_ns": self._t0_wall,
            "dur_ns": dur, "tid": threading.current_thread().name,
            "args": self.args,
        })


class _NoopSpan:
    """Shared disabled-path context manager: no allocation per probe."""

    __slots__ = ()

    def set(self, **kw: Any) -> None:
        pass

    def __enter__(self) -> "_NoopSpan":
        return self

    def __exit__(self, *exc) -> None:
        pass


_NOOP = _NoopSpan()


def span(name: str, cat: str = "app", **args: Any):
    """Context manager recording a complete ('X') event around the block."""
    if not enabled():
        return _NOOP
    return _Span(name, cat, dict(args))


def complete(name: str, cat: str, start_wall_ns: int, dur_ns: int,
             **args: Any) -> None:
    """Record a complete event whose timing the caller already measured
    (request lifecycles that start and end on different threads)."""
    if not enabled():
        return
    _append({
        "name": name, "cat": cat, "ts_ns": int(start_wall_ns),
        "dur_ns": int(dur_ns), "tid": threading.current_thread().name,
        "args": _tag_trace(args or {}),
    })


# ------------------------------------------------------------------- draining

def drain() -> List[dict]:
    """Pop every buffered event (oldest first). Used by the flush thread and
    by util/state for the in-process driver's ring."""
    global _dropped
    with _lock:
        out = list(_ring)
        _ring.clear()
        n_dropped, _dropped = _dropped, 0
    if n_dropped:
        # logger, NEVER print(): worker stdout/stderr interleaves with tqdm
        # progress bars and the head's log capture — a raw print here would
        # corrupt both. Finalize any in-progress bar line first so the warning
        # starts on its own line.
        try:
            from ray_tpu.experimental.tqdm_ray import ensure_newline

            ensure_newline()
        # graftlint: allow[swallowed-exception] a torn tqdm bar must never block the overflow warning itself
        except Exception:
            pass
        logger.warning(
            "telemetry ring overflowed: %d event(s) dropped (raise "
            "RAY_TPU_TELEMETRY_RING_SIZE or flush more often)", n_dropped)
    return out


def pending() -> int:
    with _lock:
        return len(_ring)


# -------------------------------------------------------------------- flushing

def clock_offset_ns() -> int:
    """head_clock - local_clock, measured once per process with an NTP-style
    request/response handshake against the head (midpoint of the round trip
    taken as the simultaneity point). The driver holding the cluster IS the
    head clock: offset 0."""
    global _clock_offset_ns
    if _clock_offset_ns is not None:
        return _clock_offset_ns
    from ray_tpu.core import global_state

    if global_state.try_cluster() is not None:
        _clock_offset_ns = 0
        return 0
    w = global_state.try_worker()
    if w is None or not hasattr(w, "state_request"):
        _clock_offset_ns = 0
        return 0
    try:
        t0 = time.time_ns()
        head_ns = int(w.state_request("head_clock_ns"))
        t1 = time.time_ns()
        _clock_offset_ns = head_ns - (t0 + t1) // 2
    # graftlint: allow[swallowed-exception] degrades to the coded fallback (_clock_offset_ns = 0) by design
    except Exception:
        _clock_offset_ns = 0
    return _clock_offset_ns


def flush() -> None:
    """Push buffered events to the head now (worker / remote client driver);
    the in-process driver keeps its ring local for util/state to fold in."""
    from ray_tpu.core import global_state

    w = global_state.try_worker()
    if (w is None or not hasattr(w, "push_telemetry")
            or global_state.try_cluster() is not None):
        return
    offset = clock_offset_ns()
    events = drain()
    if not events:
        return
    try:
        w.push_telemetry({"clock_offset_ns": offset, "events": events,
                          "pid": os.getpid()})
    # graftlint: allow[swallowed-exception] telemetry flush is best-effort; the ring re-drains next interval
    except Exception:
        pass  # pipe closed: worker exiting


def _flush_interval() -> float:
    """Telemetry rides the metrics push cadence — same helper, not a copy."""
    from ray_tpu.util.metrics import _report_interval

    return _report_interval()


_flush_na = False  # cached "this process never flushes" verdict


def _ensure_flush_thread() -> None:
    """Called per append: after the first resolution this is one global read.
    The in-process driver/head never flushes (util/state reads its ring
    directly) — cache that verdict instead of probing global_state per event.
    A process with NO runtime context yet (telemetry before ray_tpu.init) is
    left unresolved: a remote client driver must still get its flusher once
    init lands."""
    global _flush_thread, _flush_na
    if _flush_thread is not None or _flush_na:
        return
    from ray_tpu.core import global_state

    if global_state.try_cluster() is not None:
        _flush_na = True  # in-process driver/head: the ring is read locally
        return
    w = global_state.try_worker()
    if w is None:
        return  # pre-init: can't decide yet
    if not hasattr(w, "push_telemetry"):
        _flush_na = True
        return

    def loop():
        while True:
            time.sleep(_flush_interval())
            try:
                flush()
            # graftlint: allow[swallowed-exception] degrades to the coded fallback (return) by design
            except Exception:
                return

    with _lock:
        if _flush_thread is None:
            _flush_thread = threading.Thread(target=loop, daemon=True,
                                             name="telemetry-flush")
            _flush_thread.start()


def align_batch(batch: dict, proc: str) -> List[dict]:
    """Head-side merge step: apply the batch's measured clock offset to every
    event timestamp and tag the producing process, so the cluster ring holds
    ONE timeline whose ts_ns values are directly comparable."""
    off = int(batch.get("clock_offset_ns") or 0)
    out = []
    for ev in batch.get("events", ()):
        ev = dict(ev)
        ev["ts_ns"] = int(ev["ts_ns"]) + off
        ev["proc"] = proc
        out.append(ev)
    return out


# --------------------------------------------------------------- lazy metrics

_metric_cache: Dict[str, Any] = {}
_metric_cache_lock = threading.Lock()


def get_counter(name: str, description: str = "", tag_keys=None):
    """Process-wide metric singletons for instrumentation points: creating a
    Counter/Gauge/Histogram registers it forever, so hot paths must reuse one
    instance per name instead of re-instantiating per call."""
    return _get_metric("counter", name, description, tag_keys)


def get_gauge(name: str, description: str = "", tag_keys=None):
    return _get_metric("gauge", name, description, tag_keys)


def get_histogram(name: str, description: str = "", tag_keys=None,
                  boundaries=None):
    return _get_metric("histogram", name, description, tag_keys, boundaries)


def _get_metric(kind: str, name: str, description: str, tag_keys,
                boundaries=None):
    with _metric_cache_lock:
        m = _metric_cache.get(name)
        if m is None:
            from ray_tpu.util import metrics as rm

            if kind == "counter":
                m = rm.Counter(name, description, tag_keys=tag_keys)
            elif kind == "gauge":
                m = rm.Gauge(name, description, tag_keys=tag_keys)
            else:
                m = rm.Histogram(name, description, boundaries=boundaries,
                                 tag_keys=tag_keys)
            _metric_cache[name] = m
        return m
