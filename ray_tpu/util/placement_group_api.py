"""Placement group public API (reference: python/ray/util/placement_group.py)."""
from __future__ import annotations

from typing import Dict, List, Optional

from ..core import global_state
from ..core.placement_group import VALID_STRATEGIES, PlacementGroup


def placement_group(
    bundles: List[Dict[str, float]],
    strategy: str = "PACK",
    name: str = "",
    lifetime: Optional[str] = None,
) -> PlacementGroup:
    if strategy not in VALID_STRATEGIES:
        raise ValueError(f"strategy must be one of {VALID_STRATEGIES}, got {strategy!r}")
    if not bundles:
        raise ValueError("placement group requires at least one bundle")
    for b in bundles:
        if not b or all(v <= 0 for v in b.values()):
            raise ValueError(f"invalid bundle {b!r}")
    cluster = global_state.try_cluster()
    if cluster is not None:
        return cluster.create_placement_group([dict(b) for b in bundles], strategy, name)
    # Worker process: create via upcall, then fetch a local replica handle.
    ctx = global_state.worker()
    pg_id = ctx.create_placement_group([dict(b) for b in bundles], strategy, name)
    import threading

    pg = PlacementGroup.__new__(PlacementGroup)
    pg.id = pg_id
    pg.bundle_specs = [dict(b) for b in bundles]
    pg.strategy = strategy
    pg.name = name
    pg._ready_event = threading.Event()
    pg._failed = None
    pg._remote_poll = lambda pid: ctx.lookup_placement_group(pid)
    return pg


def remove_placement_group(pg: PlacementGroup) -> None:
    ctx = global_state.worker()
    ctx.remove_placement_group(pg.id)


def placement_group_table() -> Dict[str, dict]:
    cluster = global_state.try_cluster()
    if cluster is None:
        return {}
    out = {}
    with cluster.pg_manager._lock:
        for pg_id, (pg, bundles) in cluster.pg_manager._groups.items():
            out[pg_id.hex()] = {
                "name": pg.name,
                "strategy": pg.strategy,
                "bundles": {b.index: b.resources for b in bundles},
                "node_ids": {b.index: b.node_id.hex() for b in bundles},
                "state": "CREATED",
            }
    for pg in cluster.pending_pgs:
        out[pg.id.hex()] = {
            "name": pg.name,
            "strategy": pg.strategy,
            "bundles": dict(enumerate(pg.bundle_specs)),
            "node_ids": {},
            "state": "PENDING",
        }
    return out
