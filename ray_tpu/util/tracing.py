"""Opt-in distributed tracing: spans with cross-task context propagation.

Capability parity: reference python/ray/util/tracing/tracing_helper.py (opt-in
OpenTelemetry wrapping — spans injected around task submit/execute, context
propagated inside the TaskSpec). OTel isn't in this image, so spans are plain
dicts in the OTel shape; the trace context rides TaskSpec.trace_ctx, worker
spans flow to the coordinator over the control pipe, and util.state exposes the
merged trace (chrome-trace exportable alongside the task timeline).

Usage:
    from ray_tpu.util import tracing
    tracing.enable_tracing()           # or RAY_TPU_TRACING=1 before init
    with tracing.span("ingest", {"rows": 100}):
        ... ray_tpu.get(f.remote()) ...   # task executions become child spans
"""
from __future__ import annotations

import contextlib
import contextvars
import logging
import os
import re
import threading
import time
import uuid
from typing import Any, Dict, List, Optional

logger = logging.getLogger("ray_tpu.tracing")

_ENV = "RAY_TPU_TRACING"
_enabled = False
_local_spans: List[dict] = []
_lock = threading.Lock()

# (trace_id, span_id) of the active span in this thread/task
_ctx: contextvars.ContextVar = contextvars.ContextVar("rt_trace_ctx", default=None)

# W3C Trace Context (https://www.w3.org/TR/trace-context/):
# traceparent = version "-" trace-id(32 hex) "-" parent-id(16 hex) "-" flags
_TRACEPARENT_RE = re.compile(
    r"^[0-9a-f]{2}-([0-9a-f]{32})-([0-9a-f]{16})-[0-9a-f]{2}$")


def enable_tracing() -> None:
    """Enable in this process and (via env) in workers spawned afterwards."""
    global _enabled
    _enabled = True
    os.environ[_ENV] = "1"


def is_tracing_enabled() -> bool:
    """Globally enabled, OR a trace context is actively set in this thread/
    task — an explicitly propagated context (serve ingress traceparent,
    TaskSpec.trace_ctx) is self-sufficient for ITS request without flipping
    any process-wide switch: no feedback loop, because get_trace_context only
    MINTS a fresh context when one of the global switches is on."""
    from ray_tpu.config import CONFIG

    return _enabled or CONFIG.tracing or _ctx.get() is not None


def get_trace_context() -> Optional[Dict[str, str]]:
    """Serializable context for propagation into a TaskSpec."""
    if not is_tracing_enabled():
        return None
    cur = _ctx.get()
    if cur is None:
        # root: start a fresh trace at first emission
        cur = (uuid.uuid4().hex, "")
        _ctx.set(cur)
    return {"trace_id": cur[0], "parent_span_id": cur[1]}


def set_trace_context(ctx: Optional[Dict[str, str]]):
    if ctx is None:
        return None
    return _ctx.set((ctx["trace_id"], ctx.get("parent_span_id", "")))


def current_trace_id() -> Optional[str]:
    """The active trace id in this thread/task, or None — a pure read: unlike
    get_trace_context it never STARTS a trace, so hot-path probes (telemetry
    event tagging) can call it per event without minting contexts."""
    if not is_tracing_enabled():
        return None
    cur = _ctx.get()
    return cur[0] if cur else None


def parse_traceparent(header: Optional[str]) -> Optional[Dict[str, str]]:
    """W3C `traceparent` header -> a propagatable trace context (the serve
    HTTP ingress accepts these so external callers can stitch our spans into
    their own traces). Malformed headers are ignored, per spec."""
    if not header:
        return None
    m = _TRACEPARENT_RE.match(header.strip().lower())
    if m is None:
        return None
    trace_id, parent_id = m.group(1), m.group(2)
    if trace_id == "0" * 32 or parent_id == "0" * 16:
        return None  # all-zero ids are invalid per spec
    return {"trace_id": trace_id, "parent_span_id": parent_id}


def format_traceparent(trace_id: str, span_id: str) -> str:
    """Render a context as a W3C traceparent (version 00, sampled flag).
    span_id shorter than 16 hex (or empty, a root) is zero-padded LEFT so the
    header stays spec-shaped."""
    return f"00-{trace_id:0>32}-{(span_id or '0'):0>16}-01"


def new_span_id() -> str:
    return uuid.uuid4().hex[:16]


@contextlib.contextmanager
def span(name: str, attributes: Optional[Dict[str, Any]] = None):
    """Record a span; nested spans/tasks become children."""
    if not is_tracing_enabled():
        yield None
        return
    parent = _ctx.get()
    trace_id = parent[0] if parent else uuid.uuid4().hex
    span_id = uuid.uuid4().hex[:16]
    token = _ctx.set((trace_id, span_id))
    rec = {
        "name": name,
        "trace_id": trace_id,
        "span_id": span_id,
        "parent_span_id": parent[1] if parent else "",
        "start_time": time.time(),
        "attributes": dict(attributes or {}),
        "pid": os.getpid(),
    }
    try:
        yield rec
    finally:
        rec["end_time"] = time.time()
        _ctx.reset(token)
        with _lock:
            _local_spans.append(rec)
        _maybe_flush()


def record_complete_span(name: str, start_time: float, end_time: float,
                         trace_id: str, span_id: str, parent_span_id: str = "",
                         attributes: Optional[Dict[str, Any]] = None) -> dict:
    """Record a span whose timing the caller measured itself — request
    lifecycles that start and end on different threads (the serve HTTP proxy
    brackets a request across its event loop and executor threads)."""
    rec = {
        "name": name, "trace_id": trace_id, "span_id": span_id,
        "parent_span_id": parent_span_id, "start_time": float(start_time),
        "end_time": float(end_time), "attributes": dict(attributes or {}),
        "pid": os.getpid(),
    }
    with _lock:
        _local_spans.append(rec)
    _maybe_flush()
    return rec


def drain_local_spans() -> List[dict]:
    with _lock:
        out = list(_local_spans)
        _local_spans.clear()
    return out


def _clock_offset_s() -> float:
    """head_clock - local_clock, from the telemetry plane's one-per-process
    NTP-style handshake: span timestamps are shifted onto the HEAD's clock at
    push, so request spans from different hosts land correctly on the merged
    telemetry_timeline instead of skewing per-host."""
    try:
        from ray_tpu.util import telemetry

        return telemetry.clock_offset_ns() / 1e9
    # graftlint: allow[swallowed-exception] degrades to the coded fallback (return 0.0) by design
    except Exception:
        return 0.0


_flush_warn_interval_s = 30.0
_last_flush_warning = [0.0]  # monotonic stamp of the last logged push failure


def _maybe_flush() -> None:
    """Workers and remote client drivers push spans to the coordinator; the
    in-process driver keeps them local (util/state.get_trace collects both) —
    keyed on holding the cluster, since DriverContext also has push_spans for
    the client server's benefit."""
    from ray_tpu.core import global_state

    w = global_state.try_worker()
    if w is None or not hasattr(w, "push_spans") or global_state.try_cluster() is not None:
        return
    spans = drain_local_spans()
    if not spans:
        return
    off = _clock_offset_s()
    if off:
        for s in spans:
            s["start_time"] += off
            if "end_time" in s:
                s["end_time"] += off
    try:
        w.push_spans(spans)
    except Exception as e:  # noqa: BLE001 — pipe closed / head gone
        # the spans are already drained, i.e. LOST: log it (throttled, same
        # convention as the telemetry ring's overflow warning) so dropped
        # traces are diagnosable instead of silently vanishing
        now = time.monotonic()
        if now - _last_flush_warning[0] >= _flush_warn_interval_s:
            _last_flush_warning[0] = now
            logger.warning(
                "push_spans failed, %d span(s) dropped: %r", len(spans), e)
