"""Opt-in distributed tracing: spans with cross-task context propagation.

Capability parity: reference python/ray/util/tracing/tracing_helper.py (opt-in
OpenTelemetry wrapping — spans injected around task submit/execute, context
propagated inside the TaskSpec). OTel isn't in this image, so spans are plain
dicts in the OTel shape; the trace context rides TaskSpec.trace_ctx, worker
spans flow to the coordinator over the control pipe, and util.state exposes the
merged trace (chrome-trace exportable alongside the task timeline).

Usage:
    from ray_tpu.util import tracing
    tracing.enable_tracing()           # or RAY_TPU_TRACING=1 before init
    with tracing.span("ingest", {"rows": 100}):
        ... ray_tpu.get(f.remote()) ...   # task executions become child spans
"""
from __future__ import annotations

import contextlib
import contextvars
import os
import threading
import time
import uuid
from typing import Any, Dict, List, Optional

_ENV = "RAY_TPU_TRACING"
_enabled = False
_local_spans: List[dict] = []
_lock = threading.Lock()

# (trace_id, span_id) of the active span in this thread/task
_ctx: contextvars.ContextVar = contextvars.ContextVar("rt_trace_ctx", default=None)


def enable_tracing() -> None:
    """Enable in this process and (via env) in workers spawned afterwards."""
    global _enabled
    _enabled = True
    os.environ[_ENV] = "1"


def is_tracing_enabled() -> bool:
    from ray_tpu.config import CONFIG

    return _enabled or CONFIG.tracing


def get_trace_context() -> Optional[Dict[str, str]]:
    """Serializable context for propagation into a TaskSpec."""
    if not is_tracing_enabled():
        return None
    cur = _ctx.get()
    if cur is None:
        # root: start a fresh trace at first emission
        cur = (uuid.uuid4().hex, "")
        _ctx.set(cur)
    return {"trace_id": cur[0], "parent_span_id": cur[1]}


def set_trace_context(ctx: Optional[Dict[str, str]]):
    if ctx is None:
        return None
    return _ctx.set((ctx["trace_id"], ctx.get("parent_span_id", "")))


@contextlib.contextmanager
def span(name: str, attributes: Optional[Dict[str, Any]] = None):
    """Record a span; nested spans/tasks become children."""
    if not is_tracing_enabled():
        yield None
        return
    parent = _ctx.get()
    trace_id = parent[0] if parent else uuid.uuid4().hex
    span_id = uuid.uuid4().hex[:16]
    token = _ctx.set((trace_id, span_id))
    rec = {
        "name": name,
        "trace_id": trace_id,
        "span_id": span_id,
        "parent_span_id": parent[1] if parent else "",
        "start_time": time.time(),
        "attributes": dict(attributes or {}),
        "pid": os.getpid(),
    }
    try:
        yield rec
    finally:
        rec["end_time"] = time.time()
        _ctx.reset(token)
        with _lock:
            _local_spans.append(rec)
        _maybe_flush()


def drain_local_spans() -> List[dict]:
    with _lock:
        out = list(_local_spans)
        _local_spans.clear()
    return out


def _maybe_flush() -> None:
    """Workers and remote client drivers push spans to the coordinator; the
    in-process driver keeps them local (util/state.get_trace collects both) —
    keyed on holding the cluster, since DriverContext also has push_spans for
    the client server's benefit."""
    from ray_tpu.core import global_state

    w = global_state.try_worker()
    if w is None or not hasattr(w, "push_spans") or global_state.try_cluster() is not None:
        return
    spans = drain_local_spans()
    if spans:
        try:
            w.push_spans(spans)
        except Exception:
            pass
