"""Throttled logging primitive for repeat-prone warning sites.

The project convention (PR 8): a failure path that can fire per-iteration
(reconcile loops, metric exporters, maintenance checks) logs through a
throttle so an outage produces one line per window, not one per tick — but
is never silent. One shared primitive so the window bookkeeping doesn't get
hand-rolled (and drift) per subsystem.
"""
from __future__ import annotations

import time
from typing import Dict, Hashable


class LogThrottle:
    """`ready(key)` is True at most once per `window_s` per key.

    Not thread-safe by design: a lost race only duplicates one log line.
    Keys let one throttle instance cover several sites independently (e.g.
    the engine's per-exporter guards) instead of the first firing site
    muting the others.
    """

    def __init__(self, window_s: float = 30.0):
        self.window_s = window_s
        self._last: Dict[Hashable, float] = {}

    def ready(self, key: Hashable = None) -> bool:
        now = time.monotonic()
        if now - self._last.get(key, 0.0) >= self.window_s:
            self._last[key] = now
            return True
        return False


def guarded_fanout(callbacks, arg, *, throttle: LogThrottle, logger,
                   what: str, exc_info: bool = False) -> None:
    """Deliver `arg` to every callback, individually exception-guarded with
    a per-callback throttled warning. THE fan-out for subscription surfaces
    that fire from a load-bearing thread (the metrics scraper, the SLO
    evaluator): one broken subscriber must neither starve the others nor
    kill the delivering thread, and a persistently-broken one logs once per
    throttle window, not once per event."""
    for cb in callbacks:
        try:
            cb(arg)
        except Exception as e:  # noqa: BLE001 — guarded by design
            if throttle.ready(id(cb)):
                logger.warning("%s %r raised (suppressed for %.0fs): %r",
                               what, cb, throttle.window_s, e,
                               exc_info=exc_info)
