"""Metrics history: the time dimension of the observability stack.

util/metrics.py answers "how much since process start" — lifetime counters and
lifetime histogram quantiles, which go stale the moment load changes. This
module retains a bounded ring of timestamped FRAMES (each frame one merged
cross-worker snapshot, sampled by the head-side scraper every
RAY_TPU_METRICS_SCRAPE_INTERVAL_S) and answers the windowed questions control
loops actually need:

    rate("serve_request_seconds", 60)     requests/s over the last minute
    delta("llm_prefix_cache_hits_total", 60)
    quantile("serve_ttft_seconds", 0.99, 60)
                                          windowed p99: bucket-DIFFERENCE the
                                          frame nearest (now-60s) from the
                                          latest frame, then quantile the
                                          difference — the recent regime, not
                                          the lifetime blur

The ring is bounded (RAY_TPU_METRICS_HISTORY_SIZE frames) and the scraper
lives in core/node.py's Cluster (head process), so every consumer —
`state.metrics_history()`, dashboard `/api/history`, `ray-tpu status --watch`
sparklines, the SLO engine (util/slo.py) — reads ONE retained history.
"""
from __future__ import annotations

import logging
import threading
import time
from collections import deque
from typing import Any, Dict, List, Optional, Tuple

from ray_tpu.util import metrics as _m

logger = logging.getLogger("ray_tpu.metrics_history")


def diff_histogram(new: Dict[str, Any],
                   prev: Optional[Dict[str, Any]]) -> Dict[str, Any]:
    """new-minus-prev for ONE histogram metric dict: per tag set,
    new.buckets - prev.buckets (clamped at 0; missing-in-prev = all new),
    with prev re-binned first when its boundaries drifted (same rule as
    merge_snapshots — never zip-truncate). prev=None means "everything is
    new". THE single bucket-differencing implementation: windowed quantiles
    (histogram_delta) and the dashboard's frame-over-frame series
    (state.history_series) both call this, so the edge-case rules cannot
    diverge. Returns a merged-metric-shaped dict; tag sets with no new
    observations are dropped."""
    dst_bounds = list(new.get("boundaries", []))
    old_values = (prev or {}).get("values", {})
    src_bounds = list((prev or {}).get("boundaries", dst_bounds))
    rebin = src_bounds != dst_bounds
    out_values: Dict[Tuple, Dict[str, Any]] = {}
    for key, nv in new.get("values", {}).items():
        ov = old_values.get(key)
        if ov is None:
            buckets = list(nv["buckets"])
            s, c = nv["sum"], nv["count"]
        else:
            ob = (_m._rebin(ov["buckets"], src_bounds, dst_bounds)
                  if rebin else ov["buckets"])
            buckets = [max(0, a - b) for a, b in zip(nv["buckets"], ob)]
            s = max(0.0, nv["sum"] - ov["sum"])
            c = max(0, nv["count"] - ov["count"])
        if c > 0:
            out_values[key] = {"buckets": buckets, "sum": s, "count": c}
    return {"name": new.get("name"), "type": "histogram",
            "description": new.get("description", ""),
            "boundaries": dst_bounds, "values": out_values}


class MetricsHistory:
    """Bounded ring of {ts, metrics} frames with windowed readers.

    Thread-safe: the scraper records while readers (state API, SLO engine,
    dashboard) difference frames concurrently.
    """

    def __init__(self, maxlen: Optional[int] = None):
        self._lock = threading.Lock()
        self._frames: deque = deque(maxlen=self._want_maxlen(maxlen))
        self._fixed_maxlen = maxlen
        self._frame_subs: List[Any] = []
        self._sub_warn = None  # lazy LogThrottle (keeps import cost off init)

    @staticmethod
    def _want_maxlen(explicit: Optional[int]) -> int:
        if explicit is not None:
            return max(2, int(explicit))
        try:
            from ray_tpu.config import CONFIG

            return max(2, int(CONFIG.metrics_history_size))
        # graftlint: allow[swallowed-exception] degrades to the coded fallback (return 360) by design
        except Exception:
            return 360

    # ------------------------------------------------------------- recording

    def record(self, merged: Dict[str, dict],
               ts: Optional[float] = None) -> Dict[str, Any]:
        """Append one frame (a merged metrics snapshot as produced by
        metrics.merge_snapshots). Returns the stored frame."""
        frame = {"ts": time.time() if ts is None else float(ts),
                 "metrics": merged}
        with self._lock:
            want = self._want_maxlen(self._fixed_maxlen)
            if self._frames.maxlen != want:
                self._frames = deque(self._frames, maxlen=want)
            self._frames.append(frame)
            subs = list(self._frame_subs)
        if subs:
            from ray_tpu.util.logutil import LogThrottle, guarded_fanout

            if self._sub_warn is None:
                self._sub_warn = LogThrottle(30.0)
            guarded_fanout(subs, frame, throttle=self._sub_warn,
                           logger=logger, what="metrics-history frame "
                           "subscriber")
        return frame

    def subscribe_frames(self, callback) -> Any:
        """callback(frame) after every recorded scrape frame, invoked on the
        scraper thread (keep it quick — set an event, don't compute). The
        serve autoscaler paces its ticks on this. Returns an unsubscribe fn."""
        with self._lock:
            self._frame_subs.append(callback)

        def unsubscribe():
            with self._lock:
                try:
                    self._frame_subs.remove(callback)
                except ValueError:
                    pass

        return unsubscribe

    def clear(self) -> None:
        with self._lock:
            self._frames.clear()

    def restore(self, frames: List[Dict[str, Any]]) -> int:
        """Seed the ring from journaled frames (head-restart durability):
        only well-shaped {ts, metrics} frames OLDER than anything already
        recorded are prepended, so a restore can never reorder or clobber
        live scrapes. Returns how many frames were accepted."""
        good = [f for f in frames
                if isinstance(f, dict) and isinstance(f.get("ts"), float)
                and isinstance(f.get("metrics"), dict)]
        good.sort(key=lambda f: f["ts"])
        with self._lock:
            if self._frames:
                oldest = self._frames[0]["ts"]
                good = [f for f in good if f["ts"] < oldest]
            if not good:
                return 0
            merged = good + list(self._frames)
            want = self._frames.maxlen or self._want_maxlen(self._fixed_maxlen)
            self._frames = deque(merged, maxlen=want)
            return len(good)

    # --------------------------------------------------------------- reading

    def frames(self) -> List[Dict[str, Any]]:
        with self._lock:
            return list(self._frames)

    def __len__(self) -> int:
        with self._lock:
            return len(self._frames)

    def latest(self) -> Optional[Dict[str, Any]]:
        with self._lock:
            return self._frames[-1] if self._frames else None

    def window_pair(self, window_s: float, now: Optional[float] = None
                    ) -> Optional[Tuple[Dict[str, Any], Dict[str, Any]]]:
        """(old, new): the newest frame and the frame closest to
        new.ts - window_s (clamped to the oldest retained). None with fewer
        than two frames — a window needs two points to difference."""
        with self._lock:
            if len(self._frames) < 2:
                return None
            frames = list(self._frames)
        new = frames[-1]
        target = (new["ts"] if now is None else float(now)) - float(window_s)
        # closest-to-target frame (excluding the newest): a frame 0.1s after
        # the window boundary beats one a full scrape interval before it
        old = min(frames[:-1], key=lambda f: abs(f["ts"] - target))
        return old, new

    # ------------------------------------------------- windowed counter math

    @staticmethod
    def _counter_total(frame: Dict[str, Any], name: str,
                       where: Optional[Dict[str, str]] = None) -> float:
        m = frame["metrics"].get(name)
        if m is None:
            return 0.0
        if m["type"] == "histogram":
            return float(sum(v["count"] for k, v in m["values"].items()
                             if _m._tags_match(k, where)))
        return float(sum(v for k, v in m["values"].items()
                         if _m._tags_match(k, where)))

    def delta(self, name: str, window_s: float,
              where: Optional[Dict[str, str]] = None) -> Optional[float]:
        """Counter (or histogram count) increase across the window. None when
        the history can't answer (fewer than 2 frames)."""
        pair = self.window_pair(window_s)
        if pair is None:
            return None
        old, new = pair
        return max(0.0, self._counter_total(new, name, where)
                   - self._counter_total(old, name, where))

    def rate(self, name: str, window_s: float,
             where: Optional[Dict[str, str]] = None) -> Optional[float]:
        """Per-second increase over the window (delta / ACTUAL frame spacing,
        not the nominal window — frames land where the scraper put them)."""
        pair = self.window_pair(window_s)
        if pair is None:
            return None
        old, new = pair
        dt = new["ts"] - old["ts"]
        if dt <= 0:
            return None
        d = max(0.0, self._counter_total(new, name, where)
                - self._counter_total(old, name, where))
        return d / dt

    # ----------------------------------------------- windowed histogram math

    def histogram_delta(self, name: str, window_s: float
                        ) -> Optional[Dict[str, Any]]:
        """The histogram of ONLY the window's observations, built by
        bucket-differencing the two frames bracketing the window: for each tag
        set, new.buckets - old.buckets (missing-in-old = all new). Boundary
        drift between frames (a process re-registered with different buckets)
        re-bins the old frame onto the new frame's boundaries first. Returns a
        merged-metric-shaped dict usable with metrics.histogram_quantile."""
        pair = self.window_pair(window_s)
        if pair is None:
            return None
        old_f, new_f = pair
        new = new_f["metrics"].get(name)
        if new is None or new.get("type") != "histogram":
            return None
        out = diff_histogram(new, old_f["metrics"].get(name))
        out["window_s"] = new_f["ts"] - old_f["ts"]
        return out

    def quantile(self, name: str, q: float, window_s: float,
                 where: Optional[Dict[str, str]] = None) -> Optional[float]:
        """Windowed quantile: p99 of the LAST window_s seconds of
        observations, not the process lifetime."""
        diff = self.histogram_delta(name, window_s)
        if diff is None:
            return None
        return _m.histogram_quantile(diff, q, where=where)

    def counts_below(self, name: str, threshold: float, window_s: float,
                     where: Optional[Dict[str, str]] = None
                     ) -> Optional[Tuple[float, int]]:
        """(observations <= threshold, total) within the window — the
        good/total split latency SLO burn rates are computed from."""
        diff = self.histogram_delta(name, window_s)
        if diff is None:
            return None
        return _m.histogram_counts_below(diff, threshold, where=where)

    def gauge_values(self, name: str, window_s: float,
                     where: Optional[Dict[str, str]] = None) -> List[float]:
        """Per-frame aggregate (sum across matching tag sets) of a gauge over
        the window — queue-depth saturation SLOs sample these."""
        frames = self.frames()
        if not frames:
            return []
        now = frames[-1]["ts"]
        out = []
        for f in frames:
            if f["ts"] < now - window_s:
                continue
            m = f["metrics"].get(name)
            if m is None:
                continue
            out.append(float(sum(v for k, v in m["values"].items()
                                 if _m._tags_match(k, where))))
        return out


# ----------------------------------------------------------------- scraper

def scraper_loop(history: MetricsHistory, snapshot_fn, is_shutdown,
                 on_frame=None, tick_s: float = 0.25) -> None:
    """Head-side scrape loop body (run on a daemon thread by core/node.py):
    every CONFIG.metrics_scrape_interval_s, sample snapshot_fn() into the
    ring and invoke on_frame (the SLO engine's evaluate hook). The interval
    is re-read each tick so tests/operators can retune a live cluster; an
    interval <= 0 disables scraping but keeps the thread parked cheaply."""
    from ray_tpu.config import CONFIG

    last = 0.0
    last_warn = 0.0
    while not is_shutdown():
        try:
            interval = float(CONFIG.metrics_scrape_interval_s)
        # graftlint: allow[swallowed-exception] degrades to the coded fallback (interval = 5.0) by design
        except Exception:
            interval = 5.0
        now = time.time()
        if interval > 0 and now - last >= interval:
            last = now
            try:
                t0 = time.perf_counter()
                history.record(snapshot_fn(), ts=now)
                if on_frame is not None:
                    on_frame()
                # control-plane self-telemetry: the scraper measures ITS OWN
                # wall cost (merge + record + SLO/autoscaler on_frame) — the
                # head-side number the control-plane bench gates on
                from ray_tpu.util import telemetry as _tel

                _tel.get_histogram(
                    "control_scrape_seconds",
                    "head scrape tick wall time: merged snapshot + history "
                    "record + on_frame (SLO evaluate) chain",
                ).observe(time.perf_counter() - t0)
            except Exception as e:  # noqa: BLE001
                # observability must never take the head down — but a
                # persistently failing scrape silently freezes the history
                # AND every SLO, so log it (throttled, same convention as
                # tracing's dropped-span warning)
                if time.monotonic() - last_warn >= 30.0:
                    last_warn = time.monotonic()
                    logger.warning("metrics-history scrape failed (history "
                                   "frozen, SLOs stale until it recovers): %r",
                                   e)
        time.sleep(min(tick_s, interval) if interval > 0 else tick_s)
