"""Distributed FIFO queue backed by an actor.

Capability parity: reference python/ray/util/queue.py (Queue over an async
actor: put/get with block/timeout, qsize/empty/full, batch ops, Empty/Full
re-exported). The reference parks blocked callers on the actor's asyncio loop;
here the actor is strictly non-blocking and CLIENTS poll with a short sleep —
an arbitrary number of blocked producers/consumers can wait without consuming
any actor concurrency (no thread-pool deadlock), at ~5 ms wakeup granularity.
"""
from __future__ import annotations

import queue as _stdlib_queue
import time
from collections import deque
from typing import Any, List, Optional

import ray_tpu

Empty = _stdlib_queue.Empty
Full = _stdlib_queue.Full

_POLL_S = 0.005


class _QueueActor:
    """Non-blocking FIFO state; all blocking lives client-side."""

    def __init__(self, maxsize: int):
        self._maxsize = maxsize
        self._q: deque = deque()

    def try_put(self, item) -> bool:
        if self._maxsize and len(self._q) >= self._maxsize:
            return False
        self._q.append(item)
        return True

    def try_get(self):
        if self._q:
            return True, self._q.popleft()
        return False, None

    def try_put_batch(self, items: List[Any]) -> bool:
        """All-or-nothing insert."""
        if self._maxsize and len(self._q) + len(items) > self._maxsize:
            return False
        self._q.extend(items)
        return True

    def try_get_batch(self, n: int):
        """All-or-nothing removal."""
        if len(self._q) < n:
            return False, None
        return True, [self._q.popleft() for _ in range(n)]

    def qsize(self) -> int:
        return len(self._q)

    def full(self) -> bool:
        return bool(self._maxsize) and len(self._q) >= self._maxsize


class Queue:
    def __init__(self, maxsize: int = 0, actor_options: Optional[dict] = None):
        self.maxsize = maxsize
        opts = {"num_cpus": 0.1, **(actor_options or {})}
        self._actor = ray_tpu.remote(**opts)(_QueueActor).remote(maxsize)

    def _poll(self, attempt, block: bool, timeout: Optional[float], exc):
        deadline = None if timeout is None else time.monotonic() + timeout
        while True:
            ok, value = attempt()
            if ok:
                return value
            if not block:
                raise exc
            if deadline is not None and time.monotonic() >= deadline:
                raise exc
            time.sleep(_POLL_S)

    # -- single ----------------------------------------------------------------
    def put(self, item: Any, block: bool = True, timeout: Optional[float] = None) -> None:
        self._poll(lambda: (ray_tpu.get(self._actor.try_put.remote(item)), None),
                   block, timeout, Full())

    def put_nowait(self, item: Any) -> None:
        self.put(item, block=False)

    def get(self, block: bool = True, timeout: Optional[float] = None) -> Any:
        return self._poll(lambda: ray_tpu.get(self._actor.try_get.remote()),
                          block, timeout, Empty())

    def get_nowait(self) -> Any:
        return self.get(block=False)

    # -- batch (atomic: reference put_nowait_batch/get_nowait_batch) -----------
    def put_nowait_batch(self, items: List[Any]) -> None:
        if not ray_tpu.get(self._actor.try_put_batch.remote(list(items))):
            raise Full(f"cannot add {len(items)} items to a queue of size "
                       f"{self.qsize()} (maxsize {self.maxsize})")

    def get_nowait_batch(self, num_items: int) -> List[Any]:
        ok, items = ray_tpu.get(self._actor.try_get_batch.remote(num_items))
        if not ok:
            raise Empty(f"queue holds fewer than {num_items} items")
        return items

    # -- introspection ---------------------------------------------------------
    def qsize(self) -> int:
        return ray_tpu.get(self._actor.qsize.remote())

    def empty(self) -> bool:
        return self.qsize() == 0

    def full(self) -> bool:
        return ray_tpu.get(self._actor.full.remote())

    def shutdown(self) -> None:
        ray_tpu.kill(self._actor)
